"""Follow-the-tip serving plane: continuous batching of multi-peer
candidate suffixes into shared packed device windows.

The reference's production workload is not one long replay — it is
ChainSel plus thousands of concurrent per-peer ChainSync instances each
pushing a SHORT candidate suffix at the tip (SURVEY.md §3.2/§3.5; the
ROADMAP north-star shape). A naive port would dispatch one device
window per peer: at tip-follow depth (a handful of headers per
candidate) that pads every window to the minimum bucket and burns the
whole dispatch wall per peer. This module applies the inference-server
answer — continuous batching (Orca-style iteration-level scheduling;
vLLM-style slot reuse) — to header validation:

  * every peer (tenant) owns a FIFO of candidate suffixes and its own
    sequential fold state (PraosState: nonce carry + OCert counters);
  * a single scheduler thread fills SHARED packed windows from whatever
    lanes are pending across tenants of one window shape, dispatches
    through the existing packed-stage path (`prepare_window` /
    `dispatch_prepared` / `materialize_verdicts` — the same programs
    the replay plane compiled), and scatters per-tenant first-failure
    verdicts back by slicing the window's HostChecks/Verdicts columns
    per tenant segment and running the sequential `_epilogue` against
    THAT tenant's state;
  * correctness of sharing: every per-lane device check depends only on
    (params, ledger view, epoch nonce, header bytes) — the ONLY
    cross-lane state is the sequential fold, which never runs on shared
    lanes: each tenant's epilogue folds its own segment against its own
    state, so lanes from different tenants cannot bleed into each
    other's verdicts by construction. A window with a single tenant
    additionally chains the on-device nonce-scan carry from that
    tenant's host state (`_state_carry`) — the per-chain device carry
    of the replay plane, preserved per tenant;
  * admission is priced (protocol/admission.py): a cold tenant whose
    window shape misses the warm/AOT store rides the warm-compile rung
    ladder instead of stalling warm traffic;
  * a device fault mid-window sheds each affected tenant segment down
    the PR 12 recovery ladder (`recover_window` — retry / stage-split /
    xla-twin / host-reference), every rung a full re-validation with
    identical semantics, so the shed verdicts are byte-identical and no
    tenant is dropped; the episode is recorded as a DEGRADED interval
    on the SLO surface instead of a run abort;
  * `OCT_SERVE_DEVICE=0` kill-switches the device plane entirely: every
    window reroutes to the per-tenant host reference fold (the ladder's
    floor — real host crypto, no staging, no JAX dispatch);
  * `OCT_SERVE_CHECKPOINT=<file>` persists a per-retired-window
    atomic progress record (tmp+rename, digest, fail-closed read) so a
    SIGKILL'd service relaunches with per-tenant carry resume: seeded
    traffic regenerates byte-identically (testing/traffic.py) and
    `submit` fast-forwards past already-banked suffixes.

The SLO surface is `slo_snapshot()` — p50/p99 verdict latency,
aggregate headers/s, queue depths, the degraded flag and its
intervals — served live by obs/server.py's `/slo` route when a
MetricsServer is mounted with `slo_doc=service.slo_snapshot`."""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..obs import recovery as _recovery
from ..obs import registry as _registry
from ..protocol import batch as pbatch
from ..protocol import praos
from ..protocol.admission import AdmissionPolicy, WindowShape, shape_of

__all__ = [
    "SuffixVerdict", "Tenant", "ValidationService", "read_serve_checkpoint",
]

_DEVICE_ENV = "OCT_SERVE_DEVICE"
_CKPT_ENV = "OCT_SERVE_CHECKPOINT"

SCHEMA_VERSION = 1


def _device_serving() -> bool:
    """OCT_SERVE_DEVICE (default on): the packed device window path.
    =0 kill-switches the device plane — every window reroutes to the
    per-tenant host reference fold (read per window so a flip mid-run
    takes effect at the next window boundary)."""
    return os.environ.get(_DEVICE_ENV, "1") != "0"


@dataclass(frozen=True)
class SuffixVerdict:
    """One resolved candidate suffix: how many headers of it extended
    the tenant's chain, and the first-failure error (None = the whole
    suffix was valid). `n_valid` counts valid headers WITHIN the
    suffix — the reference's first-failure contract: everything after
    the first invalid header is discarded unexamined."""

    tenant_id: str
    seq: int
    n_valid: int
    error: str | None

    def row(self) -> list:
        """Canonical comparable form (checkpoint + byte-identity
        assertions across degraded/host/device paths)."""
        return [self.seq, self.n_valid, self.error]


def _canon_error(err) -> str | None:
    """Canonical error string: class name + message, identical across
    the device epilogue, every recovery rung and the host fold (all
    raise the same reference taxonomy classes with the same args)."""
    if err is None:
        return None
    return f"{type(err).__name__}: {err}"


@dataclass
class _Job:
    """One queued candidate suffix; `offset` = headers already folded
    into the tenant's state (a suffix may span several windows)."""

    seq: int
    hvs: tuple
    shape: WindowShape
    offset: int = 0
    t_submit: float = 0.0


@dataclass
class Tenant:
    """One simulated peer's server-side lane: fold state, suffix FIFO
    and resolved verdicts. All mutation happens on the scheduler
    thread (pump) or under the service lock."""

    tenant_id: str
    state: praos.PraosState
    queue: deque = field(default_factory=deque)
    verdicts: list = field(default_factory=list)
    seen: int = 0  # suffixes ever submitted (resume fast-forward key)
    done: int = 0  # suffixes finalized (verdict banked)
    headers_done: int = 0
    resume_offset: int = 0  # of suffix `done`, folded pre-relaunch

    def pending_headers(self) -> int:
        return sum(len(j.hvs) - j.offset for j in self.queue)


def _doc_digest(doc: dict) -> str:
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
    return hashlib.blake2s(blob, digest_size=16).hexdigest()


def read_serve_checkpoint(path: str | None) -> dict | None:
    """Read + integrity-check a serve progress record; None when
    absent, torn, schema-alien or digest-mismatched (fail closed — the
    same contract as obs/recovery.read_checkpoint: a fresh start is
    always correct, a wrong re-seed never is)."""
    if not path:
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("kind") != "oct-serve-checkpoint":
        return None
    if doc.get("schema") != SCHEMA_VERSION:
        return None
    digest = doc.get("digest")
    body = {k: v for k, v in doc.items() if k != "digest"}
    if digest != _doc_digest(body):
        return None
    return doc


class ValidationService:
    """The long-lived serving plane: tenants `submit()` candidate
    suffixes, `pump()` runs one continuous-batching iteration (fill one
    shared window, dispatch, scatter verdicts), `run_until_drained()`
    loops it. One scheduler thread owns pump(); `submit`, `register`
    and `slo_snapshot` may be called from other threads (the service
    lock guards the shared tenant/interval structures)."""

    def __init__(self, params, lview, eta0: bytes, *, registry=None,
                 policy: AdmissionPolicy | None = None,
                 max_window: int = 256, checkpoint: str | None = None,
                 serve_tag: str | None = None):
        self.params = params
        self.lview = lview
        self.eta0 = eta0
        self.registry = (registry if registry is not None
                         else _registry.default_registry())
        self.policy = policy if policy is not None else AdmissionPolicy()
        self.max_window = max(1, int(max_window))
        self.checkpoint = (checkpoint if checkpoint is not None
                           else os.environ.get(_CKPT_ENV) or None)
        if serve_tag is None:
            blob = f"{params!r}|{eta0.hex()}".encode()
            serve_tag = hashlib.blake2s(blob, digest_size=8).hexdigest()
        self.serve_tag = serve_tag
        self._lock = threading.Lock()
        self.tenants: dict[str, Tenant] = {}  # guarded-by: _lock
        self.windows = 0  # guarded-by: _lock
        self.degraded = False  # guarded-by: _lock
        # [t_open, t_close | None, fault-class] — guarded-by: _lock
        self.degraded_intervals: list[list] = []
        self._clean_streak = 0
        self._rr = 0  # window fill rotation cursor (scheduler thread)
        self.resumed = False
        self._t0 = time.monotonic()
        r = self.registry
        self._m_suffixes = r.counter(
            "oct_serve_suffixes_total",
            "candidate suffixes resolved by the serving plane",
            ("result",),
        )
        self._m_headers = r.counter(
            "oct_serve_headers_total",
            "headers validated by the serving plane",
        )
        self._m_windows = r.counter(
            "oct_serve_windows_total",
            "shared serving windows retired", ("mode",),
        )
        self._m_degraded = r.gauge(
            "oct_serve_degraded",
            "1 while serving rides the recovery ladder (degraded mode)",
        )
        self._m_queue = r.gauge(
            "oct_serve_queue_depth",
            "pending headers across all tenant queues",
        )
        self._m_latency = r.histogram(
            "oct_serve_verdict_latency_seconds",
            "submit->verdict wall per candidate suffix",
        )
        if self.checkpoint:
            self._try_resume()

    # -- tenants ------------------------------------------------------------

    def register(self, tenant_id: str, state=None) -> Tenant:
        """Idempotent: an existing tenant is returned unchanged (its
        fold state is the server's truth, not the caller's)."""
        with self._lock:
            t = self.tenants.get(tenant_id)
            if t is None:
                if state is None:
                    state = praos.PraosState(epoch_nonce=self.eta0)
                t = Tenant(tenant_id, state)
                self.tenants[tenant_id] = t
            return t

    def submit(self, tenant_id: str, hvs) -> int:
        """Enqueue one candidate suffix; returns its per-tenant
        sequence number. Malformed suffixes raise AdmissionRefused at
        the door (disposition REFUSE — nothing else is touched). After
        a resume, suffixes whose verdicts are already banked are
        fast-forwarded (the seeded traffic source re-submits the whole
        stream; the service knows what it already folded)."""
        from ..protocol.admission import AdmissionRefused

        t = self.register(tenant_id)
        try:
            shape = shape_of(tenant_id, hvs)
        except AdmissionRefused:
            self._m_suffixes.labels(result="refused").inc()
            raise
        with self._lock:
            seq = t.seen
            t.seen += 1
            if seq < t.done:
                return seq  # verdict already banked pre-relaunch
            job = _Job(seq, tuple(hvs), shape, t_submit=time.monotonic())
            if seq == t.done and t.resume_offset:
                # the killed process folded a prefix of this suffix:
                # its headers are already in the restored state
                job.offset = min(t.resume_offset, len(job.hvs))
                t.resume_offset = 0
            t.queue.append(job)
        self._update_queue_gauge()
        return seq

    def verdicts(self, tenant_id: str) -> list:
        with self._lock:
            t = self.tenants.get(tenant_id)
            return list(t.verdicts) if t is not None else []

    # -- the continuous-batching scheduler ----------------------------------

    def pump(self) -> bool:
        """One iteration-level scheduling step: pick a window shape
        with pending lanes, fill ONE shared window fairly across its
        tenants (rotating quantum fill — a cold tenant's lanes ride
        their own rung-capped windows, so it cannot starve warm
        traffic), dispatch, scatter per-tenant verdicts. Returns False
        when no tenant has pending work."""
        from ..testing import chaos

        with self._lock:
            groups: dict[WindowShape, list[Tenant]] = {}
            for t in self.tenants.values():
                if t.queue:
                    groups.setdefault(t.queue[0].shape, []).append(t)
            if not groups:
                return False
            shapes = sorted(groups, key=lambda s: (s.proof_len, s.body_len))
            shape = shapes[self._rr % len(shapes)]
            tenants = groups[shape]
            order = (tenants[self._rr % len(tenants):]
                     + tenants[:self._rr % len(tenants)])
            self._rr += 1
            pending = sum(len(t.queue[0].hvs) - t.queue[0].offset
                          for t in order)
        decision = self.policy.admit(shape, min(pending, self.max_window))
        cap = min(decision.lane_cap, self.max_window)
        # fair fill: rotating passes granting up to one quantum per
        # tenant per pass until the window is full or the shape drains
        takes = {t.tenant_id: 0 for t in order}
        avail = {t.tenant_id: len(t.queue[0].hvs) - t.queue[0].offset
                 for t in order}
        quantum = max(1, cap // max(1, len(order)))
        space = cap
        while space > 0:
            progressed = False
            for t in order:
                room = min(avail[t.tenant_id] - takes[t.tenant_id],
                           quantum, space)
                if room > 0:
                    takes[t.tenant_id] += room
                    space -= room
                    progressed = True
            if not progressed:
                break
        whvs: list = []
        segments: list[tuple] = []  # (tenant, job, lo, hi)
        for t in order:
            n = takes[t.tenant_id]
            if not n:
                continue
            job = t.queue[0]
            lo = len(whvs)
            whvs.extend(job.hvs[job.offset:job.offset + n])
            segments.append((t, job, lo, lo + n))
        if not whvs:
            return False
        results, fault = self._run_window(whvs, segments, self.windows)
        mode = decision.mode if _device_serving() else "host"
        self._m_windows.labels(mode=mode).inc()
        with self._lock:
            for (t, job, lo, hi), res in zip(segments, results):
                t.state = res.state
                t.headers_done += res.n_valid
                job.offset += res.n_valid
                self._m_headers.inc(res.n_valid)
                if res.error is not None:
                    self._finalize(t, job, res.error)
                elif job.offset >= len(job.hvs):
                    self._finalize(t, job, None)
            self.windows += 1
            self._note_fault(fault)
        if fault is None and mode != "host":
            # promotion is earned: only a CLEAN device window warms its
            # bucket for the admission ladder
            self.policy.note_window(shape, len(whvs))
        self._update_queue_gauge()
        self._write_checkpoint()
        # checkpoint-before-kill ordering: the record for THIS window is
        # durable before the sigkill seam can fire (chaos: sigkill@serve:N)
        chaos.fire("serve")
        return True

    def run_until_drained(self, max_windows: int = 100_000) -> int:
        n = 0
        while n < max_windows and self.pump():
            n += 1
        return n

    # -- one window ---------------------------------------------------------

    def _run_window(self, whvs, segments, widx):
        """Dispatch one shared window and fold each tenant segment.
        Device faults shed each affected segment down the recovery
        ladder (full re-validation per rung — verdicts byte-identical
        by construction); with the device plane kill-switched every
        window reroutes to the per-tenant host reference fold."""
        from ..testing import chaos

        if not _device_serving():
            return self._host_window(whvs, segments), None
        try:
            # the serving dispatch seam (chaos:
            # device-error@serve-dispatch:N) fires BEFORE staging so a
            # faulted window sheds whole segments, never half-built state
            chaos.fire("serve-dispatch")
            sw = pbatch.prepare_window(self.params, self.lview, self.eta0,
                                       whvs)
            carry = None
            if len(segments) == 1:
                # solo-tenant window: chain the device nonce scan from
                # the tenant's host state (the replay plane's per-chain
                # carry, preserved per tenant)
                carry = pbatch._state_carry(segments[0][0].state)
            pre, tagged, b, _carry_out = pbatch.dispatch_prepared(
                sw, carry=carry
            )
            v = pbatch.materialize_verdicts(tagged, b)
            results = []
            if len(segments) == 1:
                t, _job, _lo, _hi = segments[0]
                ticked = praos.tick(self.params, self.lview, whvs[0].slot,
                                    t.state)
                results.append(
                    pbatch._epilogue(self.params, ticked, whvs, pre, v)
                )
            else:
                full = (v.full() if isinstance(v, pbatch.PackedVerdicts)
                        else v)
                for t, _job, lo, hi in segments:
                    results.append(
                        self._segment_epilogue(t, whvs, pre, full, lo, hi)
                    )
            return results, None
        except Exception as exc:  # noqa: BLE001 — routed through triage:
            # recover_window absorbs ONLY RECOVER-class faults (device
            # runtime errors, the chaos taxonomy); anything else
            # re-raises out of the ladder unmasked
            results = []
            for t, _job, lo, hi in segments:
                seg = list(whvs[lo:hi])
                ticked = praos.tick(self.params, self.lview, seg[0].slot,
                                    t.state)
                results.append(_recovery.supervisor().recover_window(
                    self.params, ticked, seg, exc, backend="device",
                    window=widx,
                ))
            return results, exc

    def _segment_epilogue(self, tenant, whvs, pre, full, lo, hi):
        """Scatter one tenant's slice of a shared window: slice the
        positional HostChecks/Verdicts columns and run the sequential
        fold against THAT tenant's state — the only stateful step, so
        cross-tenant bleed is structurally impossible."""
        seg = list(whvs[lo:hi])
        ticked = praos.tick(self.params, self.lview, seg[0].slot,
                            tenant.state)
        pre_t = pbatch.HostChecks(
            kes_window_errors=list(pre.kes_window_errors[lo:hi]),
            vrf_lookup_errors=list(pre.vrf_lookup_errors[lo:hi]),
            kes_evolution=np.asarray(pre.kes_evolution)[lo:hi],
        )
        v_t = pbatch.Verdicts(
            *(np.asarray(col)[lo:hi] for col in full)
        )
        return pbatch._epilogue(self.params, ticked, seg, pre_t, v_t)

    def _host_window(self, whvs, segments):
        """The OCT_SERVE_DEVICE=0 reroute: per-tenant sequential host
        reference fold (the recovery ladder's floor) — no staging, no
        device dispatch, real host crypto."""
        results = []
        for t, _job, lo, hi in segments:
            seg = list(whvs[lo:hi])
            ticked = praos.tick(self.params, self.lview, seg[0].slot,
                                t.state)
            results.append(
                _recovery.host_reference_fold(self.params, ticked, seg)
            )
        return results

    # -- bookkeeping (callers hold self._lock where noted) -------------------

    def _finalize(self, tenant, job, error) -> None:
        # caller holds self._lock
        tenant.queue.popleft()
        tenant.done += 1
        err = _canon_error(error)
        tenant.verdicts.append(
            SuffixVerdict(tenant.tenant_id, job.seq, job.offset, err)
        )
        self._m_suffixes.labels(
            result="valid" if err is None else "invalid"
        ).inc()
        if job.t_submit:
            self._m_latency.observe(time.monotonic() - job.t_submit)

    def _note_fault(self, fault) -> None:
        # caller holds self._lock
        now = time.monotonic() - self._t0
        if fault is not None:
            self._clean_streak = 0
            if not self.degraded:
                self.degraded = True
                self.degraded_intervals.append(
                    [now, None, type(fault).__name__]
                )
                self._m_degraded.set(1)
            return
        self._clean_streak += 1
        if self.degraded and self._clean_streak >= 2:
            # two consecutive clean windows close the degraded interval
            self.degraded = False
            self.degraded_intervals[-1][1] = now
            self._m_degraded.set(0)

    def _update_queue_gauge(self) -> None:
        with self._lock:
            depth = sum(t.pending_headers() for t in self.tenants.values())
        self._m_queue.set(depth)

    # -- the SLO surface -----------------------------------------------------

    def slo_snapshot(self) -> dict:
        """The live SLO document (obs/server.py `/slo`): verdict-latency
        tails, aggregate throughput, queue depths, degraded state and
        the admission decision mix."""
        with self._lock:
            headers = sum(t.headers_done for t in self.tenants.values())
            depths = [t.pending_headers() for t in self.tenants.values()]
            doc = {
                "kind": "oct-serve-slo",
                "schema": SCHEMA_VERSION,
                "serve_tag": self.serve_tag,
                "tenants": len(self.tenants),
                "windows": self.windows,
                "headers": headers,
                "suffixes_done": sum(t.done
                                     for t in self.tenants.values()),
                "queue_depth": sum(depths),
                "queue_depth_max": max(depths, default=0),
                "degraded": self.degraded,
                "degraded_intervals": [list(iv) for iv
                                       in self.degraded_intervals],
                "resumed": self.resumed,
            }
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        doc["headers_per_s"] = headers / elapsed
        doc["verdict_latency_p50_s"] = self._m_latency.quantile(0.5)
        doc["verdict_latency_p99_s"] = self._m_latency.quantile(0.99)
        doc["admission"] = dict(self.policy.decisions)
        doc["device_serving"] = _device_serving()
        doc["ts_unix"] = time.time()
        return doc

    # -- checkpoint / resume -------------------------------------------------

    def _write_checkpoint(self) -> None:
        """Per-retired-window atomic progress record (tmp+rename, the
        obs/recovery crash contract): tenant fold states, banked
        verdicts and the in-progress suffix offset — everything a
        relaunch needs to resume without re-folding or double-counting."""
        if not self.checkpoint:
            return
        with self._lock:
            doc = {
                "schema": SCHEMA_VERSION,
                "kind": "oct-serve-checkpoint",
                "serve_tag": self.serve_tag,
                "windows": self.windows,
                "tenants": {
                    tid: {
                        "state": _recovery.encode_state(t.state),
                        "done": t.done,
                        "headers_done": t.headers_done,
                        "offset": (t.queue[0].offset if t.queue else 0),
                        "verdicts": [v.row() for v in t.verdicts],
                    }
                    for tid, t in sorted(self.tenants.items())
                },
                "pid": os.getpid(),
                "ts_unix": time.time(),
            }
        doc["digest"] = _doc_digest(doc)
        try:
            tmp = self.checkpoint + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.checkpoint)
        except OSError:
            pass  # best-effort, never breaks serving

    def _try_resume(self) -> bool:
        doc = read_serve_checkpoint(self.checkpoint)
        if doc is None or doc.get("serve_tag") != self.serve_tag:
            return False
        for tid, row in doc["tenants"].items():
            t = self.register(tid,
                              state=_recovery.decode_state(row["state"]))
            t.done = int(row["done"])
            t.headers_done = int(row["headers_done"])
            t.resume_offset = int(row["offset"])
            t.verdicts = [SuffixVerdict(tid, *r) for r in row["verdicts"]]
        with self._lock:
            self.windows = int(doc["windows"])
        self.resumed = True
        return True
