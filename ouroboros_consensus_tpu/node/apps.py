"""Versioned mini-protocol application bundles.

Reference: `ouroboros-consensus-diffusion` `Network/NodeToNode.hs:434-466`
— the `Apps` record groups the consensus side of every node-to-node
mini-protocol (ChainSync, BlockFetch, TxSubmission2, KeepAlive,
PeerSharing), assembled per NEGOTIATED version; `Network/NodeToClient.hs`
does the same for the local protocols. The handshake (handshake.py)
picks the version; the bundle decides which protocols exist on the
connection and how they behave.

`connect_peers` is the full wiring: run the handshake over its own
channel pair, then spawn exactly the version-gated app pairs — the
`initiator`/`responder` assembly the diffusion layer performs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..miniprotocol import blockfetch, chainsync, handshake, txsubmission
from ..miniprotocol.chainsync import Candidate
from ..utils.sim import Channel, Sim


@dataclass
class Apps:
    """The per-connection app bundle (NodeToNode.hs:434 Apps analog):
    task generators keyed by protocol name, version-gated."""

    version: int
    tasks: list = field(default_factory=list)  # (owner, name, generator)
    # per-protocol (req, rsp) channel pairs for callers that drive the
    # servers directly (node-to-client sessions)
    channels: dict = field(default_factory=dict)

    def protocols(self) -> set[str]:
        return {name.split(":")[0] for (_o, name, _g) in self.tasks}


def node_to_node_apps(
    server_node,
    client_node,
    version: int,
    *,
    msg_delay: float = 0.0,
    candidate: Candidate | None = None,
) -> Apps:
    """Build the consensus n2n bundle for a NEGOTIATED version: the app
    set is exactly handshake.NODE_TO_NODE_VERSIONS[version]."""
    enabled = handshake.NODE_TO_NODE_VERSIONS[version]
    apps = Apps(version)
    cand = candidate if candidate is not None else Candidate()
    client_node.candidates[server_node.name] = cand

    def chan(name):
        return Channel(delay=msg_delay, name=name)

    if "chainsync" in enabled:
        req, rsp = chan("cs-req"), chan("cs-rsp")
        apps.tasks.append(
            ("server", "chainsync:server",
             chainsync.server(server_node.chain_db, req, rsp))
        )
        apps.tasks.append(
            ("client", "chainsync:client",
             chainsync.client(client_node, server_node.name, rsp, req, cand))
        )
    if "blockfetch" in enabled:
        req, rsp = chan("bf-req"), chan("bf-rsp")
        apps.tasks.append(
            ("server", "blockfetch:server",
             blockfetch.server(server_node.chain_db, req, rsp))
        )
        apps.tasks.append(
            ("client", "blockfetch:client",
             blockfetch.client(client_node, server_node.name, rsp, req, cand))
        )
    if "txsubmission2" in enabled:
        req, rsp = chan("ts-req"), chan("ts-rsp")
        apps.tasks.append(
            ("server", "txsubmission:outbound",
             txsubmission.outbound(server_node, req, rsp))
        )
        apps.tasks.append(
            ("client", "txsubmission:inbound",
             txsubmission.inbound(client_node, server_node.name, rsp, req))
        )
    if "keepalive" in enabled:
        req, rsp = chan("ka-req"), chan("ka-rsp")
        apps.tasks.append(
            ("server", "keepalive:server", txsubmission.keepalive_server(req, rsp))
        )
        apps.tasks.append(
            ("client", "keepalive:client",
             txsubmission.keepalive_client(rsp, req))
        )
    if "peersharing" in enabled:
        req, rsp = chan("ps-req"), chan("ps-rsp")
        apps.tasks.append(
            ("server", "peersharing:server",
             txsubmission.peersharing_server(server_node, req, rsp))
        )
        apps.tasks.append(
            ("client", "peersharing:client",
             txsubmission.peersharing_client(rsp, req, 4))
        )
    return apps


def connect_peers(
    sim: Sim,
    server_node,
    client_node,
    server_versions: dict[int, handshake.VersionData],
    client_versions: dict[int, handshake.VersionData],
    *,
    msg_delay: float = 0.0,
) -> Apps:
    """Handshake (pure negotiation — the wire exchange is exercised by
    handshake.client/server tasks in tests) then spawn the version-gated
    bundle. Raises HandshakeRefused on no common version/magic."""
    version, _data = handshake.negotiate(server_versions, client_versions)
    apps = node_to_node_apps(
        server_node, client_node, version, msg_delay=msg_delay
    )
    from ..miniprotocol.rethrow import peer_guard

    spawned: list = []

    def disconnect():
        # a peer violation tears down the whole connection bundle
        # (RethrowPolicy 'disconnect peer', not node shutdown)
        for t in spawned:
            t.alive = False
            try:
                t.gen.close()
            except Exception:
                pass
        client_node.candidates.pop(server_node.name, None)

    for owner, name, gen in apps.tasks:
        label = f"{name}:{server_node.name}->{client_node.name}"
        spawned.append(
            sim.spawn(
                peer_guard(gen, label, client_node.trace, disconnect), label
            )
        )
    return apps


def node_to_client_apps(node, version: int, *, msg_delay: float = 0.0) -> Apps:
    """The local (node-to-client) bundle (Network/NodeToClient.hs):
    LocalStateQuery + LocalTxSubmission always; LocalTxMonitor from v2.
    The negotiated version also gates the QUERY vocabulary
    (localstate.QUERY_MIN_VERSION)."""
    from ..miniprotocol import localstate

    enabled = handshake.NODE_TO_CLIENT_VERSIONS[version]
    apps = Apps(version)

    def chan(name):
        return Channel(delay=msg_delay, name=name)

    if "localstatequery" in enabled:
        req, rsp = chan("lsq-req"), chan("lsq-rsp")
        apps.tasks.append(
            ("server", "localstatequery:server",
             localstate.state_query_server(node, req, rsp, version=version))
        )
        apps.channels["localstatequery"] = (req, rsp)
    if "localtxsubmission" in enabled:
        req, rsp = chan("lts-req"), chan("lts-rsp")
        apps.tasks.append(
            ("server", "localtxsubmission:server",
             localstate.tx_submission_server(node, req, rsp))
        )
        apps.channels["localtxsubmission"] = (req, rsp)
    if "localtxmonitor" in enabled:
        req, rsp = chan("ltm-req"), chan("ltm-rsp")
        apps.tasks.append(
            ("server", "localtxmonitor:server",
             localstate.tx_monitor_server(node, req, rsp))
        )
        apps.channels["localtxmonitor"] = (req, rsp)
    if "localchainsync" in enabled:
        # local ChainSync over WHOLE BLOCKS (NodeToClient.hs:92-121):
        # wallets follow the chain — including rollbacks — receiving
        # serialised blocks, never tentative headers
        req, rsp = chan("lcs-req"), chan("lcs-rsp")
        apps.tasks.append(
            ("server", "localchainsync:server",
             chainsync.server(node.chain_db, req, rsp, serve_blocks=True))
        )
        apps.channels["localchainsync"] = (req, rsp)
    return apps
