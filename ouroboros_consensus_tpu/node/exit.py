"""Exception triage: exit reasons + the repair-vs-refuse-vs-recover map.

Reference: `Node/Exit.hs:63` (`ExitReason` / `toExitReason` — process
exit codes per exception class) and `Node/RethrowPolicy.hs`
(`consensusRethrowPolicy` — the per-exception shutdown-vs-disconnect
policy). The TPU build's analog classifies every failure the durable
store and the replay pipeline can raise into a DISPOSITION that the
recovery machinery consults:

    REFUSE     loud, classified, immediate: another process holds the
               DB lock, the DB belongs to a different chain (marker
               mismatch). Retrying or degrading would be WRONG — the
               operator asked for something the store must not do.
    REPAIR     the durable store is corrupt in a way the open-with-
               repair scan owns (truncate-and-quarantine, index
               rebuild): bubbles to the store layer, never absorbed by
               the per-window recovery ladder.
    RECOVER    transient device/runtime/I-O faults (and the chaos
               taxonomy, transient by contract): the
               RecoverySupervisor's degradation ladder may absorb it.
    PROPAGATE  a programming bug (TypeError class): recovery must
               never mask a wrong program as a flaky device.
"""

from __future__ import annotations

from enum import Enum


class ExitReason(Enum):
    """Node/Exit.hs:63 ExitReason — process exit triage."""

    SUCCESS = 0
    GENERIC = 1
    CONFIG_ERROR = 2
    DB_CORRUPTION = 3
    NETWORK_ERROR = 4


class Disposition(Enum):
    """What the failure-handling machinery may DO about an exception
    (the consensusRethrowPolicy analog for the batched pipeline)."""

    REFUSE = "refuse"
    REPAIR = "repair"
    RECOVER = "recover"
    PROPAGATE = "propagate"


def to_exit_reason(exc: BaseException) -> ExitReason:
    """toExitReason (Node/Exit.hs:100)."""
    from ..storage.guard import DbLocked, DbMarkerMismatch
    from ..storage.immutable import ImmutableDBError
    from ..storage.repair import QuarantineError

    if isinstance(exc, (DbLocked, DbMarkerMismatch, QuarantineError)):
        return ExitReason.CONFIG_ERROR
    if isinstance(exc, ImmutableDBError):
        return ExitReason.DB_CORRUPTION
    if isinstance(exc, (ConnectionError, OSError)):
        return ExitReason.NETWORK_ERROR
    return ExitReason.GENERIC


def triage(exc: BaseException) -> Disposition:
    """The per-class repair-vs-refuse-vs-recover policy. The recovery
    supervisor (obs/recovery.recoverable) absorbs ONLY `RECOVER`;
    `REFUSE` and `REPAIR` classes propagate to the layer that owns
    them (the caller / the open-with-repair scan), and `PROPAGATE`
    bugs always surface raw."""
    from ..storage.guard import DbLocked, DbMarkerMismatch
    from ..storage.immutable import ImmutableDBError
    from ..storage.repair import QuarantineError
    from ..testing import chaos

    if isinstance(exc, (DbLocked, DbMarkerMismatch, QuarantineError)):
        # QuarantineError: the environment cannot honor quarantine-
        # never-delete (ENOSPC, unwritable dir) — repairing anyway
        # would destroy the bytes the repair promised to keep
        return Disposition.REFUSE
    if isinstance(exc, ImmutableDBError):
        # on-disk corruption: truncate-and-repair territory — the
        # window ladder re-dispatching the same corrupt bytes would
        # loop, and masking it would be silence
        return Disposition.REPAIR
    if isinstance(exc, chaos.ChaosError):
        return Disposition.RECOVER  # transient by construction
    if isinstance(exc, (OSError, MemoryError)):
        return Disposition.RECOVER
    # jaxlib's XlaRuntimeError (module path varies across jax versions)
    # and the RuntimeError family PJRT surfaces through
    if isinstance(exc, RuntimeError) or "XlaRuntimeError" in type(exc).__name__:
        return Disposition.RECOVER
    return Disposition.PROPAGATE
