"""Exception triage: exit reasons + the repair-vs-refuse-vs-recover map.

Reference: `Node/Exit.hs:63` (`ExitReason` / `toExitReason` — process
exit codes per exception class) and `Node/RethrowPolicy.hs`
(`consensusRethrowPolicy` — the per-exception shutdown-vs-disconnect
policy). The TPU build's analog classifies every failure the durable
store and the replay pipeline can raise into a DISPOSITION that the
recovery machinery consults:

    REFUSE     loud, classified, immediate: another process holds the
               DB lock, the DB belongs to a different chain (marker
               mismatch). Retrying or degrading would be WRONG — the
               operator asked for something the store must not do.
    REPAIR     the durable store is corrupt in a way the open-with-
               repair scan owns (truncate-and-quarantine, index
               rebuild): bubbles to the store layer, never absorbed by
               the per-window recovery ladder.
    RECOVER    transient device/runtime/I-O faults (and the chaos
               taxonomy, transient by contract): the
               RecoverySupervisor's degradation ladder may absorb it.
    PROPAGATE  a programming bug (TypeError class): recovery must
               never mask a wrong program as a flaky device.
"""

from __future__ import annotations

from enum import Enum


class ExitReason(Enum):
    """Node/Exit.hs:63 ExitReason — process exit triage."""

    SUCCESS = 0
    GENERIC = 1
    CONFIG_ERROR = 2
    DB_CORRUPTION = 3
    NETWORK_ERROR = 4


class Disposition(Enum):
    """What the failure-handling machinery may DO about an exception
    (the consensusRethrowPolicy analog for the batched pipeline)."""

    REFUSE = "refuse"
    REPAIR = "repair"
    RECOVER = "recover"
    PROPAGATE = "propagate"


# The one place a failure class gets its disposition. Keyed by CLASS
# NAME (walked along type(exc).__mro__, so a subclass inherits its
# family's row unless it has its own) because the analysis plane reads
# this table statically: octflow (analysis/flow.py FLOW301) refuses a
# `raise` of a custom exception class in the crash/verdict-bearing
# modules unless the class — or an ancestor — has a row here. Adding a
# failure class to storage/tools/protocol is therefore a two-line
# change by construction: the class, and its conscious classification.
DISPOSITIONS: dict[str, Disposition] = {
    # REFUSE — the operator asked for something the store/forger must
    # not do; retrying or degrading would be WRONG
    "DbLocked": Disposition.REFUSE,
    "DbMarkerMismatch": Disposition.REFUSE,
    "QuarantineError": Disposition.REFUSE,
    "KESKeyExpired": Disposition.REFUSE,      # forging with a dead key
    "KESBeforeStart": Disposition.REFUSE,     # cert not yet valid
    "OperationalCertIssueError": Disposition.REFUSE,
    "AdmissionRefused": Disposition.REFUSE,   # malformed serve submission
    # REPAIR — on-disk corruption the open-with-repair scan owns;
    # never absorbed by the per-window ladder, never masked
    "ImmutableDBError": Disposition.REPAIR,   # + MissingBlock subclass
    "MalformedBlock": Disposition.REPAIR,     # unparseable block bytes
    # RECOVER — transient by contract: the supervisor ladder may absorb
    "ChaosError": Disposition.RECOVER,        # the whole chaos taxonomy
    "OSError": Disposition.RECOVER,           # + ConnectionError family
    "MemoryError": Disposition.RECOVER,
    "RuntimeError": Disposition.RECOVER,      # the PJRT surface family
    # PROPAGATE — verdicts and contract violations: recovery must never
    # re-dispatch a header the protocol already judged, and chain
    # selection (not the ladder) owns invalid-block routing
    "PraosValidationError": Disposition.PROPAGATE,  # + every subclass
    "ConsensusError": Disposition.PROPAGATE,        # Bft/PBft verdicts
    "HeaderEnvelopeError": Disposition.PROPAGATE,
    "InvalidBlock": Disposition.PROPAGATE,    # chain selection owns it
    "MissingBlockError": Disposition.PROPAGATE,  # caller contract bug
    "BlockGCed": Disposition.PROPAGATE,       # caller contract bug
}


def to_exit_reason(exc: BaseException) -> ExitReason:
    """toExitReason (Node/Exit.hs:100)."""
    from ..storage.guard import DbLocked, DbMarkerMismatch
    from ..storage.immutable import ImmutableDBError
    from ..storage.repair import QuarantineError

    if isinstance(exc, (DbLocked, DbMarkerMismatch, QuarantineError)):
        return ExitReason.CONFIG_ERROR
    if isinstance(exc, ImmutableDBError):
        return ExitReason.DB_CORRUPTION
    if isinstance(exc, (ConnectionError, OSError)):
        return ExitReason.NETWORK_ERROR
    return ExitReason.GENERIC


def triage(exc: BaseException) -> Disposition:
    """The per-class repair-vs-refuse-vs-recover policy. The recovery
    supervisor (obs/recovery.recoverable) absorbs ONLY `RECOVER`;
    `REFUSE` and `REPAIR` classes propagate to the layer that owns
    them (the caller / the open-with-repair scan), and `PROPAGATE`
    bugs always surface raw.

    The MRO walk makes the DISPOSITIONS table positional: the most
    derived classified ancestor wins, so `MissingBlock` rides its
    `ImmutableDBError` REPAIR row while `DbLocked` (a plain Exception)
    hits its own REFUSE row before any family default could."""
    for klass in type(exc).__mro__:
        d = DISPOSITIONS.get(klass.__name__)
        if d is not None:
            return d
    # jaxlib's XlaRuntimeError moved modules across jax versions and is
    # not importable without jax — matched by name, not by row
    if "XlaRuntimeError" in type(exc).__name__:
        return Disposition.RECOVER
    return Disposition.PROPAGATE
