"""NodeKernel: the node's organs wired together.

Reference: `ouroboros-consensus-diffusion` `NodeKernel.hs:88-114` — the
kernel owns the ChainDB, mempool, per-peer candidate map and the forging
loop (`forkBlockForging`, NodeKernel.hs:237-436). Here the kernel is a
plain object whose loops are sim-runtime generator tasks (utils/sim.py),
so an N-node network runs deterministically in one process
(testing/threadnet.py) — the ThreadNet architecture.

Forging loop per slot (NodeKernel.hs:253-425 condensed to the mock-era
shape): current tip → past ledger → forecast ledger view → tick chain-dep
state → check_is_leader (VRF eval) → tick ledger → mempool snapshot →
forge_block (KES sign) → add to own ChainDB → mempool sync on adoption.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..block.abstract import Point
from ..block.forge import forge_block
from ..mempool import Mempool
from ..miniprotocol.chainsync import Candidate
from ..protocol import praos as praos_mod
from ..utils.sim import Sleep


@dataclass
class SlotClock:
    """BlockchainTime analog (BlockchainTime/API.hs:30): virtual-time
    slot clock — slot s starts at t0 + s*slot_length."""

    slot_length: float = 1.0
    t0: float = 0.0

    def slot_of(self, now: float) -> int:
        return max(0, int((now - self.t0) / self.slot_length))

    def start_of(self, slot: int) -> float:
        return self.t0 + slot * self.slot_length


class NodeKernel:
    """One node: ChainDB + mempool + protocol + credentials."""

    def __init__(
        self,
        name: str,
        chain_db,
        protocol,
        ledger,
        pool=None,  # PoolCredentials when this node forges
        clock: SlotClock | None = None,
        trace: Callable[[str], None] = lambda s: None,
    ):
        self.name = name
        self.chain_db = chain_db
        self.protocol = protocol
        self.ledger = ledger
        self.pool = pool
        self.clock = clock or SlotClock()
        self.trace = trace
        self.candidates: dict[str, Candidate] = {}  # per-peer
        self.mempool = Mempool(
            ledger,
            lambda: (
                chain_db.current_ledger().ledger_state,
                chain_db.current_ledger().header_state.tip.slot
                if chain_db.current_ledger().header_state.tip
                else None,
            ),
        )
        self._ocert_counter = 0

    # -- hooks used by the miniprotocol clients ---------------------------

    def ledger_view_at(self, slot: int):
        """Forecast of the ledger view for `slot` (Forecast.hs) — the
        mock ledger's view is slot-independent within the horizon."""
        fc = self.ledger.ledger_view_forecast_at(
            self.chain_db.current_ledger().ledger_state
        )
        return fc.forecast_for(slot)

    def chain_dep_state_at(self, point: Point | None):
        """Protocol state after `point` on OUR chain (for seeding a
        peer candidate at the intersection)."""
        ext = self.chain_db.get_past_ledger(point)
        if ext is None:
            raise ValueError(f"{self.name}: no ledger state at {point}")
        return ext.header_state.chain_dep_state

    def prefer_candidate(self, cand_headers: list) -> bool:
        """preferAnchoredCandidate (BlockFetch/ClientInterface.hs): is
        the candidate strictly better than our current selection?"""
        if not cand_headers:
            return False
        ours = self.chain_db.tip_header()
        if ours is None:
            return True
        our_sv = self.protocol.select_view(ours)
        their_sv = self.protocol.select_view(cand_headers[-1])
        # compare_candidates > 0 iff `theirs` strictly preferred
        return self.protocol.compare_candidates(our_sv, their_sv) > 0

    # -- forging (NodeKernel.hs:237-436) ----------------------------------

    def forge_only(self, slot: int):
        """checkShouldForge + forgeBlock without the ChainDB add —
        returns the forged Block or None."""
        if self.pool is None:
            return None
        ext = self.chain_db.current_ledger()
        lview = self.ledger_view_at(slot)
        ticked = self.protocol.tick(lview, slot, ext.header_state.chain_dep_state)
        is_leader = self.protocol.check_is_leader(
            self._can_be_leader(), slot, ticked
        )
        if is_leader is None:
            return None
        tip = self.chain_db.tip_point()
        block_no = (self.chain_db.tip_block_no() or 0) + 1 if tip else 0
        snap = self.mempool.get_snapshot_for(
            self.ledger.tick(ext.ledger_state, slot).state, slot
        )
        return forge_block(
            self.protocol.params,
            self.pool,
            slot=slot,
            block_no=block_no,
            prev_hash=tip.hash_ if tip else None,
            epoch_nonce=ticked.state.epoch_nonce,
            txs=snap.tx_bytes(),
            ocert_counter=self._ocert_counter,
            is_leader=is_leader,
        )

    def _post_adoption(self, block, res) -> None:
        if res.selected:
            self.trace(
                f"{self.name}: forged+adopted block {block.block_no}@{block.slot}"
            )
            self.mempool.sync_with_ledger()
        else:
            # self-forged block not adopted — the adoption check would
            # purge its txs (NodeKernel.hs:402-425); sync covers it
            self.trace(f"{self.name}: forged block not adopted @{block.slot}")

    def try_forge(self, slot: int):
        """One forging opportunity: returns the forged Block or None."""
        block = self.forge_only(slot)
        if block is None:
            return None
        self._post_adoption(block, self.chain_db.add_block(block))
        return block

    def _can_be_leader(self):
        from ..testing.fixtures import can_be_leader

        return can_be_leader(self.pool, counter=self._ocert_counter)

    def forging_loop(self, n_slots: int):
        """Sim task: wake at every slot start (knownSlotWatcher,
        BlockchainTime/API.hs:59) and attempt to forge. Forged blocks go
        through the add-block queue like everyone else's
        (NodeKernel.hs:402 addBlockAsync + adoption wait), so a
        self-forged block never jumps ahead of enqueued peer blocks."""
        from ..utils.sim import Wait

        for slot in range(n_slots):
            # forge at the START of slot `slot` (virtual time
            # slot*slot_length), then sleep the slot out — forging after
            # the sleep would shift every block one slot late vs the clock
            block = self.forge_only(slot)
            if block is not None:
                p = self.chain_db.add_block_async(block)
                if p.result is None:
                    yield Wait(p.processed)
                self._post_adoption(block, p.result)
            yield Sleep(self.clock.slot_length)

    def on_chain_changed(self):
        """Post-adoption bookkeeping shared by fetch/forge paths."""
        self.mempool.sync_with_ledger()
