"""NodeKernel: the node's organs wired together.

Reference: `ouroboros-consensus-diffusion` `NodeKernel.hs:88-114` — the
kernel owns the ChainDB, mempool, per-peer candidate map and the forging
loop (`forkBlockForging`, NodeKernel.hs:237-436). Here the kernel is a
plain object whose loops are sim-runtime generator tasks (utils/sim.py),
so an N-node network runs deterministically in one process
(testing/threadnet.py) — the ThreadNet architecture.

Forging loop per slot (NodeKernel.hs:253-425 condensed to the mock-era
shape): current tip → past ledger → forecast ledger view → tick chain-dep
state → check_is_leader (VRF eval) → tick ledger → mempool snapshot →
forge_block (KES sign) → add to own ChainDB → mempool sync on adoption.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..block.abstract import Point
from ..block.forge import forge_block
from ..block.metrics import NodeMetrics
from ..ledger.abstract import OutsideForecastRange
from ..mempool import Mempool
from ..miniprotocol.chainsync import Candidate
from ..protocol import praos as praos_mod
from ..protocol.hotkey import HotKey, KESBeforeStart, KESKeyExpired, issue_ocert
from ..utils.sim import Sleep
from ..utils.trace import NodeTracers, ValidatedBatch


@dataclass
class SlotClock:
    """BlockchainTime analog (BlockchainTime/API.hs:30): virtual-time
    slot clock — slot s starts at t0 + s*slot_length."""

    slot_length: float = 1.0
    t0: float = 0.0

    def slot_of(self, now: float) -> int:
        return max(0, int((now - self.t0) / self.slot_length))

    def start_of(self, slot: int) -> float:
        return self.t0 + slot * self.slot_length


class NodeKernel:
    """One node: ChainDB + mempool + protocol + credentials."""

    def __init__(
        self,
        name: str,
        chain_db,
        protocol,
        ledger,
        pool=None,  # PoolCredentials when this node forges
        clock: SlotClock | None = None,
        trace: Callable[[str], None] = lambda s: None,
        hotkey: HotKey | None = None,  # carry an EVOLVED key across a
        ocert=None,                    # restart (with its certificate)
        ocert_counter: int = 0,
        forge_fn=None,  # block-type seam: forge_fn(node, slot, block_no,
        # prev_hash, ticked, is_leader, txs) -> Block; None = Praos
        can_be_leader=None,  # protocol-shaped leadership credential
        # (Block/Forging.hs canBeLeader): PBFT nodes pass their genesis
        # key INDEX, Praos nodes default to PraosCanBeLeader from `pool`
        tracers: NodeTracers | None = None,  # Tracers' record (one per
        # subsystem); batch_validation receives ValidatedBatch events
        metrics_registry=None,  # obs.MetricsRegistry: mirror NodeMetrics
        # into oct_node_* counters (the tracers->EKG/Prometheus bridge)
    ):
        self.name = name
        self.chain_db = chain_db
        self.protocol = protocol
        self.forge_fn = forge_fn
        self._can_be_leader_override = can_be_leader
        self.ledger = ledger
        self.pool = pool
        self.clock = clock or SlotClock()
        self.trace = trace
        self.candidates: dict[str, Candidate] = {}  # per-peer
        self.known_peers: list = []  # PeerSharing registry analog
        # FetchClientRegistry analog: cross-peer in-flight block claims
        # for bulk-sync de-duplication (miniprotocol/blockfetch.py)
        from ..miniprotocol.blockfetch import FetchRegistry

        self.fetch_registry = FetchRegistry()
        # BlockSupportsMetrics consumer (SupportsMetrics.hs): counts fed
        # from a dedicated follower on every adoption
        self.metrics = NodeMetrics()
        self.tracers = tracers if tracers is not None else NodeTracers()
        if metrics_registry is not None:
            self.metrics.bind(metrics_registry)
        # batch verdicts: the LedgerDB's batched push emits one
        # ValidatedBatch per fused device segment — fold it into
        # NodeMetrics (and on to the registry) and forward it to the
        # batch_validation tracer
        ldb = getattr(chain_db, "ledgerdb", None)
        if ldb is not None:
            ldb.tracer = self._on_validated_batch
        self._metrics_follower = chain_db.new_follower()
        self.mempool = Mempool(
            ledger,
            lambda: (
                chain_db.current_ledger().ledger_state,
                chain_db.current_ledger().header_state.tip.slot
                if chain_db.current_ledger().header_state.tip
                else None,
            ),
        )
        # forging credentials: an evolving HotKey + its operational
        # certificate (Ledger/HotKey.hs; ocert counter increments on
        # every re-issue, checked by Praos.hs:585-605)
        if (hotkey is None) != (ocert is None):
            # a hot key is only usable with the certificate that binds
            # it to the cold key — a mismatched pair forges blocks every
            # peer rejects (KES vk / period mismatch)
            raise ValueError("hotkey and ocert must be carried together")
        self._ocert_counter = ocert_counter
        self.hotkey = hotkey
        self._ocert = ocert
        if (pool is not None and hotkey is None
                and hasattr(protocol.params, "max_kes_evolutions")):
            # KES-capable protocols only: a PBFT (Byron) node signs with
            # its delegate's cold Ed25519 key, no hot key to evolve
            # fresh node: derive the hot key from the pool's root seed.
            # A restart carrying an evolved key passes it in instead —
            # re-deriving here would resurrect forgotten (forward-secure)
            # evolutions and waste the 2^depth vk-tree derivation.
            self._install_hotkey(pool.kes_seed, counter=0, kes_period=0)
            # provisional: re-issued for the actual start slot's KES
            # period when the forging loop starts (see forging_loop) —
            # the reference issues the OCert at the key-creation period
            # (Ledger/HotKey.hs), not period 0
            self._hotkey_provisional = True

    def _install_hotkey(self, kes_seed: bytes, counter: int, kes_period: int):
        # any explicit (re)install supersedes the constructor's
        # provisional period-0 key — without this, a rekey() before the
        # forging loop starts would be silently discarded and replaced
        # by a root-seed re-derivation (forward-security violation)
        self._hotkey_provisional = False
        self.hotkey = HotKey(
            kes_seed,
            self.pool.kes_depth,
            kes_period,
            self.protocol.params.max_kes_evolutions,
        )
        self._ocert_counter = counter
        self._ocert = issue_ocert(
            self.pool.cold_seed, self.hotkey.vk, counter, kes_period
        )

    def rekey(self, slot: int, new_kes_seed: bytes | None = None) -> None:
        """Operational re-keying (ThreadNet/Util/Rekeying.hs analog):
        forget the old hot key, start a fresh one at `slot`'s KES period,
        re-issue the ocert with counter+1."""
        import hashlib

        if self.hotkey is not None:
            self.hotkey.forget()
        if new_kes_seed is None:
            new_kes_seed = hashlib.blake2b(
                b"rekey" + self.pool.kes_seed + bytes([self._ocert_counter + 1]),
                digest_size=32,
            ).digest()
        kp = self.protocol.params.kes_period_of(slot)
        self._install_hotkey(new_kes_seed, self._ocert_counter + 1, kp)
        self.trace(f"{self.name}: rekeyed at slot {slot} (counter {self._ocert_counter})")

    # -- hooks used by the miniprotocol clients ---------------------------

    def ledger_view_at(self, slot: int):
        """Forecast of the ledger view for `slot` (Forecast.hs) — the
        mock ledger's view is slot-independent within the horizon."""
        fc = self.ledger.ledger_view_forecast_at(
            self.chain_db.current_ledger().ledger_state
        )
        return fc.forecast_for(slot)

    def chain_dep_state_at(self, point: Point | None):
        """Protocol state after `point` on OUR chain (for seeding a
        peer candidate at the intersection) — served from the ChainDB's
        k-deep HeaderStateHistory (HeaderStateHistory.hs), not the full
        LedgerDB checkpoints."""
        hs = self.chain_db.header_state_at(point)
        if hs is None:
            raise ValueError(f"{self.name}: no header state at {point}")
        return hs.chain_dep_state

    def prefer_candidate(self, cand_headers: list) -> bool:
        """preferAnchoredCandidate (BlockFetch/ClientInterface.hs): is
        the candidate strictly better than our current selection?"""
        if not cand_headers:
            return False
        ours = self.chain_db.tip_header()
        if ours is None:
            return True
        our_sv = self.protocol.select_view(ours)
        their_sv = self.protocol.select_view(cand_headers[-1])
        # compare_candidates > 0 iff `theirs` strictly preferred
        return self.protocol.compare_candidates(our_sv, their_sv) > 0

    # -- forging (NodeKernel.hs:237-436) ----------------------------------

    def forge_only(self, slot: int):
        """checkShouldForge + forgeBlock without the ChainDB add —
        returns the forged Block or None."""
        if self.pool is None:
            return None
        ext = self.chain_db.current_ledger()
        try:
            lview = self.ledger_view_at(slot)
        except OutsideForecastRange as e:
            # checkShouldForge's ForgeStateUpdateError shape: the slot
            # is beyond what our (possibly pre-era-boundary) tip can
            # forecast — skip the opportunity, do NOT kill the loop
            self.metrics.inc("blocks_could_not_forge")
            self.trace(f"{self.name}: no forecast for slot {slot}: {e}")
            return None
        ticked = self.protocol.tick(lview, slot, ext.header_state.chain_dep_state)
        is_leader = self.protocol.check_is_leader(
            self._can_be_leader(), slot, ticked
        )
        if is_leader is None:
            return None
        self.metrics.inc("slots_led")
        tip = self.chain_db.tip_point()
        block_no = (self.chain_db.tip_block_no() or 0) + 1 if tip else 0
        snap = self.mempool.get_snapshot_for(
            self.ledger.tick(ext.ledger_state, slot).state, slot
        )
        try:
            if self.forge_fn is not None:
                return self.forge_fn(
                    self, slot, block_no,
                    tip.hash_ if tip else None,
                    ticked, is_leader, snap.tx_bytes(),
                )
            return forge_block(
                self.protocol.params,
                self.pool,
                slot=slot,
                block_no=block_no,
                prev_hash=tip.hash_ if tip else None,
                epoch_nonce=ticked.state.epoch_nonce,
                txs=snap.tx_bytes(),
                is_leader=is_leader,
                hotkey=self.hotkey,
                ocert=self._ocert,
            )
        except (KESKeyExpired, KESBeforeStart) as e:
            # checkShouldForge's CannotForge outcome (Block/Forging.hs):
            # won the slot but the hot key cannot sign — trace, skip
            self.metrics.inc("blocks_could_not_forge")
            self.trace(f"{self.name}: CannotForge at slot {slot}: {e}")
            return None

    def _on_validated_batch(self, ev) -> None:
        """One fused device batch completed (storage/ledgerdb batched
        push): fold the verdict counts and forward the typed event."""
        if isinstance(ev, ValidatedBatch):
            self.metrics.note_batch(ev)
        self.tracers.batch_validation(ev)

    def _drain_metrics(self) -> None:
        cold = self.pool.vk_cold if self.pool is not None else None
        for op in self._metrics_follower.take_updates():
            if op[0] == "addblock":
                self.metrics.note_adopted([op[1].header], cold)
            elif op[0] == "rollback":
                self.metrics.inc("chain_switches")

    def _post_adoption(self, block, res) -> None:
        self.metrics.inc("blocks_forged")
        self._drain_metrics()
        if res.selected:
            self.trace(
                f"{self.name}: forged+adopted block {block.block_no}@{block.slot}"
            )
            self.mempool.sync_with_ledger()
        else:
            # self-forged block not adopted — the adoption check would
            # purge its txs (NodeKernel.hs:402-425); sync covers it
            self.trace(f"{self.name}: forged block not adopted @{block.slot}")

    def try_forge(self, slot: int):
        """One forging opportunity: returns the forged Block or None."""
        block = self.forge_only(slot)
        if block is None:
            return None
        self._post_adoption(block, self.chain_db.add_block(block))
        return block

    def _can_be_leader(self):
        if self._can_be_leader_override is not None:
            return self._can_be_leader_override
        return praos_mod.PraosCanBeLeader(
            ocert=self._ocert,
            vk_cold=self.pool.vk_cold,
            vrf_sign_seed=self.pool.vrf_seed,
        )

    def forging_loop(self, n_slots: int, start_slot: int = 0):
        """Sim task: wake at every slot start (knownSlotWatcher,
        BlockchainTime/API.hs:59) and attempt to forge. Forged blocks go
        through the add-block queue like everyone else's
        (NodeKernel.hs:402 addBlockAsync + adoption wait), so a
        self-forged block never jumps ahead of enqueued peer blocks.
        `start_slot` supports ThreadNet join plans / restarts — the
        caller aligns the spawn time with that slot's start."""
        from ..utils.sim import Wait

        # a provisionally period-0 hot key (fresh node, no explicit key
        # carried in) is issued properly for the START slot's KES period:
        # a node joining at a later wallclock must not waste evolutions
        # covering already-elapsed periods, nor expire at absolute period
        # max_kes_evolutions regardless of its start time
        if getattr(self, "_hotkey_provisional", False):
            self._hotkey_provisional = False
            kp = self.protocol.params.kes_period_of(start_slot)
            if kp > 0:
                self.hotkey.forget()
                self._install_hotkey(
                    self.pool.kes_seed, counter=self._ocert_counter,
                    kes_period=kp,
                )

        for slot in range(start_slot, n_slots):
            # forge at the START of slot `slot` (virtual time
            # slot*slot_length), then sleep the slot out — forging after
            # the sleep would shift every block one slot late vs the clock
            block = self.forge_only(slot)
            if block is not None:
                p = self.chain_db.add_block_async(block)
                if p.result is None:
                    yield Wait(p.processed)
                self._post_adoption(block, p.result)
            yield Sleep(self.clock.slot_length)

    def on_chain_changed(self):
        """Post-adoption bookkeeping shared by fetch/forge paths."""
        self._drain_metrics()
        self.mempool.sync_with_ledger()
