"""TCP transport for the node-to-node bundle: framed CBOR over asyncio
sockets, multiplexed mini-protocol channels, full versioned wiring.

Reference: the reference hands its mini-protocol `Apps` to
`ouroboros-network`'s diffusion — session-typed protocols, CBOR codecs,
multiplexed over ONE TCP bearer per peer (`Node.hs:103-120`,
`Network/NodeToNode.hs:434-466`). This module is that layer for the TPU
framework: one socket per peer, each mini-protocol on its own mux
channel (`[channel_id, payload]` frames), the wire handshake FIRST, then
exactly the version-gated app set — the same `Apps` assembly as the
in-memory `node/apps.py`, interpreted by `utils/aio.AsyncRuntime`
instead of the deterministic Sim (the IOLike seam).

The framing (4-byte length prefix + deterministic CBOR) is shared with
`tools/immdb_server.py`, which predates this module and now imports it.
"""

from __future__ import annotations

import asyncio
from typing import Any

from ..block.abstract import Point
from ..miniprotocol import blockfetch, chainsync, handshake, txsubmission
from ..miniprotocol.chainsync import Candidate
from ..miniprotocol.rethrow import peer_guard
from ..utils import cbor
from ..utils.aio import AsyncRuntime
from ..utils.sim import Channel

# -- wire encoding (shared with immdb_server) --------------------------------


def to_wire(obj) -> Any:
    """Anything a mini-protocol or query can produce -> CBOR-encodable.
    TOTAL by construction: known rich types get tagged encodings;
    dataclasses (query results like PoolParams/ShelleyGenesis, debug
    state dumps) travel as tagged field maps and arrive as plain dicts
    (the reference likewise serializes query results — the class
    identity is a codec concern, not wire data); anything else falls
    back to its repr — a lossy but NON-FATAL encoding, so an exotic
    result can never kill a server task mid-Send."""
    import dataclasses
    from fractions import Fraction

    if obj is None or isinstance(obj, (bytes, str, bool, float)):
        return obj
    if isinstance(obj, Point):
        return ["pt", obj.slot, obj.hash_]
    if isinstance(obj, handshake.VersionData):
        return ["vd", obj.network_magic]
    if isinstance(obj, Fraction):
        return ["fr", obj.numerator, obj.denominator]
    from ..ledger.mary import MaryValue

    if isinstance(obj, MaryValue):
        return ["mv", int(obj), obj.to_triples()]
    if isinstance(obj, int):
        return obj
    if isinstance(obj, dict):
        return ["map", [[to_wire(k), to_wire(v)] for k, v in obj.items()]]
    if isinstance(obj, (set, frozenset)):
        try:
            members = sorted(obj)
        except TypeError:  # unorderable mix: deterministic repr order
            members = sorted(obj, key=repr)
        return ["set", [to_wire(x) for x in members]]
    if isinstance(obj, (list, tuple)):
        return [to_wire(x) for x in obj]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return ["dc", type(obj).__name__, [
            [f.name, to_wire(getattr(obj, f.name))]
            for f in dataclasses.fields(obj)
        ]]
    return ["repr", repr(obj)]


def from_wire(obj) -> Any:
    from fractions import Fraction

    if isinstance(obj, list):
        if len(obj) == 3 and obj[0] == "pt":
            return Point(obj[1], obj[2])
        if len(obj) == 2 and obj[0] == "vd":
            return handshake.VersionData(network_magic=obj[1])
        if len(obj) == 3 and obj[0] == "fr":
            return Fraction(obj[1], obj[2])
        if len(obj) == 3 and obj[0] == "mv":
            from ..ledger.mary import MaryValue

            return MaryValue.from_triples(obj[1], obj[2])
        if len(obj) == 2 and obj[0] == "map" and isinstance(obj[1], list):
            return {from_wire(k): from_wire(v) for k, v in obj[1]}
        if len(obj) == 2 and obj[0] == "set" and isinstance(obj[1], list):
            return frozenset(from_wire(x) for x in obj[1])
        if len(obj) == 3 and obj[0] == "dc" and isinstance(obj[2], list):
            # dataclass results arrive as {"__type__": name, **fields}
            out = {from_wire(k): from_wire(v) for k, v in obj[2]}
            out["__type__"] = obj[1]
            return out
        if len(obj) == 2 and obj[0] == "repr":
            return ("opaque", obj[1])
        return tuple(from_wire(x) for x in obj)
    return obj


def frame(msg) -> bytes:
    data = cbor.encode(to_wire(msg))
    return len(data).to_bytes(4, "big") + data


async def read_frame(reader: asyncio.StreamReader) -> Any:
    hdr = await reader.readexactly(4)
    n = int.from_bytes(hdr, "big")
    return from_wire(cbor.decode(await reader.readexactly(n)))


# -- mux ---------------------------------------------------------------------


class RemoteChannel(Channel):
    """A Channel whose Send effect goes straight to the socket (the
    AsyncRuntime checks for `remote_send`)."""

    def __init__(self, mux: "Mux", chan_id: str):
        super().__init__(name=chan_id)
        self._mux = mux
        self.chan_id = chan_id

    def remote_send(self, msg) -> None:
        self._mux.send(self.chan_id, msg)


class Mux:
    """One TCP bearer, many mini-protocol channels (the `mux` analog):
    outbound messages are `[chan_id, payload]` frames; the rx pump
    routes inbound frames to registered local channels."""

    def __init__(self, reader, writer, runtime: AsyncRuntime):
        self.reader = reader
        self.writer = writer
        self.runtime = runtime
        self._inbound: dict[str, Channel] = {}
        self.closed = asyncio.Event()

    def outbound(self, chan_id: str) -> RemoteChannel:
        return RemoteChannel(self, chan_id)

    def inbound(self, chan_id: str) -> Channel:
        ch = Channel(name=chan_id)
        self._inbound[chan_id] = ch
        return ch

    def send(self, chan_id: str, msg) -> None:
        self.writer.write(frame([chan_id, msg]))

    async def pump(self) -> None:
        """Route inbound frames until the peer hangs up."""
        try:
            while True:
                chan_id, payload = await read_frame(self.reader)
                ch = self._inbound.get(chan_id)
                if ch is not None:
                    self.runtime.deliver(ch, payload)
                # unknown channel: the peer speaks a protocol this side
                # did not negotiate — drop the frame (mux discards, the
                # version gate already agreed what runs)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self.closed.set()
            try:
                self.writer.close()
            except Exception:
                pass

    def channel_pair(self, proto: str, *, initiator: bool):
        """(rx, tx) for this side of `proto`: the initiator transmits on
        `proto:req` and receives on `proto:rsp`; the responder mirrors."""
        if initiator:
            return self.inbound(f"{proto}:rsp"), self.outbound(f"{proto}:req")
        return self.inbound(f"{proto}:req"), self.outbound(f"{proto}:rsp")


async def open_mux(
    reader,
    writer,
    runtime: AsyncRuntime,
    versions: dict[int, handshake.VersionData],
    *,
    initiator: bool,
    label: str,
) -> tuple[Mux, int]:
    """The per-connection scaffolding every endpoint shares: fresh Mux,
    rx pump, wire handshake FIRST (initiator proposes, responder picks),
    cleanup on refusal. Returns (mux, negotiated_version); the pump task
    is parked on mux.pump_task."""
    mux = Mux(reader, writer, runtime)
    if initiator:
        hs_gen = handshake.client(
            mux.inbound("handshake:rsp"), mux.outbound("handshake:req"),
            versions,
        )
    else:
        hs_gen = handshake.server(
            mux.inbound("handshake:req"), mux.outbound("handshake:rsp"),
            versions,
        )
    pump = asyncio.ensure_future(mux.pump())
    try:
        version, _data = await runtime.spawn(hs_gen, label)
    except BaseException:
        pump.cancel()
        try:
            writer.close()
        except Exception:
            pass
        raise
    mux.pump_task = pump
    return mux, version


def _default_versions(table: dict) -> dict[int, handshake.VersionData]:
    return {v: handshake.VersionData(network_magic=764824073) for v in table}


# -- the versioned bundle over a mux ----------------------------------------


def _spawn_bundle(
    runtime: AsyncRuntime,
    mux: Mux,
    node,
    peer_name: str,
    version: int,
    *,
    initiator: bool,
    trace=lambda s: None,
) -> list:
    """Spawn THIS side's half of the version-gated app set — the same
    protocol gating as node/apps.py node_to_node_apps, but each side
    builds only its own tasks, channels bound to the mux."""
    enabled = handshake.NODE_TO_NODE_VERSIONS[version]
    tasks = []

    def disconnect():
        for t in tasks:
            t.cancel()
        node.candidates.pop(peer_name, None)

    def spawn(name, gen):
        label = f"{name}:{peer_name}"
        tasks.append(
            runtime.spawn(peer_guard(gen, label, trace, disconnect), label)
        )

    if initiator:
        cand = Candidate()
        node.candidates[peer_name] = cand
        if "chainsync" in enabled:
            rx, tx = mux.channel_pair("chainsync", initiator=True)
            spawn("chainsync:client",
                  chainsync.client(node, peer_name, rx, tx, cand))
        if "blockfetch" in enabled:
            rx, tx = mux.channel_pair("blockfetch", initiator=True)
            spawn("blockfetch:client",
                  blockfetch.client(node, peer_name, rx, tx, cand))
        if "txsubmission2" in enabled:
            rx, tx = mux.channel_pair("txsubmission", initiator=True)
            spawn("txsubmission:inbound",
                  txsubmission.inbound(node, peer_name, rx, tx))
        if "keepalive" in enabled:
            rx, tx = mux.channel_pair("keepalive", initiator=True)
            spawn("keepalive:client", txsubmission.keepalive_client(rx, tx))
        if "peersharing" in enabled:
            rx, tx = mux.channel_pair("peersharing", initiator=True)
            spawn("peersharing:client",
                  txsubmission.peersharing_client(rx, tx, 4))
    else:
        if "chainsync" in enabled:
            rx, tx = mux.channel_pair("chainsync", initiator=False)
            spawn("chainsync:server",
                  chainsync.server(node.chain_db, rx, tx))
        if "blockfetch" in enabled:
            rx, tx = mux.channel_pair("blockfetch", initiator=False)
            spawn("blockfetch:server",
                  blockfetch.server(node.chain_db, rx, tx))
        if "txsubmission2" in enabled:
            rx, tx = mux.channel_pair("txsubmission", initiator=False)
            spawn("txsubmission:outbound",
                  txsubmission.outbound(node, rx, tx))
        if "keepalive" in enabled:
            rx, tx = mux.channel_pair("keepalive", initiator=False)
            spawn("keepalive:server",
                  txsubmission.keepalive_server(rx, tx))
        if "peersharing" in enabled:
            rx, tx = mux.channel_pair("peersharing", initiator=False)
            spawn("peersharing:server",
                  txsubmission.peersharing_server(node, rx, tx))
    return tasks


async def serve_node(
    node,
    runtime: AsyncRuntime,
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    versions: dict[int, handshake.VersionData] | None = None,
    trace=lambda s: None,
):
    """Listen for peers; per connection: wire handshake (responder),
    then the responder half of the bundle. Returns the asyncio server
    (its .sockets[0].getsockname()[1] is the bound port)."""
    ours = versions if versions is not None else _default_versions(
        handshake.NODE_TO_NODE_VERSIONS
    )

    async def handle(reader, writer):
        peer = writer.get_extra_info("peername")
        tasks: list = []
        mux = None
        try:
            mux, version = await open_mux(
                reader, writer, runtime, ours,
                initiator=False, label=f"handshake:{peer}",
            )
            trace(f"{node.name}: peer {peer} negotiated v{version}")
            tasks = _spawn_bundle(
                runtime, mux, node, f"tcp:{peer}", version,
                initiator=False, trace=trace,
            )
            await mux.closed.wait()
        except handshake.HandshakeRefused as e:
            trace(f"{node.name}: refused {peer}: {e}")
        finally:
            for t in tasks:
                t.cancel()
            if mux is not None:
                mux.pump_task.cancel()

    return await asyncio.start_server(handle, host, port)


async def serve_node_to_client(
    node,
    runtime: AsyncRuntime,
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    versions: dict[int, handshake.VersionData] | None = None,
    trace=lambda s: None,
):
    """The node-to-client side over TCP (Network/NodeToClient.hs — the
    reference serves wallets/CLIs over a local socket): wire handshake
    against NODE_TO_CLIENT_VERSIONS, then the version-gated local bundle
    (LocalStateQuery, LocalTxSubmission, LocalTxMonitor). The negotiated
    version also gates the query vocabulary
    (localstate.QUERY_MIN_VERSION)."""
    from ..miniprotocol import localstate

    ours = versions if versions is not None else _default_versions(
        handshake.NODE_TO_CLIENT_VERSIONS
    )

    async def handle(reader, writer):
        peer = writer.get_extra_info("peername")
        tasks: list = []
        mux = None
        try:
            mux, version = await open_mux(
                reader, writer, runtime, ours,
                initiator=False, label=f"n2c-handshake:{peer}",
            )
            enabled = handshake.NODE_TO_CLIENT_VERSIONS[version]
            if "localstatequery" in enabled:
                rx, tx = mux.channel_pair("localstatequery", initiator=False)
                tasks.append(runtime.spawn(
                    localstate.state_query_server(
                        node, rx, tx, version=version
                    ),
                    f"lsq:{peer}",
                ))
            if "localtxsubmission" in enabled:
                rx, tx = mux.channel_pair(
                    "localtxsubmission", initiator=False
                )
                tasks.append(runtime.spawn(
                    localstate.tx_submission_server(node, rx, tx),
                    f"lts:{peer}",
                ))
            if "localtxmonitor" in enabled:
                rx, tx = mux.channel_pair("localtxmonitor", initiator=False)
                tasks.append(runtime.spawn(
                    localstate.tx_monitor_server(node, rx, tx),
                    f"ltm:{peer}",
                ))
            await mux.closed.wait()
        except handshake.HandshakeRefused as e:
            trace(f"{node.name}: refused n2c {peer}: {e}")
        finally:
            for t in tasks:
                t.cancel()
            if mux is not None:
                mux.pump_task.cancel()

    return await asyncio.start_server(handle, host, port)


class LocalClient:
    """A minimal node-to-client session over TCP: handshake, then
    request/reply on the local protocols (the wallet/CLI side)."""

    def __init__(self, mux: Mux, runtime: AsyncRuntime, version: int):
        self.mux = mux
        self.runtime = runtime
        self.version = version
        self._chans: dict[str, tuple] = {}

    @classmethod
    async def connect(cls, runtime: AsyncRuntime, host: str, port: int, *,
                      versions=None):
        ours = versions if versions is not None else _default_versions(
            handshake.NODE_TO_CLIENT_VERSIONS
        )
        reader, writer = await asyncio.open_connection(host, port)
        mux, version = await open_mux(
            reader, writer, runtime, ours,
            initiator=True, label="n2c-handshake",
        )
        return cls(mux, runtime, version)

    def _chan(self, proto: str):
        if proto not in self._chans:
            rx, tx = self.mux.channel_pair(proto, initiator=True)
            self._chans[proto] = (rx, tx)
        return self._chans[proto]

    async def request(self, proto: str, msg) -> Any:
        """One request/reply; raises ConnectionError if the connection
        dies mid-request instead of blocking forever."""
        rx, tx = self._chan(proto)
        self.runtime.send(tx, msg)
        await self.mux.writer.drain()
        get = asyncio.ensure_future(self.runtime._q(rx).get())
        closed = asyncio.ensure_future(self.mux.closed.wait())
        done, _pending = await asyncio.wait(
            {get, closed}, return_when=asyncio.FIRST_COMPLETED
        )
        if get in done:
            closed.cancel()
            return get.result()
        get.cancel()
        raise ConnectionError("node-to-client connection closed")

    def close(self) -> None:
        self.mux.pump_task.cancel()
        self.mux.writer.close()


async def connect_node(
    node,
    runtime: AsyncRuntime,
    host: str,
    port: int,
    *,
    versions: dict[int, handshake.VersionData] | None = None,
    trace=lambda s: None,
) -> Mux:
    """Dial a peer: wire handshake (initiator), then the initiator half
    of the bundle (ChainSync/BlockFetch/... clients feeding this node's
    ChainDB). Returns the live Mux; closing it tears the bundle down."""
    ours = versions if versions is not None else _default_versions(
        handshake.NODE_TO_NODE_VERSIONS
    )
    reader, writer = await asyncio.open_connection(host, port)
    mux, version = await open_mux(
        reader, writer, runtime, ours,
        initiator=True, label="handshake:client",
    )
    trace(f"{node.name}: connected to {host}:{port} at v{version}")
    # the peers we dialed are what WE can share (the PeerSharing
    # registry's outbound side, NodeKernel.hs:88-114)
    if [host, port] not in node.known_peers:
        node.known_peers.append([host, port])
    tasks = _spawn_bundle(
        runtime, mux, node, f"tcp:{host}:{port}", version,
        initiator=True, trace=trace,
    )
    mux.tasks = tasks  # for teardown by the caller
    return mux
