"""The Praos consensus protocol: chain-dependent state machine (host).

Semantics mirror the reference `ConsensusProtocol (Praos c)` instance
(ouroboros-consensus-protocol/.../Protocol/Praos.hs:364-606) exactly:

  * `tick`          = tickChainDepState (Praos.hs:407-432): epoch-boundary
                      nonce rotation.
  * `update`        = updateChainDepState (Praos.hs:441-466): KES checks,
                      then VRF checks, then `reupdate`.
  * `reupdate`      = reupdateChainDepState (Praos.hs:468-502): nonce and
                      ocert-counter bookkeeping, no crypto.
  * `check_is_leader` (Praos.hs:375-397): forging-side VRF evaluation +
                      leader threshold.

Crypto is routed through a `CryptoVerifier` so the host reference
implementation and the TPU batch backend (protocol/batch.py) are
interchangeable; `update` is the batch-of-1 spec the kernels are tested
against. Validation order and the error taxonomy follow
`PraosValidationErr` (Praos.hs:319-356) constructor by constructor.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from fractions import Fraction
from functools import cached_property
from typing import Mapping, Protocol as TyProtocol

from ..ops.host import ecvrf as host_ecvrf
from ..ops.host import ed25519 as host_ed25519
from ..ops.host import kes as host_kes
from . import nonces
from .leader import check_leader_value
from .nonces import Nonce
from .views import HeaderView, LedgerView, OCert, hash_key, hash_vrf_vk

# ---------------------------------------------------------------------------
# Parameters & epoch structure
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PraosParams:
    """Node-independent Praos parameters (Praos.hs:184-209)."""

    slots_per_kes_period: int = 129600
    max_kes_evolutions: int = 62
    security_param: int = 2160  # k
    active_slot_coeff: Fraction = Fraction(1, 20)  # f
    epoch_length: int = 432000  # fixed EpochInfo (slots per epoch)
    kes_depth: int = host_kes.DEFAULT_DEPTH  # CompactSum tree depth

    @cached_property
    def stability_window(self) -> int:
        """3k/f rounded up (cardano-ledger computeStabilityWindow).
        Cached: the Fraction division costs ~12 us and the replay fold
        asks once per header (frozen dataclass — the value is stored in
        the instance __dict__, bypassing the frozen setattr guard)."""
        w = 3 * self.security_param / self.active_slot_coeff
        return int(-(-w // 1))

    def epoch_of(self, slot: int) -> int:
        return slot // self.epoch_length

    def first_slot_of(self, epoch: int) -> int:
        return epoch * self.epoch_length

    def kes_period_of(self, slot: int) -> int:
        assert self.slots_per_kes_period > 0
        return slot // self.slots_per_kes_period

    def is_new_epoch(self, last_slot: int | None, slot: int) -> bool:
        """isNewEpoch (Protocol/Ledger/Util.hs:18-40); Origin -> epoch 0."""
        old_epoch = 0 if last_slot is None else self.epoch_of(last_slot)
        first = self.first_slot_of(old_epoch)
        epochs_after = max(0, slot - first) // self.epoch_length
        return old_epoch + epochs_after > old_epoch


# ---------------------------------------------------------------------------
# Chain-dependent state
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PraosState:
    """PraosState (Praos.hs:248-264): last slot, ocert counters, 5 nonces."""

    last_slot: int | None = None  # WithOrigin SlotNo
    ocert_counters: Mapping[bytes, int] = field(default_factory=dict)
    evolving_nonce: Nonce = None
    candidate_nonce: Nonce = None
    epoch_nonce: Nonce = None
    lab_nonce: Nonce = None  # nonce from last applied block's prev-hash
    last_epoch_block_nonce: Nonce = None


@dataclass(frozen=True)
class TickedPraosState:
    state: PraosState
    ledger_view: LedgerView


# ---------------------------------------------------------------------------
# Error taxonomy (PraosValidationErr, Praos.hs:319-356)
# ---------------------------------------------------------------------------


class PraosValidationError(Exception):
    """Base of the Praos validation error taxonomy."""


@dataclass
class VRFKeyUnknown(PraosValidationError):
    pool_key_hash: bytes


@dataclass
class VRFKeyWrongVRFKey(PraosValidationError):
    pool_key_hash: bytes
    registered_vrf_hash: bytes
    header_vrf_hash: bytes


@dataclass
class VRFKeyBadProof(PraosValidationError):
    slot: int
    epoch_nonce: Nonce


@dataclass
class VRFLeaderValueTooBig(PraosValidationError):
    leader_value: int
    sigma: Fraction
    active_slot_coeff: Fraction


@dataclass
class KESBeforeStartOCERT(PraosValidationError):
    ocert_start_period: int
    current_period: int


@dataclass
class KESAfterEndOCERT(PraosValidationError):
    current_period: int
    ocert_start_period: int
    max_kes_evolutions: int


@dataclass
class CounterTooSmallOCERT(PraosValidationError):
    last_counter: int
    current_counter: int


@dataclass
class CounterOverIncrementedOCERT(PraosValidationError):
    last_counter: int
    current_counter: int


@dataclass
class InvalidSignatureOCERT(PraosValidationError):
    counter: int
    kes_period: int


@dataclass
class InvalidKesSignatureOCERT(PraosValidationError):
    current_period: int
    start_period: int
    expected_evolutions: int


@dataclass
class NoCounterForKeyHashOCERT(PraosValidationError):
    pool_key_hash: bytes


# ---------------------------------------------------------------------------
# Crypto routing
# ---------------------------------------------------------------------------


class CryptoVerifier(TyProtocol):
    """The three verifications of the hot path, swappable host/TPU."""

    def verify_dsign(self, vk: bytes, msg: bytes, sig: bytes) -> bool: ...

    def verify_kes(
        self, vk: bytes, depth: int, period: int, msg: bytes, sig: bytes
    ) -> bool: ...

    def verify_vrf(self, vk: bytes, proof: bytes, alpha: bytes, output: bytes) -> bool: ...


class HostVerifier:
    """Pure-Python reference crypto (ops/host/*)."""

    def verify_dsign(self, vk, msg, sig):
        return host_ed25519.verify(vk, msg, sig)

    def verify_kes(self, vk, depth, period, msg, sig):
        return host_kes.verify(vk, depth, period, msg, sig)

    def verify_vrf(self, vk, proof, alpha, output):
        beta = host_ecvrf.verify(vk, proof, alpha)
        return beta is not None and beta == output


HOST_VERIFIER = HostVerifier()


class NativeVerifier:
    """C++ host crypto (native/hostcrypto.cpp via ctypes) — the same
    per-header semantics as HostVerifier at libsodium-class speed; used
    where a test/tool needs many sequential host validations."""

    def verify_dsign(self, vk, msg, sig):
        from .. import native_loader

        return native_loader.native_ed25519_verify(vk, sig, msg)

    def verify_kes(self, vk, depth, period, msg, sig):
        from .. import native_loader

        return native_loader.native_kes_verify(vk, depth, period, msg, sig)

    def verify_vrf(self, vk, proof, alpha, output):
        from .. import native_loader

        beta = native_loader.native_ecvrf_verify(vk, proof, alpha)
        return beta is not None and beta == output


def native_verifier_or_host() -> CryptoVerifier:
    """NativeVerifier when the C++ library is buildable, else the
    pure-Python fallback (import-time cheap; load is lazy per call)."""
    from .. import native_loader

    return NativeVerifier() if native_loader.load_crypto() is not None else HOST_VERIFIER


# ---------------------------------------------------------------------------
# Protocol transitions
# ---------------------------------------------------------------------------


def tick(
    params: PraosParams, ledger_view: LedgerView, slot: int, state: PraosState
) -> TickedPraosState:
    """tickChainDepState (Praos.hs:407-432): on epoch change, rotate
    epoch nonce (candidate ⭒ last-epoch-block nonce) and latch the LAB
    nonce as the new last-epoch-block nonce."""
    if params.is_new_epoch(state.last_slot, slot):
        state = replace(
            state,
            epoch_nonce=nonces.combine(
                state.candidate_nonce, state.last_epoch_block_nonce
            ),
            last_epoch_block_nonce=state.lab_nonce,
        )
    return TickedPraosState(state, ledger_view)


def validate_kes_signature(
    params: PraosParams,
    ledger_view: LedgerView,
    ocert_counters: Mapping[bytes, int],
    hv: HeaderView,
    crypto: CryptoVerifier = HOST_VERIFIER,
) -> None:
    """validateKESSignature (Praos.hs:558-606), same check order."""
    oc = hv.ocert
    c0 = oc.kes_period
    kp = params.kes_period_of(hv.slot)
    hk = hash_key(hv.vk_cold)

    if not c0 <= kp:
        raise KESBeforeStartOCERT(c0, kp)
    if not kp < c0 + params.max_kes_evolutions:
        raise KESAfterEndOCERT(kp, c0, params.max_kes_evolutions)

    t = kp - c0 if kp >= c0 else 0

    if not crypto.verify_dsign(hv.vk_cold, oc.signable(), oc.sigma):
        raise InvalidSignatureOCERT(oc.counter, c0)
    if not crypto.verify_kes(
        oc.vk_hot, params.kes_depth, t, hv.signed_bytes, hv.kes_sig
    ):
        raise InvalidKesSignatureOCERT(kp, c0, t)

    if hk in ocert_counters:
        m = ocert_counters[hk]
    elif hk in ledger_view.pool_distr:
        m = 0
    else:
        raise NoCounterForKeyHashOCERT(hk)
    n = oc.counter
    if not m <= n:
        raise CounterTooSmallOCERT(m, n)
    if not n <= m + 1:
        raise CounterOverIncrementedOCERT(m, n)


def validate_vrf_signature(
    epoch_nonce: Nonce,
    ledger_view: LedgerView,
    active_slot_coeff: Fraction,
    hv: HeaderView,
    crypto: CryptoVerifier = HOST_VERIFIER,
) -> None:
    """validateVRFSignature (Praos.hs:528-556), same check order."""
    hk = hash_key(hv.vk_cold)
    entry = ledger_view.pool_distr.get(hk)
    if entry is None:
        raise VRFKeyUnknown(hk)
    header_vrf_hash = hash_vrf_vk(hv.vrf_vk)
    if entry.vrf_key_hash != header_vrf_hash:
        raise VRFKeyWrongVRFKey(hk, entry.vrf_key_hash, header_vrf_hash)
    alpha = nonces.mk_input_vrf(hv.slot, epoch_nonce)
    if not crypto.verify_vrf(hv.vrf_vk, hv.vrf_proof, alpha, hv.vrf_output):
        raise VRFKeyBadProof(hv.slot, epoch_nonce)
    lv_val = nonces.vrf_leader_value(hv.vrf_output)
    if not check_leader_value(lv_val, entry.stake, active_slot_coeff):
        raise VRFLeaderValueTooBig(lv_val, entry.stake, active_slot_coeff)


def reupdate(
    params: PraosParams, hv: HeaderView, slot: int, ticked: TickedPraosState
) -> PraosState:
    """reupdateChainDepState (Praos.hs:468-502): bookkeeping, no crypto."""
    cs = ticked.state
    eta = nonces.vrf_nonce_value(hv.vrf_output)
    new_evolving = nonces.combine(cs.evolving_nonce, eta)
    first_slot_next_epoch = params.first_slot_of(params.epoch_of(slot) + 1)
    within_stability = slot + params.stability_window < first_slot_next_epoch
    counters = dict(cs.ocert_counters)
    counters[hash_key(hv.vk_cold)] = hv.ocert.counter
    return replace(
        cs,
        last_slot=slot,
        lab_nonce=nonces.prev_hash_to_nonce(hv.prev_hash),
        evolving_nonce=new_evolving,
        candidate_nonce=new_evolving if within_stability else cs.candidate_nonce,
        ocert_counters=counters,
    )


def update(
    params: PraosParams,
    hv: HeaderView,
    slot: int,
    ticked: TickedPraosState,
    crypto: CryptoVerifier = HOST_VERIFIER,
) -> PraosState:
    """updateChainDepState (Praos.hs:441-466): KES, then VRF, then reupdate."""
    cs = ticked.state
    validate_kes_signature(params, ticked.ledger_view, cs.ocert_counters, hv, crypto)
    validate_vrf_signature(
        cs.epoch_nonce, ticked.ledger_view, params.active_slot_coeff, hv, crypto
    )
    return reupdate(params, hv, slot, ticked)


# ---------------------------------------------------------------------------
# Forging side
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PraosCanBeLeader:
    """Forging credentials (Praos/Common.hs:83-93)."""

    ocert: OCert
    vk_cold: bytes
    vrf_sign_seed: bytes  # VRF signing key seed


@dataclass(frozen=True)
class PraosIsLeader:
    """Proof of leadership: the certified VRF result (Praos.hs:212-216)."""

    vrf_output: bytes  # 64
    vrf_proof: bytes  # 80 (draft-03) or 128 (batch-compatible)


def check_is_leader(
    params: PraosParams,
    can_be_leader: PraosCanBeLeader,
    slot: int,
    ticked: TickedPraosState,
) -> PraosIsLeader | None:
    """checkIsLeader (Praos.hs:375-397): evaluate the VRF at
    InputVRF(slot, eta0) and test the leader threshold."""
    from ..ops.host import fast

    eta0 = ticked.state.epoch_nonce
    alpha = nonces.mk_input_vrf(slot, eta0)
    proof = fast.ecvrf_prove(can_be_leader.vrf_sign_seed, alpha)
    output = fast.ecvrf_proof_to_hash(proof)
    hk = hash_key(can_be_leader.vk_cold)
    entry = ticked.ledger_view.pool_distr.get(hk)
    sigma = entry.stake if entry is not None else Fraction(0)
    if check_leader_value(
        nonces.vrf_leader_value(output), sigma, params.active_slot_coeff
    ):
        return PraosIsLeader(output, proof)
    return None
