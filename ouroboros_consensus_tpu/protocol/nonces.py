"""Praos nonces and VRF range extension (host control-plane).

A `Nonce` is `bytes` (32) or `None` for the neutral nonce. Semantics follow
the reference exactly:
  * combine (⭒): Blake2b-256(a ‖ b); neutral is identity on either side
    (cardano-ledger `Nonce` ⭒).
  * mkInputVRF: Blake2b-256(slot_be8 ‖ nonce-bytes); the neutral nonce
    contributes NO bytes (Praos/VRF.hs:55-69 `mkInputVRF`).
  * leader value: "L"-tagged hash of the certified VRF output, as a natural
    bounded by 2^256 (Praos/VRF.hs:103 `vrfLeaderValue`).
  * nonce value: "N"-tagged double hash (Praos/VRF.hs:116 `vrfNonceValue`).
  * prevHashToNonce: genesis prev-hash -> neutral; else the hash bytes
    (cardano-ledger `prevHashToNonce`, used at Praos.hs:474).
"""

from __future__ import annotations

from ..ops.host.hashes import blake2b_256

Nonce = bytes | None

NEUTRAL: Nonce = None

LEADER_VALUE_MAX = 1 << 256  # 2^(8 * sizeHash Blake2b_256)


def combine(a: Nonce, b: Nonce) -> Nonce:
    """eta ⭒ v. Non-associative hash fold; neutral is identity."""
    if a is None:
        return b
    if b is None:
        return a
    return blake2b_256(a + b)


def prev_hash_to_nonce(prev_hash: bytes | None) -> Nonce:
    return None if prev_hash is None else prev_hash


def mk_input_vrf(slot: int, epoch_nonce: Nonce) -> bytes:
    tail = b"" if epoch_nonce is None else epoch_nonce
    return blake2b_256(slot.to_bytes(8, "big") + tail)


def vrf_leader_value(vrf_output: bytes) -> int:
    """Bounded natural in [0, 2^256) for the leader threshold check."""
    return int.from_bytes(blake2b_256(b"L" + vrf_output), "big")


def vrf_nonce_value(vrf_output: bytes) -> bytes:
    return blake2b_256(blake2b_256(b"N" + vrf_output))
