"""Device-batched chain synthesis: forge at the speed you verify.

Reference: the `runForge` loop (Tools/DBSynthesizer/Forging.hs:54-57)
checks leadership per slot per credential and forges the winner — a
strictly sequential host loop. The TPU build splits that loop into the
part with no chain dependency and the part with one:

  * **Leader election has no chain dependency.** The VRF input is
    `mkInputVRF(slot, eta0)` (Praos/VRF.hs:47) and eta0 is
    epoch-constant, so the election for EVERY (slot, pool) pair of a
    window is one packed dispatch: `forge_sweep` evaluates
    `ops/ecvrf_batch.prove` over the pools×slots grid and brackets the
    leader value against the per-pool thresholds on device (the same
    two-threshold bracket the verify side dispatches), scattering the
    elected (slot, pool) pairs back as a host column. The host resolves
    only the ambiguous band exactly (empty in practice).

  * **Header assembly keeps one chain dependency.** Each body embeds
    the previous header's hash INSIDE the KES-signed bytes, so the
    per-block leaf signature is inherently sequential. Everything else
    is hoisted: OCert issue signatures dedup per (pool, counter,
    evolution-window) — `forge_sign` batches them on device — and the
    KES leaf seed + sibling path per (pool, period) are
    message-independent (`ops/host/kes.leaf_path`), leaving splice →
    leaf-sign → hash as the only per-block tail.

Engines (`engine_from_env`): "device" dispatches the packed sweep,
"host" runs the same staged election with native per-pair proves and
vectorized threshold compares, "loop" (`OCT_FORGE_DEVICE=0`) is the
untouched per-slot reference loop in tools/db_synthesizer. All three
are byte-identical for the same seed/params (tests/test_forge.py).

Failure citizenship: election dispatches ride a recovery ladder
(retry → host-reference exact loop, obs/recovery.py vocabulary) and
carry the `forge-dispatch` / `forge` chaos seams (testing/chaos.py).
"""

from __future__ import annotations

import os
import threading
from fractions import Fraction
from typing import NamedTuple

import numpy as np

from ..ops.host import fast
from ..ops.host import kes as host_kes
from ..testing import chaos
from ..utils.trace import RecoveryEvent
from . import nonces
from .leader import check_leader_value
from .praos import PraosIsLeader, PraosParams
from .views import LedgerView

_ENV_DEVICE = "OCT_FORGE_DEVICE"

# one packed dispatch's lane count (the jit caches exactly one shape);
# module-level so the differential tests can shrink it
FORGE_BUCKET = 4096


def engine_from_env(vrf_backend: str = "auto") -> str:
    """Resolve the forging engine: the OCT_FORGE_DEVICE lever wins
    ("1" = packed device sweep, "0" = the per-slot reference loop);
    unset, the synthesizer's vrf_backend picks device and everything
    else lands on the batched host engine (the default fast path)."""
    v = os.environ.get(_ENV_DEVICE, "").strip()
    if v == "0":
        return "loop"
    if v == "1":
        return "device"
    if vrf_backend == "device":
        return "device"
    return "host"


class Elected(NamedTuple):
    """One won slot scattered back from the election sweep."""

    slot: int
    pool: int  # index into the credentials list (first winner per slot)
    is_leader: PraosIsLeader


# ---------------------------------------------------------------------------
# Registry graphs (analysis/graphs.py: forge_sweep / forge_sign)
# ---------------------------------------------------------------------------


def forge_sweep(x, prefix, pk, slots, nonce, thr_lo, thr_hi):
    """The leader-election sweep kernel: one packed dispatch electing a
    pools×slots grid. alpha = mkInputVRF(slot, eta0) on device
    (alpha_from_slots — byte-identical to the host), the full VRF prove
    (both proof serializations come back as columns), then the verify
    side's leader tail: lv = Blake2b("L" ‖ beta) bracketed against the
    per-pair thresholds with the cumsum `_lt_be` compare.

    x/prefix/pk/nonce/thr_* are [B, 32] / [32] int32 byte arrays,
    slots [B] int32. Returns the five proof columns + beta plus the
    [B] win/ambiguous verdict bitmaps (ambiguous lanes get the exact
    host Fraction check — the same division of labor as verify)."""
    import jax.numpy as jnp

    from ..ops import blake2b, ecvrf_batch
    from .batch import _lt_be

    alpha = ecvrf_batch.alpha_from_slots(slots, nonce)
    g_enc, c16, u_enc, v_enc, s32, beta = ecvrf_batch.prove(
        x, prefix, pk, alpha
    )
    tag_l = jnp.broadcast_to(
        jnp.asarray([ord("L")], jnp.int32), (*beta.shape[:-1], 1)
    )
    lv = blake2b.blake2b_fixed(
        jnp.concatenate([tag_l, beta], axis=-1), 65, 32
    )
    thr_lo = jnp.asarray(thr_lo).astype(jnp.int32)
    thr_hi = jnp.asarray(thr_hi).astype(jnp.int32)
    win = _lt_be(lv, thr_lo)
    ambiguous = ~win & _lt_be(lv, thr_hi)
    return g_enc, c16, u_enc, v_enc, s32, beta, win, ambiguous


def forge_sign(a, a_enc, rblocks, rnblocks, hblocks, hnblocks):
    """The packed OCert-issue signer: the certified ed25519 sign kernel
    under its forge-lane registry name, so the sign direction of the
    forging pipeline carries its own budget/cost/resource pins at the
    shape the synthesizer dispatches (deduped OCert signables, not
    headers)."""
    from ..ops import ed25519_batch

    return ed25519_batch.sign(a, a_enc, rblocks, rnblocks, hblocks, hnblocks)


# test seam: install_stub_forge (testing/stubs.py) swaps these for
# hash-twin kernels that compile in seconds on XLA:CPU, and resets the
# jit memo — production never touches them
_SWEEP_FN = forge_sweep
_SIGN_FN = forge_sign
_JITS: dict = {}


def _make_sweep_neutral(sweep_fn):
    """The neutral-nonce sweep variant: epoch 0 of a fresh chain (and
    any window before the first epoch transition establishes a real
    nonce) elects under `epoch_nonce=None`, which `alpha_from_slots`
    folds as a STATIC trace-time branch (8-byte alpha input instead of
    40) — the same per-layout staticness the verify side bakes through
    `layout.has_nonce`. A separate traced program under its own stage /
    AOT-store name; `None` cannot ride as a runtime argument (the
    warm-store signature walks arg shapes). A FACTORY for the same
    reason as make_stub_forge_sweep: jax's tracing cache keys on
    function identity, and a module-level wrapper would serve a stale
    install's trace after install_stub_forge swaps the kernel."""

    def sweep_neutral(x, prefix, pk, slots, thr_lo, thr_hi):
        return sweep_fn(x, prefix, pk, slots, None, thr_lo, thr_hi)

    return sweep_neutral


def _jit_of(name: str, fn):
    if name not in _JITS:
        import jax

        from . import batch as pbatch

        _JITS[name] = pbatch._warm_timed(name, jax.jit(fn))
    return _JITS[name]


# ---------------------------------------------------------------------------
# Window staging (host, once per run / per window)
# ---------------------------------------------------------------------------


class PoolStaging(NamedTuple):
    """Per-pool device columns, staged once per synthesis run."""

    x: np.ndarray  # [P, 32] expanded VRF scalars
    prefix: np.ndarray  # [P, 32] nonce prefixes
    pk: np.ndarray  # [P, 32] VRF verification keys


def stage_pools(pools) -> PoolStaging:
    from ..ops import ecvrf_batch

    x, prefix, pk = ecvrf_batch.stage_prove_np([p.vrf_seed for p in pools])
    return PoolStaging(x, prefix, pk)


def pool_thresholds(params: PraosParams, lview: LedgerView, pools):
    """Per-pool (lo_rows [P,32], hi_rows [P,32], sigmas) — the
    unknown-pool sigma-0 convention and clamped bracket encoding of
    batch._threshold_rows, keyed by the window's ledger view."""
    from . import batch as pbatch

    f = Fraction(params.active_slot_coeff)
    lo_rows, hi_rows, sigmas = [], [], []
    for pool in pools:
        entry = lview.pool_distr.get(pool.pool_id)
        sigma = entry.stake if entry is not None else Fraction(0)
        lo, hi = pbatch._threshold_rows(sigma, f)
        lo_rows.append(lo)
        hi_rows.append(hi)
        sigmas.append(sigma)
    return np.stack(lo_rows), np.stack(hi_rows), sigmas


def window_slots(n_pools: int) -> int:
    """Slots per election window: ~4 packed buckets of (slot, pool)
    pairs — enough to amortize dispatch, small enough that the
    blocks-limit overshoot stays bounded."""
    return max(1, (4 * FORGE_BUCKET) // max(1, n_pools))


# ---------------------------------------------------------------------------
# Election engines
# ---------------------------------------------------------------------------


def _first_winners(params, slots, pools, sigmas, win, amb, lv_rows,
                   beta_of, proof_of) -> list[Elected]:
    """Shared election tail: resolve the ambiguous band with the exact
    Fraction check, then scatter the first winning pool per slot
    (list order — the reference's first-credential-forges rule)."""
    p = len(pools)
    f = params.active_slot_coeff
    for idx in np.nonzero(amb)[0]:
        lv_val = int.from_bytes(bytes(lv_rows[idx]), "big")
        win[idx] = check_leader_value(lv_val, sigmas[idx % p], f)
    winm = win.reshape(len(slots), p)
    has = winm.any(axis=1)
    first = winm.argmax(axis=1)
    out = []
    slots = list(slots)
    for j in np.nonzero(has)[0]:
        i = int(first[j])
        idx = j * p + i
        out.append(
            Elected(
                int(slots[j]), i,
                PraosIsLeader(beta_of(idx), proof_of(idx)),
            )
        )
    return out


def _elect_window_host(params, pools, thr, slots, eta0) -> list[Elected]:
    """Batched host engine: native per-pair proves, then ONE vectorized
    threshold compare over the whole window (the per-pair Fraction
    check — the legacy loop's dominant cost — survives only for the
    ambiguous band)."""
    from . import batch as pbatch

    lo_rows, hi_rows, sigmas = thr
    p = len(pools)
    ns = len(slots)
    b = ns * p
    from ..ops.host.hashes import blake2b_256

    betas: list[bytes] = []
    proofs: list[bytes] = []
    lv_rows = np.empty((b, 32), np.uint8)
    k = 0
    for s in slots:
        alpha = nonces.mk_input_vrf(s, eta0)
        for pool in pools:
            proof = fast.ecvrf_prove(pool.vrf_seed, alpha)
            beta = fast.ecvrf_proof_to_hash(proof)
            proofs.append(proof)
            betas.append(beta)
            lv_rows[k] = np.frombuffer(blake2b_256(b"L" + beta), np.uint8)
            k += 1
    thr_lo = np.tile(lo_rows, (ns, 1))
    thr_hi = np.tile(hi_rows, (ns, 1))
    win = pbatch._lt_be_rows(lv_rows, thr_lo)
    amb = ~win & pbatch._lt_be_rows(lv_rows, thr_hi)
    return _first_winners(
        params, slots, pools, sigmas, win, amb, lv_rows,
        lambda i: betas[i], lambda i: proofs[i],
    )


def _elect_window_device(params, pools, stg: PoolStaging, thr, slots,
                         eta0) -> list[Elected]:
    """Packed device engine: the whole pools×slots grid through
    forge_sweep in FORGE_BUCKET dispatches (padded to one cached
    shape), verdict bitmaps and proof columns scattered back."""
    lo_rows, hi_rows, sigmas = thr
    p = len(pools)
    ns = len(slots)
    b = ns * p
    # pair order is slot-major (s0p0, s0p1, s1p0, ...): the first
    # winning POOL per slot must be the list-order first
    x = np.tile(stg.x, (ns, 1))
    prefix = np.tile(stg.prefix, (ns, 1))
    pk = np.tile(stg.pk, (ns, 1))
    slot_col = np.repeat(np.asarray(list(slots), np.int64), p)
    thr_lo = np.tile(lo_rows, (ns, 1))
    thr_hi = np.tile(hi_rows, (ns, 1))
    if eta0 is None:
        # neutral nonce (fresh chain, epoch 0): dispatch the statically
        # nonce-free variant — a distinct compiled program, same family
        sweep = _jit_of("forge_sweep-neutral", _make_sweep_neutral(_SWEEP_FN))
        nonce_args = ()
    else:
        sweep = _jit_of("forge_sweep", _SWEEP_FN)
        nonce_args = (np.frombuffer(eta0, np.uint8),)
    cols = [[] for _ in range(6)]
    win = np.zeros(b, bool)
    amb = np.zeros(b, bool)
    for lo in range(0, b, FORGE_BUCKET):
        n = min(FORGE_BUCKET, b - lo)
        sl = slice(lo, lo + n)

        def pad(a):
            if n == FORGE_BUCKET:
                return a[sl]
            reps = np.concatenate(
                [a[sl], np.repeat(a[lo:lo + 1], FORGE_BUCKET - n, axis=0)]
            )
            return reps

        out = sweep(
            pad(x), pad(prefix), pad(pk),
            pad(slot_col.reshape(-1, 1)).reshape(-1).astype(np.int32),
            *nonce_args, pad(thr_lo), pad(thr_hi),
        )
        for acc, col in zip(cols, out[:6]):
            acc.append(np.asarray(col[:n]).astype(np.uint8))
        win[sl] = np.asarray(out[6][:n])
        amb[sl] = np.asarray(out[7][:n])
    g_enc, c16, u_enc, v_enc, s32, beta = (
        np.concatenate(a) for a in cols
    )
    compat = fast.vrf_batch_compat()
    # lv is re-derived host-side only for the (normally empty)
    # ambiguous band — the device already folded it into win/amb
    from ..ops.host.hashes import blake2b_256

    lv_rows = {
        int(i): np.frombuffer(
            blake2b_256(b"L" + bytes(beta[i])), np.uint8
        )
        for i in np.nonzero(amb)[0]
    }

    def proof_of(i):
        if compat:
            parts = (g_enc[i], u_enc[i], v_enc[i], s32[i])
        else:
            parts = (g_enc[i], c16[i], s32[i])
        return b"".join(bytes(q) for q in parts)

    return _first_winners(
        params, slots, pools, sigmas, win, amb,
        _LazyRows(lv_rows), lambda i: bytes(beta[i]), proof_of,
    )


class _LazyRows:
    """lv rows materialized only for the ambiguous indices."""

    def __init__(self, rows: dict):
        self._rows = rows

    def __getitem__(self, i):
        return self._rows[int(i)]


def _elect_window_reference(params, pools, lview, slots,
                            eta0) -> list[Elected]:
    """The exact host reference: per-slot, per-pool prove + Fraction
    leader check — the recovery ladder's floor (and the legacy loop's
    election semantics, verbatim)."""
    out = []
    f = params.active_slot_coeff
    for s in slots:
        alpha = nonces.mk_input_vrf(s, eta0)
        for i, pool in enumerate(pools):
            proof = fast.ecvrf_prove(pool.vrf_seed, alpha)
            is_leader = PraosIsLeader(
                fast.ecvrf_proof_to_hash(proof), proof
            )
            lv_val = nonces.vrf_leader_value(is_leader.vrf_output)
            entry = lview.pool_distr.get(pool.pool_id)
            if entry is None:
                continue
            if not check_leader_value(lv_val, entry.stake, f):
                continue
            out.append(Elected(int(s), i, is_leader))
            break
    return out


def elect_window(params, pools, stg, thr, slots, eta0,
                 engine: str) -> list[Elected]:
    """One window's election dispatch (the `forge-dispatch` chaos
    seam lives here — a window dispatch is the recovery ladder's unit
    of retry)."""
    chaos.fire("forge-dispatch")
    if engine == "device":
        return _elect_window_device(params, pools, stg, thr, slots, eta0)
    return _elect_window_host(params, pools, thr, slots, eta0)


def elect_window_recovering(params, pools, stg, thr, slots, eta0,
                            engine: str, lview, window: int,
                            tracer=None) -> list[Elected]:
    """The forge arm of the PR 12 recovery ladder: a failing election
    dispatch is retried once (chaos faults are transient by contract;
    so are real device hiccups worth one retry), then dropped to the
    exact host reference loop — the floor that cannot fail for device
    reasons. Every transition emits a RecoveryEvent so the episode is
    countable (oct_recovery_total{action=})."""
    lanes = len(slots) * len(pools)

    def emit(ev):
        if tracer is not None:
            tracer(ev)

    try:
        return elect_window(params, pools, stg, thr, slots, eta0, engine)
    except Exception as e:  # noqa: BLE001 — ladder owns classification
        emit(RecoveryEvent(
            action="retry", window=window, lanes=lanes, attempt=1,
            fault=type(e).__name__, detail=repr(e)[:200],
        ))
        try:
            out = elect_window(
                params, pools, stg, thr, slots, eta0, engine
            )
            emit(RecoveryEvent(
                action="recovered", window=window, lanes=lanes,
                attempt=2, fault=type(e).__name__,
                detail=repr(e)[:200], ok=True,
            ))
            return out
        except Exception as e2:  # noqa: BLE001
            emit(RecoveryEvent(
                action="host-reference", window=window, lanes=lanes,
                attempt=2, fault=type(e2).__name__,
                detail=repr(e2)[:200],
            ))
            out = _elect_window_reference(params, pools, lview, slots, eta0)
            emit(RecoveryEvent(
                action="recovered", window=window, lanes=lanes,
                attempt=3, fault=type(e2).__name__,
                detail=repr(e2)[:200], ok=True,
            ))
            return out


# ---------------------------------------------------------------------------
# Batched assembly (the sequential tail, with everything hoistable hoisted)
# ---------------------------------------------------------------------------

_SIGN_BUCKET = 16


def sign_ocerts_batch(pools, triples) -> dict:
    """Batch-sign the deduped OCert signables through the forge_sign
    graph: {(pool_i, counter, kes_period): OCert}. The ed25519 sign
    kernel is octrange-certified byte-identical to the host signer, so
    this swap preserves chain bytes."""
    from ..ops import ed25519_batch
    from .views import OCert

    triples = sorted(triples)
    if not triples:
        return {}
    seeds, msgs, protos = [], [], []
    for pool_i, counter, kp0 in triples:
        pool = pools[pool_i]
        oc = OCert(pool.kes_vk, counter, kp0, b"")
        seeds.append(pool.cold_seed)
        msgs.append(oc.signable())
        protos.append(oc)
    pad = (-len(seeds)) % _SIGN_BUCKET
    seeds.extend([seeds[0]] * pad)
    msgs.extend([msgs[0]] * pad)
    batch = ed25519_batch.stage_sign_np(seeds, msgs)
    sign = _jit_of("forge_sign", _SIGN_FN)
    r_enc, s = sign(*batch)
    sigs = np.concatenate(
        [np.asarray(r_enc), np.asarray(s)], axis=-1
    ).astype(np.uint8)
    return {
        key: OCert(oc.vk_hot, oc.counter, oc.kes_period, bytes(sigs[i]))
        for i, (key, oc) in enumerate(zip(triples, protos))
    }


class BlockAssembler:
    """The sequential forge tail with the message-independent work
    cached: OCert issue signatures per (pool, counter,
    evolution-window) and KES leaf seed + vk + sibling path per
    (pool, period). What remains per block — CBOR body with the
    previous hash spliced in, one leaf ed25519 sign, one Blake2b — is
    the irreducible chain dependency (COVERAGE.md §forge)."""

    def __init__(self, params: PraosParams, pools):
        self.params = params
        self.pools = pools
        self._ocerts: dict = {}
        self._leaves: dict = {}

    def ocert_window(self, slot: int) -> int:
        kp = self.params.kes_period_of(slot)
        return max(0, kp - (kp % self.params.max_kes_evolutions))

    def prime_ocerts(self, signed: dict) -> None:
        self._ocerts.update(signed)

    def _ocert(self, pool_i: int, counter: int, kp0: int):
        key = (pool_i, counter, kp0)
        oc = self._ocerts.get(key)
        if oc is None:
            oc = self.pools[pool_i].make_ocert(counter, kp0)
            self._ocerts[key] = oc
        return oc

    def _leaf(self, pool_i: int, t: int):
        key = (pool_i, t)
        leaf = self._leaves.get(key)
        if leaf is None:
            pool = self.pools[pool_i]
            leaf_seed, sibs = host_kes.leaf_path(
                pool.kes_seed, pool.kes_depth, t
            )
            leaf = (
                leaf_seed,
                fast.ed25519_public(leaf_seed) + b"".join(sibs),
            )
            self._leaves[key] = leaf
        return leaf

    def forge(self, pool_i: int, *, slot: int, block_no: int,
              prev_hash: bytes | None, txs: tuple,
              ocert_counter: int, is_leader: PraosIsLeader,
              protocol_version: tuple[int, int] = (9, 0)):
        """Byte-identical to block/forge.forge_block (the differential
        suite holds this equation), at amortized-constant signing cost."""
        from ..block.praos_block import Block, Header, HeaderBody, body_hash

        pool = self.pools[pool_i]
        kp = self.params.kes_period_of(slot)
        kp0 = self.ocert_window(slot)
        ocert = self._ocert(pool_i, ocert_counter, kp0)
        body = HeaderBody(
            block_no=block_no,
            slot=slot,
            prev_hash=prev_hash,
            issuer_vk=pool.vk_cold,
            vrf_vk=pool.vrf_vk,
            vrf_output=is_leader.vrf_output,
            vrf_proof=is_leader.vrf_proof,
            body_size=sum(len(t_) for t_ in txs),
            body_hash=body_hash(txs),
            ocert=ocert,
            protocol_version=protocol_version,
        )
        leaf_seed, tail = self._leaf(pool_i, kp - kp0)
        kes_sig = fast.ed25519_sign(leaf_seed, body.signed_bytes) + tail
        return Block(Header(body, kes_sig), tuple(txs))


# process-wide forge-window sequence (ForgeSpan.index)
_WINDOW_SEQ = [0]
_WINDOW_LOCK = threading.Lock()


def next_window_index() -> int:
    with _WINDOW_LOCK:
        n = _WINDOW_SEQ[0]
        _WINDOW_SEQ[0] = n + 1
        return n
