"""Praos leader-threshold check (exact interval arithmetic).

The rule (cardano-ledger `checkLeaderNatValue`, called from the reference
hot path at Praos.hs:505 `meetsLeaderThreshold` and Praos.hs:551 VRF
validation): a pool with relative stake sigma leads the slot iff

    p < 1 - (1 - f)^sigma        with p = leaderValue / 2^256

evaluated as  1/(1-p) < exp(-sigma * ln(1-f)).

The reference computes this in 34-decimal-digit fixed point with a
Taylor-series comparison (`taylorExpCmp`). We instead use exact rational
interval arithmetic: ln(1-f) and exp are bracketed by partial sums with
rigorous remainder bounds, tightened until the comparison is decided.
This is deterministic and, because the quantities are continuous in the
inputs, agrees with the fixed-point reference except on a measure-zero
boundary band narrower than the reference's own rounding error.
"""

from __future__ import annotations

from fractions import Fraction
from functools import lru_cache

LEADER_VALUE_MAX = 1 << 256


@lru_cache(maxsize=64)
def _neg_log1m_interval(f: Fraction, terms: int) -> tuple[Fraction, Fraction]:
    """[lo, hi] bracketing -ln(1 - f) for 0 < f < 1 via the Mercator series
    -ln(1-f) = sum_{n>=1} f^n / n, remainder < f^(N+1)/((N+1)(1-f))."""
    acc = Fraction(0)
    fp = Fraction(1)
    for n in range(1, terms + 1):
        fp *= f
        acc += fp / n
    rem = fp * f / ((terms + 1) * (1 - f))
    return acc, acc + rem


def _exp_interval(lo: Fraction, hi: Fraction, terms: int) -> tuple[Fraction, Fraction]:
    """[exp_lo, exp_hi] for x in [lo, hi], 0 <= x < 1: partial sums plus a
    geometric remainder bound x^(N+1)/(N+1)! * 1/(1-x)."""
    def partial(x: Fraction) -> tuple[Fraction, Fraction]:
        acc = Fraction(1)
        term = Fraction(1)
        for n in range(1, terms + 1):
            term = term * x / n
            acc += term
        rem = term * x / (terms + 1) / (1 - x)
        return acc, rem

    lo_sum, _ = partial(lo)
    hi_sum, hi_rem = partial(hi)
    return lo_sum, hi_sum + hi_rem


def check_leader_value(leader_value: int, sigma: Fraction, active_slot_coeff: Fraction) -> bool:
    """True iff `leader_value` wins the slot for relative stake `sigma`.

    active_slot_coeff is f in (0, 1]; f == 1 means every slot is active for
    everyone (reference: activeSlotVal == maxBound short-circuit).
    """
    f = Fraction(active_slot_coeff)
    sigma = Fraction(sigma)
    if f == 1:
        return True
    if sigma == 0:
        # exp(0) = 1 and 1/(1-p) >= 1 always: never a leader
        return False
    lhs = Fraction(LEADER_VALUE_MAX, LEADER_VALUE_MAX - leader_value)
    for terms in (8, 16, 32, 64, 128):
        llo, lhi = _neg_log1m_interval(f, terms)
        xlo, xhi = sigma * llo, sigma * lhi
        elo, ehi = _exp_interval(xlo, xhi, terms)
        if lhs < elo:
            return True
        if lhs >= ehi:
            return False
    # interval still undecided after 128 terms: the value sits within an
    # astronomically thin band; break the tie on the midpoint, determinism
    # preserved (same computation on every node)
    return lhs < (elo + ehi) / 2
