"""Praos chain-order: the SelectView and its comparison.

Reference: `PraosChainSelectView` (Praos/Common.hs:53-81) — candidates are
ordered by (1) chain length; (2) when the tips have the SAME issuer, the
higher OCert issue number; (3) the LOWER tie-break VRF value (the "L"
range extension of the certified output, pTieBreakVRFValue). ChainSel
(storage/chaindb) sorts candidate fragments by the select view of their
tip header.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import nonces


@dataclass(frozen=True)
class PraosSelectView:
    block_no: int
    slot: int
    issuer_vk: bytes
    issue_no: int  # ocert counter
    tiebreak_vrf: int  # vrfLeaderValue of the tip's certified output

    @classmethod
    def from_header(cls, header) -> "PraosSelectView":
        b = header.body
        return cls(
            block_no=b.block_no,
            slot=b.slot,
            issuer_vk=b.issuer_vk,
            issue_no=b.ocert.counter,
            tiebreak_vrf=nonces.vrf_leader_value(b.vrf_output),
        )


def compare_select_views(ours: PraosSelectView | None, theirs: PraosSelectView | None) -> int:
    """> 0 iff `theirs` is strictly preferred (preferCandidate).

    None = empty chain (genesis-only): any non-empty candidate wins.
    """
    if theirs is None:
        return -1 if ours is not None else 0
    if ours is None:
        return 1
    if theirs.block_no != ours.block_no:
        return 1 if theirs.block_no > ours.block_no else -1
    if theirs.issuer_vk == ours.issuer_vk and theirs.issue_no != ours.issue_no:
        return 1 if theirs.issue_no > ours.issue_no else -1
    if theirs.tiebreak_vrf != ours.tiebreak_vrf:
        return 1 if theirs.tiebreak_vrf < ours.tiebreak_vrf else -1
    return 0
