"""Priced admission for the serving plane: warm shapes go straight to
the device; cold shapes ride the rung ladder instead of stalling warm
traffic.

The serving scheduler (node/serve.py) fills shared packed windows from
whatever lanes are pending across tenants. Every window pads to a
power-of-two-family bucket (protocol/batch.bucket_size), and each
DISTINCT (proof format, body length, bucket) shape is one compiled
device program: the first dispatch of a shape pays its compile wall.
On a TPU session that wall is minutes (PERF.md round 6) — letting one
cold tenant's odd shape compile INLINE would stall every warm tenant
behind it, the exact head-of-line blocking the round-10 warm ladder
exists to avoid during replays.

This module is the serving-side twin of that ladder, as an admission
decision instead of a window re-tiler:

  * a WARM shape (its bucket has already dispatched this process, or
    an AOT-pinned rung program covers it) is admitted at full size;
  * a COLD shape is CAPPED to the warm-compile rung ladder
    (analysis/costmodel.LADDER_RUNGS, the same rungs the replay ladder
    compiles and octwall pins): the tenant serves on rung-sized
    windows — individually cheap compiles, promoted bucket by bucket
    as each retires warm — and escalates to its full requested shape
    only once the ladder has walked there;
  * pricing is the octwall surface: `costmodel.predicted_wall` for the
    shape's registered graph twin and `costmodel.preflight` under an
    exported $OCT_WALL_DEADLINE, with the per-stage
    `obs.resources.RESOURCES` device-resources rows attached to the
    decision so the SLO surface can show WHY a tenant is rung-capped.

Malformed submissions are REFUSED at the door (`AdmissionRefused`,
disposition REFUSE in node/exit.DISPOSITIONS): an empty suffix, a
suffix mixing proof formats (a window must stage one uniform proof
column), or non-increasing slots (a candidate suffix is a chain).

Single-writer discipline: one scheduler thread owns a policy instance
(node/serve.py's pump loop); the class keeps no locks by design."""

from __future__ import annotations

import os
from dataclasses import dataclass

from .batch import bucket_size

_DEVICE_ENV = "OCT_SERVE_DEVICE"


class AdmissionRefused(Exception):
    """A submission the serving plane rejects at the door (malformed
    suffix — never a capacity decision; capacity cold-starts are CAPPED,
    not refused). Disposition REFUSE: the tenant's input is wrong and
    retrying the identical submission cannot succeed."""

    def __init__(self, tenant_id: str, reason: str):
        self.tenant_id = tenant_id
        self.reason = reason
        super().__init__(f"tenant {tenant_id}: {reason}")


@dataclass(frozen=True)
class WindowShape:
    """The compile-relevant shape of a candidate suffix: what selects
    the staged layout (and therefore the compiled program family)."""

    proof_len: int  # 80 draft-03 | 128 batch-compatible
    body_len: int  # KES-signed body bytes (packed layout body column)

    def graph(self) -> str:
        """Registered costmodel graph twin of this shape's packed
        program (the xla-packed path's structural twin — the serving
        rig's dispatch impl)."""
        return ("verify_praos_core" if self.proof_len == 80
                else "verify_praos_core_bc")

    def stage_label(self, lanes: int) -> str:
        """Warmup-vocabulary stage label for preflight pricing (the
        xla-packed label family of protocol/batch._jitted_packed_xla)."""
        return f"xla-packed:{self.body_len}b:p{self.proof_len}:noscan@{lanes}"


@dataclass(frozen=True)
class AdmissionDecision:
    """One priced admission: how many lanes this shape may fill in the
    next shared window, and why."""

    mode: str  # "warm" | "rung" | "host"
    lane_cap: int  # max lanes of this shape in the next window
    bucket: int  # the padded bucket the cap dispatches as
    predicted_wall_s: float | None  # octwall price of that bucket (cold)
    device_resources: dict | None  # per-stage ledger rows, when banked


def shape_of(tenant_id: str, hvs) -> WindowShape:
    """Validate one candidate suffix at the door and derive its shape.
    Raises AdmissionRefused on the malformed cases the packed stage
    cannot window (the caller scatters the refusal back to the tenant
    without touching any other tenant's traffic)."""
    if not len(hvs):
        raise AdmissionRefused(tenant_id, "empty candidate suffix")
    plen = len(hvs[0].vrf_proof)
    blen = len(hvs[0].signed_bytes)
    prev_slot = None
    for hv in hvs:
        if len(hv.vrf_proof) != plen:
            raise AdmissionRefused(
                tenant_id,
                f"suffix mixes proof formats ({plen} and "
                f"{len(hv.vrf_proof)} bytes) — one window stages one "
                "uniform proof column",
            )
        if len(hv.signed_bytes) != blen:
            raise AdmissionRefused(
                tenant_id,
                "suffix mixes body lengths — packed staging needs "
                "rectangular columns",
            )
        if prev_slot is not None and hv.slot <= prev_slot:
            raise AdmissionRefused(
                tenant_id,
                f"non-increasing slot {hv.slot} after {prev_slot} — a "
                "candidate suffix is a chain",
            )
        prev_slot = hv.slot
    return WindowShape(proof_len=plen, body_len=blen)


class AdmissionPolicy:
    """Warm-shape tracking + rung-ladder capping for one service.

    `admit(shape, requested)` prices the shape's next window;
    `note_window(shape, lanes)` marks the dispatched bucket warm after
    the window retires (promotion is EARNED, never assumed — a shed or
    recovered window does not warm its bucket). One scheduler thread
    owns the instance; no locks by design."""

    def __init__(self, rungs: tuple | None = None):
        from ..analysis import costmodel

        self._costmodel = costmodel
        self.rungs = tuple(sorted(rungs if rungs is not None
                                  else costmodel.LADDER_RUNGS))
        # shape -> set of buckets proven warm in this process
        self._warm: dict[WindowShape, set] = {}
        self.decisions: dict[str, int] = {"warm": 0, "rung": 0, "host": 0}

    # -- warm-set bookkeeping ----------------------------------------------

    def is_warm(self, shape: WindowShape, bucket: int) -> bool:
        if bucket in self._warm.get(shape, ()):
            return True
        # an octwall rung pin covers the bucket: the program was
        # AOT-priced and its compile is known to fit the rung budget —
        # treat the PINNED rungs as warm-startable, exactly like the
        # replay ladder does when choosing its first rung
        pin = self._costmodel.ladder_pin_name(shape.graph(), bucket)
        return self._costmodel.pinned(pin) is not None

    def note_window(self, shape: WindowShape, lanes: int) -> None:
        """A window of this shape retired cleanly at `lanes`: its
        bucket (and every smaller one — bucket_size is monotone) is
        warm for the rest of the process."""
        self._warm.setdefault(shape, set()).add(bucket_size(lanes))

    def warm_buckets(self, shape: WindowShape) -> tuple:
        return tuple(sorted(self._warm.get(shape, ())))

    # -- pricing ------------------------------------------------------------

    def price(self, shape: WindowShape, bucket: int) -> float | None:
        """Predicted cold-compile wall of this shape at `bucket` lanes:
        the rung pin when octwall has one, else the base graph pin.
        None = unpriced (the gate never blocks on ignorance)."""
        cm = self._costmodel
        pred = cm.predicted_wall(cm.ladder_pin_name(shape.graph(), bucket))
        if pred is None:
            pred = cm.predicted_wall(shape.graph())
        return pred

    def _resources_rows(self, shape: WindowShape) -> dict | None:
        """The per-stage device-resources ledger rows banked for this
        shape's graph family, when the resources plane is armed —
        attached to decisions so the SLO surface can show the price."""
        from ..obs.resources import RESOURCES

        report = RESOURCES.report()
        if not report:
            return None
        base = shape.graph()
        rows = {k: v for k, v in report.items() if base in k}
        return rows or None

    # -- the decision -------------------------------------------------------

    def admit(self, shape: WindowShape, requested: int) -> AdmissionDecision:
        """Lane cap for this shape's next window.

        Warm bucket -> full size. Cold -> the rung ladder: serve at the
        largest already-warm bucket of this shape, else at the
        octwall-chosen starting rung (`costmodel.choose_rung` against
        $OCT_WALL_DEADLINE), escalating one rung per warm window until
        the requested bucket is reachable. With the device plane
        kill-switched (OCT_SERVE_DEVICE=0) every shape is mode="host":
        the host fold has no compile wall to price."""
        requested = max(1, int(requested))
        if os.environ.get(_DEVICE_ENV, "1") == "0":
            self.decisions["host"] += 1
            return AdmissionDecision("host", requested,
                                     bucket_size(requested), None, None)
        bucket = bucket_size(requested)
        if self.is_warm(shape, bucket):
            self.decisions["warm"] += 1
            return AdmissionDecision("warm", requested, bucket,
                                     self.price(shape, bucket), None)
        warm = self.warm_buckets(shape)
        if warm:
            # escalate one rung past the largest earned bucket; the
            # ladder positions are the octwall rungs plus the requested
            # bucket as its top
            ladder = sorted({*(r for r in self.rungs), bucket})
            nxt = next((r for r in ladder if r > warm[-1]), bucket)
            cap = min(requested, nxt)
        else:
            start = self._costmodel.choose_rung(shape.graph())
            cap = min(requested, start if start else min(self.rungs))
        # octwall preflight on the capped shape: under a wall deadline a
        # rung whose own compile does not fit sheds further down
        while cap > 1 and not self._costmodel.preflight(
            shape.stage_label(bucket_size(cap)),
            graph=self._costmodel.ladder_pin_name(
                shape.graph(), bucket_size(cap)),
            action="serve-rung-shed",
        ):
            lower = [r for r in self.rungs if r < cap]
            if not lower:
                break
            cap = lower[-1]
        self.decisions["rung"] += 1
        return AdmissionDecision(
            "rung", cap, bucket_size(cap),
            self.price(shape, bucket_size(cap)),
            self._resources_rows(shape),
        )
