"""Concrete ConsensusProtocol instances.

* `PraosProtocol` — the flagship: host semantics from protocol/praos.py,
  batched device crypto from protocol/batch.py (reference instance:
  Praos.hs:364).
* `BftProtocol` — trivial round-robin BFT for tests (Protocol/BFT.hs):
  slot s must be signed by node (s mod n); one Ed25519 verify, no state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from ..ops.host import ed25519 as host_ed25519
from . import batch as pbatch
from . import praos, select
from .abstract import ConsensusError
from .praos import PraosParams, PraosState, TickedPraosState


class PraosProtocol:
    """ConsensusProtocol (Praos c) — instance-as-object over praos.py."""

    def __init__(
        self,
        params: PraosParams,
        crypto: praos.CryptoVerifier = praos.HOST_VERIFIER,
        use_device_batch: bool = True,
    ):
        self.params = params
        self.crypto = crypto
        self.security_param = params.security_param
        # False routes LedgerDB/ChainSel through the sequential host fold
        # (useful for tests that should not pay kernel compilation)
        self.use_device_batch = use_device_batch

    def initial_state(self) -> PraosState:
        return PraosState()

    def tick(self, ledger_view, slot, state) -> TickedPraosState:
        return praos.tick(self.params, ledger_view, slot, state)

    def update(self, view, slot, ticked) -> PraosState:
        return praos.update(self.params, view, slot, ticked, self.crypto)

    def reupdate(self, view, slot, ticked) -> PraosState:
        return praos.reupdate(self.params, view, slot, ticked)

    def check_is_leader(self, can_be_leader, slot, ticked):
        return praos.check_is_leader(self.params, can_be_leader, slot, ticked)

    def select_view(self, header) -> select.PraosSelectView:
        return select.PraosSelectView.from_header(header)

    def compare_candidates(self, ours, theirs) -> int:
        return select.compare_select_views(ours, theirs)

    def validate_batch(
        self, ticked, views: Sequence, collect_states: bool = False
    ) -> pbatch.BatchResult:
        """Batched fold of `update` with fused device crypto."""
        return pbatch.validate_batch(self.params, ticked, views, collect_states)


# ---------------------------------------------------------------------------
# BFT (Protocol/BFT.hs): round-robin signing for tests
# ---------------------------------------------------------------------------


@dataclass
class BftInvalidSignature(ConsensusError):
    slot: int


@dataclass
class BftWrongLeader(ConsensusError):
    slot: int
    expected_node: int


@dataclass(frozen=True)
class BftState:
    """BFT has no interesting chain-dep state (reference: ())."""

    last_slot: int | None = None


@dataclass(frozen=True)
class TickedBftState:
    state: BftState


@dataclass(frozen=True)
class BftView:
    """ValidateView: the signed bytes + signature + claimed node id."""

    node_id: int
    signed_bytes: bytes
    signature: bytes


class BftProtocol:
    """Round-robin: slot s is led by node (s mod num_nodes)."""

    def __init__(self, num_nodes: int, verification_keys: Sequence[bytes], security_param: int = 2160):
        self.num_nodes = num_nodes
        self.vks = list(verification_keys)
        self.security_param = security_param

    def initial_state(self) -> BftState:
        return BftState()

    def tick(self, ledger_view, slot, state) -> TickedBftState:
        return TickedBftState(state)

    def update(self, view: BftView, slot, ticked) -> BftState:
        expected = slot % self.num_nodes
        if view.node_id != expected:
            raise BftWrongLeader(slot, expected)
        if not host_ed25519.verify(self.vks[expected], view.signed_bytes, view.signature):
            raise BftInvalidSignature(slot)
        return BftState(slot)

    def reupdate(self, view, slot, ticked) -> BftState:
        return BftState(slot)

    def check_is_leader(self, node_id: int, slot, ticked):
        return node_id if slot % self.num_nodes == node_id else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)
