"""Concrete ConsensusProtocol instances.

* `PraosProtocol` — the flagship: host semantics from protocol/praos.py,
  batched device crypto from protocol/batch.py (reference instance:
  Praos.hs:364).
* `BftProtocol` — trivial round-robin BFT for tests (Protocol/BFT.hs):
  slot s must be signed by node (s mod n); one Ed25519 verify, no state.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Mapping, Sequence

from ..ops.host import ed25519 as host_ed25519
from . import batch as pbatch
from . import praos, select
from .abstract import ConsensusError
from .praos import PraosParams, PraosState, TickedPraosState


class PraosProtocol:
    """ConsensusProtocol (Praos c) — instance-as-object over praos.py."""

    def __init__(
        self,
        params: PraosParams,
        crypto: praos.CryptoVerifier = praos.HOST_VERIFIER,
        use_device_batch: bool = True,
    ):
        self.params = params
        self.crypto = crypto
        self.security_param = params.security_param
        # False routes LedgerDB/ChainSel through the sequential host fold
        # (useful for tests that should not pay kernel compilation)
        self.use_device_batch = use_device_batch

    def initial_state(self) -> PraosState:
        return PraosState()

    def tick(self, ledger_view, slot, state) -> TickedPraosState:
        return praos.tick(self.params, ledger_view, slot, state)

    def update(self, view, slot, ticked) -> PraosState:
        return praos.update(self.params, view, slot, ticked, self.crypto)

    def reupdate(self, view, slot, ticked) -> PraosState:
        return praos.reupdate(self.params, view, slot, ticked)

    def check_is_leader(self, can_be_leader, slot, ticked):
        return praos.check_is_leader(self.params, can_be_leader, slot, ticked)

    def select_view(self, header) -> select.PraosSelectView:
        return select.PraosSelectView.from_header(header)

    def compare_candidates(self, ours, theirs) -> int:
        return select.compare_select_views(ours, theirs)

    def validate_batch(
        self, ticked, views: Sequence, collect_states: bool = False,
        backend: str | None = None,
    ) -> pbatch.BatchResult:
        """Batched fold of `update`: fused device crypto ("device"),
        the C++ verifier ("native"), or a sequential pure fold
        ("host-fold" — also the use_device_batch=False default)."""
        if backend is None:
            backend = "device" if self.use_device_batch else "host-fold"
        if backend == "host-fold":
            return self._host_fold(ticked, views, collect_states)
        return pbatch.validate_batch(
            self.params, ticked, views, collect_states, backend=backend
        )

    def _host_fold(self, ticked, hvs, collect_states):
        """Sequential fold from an ALREADY-ticked state: the first header
        must not be ticked again (a second tick at an epoch boundary
        would rotate the nonce twice); later headers share the epoch, so
        their ticks are no-ops by construction."""
        st = ticked.state
        states = [] if collect_states else None
        t = ticked
        for i, hv in enumerate(hvs):
            if i > 0:
                t = praos.tick(self.params, ticked.ledger_view, hv.slot, st)
            try:
                st = praos.update(self.params, hv, hv.slot, t, self.crypto)
            except praos.PraosValidationError as e:
                return pbatch.BatchResult(st, i, e, states)
            if states is not None:
                states.append(st)
        return pbatch.BatchResult(st, len(hvs), None, states)


# ---------------------------------------------------------------------------
# BFT (Protocol/BFT.hs): round-robin signing for tests
# ---------------------------------------------------------------------------


@dataclass
class BftInvalidSignature(ConsensusError):
    slot: int


@dataclass
class BftWrongLeader(ConsensusError):
    slot: int
    expected_node: int


@dataclass(frozen=True)
class BftState:
    """BFT has no interesting chain-dep state (reference: ())."""

    last_slot: int | None = None


@dataclass(frozen=True)
class TickedBftState:
    state: BftState


@dataclass(frozen=True)
class BftView:
    """ValidateView: the signed bytes + signature + claimed node id."""

    node_id: int
    signed_bytes: bytes
    signature: bytes


class BftProtocol:
    """Round-robin: slot s is led by node (s mod num_nodes)."""

    def __init__(self, num_nodes: int, verification_keys: Sequence[bytes], security_param: int = 2160):
        self.num_nodes = num_nodes
        self.vks = list(verification_keys)
        self.security_param = security_param

    def initial_state(self) -> BftState:
        return BftState()

    def tick(self, ledger_view, slot, state) -> TickedBftState:
        return TickedBftState(state)

    def update(self, view: BftView, slot, ticked) -> BftState:
        expected = slot % self.num_nodes
        if view.node_id != expected:
            raise BftWrongLeader(slot, expected)
        if not host_ed25519.verify(self.vks[expected], view.signed_bytes, view.signature):
            raise BftInvalidSignature(slot)
        return BftState(slot)

    def reupdate(self, view, slot, ticked) -> BftState:
        return BftState(slot)

    def check_is_leader(self, node_id: int, slot, ticked):
        return node_id if slot % self.num_nodes == node_id else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


# ---------------------------------------------------------------------------
# PBFT (Protocol/PBFT.hs): permissive BFT — the issuer must be a delegate
# of a genesis key per the CURRENT ledger view's delegation map
# (PBftLedgerView, PBFT.hs:190), and no genesis key may have signed more
# than floor(threshold·window) of the last `window` signed blocks
# (PBftState tracks (slot, genesis-key) pairs, PBFT/State.hs:82).
# ---------------------------------------------------------------------------


@dataclass
class PBftNotGenesisDelegate(ConsensusError):
    slot: int
    issuer_vk: bytes


@dataclass
class PBftInvalidSignature(ConsensusError):
    slot: int


@dataclass
class PBftInvalidSlot(ConsensusError):
    """Slot before the last signed slot (PBFT.hs PBftInvalidSlot; the
    inequality is non-strict because EBBs share their epoch's first
    slot)."""

    slot: int
    last_signed: int


@dataclass
class PBftExceededSignThreshold(ConsensusError):
    slot: int
    genesis_key: int
    signed: int
    allowed: int


@dataclass(frozen=True)
class PBftParams:
    """PBftParams (Protocol/PBFT.hs:222-240): threshold is the fraction
    of the window one genesis key may sign; window = k signed blocks
    (pbftWindowSize = pbftSecurityParam)."""

    num_genesis_keys: int
    threshold: Fraction
    window: int  # number of recent signed blocks retained (k)
    security_param: int = 2160


@dataclass(frozen=True)
class PBftLedgerView:
    """The delegation map (PBFT.hs:190 PBftLedgerView — a Bimap genesis
    key ↔ delegate key): issuer vk -> genesis key index. Byron's ledger
    updates it via delegation certificates; the identity view maps each
    genesis key to itself."""

    delegates: Mapping[bytes, int]

    @classmethod
    def identity(cls, genesis_keys: Sequence[bytes]) -> "PBftLedgerView":
        return cls({vk: i for i, vk in enumerate(genesis_keys)})


@dataclass(frozen=True)
class PBftState:
    """Last `window` signed blocks as (slot, genesis key index), oldest
    first (PBftState, PBFT/State.hs:82)."""

    signers: tuple[tuple[int, int], ...] = ()

    @property
    def last_signed_slot(self) -> int | None:
        return self.signers[-1][0] if self.signers else None

    def count_signed_by(self, gk: int) -> int:
        """countSignedBy (State.hs:178)."""
        return sum(1 for (_s, g) in self.signers if g == gk)


@dataclass(frozen=True)
class TickedPBftState:
    """Carries the TICKED ledger view (delegation map) alongside the
    chain-dep state (PBFT.hs TickedPBftState)."""

    state: PBftState
    dlg: Mapping[bytes, int]


@dataclass(frozen=True)
class PBftView:
    """ValidateView: issuer key + signature over the header body."""

    issuer_vk: bytes
    signed_bytes: bytes
    signature: bytes


class _PBftBoundaryView:
    """PBftValidateBoundary (PBFT.hs:312): an EBB carries no signature;
    validation passes it through with NO state change (:326)."""

    def __repr__(self):
        return "PBftValidateBoundary"


PBFT_BOUNDARY_VIEW = _PBftBoundaryView()


class PBftProtocol:
    """ConsensusProtocol (PBft c) (Protocol/PBFT.hs:284)."""

    def __init__(self, params: PBftParams, genesis_keys: Sequence[bytes]):
        assert len(genesis_keys) == params.num_genesis_keys
        self.params = params
        self.genesis_keys = list(genesis_keys)
        self._identity_dlg = PBftLedgerView.identity(genesis_keys).delegates
        self.security_param = params.security_param

    @property
    def _threshold_count(self) -> int:
        # pbftWindowParams (PBFT.hs:393-396): floor(ratio * winSize)
        return int(self.params.threshold * self.params.window)

    def initial_state(self) -> PBftState:
        return PBftState()

    def tick(self, ledger_view, slot, state) -> TickedPBftState:
        dlg = (
            ledger_view.delegates
            if isinstance(ledger_view, PBftLedgerView)
            else self._identity_dlg
        )
        return TickedPBftState(state, dlg)

    def _append_signer(self, st: PBftState, slot: int, gk: int) -> PBftState:
        return PBftState((st.signers + ((slot, gk),))[-self.params.window :])

    def apply_checked_sig(
        self,
        st: PBftState,
        slot: int,
        issuer_vk: bytes,
        sig_ok: bool,
        dlg: Mapping[bytes, int] | None = None,
    ) -> PBftState:
        """The non-crypto PBft rules given a signature verdict, in the
        reference's order (PBFT.hs:320-352): signature, slot
        monotonicity, delegation lookup, then the window threshold on
        the APPENDED state — shared by the sequential `update` and the
        batched byron path (hardfork/composite.py) so the rule can
        never de-synchronize."""
        if not sig_ok:
            raise PBftInvalidSignature(slot)
        last = st.last_signed_slot
        if last is not None and slot < last:
            raise PBftInvalidSlot(slot, last)
        dlg = self._identity_dlg if dlg is None else dlg
        gk = dlg.get(issuer_vk)
        if gk is None:
            raise PBftNotGenesisDelegate(slot, issuer_vk)
        new = self._append_signer(st, slot, gk)
        signed = new.count_signed_by(gk)
        if signed > self._threshold_count:
            raise PBftExceededSignThreshold(
                slot, gk, signed, self._threshold_count
            )
        return new

    def update(self, view, slot, ticked: TickedPBftState) -> PBftState:
        if view is PBFT_BOUNDARY_VIEW:
            return ticked.state  # EBB: no checks, no state change
        sig_ok = host_ed25519.verify(
            view.issuer_vk, view.signed_bytes, view.signature
        )
        return self.apply_checked_sig(
            ticked.state, slot, view.issuer_vk, sig_ok, ticked.dlg
        )

    def reupdate(self, view, slot, ticked: TickedPBftState) -> PBftState:
        """reupdateChainDepState (PBFT.hs:356-372): no signature check;
        delegation + window append still run (failures are errors, the
        checks are known to pass)."""
        if view is PBFT_BOUNDARY_VIEW:
            return ticked.state
        gk = ticked.dlg[view.issuer_vk]
        return self._append_signer(ticked.state, slot, gk)

    def check_is_leader(self, node_id: int, slot, ticked):
        """PBFT leadership is round-robin among delegates (Byron)."""
        return node_id if slot % self.params.num_genesis_keys == node_id else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


# ---------------------------------------------------------------------------
# LeaderSchedule (Protocol/LeaderSchedule.hs): scripted leadership for
# ThreadNet tests — no crypto, the schedule IS the protocol
# ---------------------------------------------------------------------------


@dataclass
class NotScheduledLeader(ConsensusError):
    slot: int
    node_id: int


@dataclass(frozen=True)
class LeaderScheduleState:
    last_slot: int | None = None


@dataclass(frozen=True)
class TickedLeaderScheduleState:
    state: LeaderScheduleState


class LeaderScheduleProtocol:
    """WithLeaderSchedule: slot -> set of leader node ids."""

    def __init__(self, schedule: Mapping[int, Sequence[int]], security_param: int = 2160):
        self.schedule = {s: tuple(ns) for s, ns in schedule.items()}
        self.security_param = security_param

    def initial_state(self) -> LeaderScheduleState:
        return LeaderScheduleState()

    def tick(self, ledger_view, slot, state) -> TickedLeaderScheduleState:
        return TickedLeaderScheduleState(state)

    def update(self, node_id: int, slot, ticked) -> LeaderScheduleState:
        if node_id not in self.schedule.get(slot, ()):
            raise NotScheduledLeader(slot, node_id)
        return LeaderScheduleState(slot)

    def reupdate(self, node_id, slot, ticked) -> LeaderScheduleState:
        return LeaderScheduleState(slot)

    def check_is_leader(self, node_id: int, slot, ticked):
        return node_id if node_id in self.schedule.get(slot, ()) else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


# ---------------------------------------------------------------------------
# Chain-selection combinators (Protocol/{ModChainSel,MockChainSel,Signed}.hs)
# ---------------------------------------------------------------------------


class ModChainSel:
    """Protocol/ModChainSel.hs: the same protocol with its chain order
    REPLACED. Everything except select_view/compare_candidates delegates
    to the wrapped instance, so ChainSel/ChainSync/forging run unchanged
    while candidate preference follows the substituted ordering."""

    def __init__(self, inner, select_view_fn, compare_fn):
        self._inner = inner
        self._select_view_fn = select_view_fn
        self._compare_fn = compare_fn

    def select_view(self, header):
        return self._select_view_fn(header)

    def compare_candidates(self, ours, theirs) -> int:
        return self._compare_fn(ours, theirs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def mock_chain_sel(inner, score):
    """Protocol/MockChainSel.hs shape: longest chain wins, ties broken
    by `score(header)` (higher preferred) — the mock-block testlib's
    pluggable tie-breaker."""

    def view(header):
        return (header.block_no, score(header))

    def cmp(ours, theirs):
        o = (-1, float("-inf")) if ours is None else ours
        t = (-1, float("-inf")) if theirs is None else theirs
        return (t > o) - (t < o)

    return ModChainSel(inner, view, cmp)


class SignedHeader:
    """Protocol/Signed.hs: the 'Signed' seam — headers expose the exact
    bytes their signature covers. Praos headers satisfy it natively
    (Header.signed_bytes = the CBOR header body, Praos/Header.hs:120
    memoised serialisation); protocols that verify signatures batch over
    precisely these bytes."""

    @staticmethod
    def header_signed(header) -> bytes:
        return header.signed_bytes
