"""Concrete ConsensusProtocol instances.

* `PraosProtocol` — the flagship: host semantics from protocol/praos.py,
  batched device crypto from protocol/batch.py (reference instance:
  Praos.hs:364).
* `BftProtocol` — trivial round-robin BFT for tests (Protocol/BFT.hs):
  slot s must be signed by node (s mod n); one Ed25519 verify, no state.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Mapping, Sequence

from ..ops.host import ed25519 as host_ed25519
from . import batch as pbatch
from . import praos, select
from .abstract import ConsensusError
from .praos import PraosParams, PraosState, TickedPraosState


class PraosProtocol:
    """ConsensusProtocol (Praos c) — instance-as-object over praos.py."""

    def __init__(
        self,
        params: PraosParams,
        crypto: praos.CryptoVerifier = praos.HOST_VERIFIER,
        use_device_batch: bool = True,
    ):
        self.params = params
        self.crypto = crypto
        self.security_param = params.security_param
        # False routes LedgerDB/ChainSel through the sequential host fold
        # (useful for tests that should not pay kernel compilation)
        self.use_device_batch = use_device_batch

    def initial_state(self) -> PraosState:
        return PraosState()

    def tick(self, ledger_view, slot, state) -> TickedPraosState:
        return praos.tick(self.params, ledger_view, slot, state)

    def update(self, view, slot, ticked) -> PraosState:
        return praos.update(self.params, view, slot, ticked, self.crypto)

    def reupdate(self, view, slot, ticked) -> PraosState:
        return praos.reupdate(self.params, view, slot, ticked)

    def check_is_leader(self, can_be_leader, slot, ticked):
        return praos.check_is_leader(self.params, can_be_leader, slot, ticked)

    def select_view(self, header) -> select.PraosSelectView:
        return select.PraosSelectView.from_header(header)

    def compare_candidates(self, ours, theirs) -> int:
        return select.compare_select_views(ours, theirs)

    def validate_batch(
        self, ticked, views: Sequence, collect_states: bool = False,
        backend: str | None = None,
    ) -> pbatch.BatchResult:
        """Batched fold of `update`: fused device crypto ("device"),
        the C++ verifier ("native"), or a sequential pure fold
        ("host-fold" — also the use_device_batch=False default)."""
        if backend is None:
            backend = "device" if self.use_device_batch else "host-fold"
        if backend == "host-fold":
            return self._host_fold(ticked, views, collect_states)
        return pbatch.validate_batch(
            self.params, ticked, views, collect_states, backend=backend
        )

    def _host_fold(self, ticked, hvs, collect_states):
        """Sequential fold from an ALREADY-ticked state: the first header
        must not be ticked again (a second tick at an epoch boundary
        would rotate the nonce twice); later headers share the epoch, so
        their ticks are no-ops by construction."""
        st = ticked.state
        states = [] if collect_states else None
        t = ticked
        for i, hv in enumerate(hvs):
            if i > 0:
                t = praos.tick(self.params, ticked.ledger_view, hv.slot, st)
            try:
                st = praos.update(self.params, hv, hv.slot, t, self.crypto)
            except praos.PraosValidationError as e:
                return pbatch.BatchResult(st, i, e, states)
            if states is not None:
                states.append(st)
        return pbatch.BatchResult(st, len(hvs), None, states)


# ---------------------------------------------------------------------------
# BFT (Protocol/BFT.hs): round-robin signing for tests
# ---------------------------------------------------------------------------


@dataclass
class BftInvalidSignature(ConsensusError):
    slot: int


@dataclass
class BftWrongLeader(ConsensusError):
    slot: int
    expected_node: int


@dataclass(frozen=True)
class BftState:
    """BFT has no interesting chain-dep state (reference: ())."""

    last_slot: int | None = None


@dataclass(frozen=True)
class TickedBftState:
    state: BftState


@dataclass(frozen=True)
class BftView:
    """ValidateView: the signed bytes + signature + claimed node id."""

    node_id: int
    signed_bytes: bytes
    signature: bytes


class BftProtocol:
    """Round-robin: slot s is led by node (s mod num_nodes)."""

    def __init__(self, num_nodes: int, verification_keys: Sequence[bytes], security_param: int = 2160):
        self.num_nodes = num_nodes
        self.vks = list(verification_keys)
        self.security_param = security_param

    def initial_state(self) -> BftState:
        return BftState()

    def tick(self, ledger_view, slot, state) -> TickedBftState:
        return TickedBftState(state)

    def update(self, view: BftView, slot, ticked) -> BftState:
        expected = slot % self.num_nodes
        if view.node_id != expected:
            raise BftWrongLeader(slot, expected)
        if not host_ed25519.verify(self.vks[expected], view.signed_bytes, view.signature):
            raise BftInvalidSignature(slot)
        return BftState(slot)

    def reupdate(self, view, slot, ticked) -> BftState:
        return BftState(slot)

    def check_is_leader(self, node_id: int, slot, ticked):
        return node_id if slot % self.num_nodes == node_id else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


# ---------------------------------------------------------------------------
# PBFT (Protocol/PBFT.hs): permissive BFT — any genesis delegate may sign,
# but no delegate may have signed more than threshold·window of the last
# `window` blocks (PBftState tracks the signer window, PBFT/State.hs:82)
# ---------------------------------------------------------------------------


@dataclass
class PBftNotGenesisDelegate(ConsensusError):
    slot: int
    issuer_vk: bytes


@dataclass
class PBftInvalidSignature(ConsensusError):
    slot: int


@dataclass
class PBftExceededSignThreshold(ConsensusError):
    slot: int
    signer: int
    signed: int
    allowed: int


@dataclass(frozen=True)
class PBftParams:
    """PBftParams (Protocol/PBFT.hs): threshold is the max fraction of
    the window one delegate may sign; window = k signed blocks."""

    num_genesis_keys: int
    threshold: Fraction
    window: int  # number of recent signers retained (k)
    security_param: int = 2160


@dataclass(frozen=True)
class PBftState:
    """Last `window` signer indices, oldest first (PBftState)."""

    signers: tuple[int, ...] = ()


@dataclass(frozen=True)
class TickedPBftState:
    state: PBftState


@dataclass(frozen=True)
class PBftView:
    """ValidateView: issuer key + signature over the header body."""

    issuer_vk: bytes
    signed_bytes: bytes
    signature: bytes


class PBftProtocol:
    """ConsensusProtocol (PBft c) (Protocol/PBFT.hs:284)."""

    def __init__(self, params: PBftParams, genesis_keys: Sequence[bytes]):
        assert len(genesis_keys) == params.num_genesis_keys
        self.params = params
        self.genesis_keys = list(genesis_keys)
        self._index = {vk: i for i, vk in enumerate(genesis_keys)}
        self.security_param = params.security_param

    def initial_state(self) -> PBftState:
        return PBftState()

    def tick(self, ledger_view, slot, state) -> TickedPBftState:
        return TickedPBftState(state)

    def _append_signer(self, st: PBftState, signer: int) -> PBftState:
        signers = (st.signers + (signer,))[-self.params.window :]
        return PBftState(signers)

    def apply_checked_sig(
        self, st: PBftState, slot: int, issuer_vk: bytes, sig_ok: bool
    ) -> PBftState:
        """The non-crypto PBft rules given a signature verdict: delegate
        membership, then signature, then the window threshold — shared
        by the sequential `update` and the batched byron path
        (hardfork/composite.py) so the rule can never de-synchronize."""
        signer = self._index.get(issuer_vk)
        if signer is None:
            raise PBftNotGenesisDelegate(slot, issuer_vk)
        if not sig_ok:
            raise PBftInvalidSignature(slot)
        # threshold check over the window INCLUDING this block
        window = st.signers[-(self.params.window - 1) :] if self.params.window > 1 else ()
        signed = sum(1 for s in window if s == signer) + 1
        allowed = int(self.params.threshold * self.params.window)
        if signed > allowed:
            raise PBftExceededSignThreshold(slot, signer, signed, allowed)
        return self._append_signer(st, signer)

    def update(self, view: PBftView, slot, ticked) -> PBftState:
        sig_ok = host_ed25519.verify(
            view.issuer_vk, view.signed_bytes, view.signature
        )
        return self.apply_checked_sig(ticked.state, slot, view.issuer_vk, sig_ok)

    def reupdate(self, view: PBftView, slot, ticked) -> PBftState:
        return self._append_signer(ticked.state, self._index[view.issuer_vk])

    def check_is_leader(self, node_id: int, slot, ticked):
        """PBFT leadership is round-robin among delegates (Byron)."""
        return node_id if slot % self.params.num_genesis_keys == node_id else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)


# ---------------------------------------------------------------------------
# LeaderSchedule (Protocol/LeaderSchedule.hs): scripted leadership for
# ThreadNet tests — no crypto, the schedule IS the protocol
# ---------------------------------------------------------------------------


@dataclass
class NotScheduledLeader(ConsensusError):
    slot: int
    node_id: int


@dataclass(frozen=True)
class LeaderScheduleState:
    last_slot: int | None = None


@dataclass(frozen=True)
class TickedLeaderScheduleState:
    state: LeaderScheduleState


class LeaderScheduleProtocol:
    """WithLeaderSchedule: slot -> set of leader node ids."""

    def __init__(self, schedule: Mapping[int, Sequence[int]], security_param: int = 2160):
        self.schedule = {s: tuple(ns) for s, ns in schedule.items()}
        self.security_param = security_param

    def initial_state(self) -> LeaderScheduleState:
        return LeaderScheduleState()

    def tick(self, ledger_view, slot, state) -> TickedLeaderScheduleState:
        return TickedLeaderScheduleState(state)

    def update(self, node_id: int, slot, ticked) -> LeaderScheduleState:
        if node_id not in self.schedule.get(slot, ()):
            raise NotScheduledLeader(slot, node_id)
        return LeaderScheduleState(slot)

    def reupdate(self, node_id, slot, ticked) -> LeaderScheduleState:
        return LeaderScheduleState(slot)

    def check_is_leader(self, node_id: int, slot, ticked):
        return node_id if node_id in self.schedule.get(slot, ()) else None

    def select_view(self, header):
        return header.block_no

    def compare_candidates(self, ours, theirs) -> int:
        o = -1 if ours is None else ours
        t = -1 if theirs is None else theirs
        return (t > o) - (t < o)
