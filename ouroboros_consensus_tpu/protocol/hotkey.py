"""HotKey: forging-side KES key management with forward-secure evolution.

Reference: `ouroboros-consensus-protocol/src/.../Protocol/Ledger/HotKey.hs`
— `KESInfo`/`kesStatus` (:45,90), the `HotKey` record with `sign` and
`evolve` (:124), `mkHotKey` (:169). Evolution FORGETS older key material
(forward security): after evolving to t, signatures for periods < t are
impossible — the reference mlocks and zeroes old keys; here the seeds are
simply dropped (the Python analog of forgetting).

Design: a CompactSum KES secret at evolution t is (leaf seed for t, the
seeds of the right subtrees hanging off the path root→t that are still
in the future). `evolve` pops the deepest pending subtree and expands its
left spine — amortized O(1) hash work per evolution, O(depth) storage.
The PUBLIC vk tree is precomputed once at construction (vks are not
secret), so signatures can carry their sibling-vk paths after the seeds
are gone.

OCert lifecycle: `issue_ocert` binds the KES vk to the cold key with an
incrementing counter (Praos.hs:585-590 checks monotonicity per issuer);
a node re-keys by constructing a fresh HotKey + ocert with counter+1
(ThreadNet/Util/Rekeying.hs is the reference's test driver for this).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ops.host import fast
from ..ops.host.kes import _h256, _seed_left, _seed_right


class KESKeyExpired(Exception):
    """Sign requested past the key's last evolution (kesStatus Expired):
    the forging loop maps this to CannotForge, not a crash."""


class KESBeforeStart(Exception):
    """Sign requested for a period before the key's start, or for an
    evolution already forgotten (forward security makes it unsignable)."""


@dataclass(frozen=True)
class KESInfo:
    """HotKey.KESInfo (HotKey.hs:45): the key's period window + current
    evolution. start/end are ABSOLUTE KES periods, end exclusive."""

    start_period: int
    end_period: int
    evolution: int

    @property
    def current_period(self) -> int:
        return self.start_period + self.evolution


def kes_status(info: KESInfo, period: int) -> str:
    """kesStatus (HotKey.hs:90): 'before' | 'in_evolution' | 'expired'."""
    if period < info.start_period:
        return "before"
    if period >= info.end_period:
        return "expired"
    return "in_evolution"


class HotKey:
    """The evolving KES signing key (HotKey.hs:124)."""

    def __init__(self, kes_seed: bytes, depth: int, start_period: int,
                 max_evolutions: int | None = None):
        self.depth = depth
        self.start_period = start_period
        self.max_evolutions = min(
            1 << depth,
            (1 << depth) if max_evolutions is None else max_evolutions,
        )
        self.evolution = 0
        # secret state: pending right-subtree seeds along the left spine,
        # deepest last; leaf seed for evolution 0
        self._pending: list[tuple[bytes, int]] = []
        seed = kes_seed
        for level in range(depth):
            self._pending.append((_seed_right(seed), depth - level - 1))
            seed = _seed_left(seed)
        self._leaf_seed: bytes | None = seed
        # public vk tree: vk[level][index], level 0 = leaves (2^depth),
        # level depth = root (1). Derived BEFORE dropping any seeds.
        self._vks = self._derive_vk_tree(kes_seed, depth)

    @staticmethod
    def _derive_vk_tree(seed: bytes, depth: int) -> list[list[bytes]]:
        leaves: list[bytes] = []

        def walk(sd: bytes, d: int):
            if d == 0:
                leaves.append(fast.ed25519_public(sd))
                return
            walk(_seed_left(sd), d - 1)
            walk(_seed_right(sd), d - 1)

        walk(seed, depth)
        levels = [leaves]
        for _ in range(depth):
            prev = levels[-1]
            levels.append(
                [_h256(prev[2 * i] + prev[2 * i + 1]) for i in range(len(prev) // 2)]
            )
        return levels

    # -- queries -------------------------------------------------------------

    @property
    def vk(self) -> bytes:
        """The root verification key (what the OCert certifies)."""
        return self._vks[self.depth][0]

    def kes_info(self) -> KESInfo:
        return KESInfo(
            self.start_period,
            self.start_period + self.max_evolutions,
            self.evolution,
        )

    # -- evolution (HotKey.hs evolve; forgets old keys) ----------------------

    def _evolve_once(self) -> None:
        self._leaf_seed = None  # forget
        if not self._pending:
            raise KESKeyExpired(f"KES key exhausted at evolution {self.evolution}")
        seed, d = self._pending.pop()
        for level in range(d):
            self._pending.append((_seed_right(seed), d - level - 1))
            seed = _seed_left(seed)
        self._leaf_seed = seed
        self.evolution += 1

    def evolve_to(self, period: int) -> None:
        """Evolve (forgetting) until the key signs for ABSOLUTE KES
        period `period` (updateForgeState's KES tick)."""
        t = period - self.start_period
        if t < self.evolution or t < 0:
            raise KESBeforeStart(
                f"period {period}: evolution {t} < current {self.evolution}"
            )
        if t >= self.max_evolutions:
            raise KESKeyExpired(
                f"period {period} >= end {self.start_period + self.max_evolutions}"
            )
        while self.evolution < t:
            self._evolve_once()

    # -- signing -------------------------------------------------------------

    def sign(self, period: int, msg: bytes) -> bytes:
        """Evolve to `period` and produce the CompactSum signature
        (HotKey.hs:142 sign = evolve-then-KES.sign)."""
        self.evolve_to(period)
        assert self._leaf_seed is not None
        t = self.evolution
        sig = fast.ed25519_sign(self._leaf_seed, msg) + self._vks[0][t]
        idx = t
        for level in range(self.depth):
            sibling = self._vks[level][idx ^ 1]
            sig += sibling
            idx >>= 1
        return sig

    def forget(self) -> None:
        """Drop ALL key material (node shutdown / rekey)."""
        self._leaf_seed = None
        self._pending.clear()
        self.evolution = self.max_evolutions


def issue_ocert(cold_seed: bytes, hot_vk: bytes, counter: int, kes_period: int):
    """Operational certificate: cold-key signature over
    (kes_vk, counter, period) — OCert.signable, checked at Praos.hs:580."""
    from .views import OCert

    oc = OCert(hot_vk, counter, kes_period, b"")
    return OCert(hot_vk, counter, kes_period, fast.ed25519_sign(cold_seed, oc.signable()))
