"""Batched Praos header validation — the TPU hot path.

This is the architectural inversion at the heart of the framework: where
the reference validates header-by-header inside a sequential fold
(`ledgerDbPushMany` = repeatedlyM, LedgerDB/Update.hs:302; crypto at
Praos.hs:441-606), we stage a columnar batch of header views (SoA) and run
ALL the expensive work as one fused device program:

  * Ed25519 verify of the OCert cold-key signature   (Praos.hs:580)
  * CompactSum KES verify of the header body          (Praos.hs:582)
  * ECVRF verify of the leader-election proof         (Praos.hs:543)
  * beta == declared certified output                 (verifyCertified)
  * leader-value range extension Blake2b("L" ‖ beta)  (Praos/VRF.hs:103)
  * leader threshold compare                          (Praos.hs:551)
  * nonce range extension Blake2b²("N" ‖ beta)        (Praos/VRF.hs:116)

Only the cheap state-threading (ocert counter monotonicity, nonce fold —
a NON-associative hash fold, so inherently sequential but ~1µs/header on
host) remains outside the kernel. Verdicts come back as per-check bitmaps;
the host locates the first failing chain position and reports the exact
`PraosValidationError` the sequential reference implementation would have
raised (re-deriving it with the host verifier for the error payload).

Leader threshold on device: the rule p < 1 − (1−f)^σ compares a 256-bit
hash against an irrational bound. Per (σ, f) — one per pool per epoch —
the host brackets T = 2²⁵⁶·(1 − (1−f)^σ) by rationals [T_lo, T_hu] tight
to ~2⁻⁴⁰ relative width (protocol/leader.py series bounds). The device
does the big-endian compare against both brackets; the measure-zero band
in between falls back to the exact host check (`leader_ambiguous` mask).

Epoch segmentation (SURVEY.md §5.7): the epoch nonce and pool distribution
are constant within an epoch, so a batch spans at most one epoch; the
chain driver (storage/ledgerdb, tools/db_analyser) cuts batches at epoch
boundaries and threads the tiny PraosState between them.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from functools import lru_cache
from typing import Mapping, NamedTuple, Sequence

import numpy as np
from jax import numpy as jnp

from ..ops import blake2b, ecvrf_batch, ed25519_batch, kes_batch
from ..ops.host import kes as host_kes
from . import leader, nonces, praos
from .praos import PraosParams, PraosState, TickedPraosState
from .views import HeaderView, LedgerView, hash_key, hash_vrf_vk

# ---------------------------------------------------------------------------
# Leader-threshold bracketing (host, cached per (sigma, f))
# ---------------------------------------------------------------------------


@lru_cache(maxsize=4096)
def leader_threshold_bracket(sigma: Fraction, f: Fraction) -> tuple[int, int]:
    """[T_lo, T_hi] integers bracketing 2^256 * (1 - (1-f)^sigma).

    leader_value < T_lo  => certainly a leader;
    leader_value >= T_hi => certainly not;
    otherwise undecided (exact host check).  With 64 series terms the
    bracket width is far below 1 for every realistic (sigma, f), so the
    ambiguous band is empty in practice.
    """
    if f == 1:
        return (leader.LEADER_VALUE_MAX, leader.LEADER_VALUE_MAX)
    if sigma == 0:
        return (0, 0)
    llo, lhi = leader._neg_log1m_interval(f, 64)
    elo, ehi = leader._exp_interval(sigma * llo, sigma * lhi, 64)
    # lhs = 2^256/(2^256 - lv) < exp(x)  <=>  lv < 2^256 (1 - 1/exp(x))
    t_lo = leader.LEADER_VALUE_MAX * (1 - Fraction(1) / elo)
    t_hi = leader.LEADER_VALUE_MAX * (1 - Fraction(1) / ehi)
    lo = int(t_lo)  # floor: lv < floor(T_lo) <= T_lo  => leader
    hi = -int(-t_hi)  # ceil: lv >= ceil(T_hi) >= T_hi => not leader
    return (lo, hi)


# ---------------------------------------------------------------------------
# SoA staging
# ---------------------------------------------------------------------------


class PraosBatch(NamedTuple):
    """Device-ready columnar batch of Praos header-validation inputs."""

    ed: ed25519_batch.Ed25519Batch  # OCert cold-key signature check
    kes: kes_batch.KesBatch  # header-body KES signature check
    vrf: ecvrf_batch.EcvrfBatch  # leader VRF proof check
    beta: np.ndarray  # [B, 64] uint8 — declared certified VRF output
    thr_lo: np.ndarray  # [B, 32] uint8 big-endian leader bound (certain win)
    thr_hi: np.ndarray  # [B, 32] uint8 big-endian leader bound (certain loss)


@dataclass(frozen=True)
class HostChecks:
    """Results of the cheap non-crypto checks.

    Split into KES-side and VRF-side error arrays because the reference
    interleaves them with the crypto verdicts in a strict order
    (validateKESSignature COMPLETELY before validateVRFSignature,
    Praos.hs:441-466) that `_lane_error` must reproduce.
    """

    # per-lane: None = pass, else the error the reference would raise
    kes_window_errors: list  # KESBeforeStart / KESAfterEnd (Praos.hs:560-574)
    vrf_lookup_errors: list  # VRFKeyUnknown / WrongVRFKey (Praos.hs:530-540)
    kes_evolution: np.ndarray  # [B] int32 — t = kes_period - c0 (clamped 0)


def host_prechecks(
    params: PraosParams,
    ledger_view: LedgerView,
    hvs: Sequence[HeaderView],
) -> HostChecks:
    """The non-crypto parts of validateKESSignature/validateVRFSignature
    (Praos.hs:558-574 window checks, :528-540 pool lookups), batch-wide.

    OCert counter monotonicity (Praos.hs:585-590) is NOT here: it depends
    on the evolving counter map and is checked in the sequential epilogue.
    """
    kes_errors: list = [None] * len(hvs)
    vrf_errors: list = [None] * len(hvs)
    evol = np.zeros((len(hvs),), np.int32)
    for i, hv in enumerate(hvs):
        c0 = hv.ocert.kes_period
        kp = params.kes_period_of(hv.slot)
        if not c0 <= kp:
            kes_errors[i] = praos.KESBeforeStartOCERT(c0, kp)
        elif not kp < c0 + params.max_kes_evolutions:
            kes_errors[i] = praos.KESAfterEndOCERT(kp, c0, params.max_kes_evolutions)
        else:
            evol[i] = kp - c0
        hk = hash_key(hv.vk_cold)
        entry = ledger_view.pool_distr.get(hk)
        if entry is None:
            vrf_errors[i] = praos.VRFKeyUnknown(hk)
        else:
            header_vrf_hash = hash_vrf_vk(hv.vrf_vk)
            if entry.vrf_key_hash != header_vrf_hash:
                vrf_errors[i] = praos.VRFKeyWrongVRFKey(
                    hk, entry.vrf_key_hash, header_vrf_hash
                )
    return HostChecks(kes_errors, vrf_errors, evol)


@lru_cache(maxsize=4096)
def _threshold_rows(sigma: Fraction, f: Fraction):
    """Encoded (lo, hi) threshold byte rows per (sigma, f) — the
    bracket itself is lru_cached too, but the per-header Fraction wrap
    + 32-byte to_bytes/frombuffer encoding dominated staging before
    this was hoisted. Clamped to the 256-bit compare domain: a
    threshold of 2^256 means "every value wins", encoded as all-0xFF +
    the hi-inclusive trick."""
    lo, hi = leader_threshold_bracket(sigma, f)
    return (
        np.frombuffer(min(lo, (1 << 256) - 1).to_bytes(32, "big"), np.uint8),
        np.frombuffer(min(hi, (1 << 256) - 1).to_bytes(32, "big"), np.uint8),
    )


def stage(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    hvs: Sequence[HeaderView],
    evolution: np.ndarray,
) -> PraosBatch:
    """Columnarize header views for the fused device kernel."""
    b = len(hvs)
    ed = ed25519_batch.stage_np(
        [hv.vk_cold for hv in hvs],
        [hv.ocert.sigma for hv in hvs],
        [hv.ocert.signable() for hv in hvs],
    )
    kes = kes_batch.stage_np(
        [hv.ocert.vk_hot for hv in hvs],
        [int(t) for t in evolution],
        [hv.signed_bytes for hv in hvs],
        [hv.kes_sig for hv in hvs],
        depth=params.kes_depth,
    )
    vrf = ecvrf_batch.stage_np(
        [hv.vrf_vk for hv in hvs],
        [hv.vrf_proof for hv in hvs],
        [nonces.mk_input_vrf(hv.slot, epoch_nonce) for hv in hvs],
    )
    assert all(len(hv.vrf_output) == 64 for hv in hvs)
    beta = np.frombuffer(
        b"".join(hv.vrf_output for hv in hvs), np.uint8
    ).reshape(b, 64).copy()
    thr_lo = np.zeros((b, 32), np.uint8)
    thr_hi = np.zeros((b, 32), np.uint8)
    f = Fraction(params.active_slot_coeff)
    for i, hv in enumerate(hvs):
        entry = ledger_view.pool_distr.get(hash_key(hv.vk_cold))
        sigma = entry.stake if entry is not None else Fraction(0)
        lo_row, hi_row = _threshold_rows(sigma, f)
        thr_lo[i] = lo_row
        thr_hi[i] = hi_row
    return PraosBatch(ed, kes, vrf, beta, thr_lo, thr_hi)


# ---------------------------------------------------------------------------
# Fused device kernel
# ---------------------------------------------------------------------------


def _lt_be(a, b):
    """Big-endian lexicographic a < b for [..., 32] int32 byte arrays."""
    eq = a == b
    # all_eq_before[i] = all(eq[:i])
    all_eq_before = jnp.cumprod(
        jnp.concatenate([jnp.ones_like(eq[..., :1]), eq[..., :-1]], axis=-1),
        axis=-1,
    ).astype(bool)
    return jnp.any(all_eq_before & (a < b), axis=-1)


class Verdicts(NamedTuple):
    """Per-lane verdict bitmaps + derived values (device arrays)."""

    ok_ocert_sig: jnp.ndarray  # [B] InvalidSignatureOCERT if False
    ok_kes_sig: jnp.ndarray  # [B] InvalidKesSignatureOCERT if False
    ok_vrf: jnp.ndarray  # [B] VRFKeyBadProof if False (proof or beta mismatch)
    ok_leader: jnp.ndarray  # [B] VRFLeaderValueTooBig if False
    leader_ambiguous: jnp.ndarray  # [B] host must decide exactly
    eta: jnp.ndarray  # [B, 32] vrfNonceValue(beta) for the nonce fold
    leader_value: jnp.ndarray  # [B, 32] big-endian Blake2b("L" ‖ beta)


def verify_praos(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
) -> Verdicts:
    """The fused Praos hot-path kernel. One jit, one device program.

    XLA fuses the three verifier subgraphs and the Blake2b range
    extensions; everything is batch-uniform control flow (mask lanes).
    The seven per-lane point compressions (Ed25519 R-check, KES leaf
    R-check, ECVRF H/Γ/U/V/8Γ) share ONE Montgomery inversion chain.
    """
    from ..ops import curve

    ok_ed_pre, ed_point = ed25519_batch.verify_point(
        ed_pk, ed_s, ed_hblocks, ed_hnblocks
    )
    ok_kes_pre, kes_point = kes_batch.verify_point(
        kes_vk, kes_period, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks,
    )
    ok_vrf_pre, vrf_points = ecvrf_batch.verify_points(
        vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha
    )
    encs = curve.compress_many([ed_point, kes_point, *vrf_points])
    ok_ed = ok_ed_pre & jnp.all(
        encs[0] == jnp.asarray(ed_r).astype(jnp.int32), axis=-1
    )
    ok_kes = ok_kes_pre & jnp.all(
        encs[1] == jnp.asarray(kes_r).astype(jnp.int32), axis=-1
    )
    ok_proof, beta = ecvrf_batch.finish(ok_vrf_pre, vrf_c, encs[2:])
    beta_decl = jnp.asarray(beta_decl).astype(jnp.int32)
    ok_vrf = ok_proof & jnp.all(beta == beta_decl, axis=-1)

    # range extensions (Praos/VRF.hs:103,116) on the DECLARED beta: the
    # reference computes them from the certified output, which ok_vrf
    # guarantees equals the proof's beta
    tag_l = jnp.broadcast_to(
        jnp.asarray([ord("L")], jnp.int32), (*beta_decl.shape[:-1], 1)
    )
    lv = blake2b.blake2b_fixed(
        jnp.concatenate([tag_l, beta_decl], axis=-1), 65, 32
    )  # 32 bytes, big-endian natural (hash bytes ARE the BE encoding)
    tag_n = jnp.broadcast_to(
        jnp.asarray([ord("N")], jnp.int32), (*beta_decl.shape[:-1], 1)
    )
    eta1 = blake2b.blake2b_fixed(
        jnp.concatenate([tag_n, beta_decl], axis=-1), 65, 32
    )
    eta = blake2b.blake2b_fixed(eta1, 32, 32)

    thr_lo = jnp.asarray(thr_lo).astype(jnp.int32)
    thr_hi = jnp.asarray(thr_hi).astype(jnp.int32)
    certain_win = _lt_be(lv, thr_lo)
    certain_loss = ~_lt_be(lv, thr_hi)
    ok_leader = certain_win
    ambiguous = ~certain_win & ~certain_loss
    return Verdicts(ok_ed, ok_kes, ok_vrf, ok_leader, ambiguous, eta, lv)


_JIT: dict = {}

# device implementation: "pk" = Pallas kernels (ops/pk, limb-first,
# ladders in VMEM — the TPU production path), "xla" = the original jnp
# graph (the cross-check twin; also the CPU default, where the pk path
# only exists as interpret-mode and compiles far slower than it runs)
DEVICE_IMPL = __import__("os").environ.get("OCT_DEVICE_IMPL", "")


def _impl() -> str:
    if DEVICE_IMPL:
        return DEVICE_IMPL
    import jax

    return "pk" if jax.devices()[0].platform == "tpu" else "xla"


def flatten_batch(batch: PraosBatch) -> list:
    """PraosBatch -> flat array list in verify_praos argument order."""
    return [*batch.ed, *batch.kes, *batch.vrf, batch.beta, batch.thr_lo, batch.thr_hi]


def _words_to_byte_blocks(w: np.ndarray) -> np.ndarray:
    """SHA-512 word blocks [B, NB, 16, 2] uint32 -> [NB, 128, B] int32
    byte blocks (the ops/pk limb-first hash input layout)."""
    b_, nb = w.shape[0], w.shape[1]
    out = np.zeros((b_, nb, 16, 8), np.int32)
    for k in range(4):
        out[..., k] = ((w[..., 0] >> (24 - 8 * k)) & 0xFF).astype(np.int32)
        out[..., 4 + k] = ((w[..., 1] >> (24 - 8 * k)) & 0xFF).astype(np.int32)
    return np.ascontiguousarray(out.reshape(b_, nb, 128).transpose(1, 2, 0))


def _t(a: np.ndarray) -> np.ndarray:
    """[B, n] -> [n, B] int32, contiguous."""
    return np.ascontiguousarray(np.asarray(a).astype(np.int32).T)


def pk_arrays(batch: PraosBatch) -> list[np.ndarray]:
    """PraosBatch ([B, ...] staging) -> limb-first arrays in
    ops/pk/kernels.verify_praos_tiles argument order."""
    ed, kes, vrf = batch.ed, batch.kes, batch.vrf
    b = batch.beta.shape[0]
    return [
        _t(ed.pk), _t(ed.r), _t(ed.s),
        _words_to_byte_blocks(ed.hblocks),
        np.ascontiguousarray(ed.hnblocks.astype(np.int32).reshape(1, b)),
        _t(kes.vk),
        np.ascontiguousarray(kes.period.astype(np.int32).reshape(1, b)),
        _t(kes.r), _t(kes.s), _t(kes.vk_leaf),
        np.ascontiguousarray(
            np.asarray(kes.siblings).astype(np.int32).transpose(1, 2, 0)
        ),
        _words_to_byte_blocks(kes.hblocks),
        np.ascontiguousarray(kes.hnblocks.astype(np.int32).reshape(1, b)),
        _t(vrf.pk), _t(vrf.gamma), _t(vrf.c), _t(vrf.s), _t(vrf.alpha),
        _t(batch.beta), _t(batch.thr_lo), _t(batch.thr_hi),
    ]


def _jitted_pk(kes_depth: int):
    import functools
    import os

    import jax

    key = ("pk", kes_depth)
    if key not in _JIT:
        from ..ops.pk import kernels as pk_kernels

        if os.environ.get("OCT_PK_FUSED"):
            # the original single-jit composition (one cache entry for
            # the whole program) — opt-in for A/B measurement
            _JIT[key] = jax.jit(
                functools.partial(
                    pk_kernels.verify_praos_staged, kes_depth=kes_depth
                )
            )
        else:
            # default: per-stage jits (kernels.verify_praos_split) — a
            # wedged compile costs one stage and the persistent cache
            # accumulates stage entries across retries (VERDICT r3 #2)
            _JIT[key] = functools.partial(
                pk_kernels.verify_praos_split, kes_depth=kes_depth
            )
    return _JIT[key]


def _pk_dispatch(batch: PraosBatch):
    """Dispatch the Pallas path (async); -> opaque handle. The staged
    [B, ...] uint8 columns go straight to the jit — transposes and the
    byte expansion run in XLA (pk_arrays on host cost ~20 us/header)."""
    depth = batch.kes.siblings.shape[-2]
    ed, kes, vrf = batch.ed, batch.kes, batch.vrf
    # (an explicit async jax.device_put of the columns first was A/B'd
    # r5: through the remote-TPU tunnel it does NOT overlap with the
    # prior window's kernels — the same ~130 ms/batch of H2D just moves
    # from the materialize wait into the dispatch bracket)
    out = _jitted_pk(depth)(
        ed.pk, ed.r, ed.s, ed.hblocks, ed.hnblocks,
        kes.vk, kes.period, kes.r, kes.s, kes.vk_leaf, kes.siblings,
        kes.hblocks, kes.hnblocks,
        vrf.pk, vrf.gamma, vrf.c, vrf.s, vrf.alpha,
        batch.beta, batch.thr_lo, batch.thr_hi,
    )
    return out


def _pk_materialize(out, b: int) -> Verdicts:
    flags, eta, lv = (np.asarray(x) for x in out)
    return Verdicts(
        ok_ocert_sig=flags[0, :b] != 0,
        ok_kes_sig=flags[1, :b] != 0,
        ok_vrf=flags[2, :b] != 0,
        ok_leader=flags[3, :b] != 0,
        leader_ambiguous=flags[4, :b] != 0,
        eta=np.ascontiguousarray(eta[:, :b].T),
        leader_value=np.ascontiguousarray(lv[:, :b].T),
    )


def pad_batch_to(batch: PraosBatch, size: int) -> PraosBatch:
    """Pad every column's batch dim up to `size` by replicating lane 0
    (guaranteed-decodable inputs; callers slice verdicts back to the true
    size). Keeps the jit cache bounded: one compilation per bucket shape
    instead of one per epoch-segment length."""
    b = batch.beta.shape[0]
    if b == size:
        return batch

    def _pad(x):
        x = np.asarray(x)
        return np.concatenate([x, np.repeat(x[:1], size - b, axis=0)], axis=0)

    def _pad_tuple(t):
        return type(t)(*(_pad(c) for c in t))

    return PraosBatch(
        ed=_pad_tuple(batch.ed),
        kes=_pad_tuple(batch.kes),
        vrf=_pad_tuple(batch.vrf),
        beta=_pad(batch.beta),
        thr_lo=_pad(batch.thr_lo),
        thr_hi=_pad(batch.thr_hi),
    )


def bucket_size(b: int, minimum: int = 8) -> int:
    """Shape bucket for a batch of b lanes: next power of two up to
    2048, then next multiple of 2048. Pure powers of two waste up to
    half the lanes on the epoch-tail batch (a ~21.6k-block epoch slices
    to 8192+8192+5216, and 5216 padded to 8192 is 36% dead work —
    ~14% of ALL device lanes at the 1M bench scale); 2048-granularity
    buckets cap tail padding at <2048 lanes while keeping the set of
    compiled shapes small (the remainder is epoch-size-distributed, so
    in practice one extra shape per chain)."""
    n = minimum
    while n < b and n < 2048:
        n *= 2
    if b <= n:
        return n
    return ((b + 2047) // 2048) * 2048


def _jitted_verify():
    import jax

    if "fn" not in _JIT:
        _JIT["fn"] = jax.jit(verify_praos)
    return _JIT["fn"]


def run_batch_native(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce,
    hvs: Sequence[HeaderView],
    pre: HostChecks,
) -> Verdicts:
    """Native (C++) crypto backend producing the same Verdicts shape as
    the device kernel — the honest single-core comparison path and the
    fallback when no accelerator is available (native/hostcrypto.cpp
    oc_validate_praos). Short-circuits at the first failing lane; lanes
    past it carry don't-care verdicts, which the sequential epilogue
    never reads."""
    from .. import native_loader as nl

    n = len(hvs)
    cold_vk = np.stack([np.frombuffer(hv.vk_cold, np.uint8) for hv in hvs])
    ocert_sig = np.stack([np.frombuffer(hv.ocert.sigma, np.uint8) for hv in hvs])
    ocert_msg = np.stack(
        [np.frombuffer(hv.ocert.signable(), np.uint8) for hv in hvs]
    )
    kes_vk = np.stack([np.frombuffer(hv.ocert.vk_hot, np.uint8) for hv in hvs])
    kes_sig = np.stack([np.frombuffer(hv.kes_sig, np.uint8) for hv in hvs])
    body = b"".join(hv.signed_bytes for hv in hvs)
    body_off = np.zeros(n + 1, np.int64)
    np.cumsum([len(hv.signed_bytes) for hv in hvs], out=body_off[1:])
    vrf_vk = np.stack([np.frombuffer(hv.vrf_vk, np.uint8) for hv in hvs])
    vrf_proof = np.stack([np.frombuffer(hv.vrf_proof, np.uint8) for hv in hvs])
    vrf_alpha = np.stack(
        [
            np.frombuffer(nonces.mk_input_vrf(hv.slot, epoch_nonce), np.uint8)
            for hv in hvs
        ]
    )
    vrf_output = np.stack([np.frombuffer(hv.vrf_output, np.uint8) for hv in hvs])

    rc, kind, lv, eta = nl.native_validate_praos(
        cold_vk, ocert_sig, ocert_msg, kes_vk,
        pre.kes_evolution.astype(np.int64), kes_sig, params.kes_depth,
        body, body_off, vrf_vk, vrf_proof, vrf_alpha, vrf_output,
    )
    ok_ocert = np.ones(n, bool)
    ok_kes = np.ones(n, bool)
    ok_vrf = np.ones(n, bool)
    if rc >= 0:
        (ok_ocert if kind == 1 else ok_kes if kind == 2 else ok_vrf)[rc] = False

    # leader threshold: bracket compare exactly as the device kernel
    f = params.active_slot_coeff
    ok_leader = np.zeros(n, bool)
    ambiguous = np.zeros(n, bool)
    stop = n if rc < 0 else rc
    for i in range(stop):
        hv = hvs[i]
        entry = ledger_view.pool_distr.get(hash_key(hv.vk_cold))
        sigma = entry.stake if entry is not None else Fraction(0)
        lo, hi = leader_threshold_bracket(Fraction(sigma), Fraction(f))
        lv_int = int.from_bytes(lv[i].tobytes(), "big")
        ok_leader[i] = lv_int < lo
        ambiguous[i] = not ok_leader[i] and lv_int < hi
    return Verdicts(ok_ocert, ok_kes, ok_vrf, ok_leader, ambiguous, eta, lv)


def run_batch(batch: PraosBatch) -> Verdicts:
    """Stage -> device -> host verdict arrays (numpy).

    Batches are padded to power-of-two buckets so jax's per-shape trace
    cache compiles once per (bucket, kes_depth) — the crypto graph is
    large and arbitrary-length recompiles would dominate wall-clock.
    """
    b = batch.beta.shape[0]
    padded = pad_batch_to(batch, bucket_size(b))
    if _impl() == "pk":
        return _pk_materialize(_pk_dispatch(padded), b)
    out = _jitted_verify()(*(jnp.asarray(x) for x in flatten_batch(padded)))
    return Verdicts(*(np.asarray(x)[:b] for x in out))


# ---------------------------------------------------------------------------
# Batched chain-position semantics (first failure + state fold)
# ---------------------------------------------------------------------------


@dataclass
class BatchResult:
    """Outcome of validating a within-epoch run of headers."""

    state: PraosState  # state after the last VALID prefix header
    n_valid: int  # length of the valid prefix
    error: praos.PraosValidationError | None  # error at position n_valid
    states: list | None = None  # per-position states (collect_states=True)


def _counter_m(hk, counters, pool_distr):
    """The stateful OCert counter baseline: last seen counter, else 0
    for a pool with stake, else None (NoCounterForKeyHash)."""
    m = counters.get(hk)
    if m is None and hk in pool_distr:
        m = 0
    return m


def _counter_ok(m, n) -> bool:
    """Praos.hs:585-590: m <= n <= m + 1."""
    return m is not None and m <= n <= m + 1


def _lane_error(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    hv: HeaderView,
    pre: HostChecks,
    v: Verdicts,
    i: int,
    counters: Mapping[bytes, int],
) -> praos.PraosValidationError | None:
    """Map verdict bitmaps back to the EXACT error the sequential
    reference fold would raise, in its order: the whole of
    validateKESSignature (window, OCert sig, KES sig, counters —
    Praos.hs:558-606) before any of validateVRFSignature (pool lookup,
    proof, leader threshold — Praos.hs:528-556)."""
    if pre.kes_window_errors[i] is not None:
        return pre.kes_window_errors[i]
    if not v.ok_ocert_sig[i]:
        return praos.InvalidSignatureOCERT(hv.ocert.counter, hv.ocert.kes_period)
    if not v.ok_kes_sig[i]:
        kp = params.kes_period_of(hv.slot)
        c0 = hv.ocert.kes_period
        return praos.InvalidKesSignatureOCERT(kp, c0, kp - c0)
    # ocert counter monotonicity (Praos.hs:585-590), stateful
    hk = hash_key(hv.vk_cold)
    m = _counter_m(hk, counters, ledger_view.pool_distr)
    if m is None:
        return praos.NoCounterForKeyHashOCERT(hk)
    n = hv.ocert.counter
    if not m <= n:
        return praos.CounterTooSmallOCERT(m, n)
    if not n <= m + 1:
        return praos.CounterOverIncrementedOCERT(m, n)
    if pre.vrf_lookup_errors[i] is not None:
        return pre.vrf_lookup_errors[i]
    if not v.ok_vrf[i]:
        return praos.VRFKeyBadProof(hv.slot, epoch_nonce)
    if not v.leader_ambiguous[i] and v.ok_leader[i]:
        return None  # the common path: no big-int reconstruction
    entry = ledger_view.pool_distr.get(hk)
    sigma = entry.stake if entry is not None else Fraction(0)
    lv_val = int.from_bytes(bytes(v.leader_value[i].astype(np.uint8)), "big")
    if v.leader_ambiguous[i] and leader.check_leader_value(
        lv_val, sigma, params.active_slot_coeff
    ):
        return None
    return praos.VRFLeaderValueTooBig(lv_val, sigma, params.active_slot_coeff)


def validate_batch(
    params: PraosParams,
    ticked: TickedPraosState,
    hvs: Sequence[HeaderView],
    collect_states: bool = False,
    backend: str = "device",
    mesh=None,  # backend="sharded": the jax.sharding.Mesh (None = all devices)
) -> BatchResult:
    """Validate a within-epoch run of headers as one batch.

    Equivalent to folding `praos.update` over `hvs` from `ticked` — same
    resulting state, same first error — but with all crypto executed as a
    single fused device program (backend="device") or through the C++
    verifier (backend="native"). The epoch nonce must be constant across
    the run (the caller segments at epoch boundaries; `tick` between
    segments).
    """
    if not hvs:
        return BatchResult(ticked.state, 0, None, [] if collect_states else None)
    lview = ticked.ledger_view
    eta0 = ticked.state.epoch_nonce

    pre = host_prechecks(params, lview, hvs)
    if backend == "native":
        v = run_batch_native(params, lview, eta0, hvs, pre)
    elif backend == "sharded":
        # multi-chip SPMD: batch axis over the device mesh, psum/pmin
        # verdict collectives (parallel/spmd.py; SURVEY.md §5.8)
        from ..parallel import spmd

        batch = stage(params, lview, eta0, hvs, pre.kes_evolution)
        v, _first_bad, _n_ok = spmd.sharded_run_batch(batch, mesh)
    else:
        batch = stage(params, lview, eta0, hvs, pre.kes_evolution)
        v = run_batch(batch)
    return _epilogue(params, ticked, hvs, pre, v, collect_states)


# Enclose latency brackets (Util/Enclose.hs) around the hot-path
# phases: stage (host CBOR->SoA), dispatch (device kernel launch),
# materialize (device wait), epilogue (sequential fold). Settable so
# the embedding application (bench, node, tests) observes per-phase
# latency without touching the code path.
BATCH_TRACER = None  # None = off (zero overhead on the hot path)


def set_batch_tracer(tracer) -> None:
    global BATCH_TRACER
    BATCH_TRACER = tracer


def _enclose(label):
    from ..utils.trace import Enclose

    class _Null:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    return Enclose(BATCH_TRACER, label) if BATCH_TRACER is not None else _Null()


def dispatch_batch(params, lview, eta0, hvs):
    """Stage a within-epoch window and dispatch the fused kernel WITHOUT
    waiting: jax execution is asynchronous, so the caller can stage the
    next window while this one runs on device (the §7.3.6 host/device
    overlap; the reference's analog is the decoupled add-block queue,
    ChainSel.hs:217-246). Staging depends only on the epoch nonce and
    ledger view — never on the sequential fold — which is what makes
    in-flight windows safe."""
    with _enclose("stage"):
        pre = host_prechecks(params, lview, hvs)
        batch = stage(params, lview, eta0, hvs, pre.kes_evolution)
        b = batch.beta.shape[0]
        padded = pad_batch_to(batch, bucket_size(b))
    with _enclose("dispatch"):
        if _impl() == "pk":
            return pre, ("pk", _pk_dispatch(padded)), b
        out = _jitted_verify()(
            *(jnp.asarray(x) for x in flatten_batch(padded))
        )
        return pre, ("xla", out), b


def materialize_verdicts(tagged, b) -> Verdicts:
    """Block on a dispatched window's device computation."""
    impl, out = tagged
    if impl == "pk":
        return _pk_materialize(out, b)
    return Verdicts(*(np.asarray(x)[:b] for x in out))


def _epilogue(
    params: PraosParams,
    ticked: TickedPraosState,
    hvs: Sequence[HeaderView],
    pre: HostChecks,
    v: Verdicts,
    collect_states: bool = False,
    lane_error=None,
) -> BatchResult:
    """Sequential epilogue: counters + nonce fold, stop at first failure.

    `lane_error` defaults to the Praos `_lane_error`; TPraos passes an
    overlay-aware variant (protocol/tpraos.py)."""
    if lane_error is None:
        lane_error = _lane_error
    lview = ticked.ledger_view
    eta0 = ticked.state.epoch_nonce
    st = ticked.state
    counters = dict(st.ocert_counters)
    evolving = st.evolving_nonce
    candidate = st.candidate_nonce
    lab = st.lab_nonce
    last_slot = st.last_slot
    states_out: list | None = [] if collect_states else None
    # one array conversion for the whole batch (a per-row astype cost
    # ~2us/header in the fold)
    etas = np.ascontiguousarray(np.asarray(v.eta).astype(np.uint8))
    # vectorized all-clear gate for the DEFAULT lane semantics: lanes
    # where every verdict bit is set and no precomputed error exists
    # only need the stateful counter-monotonicity check — `lane_error`
    # is the slow path that reconstructs the exact reference error.
    # (TPraos passes its own lane_error with different counter
    # semantics: it always takes the full path.)
    if lane_error is _lane_error:
        fast_ok = (
            np.asarray(v.ok_ocert_sig) & np.asarray(v.ok_kes_sig)
            & np.asarray(v.ok_vrf) & np.asarray(v.ok_leader)
            & ~np.asarray(v.leader_ambiguous)
        ).tolist()
    else:
        fast_ok = None
    for i, hv in enumerate(hvs):
        if (
            fast_ok is not None
            and fast_ok[i]
            and pre.kes_window_errors[i] is None
            and pre.vrf_lookup_errors[i] is None
        ):
            hk = hash_key(hv.vk_cold)
            m = _counter_m(hk, counters, lview.pool_distr)
            if _counter_ok(m, hv.ocert.counter):
                err = None
            else:
                err = lane_error(params, lview, eta0, hv, pre, v, i, counters)
        else:
            err = lane_error(params, lview, eta0, hv, pre, v, i, counters)
        if err is not None:
            state = PraosState(
                last_slot=last_slot,
                ocert_counters=counters,
                evolving_nonce=evolving,
                candidate_nonce=candidate,
                epoch_nonce=st.epoch_nonce,
                lab_nonce=lab,
                last_epoch_block_nonce=st.last_epoch_block_nonce,
            )
            return BatchResult(state, i, err, states_out)
        # reupdate bookkeeping (Praos.hs:468-502) with the device-computed
        # eta (Blake2b² range extension)
        eta = etas[i].tobytes()
        evolving = nonces.combine(evolving, eta)
        slot = hv.slot
        first_next = params.first_slot_of(params.epoch_of(slot) + 1)
        if slot + params.stability_window < first_next:
            candidate = evolving
        lab = nonces.prev_hash_to_nonce(hv.prev_hash)
        counters[hash_key(hv.vk_cold)] = hv.ocert.counter
        last_slot = slot
        if states_out is not None:
            states_out.append(
                PraosState(
                    last_slot=last_slot,
                    ocert_counters=dict(counters),
                    evolving_nonce=evolving,
                    candidate_nonce=candidate,
                    epoch_nonce=st.epoch_nonce,
                    lab_nonce=lab,
                    last_epoch_block_nonce=st.last_epoch_block_nonce,
                )
            )

    state = PraosState(
        last_slot=last_slot,
        ocert_counters=counters,
        evolving_nonce=evolving,
        candidate_nonce=candidate,
        epoch_nonce=st.epoch_nonce,
        lab_nonce=lab,
        last_epoch_block_nonce=st.last_epoch_block_nonce,
    )
    return BatchResult(state, len(hvs), None, states_out)


def validate_chain(
    params: PraosParams,
    ledger_view_for_epoch,
    state: PraosState,
    hvs: Sequence[HeaderView],
    max_batch: int = 8192,
    backend: str = "device",
    pipeline_depth: int = 3,  # 2 windows hide staging behind the device;
    # the third absorbs the shorter epoch-tail batches (6144-lane
    # buckets) without a bubble. ~14 MB staged + ~26 MB on-device per
    # window — far under HBM at depth 3.
    mesh=None,  # backend="sharded": the jax.sharding.Mesh (None = all devices)
) -> BatchResult:
    """Validate an arbitrary run of headers, segmenting at epoch
    boundaries (and at `max_batch` within an epoch) per SURVEY.md §5.7.

    `ledger_view_for_epoch(epoch) -> LedgerView` supplies the forecastable
    per-epoch pool distribution (constant within an epoch).

    Device backend: up to `pipeline_depth` windows of the same epoch are
    in flight at once — window w+1 is staged (host CBOR→SoA + H2D) while
    window w executes, because staging depends only on the epoch nonce.
    The pipeline drains at epoch boundaries (the next epoch's nonce needs
    the previous epoch's fold) and on the first invalid header (in-flight
    successors are discarded, exactly like queued blocks after a failed
    chain selection in the reference's add-block queue).
    """
    # one worker thread owns the BLOCKING device reads: the main thread
    # keeps staging/dispatching while the worker waits, so host staging
    # hides behind device execution even when the backend only makes
    # progress under a blocking read (observed through the remote-TPU
    # tunnel: wall == stage + device with same-thread materialize,
    # scripts/profile_replay.py r5)
    pool = None
    if backend == "device":
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=1)
    try:
        return _validate_chain_loop(
            params, ledger_view_for_epoch, state, hvs, max_batch, backend,
            pipeline_depth, mesh, pool,
        )
    finally:
        if pool is not None:
            # cancel_futures: on an early error return the queued
            # materialize futures belong to DISCARDED windows — without
            # it the worker keeps issuing blocking device reads for
            # results nobody wants and the atexit join stalls exit
            pool.shutdown(wait=False, cancel_futures=True)


def _validate_chain_loop(
    params, ledger_view_for_epoch, state, hvs, max_batch, backend,
    pipeline_depth, mesh, pool,
):
    total_valid = 0
    i = 0
    n = len(hvs)
    if backend != "device":
        while i < n:
            epoch = params.epoch_of(hvs[i].slot)
            seg_end = i
            while seg_end < n and params.epoch_of(hvs[seg_end].slot) == epoch:
                seg_end += 1
            lview = ledger_view_for_epoch(epoch)
            while i < seg_end:
                j = min(i + max_batch, seg_end)
                ticked = praos.tick(params, lview, hvs[i].slot, state)
                res = validate_batch(
                    params, ticked, hvs[i:j], backend=backend, mesh=mesh
                )
                state = res.state
                total_valid += res.n_valid
                if res.error is not None:
                    return BatchResult(state, total_valid, res.error)
                i = j
        return BatchResult(state, total_valid, None)

    # Device backend: ONE pipeline across epoch boundaries. Staging a
    # window needs only (epoch nonce, ledger view); the next epoch's
    # nonce is tick's rotation combine(candidate, last_epoch_block_nonce)
    # (Praos.hs:407-432), whose inputs are final well before the current
    # epoch drains: candidate_nonce freezes at the stability window
    # (last update from a header with slot < first_slot(e+1) - 3k/f,
    # Praos.hs:497) and last_epoch_block_nonce was latched at the
    # PREVIOUS boundary. So once the fold retires past the freeze slot,
    # the next epoch's first windows dispatch while this epoch's tail is
    # still on device — no drain bubble per boundary (~one batch wall
    # each, ~46 boundaries on the 1M bench chain). The retire-time tick
    # asserts the staged nonce byte-for-byte.
    from collections import deque

    segments: list[tuple[int, int, int]] = []
    while i < n:
        epoch = params.epoch_of(hvs[i].slot)
        j = i
        while j < n and params.epoch_of(hvs[j].slot) == epoch:
            j += 1
        segments.append((epoch, i, j))
        i = j

    lviews: dict[int, object] = {}

    def lview_for(s: int):
        if s not in lviews:
            lviews[s] = ledger_view_for_epoch(segments[s][0])
        return lviews[s]

    eta_known: dict[int, object] = {}
    if segments:
        eta_known[0] = praos.tick(
            params, lview_for(0), hvs[segments[0][1]].slot, state
        ).state.epoch_nonce

    inflight: deque = deque()  # (seg_idx, window_hvs, pre, future)
    s_stage = 0  # segment currently being staged
    w = segments[0][1] if segments else 0
    retired = 0  # index of the next header to retire

    while retired < n or inflight:
        while (
            s_stage < len(segments)
            and len(inflight) < pipeline_depth
            and s_stage in eta_known
        ):
            _, _, seg_end = segments[s_stage]
            j = min(w + max_batch, seg_end)
            pre, out, b = dispatch_batch(
                params, lview_for(s_stage), eta_known[s_stage], hvs[w:j]
            )
            inflight.append(
                (s_stage, hvs[w:j], pre,
                 pool.submit(materialize_verdicts, out, b))
            )
            w = j
            if w >= seg_end:
                s_stage += 1
                if s_stage < len(segments):
                    w = segments[s_stage][1]

        if not inflight:
            # eta for s_stage not derivable before its predecessor fully
            # retires (no header past the freeze slot) — the retire path
            # below will publish it; nothing in flight means we can
            # compute it right now from the fully-folded state
            eta_known[s_stage] = praos.tick(
                params, lview_for(s_stage),
                hvs[segments[s_stage][1]].slot, state,
            ).state.epoch_nonce
            continue

        s_b, whvs, pre, fut = inflight.popleft()
        with _enclose("materialize"):
            v = fut.result()
        ticked = praos.tick(params, lview_for(s_b), whvs[0].slot, state)
        if whvs[0] is hvs[segments[s_b][1]]:
            # first batch of a segment staged with a LOOKAHEAD nonce:
            # the real rotation must agree (internal invariant)
            assert ticked.state.epoch_nonce == eta_known[s_b], (
                "lookahead epoch nonce mismatch"
            )
        with _enclose("epilogue"):
            res = _epilogue(params, ticked, whvs, pre, v)
        state = res.state
        total_valid += res.n_valid
        if res.error is not None:
            return BatchResult(state, total_valid, res.error)
        retired += len(whvs)

        nxt = s_b + 1
        if nxt < len(segments) and nxt not in eta_known:
            epoch, _, seg_end = segments[s_b]
            if retired >= seg_end:
                eta_known[nxt] = praos.tick(
                    params, lview_for(nxt), hvs[segments[nxt][1]].slot,
                    state,
                ).state.epoch_nonce
            else:
                freeze = (
                    params.first_slot_of(epoch + 1)
                    - params.stability_window
                )
                if hvs[retired].slot >= freeze:
                    # candidate is frozen and the LAB component was
                    # latched a boundary ago: the rotation is decided
                    eta_known[nxt] = nonces.combine(
                        state.candidate_nonce,
                        state.last_epoch_block_nonce,
                    )
    return BatchResult(state, total_valid, None)
