"""Batched Praos header validation — the TPU hot path.

This is the architectural inversion at the heart of the framework: where
the reference validates header-by-header inside a sequential fold
(`ledgerDbPushMany` = repeatedlyM, LedgerDB/Update.hs:302; crypto at
Praos.hs:441-606), we stage a columnar batch of header views (SoA) and run
ALL the expensive work as one fused device program:

  * Ed25519 verify of the OCert cold-key signature   (Praos.hs:580)
  * CompactSum KES verify of the header body          (Praos.hs:582)
  * ECVRF verify of the leader-election proof         (Praos.hs:543)
  * beta == declared certified output                 (verifyCertified)
  * leader-value range extension Blake2b("L" ‖ beta)  (Praos/VRF.hs:103)
  * leader threshold compare                          (Praos.hs:551)
  * nonce range extension Blake2b²("N" ‖ beta)        (Praos/VRF.hs:116)

Only the cheap state-threading (ocert counter monotonicity, nonce fold —
a NON-associative hash fold, so inherently sequential but ~1µs/header on
host) remains outside the kernel. Verdicts come back as per-check bitmaps;
the host locates the first failing chain position and reports the exact
`PraosValidationError` the sequential reference implementation would have
raised (re-deriving it with the host verifier for the error payload).

The device boundary itself is packed (round 6, "cut the wire"): windows
stage as body-sourced u8 columns (`stage_packed` — the KES-signed header
body is the single wire copy of every field it embeds; SHA padding, the
VRF alpha and the limb relayout run on device), and results come back as
u32 verdict bitmask words plus ONE device-scanned evolving/candidate
nonce pair per window (`verdict_reduce`, ops/blake2b.nonce_fold_scan),
with the per-lane columns left device-resident for the exact-error slow
path. Non-qualifying windows (mixed CBOR layouts, synthetic test views)
fall back to the original staged path — verified byte-for-byte at
staging time, so both wires are semantically identical.

Leader threshold on device: the rule p < 1 − (1−f)^σ compares a 256-bit
hash against an irrational bound. Per (σ, f) — one per pool per epoch —
the host brackets T = 2²⁵⁶·(1 − (1−f)^σ) by rationals [T_lo, T_hu] tight
to ~2⁻⁴⁰ relative width (protocol/leader.py series bounds). The device
does the big-endian compare against both brackets; the measure-zero band
in between falls back to the exact host check (`leader_ambiguous` mask).

Epoch segmentation (SURVEY.md §5.7): the epoch nonce and pool distribution
are constant within an epoch, so a batch spans at most one epoch; the
chain driver (storage/ledgerdb, tools/db_analyser) cuts batches at epoch
boundaries and threads the tiny PraosState between them.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from fractions import Fraction
from functools import lru_cache
from typing import Mapping, NamedTuple, Sequence

import numpy as np
from jax import numpy as jnp

from ..ops import blake2b, ecvrf_batch, ed25519_batch, kes_batch
from ..ops.host import kes as host_kes
from . import leader, nonces, praos
from .praos import PraosParams, PraosState, TickedPraosState
from .views import (
    HeaderView, LedgerView, ViewColumns, hash_key, hash_vrf_vk,
)

# ---------------------------------------------------------------------------
# Leader-threshold bracketing (host, cached per (sigma, f))
# ---------------------------------------------------------------------------


@lru_cache(maxsize=4096)
def leader_threshold_bracket(sigma: Fraction, f: Fraction) -> tuple[int, int]:
    """[T_lo, T_hi] integers bracketing 2^256 * (1 - (1-f)^sigma).

    leader_value < T_lo  => certainly a leader;
    leader_value >= T_hi => certainly not;
    otherwise undecided (exact host check).  With 64 series terms the
    bracket width is far below 1 for every realistic (sigma, f), so the
    ambiguous band is empty in practice.
    """
    if f == 1:
        return (leader.LEADER_VALUE_MAX, leader.LEADER_VALUE_MAX)
    if sigma == 0:
        return (0, 0)
    llo, lhi = leader._neg_log1m_interval(f, 64)
    elo, ehi = leader._exp_interval(sigma * llo, sigma * lhi, 64)
    # lhs = 2^256/(2^256 - lv) < exp(x)  <=>  lv < 2^256 (1 - 1/exp(x))
    t_lo = leader.LEADER_VALUE_MAX * (1 - Fraction(1) / elo)
    t_hi = leader.LEADER_VALUE_MAX * (1 - Fraction(1) / ehi)
    lo = int(t_lo)  # floor: lv < floor(T_lo) <= T_lo  => leader
    hi = -int(-t_hi)  # ceil: lv >= ceil(T_hi) >= T_hi => not leader
    return (lo, hi)


# ---------------------------------------------------------------------------
# SoA staging
# ---------------------------------------------------------------------------


class PraosBatch(NamedTuple):
    """Device-ready columnar batch of Praos header-validation inputs."""

    ed: ed25519_batch.Ed25519Batch  # OCert cold-key signature check
    kes: kes_batch.KesBatch  # header-body KES signature check
    # leader VRF proof check; the staged type follows the proof format
    # (EcvrfBatch = draft-03, EcvrfBcBatch = batch-compatible)
    vrf: "ecvrf_batch.EcvrfBatch | ecvrf_batch.EcvrfBcBatch"
    beta: np.ndarray  # [B, 64] uint8 — declared certified VRF output
    thr_lo: np.ndarray  # [B, 32] uint8 big-endian leader bound (certain win)
    thr_hi: np.ndarray  # [B, 32] uint8 big-endian leader bound (certain loss)


@dataclass(frozen=True)
class HostChecks:
    """Results of the cheap non-crypto checks.

    Split into KES-side and VRF-side error arrays because the reference
    interleaves them with the crypto verdicts in a strict order
    (validateKESSignature COMPLETELY before validateVRFSignature,
    Praos.hs:441-466) that `_lane_error` must reproduce.
    """

    # per-lane: None = pass, else the error the reference would raise
    kes_window_errors: list  # KESBeforeStart / KESAfterEnd (Praos.hs:560-574)
    vrf_lookup_errors: list  # VRFKeyUnknown / WrongVRFKey (Praos.hs:530-540)
    kes_evolution: np.ndarray  # [B] int32 — t = kes_period - c0 (clamped 0)

    def any_errors(self) -> bool:
        return any(e is not None for e in self.kes_window_errors) or any(
            e is not None for e in self.vrf_lookup_errors
        )


@dataclass(frozen=True)
class ColumnChecks(HostChecks):
    """HostChecks from the columnar precheck pass, carrying the
    per-window pool dedup so later stages (threshold tables, counter
    monotonicity, the native leader compare) never repeat the
    hash_key + pool_distr lookups per lane."""

    uniq_inv: np.ndarray  # [B] int32 — lane -> unique (cold, vrf) pair
    uniq_hk: tuple  # per-unique KeyHash bytes
    uniq_entry: tuple  # per-unique IndividualPoolStake | None
    clean: bool = False  # True = no precheck error in any lane

    def any_errors(self) -> bool:
        return not self.clean


def host_prechecks(
    params: PraosParams,
    ledger_view: LedgerView,
    hvs: "Sequence[HeaderView] | ViewColumns",
) -> HostChecks:
    """The non-crypto parts of validateKESSignature/validateVRFSignature
    (Praos.hs:558-574 window checks, :528-540 pool lookups), batch-wide.

    OCert counter monotonicity (Praos.hs:585-590) is NOT here: it depends
    on the evolving counter map and is checked in the sequential epilogue.

    A ViewColumns window takes the vectorized path: whole-column KES
    window arithmetic, pool lookups deduplicated per unique
    (cold-key, vrf-key) pair — hash_key and the dict probe run once per
    pool per window, not once per header.
    """
    if isinstance(hvs, ViewColumns):
        return host_prechecks_columns(params, ledger_view, hvs)
    kes_errors: list = [None] * len(hvs)
    vrf_errors: list = [None] * len(hvs)
    evol = np.zeros((len(hvs),), np.int32)
    for i, hv in enumerate(hvs):
        c0 = hv.ocert.kes_period
        kp = params.kes_period_of(hv.slot)
        if not c0 <= kp:
            kes_errors[i] = praos.KESBeforeStartOCERT(c0, kp)
        elif not kp < c0 + params.max_kes_evolutions:
            kes_errors[i] = praos.KESAfterEndOCERT(kp, c0, params.max_kes_evolutions)
        else:
            evol[i] = kp - c0
        hk = hash_key(hv.vk_cold)
        entry = ledger_view.pool_distr.get(hk)
        if entry is None:
            vrf_errors[i] = praos.VRFKeyUnknown(hk)
        else:
            header_vrf_hash = hash_vrf_vk(hv.vrf_vk)
            if entry.vrf_key_hash != header_vrf_hash:
                vrf_errors[i] = praos.VRFKeyWrongVRFKey(
                    hk, entry.vrf_key_hash, header_vrf_hash
                )
    return HostChecks(kes_errors, vrf_errors, evol)


def _dedup_rows(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(unique_rows [k, w], inverse [n]) over a [n, w] uint8 matrix —
    np.unique(axis=0) semantics (sorted-by-something stable grouping +
    gather indices) WITHOUT its void-dtype argsort, which comparison-
    sorts w-byte keys (~27 µs/row at w=288: slower than the rest of the
    columnar stage combined). Rows are grouped by a vectorized 64-bit
    Horner fingerprint over their u64 words and the grouping is then
    VERIFIED by one exact gather-compare; a fingerprint collision (only
    adversarially reachable) falls back to the exact np.unique."""
    n, w = rows.shape
    if n == 0:
        return rows.copy(), np.zeros(0, np.int64)
    pad = (-w) % 8
    if pad:
        padded = np.zeros((n, w + pad), np.uint8)
        padded[:, :w] = rows
    else:
        padded = np.ascontiguousarray(rows)
    words = padded.view(np.uint64)
    h = np.zeros(n, np.uint64)
    mult = np.uint64(0x9E3779B97F4A7C15)
    with np.errstate(over="ignore"):
        for c in range(words.shape[1]):
            h = h * mult + words[:, c]
    uh, inv = np.unique(h, return_inverse=True)
    first = np.full(uh.shape[0], -1, np.int64)
    # first occurrence per group (reverse scatter keeps the lowest index)
    first[inv[::-1]] = np.arange(n - 1, -1, -1)
    uniq = rows[first]
    if not np.array_equal(uniq[inv], rows):
        return np.unique(rows, axis=0, return_inverse=True)
    return uniq, inv


def host_prechecks_columns(
    params: PraosParams,
    ledger_view: LedgerView,
    vc: ViewColumns,
) -> ColumnChecks:
    """Columnar host_prechecks: same verdicts and error objects, zero
    per-header Python on the clean path."""
    n = len(vc)
    c0 = vc.ocert_kes_period
    kp = vc.slot // params.slots_per_kes_period
    before = c0 > kp
    after = ~before & (kp >= c0 + params.max_kes_evolutions)
    bad_window = before | after
    evol = np.where(bad_window, 0, kp - c0).astype(np.int32)
    kes_errors: list = [None] * n
    if bad_window.any():
        for i in np.flatnonzero(before).tolist():
            kes_errors[i] = praos.KESBeforeStartOCERT(int(c0[i]), int(kp[i]))
        for i in np.flatnonzero(after).tolist():
            kes_errors[i] = praos.KESAfterEndOCERT(
                int(kp[i]), int(c0[i]), params.max_kes_evolutions
            )

    # pool lookups once per unique (cold key, vrf key) pair: real chains
    # have a handful of issuers per window, so the Blake2b-224 hash_key,
    # the pool_distr probe and the vrf-key-hash equality run O(pools)
    # times instead of O(headers)
    pair = np.concatenate([vc.vk_cold, vc.vrf_vk], axis=1)
    uniq, inv = _dedup_rows(pair)
    hks, entries, uerrs = [], [], []
    for j in range(uniq.shape[0]):
        vk_cold = uniq[j, :32].tobytes()
        hk = hash_key(vk_cold)
        entry = ledger_view.pool_distr.get(hk)
        hks.append(hk)
        entries.append(entry)
        if entry is None:
            uerrs.append(praos.VRFKeyUnknown(hk))
        else:
            header_vrf_hash = hash_vrf_vk(uniq[j, 32:].tobytes())
            if entry.vrf_key_hash != header_vrf_hash:
                uerrs.append(praos.VRFKeyWrongVRFKey(
                    hk, entry.vrf_key_hash, header_vrf_hash
                ))
            else:
                uerrs.append(None)
    if any(e is not None for e in uerrs):
        vrf_errors = [uerrs[j] for j in inv.tolist()]
    else:
        vrf_errors = [None] * n
    clean = not bad_window.any() and all(e is None for e in uerrs)
    return ColumnChecks(
        kes_errors, vrf_errors, evol,
        inv.astype(np.int32), tuple(hks), tuple(entries), clean,
    )


@lru_cache(maxsize=4096)
def _threshold_rows(sigma: Fraction, f: Fraction):
    """Encoded (lo, hi) threshold byte rows per (sigma, f) — the
    bracket itself is lru_cached too, but the per-header Fraction wrap
    + 32-byte to_bytes/frombuffer encoding dominated staging before
    this was hoisted. Clamped to the 256-bit compare domain: a
    threshold of 2^256 means "every value wins", encoded as all-0xFF +
    the hi-inclusive trick."""
    lo, hi = leader_threshold_bracket(sigma, f)
    return (
        np.frombuffer(min(lo, (1 << 256) - 1).to_bytes(32, "big"), np.uint8),
        np.frombuffer(min(hi, (1 << 256) - 1).to_bytes(32, "big"), np.uint8),
    )


def stage(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    hvs: Sequence[HeaderView],
    evolution: np.ndarray,
) -> PraosBatch:
    """Columnarize header views for the fused device kernel."""
    b = len(hvs)
    ed = ed25519_batch.stage_np(
        [hv.vk_cold for hv in hvs],
        [hv.ocert.sigma for hv in hvs],
        [hv.ocert.signable() for hv in hvs],
    )
    kes = kes_batch.stage_np(
        [hv.ocert.vk_hot for hv in hvs],
        [int(t) for t in evolution],
        [hv.signed_bytes for hv in hvs],
        [hv.kes_sig for hv in hvs],
        depth=params.kes_depth,
    )
    vrf = ecvrf_batch.stage_np(
        [hv.vrf_vk for hv in hvs],
        [hv.vrf_proof for hv in hvs],
        [nonces.mk_input_vrf(hv.slot, epoch_nonce) for hv in hvs],
    )
    assert all(len(hv.vrf_output) == 64 for hv in hvs)
    beta = np.frombuffer(
        b"".join(hv.vrf_output for hv in hvs), np.uint8
    ).reshape(b, 64).copy()
    thr_lo = np.zeros((b, 32), np.uint8)
    thr_hi = np.zeros((b, 32), np.uint8)
    f = Fraction(params.active_slot_coeff)
    for i, hv in enumerate(hvs):
        entry = ledger_view.pool_distr.get(hash_key(hv.vk_cold))
        sigma = entry.stake if entry is not None else Fraction(0)
        lo_row, hi_row = _threshold_rows(sigma, f)
        thr_lo[i] = lo_row
        thr_hi[i] = hi_row
    return PraosBatch(ed, kes, vrf, beta, thr_lo, thr_hi)


def _be8_np(a: np.ndarray) -> np.ndarray:
    """[n] nonnegative int64 -> [n, 8] uint8 big-endian rows (the
    vectorized int.to_bytes(8, "big"))."""
    return np.ascontiguousarray(a).astype(">u8").view(np.uint8).reshape(-1, 8)


def _uniq_threshold_rows(
    params: PraosParams, pre: ColumnChecks
) -> tuple[list[np.ndarray], list[np.ndarray]]:
    """Per-UNIQUE-pool (lo, hi) threshold byte rows from the precheck
    dedup — the one place the unknown-pool sigma-0 convention and the
    clamped bracket encoding live for the columnar paths."""
    f = Fraction(params.active_slot_coeff)
    lo_rows, hi_rows = [], []
    for entry in pre.uniq_entry:
        sigma = entry.stake if entry is not None else Fraction(0)
        lo, hi = _threshold_rows(sigma, f)
        lo_rows.append(lo)
        hi_rows.append(hi)
    return lo_rows, hi_rows


def _uniq_threshold_tables(
    params: PraosParams, pre: ColumnChecks
) -> tuple[np.ndarray, np.ndarray]:
    """(thr_lo [B, 32], thr_hi [B, 32]): the per-unique rows gathered
    per lane."""
    lo_rows, hi_rows = _uniq_threshold_rows(params, pre)
    inv = pre.uniq_inv
    return np.stack(lo_rows)[inv], np.stack(hi_rows)[inv]


def _alpha_column(vc: ViewColumns, epoch_nonce: nonces.Nonce) -> np.ndarray:
    """[B, 32] VRF input column (mkInputVRF per slot). The Blake2b per
    header is inherent (host staging of the generic/native paths); the
    packed device path skips it entirely via alpha_from_slots."""
    b = len(vc)
    out = np.empty((b, 32), np.uint8)
    slots = vc.slot.tolist()
    for i in range(b):
        out[i] = np.frombuffer(
            nonces.mk_input_vrf(slots[i], epoch_nonce), np.uint8
        )
    return out


def stage_columns(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    vc: ViewColumns,
    evolution: np.ndarray,
    pre: ColumnChecks,
) -> PraosBatch:
    """Columnar `stage`: the generic SoA batch built straight from the
    window columns — whole-matrix slices and one vectorized SHA pad per
    hash family, no per-header bytes. Byte-identical to
    `stage(..., vc.views(), ...)` (the columnar differential suite)."""
    from ..ops import sha512

    sigma = vc.ocert_sigma
    ed_r = np.ascontiguousarray(sigma[:, :32])
    ed_s = np.ascontiguousarray(sigma[:, 32:])
    # Ed25519 challenge-hash input R ‖ A ‖ signable(vk_hot ‖ n ‖ c0)
    ed_msg = np.concatenate(
        [ed_r, vc.vk_cold, vc.ocert_vk_hot,
         _be8_np(vc.ocert_counter), _be8_np(vc.ocert_kes_period)], axis=1,
    )
    ed_hb, ed_hnb = sha512.pad_matrix_np(ed_msg)
    ed = ed25519_batch.Ed25519Batch(
        np.ascontiguousarray(vc.vk_cold), ed_r, ed_s, ed_hb, ed_hnb
    )

    ks = vc.kes_sig
    kes_r = np.ascontiguousarray(ks[:, :32])
    kes_s = np.ascontiguousarray(ks[:, 32:64])
    vk_leaf = np.ascontiguousarray(ks[:, 64:96])
    depth = params.kes_depth
    siblings = np.ascontiguousarray(ks[:, 96:].reshape(len(vc), depth, 32))
    kes_msg = np.concatenate([kes_r, vk_leaf, vc.signed_bytes], axis=1)
    kes_hb, kes_hnb = sha512.pad_matrix_np(kes_msg)
    kes = kes_batch.KesBatch(
        np.ascontiguousarray(vc.ocert_vk_hot),
        np.asarray(evolution, np.int32),
        kes_r, kes_s, vk_leaf, siblings, kes_hb, kes_hnb,
    )

    plen = int(vc.vrf_proof_len[0])
    proof = vc.vrf_proof
    gamma = np.ascontiguousarray(proof[:, :32])
    alpha = _alpha_column(vc, epoch_nonce)
    pk = np.ascontiguousarray(vc.vrf_vk)
    if plen == 128:
        vrf = ecvrf_batch.EcvrfBcBatch(
            pk, gamma,
            np.ascontiguousarray(proof[:, 32:64]),
            np.ascontiguousarray(proof[:, 64:96]),
            np.ascontiguousarray(proof[:, 96:128]),
            alpha,
        )
    else:
        vrf = ecvrf_batch.EcvrfBatch(
            pk, gamma,
            np.ascontiguousarray(proof[:, 32:48]),
            np.ascontiguousarray(proof[:, 48:80]),
            alpha,
        )

    thr_lo, thr_hi = _uniq_threshold_tables(params, pre)
    beta = np.ascontiguousarray(vc.vrf_output)
    return PraosBatch(ed, kes, vrf, beta, thr_lo, thr_hi)


# ---------------------------------------------------------------------------
# Fused device kernel
# ---------------------------------------------------------------------------


def _lt_be(a, b):
    """Big-endian lexicographic a < b for [..., 32] int32 byte arrays.

    all_eq_before via a CUMSUM of mismatch indicators (== 0 while every
    earlier byte matched), not cumprod: an unrolled 32-long cumprod is a
    multiply chain in the top-level computation, and two of these (leader
    lo/hi compares) were the op pattern that still sent XLA's algebraic
    simplifier into its circular-simplification loop on the composed spmd
    program (round-7; same family as the PR-1 ladder-chain remediation —
    cumsum is add-class, which the simplifier's reassociation rewrites
    leave alone)."""
    ne = (a != b).astype(jnp.int32)
    mismatches_before = jnp.cumsum(
        jnp.concatenate([jnp.zeros_like(ne[..., :1]), ne[..., :-1]], axis=-1),
        axis=-1,
    )
    all_eq_before = mismatches_before == 0
    return jnp.any(all_eq_before & (a < b), axis=-1)


class Verdicts(NamedTuple):
    """Per-lane verdict bitmaps + derived values (device arrays)."""

    ok_ocert_sig: jnp.ndarray  # [B] InvalidSignatureOCERT if False
    ok_kes_sig: jnp.ndarray  # [B] InvalidKesSignatureOCERT if False
    ok_vrf: jnp.ndarray  # [B] VRFKeyBadProof if False (proof or beta mismatch)
    ok_leader: jnp.ndarray  # [B] VRFLeaderValueTooBig if False
    leader_ambiguous: jnp.ndarray  # [B] host must decide exactly
    eta: jnp.ndarray  # [B, 32] vrfNonceValue(beta) for the nonce fold
    leader_value: jnp.ndarray  # [B, 32] big-endian Blake2b("L" ‖ beta)


def _leader_nonce_tail(beta_decl, thr_lo, thr_hi):
    """Shared tail of the fused verifiers: leader-value + eta range
    extensions (Praos/VRF.hs:103,116) on the DECLARED beta — ok_vrf
    guarantees it equals the proof's beta — and the two-threshold
    leader comparison. (ops/pk/aggregate.py carries the limb-first
    twin of this block.)"""
    tag_l = jnp.broadcast_to(
        jnp.asarray([ord("L")], jnp.int32), (*beta_decl.shape[:-1], 1)
    )
    lv = blake2b.blake2b_fixed(
        jnp.concatenate([tag_l, beta_decl], axis=-1), 65, 32
    )  # 32 bytes, big-endian natural (hash bytes ARE the BE encoding)
    tag_n = jnp.broadcast_to(
        jnp.asarray([ord("N")], jnp.int32), (*beta_decl.shape[:-1], 1)
    )
    eta1 = blake2b.blake2b_fixed(
        jnp.concatenate([tag_n, beta_decl], axis=-1), 65, 32
    )
    eta = blake2b.blake2b_fixed(eta1, 32, 32)

    thr_lo = jnp.asarray(thr_lo).astype(jnp.int32)
    thr_hi = jnp.asarray(thr_hi).astype(jnp.int32)
    certain_win = _lt_be(lv, thr_lo)
    certain_loss = ~_lt_be(lv, thr_hi)
    ambiguous = ~certain_win & ~certain_loss
    return certain_win, ambiguous, eta, lv


def verify_praos(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
) -> Verdicts:
    """The fused Praos hot-path kernel. One jit, one device program.

    XLA fuses the three verifier subgraphs and the Blake2b range
    extensions; everything is batch-uniform control flow (mask lanes).
    The seven per-lane point compressions (Ed25519 R-check, KES leaf
    R-check, ECVRF H/Γ/U/V/8Γ) share ONE Montgomery inversion chain.
    """
    from ..ops import curve

    ok_ed_pre, ed_point = ed25519_batch.verify_point(
        ed_pk, ed_s, ed_hblocks, ed_hnblocks
    )
    ok_kes_pre, kes_point = kes_batch.verify_point(
        kes_vk, kes_period, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks,
    )
    ok_vrf_pre, vrf_points = ecvrf_batch.verify_points(
        vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha
    )
    encs = curve.compress_many([ed_point, kes_point, *vrf_points])
    ok_ed = ok_ed_pre & jnp.all(
        encs[0] == jnp.asarray(ed_r).astype(jnp.int32), axis=-1
    )
    ok_kes = ok_kes_pre & jnp.all(
        encs[1] == jnp.asarray(kes_r).astype(jnp.int32), axis=-1
    )
    ok_proof, beta = ecvrf_batch.finish(ok_vrf_pre, vrf_c, encs[2:])
    beta_decl = jnp.asarray(beta_decl).astype(jnp.int32)
    ok_vrf = ok_proof & jnp.all(beta == beta_decl, axis=-1)

    certain_win, ambiguous, eta, lv = _leader_nonce_tail(
        beta_decl, thr_lo, thr_hi
    )
    return Verdicts(ok_ed, ok_kes, ok_vrf, certain_win, ambiguous, eta, lv)


def verify_praos_bc(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
) -> Verdicts:
    """The fused hot path over BATCH-COMPATIBLE (128-byte) VRF proofs:
    identical to verify_praos except the challenge is derived on device
    from the announced U, V (ops/ecvrf_batch.verify_points_bc); the
    ed/kes subgraphs and the finish hashing are byte-identical."""
    from ..ops import curve

    ok_ed_pre, ed_point = ed25519_batch.verify_point(
        ed_pk, ed_s, ed_hblocks, ed_hnblocks
    )
    ok_kes_pre, kes_point = kes_batch.verify_point(
        kes_vk, kes_period, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks,
    )
    ok_vrf_pre, c16, vrf_points = ecvrf_batch.verify_points_bc(
        vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha
    )
    encs = curve.compress_many([ed_point, kes_point, *vrf_points])
    ok_ed = ok_ed_pre & jnp.all(
        encs[0] == jnp.asarray(ed_r).astype(jnp.int32), axis=-1
    )
    ok_kes = ok_kes_pre & jnp.all(
        encs[1] == jnp.asarray(kes_r).astype(jnp.int32), axis=-1
    )
    ok_proof, beta = ecvrf_batch.finish(ok_vrf_pre, c16, encs[2:])
    beta_decl = jnp.asarray(beta_decl).astype(jnp.int32)
    ok_vrf = ok_proof & jnp.all(beta == beta_decl, axis=-1)

    certain_win, ambiguous, eta, lv = _leader_nonce_tail(
        beta_decl, thr_lo, thr_hi
    )
    return Verdicts(ok_ed, ok_kes, ok_vrf, certain_win, ambiguous, eta, lv)


def verify_praos_any(*cols) -> Verdicts:
    """Arity dispatch over the two staged formats: 21 columns = draft-03
    (verify_praos), 22 = batch-compatible (verify_praos_bc). Used by the
    spmd local step, whose column list follows the staged batch."""
    if len(cols) == 22:
        return verify_praos_bc(*cols)
    return verify_praos(*cols)


_JIT: dict = {}

# warmup forensics: (stage:lanes) labels whose first execute has been
# recorded — the wrapper below costs one set lookup per call after that
_WARM_SEEN: set = set()


def _arg_lanes(a) -> int | None:
    """Leading batch axis of the first array argument."""
    return next(
        (int(x.shape[0]) for x in a
         if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1),
        None,
    )


def _store_name(label: str) -> str:
    """AOT-store stage name of an XLA-twin warmup label (the label's
    lane qualifier is carried by the store key's `b`, not the name)."""
    import re

    return re.sub(r"[^A-Za-z0-9_]+", "_", label)


def _warm_timed(stage: str, fn):
    """Wrap a jitted program so its FIRST execute (where the compile —
    or cache/store load — happens synchronously) records its wall into
    the obs warmup flight recorder. The r02-r05 ~410 s compile walls
    died without attribution; this is the per-stage black box.

    The first-execute label is qualified by the padded LANE count
    (`<stage>:<lanes>l`): the warm ladder dispatches the same program
    family at rung and production lane counts, and the compile gate /
    warmup report must attribute each shape's first execute separately
    (a 1024-lane first execute does not make the 8192-lane program
    warm). The first execute also consults the build-pinned AOT store
    (ops/pk/aot): a stored executable loads instead of compiling, and
    with OCT_PK_AOT_WRITEBACK=1 a fresh compile is re-serialized into
    the store so the next process on this build loads warm. The
    load/write-back executable memo is CLOSURE-local (per wrapped fn,
    sig-checked — a Compiled is shape-exact and the generic staged
    program's KES hash-block count varies per batch): the explicit
    compile path does not populate the jit's own cache, but a memo
    keyed by label alone would keep serving a stale program after the
    jit behind the label is rebuilt."""
    warm_exec: dict = {}

    def wrapper(*a, **k):
        from ..ops.pk import aot as pk_aot

        lanes = _arg_lanes(a)
        label = f"{stage}:{lanes}l" if lanes is not None else stage
        if label in _WARM_SEEN:
            stored = warm_exec.get(label)
            if stored is not None and stored[0] == pk_aot.sig_of(a):
                return stored[1](*a)
            return fn(*a, **k)
        from ..obs.warmup import WARMUP

        # breadcrumb BEFORE the call: a kill mid-compile still leaves
        # "<label> first execute starting" as the report's last note
        WARMUP.note(f"{label} first execute starting")
        t0 = time.monotonic()
        ex = None
        via = "xla-jit"
        name = _store_name(stage)
        if pk_aot.enabled():
            try:
                sig = pk_aot.sig_of(a)
                ex = pk_aot.load(name, lanes or 0, 0, 0, sig)
                if ex is not None:
                    via = "xla-aot"
            except Exception:  # noqa: BLE001 # octflow: disable=FLOW303
                # — fail-soft by contract: a failed AOT load falls
                # through to the fresh-compile dispatch just below
                ex = None
        if ex is None and pk_aot.writeback_enabled():
            ex = pk_aot.compile_and_store(name, lanes or 0, 0, 0, fn, a)
        try:
            out = ex(*a, **k) if ex is not None else fn(*a, **k)
        except Exception as e:
            if ex is None:
                raise
            # a stored executable that dies on device falls back to the
            # jit path — never worse than the pre-store behavior
            pk_aot.note_failure(e)
            pk_aot._note_aot(name, "run_failed", detail=repr(e))
            ex, via = None, "xla-jit"
            out = fn(*a, **k)
        if ex is not None:
            import jax

            jax.block_until_ready(out)
            warm_exec[label] = (pk_aot.sig_of(a), ex)
        wall = time.monotonic() - t0
        _WARM_SEEN.add(label)
        from ..analysis import costmodel

        WARMUP.note_stage(label, wall, via=via,
                          feature_hash=costmodel.stage_feature_hash(label))
        # device resource accounting rides the same first-execute gate:
        # one re-lower (trace only, no XLA compile) while capture is
        # enabled — lanes read off the leading batch axis. AFTER the
        # warmup note by design: a kill mid-capture must not eat the
        # already-flushed compile-wall forensics.
        from ..obs import resources as obs_resources

        obs_resources.capture_stage(label, ex if ex is not None else fn,
                                    a, lanes=lanes, via=via)
        return out

    return wrapper


# device implementation: "pk" = Pallas kernels (ops/pk, limb-first,
# ladders in VMEM — the TPU production path), "xla" = the original jnp
# graph (the cross-check twin; also the CPU default, where the pk path
# only exists as interpret-mode and compiles far slower than it runs)
DEVICE_IMPL = os.environ.get("OCT_DEVICE_IMPL", "")

# the "cut the wire" path: packed body-sourced H2D staging + on-device
# verdict-bit packing and nonce scan. OCT_PACKED_STAGE=0 restores the
# round-5 staged-column path end to end; OCT_NONCE_SCAN=0 keeps packed
# staging but ships the per-lane eta column (packed uint8) back instead
# of running the sequential on-device nonce fold — the A/B lever if the
# scan's serial cost ever exceeds the eta transfer it saves.
PACKED_STAGE = os.environ.get("OCT_PACKED_STAGE", "1") != "0"
NONCE_SCAN = os.environ.get("OCT_NONCE_SCAN", "1") != "0"


def _stage_thread_enabled() -> bool:
    """OCT_STAGE_THREAD (default 1): run prechecks + packed staging on
    a producer thread ahead of dispatch in validate_chain's device
    loop, double-buffering H2D staging against device compute with
    backpressure at pipeline_depth. =0 restores the inline (round-9)
    staging — the differential kill-switch; read per call so tests can
    A/B both paths in one process."""
    return os.environ.get("OCT_STAGE_THREAD", "1") != "0"


def _compile_gate_admit(stage: str, action: str,
                        fallback_graph: str | None,
                        lanes: int | None = None) -> bool:
    """octwall pre-flight (analysis/costmodel.preflight): when bench.py
    has exported a wall deadline ($OCT_WALL_DEADLINE), a COLD monolith
    program whose PREDICTED cold-compile wall does not fit the
    remaining budget is refused here — the window rides the fallback
    path named by `action` instead, and the refusal lands in the warmup
    report. On the pk impl that fallback is the per-stage split
    (individually small programs, each banked by the persistent cache
    across retries); on the xla impl it is the per-lane packed monolith,
    so `fallback_graph` names its twin and the gate only refuses when
    that twin is predicted CHEAPER (trading one doomed compile for
    another helps nobody). No deadline / no model / OCT_COMPILE_GATE=0
    -> always admit; the gate must never break dispatch."""
    if os.environ.get("OCT_COMPILE_GATE", "1") == "0":
        return True
    try:
        from ..analysis import costmodel

        return costmodel.preflight(stage, action=action,
                                   fallback_graph=fallback_graph,
                                   lanes=lanes)
    except Exception:  # noqa: BLE001 # octflow: disable=FLOW303 —
        # fail-open by contract: the compile-wall gate must never
        # break dispatch; admitting is the no-gate behavior, and the
        # window's verdict still comes from the full validation
        return True


def _agg_enabled() -> bool:
    """OCT_VRF_AGG (default 1): verify packed batch-compatible windows
    by the random-linear-combination aggregate + MSM
    (ops/pk/aggregate.py) with per-lane fallback on any anomaly. =0
    always runs the per-lane stage kernels. Read per call so the
    differential tests can A/B both paths in one process."""
    ov = getattr(_RECOVERY_OVERRIDES, "vals", None)
    if ov is not None and ov.get("agg") is not None:
        return bool(ov["agg"])
    return os.environ.get("OCT_VRF_AGG", "1") != "0"


def _rlc_all_enabled() -> bool:
    """OCT_RLC_ALL (default 1): fold the Ed25519 and KES equations into
    the shared-bucket window MSM (`aggregate_window` — one signed-digit
    bucket pass over every stage). =0 keeps the window aggregated but
    restores the vrf-only RLC with exact per-lane ed/kes ladders
    (`aggregate_window_vrf`, the pre-fold shape on the unsigned engine)
    — the isolation kill-switch for the shared-bucket machinery. Only
    consulted when `_agg_enabled()` admits the aggregate path at all.
    Read per call like OCT_VRF_AGG so tests can A/B in one process."""
    ov = getattr(_RECOVERY_OVERRIDES, "vals", None)
    if ov is not None and ov.get("rlc_all") is not None:
        return bool(ov["rlc_all"])
    return os.environ.get("OCT_RLC_ALL", "1") != "0"


def _impl() -> str:
    ov = getattr(_RECOVERY_OVERRIDES, "vals", None)
    if ov is not None and ov.get("impl"):
        return ov["impl"]
    if DEVICE_IMPL:
        return DEVICE_IMPL
    import jax

    return "pk" if jax.devices()[0].platform == "tpu" else "xla"


# per-thread path overrides for the recovery ladder (obs/recovery.py):
# a rung re-validates ONE failing window with the aggregate fast path
# forced off (stage-split — the materialize_verdicts taxonomy path) or
# the implementation pinned to the XLA twin, without touching the env
# the rest of the process (and the staging thread) keeps reading.
_RECOVERY_OVERRIDES = threading.local()


class recovery_overrides:
    """Context manager: pin `_agg_enabled()` / `_impl()` for THIS
    thread while a recovery rung re-validates a window."""

    def __init__(self, agg=None, impl=None, rlc_all=None):
        self._vals = {"agg": agg, "impl": impl, "rlc_all": rlc_all}

    def __enter__(self):
        self._prev = getattr(_RECOVERY_OVERRIDES, "vals", None)
        _RECOVERY_OVERRIDES.vals = self._vals
        return self

    def __exit__(self, *exc):
        _RECOVERY_OVERRIDES.vals = self._prev
        return False


def flatten_batch(batch: PraosBatch) -> list:
    """PraosBatch -> flat array list in verify_praos argument order."""
    return [*batch.ed, *batch.kes, *batch.vrf, batch.beta, batch.thr_lo, batch.thr_hi]


def _words_to_byte_blocks(w: np.ndarray) -> np.ndarray:
    """SHA-512 word blocks [B, NB, 16, 2] uint32 -> [NB, 128, B] int32
    byte blocks (the ops/pk limb-first hash input layout)."""
    b_, nb = w.shape[0], w.shape[1]
    out = np.zeros((b_, nb, 16, 8), np.int32)
    for k in range(4):
        out[..., k] = ((w[..., 0] >> (24 - 8 * k)) & 0xFF).astype(np.int32)
        out[..., 4 + k] = ((w[..., 1] >> (24 - 8 * k)) & 0xFF).astype(np.int32)
    return np.ascontiguousarray(out.reshape(b_, nb, 128).transpose(1, 2, 0))


def _t(a: np.ndarray) -> np.ndarray:
    """[B, n] -> [n, B] int32, contiguous."""
    return np.ascontiguousarray(np.asarray(a).astype(np.int32).T)


def batch_is_bc(batch: PraosBatch) -> bool:
    """True when the staged vrf columns carry batch-compatible proofs."""
    return isinstance(batch.vrf, ecvrf_batch.EcvrfBcBatch)


def pk_arrays(batch: PraosBatch) -> list[np.ndarray]:
    """PraosBatch ([B, ...] staging) -> limb-first arrays in
    ops/pk/kernels.verify_praos_tiles argument order (the bc-staged
    format inserts the announced u, v columns in place of c)."""
    ed, kes, vrf = batch.ed, batch.kes, batch.vrf
    b = batch.beta.shape[0]
    if batch_is_bc(batch):
        vrf_cols = [_t(vrf.pk), _t(vrf.gamma), _t(vrf.u), _t(vrf.v),
                    _t(vrf.s), _t(vrf.alpha)]
    else:
        vrf_cols = [_t(vrf.pk), _t(vrf.gamma), _t(vrf.c), _t(vrf.s),
                    _t(vrf.alpha)]
    return [
        _t(ed.pk), _t(ed.r), _t(ed.s),
        _words_to_byte_blocks(ed.hblocks),
        np.ascontiguousarray(ed.hnblocks.astype(np.int32).reshape(1, b)),
        _t(kes.vk),
        np.ascontiguousarray(kes.period.astype(np.int32).reshape(1, b)),
        _t(kes.r), _t(kes.s), _t(kes.vk_leaf),
        np.ascontiguousarray(
            np.asarray(kes.siblings).astype(np.int32).transpose(1, 2, 0)
        ),
        _words_to_byte_blocks(kes.hblocks),
        np.ascontiguousarray(kes.hnblocks.astype(np.int32).reshape(1, b)),
        *vrf_cols,
        _t(batch.beta), _t(batch.thr_lo), _t(batch.thr_hi),
    ]


# ---------------------------------------------------------------------------
# Packed staging: body-sourced H2D columns + on-device verdict reduction
# ---------------------------------------------------------------------------


class PraosPackedLayout(NamedTuple):
    """Static per-window descriptor of the packed staging format
    (hashable — part of the jit cache key). The offsets point INTO the
    KES-signed header body at the byte positions of each field the
    device extracts; `stage_packed` VERIFIES them lane-for-lane before
    committing to this format."""

    body_len: int
    o_issuer: int  # vk_cold (32)
    o_vrf_vk: int  # vrf_vk (32)
    o_vrf_out: int  # declared beta (64)
    o_vrf_proof: int  # gamma ‖ c ‖ s (80) or gamma ‖ u ‖ v ‖ s (128)
    o_vk_hot: int  # OCert KES root vk (32)
    o_sigma: int  # OCert cold-key signature R ‖ s (64)
    kes_depth: int
    slots_per_kes: int
    has_nonce: bool  # False = neutral epoch nonce (genesis)
    vrf_proof_len: int = 80  # 80 = draft-03, 128 = batch-compatible


class PraosPacked(NamedTuple):
    """Packed device-ready columns — the minimal wire format.

    ~2-3x fewer H2D bytes per window than PraosBatch on real chains: the
    signed body column is the SINGLE source of every field it embeds
    (issuer/VRF keys, proof, declared beta, OCert), the KES Merkle tail
    (leaf vk ‖ siblings — period-constant per pool) is deduplicated into
    a window table, SHA-512 block padding and the 32-byte VRF alpha are
    built on device (ops/sha512.pad_blocks_fixed,
    ops/ecvrf_batch.alpha_from_slots), and the leader thresholds ride as
    a per-pool table + per-lane index."""

    body: np.ndarray  # [B, body_len] uint8 — KES-signed header body
    kes_rs: np.ndarray  # [B, 64] uint8 — KES leaf signature R ‖ s
    kes_tail_idx: np.ndarray  # [B] int32 into kes_tail_tab
    kes_tail_tab: np.ndarray  # [Kt, 32 + depth*32] uint8 — leaf vk ‖ siblings
    slot: np.ndarray  # [B] int32
    counter: np.ndarray  # [B] int32 — OCert issue number
    c0: np.ndarray  # [B] int32 — OCert start KES period
    thr_idx: np.ndarray  # [B] int32 into thr_tab
    thr_tab: np.ndarray  # [Kr, 64] uint8 — thr_lo ‖ thr_hi per pool
    nonce: np.ndarray  # [32] uint8 — epoch nonce bytes (zeros if neutral)
    within: np.ndarray  # [B] uint8 — stability-window flag (nonce scan)


# why the last packed-staging attempt declined (the PR 5 gates were
# silent about why a window fell back). Written by `_decline` on every
# early-out in stage_packed/stage_packed_columns — one module-global
# assignment, so the qualification hot path stays untaxed — and read by
# dispatch_batch into the WindowStaged/WindowSpan telemetry events.
_LAST_DECLINE: str | None = None


def _decline(reason: str) -> None:
    """Record WHICH qualification gate said no, then decline (None)."""
    global _LAST_DECLINE
    _LAST_DECLINE = reason
    return None


def _table_bucket(k: int, minimum: int = 8) -> int:
    """Power-of-two bucket for a window table's row count (bounds the
    set of compiled shapes, same rationale as bucket_size)."""
    n = minimum
    while n < k:
        n *= 2
    return n


def _col(parts: Sequence[bytes], n: int) -> np.ndarray:
    b = len(parts)
    return np.frombuffer(b"".join(parts), np.uint8).reshape(b, n)


def stage_packed(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    hvs: Sequence[HeaderView],
) -> tuple[PraosPackedLayout, PraosPacked] | None:
    """Columnarize a window into the packed H2D format, or None when the
    window does not qualify (the caller falls back to `stage`).

    Qualification is VERIFIED, not assumed: all bodies must share one
    length, every device-extracted field must equal the parsed
    HeaderView field byte-for-byte in EVERY lane at the lane-0 offsets,
    and the staged integers must fit int32. Whenever this returns a
    layout, the device extraction is byte-identical to the generic
    staged path by construction — real CBOR header codecs (block/
    praos_block.py, the synthesizer chains) always qualify; synthetic
    test views whose signed bytes do not embed the fields fall back."""
    if not hvs:
        return _decline("empty-window")
    b = len(hvs)
    h0 = hvs[0]
    body0 = h0.signed_bytes
    lb = len(body0)
    if any(len(hv.signed_bytes) != lb for hv in hvs):
        return _decline("body-width-mixed")
    if epoch_nonce is not None and len(epoch_nonce) != 32:
        return _decline("nonce-len")
    depth = params.kes_depth
    sig_len = 64 + 32 + 32 * depth
    if any(len(hv.kes_sig) != sig_len for hv in hvs):
        return _decline("kes-sig-len")

    plen = len(h0.vrf_proof)
    if plen not in (80, 128) or any(
        len(hv.vrf_proof) != plen for hv in hvs
    ):
        return _decline("proof-format")

    # lane-0 offset discovery (how the offset is FOUND does not matter —
    # the per-lane verification below is what makes extraction correct)
    fields0 = (
        h0.vk_cold, h0.vrf_vk, h0.vrf_output, h0.vrf_proof,
        h0.ocert.vk_hot, h0.ocert.sigma,
    )
    offs = tuple(body0.find(f) for f in fields0)
    if min(offs) < 0:
        return _decline("field-offsets")

    body = np.frombuffer(
        b"".join(hv.signed_bytes for hv in hvs), np.uint8
    ).reshape(b, lb)
    refs = (
        (offs[0], _col([hv.vk_cold for hv in hvs], 32)),
        (offs[1], _col([hv.vrf_vk for hv in hvs], 32)),
        (offs[2], _col([hv.vrf_output for hv in hvs], 64)),
        (offs[3], _col([hv.vrf_proof for hv in hvs], plen)),
        (offs[4], _col([hv.ocert.vk_hot for hv in hvs], 32)),
        (offs[5], _col([hv.ocert.sigma for hv in hvs], 64)),
    )
    for o, ref in refs:
        if not np.array_equal(body[:, o : o + ref.shape[1]], ref):
            return _decline("field-mismatch")

    slot = np.fromiter((hv.slot for hv in hvs), np.int64, b)
    counter = np.fromiter((hv.ocert.counter for hv in hvs), np.int64, b)
    c0 = np.fromiter((hv.ocert.kes_period for hv in hvs), np.int64, b)
    for a in (slot, counter, c0):
        if a.min() < 0 or a.max() >= 2**31:
            return _decline("int32-range")

    sigs = np.frombuffer(
        b"".join(hv.kes_sig for hv in hvs), np.uint8
    ).reshape(b, sig_len)
    kes_rs = np.ascontiguousarray(sigs[:, :64])
    tails: dict[bytes, int] = {}
    kt_idx = np.empty(b, np.int32)
    for i, hv in enumerate(hvs):
        kt_idx[i] = tails.setdefault(hv.kes_sig[64:], len(tails))
    kt_tab = np.zeros((_table_bucket(len(tails)), sig_len - 64), np.uint8)
    for t, j in tails.items():
        kt_tab[j] = np.frombuffer(t, np.uint8)
    kt_tab[len(tails) :] = kt_tab[0]

    f = Fraction(params.active_slot_coeff)
    thr_rows: dict = {}
    rows: list[np.ndarray] = []
    thr_idx = np.empty(b, np.int32)
    for i, hv in enumerate(hvs):
        entry = ledger_view.pool_distr.get(hash_key(hv.vk_cold))
        sigma = entry.stake if entry is not None else Fraction(0)
        j = thr_rows.get(sigma)
        if j is None:
            j = thr_rows[sigma] = len(rows)
            lo, hi = _threshold_rows(sigma, f)
            rows.append(np.concatenate([lo, hi]))
        thr_idx[i] = j
    thr_tab = np.zeros((_table_bucket(len(rows)), 64), np.uint8)
    thr_tab[: len(rows)] = np.stack(rows)
    thr_tab[len(rows) :] = thr_tab[0]

    first_next = (slot // params.epoch_length + 1) * params.epoch_length
    within = (slot + params.stability_window < first_next).astype(np.uint8)

    layout = PraosPackedLayout(
        lb, *offs, depth, params.slots_per_kes_period,
        epoch_nonce is not None, plen,
    )
    packed = PraosPacked(
        body=body.copy(),
        kes_rs=kes_rs,
        kes_tail_idx=kt_idx,
        kes_tail_tab=kt_tab,
        slot=slot.astype(np.int32),
        counter=counter.astype(np.int32),
        c0=c0.astype(np.int32),
        thr_idx=thr_idx,
        thr_tab=thr_tab,
        nonce=np.frombuffer(epoch_nonce or bytes(32), np.uint8),
        within=within,
    )
    return layout, packed


def stage_packed_columns(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    vc: ViewColumns,
    pre: ColumnChecks,
) -> tuple[PraosPackedLayout, PraosPacked] | None:
    """Columnar `stage_packed`: the packed wire built straight from the
    window columns. The columns are already row-major uint8, so the
    body column IS `vc.signed_bytes`, the per-field verification is six
    whole-matrix compares, the KES-tail dedup is one np.unique, and the
    threshold table rides the precheck pool dedup — nothing slices
    per-header bytes. Qualification rules are IDENTICAL to
    `stage_packed` (same verified offsets, same int32 gates), so the
    two stagings are interchangeable lane-for-lane; only the dedup
    table ORDERING may differ (gather indices compensate)."""
    b = len(vc)
    if not b:
        return _decline("empty-window")
    body = vc.signed_bytes
    lb = int(body.shape[1])
    if epoch_nonce is not None and len(epoch_nonce) != 32:
        return _decline("nonce-len")
    depth = params.kes_depth
    sig_len = 64 + 32 + 32 * depth
    if vc.kes_sig.shape[1] != sig_len:
        return _decline("kes-sig-len")
    plen = int(vc.vrf_proof_len[0])
    if plen not in (80, 128) or not (vc.vrf_proof_len == plen).all():
        return _decline("proof-format")

    # lane-0 offset discovery, then whole-matrix per-lane verification
    # (the same contract as stage_packed: HOW the offsets are found does
    # not matter, the byte-equality below makes extraction correct)
    body0 = body[0].tobytes()
    proof_ref = np.ascontiguousarray(vc.vrf_proof[:, :plen])
    refs = (
        vc.vk_cold, vc.vrf_vk, vc.vrf_output, proof_ref,
        vc.ocert_vk_hot, vc.ocert_sigma,
    )
    offs = tuple(body0.find(r[0].tobytes()) for r in refs)
    if min(offs) < 0:
        return _decline("field-offsets")
    for o, ref in zip(offs, refs):
        if not np.array_equal(body[:, o : o + ref.shape[1]], ref):
            return _decline("field-mismatch")

    slot, counter, c0 = vc.slot, vc.ocert_counter, vc.ocert_kes_period
    for a in (slot, counter, c0):
        if a.min() < 0 or a.max() >= 2**31:
            return _decline("int32-range")

    kes_rs = np.ascontiguousarray(vc.kes_sig[:, :64])
    kt_rows, kt_idx = _dedup_rows(vc.kes_sig[:, 64:])
    kt_tab = np.zeros((_table_bucket(kt_rows.shape[0]), sig_len - 64), np.uint8)
    kt_tab[: kt_rows.shape[0]] = kt_rows
    kt_tab[kt_rows.shape[0] :] = kt_tab[0]

    lo_rows, hi_rows = _uniq_threshold_rows(params, pre)
    rows = [np.concatenate([lo, hi]) for lo, hi in zip(lo_rows, hi_rows)]
    thr_tab = np.zeros((_table_bucket(len(rows)), 64), np.uint8)
    thr_tab[: len(rows)] = np.stack(rows)
    thr_tab[len(rows) :] = thr_tab[0]

    first_next = (slot // params.epoch_length + 1) * params.epoch_length
    within = (slot + params.stability_window < first_next).astype(np.uint8)

    layout = PraosPackedLayout(
        lb, *offs, depth, params.slots_per_kes_period,
        epoch_nonce is not None, plen,
    )
    packed = PraosPacked(
        body=np.ascontiguousarray(body),
        kes_rs=kes_rs,
        kes_tail_idx=kt_idx.astype(np.int32),
        kes_tail_tab=kt_tab,
        slot=slot.astype(np.int32),
        counter=counter.astype(np.int32),
        c0=c0.astype(np.int32),
        thr_idx=pre.uniq_inv.astype(np.int32),
        thr_tab=thr_tab,
        nonce=np.frombuffer(epoch_nonce or bytes(32), np.uint8),
        within=within,
    )
    return layout, packed


def pad_packed_to(packed: PraosPacked, size: int) -> PraosPacked:
    """Pad the per-lane columns up to `size` by replicating lane 0
    (window tables and the nonce are shared, not padded). Same jit-cache
    rationale as pad_batch_to."""
    b = packed.body.shape[0]
    if b == size:
        return packed

    def _pad(x):
        return np.concatenate([x, np.repeat(x[:1], size - b, axis=0)], axis=0)

    return packed._replace(
        body=_pad(packed.body),
        kes_rs=_pad(packed.kes_rs),
        kes_tail_idx=_pad(packed.kes_tail_idx),
        slot=_pad(packed.slot),
        counter=_pad(packed.counter),
        c0=_pad(packed.c0),
        thr_idx=_pad(packed.thr_idx),
        within=_pad(packed.within),
    )


def _be8(x):
    """[B] int32 (< 2^31) -> [B, 8] uint8 big-endian, as int.to_bytes(8)."""
    from ..ops import bigint as bi

    return bi.be8_rows(x).astype(jnp.uint8)


def unpack_packed(
    layout: PraosPackedLayout,
    body, kes_rs, kes_tail_idx, kes_tail_tab, slot, counter, c0,
    thr_idx, thr_tab, nonce,
):
    """The device-side unpack: packed columns -> the 21 staged columns
    in flatten_batch order, byte-identical to what `stage` builds on the
    host (the packed round-trip property, tests/test_packed_batch.py).
    Runs inside the jit — limb decomposition for the pk path continues
    through ops/pk/kernels.staged_to_limb_first on these outputs."""
    body = jnp.asarray(body).astype(jnp.uint8)
    bsz = body.shape[0]

    def _slice(o, n):
        return body[:, o : o + n]

    issuer = _slice(layout.o_issuer, 32)
    vrf_vk = _slice(layout.o_vrf_vk, 32)
    beta = _slice(layout.o_vrf_out, 64)
    bc = layout.vrf_proof_len == 128
    proof = _slice(layout.o_vrf_proof, layout.vrf_proof_len)
    if bc:  # gamma ‖ u ‖ v ‖ s announced-points format
        gamma, vrf_u, vrf_v, vrf_s = (
            proof[:, :32], proof[:, 32:64], proof[:, 64:96], proof[:, 96:]
        )
    else:
        gamma, vrf_c, vrf_s = proof[:, :32], proof[:, 32:48], proof[:, 48:]
    vk_hot = _slice(layout.o_vk_hot, 32)
    sigma = _slice(layout.o_sigma, 64)
    ed_r, ed_s = sigma[:, :32], sigma[:, 32:]

    kes_rs = jnp.asarray(kes_rs).astype(jnp.uint8)
    kes_r, kes_s = kes_rs[:, :32], kes_rs[:, 32:]
    tail = jnp.take(
        jnp.asarray(kes_tail_tab).astype(jnp.uint8),
        jnp.asarray(kes_tail_idx), axis=0,
    )
    vk_leaf = tail[:, :32]
    siblings = tail[:, 32:].reshape(bsz, layout.kes_depth, 32)

    thr = jnp.take(
        jnp.asarray(thr_tab).astype(jnp.uint8), jnp.asarray(thr_idx), axis=0
    )
    thr_lo, thr_hi = thr[:, :32], thr[:, 32:]

    slot = jnp.asarray(slot).astype(jnp.int32)
    counter = jnp.asarray(counter).astype(jnp.int32)
    c0 = jnp.asarray(c0).astype(jnp.int32)

    # OCert DSIGN message: R ‖ A ‖ (vk_hot ‖ counter_be8 ‖ period_be8)
    ed_msg = jnp.concatenate(
        [ed_r, issuer, vk_hot, _be8(counter), _be8(c0)], axis=-1
    )
    ed_hb, ed_hnb = ed25519_batch.build_hblocks(
        ed_msg[:, :32], ed_msg[:, 32:64], ed_msg[:, 64:]
    )
    kes_hb, kes_hnb = kes_batch.build_hblocks(kes_r, vk_leaf, body)

    alpha = ecvrf_batch.alpha_from_slots(
        slot, nonce if layout.has_nonce else None
    ).astype(jnp.uint8)

    # evolution index t = kes_period_of(slot) - c0; window-check-failing
    # lanes get an out-of-range t (vs the host's clamped 0) — don't-care
    # lanes, masked by the precheck error that precedes the KES verdict
    # in the reference's error order
    period = slot // layout.slots_per_kes - c0

    if bc:
        return (
            issuer, ed_r, ed_s, ed_hb, ed_hnb,
            vk_hot, period, kes_r, kes_s, vk_leaf, siblings, kes_hb,
            kes_hnb,
            vrf_vk, gamma, vrf_u, vrf_v, vrf_s, alpha,
            beta, thr_lo, thr_hi,
        )
    return (
        issuer, ed_r, ed_s, ed_hb, ed_hnb,
        vk_hot, period, kes_r, kes_s, vk_leaf, siblings, kes_hb, kes_hnb,
        vrf_vk, gamma, vrf_c, vrf_s, alpha,
        beta, thr_lo, thr_hi,
    )


def _pack_bits_u32(bits):
    """[B] bool -> [ceil(B/32)] uint32; lane i -> word i//32, bit i%32
    (host unpack: protocol/batch._mask_bits)."""
    b = bits.shape[0]
    w = -(-b // 32)
    x = bits.astype(jnp.uint32)
    if w * 32 > b:
        x = jnp.concatenate([x, jnp.zeros((w * 32 - b,), jnp.uint32)])
    return (x.reshape(w, 32) << jnp.arange(32, dtype=jnp.uint32)).sum(
        axis=1, dtype=jnp.uint32
    )


def _mask_bits(words: np.ndarray, b: int) -> np.ndarray:
    """Host inverse of _pack_bits_u32: [W] uint32 -> [b] bool."""
    bits = np.unpackbits(
        np.ascontiguousarray(words).view(np.uint8), bitorder="little"
    )
    return bits[:b].astype(bool)


def verdict_reduce(
    flags, eta_bt, within, n_real, ev0, ev0_set, cand0, cand0_set,
    *, scan: bool,
):
    """On-device D2H reduction: pack the five verdict bit rows into u32
    bitmask words and (scan=True) fold the evolving/candidate nonces of
    the window on device (ops/blake2b.nonce_fold_scan), so materialize
    transfers O(bits + one nonce pair) instead of O(lanes x 40 B).

      flags [5, B] int32 — rows ok_ocert_sig, ok_kes_sig, ok_vrf,
        ok_leader, leader_ambiguous; eta_bt [B, 32] int32;
      within [B]; n_real [] int32 (true window size before bucket pad);
      ev0/cand0 [32] int32 + ev0_set/cand0_set [] bool — the carry-in.

    scan=True  -> (masks [5, W] uint32, ev, ev_set, cand, cand_set)
    scan=False -> (masks, eta_u8 [B, 32] uint8) — the eta column still
    ships 4x smaller than the int32 layout; the host keeps the fold.
    """
    b = flags.shape[-1]
    masks = jnp.stack([_pack_bits_u32(flags[i] != 0) for i in range(5)])
    if not scan:
        return masks, eta_bt.astype(jnp.uint8)
    is_real = jnp.arange(b, dtype=jnp.int32) < n_real
    ev, evs, cand, cands = blake2b.nonce_fold_scan(
        eta_bt.astype(jnp.int32),
        jnp.asarray(within) != 0,
        is_real,
        jnp.asarray(ev0).astype(jnp.int32),
        jnp.asarray(ev0_set).astype(bool).reshape(()),
        jnp.asarray(cand0).astype(jnp.int32),
        jnp.asarray(cand0_set).astype(bool).reshape(()),
    )
    return masks, ev, evs, cand, cands


def _state_carry(state: PraosState):
    """Host-side nonce-scan carry from a PraosState (the chain seed)."""

    def arr(n):
        if n is None:
            return np.zeros(32, np.int32)
        return np.frombuffer(n, np.uint8).astype(np.int32)

    return (
        arr(state.evolving_nonce), np.bool_(state.evolving_nonce is not None),
        arr(state.candidate_nonce), np.bool_(state.candidate_nonce is not None),
    )


_ZERO_CARRY = (
    np.zeros(32, np.int32), np.bool_(False),
    np.zeros(32, np.int32), np.bool_(False),
)


def _jitted_packed_xla(layout: PraosPackedLayout, scan: bool):
    """The XLA-twin packed program: unpack -> fused verify -> reduce,
    one jit per (layout, scan)."""
    import jax

    key = ("xla-packed", layout, scan)
    if key not in _JIT:

        def fn(body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
               thr_idx, thr_tab, nonce, within, n_real,
               ev0, ev0_set, cand0, cand0_set):
            cols = unpack_packed(
                layout, body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
                thr_idx, thr_tab, nonce,
            )
            v = verify_praos_any(*cols)
            flags = jnp.stack(
                [v.ok_ocert_sig, v.ok_kes_sig, v.ok_vrf, v.ok_leader,
                 v.leader_ambiguous]
            ).astype(jnp.int32)
            red = verdict_reduce(
                flags, v.eta, within, n_real, ev0, ev0_set, cand0,
                cand0_set, scan=scan,
            )
            return red, flags, v.eta, v.leader_value

        _JIT[key] = _warm_timed(
            f"xla-packed:{layout.body_len}b:p{layout.vrf_proof_len}:"
            f"{'scan' if scan else 'noscan'}",
            jax.jit(fn),
        )
    return _JIT[key]


def _jitted_packed_agg(layout: PraosPackedLayout, scan: bool,
                       mode: str = "all"):
    """The AGGREGATED packed program (batch-compatible layouts only):
    device unpack -> limb relayout -> the window aggregate ->
    verdict_reduce. `mode` selects the aggregate:

      "all" — ops/pk/aggregate.aggregate_window, EVERY stage folded
              into one shared-bucket signed-digit MSM (the default;
              label family "agg-packed");
      "vrf" — aggregate_window_vrf, exact per-lane ed/kes ladders with
              only the VRF equations aggregated on the unsigned engine
              (the OCT_RLC_ALL=0 kill-switch; label family "agg-vrf").

    One jit per (layout, scan, mode); identical output vocabulary to
    the per-lane packed programs, with the aggregate verdict folded
    into the ok mask rows — a window that is not clean under
    aggregation is re-dispatched through the UNCHANGED per-lane stages
    by materialize_verdicts. The `_warm_timed` wrap gives both mode
    families first-execute attribution AND build-pinned AOT store
    coverage (load / write-back) under their label-derived store
    names."""
    import jax

    key = ("agg-packed", layout, scan, mode)
    if key not in _JIT:
        _JIT[key] = _warm_timed(
            f"{_AGG_STAGE_FAMILY[mode]}:{layout.body_len}b:"
            f"{'scan' if scan else 'noscan'}",
            jax.jit(_packed_agg_fn(layout, scan, mode)),
        )
    return _JIT[key]


def _packed_agg_fn(layout: PraosPackedLayout, scan: bool,
                   mode: str = "all"):
    """The RAW (un-jitted) aggregated stage program for (layout, scan,
    mode) — the function the jit builder above wraps, exposed so
    scripts/aot_precompile.py can trace/lower/compile the SAME program
    into the build-pinned store under its `_store_name(label)` row
    (the first execute then loads instead of compiling)."""
    from ..ops.pk import aggregate as pk_aggregate
    from ..ops.pk import kernels as pk_kernels

    agg_fn = (pk_aggregate.aggregate_window if mode == "all"
              else pk_aggregate.aggregate_window_vrf)

    def fn(body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
           thr_idx, thr_tab, nonce, within, n_real,
           ev0, ev0_set, cand0, cand0_set):
        cols = unpack_packed(
            layout, body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
            thr_idx, thr_tab, nonce,
        )
        limb = pk_kernels.staged_to_limb_first_bc(*cols)
        av = agg_fn(*limb, kes_depth=layout.kes_depth)
        red = verdict_reduce(
            av.flags, jnp.transpose(av.eta), within, n_real,
            ev0, ev0_set, cand0, cand0_set, scan=scan,
        )
        return red, av.flags, av.eta, av.leader_value

    return fn


# warmup/compile-gate label families of the two aggregate modes (the
# family prefix is what analysis/costmodel.STAGE_GRAPHS keys on)
_AGG_STAGE_FAMILY = {"all": "agg-packed", "vrf": "agg-vrf"}


def _jitted_pk(kes_depth: int, bc: bool = False):
    import functools
    import os

    import jax

    key = ("pk", kes_depth, bc)
    if key not in _JIT:
        from ..ops.pk import kernels as pk_kernels

        if os.environ.get("OCT_PK_FUSED") and not bc:
            # the original single-jit composition (one cache entry for
            # the whole program) — opt-in for A/B measurement
            _JIT[key] = jax.jit(
                functools.partial(
                    pk_kernels.verify_praos_staged, kes_depth=kes_depth
                )
            )
        else:
            # default: per-stage jits (kernels.verify_praos_split) — a
            # wedged compile costs one stage and the persistent cache
            # accumulates stage entries across retries (VERDICT r3 #2)
            fn = (pk_kernels.verify_praos_split_bc if bc
                  else pk_kernels.verify_praos_split)
            _JIT[key] = functools.partial(fn, kes_depth=kes_depth)
    return _JIT[key]


def _pk_dispatch(batch: PraosBatch):
    """Dispatch the Pallas path (async); -> opaque handle. The staged
    [B, ...] uint8 columns go straight to the jit — transposes and the
    byte expansion run in XLA (pk_arrays on host cost ~20 us/header)."""
    depth = batch.kes.siblings.shape[-2]
    ed, kes, vrf = batch.ed, batch.kes, batch.vrf
    # (an explicit async jax.device_put of the columns first was A/B'd
    # r5: through the remote-TPU tunnel it does NOT overlap with the
    # prior window's kernels — the same ~130 ms/batch of H2D just moves
    # from the materialize wait into the dispatch bracket)
    out = _jitted_pk(depth, batch_is_bc(batch))(
        ed.pk, ed.r, ed.s, ed.hblocks, ed.hnblocks,
        kes.vk, kes.period, kes.r, kes.s, kes.vk_leaf, kes.siblings,
        kes.hblocks, kes.hnblocks,
        *batch.vrf,
        batch.beta, batch.thr_lo, batch.thr_hi,
    )
    return out


def _pk_materialize(out, b: int) -> Verdicts:
    flags, eta, lv = (np.asarray(x) for x in out)
    return Verdicts(
        ok_ocert_sig=flags[0, :b] != 0,
        ok_kes_sig=flags[1, :b] != 0,
        ok_vrf=flags[2, :b] != 0,
        ok_leader=flags[3, :b] != 0,
        leader_ambiguous=flags[4, :b] != 0,
        eta=np.ascontiguousarray(eta[:, :b].T),
        leader_value=np.ascontiguousarray(lv[:, :b].T),
    )


def pad_batch_to(batch: PraosBatch, size: int) -> PraosBatch:
    """Pad every column's batch dim up to `size` by replicating lane 0
    (guaranteed-decodable inputs; callers slice verdicts back to the true
    size). Keeps the jit cache bounded: one compilation per bucket shape
    instead of one per epoch-segment length."""
    b = batch.beta.shape[0]
    if b == size:
        return batch

    def _pad(x):
        x = np.asarray(x)
        return np.concatenate([x, np.repeat(x[:1], size - b, axis=0)], axis=0)

    def _pad_tuple(t):
        return type(t)(*(_pad(c) for c in t))

    return PraosBatch(
        ed=_pad_tuple(batch.ed),
        kes=_pad_tuple(batch.kes),
        vrf=_pad_tuple(batch.vrf),
        beta=_pad(batch.beta),
        thr_lo=_pad(batch.thr_lo),
        thr_hi=_pad(batch.thr_hi),
    )


def bucket_size(b: int, minimum: int = 8) -> int:
    """Shape bucket for a batch of b lanes: next power of two up to
    2048, then next multiple of 2048. Pure powers of two waste up to
    half the lanes on the epoch-tail batch (a ~21.6k-block epoch slices
    to 8192+8192+5216, and 5216 padded to 8192 is 36% dead work —
    ~14% of ALL device lanes at the 1M bench scale); 2048-granularity
    buckets cap tail padding at <2048 lanes while keeping the set of
    compiled shapes small (the remainder is epoch-size-distributed, so
    in practice one extra shape per chain)."""
    n = minimum
    while n < b and n < 2048:
        n *= 2
    if b <= n:
        return n
    return ((b + 2047) // 2048) * 2048


def _jitted_verify(bc: bool = False):
    import jax

    key = ("fn", bc)
    if key not in _JIT:
        _JIT[key] = _warm_timed(
            f"xla-fused{'-bc' if bc else ''}",
            jax.jit(verify_praos_bc if bc else verify_praos),
        )
    return _JIT[key]


def _lt_be_rows(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Vectorized big-endian lexicographic a < b per row, [n, 32] uint8
    (the host numpy twin of the device `_lt_be`)."""
    ne = a != b
    any_ne = ne.any(axis=1)
    first = ne.argmax(axis=1)
    rows = np.arange(a.shape[0])
    return any_ne & (a[rows, first] < b[rows, first])


def run_batch_native(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce,
    hvs: "Sequence[HeaderView] | ViewColumns",
    pre: HostChecks,
) -> Verdicts:
    """Native (C++) crypto backend producing the same Verdicts shape as
    the device kernel — the honest single-core comparison path and the
    fallback when no accelerator is available (native/hostcrypto.cpp
    oc_validate_praos). Short-circuits at the first failing lane; lanes
    past it carry don't-care verdicts, which the sequential epilogue
    never reads.

    A ViewColumns window passes its matrices through untouched (no
    per-header np.stack) and runs the leader bracket as one vectorized
    byte compare against the per-pool threshold tables — the same
    clamped byte rows the device kernel compares against."""
    from .. import native_loader as nl

    n = len(hvs)
    if isinstance(hvs, ViewColumns):
        vc = hvs
        cold_vk = vc.vk_cold
        ocert_sig = vc.ocert_sigma
        ocert_msg = np.concatenate(
            [vc.ocert_vk_hot, _be8_np(vc.ocert_counter),
             _be8_np(vc.ocert_kes_period)], axis=1,
        )
        kes_vk = vc.ocert_vk_hot
        kes_sig = vc.kes_sig
        lb = vc.signed_bytes.shape[1]
        body = vc.signed_bytes.tobytes()
        body_off = np.arange(n + 1, dtype=np.int64) * lb
        vrf_vk = vc.vrf_vk
        plen = int(vc.vrf_proof_len[0])
        vrf_proof = np.ascontiguousarray(vc.vrf_proof[:, :plen])
        vrf_alpha = _alpha_column(vc, epoch_nonce)
        vrf_output = vc.vrf_output
    else:
        cold_vk = np.stack([np.frombuffer(hv.vk_cold, np.uint8) for hv in hvs])
        ocert_sig = np.stack(
            [np.frombuffer(hv.ocert.sigma, np.uint8) for hv in hvs]
        )
        ocert_msg = np.stack(
            [np.frombuffer(hv.ocert.signable(), np.uint8) for hv in hvs]
        )
        kes_vk = np.stack(
            [np.frombuffer(hv.ocert.vk_hot, np.uint8) for hv in hvs]
        )
        kes_sig = np.stack([np.frombuffer(hv.kes_sig, np.uint8) for hv in hvs])
        body = b"".join(hv.signed_bytes for hv in hvs)
        body_off = np.zeros(n + 1, np.int64)
        np.cumsum([len(hv.signed_bytes) for hv in hvs], out=body_off[1:])
        vrf_vk = np.stack([np.frombuffer(hv.vrf_vk, np.uint8) for hv in hvs])
        vrf_proof = np.stack(
            [np.frombuffer(hv.vrf_proof, np.uint8) for hv in hvs]
        )
        vrf_alpha = np.stack(
            [
                np.frombuffer(nonces.mk_input_vrf(hv.slot, epoch_nonce), np.uint8)
                for hv in hvs
            ]
        )
        vrf_output = np.stack(
            [np.frombuffer(hv.vrf_output, np.uint8) for hv in hvs]
        )

    rc, kind, lv, eta = nl.native_validate_praos(
        cold_vk, ocert_sig, ocert_msg, kes_vk,
        pre.kes_evolution.astype(np.int64), kes_sig, params.kes_depth,
        body, body_off, vrf_vk, vrf_proof, vrf_alpha, vrf_output,
    )
    ok_ocert = np.ones(n, bool)
    ok_kes = np.ones(n, bool)
    ok_vrf = np.ones(n, bool)
    if rc >= 0:
        (ok_ocert if kind == 1 else ok_kes if kind == 2 else ok_vrf)[rc] = False

    stop = n if rc < 0 else rc
    if isinstance(hvs, ViewColumns) and isinstance(pre, ColumnChecks):
        # bracket compare vectorized against the per-pool byte tables
        # (Fraction math once per unique pool; ambiguous lanes still go
        # to the exact host check in _lane_error)
        thr_lo, thr_hi = _uniq_threshold_tables(params, pre)
        win = _lt_be_rows(lv, thr_lo)
        amb = ~win & _lt_be_rows(lv, thr_hi)
        live = np.arange(n) < stop
        ok_leader = win & live
        ambiguous = amb & live
    else:
        # leader threshold: bracket compare exactly as the device kernel
        f = params.active_slot_coeff
        ok_leader = np.zeros(n, bool)
        ambiguous = np.zeros(n, bool)
        for i in range(stop):
            hv = hvs[i]
            entry = ledger_view.pool_distr.get(hash_key(hv.vk_cold))
            sigma = entry.stake if entry is not None else Fraction(0)
            lo, hi = leader_threshold_bracket(Fraction(sigma), Fraction(f))
            lv_int = int.from_bytes(lv[i].tobytes(), "big")
            ok_leader[i] = lv_int < lo
            ambiguous[i] = not ok_leader[i] and lv_int < hi
    return Verdicts(ok_ocert, ok_kes, ok_vrf, ok_leader, ambiguous, eta, lv)


def run_batch(batch: PraosBatch) -> Verdicts:
    """Stage -> device -> host verdict arrays (numpy).

    Batches are padded to power-of-two buckets so jax's per-shape trace
    cache compiles once per (bucket, kes_depth) — the crypto graph is
    large and arbitrary-length recompiles would dominate wall-clock.
    """
    b = batch.beta.shape[0]
    padded = pad_batch_to(batch, bucket_size(b))
    if _impl() == "pk":
        return _pk_materialize(_pk_dispatch(padded), b)
    out = _jitted_verify(batch_is_bc(padded))(
        *(jnp.asarray(x) for x in flatten_batch(padded))
    )
    return Verdicts(*(np.asarray(x)[:b] for x in out))


# ---------------------------------------------------------------------------
# Batched chain-position semantics (first failure + state fold)
# ---------------------------------------------------------------------------


@dataclass
class BatchResult:
    """Outcome of validating a within-epoch run of headers."""

    state: PraosState  # state after the last VALID prefix header
    n_valid: int  # length of the valid prefix
    error: praos.PraosValidationError | None  # error at position n_valid
    states: list | None = None  # per-position states (collect_states=True)


def _counter_m(hk, counters, pool_distr):
    """The stateful OCert counter baseline: last seen counter, else 0
    for a pool with stake, else None (NoCounterForKeyHash)."""
    m = counters.get(hk)
    if m is None and hk in pool_distr:
        m = 0
    return m


def _counter_ok(m, n) -> bool:
    """Praos.hs:585-590: m <= n <= m + 1."""
    return m is not None and m <= n <= m + 1


def _lane_error(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce: nonces.Nonce,
    hv: HeaderView,
    pre: HostChecks,
    v: Verdicts,
    i: int,
    counters: Mapping[bytes, int],
) -> praos.PraosValidationError | None:
    """Map verdict bitmaps back to the EXACT error the sequential
    reference fold would raise, in its order: the whole of
    validateKESSignature (window, OCert sig, KES sig, counters —
    Praos.hs:558-606) before any of validateVRFSignature (pool lookup,
    proof, leader threshold — Praos.hs:528-556)."""
    if pre.kes_window_errors[i] is not None:
        return pre.kes_window_errors[i]
    if not v.ok_ocert_sig[i]:
        return praos.InvalidSignatureOCERT(hv.ocert.counter, hv.ocert.kes_period)
    if not v.ok_kes_sig[i]:
        kp = params.kes_period_of(hv.slot)
        c0 = hv.ocert.kes_period
        return praos.InvalidKesSignatureOCERT(kp, c0, kp - c0)
    # ocert counter monotonicity (Praos.hs:585-590), stateful
    hk = hash_key(hv.vk_cold)
    m = _counter_m(hk, counters, ledger_view.pool_distr)
    if m is None:
        return praos.NoCounterForKeyHashOCERT(hk)
    n = hv.ocert.counter
    if not m <= n:
        return praos.CounterTooSmallOCERT(m, n)
    if not n <= m + 1:
        return praos.CounterOverIncrementedOCERT(m, n)
    if pre.vrf_lookup_errors[i] is not None:
        return pre.vrf_lookup_errors[i]
    if not v.ok_vrf[i]:
        return praos.VRFKeyBadProof(hv.slot, epoch_nonce)
    if not v.leader_ambiguous[i] and v.ok_leader[i]:
        return None  # the common path: no big-int reconstruction
    entry = ledger_view.pool_distr.get(hk)
    sigma = entry.stake if entry is not None else Fraction(0)
    lv_val = int.from_bytes(bytes(v.leader_value[i].astype(np.uint8)), "big")
    if v.leader_ambiguous[i] and leader.check_leader_value(
        lv_val, sigma, params.active_slot_coeff
    ):
        return None
    return praos.VRFLeaderValueTooBig(lv_val, sigma, params.active_slot_coeff)


def _proof_len_uniform(hvs) -> bool:
    if isinstance(hvs, ViewColumns):
        pl = hvs.vrf_proof_len
        return bool((pl == pl[0]).all())
    return len({len(hv.vrf_proof) for hv in hvs}) <= 1


def _proof_len_at(hvs, i: int) -> int:
    if isinstance(hvs, ViewColumns):
        return int(hvs.vrf_proof_len[i])
    return len(hvs[i].vrf_proof)


def _slot_at(hvs, i: int) -> int:
    if isinstance(hvs, ViewColumns):
        return int(hvs.slot[i])
    return hvs[i].slot


def validate_batch(
    params: PraosParams,
    ticked: TickedPraosState,
    hvs: "Sequence[HeaderView] | ViewColumns",
    collect_states: bool = False,
    backend: str = "device",
    mesh=None,  # backend="sharded": the jax.sharding.Mesh (None = all devices)
) -> BatchResult:
    """Validate a within-epoch run of headers as one batch.

    Equivalent to folding `praos.update` over `hvs` from `ticked` — same
    resulting state, same first error — but with all crypto executed as a
    single fused device program (backend="device") or through the C++
    verifier (backend="native"). The epoch nonce must be constant across
    the run (the caller segments at epoch boundaries; `tick` between
    segments).

    `hvs` may be a ViewColumns window: prechecks, staging and the
    all-clean epilogue then run columnar (no per-header objects);
    HeaderViews materialize only for anomaly lanes.
    """
    if not len(hvs):
        return BatchResult(ticked.state, 0, None, [] if collect_states else None)
    lview = ticked.ledger_view
    eta0 = ticked.state.epoch_nonce

    if not _proof_len_uniform(hvs):
        # a run mixing 80- and 128-byte proofs cannot stage as one
        # uniform proof column; segment at format boundaries — the
        # reference fold length-dispatches per header, and segmentation
        # never changes per-lane verdicts or the first error
        states = [] if collect_states else None
        total = 0
        i = 0
        n = len(hvs)
        while True:
            j = _proof_break(hvs, i, n)
            res = validate_batch(
                params, ticked, hvs[i:j], collect_states, backend, mesh
            )
            total += res.n_valid
            if collect_states:
                states.extend(res.states or [])
            if res.error is not None or j == n:
                return BatchResult(res.state, total, res.error, states)
            i = j
            ticked = praos.tick(params, lview, _slot_at(hvs, i), res.state)

    pre = host_prechecks(params, lview, hvs)
    if backend == "native":
        v = run_batch_native(params, lview, eta0, hvs, pre)
    elif backend == "sharded":
        # multi-chip SPMD: batch axis over the device mesh, psum/pmin
        # verdict collectives (parallel/spmd.py; SURVEY.md §5.8)
        from ..parallel import spmd

        v, _first_bad, _n_ok = spmd.sharded_stage_run(
            params, lview, eta0, hvs, pre, mesh
        )
    else:
        batch = stage_any(params, lview, eta0, hvs, pre)
        v = run_batch(batch)
    return _epilogue(params, ticked, hvs, pre, v, collect_states)


def stage_any(
    params: PraosParams,
    ledger_view: LedgerView,
    epoch_nonce,
    hvs: "Sequence[HeaderView] | ViewColumns",
    pre: HostChecks,
) -> PraosBatch:
    """Stage whichever window representation arrives: ViewColumns go
    through the columnar stage; HeaderView lists through the classic
    per-view stage (also the lazy fallback for columnar windows that
    cannot stage columnar, e.g. non-int32 slots)."""
    if isinstance(hvs, ViewColumns) and isinstance(pre, ColumnChecks):
        return stage_columns(
            params, ledger_view, epoch_nonce, hvs, pre.kes_evolution, pre
        )
    if isinstance(hvs, ViewColumns):
        hvs = hvs.views()
    return stage(params, ledger_view, epoch_nonce, hvs, pre.kes_evolution)


# Enclose latency brackets (Util/Enclose.hs) around the hot-path
# phases: stage (host CBOR->SoA), dispatch (device kernel launch),
# materialize (device wait), epilogue (sequential fold). Settable so
# the embedding application (bench, node, tests) observes per-phase
# latency without touching the code path.
BATCH_TRACER = None  # None = off (zero overhead on the hot path)


def set_batch_tracer(tracer) -> None:
    global BATCH_TRACER
    BATCH_TRACER = tracer


def _enclose(label):
    from ..utils.trace import Enclose

    class _Null:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    return Enclose(BATCH_TRACER, label) if BATCH_TRACER is not None else _Null()


class _FailedDispatch:
    """In-flight placeholder for a window whose staging or dispatch
    raised a RECOVERABLE error (obs/recovery): the exception is
    re-raised at the window's retire slot, where the supervisor has the
    exact fold state (`ticked`) a re-validation needs — so recovery
    happens in retire order and the pipeline's windows never reorder."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc

    def result(self):
        raise self.exc


class _Dispatched(NamedTuple):
    """Opaque handle between dispatch_batch and materialize_verdicts."""

    impl: str  # "pk" | "xla"
    packed: bool
    carried: bool  # device nonce-scan outputs extend the chain carry
    scan: bool
    out: tuple  # impl-specific device handles
    # telemetry: (index, outcome, gate, stage_s, dispatch_s,
    # lanes_padded, t_dispatch) — None when tracing is off
    meta: tuple | None = None


def _nbytes(arrays) -> int:
    return int(sum(np.asarray(a).nbytes for a in arrays))


def _emit_transfer(phase: str, **kw) -> None:
    if BATCH_TRACER is not None:
        from ..utils.trace import TransferEvent

        BATCH_TRACER(TransferEvent(phase=phase, **kw))


# process-wide window dispatch sequence (the WindowStaged/WindowSpan
# `index`); only advanced while a tracer is installed
_WIN_SEQ = 0


def _win_meta(outcome: str, gate: str | None, b: int, lanes: int,
              t0: float, t1: float) -> tuple | None:
    """Build the per-window telemetry meta and emit the WindowStaged
    event. Returns None (zero residual cost) when no tracer is set."""
    global _WIN_SEQ
    if BATCH_TRACER is None:
        return None
    from ..utils.trace import WindowStaged

    idx = _WIN_SEQ
    _WIN_SEQ += 1
    t2 = time.monotonic()
    BATCH_TRACER(
        WindowStaged(idx, b, lanes, outcome, gate, t1 - t0, t2 - t1)
    )
    return (idx, outcome, gate, t1 - t0, t2 - t1, lanes, t2)


def _emit_window_span(meta, lanes: int, n_valid: int, failed: bool,
                      t_m0: float, t_m1: float, t_e0: float,
                      t_done: float) -> None:
    """Emit the retired-window span (dispatch_batch meta + the
    materialize/epilogue walls measured in the validate_chain loop)."""
    if BATCH_TRACER is None or meta is None:
        return
    from ..utils.trace import WindowSpan

    idx, outcome, gate, stage_s, dispatch_s, _lanes_padded, t_disp = meta
    BATCH_TRACER(WindowSpan(
        index=idx, lanes=lanes, outcome=outcome, gate=gate,
        stage_s=stage_s, dispatch_s=dispatch_s,
        materialize_s=t_m1 - t_m0, epilogue_s=t_done - t_e0,
        t_dispatch=t_disp, t_materialized=t_m1, t_done=t_done,
        n_valid=n_valid, failed=failed,
    ))


class _StagedWindow(NamedTuple):
    """Output of `prepare_window` — everything `dispatch_prepared`
    needs, so staging can run on a producer thread ahead of dispatch
    (the round-10 threaded staging pipeline; the split is also what
    keeps the kill-switched path byte-identical: dispatch_batch is the
    two halves composed inline)."""

    pre: "HostChecks"
    packed: "tuple | None"  # (layout, padded PraosPacked) when packed
    padded: "PraosBatch | None"  # generic fallback, padded
    b: int
    lanes: int
    h2d: int
    gate: str | None
    t0: float
    t1: float


def prepare_window(params, lview, eta0, hvs) -> _StagedWindow:
    """The HOST half of dispatch_batch: prechecks + packed/generic
    staging + bucket padding. Pure with respect to the sequential fold
    (depends only on the epoch nonce and ledger view), so a producer
    thread may run it arbitrarily far ahead of dispatch — the round-10
    staging thread overlaps this wall with device compute and the
    retire-side epilogue work on the main thread."""
    from ..testing import chaos

    # the staging seam (chaos: staging-thread-death@window:N) — when the
    # producer thread runs this, the raise kills THAT thread's future
    # exactly like a real mid-prepare death; disarmed it is one module
    # bool test
    chaos.fire("stage")
    b = len(hvs)
    t0 = time.monotonic()
    with _enclose("stage"):
        pre = host_prechecks(params, lview, hvs)
        packed = None
        gate = None
        if PACKED_STAGE and not os.environ.get("OCT_PK_FUSED"):
            if isinstance(hvs, ViewColumns):
                if isinstance(pre, ColumnChecks):
                    packed = stage_packed_columns(
                        params, lview, eta0, hvs, pre
                    )
                    if packed is None:
                        gate = _LAST_DECLINE
                else:
                    gate = "no-column-prechecks"
            else:
                packed = stage_packed(params, lview, eta0, hvs)
                if packed is None:
                    gate = _LAST_DECLINE
        else:
            gate = "packed-off"
        if packed is None:
            batch = stage_any(params, lview, eta0, hvs, pre)
            padded = pad_batch_to(batch, bucket_size(b))
            h2d = _nbytes(flatten_batch(padded))
            lanes = padded.beta.shape[0]
            return _StagedWindow(pre, None, padded, b, lanes, h2d, gate,
                                 t0, time.monotonic())
        layout, parr = packed
        parr = pad_packed_to(parr, bucket_size(b))
        h2d = _nbytes(parr)
        lanes = parr.body.shape[0]
    return _StagedWindow(pre, (layout, parr), None, b, lanes, h2d, gate,
                         t0, time.monotonic())


def _agg_label(layout, lanes: int, scan: bool,
               mode: str = "all") -> str:
    """The aggregate monolith's warmup/first-execute label at one
    padded lane count (must match what `_warm_timed` derives from the
    dispatched arguments — the compile gate and the warm ladder key
    their cold/warm decisions on it). `mode` picks the label family:
    "all" -> agg-packed (shared-bucket fold), "vrf" -> agg-vrf (the
    OCT_RLC_ALL=0 vrf-only aggregate)."""
    return (f"{_AGG_STAGE_FAMILY[mode]}:{layout.body_len}b:"
            f"{'scan' if scan else 'noscan'}:{lanes}l")


def dispatch_prepared(sw: _StagedWindow, carry=None, ladder=None):
    """The DEVICE half of dispatch_batch: launch the fused kernel for a
    prepared window WITHOUT waiting (jax dispatch is asynchronous).
    Must run in window order on one thread — the device nonce-scan
    carry chains dispatch-to-dispatch.

    `carry` is the previous window's device nonce-scan carry (or a host
    `_state_carry`); when given and the window staged packed, the
    on-device nonce fold chains through this window and the new carry
    is returned — the non-associative fold never leaves the device
    while the pipeline is intact (praos.tick only rotates the epoch
    nonce, so the chain crosses epoch boundaries untouched).

    Returns (pre, dispatched, b, carry_out); carry_out is None when this
    window cannot extend the chain (generic fallback or scan disabled).
    """
    from ..testing import chaos

    # the dispatch seam (chaos: device-error@dispatch:N — a fake
    # XlaRuntimeError-class failure at window launch — and
    # compile-stall@window:N, a simulated compile wall)
    chaos.fire("dispatch")
    pre, b, lanes, h2d, gate, t0, t1 = (
        sw.pre, sw.b, sw.lanes, sw.h2d, sw.gate, sw.t0, sw.t1
    )
    with _enclose("dispatch"):
        _emit_transfer(
            "dispatch", lanes=lanes, h2d_bytes=h2d,
            packed=sw.packed is not None,
        )
        if sw.packed is None:
            padded = sw.padded
            if _impl() == "pk":
                out = _pk_dispatch(padded)
                impl = "pk"
            else:
                out = _jitted_verify(batch_is_bc(padded))(
                    *(jnp.asarray(x) for x in flatten_batch(padded))
                )
                impl = "xla"
            meta = _win_meta("generic", gate, b, lanes, t0, t1)
            disp = _Dispatched(impl, False, False, False, out, meta)
            return pre, disp, b, None
        layout, parr = sw.packed
        scan_mode = NONCE_SCAN and carry is not None
        cargs = carry if scan_mode else _ZERO_CARRY
        n_real = np.int32(b)
        refused_gate = None
        agg_mode = "all" if _rlc_all_enabled() else "vrf"
        agg_stage = _agg_label(layout, lanes, scan_mode, agg_mode)
        agg_path = layout.vrf_proof_len == 128 and _agg_enabled()
        if agg_path and ladder is not None:
            # the warm ladder owns the production-bucket compile: hand
            # it the first packed window so the background thread can
            # start warming the target-lane program while the replay
            # serves rung-sized windows
            ladder.observe(layout, parr, scan_mode)
        if agg_path:
            # the pk fallback is the per-stage split; the xla fallback
            # is itself the per-lane packed monolith, so name its twin
            # and only refuse when that twin is predicted cheaper
            impl_is_pk = _impl() == "pk"
            if not _compile_gate_admit(
                agg_stage,
                action=("stage-split-fallback" if impl_is_pk
                        else "xla-packed-fallback"),
                fallback_graph=(None if impl_is_pk
                                else "verify_praos_core_bc"),
                lanes=lanes,
            ):
                # predicted compile wall over budget AND the fallback
                # path is cheaper: skip the 330k-eqn aggregate monolith
                # (decision in warmup report)
                refused_gate = "compile-wall-refused"
        if agg_path and refused_gate is None:
            # the aggregated fast path: ONE RLC/MSM program instead of
            # the per-lane ladder stages; the eta/nonce outputs are
            # identical to the per-lane path by construction, so the
            # scan carry chain is valid even if this window later falls
            # back (materialize_verdicts re-dispatches per-lane on any
            # anomaly — the fallback recomputes the same etas)
            out = _jitted_packed_agg(layout, scan_mode, agg_mode)(
                *parr, n_real, *cargs
            )
            carry_out = tuple(out[0][1:5]) if scan_mode else None
            meta = _win_meta("packed-agg", None, b, lanes, t0, t1)
            disp = _Dispatched(
                "agg", True, scan_mode, scan_mode,
                (layout, parr, n_real, cargs, out), meta,
            )
            return pre, disp, b, carry_out
        if _impl() == "pk":
            from ..ops.pk import kernels as pk_kernels

            out = pk_kernels.verify_praos_packed_split(
                layout, *parr, n_real, *cargs, scan=scan_mode
            )
            impl = "pk"
        else:
            out = _jitted_packed_xla(layout, scan_mode)(
                *parr, n_real, *cargs
            )
            impl = "xla"
        carry_out = tuple(out[0][1:5]) if scan_mode else None
        meta = _win_meta("packed", refused_gate, b, lanes, t0, t1)
        disp = _Dispatched(impl, True, scan_mode, scan_mode, out, meta)
        return pre, disp, b, carry_out


def dispatch_batch(params, lview, eta0, hvs, carry=None, ladder=None):
    """Stage a within-epoch window and dispatch the fused kernel WITHOUT
    waiting (the §7.3.6 host/device overlap; the reference's analog is
    the decoupled add-block queue, ChainSel.hs:217-246) — the inline
    composition of `prepare_window` + `dispatch_prepared`; the
    pipelined validate_chain loop calls the halves separately so a
    producer thread can stage ahead of dispatch."""
    return dispatch_prepared(  # octflow: disable=FLOW304 — public
        # composition seam with no in-package caller: the pipelined
        # loops call the halves separately (and ride the supervisor);
        # an external caller of the inline form owns its own recovery,
        # exactly like calling dispatch_prepared directly
        prepare_window(params, lview, eta0, hvs), carry, ladder
    )


# ---------------------------------------------------------------------------
# Warm-while-serving compile ladder
# ---------------------------------------------------------------------------

# OCT_WARM_LADDER: "0" = off (windows always slice at max_batch and the
# production program compiles synchronously at first dispatch — the
# pre-round-10 behavior, verdict-identical by construction since window
# re-tiling never changes verdicts); "1"/unset = auto (engage only when
# a wall deadline is exported and the production aggregate monolith is
# predicted not to fit it); "force" = engage whenever the production
# program is cold (tests, profiling).


class WarmLadder:
    """Warm-while-serving compile ladder (round 10 tentpole).

    When the production-bucket aggregate monolith is cold and predicted
    over the remaining wall (octwall), the replay does NOT gamble the
    budget on one synchronous compile: the validate_chain loop slices
    windows at a small RUNG lane count — chosen by
    analysis/costmodel.choose_rung against $OCT_WALL_DEADLINE — and a
    background thread compiles the production-lane program off the
    first window's packed columns. The moment it lands, the loop
    re-tiles onto the production bucket (`swap`). Replay progress and
    the monolith compile overlap instead of serializing, so the bench
    child banks a provisional device checkpoint while the big program
    is still in XLA.

    Verdict-identical by construction: the rung only changes WINDOW
    SLICING, and validate_batch is segmentation-invariant (same
    verdicts, same first error, same nonce carry — the differential
    suite drives all four ladder x staging-thread combinations).

    Every transition is first-class warmup forensics
    (obs/warmup.note_ladder + LadderEvent through the batch tracer):
    engaged / bg-compile-started / bg-compile-done / bg-compile-failed
    / swap, each carrying the octwall feature hash of the program
    involved."""

    def __init__(self, target: int, rung: int, graph: str,
                 predicted_s: float | None):
        self.target = target
        self.rung = rung
        self.graph = graph
        self.predicted_s = predicted_s
        # the ladder's transition latches cross threads (the loop reads
        # what the background compile writes) — serialize them so the
        # serving tier can drive poll_swap from more than one thread
        self._state_lock = threading.Lock()
        self._engaged = False  # guarded-by: _state_lock
        self._done = threading.Event()
        self._bg: threading.Thread | None = None
        self._swapped = False  # guarded-by: _state_lock
        self.failed = False  # guarded-by: _state_lock

    # -- loop-facing ---------------------------------------------------------

    def cap(self) -> int | None:
        """Lane cap for the next window slice (None = production)."""
        with self._state_lock:
            if self._swapped or self._done.is_set():
                return None
            return self.rung

    def note_engaged_once(self) -> None:
        """Record engagement the first time a slice is actually capped
        (a chain shorter than the rung never engages — no noise)."""
        with self._state_lock:
            if self._engaged:
                return
            self._engaged = True
        from ..analysis import costmodel
        from ..obs.warmup import WARMUP

        rung_pin = costmodel.pinned(
            costmodel.ladder_pin_name(self.graph, self.rung)
        )
        WARMUP.note_ladder(
            "engaged", rung=self.rung, target=self.target,
            graph=self.graph, predicted_s=self.predicted_s,
            feature_hash=(rung_pin or {}).get("feature_hash"),
        )
        self._emit("engaged", self.rung)

    def poll_swap(self) -> bool:
        """True exactly once, when the background compile has landed
        and the loop should re-tile onto the production bucket."""
        with self._state_lock:
            if (self._swapped or not self._engaged
                    or not self._done.is_set()):
                return False
            self._swapped = True
            failed = self.failed
        from ..obs.warmup import WARMUP

        WARMUP.note_ladder("swap", rung=self.rung, target=self.target,
                           failed=failed or None)
        self._emit("swap", None)
        return True

    # -- dispatch-facing -----------------------------------------------------

    def observe(self, layout, parr, scan: bool) -> None:
        """First packed window seen: start the background production
        compile (or finish immediately when the production label is
        already warm in this process)."""
        if self._bg is not None or self._done.is_set():
            return
        # warm the mode that dispatch will actually serve (agg-packed
        # unless the OCT_RLC_ALL kill-switch pins the vrf-only family)
        mode = "all" if _rlc_all_enabled() else "vrf"
        label = _agg_label(layout, self.target, scan, mode)
        from ..obs.warmup import WARMUP

        if label in WARMUP.stages:
            self._done.set()
            return
        from ..analysis import costmodel

        WARMUP.note_ladder(
            "bg-compile-started", rung=self.rung, target=self.target,
            stage=label,
            feature_hash=costmodel.stage_feature_hash(label),
        )
        self._emit("bg-compile-started", self.rung)
        self._bg = threading.Thread(
            target=self._warm, args=(layout, parr, scan, mode),
            daemon=True, name="oct-warm-ladder",
        )
        self._bg.start()

    def _warm(self, layout, parr, scan: bool, mode: str = "all") -> None:
        """Background thread body: pad the observed window's packed
        columns to the production bucket and run the production program
        once, blocking until the compile (and one execute) lands. XLA
        compiles outside the GIL, so the replay keeps serving rung
        windows meanwhile; the execute itself is one window of device
        time. Bypasses the compile gate by design — eating this wall in
        the background is the ladder's whole purpose."""
        import jax

        t0 = time.monotonic()
        try:
            parr_t = pad_packed_to(parr, self.target)
            n_real = np.int32(parr.body.shape[0])
            out = _jitted_packed_agg(layout, scan, mode)(
                *parr_t, n_real, *_ZERO_CARRY
            )
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 — fail-open: the loop
            # simply dispatches the production program synchronously
            with self._state_lock:
                self.failed = True
            from ..obs.warmup import WARMUP

            WARMUP.note_ladder("bg-compile-failed", rung=self.rung,
                               target=self.target, detail=repr(e)[:200])
            self._emit("bg-compile-failed", self.rung)
        else:
            from ..obs.warmup import WARMUP

            WARMUP.note_ladder(
                "bg-compile-done", rung=self.rung, target=self.target,
                wall_s=time.monotonic() - t0,
            )
            self._emit("bg-compile-done", self.rung)
        finally:
            self._done.set()

    def _emit(self, kind: str, rung: int | None) -> None:
        if BATCH_TRACER is not None:
            from ..utils.trace import LadderEvent

            BATCH_TRACER(LadderEvent(kind, rung, self.target))


_LADDER: WarmLadder | None = None


def reset_warm_ladder() -> None:
    """Test isolation: forget the process-wide ladder."""
    global _LADDER
    _LADDER = None


def _maybe_ladder(max_batch: int) -> WarmLadder | None:
    """Create (once per process) or return the warm ladder for a device
    replay. Engages only when the production path is the aggregate
    monolith (OCT_VRF_AGG on, bc windows — on every other path the cold
    programs are the individually-small split stages and re-tiling buys
    nothing) and, in auto mode, only when an exported wall deadline
    says the monolith's predicted compile does not fit."""
    global _LADDER
    mode = os.environ.get("OCT_WARM_LADDER", "1")
    if mode == "0":
        return None
    if _LADDER is not None:
        return _LADDER
    if not _agg_enabled():
        return None
    from ..analysis import costmodel

    target = bucket_size(max_batch)
    rungs = tuple(r for r in costmodel.LADDER_RUNGS if r < target)
    if not rungs:
        return None
    graph = "aggregate_core"
    pred = costmodel.predicted_wall(graph)
    if mode != "force":
        deadline = costmodel.wall_deadline()
        if deadline is None or pred is None:
            return None
        if pred + costmodel.PREFLIGHT_MARGIN_S <= deadline - time.time():
            return None  # the monolith fits: compile it up front
    rung = costmodel.choose_rung(graph, rungs=rungs)
    _LADDER = WarmLadder(target, rung, graph, pred)
    return _LADDER


class PackedVerdicts:
    """Materialized packed window result: the u32 verdict bitmasks (and
    the scanned nonce carry, or the packed eta column) on host; the
    per-lane flags/eta/leader-value stay DEVICE-RESIDENT handles,
    transferred only by `full()` when the epilogue needs the exact
    per-lane slow path (a failing or ambiguous lane)."""

    def __init__(self, masks, b, impl, carried, nonces, eta_u8, handles):
        self.masks = masks  # [5, W] uint32
        self.b = b
        self.impl = impl
        self.carried = carried
        self.nonces = nonces  # (ev u8[32], ev_set, cand u8[32], cand_set) | None
        self.eta_u8 = eta_u8  # [b, 32] uint8 | None (scan-off mode)
        self._handles = handles  # (flags, eta, lv) device arrays
        self._full = None

    def _row_all_set(self, row: int) -> bool:
        full, rem = divmod(self.b, 32)
        w = self.masks[row]
        if full and not bool((w[:full] == np.uint32(0xFFFFFFFF)).all()):
            return False
        if rem:
            m = np.uint32((1 << rem) - 1)
            if np.uint32(w[full] & m) != m:
                return False
        return True

    def _row_none_set(self, row: int) -> bool:
        full, rem = divmod(self.b, 32)
        w = self.masks[row]
        if full and bool(w[:full].any()):
            return False
        if rem and np.uint32(w[full] & np.uint32((1 << rem) - 1)):
            return False
        return True

    def clean(self) -> bool:
        """True iff every real lane passed every check outright: rows
        ok_ocert/ok_kes/ok_vrf/ok_leader all set, leader_ambiguous clear."""
        return all(self._row_all_set(r) for r in range(4)) and (
            self._row_none_set(4)
        )

    def eta_bytes(self) -> np.ndarray:
        """[b, 32] uint8 eta column (fetches from device if the scan-off
        transfer did not already ship it)."""
        if self.eta_u8 is not None:
            return self.eta_u8
        _flags, eta, _lv = self._handles
        a = np.asarray(eta)
        a = a[:, : self.b].T if self.impl == "pk" else a[: self.b]
        return np.ascontiguousarray(a.astype(np.uint8))

    def full(self) -> Verdicts:
        """Transfer the per-lane arrays and rebuild the classic Verdicts
        (the slow-path contract of `_epilogue`/`_lane_error`)."""
        if self._full is None:
            flags, eta, lv = self._handles
            f = np.asarray(flags)
            b = self.b
            if self.impl == "pk":
                eta_np = np.ascontiguousarray(np.asarray(eta)[:, :b].T)
                lv_np = np.ascontiguousarray(np.asarray(lv)[:, :b].T)
            else:
                eta_np = np.asarray(eta)[:b]
                lv_np = np.asarray(lv)[:b]
            self._full = Verdicts(
                ok_ocert_sig=f[0, :b] != 0,
                ok_kes_sig=f[1, :b] != 0,
                ok_vrf=f[2, :b] != 0,
                ok_leader=f[3, :b] != 0,
                leader_ambiguous=f[4, :b] != 0,
                eta=eta_np,
                leader_value=lv_np,
            )
        return self._full


def materialize_verdicts(tagged, b):
    """Block on a dispatched window's device computation.

    Generic windows transfer the full Verdicts (the round-5 contract);
    packed windows transfer the verdict bitmasks plus either the scanned
    nonce carry (64 B) or the packed eta column — O(bits + one nonce)
    instead of O(lanes x 40 B) — and keep the per-lane arrays
    device-resident for the slow path.

    Aggregated windows ("agg"): when the bitmasks show the window clean
    (every lane passed its cheap checks AND the RLC aggregate was the
    identity), the result is used as-is. On ANY anomaly the aggregate's
    per-lane flags are meaningless (a single bad lane zeroes the ok rows
    of EVERY lane), so the window is re-dispatched through the unchanged
    per-lane stage kernels here — exact reference error taxonomy and
    lane isolation, at the cost of one extra round trip on the rare
    dirty window."""
    if not tagged.packed:
        out = tagged.out
        d2h = int(sum(x.nbytes for x in out))
        if tagged.impl == "pk":
            v = _pk_materialize(out, b)
        else:
            v = Verdicts(*(np.asarray(x)[:b] for x in out))
        _emit_transfer("materialize", lanes=b, d2h_bytes=d2h, packed=False)
        return v
    if tagged.impl == "agg":
        layout, parr, n_real, cargs, out = tagged.out
        pv = _materialize_packed(out, b, "pk", tagged.scan, tagged.carried)
        if pv.clean():
            return pv
        if BATCH_TRACER is not None:
            from ..utils.trace import AggRedispatch

            BATCH_TRACER(AggRedispatch(b))
        if _impl() == "pk":
            from ..ops.pk import kernels as pk_kernels

            out2 = pk_kernels.verify_praos_packed_split(
                layout, *parr, n_real, *cargs, scan=tagged.scan
            )
            impl2 = "pk"
        else:
            out2 = _jitted_packed_xla(layout, tagged.scan)(
                *parr, n_real, *cargs
            )
            impl2 = "xla"
        return _materialize_packed(out2, b, impl2, tagged.scan,
                                   tagged.carried)
    return _materialize_packed(tagged.out, b, tagged.impl, tagged.scan,
                               tagged.carried)


def _materialize_packed(out, b, impl, scan, carried):
    red, flags, eta, lv = out
    if scan:
        masks_d, ev, evs, cand, cands = red
        masks = np.asarray(masks_d)
        nonces_out = (
            np.ascontiguousarray(np.asarray(ev).astype(np.uint8)),
            bool(np.asarray(evs)),
            np.ascontiguousarray(np.asarray(cand).astype(np.uint8)),
            bool(np.asarray(cands)),
        )
        eta_u8 = None
        d2h = masks.nbytes + 2 * 32 + 2
    else:
        masks_d, eta_d = red
        masks = np.asarray(masks_d)
        eta_u8 = np.asarray(eta_d)[:b]
        nonces_out = None
        d2h = masks.nbytes + eta_u8.nbytes
    pv = PackedVerdicts(
        masks, b, impl, carried, nonces_out, eta_u8,
        (flags, eta, lv),
    )
    _emit_transfer("materialize", lanes=b, d2h_bytes=d2h, packed=True)
    return pv


def _epilogue_packed_fast(
    params: PraosParams,
    ticked: TickedPraosState,
    hvs: Sequence[HeaderView],
    pre: HostChecks,
    v: PackedVerdicts,
) -> BatchResult | None:
    """The packed-verdict fast path: when the bitmask shows every lane
    clean, no precheck error exists, and the stateful OCert
    counter-monotonicity gate passes, assemble the final state straight
    from the device-scanned nonces (or one vectorized host fold of the
    packed eta bytes) — no per-lane error reconstruction, no per-lane
    device columns transferred. Returns None when ANY gate trips; the
    caller then runs the exact sequential slow path on the full
    Verdicts, so failure semantics are byte-identical to the reference
    fold by construction."""
    if not v.clean():
        return None
    if any(e is not None for e in pre.kes_window_errors):
        return None
    if any(e is not None for e in pre.vrf_lookup_errors):
        return None
    st = ticked.state
    lview = ticked.ledger_view
    counters = dict(st.ocert_counters)
    for hv in hvs:
        hk = hash_key(hv.vk_cold)
        if not _counter_ok(
            _counter_m(hk, counters, lview.pool_distr), hv.ocert.counter
        ):
            return None  # slow path reconstructs the exact error
        counters[hk] = hv.ocert.counter
    if v.carried and v.nonces is not None:
        ev, evs, cand, cands = v.nonces
        evolving = ev.tobytes() if evs else None
        candidate = cand.tobytes() if cands else None
    else:
        evolving = st.evolving_nonce
        candidate = st.candidate_nonce
        etas = v.eta_bytes()
        for i, hv in enumerate(hvs):
            evolving = nonces.combine(evolving, etas[i].tobytes())
            first_next = params.first_slot_of(params.epoch_of(hv.slot) + 1)
            if hv.slot + params.stability_window < first_next:
                candidate = evolving
    state = PraosState(
        last_slot=hvs[-1].slot,
        ocert_counters=counters,
        evolving_nonce=evolving,
        candidate_nonce=candidate,
        epoch_nonce=st.epoch_nonce,
        lab_nonce=nonces.prev_hash_to_nonce(hvs[-1].prev_hash),
        last_epoch_block_nonce=st.last_epoch_block_nonce,
    )
    return BatchResult(state, len(hvs), None, None)


def _verdicts_clean(v, b: int) -> bool:
    """Every real lane passed every check outright (no ambiguity)."""
    if isinstance(v, PackedVerdicts):
        return v.clean()
    return bool(
        np.asarray(v.ok_ocert_sig)[:b].all()
        and np.asarray(v.ok_kes_sig)[:b].all()
        and np.asarray(v.ok_vrf)[:b].all()
        and np.asarray(v.ok_leader)[:b].all()
        and not np.asarray(v.leader_ambiguous)[:b].any()
    )


def _epilogue_columns_fast(
    params: PraosParams,
    ticked: TickedPraosState,
    vc: ViewColumns,
    pre: HostChecks,
    v,
) -> BatchResult | None:
    """The columnar all-clean epilogue: counter monotonicity checked per
    unique pool over whole column slices, the candidate-nonce gate
    computed as one vectorized window compare, and the final state
    assembled without materializing a single HeaderView. Returns None
    when ANY gate trips (verdict anomaly, precheck error, counter
    violation, no pool dedup available) — the caller falls back to the
    exact per-header reference fold, so failure semantics are untouched.

    The evolving/candidate nonce fold is the device-scanned carry when
    the window rode the packed nonce scan; otherwise the sequential
    Blake2b fold over the eta column runs here — a hash chain is
    inherently per-header (COVERAGE.md §5.11)."""
    b = len(vc)
    if not isinstance(pre, ColumnChecks) or pre.any_errors():
        return None
    if not _verdicts_clean(v, b):
        return None
    st = ticked.state
    lview = ticked.ledger_view
    counters = dict(st.ocert_counters)
    cnt = vc.ocert_counter
    inv = pre.uniq_inv
    for j, hk in enumerate(pre.uniq_hk):
        m = _counter_m(hk, counters, lview.pool_distr)
        if m is None:
            return None
        cs = cnt[inv == j]
        d = np.diff(cs)
        if not (
            m <= cs[0] <= m + 1 and (d >= 0).all() and (d <= 1).all()
        ):
            return None
        counters[hk] = int(cs[-1])

    carried = isinstance(v, PackedVerdicts) and v.carried and v.nonces is not None
    if carried:
        ev, evs, cand, cands = v.nonces
        evolving = ev.tobytes() if evs else None
        candidate = cand.tobytes() if cands else None
    else:
        etas = (
            v.eta_bytes() if isinstance(v, PackedVerdicts)
            else np.ascontiguousarray(np.asarray(v.eta).astype(np.uint8))
        )
        first_next = (vc.slot // params.epoch_length + 1) * params.epoch_length
        within = vc.slot + params.stability_window < first_next
        w_idx = np.flatnonzero(within)
        k = int(w_idx[-1]) if w_idx.size else -1
        evolving = st.evolving_nonce
        candidate = st.candidate_nonce
        data = etas.tobytes()
        for i in range(k + 1):
            evolving = nonces.combine(evolving, data[32 * i : 32 * i + 32])
        if k >= 0:
            candidate = evolving
        for i in range(k + 1, b):
            evolving = nonces.combine(evolving, data[32 * i : 32 * i + 32])

    last = b - 1
    prev = vc.prev_hash[last].tobytes() if vc.has_prev[last] else None
    state = PraosState(
        last_slot=int(vc.slot[last]),
        ocert_counters=counters,
        evolving_nonce=evolving,
        candidate_nonce=candidate,
        epoch_nonce=st.epoch_nonce,
        lab_nonce=nonces.prev_hash_to_nonce(prev),
        last_epoch_block_nonce=st.last_epoch_block_nonce,
    )
    return BatchResult(state, b, None, None)


def _epilogue(
    params: PraosParams,
    ticked: TickedPraosState,
    hvs: "Sequence[HeaderView] | ViewColumns",
    pre: HostChecks,
    v: Verdicts,
    collect_states: bool = False,
    lane_error=None,
) -> BatchResult:
    """Sequential epilogue: counters + nonce fold, stop at first failure.

    `lane_error` defaults to the Praos `_lane_error`; TPraos passes an
    overlay-aware variant (protocol/tpraos.py). A PackedVerdicts `v`
    first tries the bitmask fast path (_epilogue_packed_fast) and only
    materializes the per-lane columns when a gate trips. A ViewColumns
    window first tries the fully-columnar fast path; HeaderViews
    materialize only when a gate trips (anomaly windows — the exact
    per-header reference fold)."""
    columns_declined = False
    if isinstance(hvs, ViewColumns):
        if lane_error is None and not collect_states and len(hvs):
            res = _epilogue_columns_fast(params, ticked, hvs, pre, v)
            if res is not None:
                return res
            columns_declined = True
        hvs = hvs.views()
    if isinstance(v, PackedVerdicts):
        # a declined columnar fast path already proved a gate trips —
        # the packed fast path checks the equivalent gates and would
        # burn O(lanes) re-proving it before the slow path
        if (lane_error is None and not collect_states and hvs
                and not columns_declined):
            res = _epilogue_packed_fast(params, ticked, hvs, pre, v)
            if res is not None:
                return res
        v = v.full()
    if lane_error is None:
        lane_error = _lane_error
    lview = ticked.ledger_view
    eta0 = ticked.state.epoch_nonce
    st = ticked.state
    counters = dict(st.ocert_counters)
    evolving = st.evolving_nonce
    candidate = st.candidate_nonce
    lab = st.lab_nonce
    last_slot = st.last_slot
    states_out: list | None = [] if collect_states else None
    # one array conversion for the whole batch (a per-row astype cost
    # ~2us/header in the fold)
    etas = np.ascontiguousarray(np.asarray(v.eta).astype(np.uint8))
    # vectorized all-clear gate for the DEFAULT lane semantics: lanes
    # where every verdict bit is set and no precomputed error exists
    # only need the stateful counter-monotonicity check — `lane_error`
    # is the slow path that reconstructs the exact reference error.
    # (TPraos passes its own lane_error with different counter
    # semantics: it always takes the full path.)
    if lane_error is _lane_error:
        fast_ok = (
            np.asarray(v.ok_ocert_sig) & np.asarray(v.ok_kes_sig)
            & np.asarray(v.ok_vrf) & np.asarray(v.ok_leader)
            & ~np.asarray(v.leader_ambiguous)
        ).tolist()
    else:
        fast_ok = None
    for i, hv in enumerate(hvs):
        if (
            fast_ok is not None
            and fast_ok[i]
            and pre.kes_window_errors[i] is None
            and pre.vrf_lookup_errors[i] is None
        ):
            hk = hash_key(hv.vk_cold)
            m = _counter_m(hk, counters, lview.pool_distr)
            if _counter_ok(m, hv.ocert.counter):
                err = None
            else:
                err = lane_error(params, lview, eta0, hv, pre, v, i, counters)
        else:
            err = lane_error(params, lview, eta0, hv, pre, v, i, counters)
        if err is not None:
            state = PraosState(
                last_slot=last_slot,
                ocert_counters=counters,
                evolving_nonce=evolving,
                candidate_nonce=candidate,
                epoch_nonce=st.epoch_nonce,
                lab_nonce=lab,
                last_epoch_block_nonce=st.last_epoch_block_nonce,
            )
            return BatchResult(state, i, err, states_out)
        # reupdate bookkeeping (Praos.hs:468-502) with the device-computed
        # eta (Blake2b² range extension)
        eta = etas[i].tobytes()
        evolving = nonces.combine(evolving, eta)
        slot = hv.slot
        first_next = params.first_slot_of(params.epoch_of(slot) + 1)
        if slot + params.stability_window < first_next:
            candidate = evolving
        lab = nonces.prev_hash_to_nonce(hv.prev_hash)
        counters[hash_key(hv.vk_cold)] = hv.ocert.counter
        last_slot = slot
        if states_out is not None:
            states_out.append(
                PraosState(
                    last_slot=last_slot,
                    ocert_counters=dict(counters),
                    evolving_nonce=evolving,
                    candidate_nonce=candidate,
                    epoch_nonce=st.epoch_nonce,
                    lab_nonce=lab,
                    last_epoch_block_nonce=st.last_epoch_block_nonce,
                )
            )

    state = PraosState(
        last_slot=last_slot,
        ocert_counters=counters,
        evolving_nonce=evolving,
        candidate_nonce=candidate,
        epoch_nonce=st.epoch_nonce,
        lab_nonce=lab,
        last_epoch_block_nonce=st.last_epoch_block_nonce,
    )
    return BatchResult(state, len(hvs), None, states_out)


def validate_chain(
    params: PraosParams,
    ledger_view_for_epoch,
    state: PraosState,
    hvs: Sequence[HeaderView],
    max_batch: int = 8192,
    backend: str = "device",
    pipeline_depth: int = 3,  # 2 windows hide staging behind the device;
    # the third absorbs the shorter epoch-tail batches (6144-lane
    # buckets) without a bubble. ~4 MB staged (packed; ~14 MB on the
    # generic fallback) + ~26 MB on-device per window — far under HBM
    # at depth 3.
    mesh=None,  # backend="sharded": the jax.sharding.Mesh (None = all devices)
) -> BatchResult:
    """Validate an arbitrary run of headers, segmenting at epoch
    boundaries (and at `max_batch` within an epoch) per SURVEY.md §5.7.

    `ledger_view_for_epoch(epoch) -> LedgerView` supplies the forecastable
    per-epoch pool distribution (constant within an epoch).

    Device backend: up to `pipeline_depth` windows of the same epoch are
    in flight at once — window w+1 is staged (host CBOR→SoA + H2D) while
    window w executes, because staging depends only on the epoch nonce.
    The pipeline drains at epoch boundaries (the next epoch's nonce needs
    the previous epoch's fold) and on the first invalid header (in-flight
    successors are discarded, exactly like queued blocks after a failed
    chain selection in the reference's add-block queue).
    """
    # one worker thread owns the BLOCKING device reads: the main thread
    # keeps staging/dispatching while the worker waits, so host staging
    # hides behind device execution even when the backend only makes
    # progress under a blocking read (observed through the remote-TPU
    # tunnel: wall == stage + device with same-thread materialize,
    # scripts/profile_replay.py r5)
    pool = None
    if backend == "device":
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=1)
    try:
        return _validate_chain_loop(
            params, ledger_view_for_epoch, state, hvs, max_batch, backend,
            pipeline_depth, mesh, pool,
        )
    finally:
        if pool is not None:
            # cancel_futures: on an early error return the queued
            # materialize futures belong to DISCARDED windows — without
            # it the worker keeps issuing blocking device reads for
            # results nobody wants and the atexit join stalls exit
            pool.shutdown(wait=False, cancel_futures=True)


def _epoch_segments_idx(params, hvs) -> list[tuple[int, int, int]]:
    """[(epoch, start, end)] index segmentation at epoch boundaries —
    one vectorized pass for ViewColumns, the per-header walk for lists."""
    n = len(hvs)
    if n == 0:
        return []
    if isinstance(hvs, ViewColumns):
        epochs = hvs.slot // params.epoch_length
        cuts = np.flatnonzero(np.diff(epochs)) + 1
        bounds = [0, *cuts.tolist(), n]
        return [
            (int(epochs[bounds[k]]), bounds[k], bounds[k + 1])
            for k in range(len(bounds) - 1)
        ]
    segments = []
    i = 0
    while i < n:
        epoch = params.epoch_of(hvs[i].slot)
        j = i
        while j < n and params.epoch_of(hvs[j].slot) == epoch:
            j += 1
        segments.append((epoch, i, j))
        i = j
    return segments


def _proof_break(hvs, w: int, j: int) -> int:
    """First index in (w, j) where the VRF proof format changes (a
    window must stage one uniform proof column), else j."""
    if isinstance(hvs, ViewColumns):
        pl = hvs.vrf_proof_len
        diff = np.flatnonzero(pl[w + 1 : j] != pl[w])
        return w + 1 + int(diff[0]) if diff.size else j
    plen = len(hvs[w].vrf_proof)
    for k in range(w + 1, j):
        if len(hvs[k].vrf_proof) != plen:
            return k
    return j


def _validate_chain_loop(
    params, ledger_view_for_epoch, state, hvs, max_batch, backend,
    pipeline_depth, mesh, pool,
):
    from ..obs import recovery as _recovery
    from ..testing import chaos as _chaos

    total_valid = 0
    i = 0
    n = len(hvs)
    win_idx = 0  # retire-order window index (RecoveryEvent / checkpoints)
    if backend != "device":
        for epoch, i, seg_end in _epoch_segments_idx(params, hvs):
            lview = ledger_view_for_epoch(epoch)
            while i < seg_end:
                j = min(i + max_batch, seg_end)
                ticked = praos.tick(params, lview, _slot_at(hvs, i), state)
                try:
                    res = validate_batch(
                        params, ticked, hvs[i:j], backend=backend, mesh=mesh
                    )
                except Exception as e:  # noqa: BLE001 — supervisor gates
                    # the degradation ladder (obs/recovery.py): re-raises
                    # unrecoverable classes / OCT_RECOVERY=0 unchanged
                    res = _recovery.supervisor().recover_window(
                        params, ticked, hvs[i:j], e, backend=backend,
                        mesh=mesh, window=win_idx,
                    )
                state = res.state
                total_valid += res.n_valid
                if res.error is not None:
                    return BatchResult(state, total_valid, res.error)
                # crash-consistent progress record per retired window
                # (one None check when OCT_CHECKPOINT is unset), THEN
                # the sigkill seam — a chaos kill lands AFTER the
                # checkpoint, the exactly-once window boundary
                _recovery.note_window(state, res.n_valid)
                _chaos.fire("retire")
                win_idx += 1
                i = j
        return BatchResult(state, total_valid, None)

    # Device backend: ONE pipeline across epoch boundaries. Staging a
    # window needs only (epoch nonce, ledger view); the next epoch's
    # nonce is tick's rotation combine(candidate, last_epoch_block_nonce)
    # (Praos.hs:407-432), whose inputs are final well before the current
    # epoch drains: candidate_nonce freezes at the stability window
    # (last update from a header with slot < first_slot(e+1) - 3k/f,
    # Praos.hs:497) and last_epoch_block_nonce was latched at the
    # PREVIOUS boundary. So once the fold retires past the freeze slot,
    # the next epoch's first windows dispatch while this epoch's tail is
    # still on device — no drain bubble per boundary (~one batch wall
    # each, ~46 boundaries on the 1M bench chain). The retire-time tick
    # asserts the staged nonce byte-for-byte.
    from collections import deque

    segments = _epoch_segments_idx(params, hvs)

    lviews: dict[int, object] = {}

    def lview_for(s: int):
        if s not in lviews:
            lviews[s] = ledger_view_for_epoch(segments[s][0])
        return lviews[s]

    eta_known: dict[int, object] = {}
    if segments:
        eta_known[0] = praos.tick(
            params, lview_for(0), _slot_at(hvs, segments[0][1]), state
        ).state.epoch_nonce

    inflight: deque = deque()  # (seg_idx, window_hvs, window_start, pre, future)
    # windows staged (possibly on the producer thread) but not yet
    # dispatched: (seg_idx, window_hvs, window_start, staged-or-future)
    staged: deque = deque()
    s_stage = 0  # segment currently being staged
    w = segments[0][1] if segments else 0
    retired = 0  # index of the next header to retire
    # the on-device nonce-scan carry chain: each packed window's scan
    # starts from the previous window's device carry (tick never touches
    # evolving/candidate, so the chain crosses epoch boundaries). A
    # generic-fallback window breaks the chain; it re-seeds from the
    # host-folded state once the pipeline drains.
    carry = _state_carry(state)
    carry_ok = True
    # warm-while-serving compile ladder: while the production-bucket
    # aggregate monolith compiles on a background thread, windows slice
    # at the rung lane cap; the loop re-tiles the moment it lands
    # (poll_swap after each retire). Window re-tiling never changes
    # verdicts — validate_batch is segmentation-invariant.
    ladder = _maybe_ladder(max_batch)
    # producer thread: prechecks + packed staging + padding run ahead
    # of dispatch (prepare_window is fold-independent), overlapping the
    # staging wall with device compute and the retire-side epilogue.
    # Backpressure at pipeline_depth on EACH side of the double buffer:
    # up to pipeline_depth windows staged-but-undispatched AND up to
    # pipeline_depth dispatched-but-unretired (without the thread the
    # staged deque never exceeds one window, so the memory bound is the
    # round-9 one; with it, at most 2 x pipeline_depth windows are
    # alive — ~8 MB packed each at 8192 lanes, still far under HBM).
    stage_pool = None
    if _stage_thread_enabled():
        from concurrent.futures import ThreadPoolExecutor

        stage_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="oct-stage"
        )
    try:
        return _device_loop(
            params, hvs, max_batch, pipeline_depth, pool, stage_pool,
            segments, lview_for, eta_known, inflight, staged, s_stage, w,
            retired, carry, carry_ok, ladder, state, total_valid, n,
        )
    finally:
        if stage_pool is not None:
            # discarded staging futures belong to windows nobody will
            # dispatch (early error return) — never block exit on them
            stage_pool.shutdown(wait=False, cancel_futures=True)


def _device_loop(
    params, hvs, max_batch, pipeline_depth, pool, stage_pool,
    segments, lview_for, eta_known, inflight, staged, s_stage, w,
    retired, carry, carry_ok, ladder, state, total_valid, n,
):
    def enqueue_staging():
        nonlocal s_stage, w
        cap = ladder.cap() if ladder is not None else None
        while (
            s_stage < len(segments)
            and (
                # producer thread: stage ahead up to pipeline_depth
                # regardless of the in-flight side (double buffer)
                len(staged) < pipeline_depth
                if stage_pool is not None
                # inline (OCT_STAGE_THREAD=0): stage only what can
                # dispatch immediately — the round-9 loop exactly
                else not staged and len(inflight) < pipeline_depth
            )
            and s_stage in eta_known
        ):
            _, _, seg_end = segments[s_stage]
            j_full = min(w + max_batch, seg_end)
            j = j_full
            if cap is not None and j - w > cap:
                j = w + cap
                ladder.note_engaged_once()
            # a window must stage a uniform proof column: break at the
            # first 80/128-byte format change (the reference fold
            # length-dispatches per header, so mixed chains stay valid;
            # segmentation never changes verdicts or the first error)
            j = _proof_break(hvs, w, j)
            whvs = hvs[w:j]
            if stage_pool is not None:
                item = stage_pool.submit(
                    prepare_window, params, lview_for(s_stage),
                    eta_known[s_stage], whvs,
                )
            else:
                item = prepare_window(
                    params, lview_for(s_stage), eta_known[s_stage], whvs
                )
            staged.append((s_stage, whvs, w, item))
            w = j
            if w >= seg_end:
                s_stage += 1
                if s_stage < len(segments):
                    w = segments[s_stage][1]

    from ..obs import recovery as _recovery
    from ..testing import chaos as _chaos

    def _queue_failure(exc: BaseException) -> bool:
        """True when the supervisor may absorb `exc`: the window rides
        the pipeline as a _FailedDispatch and recovers at its retire
        slot. False (disabled / unrecoverable class) -> raise-through,
        the pre-PR-12 behavior."""
        return _recovery.enabled() and _recovery.recoverable(exc)

    def drain_dispatch():
        # dispatch staged windows IN ORDER (the device carry chains
        # dispatch-to-dispatch) while the in-flight side of the double
        # buffer has room: drain every ready one; when nothing is in
        # flight, block on the staging head — otherwise let a
        # materialize retire while the producer keeps staging
        nonlocal carry, carry_ok
        while staged and len(inflight) < pipeline_depth:
            s_w, whvs_w, w_start_w, item = staged[0]
            if stage_pool is not None and hasattr(item, "result"):
                if not item.done() and inflight:
                    break
                try:
                    item = item.result()
                except Exception as e:  # noqa: BLE001 — gated below
                    # the staging producer died mid-prepare: the window
                    # recovers at its retire slot (full re-validation)
                    staged.popleft()
                    if not _queue_failure(e):
                        raise
                    carry_ok = False
                    inflight.append(
                        (s_w, whvs_w, w_start_w, None, None,
                         _FailedDispatch(e))
                    )
                    continue
            staged.popleft()
            try:
                pre, out, b, carry_out = dispatch_prepared(
                    item, carry if carry_ok else None, ladder
                )
            except Exception as e:  # noqa: BLE001 — gated below
                if not _queue_failure(e):
                    raise
                carry_ok = False
                inflight.append(
                    (s_w, whvs_w, w_start_w, None, None, _FailedDispatch(e))
                )
                continue
            if carry_out is None:
                carry_ok = False
            else:
                carry = carry_out
            inflight.append(
                (s_w, whvs_w, w_start_w, pre, out.meta,
                 pool.submit(materialize_verdicts, out, b))
            )

    win_retired = 0  # retire-order window index (recovery/checkpoints)
    while retired < n or inflight or staged:
        # alternate stage/dispatch to a FIXPOINT: the inline
        # (OCT_STAGE_THREAD=0) mode stages one window at a time and
        # dispatches it immediately, so the in-flight side still fills
        # to pipeline_depth exactly as the round-9 loop did (staging a
        # single window per outer iteration would cap the pipeline at
        # ONE window in flight); the threaded mode reaches the same
        # fixpoint in one or two rounds
        while True:
            before = (len(staged), len(inflight), w, s_stage)
            enqueue_staging()
            drain_dispatch()
            if (len(staged), len(inflight), w, s_stage) == before:
                break

        if not inflight:
            # eta for s_stage not derivable before its predecessor fully
            # retires (no header past the freeze slot) — the retire path
            # below will publish it; nothing staged or in flight means we
            # can compute it right now from the fully-folded state
            eta_known[s_stage] = praos.tick(
                params, lview_for(s_stage),
                _slot_at(hvs, segments[s_stage][1]), state,
            ).state.epoch_nonce
            if not carry_ok:
                carry = _state_carry(state)
                carry_ok = True
            continue

        # refill the staging side BEFORE blocking on the retire below:
        # dispatching just freed buffer room, and the producer must be
        # working through the device wait — without this the staging
        # thread idled during every retire block (the whole overlap)
        enqueue_staging()

        s_b, whvs, w_start, pre, meta, fut = inflight.popleft()
        t_m0 = time.monotonic()
        fail: BaseException | None = None
        v = None
        try:
            with _enclose("materialize"):
                v = fut.result()
        except Exception as e:  # noqa: BLE001 — gated by _queue_failure
            if not _queue_failure(e):
                raise
            fail = e
        t_m1 = time.monotonic()
        ticked = praos.tick(params, lview_for(s_b), _slot_at(whvs, 0), state)
        if w_start == segments[s_b][1]:
            # first batch of a segment staged with a LOOKAHEAD nonce:
            # the real rotation must agree (internal invariant)
            assert ticked.state.epoch_nonce == eta_known[s_b], (
                "lookahead epoch nonce mismatch"
            )
        t_e0 = time.monotonic()
        if fail is None:
            try:
                with _enclose("epilogue"):
                    res = _epilogue(params, ticked, whvs, pre, v)
            except Exception as e:  # noqa: BLE001 — gated below
                if not _queue_failure(e):
                    raise
                fail = e
        if fail is not None:
            # the supervisor re-validates JUST this window down the
            # degradation ladder (retry -> stage-split -> xla-twin ->
            # host reference); any rung's result IS the window's
            # verdict. The device carry chain may have threaded through
            # the failed computation, so it re-seeds from the host fold
            # once the pipeline drains (carry_ok gate below).
            carry_ok = False
            res = _recovery.supervisor().recover_window(
                params, ticked, whvs, fail, backend="device",
                window=win_retired,
            )
        state = res.state
        total_valid += res.n_valid
        _emit_window_span(
            meta, len(whvs), res.n_valid, res.error is not None,
            t_m0, t_m1, t_e0, time.monotonic(),
        )
        if res.error is not None:
            return BatchResult(state, total_valid, res.error)
        retired += len(whvs)
        # progress record BEFORE the sigkill seam: a chaos (or real)
        # kill after this point loses nothing — the resume re-seeds
        # from exactly this retired window (obs/recovery.py)
        _recovery.note_window(state, res.n_valid)
        _chaos.fire("retire")
        win_retired += 1
        if ladder is not None:
            # the background production compile landed: record the swap
            # — the NEXT slices re-tile onto the production bucket
            ladder.poll_swap()
        if not carry_ok and not inflight:
            # the generic window that broke the chain has retired and
            # nothing dispatched after it is in flight: re-seed the
            # device fold from the now-exact host state
            carry = _state_carry(state)
            carry_ok = True

        nxt = s_b + 1
        if nxt < len(segments) and nxt not in eta_known:
            epoch, _, seg_end = segments[s_b]
            if retired >= seg_end:
                eta_known[nxt] = praos.tick(
                    params, lview_for(nxt), _slot_at(hvs, segments[nxt][1]),
                    state,
                ).state.epoch_nonce
            else:
                freeze = (
                    params.first_slot_of(epoch + 1)
                    - params.stability_window
                )
                if _slot_at(hvs, retired) >= freeze:
                    # candidate is frozen and the LAB component was
                    # latched a boundary ago: the rotation is decided
                    eta_known[nxt] = nonces.combine(
                        state.candidate_nonce,
                        state.last_epoch_block_nonce,
                    )
    return BatchResult(state, total_valid, None)
