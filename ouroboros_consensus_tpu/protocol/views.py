"""Praos header / ledger views — the exact inputs of header validation.

Reference: Praos/Views.hs:22-51 (`HeaderView`, `LedgerView`) and
cardano-protocol-tpraos `OCert`. The views isolate validation from header
serialisation: the ChainSync client, ChainSel and db-analyser all validate
through these, and the SoA batch staging (protocol/batch.py) columnarizes
lists of them for the device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from functools import lru_cache
from typing import Mapping

from ..ops.host.hashes import blake2b_224, blake2b_256


@lru_cache(maxsize=65536)
def hash_key(vk_cold: bytes) -> bytes:
    """KeyHash (Blake2b-224) of an Ed25519 cold verification key.

    Cached: a chain has few distinct issuers but the replay hot path
    asks several times per header (staging, counter fold, views)."""
    return blake2b_224(vk_cold)


def hash_vrf_vk(vrf_vk: bytes) -> bytes:
    """Blake2b-256 hash of a VRF verification key (pool registration)."""
    return blake2b_256(vrf_vk)


@dataclass(frozen=True)
class OCert:
    """Operational certificate: cold key delegates to a hot KES key.

    Reference: cardano-protocol-tpraos `OCert.OCert`; the DSIGN-signable
    representation is vk_hot ‖ counter_be8 ‖ kes_period_be8
    (`ocertToSignable`).
    """

    vk_hot: bytes  # 32 — KES root verification key
    counter: int  # issue number
    kes_period: int  # start period c0
    sigma: bytes  # 64 — Ed25519 signature by the cold key

    def signable(self) -> bytes:
        return (
            self.vk_hot
            + self.counter.to_bytes(8, "big")
            + self.kes_period.to_bytes(8, "big")
        )


@dataclass(frozen=True)
class HeaderView:
    """Exactly the header fields validation consumes (Praos/Views.hs:22-39)."""

    prev_hash: bytes | None  # None = genesis
    vk_cold: bytes  # 32 — issuer cold key
    vrf_vk: bytes  # 32
    vrf_output: bytes  # 64 — certified VRF output beta
    vrf_proof: bytes  # ECVRF proof pi: 80 (draft-03) or 128 (batch-compat)
    ocert: OCert
    slot: int
    signed_bytes: bytes  # KES-signed representation (header body CBOR)
    kes_sig: bytes  # CompactSum signature (64 + 32 + 32*depth)


@dataclass(frozen=True)
class IndividualPoolStake:
    """Relative stake + registered VRF key hash (SL.IndividualPoolStake)."""

    stake: Fraction
    vrf_key_hash: bytes  # Blake2b-256 of the pool's VRF vk


@dataclass(frozen=True)
class LedgerView:
    """Praos ledger view (Praos/Views.hs:41-51): what the protocol needs
    from the ledger — the pool stake distribution (+ size limits used by
    envelope checks)."""

    pool_distr: Mapping[bytes, IndividualPoolStake]  # KeyHash -> stake
    max_header_size: int = 1100
    max_body_size: int = 90112
    protocol_version: tuple[int, int] = (9, 0)
