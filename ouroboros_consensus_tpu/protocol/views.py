"""Praos header / ledger views — the exact inputs of header validation.

Reference: Praos/Views.hs:22-51 (`HeaderView`, `LedgerView`) and
cardano-protocol-tpraos `OCert`. The views isolate validation from header
serialisation: the ChainSync client, ChainSel and db-analyser all validate
through these, and the SoA batch staging (protocol/batch.py) columnarizes
lists of them for the device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from fractions import Fraction
from functools import lru_cache
from typing import Mapping, Sequence

import numpy as np

from ..ops.host.hashes import blake2b_224, blake2b_256


@lru_cache(maxsize=65536)
def hash_key(vk_cold: bytes) -> bytes:
    """KeyHash (Blake2b-224) of an Ed25519 cold verification key.

    Cached: a chain has few distinct issuers but the replay hot path
    asks several times per header (staging, counter fold, views)."""
    return blake2b_224(vk_cold)


def hash_vrf_vk(vrf_vk: bytes) -> bytes:
    """Blake2b-256 hash of a VRF verification key (pool registration)."""
    return blake2b_256(vrf_vk)


@dataclass(frozen=True)
class OCert:
    """Operational certificate: cold key delegates to a hot KES key.

    Reference: cardano-protocol-tpraos `OCert.OCert`; the DSIGN-signable
    representation is vk_hot ‖ counter_be8 ‖ kes_period_be8
    (`ocertToSignable`).
    """

    vk_hot: bytes  # 32 — KES root verification key
    counter: int  # issue number
    kes_period: int  # start period c0
    sigma: bytes  # 64 — Ed25519 signature by the cold key

    def signable(self) -> bytes:
        return (
            self.vk_hot
            + self.counter.to_bytes(8, "big")
            + self.kes_period.to_bytes(8, "big")
        )


@dataclass(frozen=True)
class HeaderView:
    """Exactly the header fields validation consumes (Praos/Views.hs:22-39)."""

    prev_hash: bytes | None  # None = genesis
    vk_cold: bytes  # 32 — issuer cold key
    vrf_vk: bytes  # 32
    vrf_output: bytes  # 64 — certified VRF output beta
    vrf_proof: bytes  # ECVRF proof pi: 80 (draft-03) or 128 (batch-compat)
    ocert: OCert
    slot: int
    signed_bytes: bytes  # KES-signed representation (header body CBOR)
    kes_sig: bytes  # CompactSum signature (64 + 32 + 32*depth)


@dataclass
class ViewColumns:
    """A columnar window of header views — the SoA twin of
    `Sequence[HeaderView]` that the hot path (protocol/batch,
    tools/db_analyser) flows END-TO-END without materializing per-header
    Python objects (~20-26 µs/header of interpreter tax at the 1M bench
    scale, PERF.md round-8).

    Per-lane data lives in row-major numpy columns; windowing is array
    slicing (`vc[i:j]` -> ViewColumns sharing the underlying buffers).
    `HeaderView` objects are built LAZILY — `vc[i]` / `vc.views()` — and
    only on the paths that genuinely need per-header objects: anomaly
    lanes (exact reference-error reconstruction), the generic-fallback
    staging path, and the sequential reference fold.

    Construction REQUIRES rectangular columns: `from_header_columns` /
    `from_views` return None when the KES-signed bodies (or signature
    spans) are not uniform width, and the caller streams plain
    HeaderView lists for that window instead — the columnar type never
    carries ragged data.
    """

    slot: np.ndarray  # [n] int64
    prev_hash: np.ndarray  # [n, 32] uint8
    has_prev: np.ndarray  # [n] uint8 — 0 = genesis (prev_hash is None)
    vk_cold: np.ndarray  # [n, 32] uint8
    vrf_vk: np.ndarray  # [n, 32] uint8
    vrf_output: np.ndarray  # [n, 64] uint8
    vrf_proof: np.ndarray  # [n, 128] uint8, zero-padded to the widest format
    vrf_proof_len: np.ndarray  # [n] int64 — 80 (draft-03) or 128 (bc)
    ocert_vk_hot: np.ndarray  # [n, 32] uint8
    ocert_counter: np.ndarray  # [n] int64
    ocert_kes_period: np.ndarray  # [n] int64
    ocert_sigma: np.ndarray  # [n, 64] uint8
    kes_sig: np.ndarray  # [n, 96 + 32*depth] uint8
    signed_bytes: np.ndarray  # [n, body_len] uint8

    def __len__(self) -> int:
        return int(self.slot.shape[0])

    def __getitem__(self, i):
        if isinstance(i, slice):
            return ViewColumns(*(
                getattr(self, f.name)[i] for f in fields(self)
            ))
        return self.view(int(i))

    def view(self, i: int) -> HeaderView:
        """Materialize ONE lane as a HeaderView (the lazy per-header
        path: error reconstruction, window-boundary peeks)."""
        return HeaderView(
            prev_hash=(
                self.prev_hash[i].tobytes() if self.has_prev[i] else None
            ),
            vk_cold=self.vk_cold[i].tobytes(),
            vrf_vk=self.vrf_vk[i].tobytes(),
            vrf_output=self.vrf_output[i].tobytes(),
            vrf_proof=self.vrf_proof[i, : int(self.vrf_proof_len[i])].tobytes(),
            ocert=OCert(
                self.ocert_vk_hot[i].tobytes(),
                int(self.ocert_counter[i]),
                int(self.ocert_kes_period[i]),
                self.ocert_sigma[i].tobytes(),
            ),
            slot=int(self.slot[i]),
            signed_bytes=self.signed_bytes[i].tobytes(),
            kes_sig=self.kes_sig[i].tobytes(),
        )

    def views(self) -> list[HeaderView]:
        """Materialize the whole window as HeaderViews (whole-column
        tobytes + bytes slicing — per-row numpy tobytes costs ~10x
        more). This IS the object tax; hot paths call it only on
        anomaly windows."""
        n = len(self)
        prev_b = np.ascontiguousarray(self.prev_hash).tobytes()
        cold_b = np.ascontiguousarray(self.vk_cold).tobytes()
        vrf_vk_b = np.ascontiguousarray(self.vrf_vk).tobytes()
        vrf_out_b = np.ascontiguousarray(self.vrf_output).tobytes()
        vrf_prf_b = np.ascontiguousarray(self.vrf_proof).tobytes()
        pw = self.vrf_proof.shape[1]  # row stride of the padded column
        vk_hot_b = np.ascontiguousarray(self.ocert_vk_hot).tobytes()
        sigma_b = np.ascontiguousarray(self.ocert_sigma).tobytes()
        kes_b = np.ascontiguousarray(self.kes_sig).tobytes()
        kw = self.kes_sig.shape[1]
        sgn_b = np.ascontiguousarray(self.signed_bytes).tobytes()
        sw = self.signed_bytes.shape[1]
        has_prev = self.has_prev.tolist()
        slots = self.slot.tolist()
        counters = self.ocert_counter.tolist()
        periods = self.ocert_kes_period.tolist()
        plens = self.vrf_proof_len.tolist()
        out = []
        for i in range(n):
            o32 = 32 * i
            out.append(HeaderView(
                prev_hash=prev_b[o32:o32 + 32] if has_prev[i] else None,
                vk_cold=cold_b[o32:o32 + 32],
                vrf_vk=vrf_vk_b[o32:o32 + 32],
                vrf_output=vrf_out_b[64 * i:64 * i + 64],
                vrf_proof=vrf_prf_b[pw * i:pw * i + plens[i]],
                ocert=OCert(
                    vk_hot_b[o32:o32 + 32],
                    counters[i],
                    periods[i],
                    sigma_b[64 * i:64 * i + 64],
                ),
                slot=slots[i],
                signed_bytes=sgn_b[sw * i:sw * (i + 1)],
                kes_sig=kes_b[kw * i:kw * (i + 1)],
            ))
        return out

    @classmethod
    def concat(cls, parts: Sequence["ViewColumns"]) -> "ViewColumns | None":
        """Concatenate same-shape windows (epoch segmentation across
        chunk files), or None when the parts' row widths differ (the
        caller falls back to a HeaderView list for that segment)."""
        if len(parts) == 1:
            return parts[0]
        if len({p.signed_bytes.shape[1] for p in parts}) > 1 or len(
            {p.kes_sig.shape[1] for p in parts}
        ) > 1:
            return None
        return cls(*(
            np.concatenate([getattr(p, f.name) for p in parts], axis=0)
            for f in fields(cls)
        ))

    @classmethod
    def from_header_columns(cls, hc, lo: int = 0, hi: int | None = None
                            ) -> "ViewColumns | None":
        """Build from (a range of) a native_loader.HeaderColumns chunk
        scan — pure array plumbing (the span matrices gather
        vectorized). None when the OCert sigma / KES signature /
        signed-body spans of the range are not uniform width (callers
        split at width changes via `pieces_from_header_columns`, or use
        the per-view path)."""
        from ..native_loader import _span_matrix

        hi = hc.n if hi is None else hi
        if lo == 0 and hi == hc.n:
            sigma, kes, body = (
                hc.ocert_sigma_mat, hc.kes_sig_mat, hc.signed_bytes_mat
            )
        else:
            buf = hc._buf_u8
            sigma = _span_matrix(buf, hc.sig_off[lo:hi], hc.sig_len[lo:hi])
            kes = _span_matrix(buf, hc.kes_off[lo:hi], hc.kes_len[lo:hi])
            body = _span_matrix(buf, hc.sgn_off[lo:hi], hc.sgn_len[lo:hi])
        if sigma is None or kes is None or body is None or sigma.shape[1] != 64:
            return None
        s = slice(lo, hi)
        return cls(
            slot=hc.slot[s],
            prev_hash=hc.prev_hash[s],
            has_prev=hc.has_prev[s],
            vk_cold=hc.issuer_vk[s],
            vrf_vk=hc.vrf_vk[s],
            vrf_output=hc.vrf_output[s],
            vrf_proof=hc.vrf_proof[s],
            vrf_proof_len=hc.vrf_proof_len[s],
            ocert_vk_hot=hc.ocert_vk[s],
            ocert_counter=hc.ocert_counter[s],
            ocert_kes_period=hc.ocert_kes_period[s],
            ocert_sigma=sigma,
            kes_sig=kes,
            signed_bytes=body,
        )

    @classmethod
    def pieces_from_header_columns(cls, hc) -> "list[ViewColumns] | None":
        """The chunk as a minimal list of rectangular ViewColumns
        pieces, split where any span width changes (CBOR integer-width
        steps move the signed-body length a few times per chain). None
        when even a uniform-width run cannot columnarize (malformed
        sigma width) — the caller streams per-view lists instead."""
        widths = np.stack([hc.sig_len, hc.kes_len, hc.sgn_len], axis=1)
        chg = np.flatnonzero((widths[1:] != widths[:-1]).any(axis=1)) + 1
        bounds = [0, *chg.tolist(), hc.n]
        out = []
        for k in range(len(bounds) - 1):
            vc = cls.from_header_columns(hc, bounds[k], bounds[k + 1])
            if vc is None:
                return None
            out.append(vc)
        return out

    @classmethod
    def from_views(cls, hvs: Sequence[HeaderView]) -> "ViewColumns | None":
        """Columnarize a HeaderView list (tests, synthetic chains).
        None when the views cannot form rectangular columns (mixed
        KES-signature widths)."""
        n = len(hvs)
        if n == 0:
            return None
        kw = len(hvs[0].kes_sig)
        if any(len(hv.kes_sig) != kw for hv in hvs):
            return None
        if any(len(hv.ocert.sigma) != 64 for hv in hvs):
            return None
        plen = np.asarray([len(hv.vrf_proof) for hv in hvs], np.int64)
        proof = np.zeros((n, 128), np.uint8)
        for i, hv in enumerate(hvs):
            proof[i, : plen[i]] = np.frombuffer(hv.vrf_proof, np.uint8)
        sw = len(hvs[0].signed_bytes)
        if any(len(hv.signed_bytes) != sw for hv in hvs):
            return None

        def col(get, w):
            return np.frombuffer(
                b"".join(get(hv) for hv in hvs), np.uint8
            ).reshape(n, w).copy()

        return cls(
            slot=np.asarray([hv.slot for hv in hvs], np.int64),
            prev_hash=col(
                lambda hv: hv.prev_hash if hv.prev_hash is not None
                else bytes(32), 32,
            ),
            has_prev=np.asarray(
                [hv.prev_hash is not None for hv in hvs], np.uint8
            ),
            vk_cold=col(lambda hv: hv.vk_cold, 32),
            vrf_vk=col(lambda hv: hv.vrf_vk, 32),
            vrf_output=col(lambda hv: hv.vrf_output, 64),
            vrf_proof=proof,
            vrf_proof_len=plen,
            ocert_vk_hot=col(lambda hv: hv.ocert.vk_hot, 32),
            ocert_counter=np.asarray(
                [hv.ocert.counter for hv in hvs], np.int64
            ),
            ocert_kes_period=np.asarray(
                [hv.ocert.kes_period for hv in hvs], np.int64
            ),
            ocert_sigma=col(lambda hv: hv.ocert.sigma, 64),
            kes_sig=col(lambda hv: hv.kes_sig, kw),
            signed_bytes=col(lambda hv: hv.signed_bytes, sw),
        )


@dataclass(frozen=True)
class IndividualPoolStake:
    """Relative stake + registered VRF key hash (SL.IndividualPoolStake)."""

    stake: Fraction
    vrf_key_hash: bytes  # Blake2b-256 of the pool's VRF vk


@dataclass(frozen=True)
class LedgerView:
    """Praos ledger view (Praos/Views.hs:41-51): what the protocol needs
    from the ledger — the pool stake distribution (+ size limits used by
    envelope checks)."""

    pool_distr: Mapping[bytes, IndividualPoolStake]  # KeyHash -> stake
    max_header_size: int = 1100
    max_body_size: int = 90112
    protocol_version: tuple[int, int] = (9, 0)
