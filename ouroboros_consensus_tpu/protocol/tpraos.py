"""TPraos: Transitional Praos — the Shelley-era protocol with the BFT
overlay schedule.

Reference: `ouroboros-consensus-protocol/src/.../Protocol/TPraos.hs`
(ConsensusProtocol instance :304-392). The reference delegates header
validation to the ledger package's PRTCL/OVERLAY STS rules
(`SL.updateChainDepState`, TPraos.hs:380); this module implements those
semantics directly against the same batched crypto backend the Praos
instance uses — the crypto hot path (OCert Ed25519, CompactSum KES,
ECVRF — Praos.hs:543,580,582) is IDENTICAL, only the leader rule
changes:

  * a fraction `d` (decentralization) of each epoch's slots form the
    OVERLAY schedule (Shelley `overlaySchedule`): position j of slot i
    advances when ceil((i+1)·d) crosses ceil(i·d);
  * every ascInv = ceil(1/f)-th overlay position is ACTIVE and assigned
    round-robin to a genesis delegate — that delegate must issue the
    block, with full VRF/KES/OCert checks but NO stake threshold
    (`pbftVrfChecks` vs `praosVrfChecks` in PRTCL);
  * other overlay positions are inactive: any block there is invalid;
  * non-overlay slots follow the ordinary Praos lottery.

`translate_state` is the TPraos→Praos ChainDepState translation the HFC
applies at the era boundary (Protocol/Praos/Translate.hs:1-101): the
nonces and operational-certificate counters carry over unchanged.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from fractions import Fraction
from typing import Mapping, Sequence

import numpy as np

from . import batch as pbatch
from . import nonces, praos, select
from .leader import check_leader_value
from .praos import (
    CryptoVerifier,
    HOST_VERIFIER,
    PraosParams,
    PraosState,
    PraosValidationError,
)
from .views import HeaderView, LedgerView, hash_key, hash_vrf_vk


# ---------------------------------------------------------------------------
# Parameters / state / views
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GenDeleg:
    """One genesis delegate (SL.GenDelegPair): the operational cold key
    and registered VRF key hash the overlay check matches against."""

    vk_cold: bytes
    vrf_key_hash: bytes


@dataclass(frozen=True)
class TPraosParams:
    """PraosParams + decentralization (TPraos.hs TPraosParams; `d` lives
    in the protocol parameters on-chain, here static per era)."""

    praos: PraosParams
    decentralization: Fraction  # d in [0, 1]; 0 = fully decentralized

    def __getattr__(self, name):
        return getattr(self.praos, name)


@dataclass(frozen=True)
class TPraosLedgerView(LedgerView):
    """LedgerView + the ordered genesis delegation map (SL.LedgerView
    lvGenDelegs)."""

    gen_delegs: Sequence[GenDeleg] = ()


@dataclass(frozen=True)
class TPraosState(PraosState):
    """ChainDepState (TPraos c) — the PRTCL state: same nonce/counter
    content as Praos (TPraos.hs:219, SL.ChainDepState)."""


@dataclass(frozen=True)
class TickedTPraosState:
    state: TPraosState
    ledger_view: TPraosLedgerView


# ---------------------------------------------------------------------------
# Overlay schedule (Shelley overlaySchedule / lookupInOverlaySchedule)
# ---------------------------------------------------------------------------


def _asc_inv(f: Fraction) -> int:
    return max(1, math.ceil(1 / f))


def overlay_position(params: TPraosParams, slot: int) -> int | None:
    """None if `slot` is not an overlay slot, else its overlay position
    within the epoch (isOverlaySlot: the ceil(i*d) step function
    advances exactly on overlay slots)."""
    d = params.decentralization
    if d == 0:
        return None
    i = slot - params.praos.first_slot_of(params.praos.epoch_of(slot))
    lo = math.ceil(i * d)
    hi = math.ceil((i + 1) * d)
    return lo if hi > lo else None


def overlay_slot_assignment(
    params: TPraosParams, n_delegs: int, slot: int
) -> tuple[bool, int | None] | None:
    """None = not an overlay slot; (False, None) = inactive overlay slot
    (must be empty); (True, j) = active, assigned to delegate j."""
    pos = overlay_position(params, slot)
    if pos is None:
        return None
    ai = _asc_inv(params.praos.active_slot_coeff)
    if pos % ai != 0 or n_delegs == 0:
        # no delegates registered: no overlay slot can ever be led
        return (False, None)
    return (True, (pos // ai) % n_delegs)


# ---------------------------------------------------------------------------
# Errors beyond the shared Praos taxonomy
# ---------------------------------------------------------------------------


@dataclass
class WrongGenesisDelegate(PraosValidationError):
    """An overlay block issued by someone other than the scheduled
    genesis delegate (OVERLAY WrongGenesisVRFKeyOVERLAY/NotPraosLeader)."""

    slot: int
    expected: bytes
    got: bytes


@dataclass
class NonActiveSlot(PraosValidationError):
    """A block in an inactive overlay slot (OVERLAY NonActiveSlotOVERLAY)."""

    slot: int


@dataclass
class WrongGenesisVRFKey(PraosValidationError):
    slot: int
    expected: bytes
    got: bytes


# ---------------------------------------------------------------------------
# tick / update / reupdate (host semantics)
# ---------------------------------------------------------------------------


def tick(
    params: TPraosParams, lview: TPraosLedgerView, slot: int, state: TPraosState
) -> TickedTPraosState:
    inner = praos.tick(params.praos, lview, slot, state)
    return TickedTPraosState(
        TPraosState(**vars(inner.state)), inner.ledger_view
    )


def _overlay_error(
    params: TPraosParams, lview: TPraosLedgerView, hv: HeaderView
) -> PraosValidationError | None:
    """The overlay-side replacement of the Praos pool lookup + threshold
    (lookupInOverlaySchedule + pbftVrfChecks). None when `hv.slot` is a
    non-overlay slot (caller falls through to the Praos rules)."""
    assign = overlay_slot_assignment(params, len(lview.gen_delegs), hv.slot)
    if assign is None:
        return None
    active, j = assign
    if not active:
        return NonActiveSlot(hv.slot)
    deleg = lview.gen_delegs[j]
    if hv.vk_cold != deleg.vk_cold:
        return WrongGenesisDelegate(hv.slot, deleg.vk_cold, hv.vk_cold)
    got_hash = hash_vrf_vk(hv.vrf_vk)
    if got_hash != deleg.vrf_key_hash:
        return WrongGenesisVRFKey(hv.slot, deleg.vrf_key_hash, got_hash)
    return False  # sentinel: overlay slot, delegate checks passed


def _validate_vrf_overlay_aware(
    params: TPraosParams,
    lview: TPraosLedgerView,
    epoch_nonce,
    hv: HeaderView,
    crypto: CryptoVerifier,
) -> None:
    err = _overlay_error(params, lview, hv)
    if err:  # a real error (False sentinel = overlay ok)
        raise err
    alpha = nonces.mk_input_vrf(hv.slot, epoch_nonce)
    if err is False:
        # active overlay slot: VRF proof verified, threshold skipped
        if not crypto.verify_vrf(hv.vrf_vk, hv.vrf_proof, alpha, hv.vrf_output):
            raise praos.VRFKeyBadProof(hv.slot, epoch_nonce)
        return
    # non-overlay slot: the ordinary Praos rules (pool lookup included)
    praos.validate_vrf_signature(
        epoch_nonce, lview, params.praos.active_slot_coeff, hv, crypto
    )


def _counters_known(lview: TPraosLedgerView, hk: bytes) -> bool:
    if hk in lview.pool_distr:
        return True
    return any(hash_key(d.vk_cold) == hk for d in lview.gen_delegs)


def update(
    params: TPraosParams,
    hv: HeaderView,
    slot: int,
    ticked: TickedTPraosState,
    crypto: CryptoVerifier = HOST_VERIFIER,
) -> TPraosState:
    """updateChainDepState (TPraos.hs:380 → PRTCL): KES/OCert checks
    shared with Praos, then the overlay-aware VRF section."""
    cs = ticked.state
    lview = ticked.ledger_view
    # validate_kes_signature consults pool_distr for counter defaults;
    # genesis delegates also have counters (their ocerts), so fall back
    oc = hv.ocert
    hk = hash_key(hv.vk_cold)
    try:
        praos.validate_kes_signature(
            params.praos, lview, cs.ocert_counters, hv, crypto
        )
    except praos.NoCounterForKeyHashOCERT:
        if not _counters_known(lview, hk):
            raise
        # genesis delegate with no prior counter: m = 0 (same rule the
        # pool branch applies, Praos.hs:585-590)
        m = 0
        n = oc.counter
        if not m <= n:
            raise praos.CounterTooSmallOCERT(m, n)
        if not n <= m + 1:
            raise praos.CounterOverIncrementedOCERT(m, n)
    _validate_vrf_overlay_aware(params, lview, cs.epoch_nonce, hv, crypto)
    return reupdate(params, hv, slot, ticked)


def reupdate(
    params: TPraosParams, hv: HeaderView, slot: int, ticked: TickedTPraosState
) -> TPraosState:
    inner = praos.reupdate(
        params.praos,
        hv,
        slot,
        praos.TickedPraosState(ticked.state, ticked.ledger_view),
    )
    return TPraosState(**vars(inner))


def translate_state(state: TPraosState) -> PraosState:
    """TPraos → Praos ChainDepState translation at the era boundary
    (Protocol/Praos/Translate.hs): nonces and ocert counters carry
    over unchanged; the overlay schedule simply ceases to exist."""
    return PraosState(**vars(state))


# ---------------------------------------------------------------------------
# Forging (checkIsLeader, TPraos.hs:304-355)
# ---------------------------------------------------------------------------


def check_is_leader(
    params: TPraosParams,
    can_be_leader: praos.PraosCanBeLeader,
    slot: int,
    ticked: TickedTPraosState,
    deleg_index: int | None = None,
) -> praos.PraosIsLeader | None:
    """Overlay slots: lead iff we are the scheduled delegate (the VRF is
    still evaluated — headers always certify the nonce contribution);
    non-overlay: the Praos lottery."""
    from ..ops.host import ecvrf as host_ecvrf

    lview = ticked.ledger_view
    assign = overlay_slot_assignment(params, len(lview.gen_delegs), slot)
    eta0 = ticked.state.epoch_nonce
    if assign is not None:
        active, j = assign
        if not active or deleg_index is None or j != deleg_index:
            return None
        alpha = nonces.mk_input_vrf(slot, eta0)
        proof = host_ecvrf.prove(can_be_leader.vrf_sign_seed, alpha)
        return praos.PraosIsLeader(host_ecvrf.proof_to_hash(proof), proof)
    inner_ticked = praos.TickedPraosState(ticked.state, lview)
    return praos.check_is_leader(params.praos, can_be_leader, slot, inner_ticked)


# ---------------------------------------------------------------------------
# Batched validation (device): same kernel, overlay-aware staging
# ---------------------------------------------------------------------------


def host_prechecks(
    params: TPraosParams, lview: TPraosLedgerView, hvs: Sequence[HeaderView]
) -> pbatch.HostChecks:
    """TPraos variant of pbatch.host_prechecks: overlay slots route the
    VRF-side check through the delegate assignment instead of the pool
    lookup."""
    base = pbatch.host_prechecks(params.praos, lview, hvs)
    vrf_errors = list(base.vrf_lookup_errors)
    for i, hv in enumerate(hvs):
        err = _overlay_error(params, lview, hv)
        if err is None:
            continue  # non-overlay: keep the pool-lookup result
        vrf_errors[i] = err if err else None  # False sentinel -> no error
    return pbatch.HostChecks(
        base.kes_window_errors, vrf_errors, base.kes_evolution
    )


class TPraosProtocol:
    """ConsensusProtocol (TPraos c) instance-as-object (TPraos.hs:304)."""

    def __init__(
        self,
        params: TPraosParams,
        crypto: CryptoVerifier = HOST_VERIFIER,
        use_device_batch: bool = True,
    ):
        self.params = params
        self.crypto = crypto
        self.security_param = params.praos.security_param
        self.use_device_batch = use_device_batch

    def initial_state(self) -> TPraosState:
        return TPraosState()

    def tick(self, ledger_view, slot, state) -> TickedTPraosState:
        return tick(self.params, ledger_view, slot, state)

    def update(self, view, slot, ticked) -> TPraosState:
        return update(self.params, view, slot, ticked, self.crypto)

    def reupdate(self, view, slot, ticked) -> TPraosState:
        return reupdate(self.params, view, slot, ticked)

    def check_is_leader(self, can_be_leader, slot, ticked, deleg_index=None):
        return check_is_leader(
            self.params, can_be_leader, slot, ticked, deleg_index
        )

    def select_view(self, header) -> select.PraosSelectView:
        # TPraos chain order == Praos chain order (Praos/Common.hs)
        return select.PraosSelectView.from_header(header)

    def compare_candidates(self, ours, theirs) -> int:
        return select.compare_select_views(ours, theirs)

    def validate_batch(self, ticked, hvs, collect_states=False, backend=None):
        """Same fused kernel as Praos; overlay lanes get an always-win
        threshold (their leader rule was settled by host_prechecks)."""
        if not hvs:
            return pbatch.BatchResult(
                ticked.state, 0, None, [] if collect_states else None
            )
        if backend is None:
            backend = "device" if self.use_device_batch else "host-fold"
        if backend == "host-fold":
            return self._host_fold(ticked, hvs, collect_states)
        return self.recover_fold(backend, ticked, hvs, collect_states)

    def recover_fold(self, backend, ticked, hvs, collect_states):
        """The TPraos dispatch's degradation floor (FLOW304 protector):
        TPraos windows are dispatched through the hardfork combinator's
        dynamic `proto.validate_batch`, which the RecoverySupervisor's
        static ladder never sees — so the exact-host-reference rung
        lives here. Only RECOVER-classified faults (node/exit.triage:
        device/runtime errors, I/O, the chaos taxonomy) are absorbed,
        only with the supervisor enabled (OCT_RECOVERY=0 restores
        raise-through), and every fall is banked as a RecoveryEvent —
        REFUSE/REPAIR/PROPAGATE classes surface raw, same contract as
        `RecoverySupervisor.recover_window`."""
        from ..obs import recovery as _recovery

        try:
            return self._device_batch(backend, ticked, hvs, collect_states)
        except Exception as e:  # noqa: BLE001 — triaged: only RECOVER
            # (recoverable below) is absorbed onto the host fold
            if not (_recovery.enabled() and _recovery.recoverable(e)):
                raise
            lanes = len(hvs)
            _recovery.note_recovery_event("host-fold", -1, lanes, 1, e)
            res = self._host_fold(ticked, hvs, collect_states)
            _recovery.note_recovery_event("recovered", -1, lanes, 1, e,
                                          ok=True)
            return res

    def _device_batch(self, backend, ticked, hvs, collect_states):
        params, lview = self.params, ticked.ledger_view
        eta0 = ticked.state.epoch_nonce
        pre = host_prechecks(params, lview, hvs)
        overlay = [
            overlay_position(params, hv.slot) is not None for hv in hvs
        ]
        if backend == "native":
            v = pbatch.run_batch_native(params.praos, lview, eta0, hvs, pre)
        elif backend == "sharded":
            # multi-chip SPMD, same as the Praos route — a silent
            # single-device fallback here would fake sharded coverage
            # for every TPraos (Shelley-era) segment
            from ..parallel import spmd

            batch = pbatch.stage(
                params.praos, lview, eta0, hvs, pre.kes_evolution
            )
            v, _first_bad, _n_ok = spmd.sharded_run_batch(batch)
        else:
            batch = pbatch.stage(params.praos, lview, eta0, hvs, pre.kes_evolution)
            v = pbatch.run_batch(batch)
        # overlay lanes: the leader rule was settled by host_prechecks —
        # mask the Praos threshold verdict out (exact, not probabilistic)
        v = self._override_overlay_leader(v, overlay)
        inner_ticked = praos.TickedPraosState(
            PraosState(**vars(ticked.state)), lview
        )
        res = pbatch._epilogue(
            params.praos, inner_ticked, hvs, pre, v, collect_states,
            lane_error=self._lane_error,
        )
        states = res.states
        if states is not None:
            states = [TPraosState(**vars(s)) for s in states]
        return replace(
            res, state=TPraosState(**vars(res.state)), states=states
        )

    def _lane_error(self, params, lview, eta0, hv, pre, v, i, counters):
        """Praos `_lane_error` with the genesis-delegate counter default
        (a delegate with no prior counter starts at m = 0, like pools)."""
        err = pbatch._lane_error(params, lview, eta0, hv, pre, v, i, counters)
        if isinstance(err, praos.NoCounterForKeyHashOCERT):
            hk = hash_key(hv.vk_cold)
            if _counters_known(lview, hk):
                return pbatch._lane_error(
                    params, lview, eta0, hv, pre, v, i, {**counters, hk: 0}
                )
        return err

    def _host_fold(self, ticked, hvs, collect_states):
        """Sequential fold from an ALREADY-ticked state: the first
        header must not be ticked again (a second tick at an epoch
        boundary would rotate the nonce twice); later headers share the
        epoch, so their ticks are no-ops by construction."""
        st = ticked.state
        states = [] if collect_states else None
        t = ticked
        for i, hv in enumerate(hvs):
            if i > 0:
                t = tick(self.params, ticked.ledger_view, hv.slot, st)
            try:
                st = update(self.params, hv, hv.slot, t, self.crypto)
            except PraosValidationError as e:
                return pbatch.BatchResult(st, i, e, states)
            if states is not None:
                states.append(st)
        return pbatch.BatchResult(st, len(hvs), None, states)

    def _override_overlay_leader(self, v, overlay_lanes):
        ok_leader = np.array(v.ok_leader, copy=True)
        ambiguous = np.array(v.leader_ambiguous, copy=True)
        for i, is_overlay in enumerate(overlay_lanes):
            if is_overlay:
                ok_leader[i] = True
                ambiguous[i] = False
        return v._replace(ok_leader=ok_leader, leader_ambiguous=ambiguous)
