"""Consensus protocols: abstract interface + Praos / BFT instances."""
