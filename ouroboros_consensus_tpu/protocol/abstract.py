"""The ConsensusProtocol interface — an open universe of protocols.

Reference: `Ouroboros.Consensus.Protocol.Abstract` (Protocol/Abstract.hs:50):
a consensus protocol is a header-level state machine with five associated
types (ChainDepState, LedgerView, SelectView, ValidateView, IsLeader) and
the transitions tick / update / reupdate, plus chain-order comparison.

Haskell's type classes become a plain Python class hierarchy: a protocol
instance is an OBJECT (carrying its params) and the associated types are
whatever the instance produces — duck typing replaces type families. The
data plane stays columnar: protocols that support batching expose
`validate_view_batch` consumed by the device pipeline (protocol/batch.py).
"""

from __future__ import annotations

from typing import Any, Generic, Protocol as TyProtocol, Sequence, TypeVar

S = TypeVar("S")  # ChainDepState
V = TypeVar("V")  # ValidateView


class ConsensusError(Exception):
    """Base class of protocol validation errors (ValidationErr family)."""


class ConsensusProtocol(TyProtocol):
    """Protocol/Abstract.hs:50 — the five operations every protocol has.

    * `select_view(header)`  — projection chain ordering uses (:178)
    * `tick(ledger_view, slot, state)` — advance to a slot, no header (:139)
    * `update(view, slot, ticked)` — full validation + new state (:146)
    * `reupdate(view, slot, ticked)` — bookkeeping only, no crypto (:164)
    * `check_is_leader(credentials, slot, ticked)` (:126)
    """

    security_param: int  # k

    def tick(self, ledger_view, slot: int, state): ...

    def update(self, view, slot: int, ticked): ...

    def reupdate(self, view, slot: int, ticked): ...

    def check_is_leader(self, can_be_leader, slot: int, ticked): ...

    def select_view(self, header) -> Any: ...

    def compare_candidates(self, ours, theirs) -> int:
        """preferCandidate (:178): > 0 if theirs is strictly better."""
        ...


class BatchingProtocol(ConsensusProtocol, TyProtocol):
    """Protocols whose `update` crypto runs as fused device batches."""

    def validate_batch(self, ticked, views: Sequence[Any]):
        """Fold `update` over `views` with batched device crypto; returns
        (state, n_valid, first_error) — protocol/batch.py semantics."""
        ...
