"""64-bit word ops as (hi, lo) uint32 pairs — TPU has no native u64.

Words are plain tuples of uint32 arrays so XLA sees flat elementwise ops it
can fuse freely. Shared by the SHA-512 (ops/sha512.py) and Blake2b
(ops/blake2b.py) device kernels.
"""

from __future__ import annotations

import numpy as np
from jax import numpy as jnp

U32 = jnp.uint32


def const(x: int):
    """Python int -> ((), ()) uint32 scalar pair."""
    return (jnp.uint32((x >> 32) & 0xFFFFFFFF), jnp.uint32(x & 0xFFFFFFFF))


def split_np(words) -> np.ndarray:
    """[N] python ints / uint64 -> [N, 2] uint32 (hi, lo)."""
    w = [int(x) for x in words]
    return np.array([[(x >> 32) & 0xFFFFFFFF, x & 0xFFFFFFFF] for x in w], dtype=np.uint32)


def add(a, b):
    ah, al = a
    bh, bl = b
    lo = al + bl
    carry = (lo < al).astype(U32)
    return ah + bh + carry, lo


def add_many(*xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = add(acc, x)
    return acc


def xor(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def and_(a, b):
    return a[0] & b[0], a[1] & b[1]


def not_(a):
    return ~a[0], ~a[1]


def rotr(x, n: int):
    h, l = x
    n %= 64
    if n == 0:
        return h, l
    if n == 32:
        return l, h
    if n < 32:
        return (h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n))
    m = n - 32
    return (l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m))


def shr(x, n: int):
    """Logical right shift, 0 < n < 32."""
    h, l = x
    return h >> n, (l >> n) | (h << (32 - n))


def to_bytes_be(x):
    """(hi, lo)[...] -> [..., 8] int32 bytes, big-endian (SHA-512 digest order)."""
    h, l = x
    parts = [h >> 24, h >> 16, h >> 8, h, l >> 24, l >> 16, l >> 8, l]
    return jnp.stack([(p & jnp.uint32(0xFF)).astype(jnp.int32) for p in parts], axis=-1)


def to_bytes_le(x):
    """(hi, lo)[...] -> [..., 8] int32 bytes, little-endian (Blake2b digest order)."""
    h, l = x
    parts = [l, l >> 8, l >> 16, l >> 24, h, h >> 8, h >> 16, h >> 24]
    return jnp.stack([(p & jnp.uint32(0xFF)).astype(jnp.int32) for p in parts], axis=-1)
