"""Batched GF(2^255 - 19) arithmetic for TPU: 13-bit limbs on int32.

TPU has no 64-bit integer multiplier, so field elements are represented as
20 limbs of 13 bits held in int32 (shape [..., 20], little-endian limb
order). Schoolbook products of 13-bit limbs fit comfortably in int32:
a limb-convolution coefficient is bounded by 20 * (2^13.22)^2 < 2^31.

Representation invariants:
  * "nearly normalized": every limb in [0, B_MAX] with B_MAX = 9500 < 2^13.3.
    All public ops accept and return nearly-normalized elements; values are
    only unique mod p after `canonical`.
  * reduction: 2^260 = 2^5 * 2^255 == 19 * 2^5 = 608 (mod p), so carry out
    of limb 19 wraps to limb 0 multiplied by FOLD = 608.

This module is pure jnp (XLA fuses the elementwise limb ops); a Pallas
variant can slot in underneath without changing callers. Everything is
shape-polymorphic over leading batch dimensions.

Reference equivalent: the C libsodium field arithmetic (fe25519, radix
2^25.5/2^51) used by `cardano-crypto-class`/`cardano-crypto-praos`; call
sites in the reference hot path are cited in ops/host/ed25519.py.

Bound certification (octrange, analysis/absint.py): the invariants
above are machine-checked wherever this module's graphs are registered
(the XLA-twin spmd path, the ed25519 sign path) — per-row intervals
along the MINOR [..., 20] limb axis (`LastRows` in analysis/domains.py;
the transposed twin of ops/pk/limbs.py's axis-0 `Rows`), B_MAX seeding,
and a widening ladder whose 9500 rung exists precisely so loop-carried
field elements re-prove the nearly-normalized invariant at the
scan/fori fixpoint instead of drifting to 2^14 and pushing the next
mul bound past 2^31.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax import lax
from jax import numpy as jnp

BITS = 13
NLIMBS = 20
MASK = (1 << BITS) - 1
FOLD = 608  # 19 * 2^5 : weight of carry out of limb 19
B_MAX = 9500  # nearly-normalized limb bound (see module docstring)

P_INT = 2**255 - 19
SQRT_M1_INT = pow(2, (P_INT - 1) // 4, P_INT)
D_INT = (-121665 * pow(121666, P_INT - 2, P_INT)) % P_INT


from . import bigint as _bi


def int_to_limbs_np(x: int, n: int = NLIMBS) -> np.ndarray:
    """Host-side: python int -> canonical limb vector (numpy int32)."""
    return _bi.int_to_limbs_np(x, n)


def limbs_to_int_np(limbs) -> int:
    """Host-side: limb vector (any bounds) -> python int."""
    return _bi.limbs_to_int_np(limbs)


P_LIMBS = int_to_limbs_np(P_INT)

# Subtraction constant: 48p in "spread" limb form, every limb > B_MAX, so
# (a + SUBC - b) is limb-wise non-negative for nearly-normalized a, b. The
# top limb is oversized (48p >> 247 = 12287 > B_MAX) by construction; the
# others are boosted by borrowing two units from the limb above.
_v48p = 48 * P_INT
_subc = np.array(
    [(_v48p >> (BITS * i)) & MASK for i in range(NLIMBS - 1)]
    + [_v48p >> (BITS * (NLIMBS - 1))],
    dtype=np.int64,
)
for _i in range(NLIMBS - 1):
    _subc[_i] += 2 << BITS
    _subc[_i + 1] -= 2
assert (_subc > B_MAX).all() and (_subc < 2**15.5).all()
assert limbs_to_int_np(_subc) == _v48p
SUBC = _subc.astype(np.int32)


def constant(x: int):
    """Field constant as a (20,) device array (broadcasts over batch)."""
    return jnp.asarray(int_to_limbs_np(x % P_INT))


ZERO = int_to_limbs_np(0)
ONE = int_to_limbs_np(1)


def zeros(batch_shape):
    return jnp.zeros((*batch_shape, NLIMBS), jnp.int32)


def ones(batch_shape):
    return jnp.broadcast_to(jnp.asarray(ONE), (*batch_shape, NLIMBS))


# ---------------------------------------------------------------------------
# Carry propagation
# ---------------------------------------------------------------------------


def _carry_pass(z):
    """One vectorized carry pass over the last axis; carry out of the top
    limb wraps to limb 0 with weight FOLD. Limbs must be non-negative."""
    c = z >> BITS
    r = z & MASK
    wrapped = jnp.concatenate([c[..., -1:] * FOLD, c[..., :-1]], axis=-1)
    return r + wrapped


def weak_reduce(z, passes: int = 2):
    """Bring non-negative limbs (< 2^31) down to nearly-normalized form."""
    for _ in range(passes):
        z = _carry_pass(z)
    return z


# ---------------------------------------------------------------------------
# Ring ops
# ---------------------------------------------------------------------------


def add(a, b):
    return _carry_pass(a + b)


def sub(a, b):
    # a - b + 48p (SUBC), limb-wise non-negative by construction of SUBC
    return _carry_pass(a - b + jnp.asarray(SUBC))


def neg(a):
    return sub(jnp.asarray(ZERO), a)


def mul_small(a, k: int):
    """Multiply by a small non-negative int constant (k * B_MAX * 20 < 2^31)."""
    return weak_reduce(a * k, passes=3)


def mul(a, b):
    """Field multiplication. Inputs nearly normalized; output likewise.

    Bound check: coefficients are sums of <= 20 products of limbs
    <= B_MAX, so z_k <= 20 * 9500^2 < 2^31. Carries can propagate up to
    limb 40 (product limbs reach 38, two carry passes extend two more),
    so the accumulator is 41 limbs wide and the fold covers limb 40 with
    weight 2^(13*40) == FOLD^2 (mod p).
    """
    ap = jnp.concatenate(
        [a, jnp.zeros((*a.shape[:-1], NLIMBS + 1), jnp.int32)], axis=-1
    )  # [..., 41]
    z = jnp.zeros_like(ap)
    for i in range(NLIMBS):
        # b_i * (a shifted up by i limbs); the tail of ap is zero so the
        # wrap-around of roll only moves zeros
        z = z + b[..., i : i + 1] * jnp.roll(ap, i, axis=-1)
    # two carry passes over 41 limbs (carry cannot leave limb 40: after
    # pass one limb 39 <= 2^17.4, after pass two limb 40 <= 2^4.4)
    for _ in range(2):
        c = z >> BITS
        z = (z & MASK) + jnp.concatenate(
            [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
        )
    # fold limbs [20..39] onto [0..19] with weight FOLD = 2^260 mod p and
    # limb 40 onto limb 0 with weight FOLD^2 = 2^520 mod p, then normalize
    lo, hi, top = z[..., :NLIMBS], z[..., NLIMBS : 2 * NLIMBS], z[..., 2 * NLIMBS :]
    lo = lo + hi * FOLD
    lo = lo.at[..., 0].add(top[..., 0] * (FOLD * FOLD))
    return weak_reduce(lo, passes=2)


def sqr(a):
    return mul(a, a)


def pow2k(a, k: int):
    """a^(2^k) by repeated squaring (k static)."""
    if k <= 4:
        for _ in range(k):
            a = sqr(a)
        return a
    return lax.fori_loop(0, k, lambda _, v: sqr(v), a)


def _chain_2_250m1(x):
    """x^(2^250 - 1) plus helpers (x^11)."""
    t0 = sqr(x)  # x^2
    t1 = mul(x, pow2k(t0, 2))  # x^9
    x11 = mul(t0, t1)  # x^11
    t31 = mul(t1, sqr(x11))  # x^31 = 2^5-1
    a = mul(pow2k(t31, 5), t31)  # 2^10-1
    b = mul(pow2k(a, 10), a)  # 2^20-1
    c = mul(pow2k(b, 20), b)  # 2^40-1
    d = mul(pow2k(c, 10), a)  # 2^50-1
    e = mul(pow2k(d, 50), d)  # 2^100-1
    f = mul(pow2k(e, 100), e)  # 2^200-1
    g = mul(pow2k(f, 50), d)  # 2^250-1
    return g, x11


def inv(x):
    """x^(p-2) = x^(2^255 - 21). inv(0) = 0."""
    g, x11 = _chain_2_250m1(x)
    return mul(pow2k(g, 5), x11)


def pow22523(x):
    """x^((p-5)/8) = x^(2^252 - 3)."""
    g, _ = _chain_2_250m1(x)
    return mul(pow2k(g, 2), x)


def legendre(x):
    """x^((p-1)/2) = x^(2^254 - 10); canonical 1 / p-1 / 0 as field elem."""
    g, _ = _chain_2_250m1(x)  # 2^250-1
    x4 = pow2k(x, 2)
    x6 = mul(x4, sqr(x))
    return mul(pow2k(g, 4), x6)  # (2^250-1)<<4 = 2^254-16 ; +6 -> 2^254-10


# ---------------------------------------------------------------------------
# Canonicalization, comparison, selection
# ---------------------------------------------------------------------------


def canonical(x):
    """Unique representative: limbs exactly 13-bit (top limb 8-bit, so the
    value is < 2^255 + eps), then reduced into [0, p)."""
    # two sequential carry passes, folding bits >= 2^255 back with weight 19
    for _ in range(2):
        c = jnp.zeros_like(x[..., 0])
        out = []
        for i in range(NLIMBS):
            v = x[..., i] + c
            out.append(v & MASK)
            c = v >> BITS
        # carry beyond limb 19 has weight 2^260 == FOLD; the top 5 bits of
        # limb 19 (bits 255..259 of the value) have weight 2^255 == 19
        hi = out[-1] >> 8
        out[-1] = out[-1] & 0xFF
        out[0] = out[0] + c * FOLD + hi * 19
        x = jnp.stack(out, axis=-1)
    # value < 2^255 + 2^13 < 2p: conditional subtract p (twice for safety)
    p = jnp.asarray(P_LIMBS)
    for _ in range(2):
        borrow = jnp.zeros_like(x[..., 0])
        diff = []
        for i in range(NLIMBS):
            v = x[..., i] - p[i] - borrow
            diff.append(v & MASK)
            borrow = jnp.where(v < 0, 1, 0)
        d = jnp.stack(diff, axis=-1)
        x = jnp.where((borrow == 0)[..., None], d, x)
    return x


def eq(a, b):
    """Field equality -> bool[...]."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a):
    return jnp.all(canonical(a) == 0, axis=-1)


def select(cond, a, b):
    """cond ? a : b with cond shaped [...] (broadcast over limbs)."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# Byte <-> limb conversion (on device; little-endian 32-byte strings)
# ---------------------------------------------------------------------------

def from_bytes(b):
    """[..., 32] uint8/int32 little-endian -> nearly-normalized limbs.

    Does NOT reduce mod p or reject >= p; callers handling encodings must
    canonicalize / validate separately (cf. point decompress).
    """
    return _bi.bytes_to_limbs(b, NLIMBS)


def to_bytes(x):
    """Canonical field element -> [..., 32] int32 bytes (values 0..255)."""
    x = canonical(x)
    bits = (x[..., :, None] >> jnp.arange(BITS, dtype=jnp.int32)) & 1
    bits = bits.reshape(*x.shape[:-1], NLIMBS * BITS)[..., :256]
    groups = bits.reshape(*x.shape[:-1], 32, 8)
    return jnp.sum(groups * (1 << jnp.arange(8, dtype=jnp.int32)), axis=-1)


def parity(x):
    """Low bit of the canonical value (the RFC 8032 sign bit source)."""
    return canonical(x)[..., 0] & 1


# ---------------------------------------------------------------------------
# Square roots
# ---------------------------------------------------------------------------


def sqrt_ratio(n, d):
    """(ok, r) with r = sqrt(n/d) when n/d is square (even-parity root).

    One exponentiation: r0 = n d^3 (n d^7)^((p-5)/8); then correct by
    sqrt(-1) if needed. ok is False when n/d is not a QR (and n != 0).
    For n == 0 returns (True, 0).
    """
    d2 = sqr(d)
    d3 = mul(d, d2)
    d7 = mul(d3, sqr(d2))
    r = mul(mul(n, d3), pow22523(mul(n, d7)))
    check = mul(d, sqr(r))  # should be +-n
    r_alt = mul(r, constant(SQRT_M1_INT))
    good = eq(check, n)
    good_alt = eq(check, neg(n))
    r = select(good, r, r_alt)
    ok = good | good_alt
    # normalize to even parity
    r = select(parity(r) == 1, neg(r), r)
    return ok, r


def sqrt(x):
    """(ok, even root) of a plain field element."""
    return sqrt_ratio(x, ones(x.shape[:-1]))
