"""Batched SHA-512 device kernel (pure jnp, uint32 word pairs).

Variable-length messages are staged host-side into standard padded 128-byte
blocks (`pad_messages_np`); the device kernel runs every lane through the
batch-max number of blocks with masked state updates — batch-uniform
control flow, no data-dependent branches (the TPU discipline from
SURVEY.md §7.3).

Reference equivalent: SHA-512 inside libsodium's Ed25519 (challenge hash
`H(R||A||M)`) and the vendored ECVRF proof/challenge hashes — reached from
the reference hot path at ouroboros-consensus-protocol/.../Protocol/
Praos.hs:543,580,582 via `cardano-crypto-{class,praos}`.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from jax import lax
from jax import numpy as jnp

from . import u64

BLOCK = 128

_H0_INTS = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_K_INTS = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]

H0 = u64.split_np(_H0_INTS)  # [8, 2] uint32
K = u64.split_np(_K_INTS)  # [80, 2] uint32


def nblocks_for_len(n: int) -> int:
    """Number of SHA-512 blocks for an n-byte message (incl. padding)."""
    return (n + 1 + 16 + BLOCK - 1) // BLOCK


def pad_messages_np(msgs: Sequence[bytes], nb: int | None = None):
    """Host staging: messages -> (blocks [B, NB, 16, 2] uint32, nblocks [B] int32).

    Standard SHA-512 padding (0x80, zeros, 128-bit big-endian bit length);
    trailing blocks beyond a lane's nblocks are zero and masked out on
    device.
    """
    need = max((nblocks_for_len(len(m)) for m in msgs), default=1)
    if nb is None:
        nb = need
    assert nb >= need, f"nb={nb} < required {need}"
    n = len(msgs)
    lens = {len(m) for m in msgs}
    if len(lens) == 1 and n:
        # uniform length (the common replay case: fixed header layout):
        # ONE buffer copy + vectorized padding instead of a Python loop
        ln = lens.pop()
        k = nblocks_for_len(ln)
        buf = np.zeros((n, nb * BLOCK), dtype=np.uint8)
        buf[:, :ln] = np.frombuffer(b"".join(msgs), np.uint8).reshape(n, ln)
        buf[:, ln] = 0x80
        tail = np.frombuffer((8 * ln).to_bytes(16, "big"), np.uint8)
        buf[:, k * BLOCK - 16 : k * BLOCK] = tail
        nblocks = np.full((n,), k, dtype=np.int32)
        return bytes_to_blocks_np(buf.reshape(n, nb, BLOCK)), nblocks
    buf = np.zeros((n, nb * BLOCK), dtype=np.uint8)
    nblocks = np.zeros((n,), dtype=np.int32)
    for i, m in enumerate(msgs):
        k = nblocks_for_len(len(m))
        padded = bytearray(k * BLOCK)
        padded[: len(m)] = m
        padded[len(m)] = 0x80
        padded[-16:] = (8 * len(m)).to_bytes(16, "big")
        buf[i, : k * BLOCK] = np.frombuffer(bytes(padded), dtype=np.uint8)
        nblocks[i] = k
    return bytes_to_blocks_np(buf.reshape(n, nb, BLOCK)), nblocks


def pad_matrix_np(mat: np.ndarray, nb: int | None = None):
    """`pad_messages_np` for a [B, M] uint8 matrix of uniform-length
    messages: no per-row bytes objects, no join — the columnar staging
    path (protocol/batch.stage_columns) hands whole message columns in.
    Byte-identical to pad_messages_np on the row-wise bytes."""
    n, ln = mat.shape
    k = nblocks_for_len(ln)
    if nb is None:
        nb = k
    assert nb >= k, f"nb={nb} < required {k}"
    buf = np.zeros((n, nb * BLOCK), dtype=np.uint8)
    buf[:, :ln] = mat
    buf[:, ln] = 0x80
    buf[:, k * BLOCK - 16 : k * BLOCK] = np.frombuffer(
        (8 * ln).to_bytes(16, "big"), np.uint8
    )
    nblocks = np.full((n,), k, dtype=np.int32)
    return bytes_to_blocks_np(buf.reshape(n, nb, BLOCK)), nblocks


def bytes_to_blocks_np(b: np.ndarray) -> np.ndarray:
    """[..., 128] uint8 -> [..., 16, 2] uint32 big-endian words."""
    w = b.reshape(*b.shape[:-1], 16, 8).astype(np.uint32)
    shifts = np.array([24, 16, 8, 0], dtype=np.uint32)
    hi = (w[..., :4] << shifts).sum(axis=-1, dtype=np.uint32)
    lo = (w[..., 4:] << shifts).sum(axis=-1, dtype=np.uint32)
    return np.stack([hi, lo], axis=-1)


def bytes_to_blocks(b):
    """Device variant: [..., 128] int32 bytes -> [..., 16, 2] uint32 words."""
    w = b.astype(jnp.uint32).reshape(*b.shape[:-1], 16, 8)
    shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
    hi = (w[..., :4] << shifts).sum(axis=-1).astype(jnp.uint32)
    lo = (w[..., 4:] << shifts).sum(axis=-1).astype(jnp.uint32)
    return jnp.stack([hi, lo], axis=-1)


def pad_blocks_fixed(data, msg_len: int, nb: int | None = None):
    """Device staging: [..., msg_len] byte array (any int dtype) ->
    ([..., nb, 16, 2] uint32 words, [...] int32 nblocks).

    Static-length standard SHA-512 padding (0x80, zeros, 128-bit BE bit
    length) — byte-identical to `pad_messages_np` on a batch of
    uniform-length messages, but running inside the jit so the host
    stages the RAW message bytes instead of padded block columns (the
    packed-staging H2D contract, protocol/batch.stage_packed). The pad
    tail is a trace-time constant: everything about the layout is static.
    """
    assert data.shape[-1] == msg_len
    k = nblocks_for_len(msg_len)
    if nb is None:
        nb = k
    assert nb >= k
    batch = data.shape[:-1]
    pad = np.zeros(nb * BLOCK - msg_len, np.uint8)
    pad[0] = 0x80
    tail_end = k * BLOCK - msg_len
    pad[tail_end - 16 : tail_end] = np.frombuffer(
        (8 * msg_len).to_bytes(16, "big"), np.uint8
    )
    padded = jnp.concatenate(
        [
            data.astype(jnp.uint8),
            jnp.broadcast_to(jnp.asarray(pad), (*batch, pad.shape[0])),
        ],
        axis=-1,
    )
    words = bytes_to_blocks(
        padded.reshape(*batch, nb, BLOCK).astype(jnp.int32)
    )
    return words, jnp.full(batch, k, jnp.int32)


def splice_prefix64(blocks, prefix_bytes):
    """Overwrite the first 64 bytes of block 0 with device-computed data.

    blocks: [..., NB, 16, 2] uint32 staged with a 64-byte hole at the
    front; prefix_bytes: [..., 64] int32. Used by the Ed25519 sign
    kernel, whose challenge hash input starts with R ‖ A where R is only
    known on device (R = r·B)."""
    w = prefix_bytes.astype(jnp.uint32).reshape(*prefix_bytes.shape[:-1], 8, 8)
    shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
    hi = (w[..., :4] << shifts).sum(axis=-1).astype(jnp.uint32)
    lo = (w[..., 4:] << shifts).sum(axis=-1).astype(jnp.uint32)
    words = jnp.stack([hi, lo], axis=-1)  # [..., 8, 2]
    return blocks.at[..., 0, :8, :].set(words)


def _bsig0(x):
    return u64.xor(u64.xor(u64.rotr(x, 28), u64.rotr(x, 34)), u64.rotr(x, 39))


def _bsig1(x):
    return u64.xor(u64.xor(u64.rotr(x, 14), u64.rotr(x, 18)), u64.rotr(x, 41))


def _ssig0(x):
    return u64.xor(u64.xor(u64.rotr(x, 1), u64.rotr(x, 8)), u64.shr(x, 7))


def _ssig1(x):
    return u64.xor(u64.xor(u64.rotr(x, 19), u64.rotr(x, 61)), u64.shr(x, 6))


def compress(state, block):
    """One SHA-512 compression. state [..., 8, 2]; block [..., 16, 2].

    The 80 rounds run as a `lax.fori_loop` with a rolling 16-word
    message-schedule window (W[t..t+15]) rather than Python-unrolled:
    the unrolled form emits ~2.5k HLO ops per compress and sends XLA's
    CPU backend into multi-minute LLVM optimization; the rolled body is
    ~100 ops and compiles in seconds on CPU and TPU alike. Runtime cost
    is nil — the rounds are sequentially dependent either way, and the
    batch dimension supplies the parallelism.
    """
    kc = jnp.asarray(K)  # [80, 2]
    wh0, wl0 = block[..., 0], block[..., 1]  # [..., 16]
    rh0, rl0 = state[..., 0], state[..., 1]  # [..., 8]

    def body(t, carry):
        rh, rl, wh, wl = carry

        def reg(i):
            return (rh[..., i], rl[..., i])

        a, b, c, d, e, f, g, h = (reg(i) for i in range(8))
        wt = (wh[..., 0], wl[..., 0])
        ch = u64.xor(u64.and_(e, f), u64.and_(u64.not_(e), g))
        maj = u64.xor(u64.xor(u64.and_(a, b), u64.and_(a, c)), u64.and_(b, c))
        kt = (kc[t, 0], kc[t, 1])
        t1 = u64.add_many(h, _bsig1(e), ch, kt, wt)
        t2 = u64.add(_bsig0(a), maj)
        na = u64.add(t1, t2)
        ne = u64.add(d, t1)
        rh2 = jnp.stack(
            [na[0], a[0], b[0], c[0], ne[0], e[0], f[0], g[0]], axis=-1
        )
        rl2 = jnp.stack(
            [na[1], a[1], b[1], c[1], ne[1], e[1], f[1], g[1]], axis=-1
        )
        # W[t+16] = ssig1(W[t+14]) + W[t+9] + ssig0(W[t+1]) + W[t]
        w14 = (wh[..., 14], wl[..., 14])
        w9 = (wh[..., 9], wl[..., 9])
        w1 = (wh[..., 1], wl[..., 1])
        wn = u64.add_many(_ssig1(w14), w9, _ssig0(w1), wt)
        wh2 = jnp.concatenate([wh[..., 1:], wn[0][..., None]], axis=-1)
        wl2 = jnp.concatenate([wl[..., 1:], wn[1][..., None]], axis=-1)
        return rh2, rl2, wh2, wl2

    rh, rl, _, _ = lax.fori_loop(0, 80, body, (rh0, rl0, wh0, wl0))
    hi = state[..., 0] + rh
    lo = state[..., 1] + rl
    carry = (lo < state[..., 1]).astype(jnp.uint32)
    return jnp.stack([hi + carry, lo], axis=-1)


def sha512_blocks(blocks, nblocks):
    """Batched SHA-512 over pre-padded blocks.

    blocks: [..., NB, 16, 2] uint32; nblocks: [...] int32 (1 <= n <= NB).
    Returns digest words [..., 8, 2] uint32.
    """
    nb = blocks.shape[-3]
    batch = blocks.shape[:-3]
    init = jnp.broadcast_to(jnp.asarray(H0), (*batch, 8, 2))

    if nb == 1:
        return compress(init, blocks[..., 0, :, :])

    def body(i, st):
        blk = lax.dynamic_index_in_dim(blocks, i, axis=len(batch), keepdims=False)
        nxt = compress(st, blk)
        active = (i < nblocks)[..., None, None]
        return jnp.where(active, nxt, st)

    return lax.fori_loop(0, nb, body, init)


def digest_bytes(words):
    """[..., 8, 2] words -> [..., 64] int32 bytes in digest order."""
    outs = [u64.to_bytes_be((words[..., i, 0], words[..., i, 1])) for i in range(8)]
    return jnp.concatenate(outs, axis=-1)


def sha512(blocks, nblocks):
    """Convenience: padded blocks -> [..., 64] digest bytes."""
    return digest_bytes(sha512_blocks(blocks, nblocks))


def sha512_fixed(data):
    """SHA-512 of [..., n] int32 byte arrays with a STATIC common length n.

    Padding is a compile-time constant; every block is processed (no
    masking). This is the shape of the ECVRF hash-to-curve / challenge /
    proof-to-hash inputs.
    """
    n = data.shape[-1]
    batch = data.shape[:-1]
    nb = nblocks_for_len(n)
    tail = np.zeros(nb * BLOCK - n, dtype=np.int32)
    tail[0] = 0x80
    tail[-16:] = np.frombuffer((8 * n).to_bytes(16, "big"), np.uint8)
    padded = jnp.concatenate(
        [data.astype(jnp.int32), jnp.broadcast_to(jnp.asarray(tail), (*batch, tail.size))],
        axis=-1,
    )
    blocks = bytes_to_blocks(padded.reshape(*batch, nb, BLOCK))
    state = jnp.broadcast_to(jnp.asarray(H0), (*batch, 8, 2))
    for i in range(nb):
        state = compress(state, blocks[..., i, :, :])
    return digest_bytes(state)
