"""Pure-Python Ed25519 (RFC 8032) host reference implementation.

This is the CPU reference against which the batched JAX kernels
(ops/ed25519_batch.py) are differentially tested, and the sign-side
primitive used by the chain synthesizer (tools/db_synthesizer.py).

Reference equivalents: the external `cardano-crypto-class` package's
libsodium-backed `Ed25519DSIGN` (called from the Praos hot path at
ouroboros-consensus-protocol/.../Protocol/Praos.hs:580 for OCert cold-key
checks). Verification is cofactorless (checks s*B == R + h*A exactly),
matching libsodium's crypto_sign_verify_detached semantics.

Exposes low-level group operations (field, point add/mul, decompress)
because the ECVRF implementation (ops/host/ecvrf.py) builds on them.
"""

from __future__ import annotations

import hashlib

# ---------------------------------------------------------------------------
# Field GF(2^255 - 19)
# ---------------------------------------------------------------------------

P = 2**255 - 19
# Group order: L = 2^252 + 27742317777372353535851937790883648493
L = 2**252 + 27742317777372353535851937790883648493
# Edwards curve constant d = -121665/121666 mod p
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p
# Montgomery curve25519 constant (for Elligator2 in ECVRF)
MONT_A = 486662
# sqrt(-486664) mod p, used in the Montgomery -> Edwards birational map.
# Chosen as the even root to fix a deterministic mapping.
_s = pow(-486664 % P, (P + 3) // 8, P)
if (_s * _s) % P != (-486664) % P:
    _s = (_s * SQRT_M1) % P
assert (_s * _s) % P == (-486664) % P
SQRT_M486664 = _s if _s % 2 == 0 else P - _s


def fe_inv(x: int) -> int:
    return pow(x, P - 2, P)


def fe_sqrt(x: int) -> int | None:
    """Square root mod p (returns the root with even low bit), or None."""
    r = pow(x, (P + 3) // 8, P)
    if (r * r) % P != x % P:
        r = (r * SQRT_M1) % P
    if (r * r) % P != x % P:
        return None
    return r if r % 2 == 0 else P - r


def is_square(x: int) -> bool:
    return x % P == 0 or pow(x, (P - 1) // 2, P) == 1


# ---------------------------------------------------------------------------
# Edwards point arithmetic (extended homogeneous coordinates X,Y,Z,T)
# ---------------------------------------------------------------------------

# Base point: y = 4/5, x recovered with even-ness per RFC 8032.
_by = (4 * fe_inv(5)) % P
_bx2 = ((_by * _by - 1) * fe_inv(D * _by * _by + 1)) % P
_bx = fe_sqrt(_bx2)
assert _bx is not None
if _bx % 2 != 0:
    _bx = P - _bx
B = (_bx, _by, 1, (_bx * _by) % P)
IDENT = (0, 1, 1, 0)


def point_add(p, q):
    """Unified addition (complete for twisted Edwards a=-1)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A_ = (Y1 - X1) * (Y2 - X2) % P
    B_ = (Y1 + X1) * (Y2 + X2) % P
    C_ = 2 * T1 * T2 * D % P
    D_ = 2 * Z1 * Z2 % P
    E = B_ - A_
    F = D_ - C_
    G = D_ + C_
    H = B_ + A_
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p):
    """Dedicated doubling (dbl-2008-hwcd)."""
    X1, Y1, Z1, _ = p
    A_ = X1 * X1 % P
    B_ = Y1 * Y1 % P
    C_ = 2 * Z1 * Z1 % P
    H = (A_ + B_) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A_ - B_) % P
    F = (C_ + G) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_neg(p):
    X, Y, Z, T = p
    return (P - X if X else 0, Y, Z, P - T if T else 0)


_BASE_COMB: list | None = None


def _base_comb():
    """Lazy fixed-base table: COMB[w][d] = d * 256^w * B as extended
    coords — turns every s*B into 32 point adds (the host synthesizer's
    per-block Ed25519/KES signing cost would otherwise be a full ladder)."""
    global _BASE_COMB
    if _BASE_COMB is None:
        tbl = []
        wbase = B
        for _w in range(32):
            row = [IDENT]
            acc = wbase
            for _d in range(1, 256):
                row.append(acc)
                acc = point_add(acc, wbase)
            tbl.append(row)
            for _ in range(8):
                wbase = point_double(wbase)
        _BASE_COMB = tbl
    return _BASE_COMB


def base_point_mul(s: int):
    """s*B via the fixed-base comb (s < 2^256)."""
    tbl = _base_comb()
    q = IDENT
    for w in range(32):
        d = (s >> (8 * w)) & 0xFF
        if d:
            q = point_add(q, tbl[w][d])
    return q


def point_mul(s: int, p):
    q = IDENT
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_double(p)
        s >>= 1
    return q


def point_equal(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def point_compress(p) -> bytes:
    X, Y, Z, _ = p
    zi = fe_inv(Z)
    x = X * zi % P
    y = Y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(s: bytes):
    """Decode 32-byte point encoding; None on failure (non-canonical y,
    non-residue x^2, or x=0 with sign bit set)."""
    if len(s) != 32:
        return None
    n = int.from_bytes(s, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    if y >= P:
        return None
    x2 = (y * y - 1) * fe_inv(D * y * y + 1) % P
    x = fe_sqrt(x2)
    if x is None:
        return None
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = P - x
    return (x, y, 1, x * y % P)


def point_is_on_curve(p) -> bool:
    X, Y, Z, T = p
    zi = fe_inv(Z)
    x, y = X * zi % P, Y * zi % P
    return (-x * x + y * y - 1 - D * x * x % P * y % P * y) % P == 0


# ---------------------------------------------------------------------------
# Ed25519 sign / verify (RFC 8032)
# ---------------------------------------------------------------------------


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def _clamp(b: bytes) -> int:
    a = bytearray(b[:32])
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


def secret_expand(seed: bytes):
    h = _sha512(seed[:32])
    return _clamp(h[:32]), h[32:]


from functools import lru_cache


@lru_cache(maxsize=4096)
def expand_for_staging(seed: bytes):
    """(clamped scalar LE bytes, prefix, pk bytes) — cached: batched
    forging repeats the same few pool seeds across thousands of lanes."""
    a, prefix = secret_expand(seed)
    return int.to_bytes(a, 32, "little"), prefix, secret_to_public(seed)


def secret_to_public(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(base_point_mul(a))


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(seed)
    A_enc = point_compress(base_point_mul(a))
    r = int.from_bytes(_sha512(prefix + msg), "little") % L
    R_enc = point_compress(base_point_mul(r))
    h = int.from_bytes(_sha512(R_enc + A_enc + msg), "little") % L
    s = (r + h * a) % L
    return R_enc + int.to_bytes(s, 32, "little")


def verify(public: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != 64 or len(public) != 32:
        return False
    A = point_decompress(public)
    R = point_decompress(sig[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    h = int.from_bytes(_sha512(sig[:32] + public + msg), "little") % L
    # Cofactorless check: s*B == R + h*A
    return point_equal(point_mul(s, B), point_add(R, point_mul(h, A)))
