"""ECVRF-ED25519-SHA512-Elligator2 (IETF draft-03) host reference.

Pure-Python reference implementation of the VRF used by Praos leader
election. Reference equivalents: the C libsodium fork vendored by
`cardano-crypto-praos` ("ietfdraft03" suite), reached from the hot path at
ouroboros-consensus-protocol/.../Protocol/Praos.hs:543 (verifyCertified)
and Praos.hs:397 (evalCertified, forging side).

Proof formats:
  * draft-03 (80 bytes): Gamma (32) || c (16) || s (32).
  * batch-compatible (128 bytes): Gamma (32) || U (32) || V (32) || s (32)
    — the Badertscher–Gaži–Querejeta-Azurmendi–Russell (ESORICS 2022)
    scheme behind cardano-base's `PraosBatchCompat` VRF: the proof
    ANNOUNCES the commitment points U = k·B and V = k·H instead of the
    challenge, the verifier derives c = H(suite ‖ 2 ‖ H ‖ Γ ‖ U ‖ V)
    from the announced bytes and checks the two group equations
    U = s·B − c·Y and V = s·H − c·Γ. For an honest prover the two
    formats carry the same (Γ, s) and yield the same beta; the
    announced-points form is what makes window-level random-linear-
    combination aggregation possible (ops/pk/aggregate.py).
Output (beta) is 64 bytes for both; the format is discriminated by
proof length everywhere in the framework.

NOTE on conformance: no libsodium test vectors are available in this
offline environment; this implementation follows draft-03 semantics
(suite 0x04) and is the single source of truth for the framework — the
batched JAX verifier (ops/ecvrf_batch.py), the synthesizer's prover, and
these host functions are differentially tested against each other.
"""

from __future__ import annotations

import hashlib

from .ed25519 import (
    B,
    IDENT,
    L,
    MONT_A,
    P,
    SQRT_M1,
    SQRT_M486664,
    _clamp,
    fe_inv,
    fe_sqrt,
    is_square,
    point_add,
    point_compress,
    point_decompress,
    point_equal,
    point_mul,
    point_neg,
)

SUITE = b"\x04"
PROOF_BYTES = 80
PROOF_BYTES_BATCH = 128
OUTPUT_BYTES = 64


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# ---------------------------------------------------------------------------
# Elligator2 hash-to-curve (draft-03 section 5.4.1.2 semantics)
# ---------------------------------------------------------------------------


def elligator2(r: int):
    """Map a field element r to a point on the Edwards curve.

    Deterministic Elligator2 on curve25519 followed by the birational map
    to edwards25519. Returns an extended-coordinate point (not yet
    cofactor-cleared). Sign convention: the Edwards x-coordinate is negated
    when the Montgomery v coordinate is "negative" (odd), giving a fixed
    deterministic choice mirrored exactly by the batched JAX kernel.
    """
    # u = -A / (1 + 2 r^2); if 1 + 2 r^2 == 0 use u = -A (r excluded anyway)
    t = (2 * r * r) % P
    denom = (t + 1) % P
    if denom == 0:
        denom = 1
    u = (-MONT_A * fe_inv(denom)) % P
    # w = u (u^2 + A u + 1): the Montgomery curve RHS at u
    w = u * ((u * u + MONT_A * u + 1) % P) % P
    if not is_square(w):
        # switch to the other candidate u' = -u - A; RHS becomes square
        u = (-u - MONT_A) % P
        w = u * ((u * u + MONT_A * u + 1) % P) % P
    v = fe_sqrt(w)
    assert v is not None
    # Birational map curve25519 -> edwards25519:
    #   x = sqrt(-486664) * u / v ;  y = (u - 1) / (u + 1)
    if v == 0:
        x = 0
    else:
        x = SQRT_M486664 * u % P * fe_inv(v) % P
    up1 = (u + 1) % P
    y = ((u - 1) * fe_inv(up1)) % P if up1 != 0 else 0
    # Fix sign deterministically: force x even
    if x % 2 == 1:
        x = P - x
    return (x, y, 1, x * y % P)


def hash_to_curve(pk: bytes, alpha: bytes):
    """H = cofactor * Elligator2(SHA512(suite || 0x01 || pk || alpha))."""
    h = _sha512(SUITE + b"\x01" + pk + alpha)
    r_bytes = bytearray(h[:32])
    r_bytes[31] &= 0x7F  # clear sign bit => r < 2^255
    r = int.from_bytes(bytes(r_bytes), "little") % P
    e = elligator2(r)
    # clear cofactor (multiply by 8)
    h8 = point_mul(8, e)
    return h8


def _hash_points(h, gamma, u, v) -> bytes:
    """c = first 16 bytes of SHA512(suite || 0x02 || H || Gamma || U || V)."""
    data = (
        SUITE
        + b"\x02"
        + point_compress(h)
        + point_compress(gamma)
        + point_compress(u)
        + point_compress(v)
    )
    return _sha512(data)[:16]


# ---------------------------------------------------------------------------
# Prove / verify / proof-to-hash
# ---------------------------------------------------------------------------


def _prove_parts(seed: bytes, alpha: bytes):
    """Shared prove core -> (gamma, c_bytes, s, u_enc, v_enc): both proof
    formats are serializations of the same transcript."""
    h = _sha512(seed[:32])
    x = _clamp(h[:32])
    prefix = h[32:]
    pk = point_compress(point_mul(x, B))
    H = hash_to_curve(pk, alpha)
    H_enc = point_compress(H)
    gamma = point_mul(x, H)
    # nonce k = SHA512(prefix || H) mod L   (draft-03 section 5.4.2.2)
    k = int.from_bytes(_sha512(prefix + H_enc), "little") % L
    u = point_mul(k, B)
    v = point_mul(k, H)
    c_bytes = _hash_points(H, gamma, u, v)
    c = int.from_bytes(c_bytes, "little")
    s = (k + c * x) % L
    return gamma, c_bytes, s, point_compress(u), point_compress(v)


def prove(seed: bytes, alpha: bytes) -> bytes:
    """Produce an 80-byte draft-03 proof pi for alpha under sk seed."""
    gamma, c_bytes, s, _u, _v = _prove_parts(seed, alpha)
    return point_compress(gamma) + c_bytes + int.to_bytes(s, 32, "little")


def prove_batch_compat(seed: bytes, alpha: bytes) -> bytes:
    """128-byte batch-compatible proof: Gamma ‖ U ‖ V ‖ s (the challenge
    is re-derived by the verifier from the announced U, V)."""
    gamma, _c, s, u_enc, v_enc = _prove_parts(seed, alpha)
    return point_compress(gamma) + u_enc + v_enc + int.to_bytes(s, 32, "little")


def decode_proof(pi: bytes):
    """Split pi into (Gamma point, c int, s int); None on malformed."""
    if len(pi) != PROOF_BYTES:
        return None
    gamma = point_decompress(pi[:32])
    if gamma is None:
        return None
    c = int.from_bytes(pi[32:48], "little")
    s = int.from_bytes(pi[48:80], "little")
    if s >= L:  # non-canonical scalar
        return None
    return gamma, c, s


def verify(pk: bytes, pi: bytes, alpha: bytes) -> bytes | None:
    """Verify proof (either format, by length); return beta or None."""
    if len(pi) == PROOF_BYTES_BATCH:
        return verify_batch_compat(pk, pi, alpha)
    y = point_decompress(pk)
    if y is None:
        return None
    dec = decode_proof(pi)
    if dec is None:
        return None
    gamma, c, s = dec
    H = hash_to_curve(pk, alpha)
    # U = s*B - c*Y ;  V = s*H - c*Gamma
    U = point_add(point_mul(s, B), point_neg(point_mul(c, y)))
    V = point_add(point_mul(s, H), point_neg(point_mul(c, gamma)))
    c_prime = _hash_points(H, gamma, U, V)
    if int.from_bytes(c_prime, "little") != c:
        return None
    return proof_to_hash(pi)


def verify_batch_compat(pk: bytes, pi: bytes, alpha: bytes) -> bytes | None:
    """Verify a 128-byte batch-compatible proof; return beta or None.

    The challenge is DERIVED from the announced U, V bytes, then the two
    group equations U = s·B − c·Y and V = s·H − c·Γ are checked — the
    per-lane form of the aggregated window check (ops/pk/aggregate.py),
    and the exact reference the fallback path must reproduce."""
    if len(pi) != PROOF_BYTES_BATCH:
        return None
    y = point_decompress(pk)
    if y is None:
        return None
    gamma = point_decompress(pi[:32])
    u = point_decompress(pi[32:64])
    v = point_decompress(pi[64:96])
    if gamma is None or u is None or v is None:
        return None
    s = int.from_bytes(pi[96:128], "little")
    if s >= L:
        return None
    H = hash_to_curve(pk, alpha)
    c_bytes = _sha512(
        SUITE + b"\x02" + point_compress(H) + pi[:32] + pi[32:64] + pi[64:96]
    )[:16]
    c = int.from_bytes(c_bytes, "little")
    if not point_equal(
        point_mul(s, B), point_add(u, point_mul(c, y))
    ):
        return None
    if not point_equal(
        point_mul(s, H), point_add(v, point_mul(c, gamma))
    ):
        return None
    return proof_to_hash(pi)


def proof_to_hash(pi: bytes) -> bytes:
    """beta = SHA512(suite || 0x03 || encode(cofactor * Gamma))."""
    gamma = point_decompress(pi[:32])
    if gamma is None:
        raise ValueError("malformed proof")
    g8 = point_mul(8, gamma)
    return _sha512(SUITE + b"\x03" + point_compress(g8))
