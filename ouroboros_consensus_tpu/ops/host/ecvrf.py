"""ECVRF-ED25519-SHA512-Elligator2 (IETF draft-03) host reference.

Pure-Python reference implementation of the VRF used by Praos leader
election. Reference equivalents: the C libsodium fork vendored by
`cardano-crypto-praos` ("ietfdraft03" suite), reached from the hot path at
ouroboros-consensus-protocol/.../Protocol/Praos.hs:543 (verifyCertified)
and Praos.hs:397 (evalCertified, forging side).

Proof format (80 bytes): Gamma (32) || c (16) || s (32).
Output (beta) is 64 bytes.

NOTE on conformance: no libsodium test vectors are available in this
offline environment; this implementation follows draft-03 semantics
(suite 0x04) and is the single source of truth for the framework — the
batched JAX verifier (ops/ecvrf_batch.py), the synthesizer's prover, and
these host functions are differentially tested against each other.
"""

from __future__ import annotations

import hashlib

from .ed25519 import (
    B,
    IDENT,
    L,
    MONT_A,
    P,
    SQRT_M1,
    SQRT_M486664,
    _clamp,
    fe_inv,
    fe_sqrt,
    is_square,
    point_add,
    point_compress,
    point_decompress,
    point_mul,
    point_neg,
)

SUITE = b"\x04"
PROOF_BYTES = 80
OUTPUT_BYTES = 64


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# ---------------------------------------------------------------------------
# Elligator2 hash-to-curve (draft-03 section 5.4.1.2 semantics)
# ---------------------------------------------------------------------------


def elligator2(r: int):
    """Map a field element r to a point on the Edwards curve.

    Deterministic Elligator2 on curve25519 followed by the birational map
    to edwards25519. Returns an extended-coordinate point (not yet
    cofactor-cleared). Sign convention: the Edwards x-coordinate is negated
    when the Montgomery v coordinate is "negative" (odd), giving a fixed
    deterministic choice mirrored exactly by the batched JAX kernel.
    """
    # u = -A / (1 + 2 r^2); if 1 + 2 r^2 == 0 use u = -A (r excluded anyway)
    t = (2 * r * r) % P
    denom = (t + 1) % P
    if denom == 0:
        denom = 1
    u = (-MONT_A * fe_inv(denom)) % P
    # w = u (u^2 + A u + 1): the Montgomery curve RHS at u
    w = u * ((u * u + MONT_A * u + 1) % P) % P
    if not is_square(w):
        # switch to the other candidate u' = -u - A; RHS becomes square
        u = (-u - MONT_A) % P
        w = u * ((u * u + MONT_A * u + 1) % P) % P
    v = fe_sqrt(w)
    assert v is not None
    # Birational map curve25519 -> edwards25519:
    #   x = sqrt(-486664) * u / v ;  y = (u - 1) / (u + 1)
    if v == 0:
        x = 0
    else:
        x = SQRT_M486664 * u % P * fe_inv(v) % P
    up1 = (u + 1) % P
    y = ((u - 1) * fe_inv(up1)) % P if up1 != 0 else 0
    # Fix sign deterministically: force x even
    if x % 2 == 1:
        x = P - x
    return (x, y, 1, x * y % P)


def hash_to_curve(pk: bytes, alpha: bytes):
    """H = cofactor * Elligator2(SHA512(suite || 0x01 || pk || alpha))."""
    h = _sha512(SUITE + b"\x01" + pk + alpha)
    r_bytes = bytearray(h[:32])
    r_bytes[31] &= 0x7F  # clear sign bit => r < 2^255
    r = int.from_bytes(bytes(r_bytes), "little") % P
    e = elligator2(r)
    # clear cofactor (multiply by 8)
    h8 = point_mul(8, e)
    return h8


def _hash_points(h, gamma, u, v) -> bytes:
    """c = first 16 bytes of SHA512(suite || 0x02 || H || Gamma || U || V)."""
    data = (
        SUITE
        + b"\x02"
        + point_compress(h)
        + point_compress(gamma)
        + point_compress(u)
        + point_compress(v)
    )
    return _sha512(data)[:16]


# ---------------------------------------------------------------------------
# Prove / verify / proof-to-hash
# ---------------------------------------------------------------------------


def prove(seed: bytes, alpha: bytes) -> bytes:
    """Produce an 80-byte proof pi for message alpha under sk seed."""
    h = _sha512(seed[:32])
    x = _clamp(h[:32])
    prefix = h[32:]
    pk = point_compress(point_mul(x, B))
    H = hash_to_curve(pk, alpha)
    H_enc = point_compress(H)
    gamma = point_mul(x, H)
    # nonce k = SHA512(prefix || H) mod L   (draft-03 section 5.4.2.2)
    k = int.from_bytes(_sha512(prefix + H_enc), "little") % L
    c_bytes = _hash_points(H, gamma, point_mul(k, B), point_mul(k, H))
    c = int.from_bytes(c_bytes, "little")
    s = (k + c * x) % L
    return point_compress(gamma) + c_bytes + int.to_bytes(s, 32, "little")


def decode_proof(pi: bytes):
    """Split pi into (Gamma point, c int, s int); None on malformed."""
    if len(pi) != PROOF_BYTES:
        return None
    gamma = point_decompress(pi[:32])
    if gamma is None:
        return None
    c = int.from_bytes(pi[32:48], "little")
    s = int.from_bytes(pi[48:80], "little")
    if s >= L:  # non-canonical scalar
        return None
    return gamma, c, s


def verify(pk: bytes, pi: bytes, alpha: bytes) -> bytes | None:
    """Verify proof; return beta (64-byte VRF output) or None."""
    y = point_decompress(pk)
    if y is None:
        return None
    dec = decode_proof(pi)
    if dec is None:
        return None
    gamma, c, s = dec
    H = hash_to_curve(pk, alpha)
    # U = s*B - c*Y ;  V = s*H - c*Gamma
    U = point_add(point_mul(s, B), point_neg(point_mul(c, y)))
    V = point_add(point_mul(s, H), point_neg(point_mul(c, gamma)))
    c_prime = _hash_points(H, gamma, U, V)
    if int.from_bytes(c_prime, "little") != c:
        return None
    return proof_to_hash(pi)


def proof_to_hash(pi: bytes) -> bytes:
    """beta = SHA512(suite || 0x03 || encode(cofactor * Gamma))."""
    gamma = point_decompress(pi[:32])
    if gamma is None:
        raise ValueError("malformed proof")
    g8 = point_mul(8, gamma)
    return _sha512(SUITE + b"\x03" + point_compress(g8))
