"""Pure-Python host reference crypto: Ed25519, ECVRF (draft-03), CompactSum
KES, hashes. The ground truth for differential testing of the batched JAX
kernels, and the sign-side primitives for the chain synthesizer."""

from . import ecvrf, ed25519, hashes, kes  # noqa: F401
