"""Host hashing primitives (stdlib-backed) + Praos nonce/leader-value helpers.

Reference equivalents: `cardano-crypto-class` hash classes (Blake2b_256,
Blake2b_224) and the VRF range-extension helpers at
ouroboros-consensus-protocol/.../Protocol/Praos/VRF.hs:
  * InputVRF  = Blake2b-256(slot_be8 || epoch_nonce)     (VRF.hs:47,55-69)
  * leader value = "L"-tagged hash of the VRF output      (VRF.hs:103)
  * nonce value  = "N"-tagged double hash                 (VRF.hs:116)
"""

from __future__ import annotations

import hashlib


def blake2b_256(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def blake2b_224(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=28).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# -- Praos range extension ---------------------------------------------------


def input_vrf(slot: int, epoch_nonce: bytes) -> bytes:
    """Seed for the per-slot VRF evaluation."""
    return blake2b_256(slot.to_bytes(8, "big") + epoch_nonce)


def vrf_leader_value(beta: bytes) -> int:
    """256-bit leader-election value derived from the VRF output beta."""
    return int.from_bytes(blake2b_256(b"L" + beta), "big")


def vrf_nonce_value(beta: bytes) -> bytes:
    """Per-block nonce contribution ("N"-tagged double hash)."""
    return blake2b_256(blake2b_256(b"N" + beta))


def nonce_combine(a: bytes, b: bytes) -> bytes:
    """Nonce evolution eta' = eta (*) v  (hash of concatenation).

    NOT associative (hash(hash(a||b)||c) != hash(a||hash(b||c))): nonce
    evolution is inherently a sequential fold. The TPU pipeline computes
    the per-header nonce values (vrf_nonce_value) in batch on device and
    threads this fold on host — do not replace it with a parallel scan.
    """
    return blake2b_256(a + b)
