"""Fast host sign-side dispatch: native C++ when available, pure Python
otherwise — byte-identical either way (both are the deterministic
RFC 8032 / ECVRF-draft-03 constructions; differential test:
tests/test_native_crypto.py).

The pure modules (ed25519.py, ecvrf.py, kes.py) stay untouched as the
REFERENCE implementations; forging-side callers (fixtures, forge,
hotkey, db_synthesizer) route through here so benchmark chains and
ThreadNet nodes sign at C speed.
"""

from __future__ import annotations

from . import ecvrf as _ecvrf
from . import ed25519 as _ed25519


def _lib():
    from ... import native_loader

    return native_loader.load_crypto()


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    if _lib() is not None:
        from ... import native_loader

        return native_loader.native_ed25519_sign(seed, msg)
    return _ed25519.sign(seed, msg)


def ed25519_public(seed: bytes) -> bytes:
    if _lib() is not None:
        from ... import native_loader

        return native_loader.native_ed25519_public(seed)
    return _ed25519.secret_to_public(seed)


def vrf_batch_compat() -> bool:
    """OCT_VRF_BATCH (default 1): forge batch-compatible 128-byte ECVRF
    proofs (Gamma ‖ U ‖ V ‖ s — the aggregatable PraosBatchCompat shape).
    =0 restores draft-03 80-byte proofs end to end. Read per call so
    tests can toggle both formats in one process."""
    import os

    return os.environ.get("OCT_VRF_BATCH", "1") != "0"


def ecvrf_prove(seed: bytes, alpha: bytes) -> bytes:
    """Proof in the configured format (vrf_batch_compat)."""
    if vrf_batch_compat():
        if _lib() is not None:
            from ... import native_loader

            return native_loader.native_ecvrf_prove_bc(seed, alpha)
        return _ecvrf.prove_batch_compat(seed, alpha)
    if _lib() is not None:
        from ... import native_loader

        return native_loader.native_ecvrf_prove(seed, alpha)
    return _ecvrf.prove(seed, alpha)


def ecvrf_proof_to_hash(pi: bytes) -> bytes:
    lib = _lib()
    if lib is not None:
        import ctypes

        out = ctypes.create_string_buffer(64)
        if lib.oc_ecvrf_proof_to_hash(pi, out):
            return out.raw
    return _ecvrf.proof_to_hash(pi)
