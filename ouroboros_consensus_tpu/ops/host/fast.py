"""Fast host sign-side dispatch: native C++ when available, pure Python
otherwise — byte-identical either way (both are the deterministic
RFC 8032 / ECVRF-draft-03 constructions; differential test:
tests/test_native_crypto.py).

The pure modules (ed25519.py, ecvrf.py, kes.py) stay untouched as the
REFERENCE implementations; forging-side callers (fixtures, forge,
hotkey, db_synthesizer) route through here so benchmark chains and
ThreadNet nodes sign at C speed.
"""

from __future__ import annotations

from . import ecvrf as _ecvrf
from . import ed25519 as _ed25519


def _lib():
    from ... import native_loader

    return native_loader.load_crypto()


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    if _lib() is not None:
        from ... import native_loader

        return native_loader.native_ed25519_sign(seed, msg)
    return _ed25519.sign(seed, msg)


def ed25519_public(seed: bytes) -> bytes:
    if _lib() is not None:
        from ... import native_loader

        return native_loader.native_ed25519_public(seed)
    return _ed25519.secret_to_public(seed)


def ecvrf_prove(seed: bytes, alpha: bytes) -> bytes:
    if _lib() is not None:
        from ... import native_loader

        return native_loader.native_ecvrf_prove(seed, alpha)
    return _ecvrf.prove(seed, alpha)


def ecvrf_proof_to_hash(pi: bytes) -> bytes:
    lib = _lib()
    if lib is not None:
        import ctypes

        out = ctypes.create_string_buffer(64)
        if lib.oc_ecvrf_proof_to_hash(pi, out):
            return out.raw
    return _ecvrf.proof_to_hash(pi)
