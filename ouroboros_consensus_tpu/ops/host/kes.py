"""CompactSum KES (key-evolving signatures) host reference implementation.

Reference equivalents: `cardano-crypto-class` `Cardano.Crypto.KES.CompactSum`
(Haskell over libsodium Ed25519 + Blake2b-256), reached from the Praos hot
path at ouroboros-consensus-protocol/.../Protocol/Praos.hs:582
(verifySignedKES on the header body) and from storage integrity checks at
ouroboros-consensus-cardano/src/shelley/.../Ledger/Integrity.hs:14-20.

Structure (depth d, 2^d periods, the default d=7 follows SURVEY.md §2.5):
  * verification key of a node = Blake2b-256(vk_left || vk_right)
  * a CompactSum signature carries the leaf Ed25519 signature, the leaf
    verification key, and ONE sibling vk per level; the verifier
    reconstructs the root hash bottom-up and compares with the declared vk.
  * signature size = 64 + 32 + 32*d bytes (d=7 -> 320).

Key derivation: seeds split top-down, left = Blake2b-256(0x01 || seed),
right = Blake2b-256(0x02 || seed); the leaf seed is an Ed25519 seed.
Subtree vks are memoised so a full tree is derived once per cold key.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

from . import ed25519

# Cardano's StandardCrypto resolves KES to Sum6KES (6 levels, 64 periods;
# consistent with maxKESEvolutions=62). Depth stays a parameter everywhere;
# callers wanting the 128-period variant pass depth=7.
DEFAULT_DEPTH = 6

SIG_BYTES_LEAF = 96  # 64-byte Ed25519 sig + 32-byte leaf vk


def sig_bytes(depth: int) -> int:
    return SIG_BYTES_LEAF + 32 * depth


def _h256(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def _seed_left(seed: bytes) -> bytes:
    return _h256(b"\x01" + seed)


def _seed_right(seed: bytes) -> bytes:
    return _h256(b"\x02" + seed)


@lru_cache(maxsize=1 << 14)
def derive_vk(seed: bytes, depth: int) -> bytes:
    """Verification key of the subtree rooted at `seed` with `depth` levels."""
    if depth == 0:
        # leaf key derivation routes through the fast dispatch (native
        # C when built; byte-identical) — tree derivation dominates the
        # sign-side cost otherwise
        from . import fast

        return fast.ed25519_public(seed)
    return _h256(
        derive_vk(_seed_left(seed), depth - 1)
        + derive_vk(_seed_right(seed), depth - 1)
    )


def sign(seed: bytes, depth: int, period: int, msg: bytes) -> bytes:
    """CompactSum signature for `period` (0 <= period < 2^depth)."""
    if not 0 <= period < (1 << depth):
        raise ValueError(f"period {period} out of range for depth {depth}")
    if depth == 0:
        from . import fast

        return fast.ed25519_sign(seed, msg) + fast.ed25519_public(seed)
    half = 1 << (depth - 1)
    s0, s1 = _seed_left(seed), _seed_right(seed)
    if period < half:
        inner = sign(s0, depth - 1, period, msg)
        vk_other = derive_vk(s1, depth - 1)
    else:
        inner = sign(s1, depth - 1, period - half, msg)
        vk_other = derive_vk(s0, depth - 1)
    return inner + vk_other


def _reconstruct_vk(sig: bytes, depth: int, period: int, msg: bytes) -> bytes | None:
    """Verify the leaf signature and reconstruct the root vk, or None."""
    if depth == 0:
        if len(sig) != SIG_BYTES_LEAF:
            return None
        ed_sig, vk_leaf = sig[:64], sig[64:96]
        if not ed25519.verify(vk_leaf, msg, ed_sig):
            return None
        return vk_leaf
    half = 1 << (depth - 1)
    inner, vk_other = sig[:-32], sig[-32:]
    if period < half:
        vk0 = _reconstruct_vk(inner, depth - 1, period, msg)
        if vk0 is None:
            return None
        return _h256(vk0 + vk_other)
    vk1 = _reconstruct_vk(inner, depth - 1, period - half, msg)
    if vk1 is None:
        return None
    return _h256(vk_other + vk1)


def verify(vk: bytes, depth: int, period: int, msg: bytes, sig: bytes) -> bool:
    if len(sig) != sig_bytes(depth) or not 0 <= period < (1 << depth):
        return False
    return _reconstruct_vk(sig, depth, period, msg) == vk


def leaf_path(seed: bytes, depth: int, period: int):
    """(leaf_seed, siblings bottom-up) for `period` — the static part of
    a CompactSum signature: sign the leaf seed over the message (host or
    ops/ed25519_batch.sign) and append vk_leaf + this sibling path to
    assemble the full signature."""
    if not 0 <= period < (1 << depth):
        raise ValueError(f"period {period} out of range for depth {depth}")
    sibs: list[bytes] = []

    def walk(sd: bytes, d: int, per: int) -> bytes:
        if d == 0:
            return sd
        half = 1 << (d - 1)
        s0, s1 = _seed_left(sd), _seed_right(sd)
        if per < half:
            leaf = walk(s0, d - 1, per)
            sibs.append(derive_vk(s1, d - 1))
        else:
            leaf = walk(s1, d - 1, per - half)
            sibs.append(derive_vk(s0, d - 1))
        return leaf

    leaf = walk(seed, depth, period)
    return leaf, sibs


def decompose_sig(sig: bytes, depth: int):
    """Split a CompactSum signature into (ed_sig 64, vk_leaf 32, [sibling vks
    bottom-up: level 1 .. depth]). Used by SoA staging for the batch kernel."""
    if len(sig) != sig_bytes(depth):
        raise ValueError("bad signature size")
    ed_sig, vk_leaf = sig[:64], sig[64:96]
    siblings = [sig[96 + 32 * i : 128 + 32 * i] for i in range(depth)]
    return ed_sig, vk_leaf, siblings
