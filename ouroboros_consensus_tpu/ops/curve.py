"""Batched edwards25519 point arithmetic on limb-vector coordinates.

Points are extended homogeneous coordinates (X, Y, Z, T) with T = XY/Z,
each coordinate a nearly-normalized field element [..., 20] (ops/field.py).
All control flow is batch-uniform: failures (bad encodings) are carried as
mask lanes, never branches — the TPU-native discipline for the Praos hot
path (SURVEY.md section 7.3).

The unified addition law (complete for twisted Edwards a=-1) is used for
both generic adds and table lookups, so the identity and doublings need no
special-casing inside ladders.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
from jax import lax
from jax import numpy as jnp

from . import bigint as bi
from . import field as fe
from .host import ed25519 as he


class Point(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(batch_shape=()) -> Point:
    return Point(
        fe.zeros(batch_shape),
        fe.ones(batch_shape),
        fe.ones(batch_shape),
        fe.zeros(batch_shape),
    )


def add(p: Point, q: Point) -> Point:
    a = fe.mul(fe.sub(p.y, p.x), fe.sub(q.y, q.x))
    b = fe.mul(fe.add(p.y, p.x), fe.add(q.y, q.x))
    c = fe.mul(fe.mul_small(fe.mul(p.t, q.t), 2), fe.constant(fe.D_INT))
    d = fe.mul_small(fe.mul(p.z, q.z), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def double(p: Point) -> Point:
    a = fe.sqr(p.x)
    b = fe.sqr(p.y)
    c = fe.mul_small(fe.sqr(p.z), 2)
    h = fe.add(a, b)
    e = fe.sub(h, fe.sqr(fe.add(p.x, p.y)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def double_partial(x, y, z):
    """Doubling on projective (X, Y, Z) only — T is not an input of the
    doubling formulas, so runs of doublings between window adds can skip
    the T = E*H product (1 of 8 muls) until the last step."""
    a = fe.sqr(x)
    b = fe.sqr(y)
    c = fe.mul_small(fe.sqr(z), 2)
    h = fe.add(a, b)
    e = fe.sub(h, fe.sqr(fe.add(x, y)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return fe.mul(e, f), fe.mul(g, h), fe.mul(f, g)


def doubles(p: Point, k: int) -> Point:
    """k successive doublings; T is only materialized by the last one
    (the doubling formulas never read p.t)."""
    x, y, z = p.x, p.y, p.z
    for _ in range(k - 1):
        x, y, z = double_partial(x, y, z)
    return double(Point(x, y, z, x))  # .t unused by double()


def neg(p: Point) -> Point:
    return Point(fe.neg(p.x), p.y, p.z, fe.neg(p.t))


def select(cond, p: Point, q: Point) -> Point:
    """cond ? p : q, cond shaped like the batch."""
    return Point(*(fe.select(cond, a, b) for a, b in zip(p, q)))


def eq(p: Point, q: Point):
    """Projective equality -> bool[...]. (Cross-multiplied, no inversion.)"""
    ex = fe.eq(fe.mul(p.x, q.z), fe.mul(q.x, p.z))
    ey = fe.eq(fe.mul(p.y, q.z), fe.mul(q.y, p.z))
    return ex & ey


def is_identity(p: Point):
    return fe.is_zero(p.x) & fe.eq(p.y, p.z)


def mul_cofactor(p: Point) -> Point:
    return double(double(double(p)))


# ---------------------------------------------------------------------------
# Scalar multiplication
# ---------------------------------------------------------------------------


def scalar_mul(bits, p: Point) -> Point:
    """Variable-base double-and-add. bits: [..., nb] int32 little-endian.

    Batch-uniform: every lane does nb doublings and nb selected adds.
    """
    nb = bits.shape[-1]
    rev = jnp.flip(bits, axis=-1)  # msb first

    def body(i, q):
        q = double(q)
        bit = lax.dynamic_index_in_dim(rev, i, axis=-1, keepdims=False)
        return select(bit == 1, add(q, p), q)

    return lax.fori_loop(0, nb, body, identity(bits.shape[:-1]))


def double_scalar_mul(bits_a, pa: Point, bits_b, pb: Point) -> Point:
    """a*PA + b*PB with a shared doubling chain (Strauss-Shamir)."""
    nb = max(bits_a.shape[-1], bits_b.shape[-1])

    def pad(bits):
        d = nb - bits.shape[-1]
        if d:
            bits = jnp.concatenate(
                [bits, jnp.zeros((*bits.shape[:-1], d), jnp.int32)], axis=-1
            )
        return jnp.flip(bits, axis=-1)

    ra, rb = pad(bits_a), pad(bits_b)
    pab = add(pa, pb)

    def body(i, q):
        q = double(q)
        ba = lax.dynamic_index_in_dim(ra, i, axis=-1, keepdims=False)
        bb = lax.dynamic_index_in_dim(rb, i, axis=-1, keepdims=False)
        qa = select(ba == 1, add(q, pa), q)
        qboth = select(ba == 1, add(q, pab), add(q, pb))
        return select(bb == 1, qboth, qa)

    return lax.fori_loop(0, nb, body, identity(ra.shape[:-1]))


def scalar_mul_w4(digits, p: Point) -> Point:
    """Variable-base windowed mul: digits [..., k] base-16, little-endian.

    Builds a per-lane table [0..15]*P (15 adds), then k iterations of
    4 doublings + one gathered table add. ~70% fewer adds than the bit
    ladder for 253-bit scalars (64 windows: 256 doubles + 79 adds).
    """
    k = digits.shape[-1]
    batch = digits.shape[:-1]

    # table[d] = d*P, extended coords stacked [..., 16, 4, NL]. Built
    # with a fori_loop + indexed store: the Python-unrolled build (14
    # point adds at trace time) multiplied out to ~15k HLO ops per call
    # site and dominated XLA compile time of the fused verifier.
    tbl = _build_lane_table(p, batch)
    rev = jnp.flip(digits, axis=-1)  # msb window first

    def body(i, q):
        q = doubles(q, 4)
        dw = lax.dynamic_index_in_dim(rev, i, axis=-1, keepdims=False)  # [...]
        return add(q, _table_lookup(tbl, dw))

    return lax.fori_loop(0, k, body, identity(batch))


def _build_lane_table(p: Point, batch):
    """Per-lane window table [..., 16, 4, NL] with table[d] = d*P."""

    def _stack_pt(q: Point):
        return jnp.stack([q.x, q.y, q.z, q.t], axis=-2)

    ident = identity(batch)
    tbl0 = jnp.zeros((*batch, 16, 4, ident.x.shape[-1]), ident.x.dtype)
    tbl0 = tbl0.at[..., 0, :, :].set(_stack_pt(ident))
    tbl0 = tbl0.at[..., 1, :, :].set(_stack_pt(p))

    def tbuild(i, carry):
        tbl, last = carry
        nxt = add(last, p)
        return tbl.at[..., i, :, :].set(_stack_pt(nxt)), nxt

    tbl, _ = lax.fori_loop(2, 16, tbuild, (tbl0, p))
    return tbl


def _table_lookup(tbl, dw) -> Point:
    e = jnp.take_along_axis(tbl, dw[..., None, None, None], axis=-3)
    e = e[..., 0, :, :]
    return Point(e[..., 0, :], e[..., 1, :], e[..., 2, :], e[..., 3, :])


def double_scalar_mul_w4(digits_a, pa: Point, digits_b, pb: Point) -> Point:
    """a*PA + b*PB with a SHARED doubling chain (windowed Strauss-Shamir):
    one run of 4 doublings per window plus two table adds, instead of two
    independent ladders — saves the second chain's doublings (the Praos
    ECVRF V = s*H - c*Gamma computation; cf. the batch-verification trick
    the reference cites at Praos/VRF.hs:13-14, applied per-lane so
    acceptance stays bit-exact with sequential verification).

    When b has fewer windows than a, the leading (high) windows run a
    single-stream phase — no identity adds for the missing b digits."""
    if digits_a.shape[-1] < digits_b.shape[-1]:
        digits_a, pa, digits_b, pb = digits_b, pb, digits_a, pa
    ka, kb = digits_a.shape[-1], digits_b.shape[-1]
    batch = digits_a.shape[:-1]

    ra = jnp.flip(digits_a, axis=-1)  # msb window first
    rb = jnp.flip(digits_b, axis=-1)
    ta = _build_lane_table(pa, batch)
    tb = _build_lane_table(pb, batch)

    def body_a(i, q):
        q = doubles(q, 4)
        da = lax.dynamic_index_in_dim(ra, i, axis=-1, keepdims=False)
        return add(q, _table_lookup(ta, da))

    def body_ab(i, q):
        da = lax.dynamic_index_in_dim(ra, (ka - kb) + i, axis=-1, keepdims=False)
        db = lax.dynamic_index_in_dim(rb, i, axis=-1, keepdims=False)
        q = doubles(q, 4)
        q = add(q, _table_lookup(ta, da))
        return add(q, _table_lookup(tb, db))

    q = lax.fori_loop(0, ka - kb, body_a, identity(batch))
    return lax.fori_loop(0, kb, body_ab, q)


# Fixed-base tables for B: `windows` windows of `wbits` bits each,
# TABLE[w][d] = d * 2^(wbits*w) * B. Built lazily on the host and cached.
_BASE_TABLES: dict[int, np.ndarray] = {}


def _base_table(wbits: int) -> np.ndarray:  # octlint: disable=OCT103 — append-only host memo of pure table builds; entries never change once written
    if wbits not in _BASE_TABLES:
        windows = 256 // wbits
        tbl = np.zeros((windows, 1 << wbits, 4, fe.NLIMBS), dtype=np.int32)
        wbase = he.B
        for w in range(windows):
            acc = he.IDENT
            for d in range(1 << wbits):
                x, y, z, t = acc
                zi = pow(z, fe.P_INT - 2, fe.P_INT)
                ax, ay = x * zi % fe.P_INT, y * zi % fe.P_INT
                tbl[w, d, 0] = fe.int_to_limbs_np(ax)
                tbl[w, d, 1] = fe.int_to_limbs_np(ay)
                tbl[w, d, 2] = fe.int_to_limbs_np(1)
                tbl[w, d, 3] = fe.int_to_limbs_np(ax * ay % fe.P_INT)
                acc = he.point_add(acc, wbase)
            for _ in range(wbits):
                wbase = he.point_double(wbase)
        _BASE_TABLES[wbits] = tbl
    return _BASE_TABLES[wbits]


def _base_mul_windows(digits, wbits: int) -> Point:
    """Fixed-base s·B by table walk. On the SIGN path the digits derive
    from the secret nonce/scalar, making the window-table `jnp.take`
    below the repo's one secret-indexed access — pinned as such in
    analysis/certified.json (octrange taint pass; any second
    secret-steered site is a ratchet violation). Batch lanes gather the
    whole [2^wbits, 4, 20] window from device memory with no
    CPU-cache-line timing channel, but the inventory stays explicit."""
    table = jnp.asarray(_base_table(wbits))  # [windows, 2^wbits, 4, 20]
    windows = table.shape[0]

    def body(w, q):
        tw = lax.dynamic_index_in_dim(table, w, axis=0, keepdims=False)
        dw = lax.dynamic_index_in_dim(digits, w, axis=-1, keepdims=False)
        entry = jnp.take(tw, dw, axis=0)  # [..., 4, 20]
        pt = Point(
            entry[..., 0, :], entry[..., 1, :], entry[..., 2, :], entry[..., 3, :]
        )
        return add(q, pt)

    return lax.fori_loop(0, windows, body, identity(digits.shape[:-1]))


def base_mul(digits) -> Point:
    """s*B from base-16 digits [..., 64] (s < 2^256, canonical digits)."""
    return _base_mul_windows(digits, 4)


def base_mul_w8(digits) -> Point:
    """s*B from base-256 digits [..., 32]: half the adds of base_mul in
    exchange for a 256-entry-per-window table (~2.6 MB device constant)."""
    return _base_mul_windows(digits, 8)


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------


def decompress(b32):
    """[..., 32] bytes -> (ok[...], Point). Rejects non-canonical y (>= p),
    non-residue x^2, and x=0 with sign bit set — matching the host
    reference point_decompress (ops/host/ed25519.py)."""
    b32 = b32.astype(jnp.int32)
    sign = (b32[..., 31] >> 7) & 1
    y = fe.from_bytes(b32.at[..., 31].set(b32[..., 31] & 0x7F))
    y_ok = ~bi.geq(y, jnp.broadcast_to(jnp.asarray(fe.P_LIMBS), y.shape))
    one = fe.ones(y.shape[:-1])
    y2 = fe.sqr(y)
    num = fe.sub(y2, one)
    den = fe.add(fe.mul(y2, fe.constant(fe.D_INT)), one)
    ok_sqrt, x = fe.sqrt_ratio(num, den)
    x_zero = fe.is_zero(x)
    flip = (fe.parity(x) != sign) & ~x_zero
    x = fe.select(flip, fe.neg(x), x)
    ok = y_ok & ok_sqrt & ~(x_zero & (sign == 1))
    return ok, Point(x, y, one, fe.mul(x, y))


def compress(p: Point):
    """Point -> [..., 32] int32 bytes. One inv chain per batch lane; stack
    multiple points on a new axis to amortize (vectorized chain)."""
    zi = fe.inv(p.z)
    x = fe.canonical(fe.mul(p.x, zi))
    y = fe.mul(p.y, zi)
    b = fe.to_bytes(y)
    sign = (x[..., 0] & 1) << 7
    return b.at[..., 31].add(sign)


def compress_many(points):
    """Compress k points sharing ONE inversion chain (Montgomery's trick:
    k-1 prefix muls + 1 inv + 2(k-1) muls instead of k inversions).
    Used by the ECVRF challenge hash (compresses H, Gamma, U, V)."""
    zs = [p.z for p in points]
    prefix = [zs[0]]
    for z in zs[1:]:
        prefix.append(fe.mul(prefix[-1], z))
    acc = fe.inv(prefix[-1])
    invs: list = [None] * len(zs)
    for i in range(len(zs) - 1, 0, -1):
        invs[i] = fe.mul(acc, prefix[i - 1])
        acc = fe.mul(acc, zs[i])
    invs[0] = acc
    outs = []
    for p, zi in zip(points, invs):
        x = fe.canonical(fe.mul(p.x, zi))
        b = fe.to_bytes(fe.mul(p.y, zi))
        outs.append(b.at[..., 31].add((x[..., 0] & 1) << 7))
    return outs
