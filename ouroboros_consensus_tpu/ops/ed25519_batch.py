"""Batched Ed25519 verification on device (cofactorless, RFC 8032).

Per lane: decompress A and R, reject non-canonical s, compute the
challenge h = SHA-512(R ‖ A ‖ M) mod L on device, and check
s·B == R + h·A with a fixed-base table for s·B and a windowed ladder for
h·A. All failures are mask lanes — batch-uniform control flow throughout.

Host staging (`stage_np`) pads R ‖ A ‖ M into SHA-512 blocks; messages in a
batch may have different lengths (per-lane block counts, masked on device).

Reference equivalent: libsodium `crypto_sign_verify_detached`
(cofactorless) via `cardano-crypto-class` Ed25519DSIGN — the OCert
cold-key check in the Praos hot path
(ouroboros-consensus-protocol/.../Protocol/Praos.hs:580) and Byron/tx
witness checks. Differentially tested against ops/host/ed25519.py.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np
from jax import numpy as jnp

from . import curve, field as fe, scalar, sha512


class Ed25519Batch(NamedTuple):
    """SoA staging of a verification batch (host numpy arrays)."""

    pk: np.ndarray  # [B, 32] uint8
    r: np.ndarray  # [B, 32] uint8
    s: np.ndarray  # [B, 32] uint8
    hblocks: np.ndarray  # [B, NB, 16, 2] uint32 — padded SHA-512(R||A||M)
    hnblocks: np.ndarray  # [B] int32


def stage_np(
    pks: Sequence[bytes], sigs: Sequence[bytes], msgs: Sequence[bytes], nb: int | None = None
) -> Ed25519Batch:
    """Stage (pk, sig, msg) triples into device-ready arrays."""
    assert len(pks) == len(sigs) == len(msgs)
    b = len(pks)
    assert all(len(p) == 32 for p in pks)
    assert all(len(sig) == 64 for sig in sigs)
    # one C-level join + reshape per column (a per-row np.frombuffer
    # loop dominated staging at ~24 conversions/header)
    pk = np.frombuffer(b"".join(pks), np.uint8).reshape(b, 32).copy()
    rs = np.frombuffer(b"".join(sigs), np.uint8).reshape(b, 64)
    r = np.ascontiguousarray(rs[:, :32])
    s = np.ascontiguousarray(rs[:, 32:])
    hmsgs = [sig[:32] + p + m for p, sig, m in zip(pks, sigs, msgs)]
    hblocks, hnblocks = sha512.pad_messages_np(hmsgs, nb)
    return Ed25519Batch(pk, r, s, hblocks, hnblocks)


def build_hblocks(r, pk, msg):
    """Device staging of the challenge-hash input R ‖ A ‖ M for a batch
    of FIXED-length messages: [..., 32] r/pk byte arrays + [..., M] msg
    -> (hblocks [..., NB, 16, 2] uint32, hnblocks [...] int32),
    byte-identical to the blocks `stage_np` pads on host. Used by the
    packed-staging path (protocol/batch.stage_packed), which ships the
    raw message columns and moves the SHA padding into the jit."""
    data = jnp.concatenate(
        [r.astype(jnp.uint8), pk.astype(jnp.uint8), msg.astype(jnp.uint8)],
        axis=-1,
    )
    return sha512.pad_blocks_fixed(data, 64 + msg.shape[-1])


def verify_point(pk, s, hblocks, hnblocks):
    """(ok_pre bool[B], P Point) with P = s·B − h·A.

    The RFC 8032 cofactorless equation s·B == R + h·A holds iff the
    canonical compression of P equals the signature's 32 R bytes: a
    valid R encoding decompresses to exactly one point whose canonical
    re-compression is itself, and every invalid-or-non-canonical R
    (y ≥ p, off-curve, x=0 with sign bit) can never equal a canonical
    compression — so compare-on-bytes is bit-exact with the reference's
    decompress-then-compare while skipping R's square-root chain."""
    ok_a, a_pt = curve.decompress(jnp.asarray(pk).astype(jnp.int32))
    s = jnp.asarray(s).astype(jnp.int32)
    s_ok = scalar.is_canonical32(s)

    digest = sha512.sha512(jnp.asarray(hblocks), jnp.asarray(hnblocks))
    h = scalar.reduce512(digest)  # [B, 20] limbs < L

    sb = curve.base_mul_w8(
        scalar.windows8_from_bits(scalar.bits_from_bytes(s, 256))
    )
    h_digits = scalar.windows4_from_bits(scalar.bits_from_limbs(h, 256))
    nha = curve.scalar_mul_w4(h_digits, curve.neg(a_pt))
    return ok_a & s_ok, curve.add(sb, nha)


def verify(pk, r, s, hblocks, hnblocks):
    """Device kernel: -> ok bool[B]. Arguments as in Ed25519Batch."""
    ok_pre, p = verify_point(pk, s, hblocks, hnblocks)
    enc = curve.compress(p)
    r_bytes = jnp.asarray(r).astype(jnp.int32)
    return ok_pre & jnp.all(enc == r_bytes, axis=-1)


# ---------------------------------------------------------------------------
# Sign side (db-synthesizer / forging loop: HotKey.sign + OCert issuance)
# ---------------------------------------------------------------------------


class Ed25519SignBatch(NamedTuple):
    """SoA staging of a signing batch (host numpy arrays)."""

    a: np.ndarray  # [B, 32] uint8 — clamped secret scalar (LE)
    a_enc: np.ndarray  # [B, 32] uint8 — public key bytes
    rblocks: np.ndarray  # SHA-512(prefix ‖ msg) padded blocks
    rnblocks: np.ndarray
    hblocks: np.ndarray  # SHA-512(<64-byte hole> ‖ msg) padded blocks
    hnblocks: np.ndarray


def stage_sign_np(seeds: Sequence[bytes], msgs: Sequence[bytes], nb: int | None = None) -> Ed25519SignBatch:
    """Expand seeds host-side (one SHA-512 each) and stage both hash
    inputs; the challenge-hash hole is spliced with R ‖ A on device."""
    from .host import ed25519 as he

    b = len(seeds)
    a = np.zeros((b, 32), np.uint8)
    a_enc = np.zeros((b, 32), np.uint8)
    rmsgs, hmsgs = [], []
    for i, (seed, m) in enumerate(zip(seeds, msgs)):
        x_bytes, prefix, pk = he.expand_for_staging(seed)
        a[i] = np.frombuffer(x_bytes, np.uint8)
        a_enc[i] = np.frombuffer(pk, np.uint8)
        rmsgs.append(prefix + m)
        hmsgs.append(b"\x00" * 64 + m)
    rblocks, rnblocks = sha512.pad_messages_np(rmsgs, nb)
    hblocks, hnblocks = sha512.pad_messages_np(hmsgs, nb)
    return Ed25519SignBatch(a, a_enc, rblocks, rnblocks, hblocks, hnblocks)


def sign(a, a_enc, rblocks, rnblocks, hblocks, hnblocks):
    """Device kernel -> (r_enc [B,32], s [B,32]) int32 byte arrays.

    RFC 8032 sign with the expensive parts batched: r = H(prefix‖M) mod
    L, R = r·B (wide fixed-base table), h = H(R‖A‖M) mod L (the R‖A hole
    spliced on device), s = r + h·a mod L. Mirrors ops/host/ed25519.sign;
    the reference reaches this via HotKey.sign / forgeBlock
    (ouroboros-consensus-protocol/.../Protocol/Ledger/HotKey.hs:124,
    shelley Protocol/Praos.hs:102).

    Secret-flow certificate (octrange): `a` and the nonce-hash blocks
    carry REAL `secret:` taint marks (analysis/shapes.json
    `ed25519_sign`); the taint pass proves they reach no branch
    predicate and exactly ONE access pattern — the fixed-base ladder's
    window-table gather in ops/curve._base_mul_windows, pinned in
    analysis/certified.json. The outputs (R, s) are a public signature
    by construction, so output materialization is declassified there."""
    from . import bigint as bi

    r = scalar.reduce512(sha512.sha512(jnp.asarray(rblocks), jnp.asarray(rnblocks)))
    big_r = curve.base_mul_w8(
        scalar.windows8_from_bits(scalar.bits_from_limbs(r, 256))
    )
    r_enc = curve.compress(big_r)  # [B, 32] int32
    a_enc = jnp.asarray(a_enc).astype(jnp.int32)
    spliced = sha512.splice_prefix64(
        jnp.asarray(hblocks), jnp.concatenate([r_enc, a_enc], axis=-1)
    )
    h = scalar.reduce512(sha512.sha512(spliced, jnp.asarray(hnblocks)))
    a_limbs = bi.bytes_to_limbs(jnp.asarray(a).astype(jnp.int32), 20)
    s = scalar.add_mod_l(r, scalar.mul_mod_l(h, a_limbs))
    return r_enc, scalar.to_bytes32(s)


_SIGN_JIT = None


def sign_batch(seeds, msgs):
    """Host convenience: -> [B, 64] uint8 signatures (R ‖ s)."""
    import jax

    global _SIGN_JIT
    if _SIGN_JIT is None:
        _SIGN_JIT = jax.jit(sign)
    batch = stage_sign_np(seeds, msgs)
    r_enc, s = _SIGN_JIT(*(jnp.asarray(x) for x in batch))
    out = np.concatenate(
        [np.asarray(r_enc), np.asarray(s)], axis=-1
    ).astype(np.uint8)
    return out


def verify_batch(pks, sigs, msgs) -> np.ndarray:
    """Host convenience: stage + run (jit cached by (B, NB) shape)."""
    import jax

    batch = stage_np(pks, sigs, msgs)
    fn = _jitted()
    return np.asarray(fn(*(jnp.asarray(x) for x in batch)))


_JIT = None


def _jitted():
    global _JIT
    if _JIT is None:
        import jax

        _JIT = jax.jit(verify)
    return _JIT
