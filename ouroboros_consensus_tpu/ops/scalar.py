"""Batched arithmetic mod the Ed25519 group order L (on-device).

L = 2^252 + 27742317777372353535851937790883648493.

The verify hot path needs exactly two things here:
  * reduce a 512-bit SHA-512 digest mod L (Barrett reduction in 13-bit
    limbs) to obtain the challenge scalar h — ops/ed25519_batch.py;
  * canonicality checks s < L on 32-byte signature scalars.

Reference equivalent: libsodium's sc25519_reduce / sc25519_is_canonical as
used by crypto_sign_verify_detached and the vendored VRF (call sites cited
in ops/host/ed25519.py and ops/host/ecvrf.py).
"""

from __future__ import annotations

import numpy as np
from jax import numpy as jnp

from . import bigint as bi

BITS = bi.BITS

L_INT = 2**252 + 27742317777372353535851937790883648493
NL = 20  # limbs for values < 2^260

L_LIMBS = bi.int_to_limbs_np(L_INT, NL)
L21 = bi.int_to_limbs_np(L_INT, 21)

# Barrett parameters: a = 19 limbs (247 bits), b = 21 limbs (273 bits)
_A_LIMBS = 19
_B_LIMBS = 21
MU = bi.int_to_limbs_np((1 << (BITS * (_A_LIMBS + _B_LIMBS))) // L_INT, 21)


def _barrett_reduce40(v):
    """[..., 40] NORMALIZED limbs (value < 2^512) -> [..., 20] limbs < L.

    Barrett: q = ((V >> 247) * mu) >> 273, r = V - q*L, then up to three
    conditional subtractions (error bound q - q_hat <= 2).
    """
    v1 = bi.shift_right_limbs(v, _A_LIMBS)  # 21 limbs
    t = bi.mul(v1, jnp.broadcast_to(jnp.asarray(MU), (*v1.shape[:-1], 21)))
    q = bi.shift_right_limbs(t, _B_LIMBS)[..., :21]  # <= 2^260: 21 limbs
    ql = bi.mul(q, jnp.broadcast_to(jnp.asarray(L21), (*q.shape[:-1], 21)))
    # bi.mul output limbs can slightly exceed MASK (vectorized carry
    # passes only); sub_mod_2k's borrow logic needs a normalized
    # subtrahend, so run a full sequential carry first.
    ql, _ = bi.seq_carry(ql)
    # r = V - q*L fits in [0, 3L) < 2^254 => compute mod 2^(13*21) exactly
    r = bi.sub_mod_2k(v, ql, 21)
    lc = jnp.broadcast_to(jnp.asarray(L21), r.shape)
    for _ in range(3):
        r = bi.cond_sub(r, lc)
    return r[..., :NL]


def reduce512(digest_bytes):
    """[..., 64] little-endian bytes (SHA-512 output) -> [..., 20] limbs < L."""
    return _barrett_reduce40(bi.bytes_to_limbs(digest_bytes, 40))


def mul_mod_l(a, b):
    """a*b mod L for [..., 20]-limb operands with a*b < 2^512 (sign-side
    h·a and c·x: clamped secret scalars are < 2^255, NOT < L — the only
    true requirement is the Barrett input bound)."""
    p = bi.mul(a, b)  # [..., 40], nearly normalized
    p, _ = bi.seq_carry(p)
    return _barrett_reduce40(p)


def add_mod_l(a, b):
    """(a + b) mod L for [..., 20]-limb scalars < L."""
    s, carry_out = bi.seq_carry(a + b)  # sum < 2L < 2^254: no carry-out
    s = jnp.concatenate([s, carry_out[..., None]], axis=-1)  # 21 limbs
    lc = jnp.broadcast_to(jnp.asarray(L21), s.shape)
    return bi.cond_sub(s, lc)[..., :NL]


def to_bytes32(x):
    """[..., 20] normalized limbs (< 2^256) -> [..., 32] int32 LE bytes."""
    bits = bi.limbs_to_bits(x, 256)
    groups = bits.reshape(*x.shape[:-1], 32, 8)
    return jnp.sum(groups * (1 << jnp.arange(8, dtype=jnp.int32)), axis=-1)


def is_canonical32(s_bytes):
    """s < L for [..., 32]-byte little-endian scalars -> bool[...]."""
    s = bi.bytes_to_limbs(s_bytes, NL)
    lim = jnp.broadcast_to(jnp.asarray(L_LIMBS), s.shape)
    return ~bi.geq(s, lim)


def bits_from_limbs(x, nbits: int = 253):
    return bi.limbs_to_bits(x, nbits)


def bits_from_bytes(b, nbits: int):
    """[..., n] LE bytes -> [..., nbits] bits, little-endian."""
    bits = (b.astype(jnp.int32)[..., :, None] >> jnp.arange(8, dtype=jnp.int32)) & 1
    return bits.reshape(*b.shape[:-1], b.shape[-1] * 8)[..., :nbits]


def windows4_from_bits(bits):
    """[..., 4k] bits -> [..., k] base-16 digits (for fixed-base tables)."""
    nb = bits.shape[-1]
    assert nb % 4 == 0
    g = bits.reshape(*bits.shape[:-1], nb // 4, 4)
    return jnp.sum(g * jnp.asarray([1, 2, 4, 8], jnp.int32), axis=-1)


def windows8_from_bits(bits):
    """[..., 8k] bits -> [..., k] base-256 digits (wide fixed-base windows:
    half the adds of base-16 in exchange for a 256-entry shared table)."""
    nb = bits.shape[-1]
    assert nb % 8 == 0
    g = bits.reshape(*bits.shape[:-1], nb // 8, 8)
    return jnp.sum(g * jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.int32), axis=-1)
