"""Limb-first SHA-512 + Blake2b for Pallas kernels.

Byte strings are [n, T] int32 (values 0..255), T = batch tile on lanes.
64-bit words are (hi, lo) pairs of uint32 [T] arrays, exactly as
ops/u64.py, but kept as Python tuples/lists so every round is straight-
line code over [T] vectors — inside a Pallas kernel the whole message
schedule lives in registers/VMEM.

The rounds are Python-unrolled (80 for SHA-512, 12 for Blake2b) ON TPU:
Mosaic compiles the straight-line body quickly, and unrolling makes
every SIGMA message permutation and round constant STATIC — no gathers.
On CPU the same public functions delegate to the rolled XLA twins
(ops/sha512.py, ops/blake2b.py) through layout adapters, because
XLA:CPU's LLVM pipeline takes minutes on the unrolled HLO — the exact
pathology those twins were built to avoid. Both paths are byte-exact
(differentially tested against hashlib).

Reference equivalent: libsodium SHA-512 / Blake2b as used by Ed25519,
the vendored ECVRF, and CompactSum KES (see ops/sha512.py docstring).
"""

from __future__ import annotations

import os

import numpy as np
from jax import numpy as jnp

from .. import blake2b as _xb
from .. import sha512 as _xs
from .. import u64
from ..blake2b import _SIGMA, IV as _B2B_IV
from ..sha512 import H0 as _SHA_H0, K as _SHA_K

BLOCK = 128

# "tpu" -> unrolled limb-first rounds; anything else -> rolled XLA twins
# via layout adapters. Overridable for testing the unrolled path on CPU.
FORCE_IMPL = os.environ.get("OCT_PK_HASH_IMPL", "")


def _unrolled() -> bool:
    if FORCE_IMPL:
        return FORCE_IMPL == "unrolled"
    import jax

    return jax.devices()[0].platform == "tpu"


def const_rows(vals, t):
    """[len(vals), t] int32 built from scalar-immediate fills — kernels
    cannot close over array constants, and Mosaic cannot broadcast
    column vectors, but scalar->vector fills are native."""
    return jnp.stack([jnp.full((t,), int(v), jnp.int32) for v in vals], axis=0)


# ---------------------------------------------------------------------------
# Bytes [128, T] -> 16 (hi, lo) word pairs
# ---------------------------------------------------------------------------


def _words_be(block_bytes):
    """[128, T] bytes -> list of 16 (hi, lo) uint32 [T] pairs (big-endian,
    SHA-512 order)."""
    b = block_bytes.astype(jnp.uint32)
    words = []
    for w in range(16):
        o = 8 * w
        hi = (b[o] << 24) | (b[o + 1] << 16) | (b[o + 2] << 8) | b[o + 3]
        lo = (b[o + 4] << 24) | (b[o + 5] << 16) | (b[o + 6] << 8) | b[o + 7]
        words.append((hi, lo))
    return words


def _words_le(block_bytes):
    """[128, T] bytes -> 16 (hi, lo) pairs (little-endian, Blake2b order)."""
    b = block_bytes.astype(jnp.uint32)
    words = []
    for w in range(16):
        o = 8 * w
        lo = b[o] | (b[o + 1] << 8) | (b[o + 2] << 16) | (b[o + 3] << 24)
        hi = b[o + 4] | (b[o + 5] << 8) | (b[o + 6] << 16) | (b[o + 7] << 24)
        words.append((hi, lo))
    return words


def _digest_bytes_be(words):
    """8 (hi, lo) pairs -> [64, T] int32 bytes (SHA-512 digest order)."""
    rows = []
    for h, l in words:
        for p in (h >> 24, h >> 16, h >> 8, h, l >> 24, l >> 16, l >> 8, l):
            rows.append((p & jnp.uint32(0xFF)).astype(jnp.int32))
    return jnp.stack(rows, axis=0)


def _digest_bytes_le(words, nbytes: int):
    """(hi, lo) pairs -> [nbytes, T] int32 bytes (Blake2b digest order)."""
    rows = []
    for h, l in words:
        for p in (l, l >> 8, l >> 16, l >> 24, h, h >> 8, h >> 16, h >> 24):
            rows.append((p & jnp.uint32(0xFF)).astype(jnp.int32))
    return jnp.stack(rows[:nbytes], axis=0)


# ---------------------------------------------------------------------------
# SHA-512
# ---------------------------------------------------------------------------


def _bsig0(x):
    return u64.xor(u64.xor(u64.rotr(x, 28), u64.rotr(x, 34)), u64.rotr(x, 39))


def _bsig1(x):
    return u64.xor(u64.xor(u64.rotr(x, 14), u64.rotr(x, 18)), u64.rotr(x, 41))


def _ssig0(x):
    return u64.xor(u64.xor(u64.rotr(x, 1), u64.rotr(x, 8)), u64.shr(x, 7))


def _ssig1(x):
    return u64.xor(u64.xor(u64.rotr(x, 19), u64.rotr(x, 61)), u64.shr(x, 6))


_K_PAIRS = [(int(h), int(l)) for h, l in np.asarray(_SHA_K)]
_H0_PAIRS = [(int(h), int(l)) for h, l in np.asarray(_SHA_H0)]
_B2B_IV_PAIRS = [(int(h), int(l)) for h, l in np.asarray(_B2B_IV)]


def sha512_compress(state, block_bytes):
    """One compression. state: list of 8 (hi, lo) pairs; block [128, T]."""
    w = _words_be(block_bytes)
    a, b, c, d, e, f, g, h = state
    for t in range(80):
        if t >= 16:
            wn = u64.add_many(
                _ssig1(w[t - 2]), w[t - 7], _ssig0(w[t - 15]), w[t - 16]
            )
            w.append(wn)
        kt = (jnp.uint32(_K_PAIRS[t][0]), jnp.uint32(_K_PAIRS[t][1]))
        ch = u64.xor(u64.and_(e, f), u64.and_(u64.not_(e), g))
        maj = u64.xor(u64.xor(u64.and_(a, b), u64.and_(a, c)), u64.and_(b, c))
        t1 = u64.add_many(h, _bsig1(e), ch, kt, w[t])
        t2 = u64.add(_bsig0(a), maj)
        h, g, f, e, d, c, b, a = g, f, e, u64.add(d, t1), c, b, a, u64.add(t1, t2)
    out = []
    for s0, s1 in zip(state, (a, b, c, d, e, f, g, h)):
        out.append(u64.add(s0, s1))
    return out


def _sha512_fixed_unrolled(data, length: int | None = None):
    """SHA-512 of [n, T] byte arrays with STATIC common length -> [64, T].

    Padding is compile-time; n <= 2*BLOCK-17 supported (1 or 2 blocks),
    which covers every fixed-shape hash in the Praos path (66/130-byte
    ECVRF inputs)."""
    n = data.shape[0] if length is None else length
    t = data.shape[-1]
    nb = (n + 1 + 16 + BLOCK - 1) // BLOCK
    pad_len = nb * BLOCK - n
    tail = [0] * pad_len
    tail[0] = 0x80
    for i, byte in enumerate((8 * n).to_bytes(16, "big")):
        tail[pad_len - 16 + i] = byte
    padded = jnp.concatenate(
        [data.astype(jnp.int32), const_rows(tail, t)], axis=0
    )
    state = [
        (jnp.full((t,), p[0], jnp.uint32), jnp.full((t,), p[1], jnp.uint32))
        for p in _H0_PAIRS
    ]
    for i in range(nb):
        state = sha512_compress(state, padded[i * BLOCK : (i + 1) * BLOCK])
    return _digest_bytes_be(state)


def _sha512_var_unrolled(blocks_bytes, nblocks):
    """SHA-512 over pre-padded blocks with PER-LANE block counts.

    blocks_bytes: [NB, 128, T] int32 (host-staged standard padding);
    nblocks: [T] int32. Lanes with fewer blocks mask later updates."""
    nb = blocks_bytes.shape[0]
    t = blocks_bytes.shape[-1]
    state = [
        (jnp.full((t,), p[0], jnp.uint32), jnp.full((t,), p[1], jnp.uint32))
        for p in _H0_PAIRS
    ]
    for i in range(nb):
        nxt = sha512_compress(state, blocks_bytes[i])
        if i == 0:
            state = nxt
        else:
            active = i < nblocks
            state = [
                (jnp.where(active, nh, sh), jnp.where(active, nl, sl))
                for (nh, nl), (sh, sl) in zip(nxt, state)
            ]
    return _digest_bytes_be(state)


# ---------------------------------------------------------------------------
# Blake2b
# ---------------------------------------------------------------------------


def blake2b_compress(state, block_bytes, t_bytes, is_final):
    """state: 8 pairs; block [128, T]; t_bytes [T] int32; is_final bool[T]."""
    m = _words_le(block_bytes)
    t = block_bytes.shape[-1]
    v = list(state) + [
        (jnp.full((t,), p[0], jnp.uint32), jnp.full((t,), p[1], jnp.uint32))
        for p in _B2B_IV_PAIRS
    ]
    v[12] = (v[12][0], v[12][1] ^ t_bytes.astype(jnp.uint32))
    fmask = jnp.where(is_final, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    v[14] = (v[14][0] ^ fmask, v[14][1] ^ fmask)

    def g(a, b, c, d, x, y):
        v[a] = u64.add_many(v[a], v[b], x)
        v[d] = u64.rotr(u64.xor(v[d], v[a]), 32)
        v[c] = u64.add(v[c], v[d])
        v[b] = u64.rotr(u64.xor(v[b], v[c]), 24)
        v[a] = u64.add_many(v[a], v[b], y)
        v[d] = u64.rotr(u64.xor(v[d], v[a]), 16)
        v[c] = u64.add(v[c], v[d])
        v[b] = u64.rotr(u64.xor(v[b], v[c]), 63)

    for r in range(12):
        s = _SIGMA[r % 10]
        g(0, 4, 8, 12, m[s[0]], m[s[1]])
        g(1, 5, 9, 13, m[s[2]], m[s[3]])
        g(2, 6, 10, 14, m[s[4]], m[s[5]])
        g(3, 7, 11, 15, m[s[6]], m[s[7]])
        g(0, 5, 10, 15, m[s[8]], m[s[9]])
        g(1, 6, 11, 12, m[s[10]], m[s[11]])
        g(2, 7, 8, 13, m[s[12]], m[s[13]])
        g(3, 4, 9, 14, m[s[14]], m[s[15]])
    return [
        (sh ^ v[i][0] ^ v[i + 8][0], sl ^ v[i][1] ^ v[i + 8][1])
        for i, (sh, sl) in enumerate(state)
    ]


def _b2b_init(t: int, digest_size: int):
    state = []
    for i, p in enumerate(_B2B_IV_PAIRS):
        hi, lo = p
        if i == 0:
            lo = lo ^ (0x01010000 ^ digest_size)
        state.append((jnp.full((t,), hi, jnp.uint32), jnp.full((t,), lo, jnp.uint32)))
    return state


def _blake2b_fixed_unrolled(data, data_len: int, digest_size: int = 32):
    """Single-block Blake2b of [n, T] bytes, STATIC length <= 128."""
    assert 0 < data_len <= BLOCK
    t = data.shape[-1]
    pad = BLOCK - data.shape[0]
    if pad:
        data = jnp.concatenate(
            [data.astype(jnp.int32), jnp.zeros((pad, t), jnp.int32)], axis=0
        )
    state = _b2b_init(t, digest_size)
    tb = jnp.full((t,), data_len, jnp.int32)
    fin = jnp.full((t,), True)
    state = blake2b_compress(state, data, tb, fin)
    return _digest_bytes_le(state, digest_size)


# ---------------------------------------------------------------------------
# Public dispatchers (unrolled on TPU, rolled XLA twins elsewhere)
# ---------------------------------------------------------------------------


def sha512_fixed(data, length: int | None = None):
    """SHA-512 of [n, T] byte arrays with STATIC common length -> [64, T]."""
    if _unrolled():
        return _sha512_fixed_unrolled(data, length)
    return jnp.transpose(_xs.sha512_fixed(jnp.transpose(data)))


def sha512_var(blocks_bytes, nblocks):
    """SHA-512 over pre-padded [NB, 128, T] blocks, per-lane counts [T]."""
    if _unrolled():
        return _sha512_var_unrolled(blocks_bytes, nblocks)
    bm = jnp.moveaxis(blocks_bytes.astype(jnp.int32), -1, 0)  # [T, NB, 128]
    words = _xs.bytes_to_blocks(bm)  # [T, NB, 16, 2]
    return jnp.transpose(_xs.sha512(words, nblocks))


def blake2b_fixed(data, data_len: int, digest_size: int = 32):
    """Single-block Blake2b of [n, T] bytes, STATIC length <= 128."""
    if _unrolled():
        return _blake2b_fixed_unrolled(data, data_len, digest_size)
    return jnp.transpose(
        _xb.blake2b_fixed(jnp.transpose(data), data_len, digest_size)
    )
