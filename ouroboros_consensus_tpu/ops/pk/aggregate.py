"""Window-level random-linear-combination verification (the aggregate
fast path of the Praos hot loop).

Per lane the reference checks FOUR group equations (all over the same
base point B and the per-lane variable points):

  ed    (OCert cold-key, Praos.hs:580):  s_e·B − h_e·A_e − R_e = 0
  kes   (CompactSum leaf, Praos.hs:582): s_k·B − h_k·A_k − R_k = 0
  vrf U (batch-compat ECVRF):            s_v·B − c·Y − U = 0
  vrf V (batch-compat ECVRF):            s_v·H − c·Γ − V = 0

With batch-compatible proofs announcing U and V (ops/host/ecvrf
prove_batch_compat; Badertscher et al., ESORICS 2022 — the scheme of
cardano-base's PraosBatchCompat), the right-hand sides are all explicit
points, so a window verifies with ONE random linear combination

  Σ_i  z1·eq_ed + z2·eq_kes + z3·eq_u + z4·eq_v  =  0

checked by ONE shared-bucket signed-digit MSM (msm.msm_shared: every
width group through one bucket machine, balanced base-2^12 digits,
Abel-summation weighted sums, one shared Horner chain) plus one
fixed-base mul for the collected B coefficient — replacing every
per-lane ladder (~320 point-ops/lane/ladder) with ~one bucket add per
point per window pass. Repeated-key columns (cold keys A_e, OCert
signatures R_e, KES leaf keys, VRF keys — a Praos window re-uses its
pools' credentials across many lanes) first collapse into
fixed-capacity per-distinct-key coefficient tables (`_dedupe_column`),
so four of the nine per-lane columns cost ≤ 256 bucket entries each
instead of T. `OCT_RLC_ALL=0` (protocol/batch) swaps in
`aggregate_window_vrf`: exact per-lane Ed25519/KES ladders with only
the VRF equations aggregated on the unsigned engine — the isolation
switch for the shared-bucket machinery.

The per-lane coefficients (z1..z4) are derived by Fiat–Shamir from the
LANE's own transcript (SHA-512 over its wire bytes and challenge-hash
digests, split into four 128-bit chunks), so replay is bit-reproducible
and the coefficients are invariant under window segmentation/reordering
(tests/test_aggregate.py pins this).

Soundness shape: on a clean window the combination is EXACTLY the
identity (every honest point lies in the prime-order subgroup, so the
mod-L coefficient arithmetic is exact). Any corrupted lane makes the
aggregate nonzero except with probability ~2^-128 over the
coefficients, and a nonzero aggregate only ever causes a FALLBACK to
the unchanged per-lane stage kernels (protocol/batch), which reproduce
the exact reference error taxonomy lane by lane.

Small-order caveat (the classical cofactorless-batch residual, made
worse here by DETERMINISTIC coefficients): a signature point offset by
an 8-torsion component T contributes z·T to the aggregate. Every z is
forced ODD (coprime to the cofactor), so z·T = 0 iff T = 0 — a single
tampered lane can never cancel its own torsion, closing the cheapest
offline grind (flip R by the order-2 point and regrind until z is
even). An adversary controlling SEVERAL lanes of one window can still
solve Σ z_i·T_i = 0 across lanes, because the z_i are computable
offline — so the aggregate is byte-identical to the reference on every
honestly-signed chain (the replay/bench workload it accelerates), but
is NOT a cofactor-exact adversarial verifier; `OCT_VRF_AGG=0` selects
the exact per-lane path where that distinction matters
(COVERAGE.md records this). The odd-forcing covers ALL FOUR lanes —
z1 (ed), z2 (kes), z3/z4 (vrf) — so the single-lane guarantee holds
for every folded stage, and key dedupe does not weaken it: grouping
keys are the raw wire BYTES, so a torsion-offset encoding lands in its
own table slot with its own (odd) coefficient rather than merging with
the honest encoding. Colluding lanes that submit byte-identical
tampered columns only reach the already-documented multi-lane
Σ z_i·T_i = 0 residual.

All cheap per-lane work stays per-lane: decompressions (now including
R_e, R_k, U, V — ~4 extra Shanks chains/lane), hash-to-curve, the
challenge + beta hashes, the beta compare, Merkle root walk, leader
range extensions. Pure jnp over the limb-first layout (XLA path; the
MSM's sorts have no Mosaic lowering — see ops/pk/msm.py docstring).

Certification (octrange, analysis/absint.py): the whole window program
(`aggregate_core`) is interval-proven no-overflow at the production
8192-lane window — in particular the mod-L coefficient products
(limbs.mul_mod_l, < 2^506 before Barrett) and the cross-lane
`sum_mod_l` accumulators, whose per-term carry normalization is the
PR 3 fix octrange retroactively proves (262k-lane-term boundary shape
in analysis/shapes.json). The taint pass marks every verifier input
`wire:` (public), so the Fiat–Shamir z_i — and therefore the MSM's
argsort keys AND the dedupe tables' lexicographic key sorts /
scatter-adds — provably carry no secret marks; per-lane point-op
counts (the all-stage total at 8192, vs 1018 for the per-lane ladders)
are ratcheted in budgets.json `point_ops` (`all_stage_total`).
"""

from __future__ import annotations

from typing import NamedTuple

from jax import lax
from jax import numpy as jnp

from . import curve as pc
from . import hashes as ph
from . import limbs as fe
from . import msm
from . import verify as pv

# domain-separation prefix of the Fiat–Shamir coefficient hash
_FS_TAG = tuple(b"octRLC-1")


class AggregateVerdicts(NamedTuple):
    """Outputs of one aggregated window (limb-first device arrays)."""

    flags: jnp.ndarray  # [5, T] int32 — same rows as the finish stage,
    # with the window-wide aggregate verdict folded into the ok rows
    eta: jnp.ndarray  # [32, T]
    leader_value: jnp.ndarray  # [32, T]
    agg_ok: jnp.ndarray  # [] bool — the RLC aggregate was the identity
    pre_ok: jnp.ndarray  # [] bool — every lane passed its cheap checks


def fs_coefficients(ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
                    gamma, u, v, vrf_s, vrf_pk, alpha, beta_decl):
    """Per-lane Fiat–Shamir coefficients: SHA-512 over the lane
    transcript -> four [16, T] little-endian 128-bit chunks.

    The challenge-hash digests bind the verification keys and messages
    transitively (ed_digest = SHA-512(R‖A‖M)); everything else that
    enters an equation is bound directly. A function of the LANE only —
    window segmentation cannot change a lane's coefficients.

    Each coefficient's low bit is FORCED to 1: an odd z is coprime to
    the curve cofactor, so z·T ≠ 0 for every nonzero 8-torsion T — a
    tampered lane cannot cancel its own small-order offset no matter
    how the transcript is ground (module docstring, small-order
    caveat)."""
    t = ed_r.shape[-1]
    data = jnp.concatenate(
        [ph.const_rows(_FS_TAG, t),
         ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
         gamma, u, v, vrf_s, vrf_pk, alpha, beta_decl],
        axis=0,
    ).astype(jnp.int32)
    z = ph.sha512_fixed(data)  # [64, T]
    z = z.at[0].set(z[0] | 1).at[16].set(z[16] | 1)
    z = z.at[32].set(z[32] | 1).at[48].set(z[48] | 1)
    return z[0:16], z[16:32], z[32:48], z[48:64]


# capacity of one deduped-key coefficient table: bounds the bucket work
# the tables add to the shared MSM (4 tables x 22 windows ≈ 2.8
# lane-ops/lane at 8192). A window with more distinct keys than this in
# ANY deduped column falls back to the exact per-lane path via
# agg_ok = False — correct, just slow (COVERAGE.md records the knee).
_DEDUPE_CAP = 256


def _dedupe_column(key_bytes, coeff, p, cap: int = _DEDUPE_CAP):
    """Collapse a repeated-key MSM column into per-distinct-key
    coefficient sums: (key_bytes [32, T], coeff [20, T] mod-L limbs,
    p Point [20, T]) -> (table [20, cap] limbs < L, Point [20, cap],
    ok_cap [] bool).

    Grouping is an EXACT 32-byte lexicographic multi-key sort (never a
    hash — a grouping collision would merge two different points under
    one summed coefficient, a soundness break): adjacent-inequality
    boundaries give contiguous group ids, the per-lane coefficients
    scatter-add into the group's table slot as raw int32 limb rows
    (≤ 2^17 lanes x 13-bit rows < 2^30 — exact), one carry + Barrett
    pass restores mod-L form, and each slot takes the FIRST sorted
    lane's point as representative. Unused slots keep a valid point
    with a zero coefficient (digit 0 -> the unweighted bucket).

    The sort keys are the raw public wire bytes, so the taint
    certification marks these steering sites `wire:` like the MSM's
    argsort — and byte-exact grouping means a torsion-offset encoding
    NEVER shares a slot with the honest encoding of the same point
    (the single-lane odd-coefficient guarantee survives dedupe; see
    the module small-order caveat for the multi-lane residual)."""
    t = key_bytes.shape[-1]
    iota = jnp.arange(t, dtype=jnp.int32)
    rows = [key_bytes[i].astype(jnp.int32)
            for i in range(key_bytes.shape[0])]
    sorted_ops = lax.sort(rows + [iota], num_keys=len(rows))
    sk = jnp.stack(sorted_ops[:-1])
    perm = sorted_ops[-1]
    newgrp = jnp.concatenate([
        jnp.ones((1,), bool),
        jnp.any(sk[:, 1:] != sk[:, :-1], axis=0),
    ])
    gid = jnp.cumsum(newgrp.astype(jnp.int32)) - 1  # [T] nondecreasing
    ok_cap = gid[-1] < cap
    gid_c = jnp.minimum(gid, cap - 1)
    table = fe.reduce_raw_sums(
        jnp.zeros((fe.NLIMBS, cap), jnp.int32)
        .at[:, gid_c].add(coeff[:, perm])
    )
    # group start positions via scatter-ADD: exactly one newgrp lane
    # per group, so the add IS the start index (clamped: an
    # over-capacity slot may accumulate garbage, but ok_cap already
    # voids the window)
    starts = jnp.minimum(
        jnp.zeros((cap,), jnp.int32)
        .at[gid_c].add(jnp.where(newgrp, iota, 0)),
        t - 1,
    )
    rep = jnp.take(perm, starts)
    tbl_pt = pc.Point(*(
        jnp.take(c, rep, axis=-1) for c in (p.x, p.y, p.z, p.t)
    ))
    return table, tbl_pt, ok_cap


def _cat_points(points):
    return pc.Point(*(
        jnp.concatenate([getattr(p, f) for p in points], axis=-1)
        for f in ("x", "y", "z", "t")
    ))


def _cat(arrs):
    return jnp.concatenate(list(arrs), axis=-1)


def aggregate_window(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
    *, kes_depth: int,
) -> AggregateVerdicts:
    """Aggregated verification of one window (argument order mirrors
    ops/pk/kernels.staged_to_limb_first_bc's outputs)."""
    t = ed_pk.shape[-1]

    # --- per-lane cheap work (decompressions, hashes, Merkle) ----------
    ok_a, a_pt = pc.decompress(ed_pk)
    ok_re, re_pt = pc.decompress(ed_r)
    ed_digest = ph.sha512_var(ed_hblocks, ed_hnblocks[0])
    h_ed = fe.reduce512(ed_digest)
    pre_ed = ok_a & ok_re & fe.is_canonical_scalar(ed_s)

    ok_al, al_pt = pc.decompress(kes_vk_leaf)
    ok_rk, rk_pt = pc.decompress(kes_r)
    kes_digest = ph.sha512_var(kes_hblocks, kes_hnblocks[0])
    h_kes = fe.reduce512(kes_digest)
    period = kes_period[0]
    root_ok = pv.kes_merkle_ok(kes_vk, period, kes_vk_leaf, kes_siblings,
                               kes_depth)
    period_ok = (period >= 0) & (period < (1 << kes_depth))
    pre_kes = (ok_al & ok_rk & fe.is_canonical_scalar(kes_s)
               & root_ok & period_ok)

    ok_y, y_pt = pc.decompress(vrf_pk)
    ok_g, g_pt = pc.decompress(vrf_gamma)
    ok_u, u_pt = pc.decompress(vrf_u)
    ok_v, v_pt = pc.decompress(vrf_v)
    h_pt = pv.hash_to_curve(vrf_pk, vrf_alpha)
    g8 = pc.mul_cofactor(g_pt)
    h_enc, g8_enc = pc.compress_many([h_pt, g8])
    p2 = ph.const_rows([pv.SUITE, 0x02], t)
    c16 = ph.sha512_fixed(jnp.concatenate(
        [p2, h_enc, vrf_gamma.astype(jnp.int32), vrf_u.astype(jnp.int32),
         vrf_v.astype(jnp.int32)], axis=0,
    ))[:16]
    p3 = ph.const_rows([pv.SUITE, 0x03], t)
    beta = ph.sha512_fixed(jnp.concatenate([p3, g8_enc], axis=0))
    beta_ok = jnp.all(beta == beta_decl.astype(jnp.int32), axis=0)
    pre_vrf = (ok_y & ok_g & ok_u & ok_v
               & fe.is_canonical_scalar(vrf_s) & beta_ok)

    # --- leader / nonce range extensions (identical to finish_core) ---
    beta_i = beta_decl.astype(jnp.int32)
    tag_l = ph.const_rows([ord("L")], t)
    lv = ph.blake2b_fixed(jnp.concatenate([tag_l, beta_i], axis=0), 65, 32)
    tag_n = ph.const_rows([ord("N")], t)
    eta1 = ph.blake2b_fixed(jnp.concatenate([tag_n, beta_i], axis=0), 65, 32)
    eta = ph.blake2b_fixed(eta1, 32, 32)
    certain_win = pv._lt_be(lv, thr_lo.astype(jnp.int32))
    certain_loss = ~pv._lt_be(lv, thr_hi.astype(jnp.int32))
    ambiguous = ~certain_win & ~certain_loss

    # --- Fiat–Shamir coefficients and mod-L scalar products ------------
    z1b, z2b, z3b, z4b = fs_coefficients(
        ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
        vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_pk, vrf_alpha, beta_decl,
    )
    z1 = fe.bytes_to_limbs(z1b, fe.NLIMBS)
    z2 = fe.bytes_to_limbs(z2b, fe.NLIMBS)
    z3 = fe.bytes_to_limbs(z3b, fe.NLIMBS)
    z4 = fe.bytes_to_limbs(z4b, fe.NLIMBS)
    c_l = fe.bytes_to_limbs(c16, fe.NLIMBS)
    s_e = fe.bytes_to_limbs(ed_s.astype(jnp.int32), fe.NLIMBS)
    s_k = fe.bytes_to_limbs(kes_s.astype(jnp.int32), fe.NLIMBS)
    s_v = fe.bytes_to_limbs(vrf_s.astype(jnp.int32), fe.NLIMBS)

    # collected B coefficient: z1·s_e + z2·s_k + z3·s_v (mod L), summed
    # over the whole window
    sb_scalar = fe.sum_mod_l([
        fe.mul_mod_l(z1, s_e), fe.mul_mod_l(z2, s_k), fe.mul_mod_l(z3, s_v),
    ])
    sb_pt = pc.base_mul_w8(fe.windows8_from_limbs(sb_scalar, 256))

    # repeated-key columns collapse into fixed-capacity tables before
    # the MSM: a Praos window re-uses its pools' cold keys (A_e), OCert
    # signatures (R_e), KES leaf keys (A_l) and VRF keys (Y) across many
    # lanes, so the per-distinct-key coefficient SUMS replace T bucket
    # entries with ≤ _DEDUPE_CAP (soundness guard: a window with more
    # distinct keys than capacity forces agg_ok = False -> clean
    # per-lane fallback, never a wrong verdict)
    t_re, p_re, cap1 = _dedupe_column(ed_r, z1, pc.neg(re_pt))
    t_a, p_a, cap2 = _dedupe_column(ed_pk, fe.mul_mod_l(z1, h_ed),
                                    pc.neg(a_pt))
    t_al, p_al, cap3 = _dedupe_column(kes_vk_leaf,
                                      fe.mul_mod_l(z2, h_kes),
                                      pc.neg(al_pt))
    t_y, p_y, cap4 = _dedupe_column(vrf_pk, fe.mul_mod_l(z3, c_l),
                                    pc.neg(y_pt))

    # ONE shared-bucket signed-digit MSM over every remaining column:
    # raw 128-bit coefficients on the per-lane announced points, full
    # mod-L widths on the per-lane VRF commitments and the deduped
    # tables (table sums are mod-L-wide regardless of the source width)
    group_small = (
        _cat([z2, z3, z4]),
        _cat_points([pc.neg(rk_pt), pc.neg(u_pt), pc.neg(v_pt)]),
        128,
    )
    group_wide = (
        _cat([fe.mul_mod_l(z4, c_l), fe.mul_mod_l(z4, s_v),
              t_re, t_a, t_al, t_y]),
        _cat_points([pc.neg(g_pt), h_pt, p_re, p_a, p_al, p_y]),
        253,
    )
    total = pc.add(msm.msm_shared([group_small, group_wide]), sb_pt)
    agg_ok = msm.is_identity(total)[0] & cap1 & cap2 & cap3 & cap4

    pre_ok = jnp.all(pre_ed) & jnp.all(pre_kes) & jnp.all(pre_vrf)
    okb = agg_ok[None]
    flags = jnp.stack([
        (pre_ed & okb).astype(jnp.int32),
        (pre_kes & okb).astype(jnp.int32),
        (pre_vrf & okb).astype(jnp.int32),
        certain_win.astype(jnp.int32),
        ambiguous.astype(jnp.int32),
    ], axis=0)
    return AggregateVerdicts(flags, eta, lv, agg_ok, pre_ok)


def aggregate_window_vrf(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
    *, kes_depth: int,
) -> AggregateVerdicts:
    """The `OCT_RLC_ALL=0` kill-switch window: EXACT per-lane Ed25519
    and KES ladders (ops/pk/verify.py cores, compress-and-compare — the
    pre-fold PR 3 shape) with only the two VRF equations aggregated,
    and the aggregation running on the UNSIGNED `msm.msm_groups` engine
    so the switch also isolates the shared-bucket machinery itself.
    Same signature/verdict contract as `aggregate_window`."""
    t = ed_pk.shape[-1]

    # --- exact per-lane Ed25519 + KES (reference ladders) --------------
    ok_e, ed_pt = pv.ed_core(ed_pk, ed_s, ed_hblocks, ed_hnblocks[0])
    ok_k, kes_pt = pv.kes_core(
        kes_vk, kes_period[0], kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks[0], kes_depth,
    )
    ed_enc, kes_enc = pc.compress_many([ed_pt, kes_pt])
    ed_ok = ok_e & jnp.all(ed_enc == ed_r.astype(jnp.int32), axis=0)
    kes_ok = ok_k & jnp.all(kes_enc == kes_r.astype(jnp.int32), axis=0)

    # --- per-lane VRF cheap work (as the unified path) -----------------
    ok_y, y_pt = pc.decompress(vrf_pk)
    ok_g, g_pt = pc.decompress(vrf_gamma)
    ok_u, u_pt = pc.decompress(vrf_u)
    ok_v, v_pt = pc.decompress(vrf_v)
    h_pt = pv.hash_to_curve(vrf_pk, vrf_alpha)
    g8 = pc.mul_cofactor(g_pt)
    h_enc, g8_enc = pc.compress_many([h_pt, g8])
    p2 = ph.const_rows([pv.SUITE, 0x02], t)
    c16 = ph.sha512_fixed(jnp.concatenate(
        [p2, h_enc, vrf_gamma.astype(jnp.int32), vrf_u.astype(jnp.int32),
         vrf_v.astype(jnp.int32)], axis=0,
    ))[:16]
    p3 = ph.const_rows([pv.SUITE, 0x03], t)
    beta = ph.sha512_fixed(jnp.concatenate([p3, g8_enc], axis=0))
    beta_ok = jnp.all(beta == beta_decl.astype(jnp.int32), axis=0)
    pre_vrf = (ok_y & ok_g & ok_u & ok_v
               & fe.is_canonical_scalar(vrf_s) & beta_ok)

    # --- leader / nonce range extensions -------------------------------
    beta_i = beta_decl.astype(jnp.int32)
    tag_l = ph.const_rows([ord("L")], t)
    lv = ph.blake2b_fixed(jnp.concatenate([tag_l, beta_i], axis=0), 65, 32)
    tag_n = ph.const_rows([ord("N")], t)
    eta1 = ph.blake2b_fixed(jnp.concatenate([tag_n, beta_i], axis=0), 65, 32)
    eta = ph.blake2b_fixed(eta1, 32, 32)
    certain_win = pv._lt_be(lv, thr_lo.astype(jnp.int32))
    certain_loss = ~pv._lt_be(lv, thr_hi.astype(jnp.int32))
    ambiguous = ~certain_win & ~certain_loss

    # --- vrf-only RLC (z3/z4 equations; z1/z2 unused here) -------------
    ed_digest = ph.sha512_var(ed_hblocks, ed_hnblocks[0])
    kes_digest = ph.sha512_var(kes_hblocks, kes_hnblocks[0])
    _, _, z3b, z4b = fs_coefficients(
        ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
        vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_pk, vrf_alpha, beta_decl,
    )
    z3 = fe.bytes_to_limbs(z3b, fe.NLIMBS)
    z4 = fe.bytes_to_limbs(z4b, fe.NLIMBS)
    c_l = fe.bytes_to_limbs(c16, fe.NLIMBS)
    s_v = fe.bytes_to_limbs(vrf_s.astype(jnp.int32), fe.NLIMBS)

    sb_scalar = fe.sum_mod_l([fe.mul_mod_l(z3, s_v)])
    sb_pt = pc.base_mul_w8(fe.windows8_from_limbs(sb_scalar, 256))
    group_small = (
        _cat([z3, z4]),
        _cat_points([pc.neg(u_pt), pc.neg(v_pt)]),
        128,
    )
    group_wide = (
        _cat([fe.mul_mod_l(z3, c_l), fe.mul_mod_l(z4, c_l),
              fe.mul_mod_l(z4, s_v)]),
        _cat_points([pc.neg(y_pt), pc.neg(g_pt), h_pt]),
        256,
    )
    total = pc.add(msm.msm_groups([group_small, group_wide]), sb_pt)
    agg_ok = msm.is_identity(total)[0]

    pre_ok = jnp.all(ed_ok) & jnp.all(kes_ok) & jnp.all(pre_vrf)
    okb = agg_ok[None]
    flags = jnp.stack([
        (ed_ok & okb).astype(jnp.int32),
        (kes_ok & okb).astype(jnp.int32),
        (pre_vrf & okb).astype(jnp.int32),
        certain_win.astype(jnp.int32),
        ambiguous.astype(jnp.int32),
    ], axis=0)
    return AggregateVerdicts(flags, eta, lv, agg_ok, pre_ok)
