"""Window-level random-linear-combination verification (the aggregate
fast path of the Praos hot loop).

Per lane the reference checks FOUR group equations (all over the same
base point B and the per-lane variable points):

  ed    (OCert cold-key, Praos.hs:580):  s_e·B − h_e·A_e − R_e = 0
  kes   (CompactSum leaf, Praos.hs:582): s_k·B − h_k·A_k − R_k = 0
  vrf U (batch-compat ECVRF):            s_v·B − c·Y − U = 0
  vrf V (batch-compat ECVRF):            s_v·H − c·Γ − V = 0

With batch-compatible proofs announcing U and V (ops/host/ecvrf
prove_batch_compat; Badertscher et al., ESORICS 2022 — the scheme of
cardano-base's PraosBatchCompat), the right-hand sides are all explicit
points, so a window verifies with ONE random linear combination

  Σ_i  z1·eq_ed + z2·eq_kes + z3·eq_u + z4·eq_v  =  0

checked by a single Pippenger MSM (ops/pk/msm.py) plus one fixed-base
mul for the collected B coefficient — replacing every per-lane ladder
(~320 point-ops/lane/ladder) with ~one bucket add per point per window.

The per-lane coefficients (z1..z4) are derived by Fiat–Shamir from the
LANE's own transcript (SHA-512 over its wire bytes and challenge-hash
digests, split into four 128-bit chunks), so replay is bit-reproducible
and the coefficients are invariant under window segmentation/reordering
(tests/test_aggregate.py pins this).

Soundness shape: on a clean window the combination is EXACTLY the
identity (every honest point lies in the prime-order subgroup, so the
mod-L coefficient arithmetic is exact). Any corrupted lane makes the
aggregate nonzero except with probability ~2^-128 over the
coefficients, and a nonzero aggregate only ever causes a FALLBACK to
the unchanged per-lane stage kernels (protocol/batch), which reproduce
the exact reference error taxonomy lane by lane.

Small-order caveat (the classical cofactorless-batch residual, made
worse here by DETERMINISTIC coefficients): a signature point offset by
an 8-torsion component T contributes z·T to the aggregate. Every z is
forced ODD (coprime to the cofactor), so z·T = 0 iff T = 0 — a single
tampered lane can never cancel its own torsion, closing the cheapest
offline grind (flip R by the order-2 point and regrind until z is
even). An adversary controlling SEVERAL lanes of one window can still
solve Σ z_i·T_i = 0 across lanes, because the z_i are computable
offline — so the aggregate is byte-identical to the reference on every
honestly-signed chain (the replay/bench workload it accelerates), but
is NOT a cofactor-exact adversarial verifier; `OCT_VRF_AGG=0` selects
the exact per-lane path where that distinction matters
(COVERAGE.md records this).

All cheap per-lane work stays per-lane: decompressions (now including
R_e, R_k, U, V — ~4 extra Shanks chains/lane), hash-to-curve, the
challenge + beta hashes, the beta compare, Merkle root walk, leader
range extensions. Pure jnp over the limb-first layout (XLA path; the
MSM's sorts have no Mosaic lowering — see ops/pk/msm.py docstring).

Certification (octrange, analysis/absint.py): the whole window program
(`aggregate_core`) is interval-proven no-overflow at the production
8192-lane window — in particular the mod-L coefficient products
(limbs.mul_mod_l, < 2^506 before Barrett) and the cross-lane
`sum_mod_l` accumulators, whose per-term carry normalization is the
PR 3 fix octrange retroactively proves (262k-lane-term boundary shape
in analysis/shapes.json). The taint pass marks every verifier input
`wire:` (public), so the Fiat–Shamir z_i — and therefore the MSM's
argsort keys — provably carry no secret marks; per-lane point-op
counts (260/lane at 8192, the 5.35× PR 3 win) are ratcheted in
budgets.json `point_ops`.
"""

from __future__ import annotations

from typing import NamedTuple

from jax import numpy as jnp

from . import curve as pc
from . import hashes as ph
from . import limbs as fe
from . import msm
from . import verify as pv

# domain-separation prefix of the Fiat–Shamir coefficient hash
_FS_TAG = tuple(b"octRLC-1")


class AggregateVerdicts(NamedTuple):
    """Outputs of one aggregated window (limb-first device arrays)."""

    flags: jnp.ndarray  # [5, T] int32 — same rows as the finish stage,
    # with the window-wide aggregate verdict folded into the ok rows
    eta: jnp.ndarray  # [32, T]
    leader_value: jnp.ndarray  # [32, T]
    agg_ok: jnp.ndarray  # [] bool — the RLC aggregate was the identity
    pre_ok: jnp.ndarray  # [] bool — every lane passed its cheap checks


def fs_coefficients(ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
                    gamma, u, v, vrf_s, vrf_pk, alpha, beta_decl):
    """Per-lane Fiat–Shamir coefficients: SHA-512 over the lane
    transcript -> four [16, T] little-endian 128-bit chunks.

    The challenge-hash digests bind the verification keys and messages
    transitively (ed_digest = SHA-512(R‖A‖M)); everything else that
    enters an equation is bound directly. A function of the LANE only —
    window segmentation cannot change a lane's coefficients.

    Each coefficient's low bit is FORCED to 1: an odd z is coprime to
    the curve cofactor, so z·T ≠ 0 for every nonzero 8-torsion T — a
    tampered lane cannot cancel its own small-order offset no matter
    how the transcript is ground (module docstring, small-order
    caveat)."""
    t = ed_r.shape[-1]
    data = jnp.concatenate(
        [ph.const_rows(_FS_TAG, t),
         ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
         gamma, u, v, vrf_s, vrf_pk, alpha, beta_decl],
        axis=0,
    ).astype(jnp.int32)
    z = ph.sha512_fixed(data)  # [64, T]
    z = z.at[0].set(z[0] | 1).at[16].set(z[16] | 1)
    z = z.at[32].set(z[32] | 1).at[48].set(z[48] | 1)
    return z[0:16], z[16:32], z[32:48], z[48:64]


def _cat_points(points):
    return pc.Point(*(
        jnp.concatenate([getattr(p, f) for p in points], axis=-1)
        for f in ("x", "y", "z", "t")
    ))


def _cat(arrs):
    return jnp.concatenate(list(arrs), axis=-1)


def aggregate_window(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
    *, kes_depth: int,
) -> AggregateVerdicts:
    """Aggregated verification of one window (argument order mirrors
    ops/pk/kernels.staged_to_limb_first_bc's outputs)."""
    t = ed_pk.shape[-1]

    # --- per-lane cheap work (decompressions, hashes, Merkle) ----------
    ok_a, a_pt = pc.decompress(ed_pk)
    ok_re, re_pt = pc.decompress(ed_r)
    ed_digest = ph.sha512_var(ed_hblocks, ed_hnblocks[0])
    h_ed = fe.reduce512(ed_digest)
    pre_ed = ok_a & ok_re & fe.is_canonical_scalar(ed_s)

    ok_al, al_pt = pc.decompress(kes_vk_leaf)
    ok_rk, rk_pt = pc.decompress(kes_r)
    kes_digest = ph.sha512_var(kes_hblocks, kes_hnblocks[0])
    h_kes = fe.reduce512(kes_digest)
    period = kes_period[0]
    root_ok = pv.kes_merkle_ok(kes_vk, period, kes_vk_leaf, kes_siblings,
                               kes_depth)
    period_ok = (period >= 0) & (period < (1 << kes_depth))
    pre_kes = (ok_al & ok_rk & fe.is_canonical_scalar(kes_s)
               & root_ok & period_ok)

    ok_y, y_pt = pc.decompress(vrf_pk)
    ok_g, g_pt = pc.decompress(vrf_gamma)
    ok_u, u_pt = pc.decompress(vrf_u)
    ok_v, v_pt = pc.decompress(vrf_v)
    h_pt = pv.hash_to_curve(vrf_pk, vrf_alpha)
    g8 = pc.mul_cofactor(g_pt)
    h_enc, g8_enc = pc.compress_many([h_pt, g8])
    p2 = ph.const_rows([pv.SUITE, 0x02], t)
    c16 = ph.sha512_fixed(jnp.concatenate(
        [p2, h_enc, vrf_gamma.astype(jnp.int32), vrf_u.astype(jnp.int32),
         vrf_v.astype(jnp.int32)], axis=0,
    ))[:16]
    p3 = ph.const_rows([pv.SUITE, 0x03], t)
    beta = ph.sha512_fixed(jnp.concatenate([p3, g8_enc], axis=0))
    beta_ok = jnp.all(beta == beta_decl.astype(jnp.int32), axis=0)
    pre_vrf = (ok_y & ok_g & ok_u & ok_v
               & fe.is_canonical_scalar(vrf_s) & beta_ok)

    # --- leader / nonce range extensions (identical to finish_core) ---
    beta_i = beta_decl.astype(jnp.int32)
    tag_l = ph.const_rows([ord("L")], t)
    lv = ph.blake2b_fixed(jnp.concatenate([tag_l, beta_i], axis=0), 65, 32)
    tag_n = ph.const_rows([ord("N")], t)
    eta1 = ph.blake2b_fixed(jnp.concatenate([tag_n, beta_i], axis=0), 65, 32)
    eta = ph.blake2b_fixed(eta1, 32, 32)
    certain_win = pv._lt_be(lv, thr_lo.astype(jnp.int32))
    certain_loss = ~pv._lt_be(lv, thr_hi.astype(jnp.int32))
    ambiguous = ~certain_win & ~certain_loss

    # --- Fiat–Shamir coefficients and mod-L scalar products ------------
    z1b, z2b, z3b, z4b = fs_coefficients(
        ed_r, ed_s, ed_digest, kes_r, kes_s, kes_digest,
        vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_pk, vrf_alpha, beta_decl,
    )
    z1 = fe.bytes_to_limbs(z1b, fe.NLIMBS)
    z2 = fe.bytes_to_limbs(z2b, fe.NLIMBS)
    z3 = fe.bytes_to_limbs(z3b, fe.NLIMBS)
    z4 = fe.bytes_to_limbs(z4b, fe.NLIMBS)
    c_l = fe.bytes_to_limbs(c16, fe.NLIMBS)
    s_e = fe.bytes_to_limbs(ed_s.astype(jnp.int32), fe.NLIMBS)
    s_k = fe.bytes_to_limbs(kes_s.astype(jnp.int32), fe.NLIMBS)
    s_v = fe.bytes_to_limbs(vrf_s.astype(jnp.int32), fe.NLIMBS)

    # collected B coefficient: z1·s_e + z2·s_k + z3·s_v (mod L), summed
    # over the whole window
    sb_scalar = fe.sum_mod_l([
        fe.mul_mod_l(z1, s_e), fe.mul_mod_l(z2, s_k), fe.mul_mod_l(z3, s_v),
    ])
    sb_pt = pc.base_mul_w8(fe.windows8_from_limbs(sb_scalar, 256))

    # MSM groups: raw 128-bit coefficients on the announced points,
    # full-width mod-L products on the key/commitment points
    group_small = (
        _cat([z1, z2, z3, z4]),
        _cat_points([pc.neg(re_pt), pc.neg(rk_pt), pc.neg(u_pt),
                     pc.neg(v_pt)]),
        128,
    )
    group_wide = (
        _cat([
            fe.mul_mod_l(z1, h_ed), fe.mul_mod_l(z2, h_kes),
            fe.mul_mod_l(z3, c_l), fe.mul_mod_l(z4, c_l),
            fe.mul_mod_l(z4, s_v),
        ]),
        _cat_points([pc.neg(a_pt), pc.neg(al_pt), pc.neg(y_pt),
                     pc.neg(g_pt), h_pt]),
        256,
    )
    total = pc.add(msm.msm_groups([group_small, group_wide]), sb_pt)
    agg_ok = msm.is_identity(total)[0]

    pre_ok = jnp.all(pre_ed) & jnp.all(pre_kes) & jnp.all(pre_vrf)
    okb = agg_ok[None]
    flags = jnp.stack([
        (pre_ed & okb).astype(jnp.int32),
        (pre_kes & okb).astype(jnp.int32),
        (pre_vrf & okb).astype(jnp.int32),
        certain_win.astype(jnp.int32),
        ambiguous.astype(jnp.int32),
    ], axis=0)
    return AggregateVerdicts(flags, eta, lv, agg_ok, pre_ok)
