"""Limb-first edwards25519 point ops + scalar ladders for Pallas kernels.

Points are extended homogeneous (X, Y, Z, T) coordinates, each [20, T]
(ops/pk/limbs.py layout). Same unified addition law and mask-lane
discipline as ops/curve.py; the differences are all mechanical
consequences of the kernel setting:

  * ladders run `lax.fori_loop`s whose carried point lives in
    VMEM/registers for the whole walk (inside a Pallas kernel there is
    no per-iteration HBM round-trip, which is what made the XLA twin
    ~10x slower than its component muls — scripts/exp_layout3.py);
  * per-lane window tables are Python lists of 16 points selected by a
    4-level binary select tree (no gather — Mosaic has no per-lane
    gather on values);
  * the SHARED fixed-base tables (s*B) are looked up by one-hot fp32
    matmuls that Mosaic places on the MXU: entries are 13-bit limbs, so
    a [2^w, 80] f32 table row contracted with a {0,1} one-hot matrix is
    exact in f32 (single nonzero term per output).

Reference equivalent: libsodium ge25519 double-scalarmult/scalarmult as
used by crypto_sign_verify_detached and the vendored ECVRF
(Protocol/Praos.hs:543,580,582).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
from jax import lax
from jax import numpy as jnp

from .. import curve as _xc
from . import limbs as fe


class Point(NamedTuple):
    x: jnp.ndarray  # [20, T]
    y: jnp.ndarray
    z: jnp.ndarray
    t: jnp.ndarray


def identity(t: int) -> Point:
    return Point(fe.zeros(t), fe.ones(t), fe.ones(t), fe.zeros(t))


# ---------------------------------------------------------------------------
# Point-op accounting (scripts/count_point_ops.py): when enabled, every
# add/double records (invocations, lane-width product) at TRACE time.
# Loop-fenced ops (lax.fori_loop bodies) trace once, so counts are exact
# only for fully unrolled programs — the MSM/aggregate path qualifies
# (python loops + associative structure); the per-lane ladders do not
# (fori walks) and are counted analytically by the script instead.
# Trace-time-only accounting, reset per run by op_counter().
_OPSTATS: dict = {"on": False, "ops": 0, "lane_ops": 0}


def op_counter():
    """Context manager: zero + enable the trace-time point-op counter."""

    class _Ctx:
        def __enter__(self):
            _OPSTATS.update(on=True, ops=0, lane_ops=0)
            return _OPSTATS

        def __exit__(self, *exc):
            _OPSTATS["on"] = False

    return _Ctx()


def _count(width: int, n: int = 1) -> None:
    if _OPSTATS["on"]:
        _OPSTATS["ops"] += n
        _OPSTATS["lane_ops"] += n * int(width)


def add(p: Point, q: Point) -> Point:
    _count(max(p.x.shape[-1], q.x.shape[-1]))
    a = fe.mul(fe.sub(p.y, p.x), fe.sub(q.y, q.x))
    b = fe.mul(fe.add(p.y, p.x), fe.add(q.y, q.x))
    c = fe.mul(fe.mul_small(fe.mul(p.t, q.t), 2), fe.constant(fe.D_INT))
    d = fe.mul_small(fe.mul(p.z, q.z), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def double(p: Point) -> Point:
    _count(p.x.shape[-1])
    a = fe.sqr(p.x)
    b = fe.sqr(p.y)
    c = fe.mul_small(fe.sqr(p.z), 2)
    h = fe.add(a, b)
    e = fe.sub(h, fe.sqr(fe.add(p.x, p.y)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _double_partial(x, y, z):
    _count(x.shape[-1])
    a = fe.sqr(x)
    b = fe.sqr(y)
    c = fe.mul_small(fe.sqr(z), 2)
    h = fe.add(a, b)
    e = fe.sub(h, fe.sqr(fe.add(x, y)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return fe.mul(e, f), fe.mul(g, h), fe.mul(f, g)


def doubles(p: Point, k: int) -> Point:
    """k successive doublings; T materialized only by the last."""
    x, y, z = p.x, p.y, p.z
    for _ in range(k - 1):
        x, y, z = _double_partial(x, y, z)
    return double(Point(x, y, z, x))  # .t unused by double()


def neg(p: Point) -> Point:
    return Point(fe.neg(p.x), p.y, p.z, fe.neg(p.t))


def select(cond, p: Point, q: Point) -> Point:
    return Point(*(fe.select(cond, a, b) for a, b in zip(p, q)))


def mul_cofactor(p: Point) -> Point:
    return double(double(double(p)))


# ---------------------------------------------------------------------------
# Per-lane window tables (variable base)
# ---------------------------------------------------------------------------


def _build_table16(p: Point) -> list[Point]:
    """[identity, P, 2P, ..., 15P].

    Inside a Pallas kernel: 14 unrolled adds at trace time (compute,
    not graph bloat: Mosaic compiles the loop body once per textual op,
    and the adds all reuse the same code).

    On the XLA path the same 14 sequential adds are FENCED into one
    `lax.scan`: unrolled they contribute a ~60-deep multiply chain to
    the enclosing computation, and long unrolled multiply chains are
    the family that sends XLA's algebraic simplifier into its circular
    rewrite loop on the composed graph (>30-min compiles, VERDICT r5
    weak #3/#4; budgeted by analysis/graphs.py). A scan body is a
    separate XLA computation, so the chain ends at the loop boundary.
    """
    t = p.x.shape[-1]
    if fe._KCTX["t"] is not None:
        tbl = [identity(t), p]
        for _ in range(14):
            tbl.append(add(tbl[-1], p))
        return tbl

    def step(carry, _):
        nxt = add(carry, p)
        return nxt, nxt

    _, stacked = lax.scan(step, p, None, length=14)  # entries 2P..15P
    _count(t, 13)  # scan body traced once; 14 adds happen
    return [identity(t), p] + [
        Point(stacked.x[i], stacked.y[i], stacked.z[i], stacked.t[i])
        for i in range(14)
    ]


def _select16(tbl: list[Point], dw) -> Point:
    """Binary select tree over 16 table entries by digit dw[T]."""
    level = tbl
    for bit in range(4):
        b = (dw >> bit) & 1
        level = [
            select(b == 1, level[2 * i + 1], level[2 * i])
            for i in range(len(level) // 2)
        ]
    return level[0]


def _rotate_up(d):
    """Rotate rows up by one (row 0 to the back) — Mosaic has no
    dynamic_slice on values, so ladders read row 0 (static) and rotate."""
    return jnp.concatenate([d[1:], d[:1]], axis=0)


def scalar_mul_w4(digits_msb, p: Point) -> Point:
    """Windowed variable-base mul. digits_msb: [k, T] base-16 digits,
    MSB-window-first (produced that way at staging — no device-side
    reverse). The fori carries the digit array and rotates it so each
    iteration's window is the STATIC row 0."""
    k = digits_msb.shape[0]
    t = p.x.shape[-1]
    tbl = _build_table16(p)

    def body(_, carry):
        q, d = carry
        q = doubles(q, 4)
        q = add(q, _select16(tbl, d[0]))
        return q, _rotate_up(d)

    q, _ = lax.fori_loop(0, k, body, (identity(t), digits_msb))
    _count(t, (k - 1) * 5)  # 4 doubles + 1 add per window
    return q


def double_scalar_mul_w4(da_msb, pa: Point, db_msb, pb: Point) -> Point:
    """a*PA + b*PB, shared doubling chain; len(da) >= len(db) required
    (the Praos shapes: 64-window s against 32-window c)."""
    ka, kb = da_msb.shape[0], db_msb.shape[0]
    assert ka >= kb
    t = pa.x.shape[-1]
    ta = _build_table16(pa)
    tb = _build_table16(pb)

    def body_a(_, carry):
        q, d = carry
        q = doubles(q, 4)
        q = add(q, _select16(ta, d[0]))
        return q, _rotate_up(d)

    def body_ab(_, carry):
        q, d1, d2 = carry
        q = doubles(q, 4)
        q = add(q, _select16(ta, d1[0]))
        q = add(q, _select16(tb, d2[0]))
        return q, _rotate_up(d1), _rotate_up(d2)

    q, da_rot = lax.fori_loop(0, ka - kb, body_a, (identity(t), da_msb))
    q, _, _ = lax.fori_loop(0, kb, body_ab, (q, da_rot, db_msb))
    _count(t, (ka - kb - 1) * 5 + (kb - 1) * 6)  # bodies traced once
    return q


# ---------------------------------------------------------------------------
# Shared fixed-base tables (s*B) via one-hot MXU matmuls
# ---------------------------------------------------------------------------


import jax  # noqa: E402


def _build_base8_np() -> np.ndarray:
    """[32, 160, 256] float32 — transposed flattened (x, y, z, t) limb
    rows of d * 2^(8w) * B, each 13-bit limb SPLIT into (hi, lo) halves
    with hi = limb >> 6 (< 128) and lo = limb & 63: the TPU MXU runs f32
    matmuls through bf16 passes whose 8-bit mantissa cannot represent a
    13-bit integer, but both halves (and the {0,1} one-hot operand) are
    exact in bf16, so the split lookup is bit-exact. Rows 0..79 are hi,
    80..159 lo. Reuses ops/curve's cached host table build."""
    tbl = _xc._base_table(8)  # [32, 256, 4, 20] int32
    w, n, _, _ = tbl.shape
    flat = tbl.reshape(w, n, 80).transpose(0, 2, 1)  # [32, 80, 256]
    hi = flat >> 6
    lo = flat & 63
    return np.ascontiguousarray(
        np.concatenate([hi, lo], axis=1)
    ).astype(np.float32)


BASE8_NP = _build_base8_np()

# kernel context for the shared table (see limbs.kernel_consts rationale;
# trace-time-only reads, rebuilt per trace — the reviewed exception)
# octlint: disable-file=OCT103
_KCTX: dict = {"base8": None}


def kernel_base8(value):
    class _Ctx:
        def __enter__(self):
            _KCTX["base8"] = value

        def __exit__(self, *exc):
            _KCTX["base8"] = None

    return _Ctx()


def _base8():
    v = _KCTX["base8"]
    return jnp.asarray(BASE8_NP) if v is None else v


def _onehot_lookup(table_w, dw) -> Point:
    """table_w [160, n] f32 (hi/lo split rows); dw [T] int32 -> Point.

    onehot[n, T] = (iota == dw); hi/lo = table_w @ onehot — one MXU
    matmul, exact even through bf16 passes (all values < 2^7, one
    nonzero per output). Recombined as hi*64 + lo in int32.
    """
    n = table_w.shape[-1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (n, dw.shape[-1]), 0)
    onehot = (iota == dw[None, :]).astype(jnp.float32)
    both = jax.lax.dot_general(
        table_w, onehot,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)  # [160, T]
    entry = both[:80] * 64 + both[80:]
    return Point(entry[0:20], entry[20:40], entry[40:60], entry[60:80])


def base_mul_w8(digits_lsb) -> Point:
    """s*B from base-256 digits [32, T] (LSB-window-first, matching the
    table's window order).

    Inside a Pallas kernel the 32 windows unroll (Mosaic has no
    dynamic_slice on values, so the table row must be a static index).
    On the XLA path the windows run under a `lax.fori_loop` with
    dynamic window indexing: unrolled they were the single longest
    multiply chain of the composed `verify_praos_core` graph (~32
    point-adds back to back), the main driver of the
    algebraic-simplifier circular loop (see _build_table16)."""
    tbl = _base8()
    t = digits_lsb.shape[-1]
    if fe._KCTX["t"] is not None:
        q = identity(t)
        for w in range(tbl.shape[0]):
            dw = digits_lsb[w]
            q = add(q, _onehot_lookup(tbl[w], dw))
        return q

    def body(w, q):
        entry = lax.dynamic_index_in_dim(tbl, w, axis=0, keepdims=False)
        dw = lax.dynamic_index_in_dim(digits_lsb, w, axis=0, keepdims=False)
        return add(q, _onehot_lookup(entry, dw))

    q = lax.fori_loop(0, tbl.shape[0], body, identity(t))
    _count(t, tbl.shape[0] - 1)  # one table add per window
    return q


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------


def decompress(b32) -> tuple[jnp.ndarray, Point]:
    """[32, T] bytes -> (ok[T], Point). Same rejection rules as
    ops/curve.decompress (non-canonical y, non-residue, x=0 w/ sign)."""
    b32 = b32.astype(jnp.int32)
    sign = (b32[31] >> 7) & 1
    y_bytes = jnp.concatenate([b32[:31], (b32[31] & 0x7F)[None]], axis=0)
    y = fe.from_bytes32(y_bytes)
    p_col = jnp.broadcast_to(fe.p_col(), y.shape)
    y_ok = ~fe.geq_limbs(y, p_col)
    t = b32.shape[-1]
    one = fe.ones(t)
    y2 = fe.sqr(y)
    num = fe.sub(y2, one)
    den = fe.add(fe.mul(y2, fe.constant(fe.D_INT)), one)
    ok_sqrt, x = fe.sqrt_ratio(num, den)
    x_zero = fe.is_zero(x)
    flip = (fe.parity(x) != sign) & ~x_zero
    x = fe.select(flip, fe.neg(x), x)
    ok = y_ok & ok_sqrt & ~(x_zero & (sign == 1))
    return ok, Point(x, y, one, fe.mul(x, y))


def compress_many(points: list[Point]) -> list[jnp.ndarray]:
    """Compress k points sharing ONE inversion (Montgomery's trick);
    returns [32, T] byte arrays."""
    zs = [p.z for p in points]
    prefix = [zs[0]]
    for z in zs[1:]:
        prefix.append(fe.mul(prefix[-1], z))
    acc = fe.inv(prefix[-1])
    invs: list = [None] * len(zs)
    for i in range(len(zs) - 1, 0, -1):
        invs[i] = fe.mul(acc, prefix[i - 1])
        acc = fe.mul(acc, zs[i])
    invs[0] = acc
    outs = []
    for p, zi in zip(points, invs):
        x = fe.canonical(fe.mul(p.x, zi))
        b = fe.to_bytes(fe.mul(p.y, zi))
        top = b[31] + ((x[0] & 1) << 7)
        outs.append(jnp.concatenate([b[:31], top[None]], axis=0))
    return outs


def compress(p: Point) -> jnp.ndarray:
    return compress_many([p])[0]
