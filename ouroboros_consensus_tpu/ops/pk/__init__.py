"""Pallas TPU kernels for the Praos crypto hot path (limb-first layout).

Why this package exists (round-3 measurement, scripts/exp_layout3.py):
the jnp/XLA crypto graphs in ops/ put the 20-limb axis on TPU *lanes*
(padded to 128) and run every ladder as a `lax.fori_loop` whose
loop-carried state round-trips HBM each iteration with no cross-
iteration fusion — a single 64-window scalar ladder costs ~10x its
component field-muls. Inside a Pallas kernel the whole ladder runs with
its state in VMEM/registers, and the limb axis sits on *sublanes*
([NLIMBS, T] with the batch tile T on lanes), so the VPU is fully
occupied.

Layout convention: every per-lane quantity has the batch-tile axis T
LAST. Field elements are [20, T] int32 (13-bit limbs, little-endian,
nearly normalized exactly as ops/field.py); byte strings are [n, T];
per-lane scalars are [T].

All functions are pure jnp on values, so they run identically inside a
`pallas_call` kernel (Mosaic), under `interpret=True` (tests on CPU),
and under plain jit (differential tests against ops/field, ops/curve).

Reference equivalent: same as ops/field.py / ops/curve.py — the
libsodium fe25519/ge25519 arithmetic reached from the reference hot path
(Protocol/Praos.hs:543,580,582 via cardano-crypto-{class,praos}).
"""
