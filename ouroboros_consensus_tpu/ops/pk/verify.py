"""Limb-first Praos verifier cores (pure jnp; run inside Pallas kernels).

The four stages mirror the fused XLA path (protocol/batch.verify_praos):

  ed_core     — Ed25519 verify-point of the OCert cold-key signature
                (Praos.hs:580): P = s·B − h·A, compression deferred.
  kes_core    — CompactSum KES leaf verify-point + Merkle root walk
                (Praos.hs:582).
  vrf_core    — ECVRF-ED25519-SHA512-Elligator2 draft-03 points
                (Praos.hs:543): H, Γ, U = s·B − c·Y, V = s·H − c·Γ, 8Γ.
  finish_core — ONE shared Montgomery inversion compresses all 7 points,
                then the ECVRF challenge/beta hashes, the R-byte
                compare-on-bytes checks, Blake2b leader/nonce range
                extensions (Praos/VRF.hs:103,116) and the bracketed
                leader-threshold compare.

Layout: batch tile T last everywhere (bytes [n, T] int32, points
[20, T] limb coordinates). All control flow is batch-uniform; failures
are mask lanes. Differentially tested against the host verifiers and
the XLA twins in tests/test_pk_verify.py.

Certification (octrange, analysis/absint.py): each core and both
composed graphs are interval-proven no-overflow with inputs at the
byte/limb bound classes of analysis/shapes.json, and the proofs are
LANE-UNIVERSAL — machine-verified to not depend on the batch tile T
(every reduction here is over limb/byte axes, never lanes), so the
registry-tile certificate covers the production 8192-lane window. The
taint pass confirms batch-uniformity semantically: wire marks reach no
branch predicate or access pattern. Ratcheted in analysis/certified.json.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
from jax import numpy as jnp

from ..host import ed25519 as he
from . import curve as pc
from . import hashes as ph
from . import limbs as fe

SUITE = 0x04


# ---------------------------------------------------------------------------
# Ed25519
# ---------------------------------------------------------------------------


def ed_core(pk, s, hblocks, hnblocks):
    """(ok_pre[T], Point): P = s·B − h·A with h = SHA-512(R‖A‖M) mod L.

    pk, s: [32, T] bytes; hblocks: [NB, 128, T] padded bytes; hnblocks [T].
    """
    ok_a, a_pt = pc.decompress(pk)
    s_ok = fe.is_canonical_scalar(s)
    digest = ph.sha512_var(hblocks, hnblocks)
    h = fe.reduce512(digest)
    sb = pc.base_mul_w8(fe.windows8_from_bytes(s, 256))
    h_digits = fe.windows4_from_limbs(h, 256, msb_first=True)
    nha = pc.scalar_mul_w4(h_digits, pc.neg(a_pt))
    return ok_a & s_ok, pc.add(sb, nha)


# ---------------------------------------------------------------------------
# KES (CompactSum)
# ---------------------------------------------------------------------------


def kes_merkle_ok(vk, period, vk_leaf, siblings, depth: int):
    """Bottom-up CompactSum root reconstruction; bit i of the period
    selects H(vk ‖ sib) vs H(sib ‖ vk)."""
    cur = vk_leaf.astype(jnp.int32)
    for i in range(depth):
        sib = siblings[i]
        bit = (period >> i) & 1
        left = jnp.concatenate([cur, sib], axis=0)
        right = jnp.concatenate([sib, cur], axis=0)
        data = jnp.where((bit == 1)[None, :], right, left)
        cur = ph.blake2b_fixed(data, 64, 32)
    return jnp.all(cur == vk, axis=0)


def kes_core(vk, period, s, vk_leaf, siblings, hblocks, hnblocks, depth: int):
    """(ok_pre[T], Point) — leaf Ed25519 verify-point + root + period
    window check. siblings: [depth, 32, T]."""
    ok_ed, p = ed_core(vk_leaf, s, hblocks, hnblocks)
    root_ok = kes_merkle_ok(vk, period, vk_leaf, siblings, depth)
    period_ok = (period >= 0) & (period < (1 << depth))
    return ok_ed & root_ok & period_ok, p


# ---------------------------------------------------------------------------
# ECVRF (draft-03)
# ---------------------------------------------------------------------------


def _sqrt_of(x: int) -> int:
    """Host-side sqrt mod p (p = 5 mod 8 Shanks); x must be a QR."""
    p = he.P
    s = pow(x % p, (p + 3) // 8, p)
    if (s * s - x) % p != 0:
        s = s * pow(2, (p - 1) // 4, p) % p  # multiply by sqrt(-1)
    assert (s * s - x) % p == 0
    return s


# chi(2) = chi(i) = -1 for p = 2^255-19, so both 2i and -2i are QRs;
# these are the branch-2 fixup constants of the single-chain Elligator2
_SQRT_2I = _sqrt_of(2 * fe.SQRT_M1_INT)
_SQRT_M2I = _sqrt_of(-2 * fe.SQRT_M1_INT)


def elligator2(r):
    """[20, T] field element -> Point (even-x convention, matching
    ops/host/ecvrf.elligator2).

    Projective single-chain formulation: the naive map costs FIVE
    ~254-squaring exponentiation chains (inv(denom), legendre, sqrt,
    inv(v), inv(u+1)); this one costs ONE. Write u = U/W over the
    common denominator W = 1 + 2r² and N(U, W) = U·(U² + A·U·W + W²)
    (the Montgomery RHS numerator, w = N/W³). Then

      x² = c²·u²/w = c²·U²·W / N      (c = sqrt(-486664))

    and ONE Shanks exponentiation for branch 1 decides everything. Let
    ρ = num·n³·(num·n⁷)^((p-5)/8) (the sqrt_ratio candidate for
    num = c²A²W, n = N1): n·ρ² ∈ {±num, ±i·num}, and which of the four
    identifies both the branch (χ(W·N1) = 1 ⟺ w1 square — the host's
    is_square test) and the root:

      n·ρ² = +num   → branch 1, x = ρ
      n·ρ² = -num   → branch 1, x = i·ρ
      n·ρ² = ±i·num → branch 2; u2 = 2r²·u1 and Q(u2) = Q(u1) (with
                      Q(u) = u²+Au+1, since u2 = -u1-A), so
                      w2 = (u2/u1)·w1 = 2r²·w1 and
                      x2² = c²u2²/w2 = 2r²·x1²:
                        n·ρ² = +i·num → x1² = -i·ρ² → x = r·ρ·sqrt(-2i)
                        n·ρ² = -i·num → x1² = +i·ρ² → x = r·ρ·sqrt(2i)

    Everything stays projective: the Edwards y rides as (U−W : U+W) and
    the returned point has Z ≠ 1 (every consumer — ladders, cofactor,
    compress — is projective)."""
    t = r.shape[-1]
    one = fe.ones(t)
    zero = fe.zeros(t)
    A = he.MONT_A % he.P
    A2 = A * A % he.P
    c2 = he.SQRT_M486664 * he.SQRT_M486664 % he.P  # = -486664 mod p
    w_den = fe.add(fe.mul_small(fe.sqr(r), 2), one)
    W = fe.select(fe.is_zero(w_den), one, w_den)  # host denom=0 guard
    W2 = fe.sqr(W)
    # branch 1: U1 = -A (constant numerator)
    #   N1 = (-A)·(A² - A²·W + W²); num1 = (c²·A²)·W
    a2w = fe.mul(fe.constant(A2), W)
    n1 = fe.mul(
        fe.constant((-A) % he.P),
        fe.add(fe.sub(fe.constant(A2), a2w), W2),
    )
    num1 = fe.mul(fe.constant(c2 * A2 % he.P), W)
    # ONE exponentiation chain: the sqrt_ratio candidate and its full
    # classification (limbs.sqrt_ratio_ext — shared with fe.sqrt_ratio)
    rho, good, good_alt, is_pi = fe.sqrt_ratio_ext(num1, n1)
    ok1 = good | good_alt | fe.is_zero(n1)  # w1 = 0 stays on branch 1
    x1 = fe.select(good, rho, fe.mul(rho, fe.constant(fe.SQRT_M1_INT)))
    x2 = fe.mul(
        fe.mul(r, rho),
        fe.select(is_pi, fe.constant(_SQRT_M2I), fe.constant(_SQRT_2I)),
    )
    x = fe.select(ok1, x1, x2)
    x = fe.select(fe.parity(x) == 1, fe.neg(x), x)
    u1 = jnp.broadcast_to(fe.constant((-A) % he.P), (fe.NLIMBS, t))
    u2 = fe.mul(fe.constant(A), fe.sub(one, W))  # U2 = -U1 - A·W
    un = fe.select(ok1, u1, u2)
    # y = (u-1)/(u+1) -> (Y : Z) = (U-W : U+W); host pins y=0 at u=-1
    y_num = fe.sub(un, W)
    z = fe.add(un, W)
    z_zero = fe.is_zero(z)
    y_num = fe.select(z_zero, zero, y_num)
    z = fe.select(z_zero, one, z)
    return pc.Point(fe.mul(x, z), y_num, z, fe.mul(x, y_num))


def hash_to_curve(pk_bytes, alpha_bytes):
    """H = 8 * Elligator2(SHA-512(suite ‖ 1 ‖ pk ‖ alpha) mod 2^255)."""
    t = pk_bytes.shape[-1]
    prefix = ph.const_rows([SUITE, 0x01], t)
    data = jnp.concatenate([prefix, pk_bytes, alpha_bytes], axis=0)  # [66, T]
    digest = ph.sha512_fixed(data)
    r32 = jnp.concatenate(
        [digest[:31], (digest[31] & 0x7F)[None]], axis=0
    )
    r = fe.canonical(fe.from_bytes32(r32))
    return pc.mul_cofactor(elligator2(r))


def vrf_core_prep(pk, gamma, c, s, alpha):
    """Stage A of the VRF check: decode/validate + hash-to-curve (field
    ops and SHA-512 only, no ladders). Split from the ladders so the
    Pallas kernel compiles as two small Mosaic modules instead of one
    31.8 MB / 185k-op monolith (round-3 compile-time attribution)."""
    ok_y, y_pt = pc.decompress(pk)
    ok_g, g_pt = pc.decompress(gamma)
    s_ok = fe.is_canonical_scalar(s)
    h_pt = hash_to_curve(pk, alpha)
    return ok_y & ok_g & s_ok, h_pt, y_pt, g_pt


def vrf_core_ladders(c, s, h_pt, y_pt, g_pt):
    """Stage B: the three scalar ladders (U = sB - cY, V = sH - cΓ, 8Γ)."""
    s_digits = fe.windows4_from_bytes(s, 256, msb_first=True)
    c_digits = fe.windows4_from_bytes(c, 128, msb_first=True)

    sb = pc.base_mul_w8(fe.windows8_from_bytes(s, 256))
    u_pt = pc.add(sb, pc.scalar_mul_w4(c_digits, pc.neg(y_pt)))
    v_pt = pc.double_scalar_mul_w4(s_digits, h_pt, c_digits, pc.neg(g_pt))
    g8 = pc.mul_cofactor(g_pt)
    return h_pt, g_pt, u_pt, v_pt, g8


def vrf_core(pk, gamma, c, s, alpha):
    """(ok_pre[T], (H, Γ, U, V, 8Γ)) — points left uncompressed for the
    shared inversion in finish_core. c: [16, T]; others [32, T]."""
    ok_pre, h_pt, y_pt, g_pt = vrf_core_prep(pk, gamma, c, s, alpha)
    return ok_pre, vrf_core_ladders(c, s, h_pt, y_pt, g_pt)


def vrf_core_bc_prep(pk, gamma, u, v, s, alpha):
    """Stage A for BATCH-COMPATIBLE (128-byte) proofs: decode/validate +
    hash-to-curve + the challenge c = SHA-512(suite ‖ 2 ‖ enc(H) ‖ Γ ‖
    U ‖ V)[:16] derived from the ANNOUNCED bytes (one extra inversion to
    compress H vs vrf_core_prep). Returns (ok_pre, c16 [16, T], H, Y, Γ).

    The ladders (vrf_core_ladders) and finish_core run UNCHANGED on the
    derived c: finish's c' == c compare then holds iff the recomputed
    U' = s·B − c·Y and V' = s·H − c·Γ compress to the announced U, V
    bytes — the compare-on-bytes form of the two batch-compat group
    equations (ops/ecvrf_batch.derive_c_bc rationale)."""
    ok_y, y_pt = pc.decompress(pk)
    ok_g, g_pt = pc.decompress(gamma)
    s_ok = fe.is_canonical_scalar(s)
    h_pt = hash_to_curve(pk, alpha)
    h_enc = pc.compress(h_pt)
    t = pk.shape[-1]
    p2 = ph.const_rows([SUITE, 0x02], t)
    cdata = jnp.concatenate(
        [p2, h_enc, gamma.astype(jnp.int32), u.astype(jnp.int32),
         v.astype(jnp.int32)],
        axis=0,
    )  # [130, T]
    c16 = ph.sha512_fixed(cdata)[:16]
    return ok_y & ok_g & s_ok, c16, h_pt, y_pt, g_pt


def vrf_core_bc(pk, gamma, u, v, s, alpha):
    """(ok_pre[T], c16, (H, Γ, U', V', 8Γ)) — the batch-compat per-lane
    twin of vrf_core (same ladder stage, derived challenge)."""
    ok_pre, c16, h_pt, y_pt, g_pt = vrf_core_bc_prep(pk, gamma, u, v, s, alpha)
    return ok_pre, c16, vrf_core_ladders(c16, s, h_pt, y_pt, g_pt)


# ---------------------------------------------------------------------------
# Finish: shared compression + challenge/beta + leader checks
# ---------------------------------------------------------------------------


class CoreVerdicts(NamedTuple):
    ok_ocert_sig: jnp.ndarray  # [T] bool
    ok_kes_sig: jnp.ndarray
    ok_vrf: jnp.ndarray
    ok_leader: jnp.ndarray
    leader_ambiguous: jnp.ndarray
    eta: jnp.ndarray  # [32, T] int32 bytes
    leader_value: jnp.ndarray  # [32, T] int32 bytes (big-endian value)


def _lt_be(a, b):
    """Big-endian lexicographic a < b over [32, T] byte arrays -> bool[T]."""
    lt = jnp.zeros_like(a[0], dtype=bool)
    gt = jnp.zeros_like(lt)
    for i in range(a.shape[0]):
        lt = lt | (~gt & (a[i] < b[i]))
        gt = gt | (~lt & (a[i] > b[i]))
    return lt


def finish_core(
    ok_ed_pre, ed_point, ed_r,
    ok_kes_pre, kes_point, kes_r,
    ok_vrf_pre, vrf_points, c,
    beta_decl, thr_lo, thr_hi,
):
    """All byte arrays [n, T] int32; points limb-first."""
    t = c.shape[-1]
    encs = pc.compress_many([ed_point, kes_point, *vrf_points])
    ok_ed = ok_ed_pre & jnp.all(encs[0] == ed_r.astype(jnp.int32), axis=0)
    ok_kes = ok_kes_pre & jnp.all(encs[1] == kes_r.astype(jnp.int32), axis=0)

    h_enc, gamma_enc, u_enc, v_enc, g8_enc = encs[2:]
    p2 = ph.const_rows([SUITE, 0x02], t)
    cdata = jnp.concatenate([p2, h_enc, gamma_enc, u_enc, v_enc], axis=0)
    c_prime = ph.sha512_fixed(cdata)[:16]
    p3 = ph.const_rows([SUITE, 0x03], t)
    beta = ph.sha512_fixed(jnp.concatenate([p3, g8_enc], axis=0))

    c = c.astype(jnp.int32)
    beta_decl = beta_decl.astype(jnp.int32)
    ok_proof = ok_vrf_pre & jnp.all(c_prime == c, axis=0)
    ok_vrf = ok_proof & jnp.all(beta == beta_decl, axis=0)

    tag_l = ph.const_rows([ord("L")], t)
    lv = ph.blake2b_fixed(jnp.concatenate([tag_l, beta_decl], axis=0), 65, 32)
    tag_n = ph.const_rows([ord("N")], t)
    eta1 = ph.blake2b_fixed(jnp.concatenate([tag_n, beta_decl], axis=0), 65, 32)
    eta = ph.blake2b_fixed(eta1, 32, 32)

    thr_lo = thr_lo.astype(jnp.int32)
    thr_hi = thr_hi.astype(jnp.int32)
    certain_win = _lt_be(lv, thr_lo)
    certain_loss = ~_lt_be(lv, thr_hi)
    ambiguous = ~certain_win & ~certain_loss
    return CoreVerdicts(ok_ed, ok_kes, ok_vrf, certain_win, ambiguous, eta, lv)


def verify_praos_core(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
    *, kes_depth: int,
) -> CoreVerdicts:
    """The whole fused hot path over one tile (argument order mirrors
    protocol/batch.verify_praos, transposed to limb-first layout)."""
    ok_ed_pre, ed_point = ed_core(ed_pk, ed_s, ed_hblocks, ed_hnblocks)
    ok_kes_pre, kes_point = kes_core(
        kes_vk, kes_period, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks, kes_depth,
    )
    ok_vrf_pre, vrf_points = vrf_core(vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha)
    return finish_core(
        ok_ed_pre, ed_point, ed_r,
        ok_kes_pre, kes_point, kes_r,
        ok_vrf_pre, vrf_points, vrf_c,
        beta_decl, thr_lo, thr_hi,
    )


def verify_praos_core_bc(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
    *, kes_depth: int,
) -> CoreVerdicts:
    """The composed hot path over BATCH-COMPATIBLE proofs: identical to
    verify_praos_core except the vrf challenge is derived on device from
    the announced U, V (vrf_core_bc); ed/kes/finish are byte-identical."""
    ok_ed_pre, ed_point = ed_core(ed_pk, ed_s, ed_hblocks, ed_hnblocks)
    ok_kes_pre, kes_point = kes_core(
        kes_vk, kes_period, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks, kes_depth,
    )
    ok_vrf_pre, c16, vrf_points = vrf_core_bc(
        vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha
    )
    return finish_core(
        ok_ed_pre, ed_point, ed_r,
        ok_kes_pre, kes_point, kes_r,
        ok_vrf_pre, vrf_points, c16,
        beta_decl, thr_lo, thr_hi,
    )
