"""Limb-first Pippenger-style multi-scalar multiplication (XLA path).

The window-level aggregated verifier (ops/pk/aggregate.py) reduces every
per-lane ladder of the Praos hot path to ONE multi-scalar multiplication
    total = Σ_i  k_i · P_i        (N = points-per-lane × lanes)
whose cost amortizes the ~320 point-ops/lane/ladder of the per-lane
path down to ~one bucket add per point per window plus a SHARED doubling
chain (256 doublings TOTAL instead of 256 per lane).

Structure (classic Pippenger, arranged for batch-uniform XLA):

  * scalars split into W c-bit windows (digits [W, N]);
  * per window, points are grouped by digit with an argsort and the
    per-digit bucket sums B_d = Σ_{digit=d} P_i come out of a SEGMENT
    SUM over the sorted order: a chunked inclusive prefix scan
    (`lax.fori_loop` over the within-chunk axis — the loop body is a
    separate XLA computation, so the multiply chain is FENCED exactly
    like the ladder loops remediated in PR 1) + an unrolled log2(C)
    combine of the chunk carries + one gather at the D digit-boundary
    positions;
  * the window value Σ_d d·B_d is the textbook double-accumulator
    running sum, run as ONE fori_loop over d with the window axis
    vectorized (all windows of a width-group weighted simultaneously);
  * windows combine MSB-first with c doublings per step (the shared
    doubling chain — `fori`-fenced Horner walk).

Point-op work per window ≈ N bucket adds + C chunk combines + 3D
boundary ops, so a 9-points/lane aggregate over the Praos equations
costs ≈ (4·⌈128/c⌉ + 5·⌈253/c⌉)·T lane-point-adds — ~5.8x below the
per-lane ladders at c=8 (scripts/count_point_ops.py measures both; the
measured 48 lane-ops/lane at 8192 is RATCHETED in budgets.json
`point_ops`, so an extra bucket pass fails scripts/lint.py statically).

Certification (octrange, analysis/absint.py): interval no-overflow is
proven at the production 8192-lane window (the digit/bucket-count
accumulators are the lane-sensitive part), and the taint pass proves
the per-window argsort steers on PUBLIC data only — its keys derive
exclusively from `wire:`-marked header bytes (the Fiat–Shamir
coefficients of ops/pk/aggregate.py), never from a secret, and every
steering site (sort/gather/scatter-add below) is inventoried in
analysis/certified.json so a new data-dependent access is a ratchet
violation.

Everything is pure jnp over the ops/pk limb-first [20, X] layout and
runs on the XLA path of ops/pk/{limbs,curve} (argsort/gather have no
Mosaic lowering, and the MSM is a tiny fraction of the aggregate
program's work, so it is NOT a Pallas kernel by design).
"""

from __future__ import annotations

import jax
from jax import lax
from jax import numpy as jnp

from . import curve as pc
from . import limbs as fe

# default window width: D = 256 buckets keeps the boundary-extraction
# arrays small while the accumulation work is already within ~15% of the
# c→log2(N) optimum for bench-scale N (see module docstring economics)
WINDOW_BITS = 8
# chunk count for the segment scan: C lanes run in parallel, N/C
# sequential fori steps; 256 balances sequential depth against the
# width of each vectorized point add at bench-scale N
CHUNKS = 256


def _coords(p: pc.Point):
    return (p.x, p.y, p.z, p.t)


def _point(coords) -> pc.Point:
    return pc.Point(*coords)


def _take(p: pc.Point, idx) -> pc.Point:
    return _point(tuple(jnp.take(c, idx, axis=-1) for c in _coords(p)))


def is_identity(p: pc.Point):
    """bool[...]: projective identity test (X = 0 and Y = Z)."""
    return fe.is_zero(p.x) & fe.eq(p.y, p.z)


def _segment_scan(p: pc.Point, n: int, chunks: int):
    """Inclusive prefix point-sums over the (sorted) lane axis, chunked:
    -> (local [4 coords, 20, C, M], chunk_offsets Point [20, C]) where
    global_prefix[j] = chunk_offsets[j // M] + local[j // M, j % M].

    The within-chunk walk is ONE fori_loop (M steps, each a [20, C]-wide
    point add); the cross-chunk exclusive prefix is an unrolled
    Hillis–Steele over the C chunk totals (log2(C) adds, ~C·log2(C)
    lane-work — negligible against the N-work main walk)."""
    m = n // chunks
    cs = tuple(c.reshape(20, chunks, m) for c in _coords(p))

    def body(j, carry):
        acc, outs = carry
        cur = _point(tuple(
            lax.dynamic_slice(c, (0, 0, j), (20, chunks, 1))[:, :, 0]
            for c in cs
        ))
        acc = pc.add(acc, cur)
        outs = tuple(
            lax.dynamic_update_slice(o, a[:, :, None], (0, 0, j))
            for o, a in zip(outs, _coords(acc))
        )
        return acc, outs

    init_outs = tuple(jnp.zeros((20, chunks, m), jnp.int32) for _ in range(4))
    acc0 = pc.identity(chunks)
    totals, outs = lax.fori_loop(0, m, body, (acc0, init_outs))
    pc._count(chunks, m - 1)  # fori body traced once; m runs happen

    # exclusive prefix of the chunk totals: shift right (identity in
    # front), then inclusive Hillis–Steele
    ident = pc.identity(1)
    ex = _point(tuple(
        jnp.concatenate([i_c, t_c[:, :-1]], axis=-1)
        for i_c, t_c in zip(_coords(ident), _coords(totals))
    ))
    k = 1
    while k < chunks:
        shifted = _point(tuple(
            jnp.concatenate(
                [jnp.broadcast_to(i_c, (20, k)), c[:, :-k]], axis=-1
            )
            for i_c, c in zip(_coords(ident), _coords(ex))
        ))
        ex = pc.add(ex, shifted)
        k *= 2
    return outs, ex


def _window_prefix(p: pc.Point, digits_w, nbuckets: int, chunks: int):
    """Bucket PREFIX sums E_d = Σ_{digit_i ≤ d} P_i for ONE window ->
    Point with [20, D] coords. digits_w: [N] int32 in [0, D).

    The prefixes are what the segment scan produces for free (one gather
    at the digit-boundary positions); returning them un-differenced lets
    the caller choose between per-bucket sums (B_d = E_d − E_{d−1}, the
    unsigned `msm` path) and the Abel-summation weighting of
    `_weighted_sums_abel`, which consumes E_d directly and skips the
    width-D differencing add entirely."""
    n = digits_w.shape[0]
    chunks = min(chunks, n)
    m = -(-n // chunks)
    pad = chunks * m - n
    if pad:
        # digit-0 lanes never enter the weighted sum; pad with identity
        ident = pc.identity(pad)
        p = _point(tuple(
            jnp.concatenate([c, ic], axis=-1)
            for c, ic in zip(_coords(p), _coords(ident))
        ))
        digits_w = jnp.concatenate(
            [digits_w, jnp.zeros((pad,), digits_w.dtype)]
        )
        n = n + pad

    perm = jnp.argsort(digits_w)
    ds = jnp.take(digits_w, perm)
    sp = _take(p, perm)
    local, offsets = _segment_scan(sp, n, chunks)

    counts = jnp.zeros((nbuckets,), jnp.int32).at[ds].add(1)
    cum = jnp.cumsum(counts)
    idx = jnp.maximum(cum - 1, 0)
    m_len = n // chunks
    chunk_of = idx // m_len
    m_of = idx % m_len
    local_pt = _point(tuple(c[:, chunk_of, m_of] for c in local))
    off_pt = _take(offsets, chunk_of)
    e = pc.add(off_pt, local_pt)
    return pc.select(cum > 0, e, pc.identity(nbuckets))


def _window_buckets(p: pc.Point, digits_w, nbuckets: int, chunks: int):
    """Bucket sums B_d = Σ_{digit_i = d} P_i for ONE window ->
    Point with [20, D] coords (difference of adjacent prefixes)."""
    e = _window_prefix(p, digits_w, nbuckets, chunks)
    prev = _point(tuple(
        jnp.concatenate([ic, c[:, :-1]], axis=-1)
        for ic, c in zip(_coords(pc.identity(1)), _coords(e))
    ))
    return pc.add(e, pc.neg(prev))  # B_d = E_d − E_{d−1}


def _weighted_sums(bucket_stack: pc.Point, nbuckets: int) -> pc.Point:
    """Σ_d d·B_d per window, windows vectorized: bucket_stack coords
    [20, D, W] -> Point [20, W]. Double-accumulator running sum as ONE
    fori_loop from d = D−1 down to 1 (bucket 0 is unweighted)."""
    w = bucket_stack.x.shape[-1]
    cs = _coords(bucket_stack)

    def body(i, carry):
        run, acc = carry
        d = nbuckets - 1 - i
        b = _point(tuple(
            lax.dynamic_slice(c, (0, d, 0), (20, 1, w))[:, 0, :]
            for c in cs
        ))
        run = pc.add(run, b)
        acc = pc.add(acc, run)
        return run, acc

    init = (pc.identity(w), pc.identity(w))
    _, acc = lax.fori_loop(0, nbuckets - 1, body, init)
    pc._count(w, 2 * (nbuckets - 2))  # 2 adds/step, body traced once
    return acc


def _horner(window_sums: pc.Point, cbits: int) -> pc.Point:
    """Combine per-window values MSB-first with the SHARED doubling
    chain: acc = 2^c·acc + S_w, one fori step per window -> [20, 1]."""
    w = window_sums.x.shape[-1]
    cs = _coords(window_sums)

    def body(i, acc):
        wi = w - 1 - i  # MSB window first
        s = _point(tuple(
            lax.dynamic_slice(c, (0, wi), (20, 1)) for c in cs
        ))
        acc = pc.doubles(acc, cbits)
        return pc.add(acc, s)

    out = lax.fori_loop(0, w, body, pc.identity(1))
    pc._count(1, (w - 1) * (cbits + 1))  # body traced once; w runs
    return out


def msm(scalars, p: pc.Point, nbits: int = 256, *,
        cbits: int = WINDOW_BITS, chunks: int = CHUNKS) -> pc.Point:
    """Σ_i scalars_i · P_i over the lane axis -> Point with [20, 1]
    coords. scalars: [20, N] normalized limbs (< 2^nbits); p: Point with
    [20, N] coords. nbits bounds the window count (128 for the raw
    Fiat–Shamir coefficients, 256 for mod-L products).

    The per-window bucket phase is ONE lax.scan over the W digit rows —
    the window bodies are structurally identical, so the scan keeps the
    traced graph a single window wide (~30x fewer equations than the
    unrolled form; compile time, not compute, is what this buys)."""
    assert cbits == 8, "cbits != 8 needs a digit regrouping"
    digits = fe.windows8_from_limbs(scalars, -(-nbits // 8) * 8)
    nwin = digits.shape[0]
    nbuckets = 1 << cbits

    ops0 = dict(pc._OPSTATS)

    def wbody(_, digits_w):
        b = _window_buckets(p, digits_w, nbuckets, chunks)
        return 0, _coords(b)

    _, stacked = lax.scan(wbody, 0, digits)  # coords [W, 20, D]
    if pc._OPSTATS["on"]:  # scan body traced once; nwin windows run
        for k in ("ops", "lane_ops"):
            pc._OPSTATS[k] += (nwin - 1) * (pc._OPSTATS[k] - ops0[k])
    stack = _point(tuple(jnp.moveaxis(c, 0, -1) for c in stacked))
    sums = _weighted_sums(stack, nbuckets)
    return _horner(sums, cbits)


def msm_groups(groups) -> pc.Point:
    """Sum of several MSMs with different scalar widths:
    groups = [(scalars [20, N_g], Point, nbits), ...] -> [20, 1]."""
    total = pc.identity(1)
    for scalars, p, nbits in groups:
        total = pc.add(total, msm(scalars, p, nbits))
    return total


# ---------------------------------------------------------------------------
# Shared-bucket signed-digit engine (the all-stage fold of PR 15)
# ---------------------------------------------------------------------------
#
# `msm_groups` runs one FULL Pippenger per width group: separate sorts,
# separate segment scans, separate weighted sums, separate Horner
# chains. `msm_shared` merges every group into ONE bucket machine:
#
#   * scalars recode into BALANCED signed base-2^c digits
#     d ∈ (−2^(c−1), 2^(c−1)] (python carry loop over the unsigned c-bit
#     windows — static, per-lane int32 work only). A window buckets on
#     |d| and conditionally negates the point (select/neg: field ops,
#     not counted point-ops), so D = 2^(c−1)+1 buckets replace the 2^c
#     of the unsigned path — HALF the bucket-boundary and weighted-sum
#     work at one window width wider, which is what makes c = 12
#     affordable (D = 2049) and drops the dominant per-point bucket-add
#     count from ⌈nbits/8⌉ to ⌈(nbits+1)/12⌉ passes;
#   * windows are grouped into SEGMENTS by which groups still have
#     digits: low windows walk the concatenation of every group's
#     points, high windows walk only the wide groups — one lax.scan per
#     segment, all windows of a segment sharing one traced body;
#   * each window keeps the PREFIX sums E_d (no per-bucket
#     differencing); the weighted sum uses Abel summation
#         Σ_{d=1}^{D−1} d·B_d = (D−1)·E_{D−1} − Σ_{d=0}^{D−2} E_d
#     — ONE add per bucket step (the unsigned path pays two) plus c−1
#     doublings for the (D−1) = 2^(c−1) weighting, and the digit-0
#     bucket cancels algebraically so identity padding needs no mask;
#   * every window of every segment lands in one stacked [20, D, W]
#     prefix tensor -> one vectorized Abel pass -> ONE shared Horner
#     doubling chain for the whole multi-group total.

# signed-digit window width of the shared engine: D = 2^11+1 buckets,
# ⌈129/12⌉ = 11 windows over the raw 128-bit Fiat–Shamir coefficients,
# ⌈254/12⌉ = 22 over full mod-L products (scripts/count_point_ops.py
# measures the resulting all-stage total; budgets.json ratchets it)
SHARED_BITS = 12
# chunk count of the shared path's segment scans: the counted cost is
# chunks·(m−1) + log2(chunks)·chunks (Hillis–Steele combine), so
# NARROWER chunks cost less point-op budget (N−chunks main walk, tiny
# combine) at more sequential fori steps per pass — 64 lands the
# all-stage total under the 100/lane pin with the walk still 64 lanes
# wide (the unsigned `msm` keeps CHUNKS=256: its budget has slack and
# its fori depth stays shallow for the XLA-twin walls)
SHARED_CHUNKS = 64


def signed_digit_windows(nbits: int, cbits: int = SHARED_BITS) -> int:
    """Window count of the balanced recode: the +1 bit absorbs the
    final carry, so no extra top window is ever needed."""
    return -(-(nbits + 1) // cbits)


def recode_signed(scalars, nbits: int, cbits: int = SHARED_BITS):
    """[20, N] normalized limbs (< 2^nbits) -> [W, N] int32 balanced
    signed digits with Σ_w d_w·2^(w·c) = scalar and
    d_w ∈ (−2^(c−1), 2^(c−1)].

    Static python carry loop over the unsigned c-bit windows: a window
    spans at most two 13-bit limbs for c ≤ 13, and the top window's
    slack (nbits+1 ≤ W·c) absorbs the final carry, so the loop never
    emits a W+1-th digit. Pure per-lane int32 shifts/masks — no point
    ops, no data-dependent control flow."""
    assert 2 <= cbits <= fe.BITS, "window must fit two adjacent limbs"
    w = signed_digit_windows(nbits, cbits)
    half = 1 << (cbits - 1)
    mask = (1 << cbits) - 1
    n = scalars.shape[-1]
    padded = jnp.concatenate(
        [scalars, jnp.zeros((2, n), jnp.int32)], axis=0
    )
    digits = []
    carry = jnp.zeros((n,), jnp.int32)
    for i in range(w):
        li, sh = divmod(i * cbits, fe.BITS)
        u = padded[li] >> sh
        if sh + cbits > fe.BITS:
            u = u | (padded[li + 1] << (fe.BITS - sh))
        d = (u & mask) + carry
        carry = (d > half).astype(jnp.int32)
        digits.append(d - (carry << cbits))
    return jnp.stack(digits)


def _weighted_sums_abel(prefix_stack: pc.Point, nbuckets: int,
                        cbits: int) -> pc.Point:
    """Σ_d d·B_d per window from the PREFIX sums, windows vectorized:
    prefix_stack coords [20, D, W] -> Point [20, W]. Abel summation:
    (D−1)·E_{D−1} − Σ_{d=0}^{D−2} E_d — one add per bucket step (vs the
    two of the running-sum form) and c−1 doublings for the top weight
    (D−1 = 2^(c−1) with balanced digits)."""
    assert nbuckets == (1 << (cbits - 1)) + 1
    w = prefix_stack.x.shape[-1]
    cs = _coords(prefix_stack)

    def body(d, acc):
        e = _point(tuple(
            lax.dynamic_slice(c, (0, d, 0), (20, 1, w))[:, 0, :]
            for c in cs
        ))
        return pc.add(acc, e)

    acc = lax.fori_loop(0, nbuckets - 1, body, pc.identity(w))
    pc._count(w, nbuckets - 2)  # 1 add/step, body traced once
    top = _point(tuple(c[:, nbuckets - 1, :] for c in cs))
    top = pc.doubles(top, cbits - 1)  # (D−1)·E_{D−1}
    return pc.add(top, pc.neg(acc))


def msm_shared(groups, *, cbits: int = SHARED_BITS,
               chunks: int = SHARED_CHUNKS) -> pc.Point:
    """Sum of several MSMs through ONE shared signed-digit bucket
    machine: groups = [(scalars [20, N_g], Point, nbits), ...] ->
    Point [20, 1]. See the section comment above for the structure.

    Window segments: with the group widths sorted, windows
    [0, W_min) walk every group's points concatenated, the next segment
    only the groups still holding digits, and so on — one lax.scan per
    segment (each body traced once; the op counter replicates per
    window exactly like `msm`)."""
    ws = [signed_digit_windows(nbits, cbits) for _, _, nbits in groups]
    nbuckets = (1 << (cbits - 1)) + 1
    digits = [recode_signed(s, nbits, cbits)
              for s, _, nbits in groups]  # [W_g, N_g] signed

    stacks = []
    w_lo = 0
    for w_hi in sorted(set(ws)):
        alive = [i for i in range(len(groups)) if ws[i] > w_lo]
        p_seg = _point(tuple(
            jnp.concatenate([_coords(groups[i][1])[k] for i in alive],
                            axis=-1)
            for k in range(4)
        ))
        d_seg = jnp.concatenate(
            [digits[i][w_lo:w_hi] for i in alive], axis=-1
        )  # [w_hi − w_lo, N_seg]

        ops0 = dict(pc._OPSTATS)

        def wbody(_, dw, p_seg=p_seg):
            # bucket on |d|; fold the sign into the point (select/neg
            # are field work — the bucket adds are what's counted)
            p_eff = pc.select(dw >= 0, p_seg, pc.neg(p_seg))
            e = _window_prefix(p_eff, jnp.abs(dw), nbuckets, chunks)
            return 0, _coords(e)

        _, st = lax.scan(wbody, 0, d_seg)  # coords [Wseg, 20, D]
        nwin = w_hi - w_lo
        if pc._OPSTATS["on"]:  # scan body traced once; nwin windows run
            for k in ("ops", "lane_ops"):
                pc._OPSTATS[k] += (nwin - 1) * (pc._OPSTATS[k] - ops0[k])
        stacks.append(_point(tuple(
            jnp.moveaxis(c, 0, -1) for c in st
        )))
        w_lo = w_hi

    stack = _point(tuple(
        jnp.concatenate([_coords(s)[k] for s in stacks], axis=-1)
        for k in range(4)
    ))  # [20, D, W_total] — window w weighted 2^(w·c) by the Horner
    sums = _weighted_sums_abel(stack, nbuckets, cbits)
    return _horner(sums, cbits)
