"""Pallas TPU kernel wrappers for the Praos verifier cores.

Each stage of ops/pk/verify.py runs as ONE `pallas_call` with a 1-D grid
over batch tiles: inputs arrive [*, B] (limb-first), each program sees a
[*, TILE] block in VMEM and runs the full core — ladders, hash rounds,
inversion chains — with every intermediate in VMEM/registers. The four
stages chain inside a single jit, so a verification batch is one host
dispatch regardless of tile count.

Kernels cannot close over array constants (jax requires them as
inputs): small field/Barrett constants are materialized inside the
kernel from Python-int scalar fills (limbs.kernel_consts), and the one
genuinely large constant — the [32, 80, 256] f32 fixed-base table
(curve.BASE8_NP) — is passed as a grid-invariant VMEM input where
fixed-base muls occur (curve.kernel_base8).

On non-TPU backends the same kernels run under `interpret=True`
(functionally identical, used by the CPU test suite), so correctness is
established once by the differential tests for both execution modes.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import numpy as np
from jax import numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve as pc
from . import limbs as fe
from . import verify as pv

# 128 lanes/tile: the ed/kes/vrf cores peak ~17MB of scoped VMEM at 256
# lanes on v5e (16MB limit) — measured OOM on hardware; 128 fits with
# headroom and matches the lane register width.
TILE = int(os.environ.get("OCT_PK_TILE", "128"))

_BASE8_SHAPE = pc.BASE8_NP.shape  # [32, 80, 256] f32


def _interpret() -> bool:
    # OCT_PK_INTERPRET=0 forces real Mosaic lowering even when the
    # default backend is CPU — required for deviceless AOT compilation
    # against a TPU TopologyDescription (scripts/aot_precompile.py);
    # =1 forces interpret mode (the ≤60s composed smoke test).
    force = os.environ.get("OCT_PK_INTERPRET", "")
    if force in ("0", "1"):
        return force == "1"
    return jax.devices()[0].platform != "tpu"


def _tile_spec(shape_prefix, tile):
    """BlockSpec for an array [*shape_prefix, B] tiled on the last axis."""
    nd = len(shape_prefix)
    return pl.BlockSpec(
        (*shape_prefix, tile),
        lambda i, _nd=nd: (*(0,) * _nd, i),
        memory_space=pltpu.VMEM,
    )


def _full_spec(shape):
    """BlockSpec for a grid-invariant input (consts pack, base table)."""
    nd = len(shape)
    return pl.BlockSpec(
        tuple(shape), lambda i, _nd=nd: (0,) * _nd, memory_space=pltpu.VMEM
    )


def _call(kernel, b, in_prefixes, out_prefixes, args, with_base8: bool):
    tile = min(TILE, b)
    assert b % tile == 0
    const_args = []
    const_specs = []
    if with_base8:
        const_args.append(jnp.asarray(pc.BASE8_NP))
        const_specs.append(_full_spec(_BASE8_SHAPE))
    return pl.pallas_call(
        kernel,
        grid=(b // tile,),
        in_specs=const_specs + [_tile_spec(p, tile) for p in in_prefixes],
        out_specs=tuple(_tile_spec(p, tile) for p in out_prefixes),
        out_shape=tuple(
            jax.ShapeDtypeStruct((*p, b), jnp.int32) for p in out_prefixes
        ),
        interpret=_interpret(),
    )(*const_args, *args)


# ---------------------------------------------------------------------------
# Stage kernels
# ---------------------------------------------------------------------------


def _ed_kernel(base8_ref, pk_ref, s_ref, hb_ref, hnb_ref, ok_ref, pt_ref):
    tile = pk_ref.shape[-1]
    with fe.kernel_consts(tile), pc.kernel_base8(base8_ref[:]):
        ok, p = pv.ed_core(pk_ref[:], s_ref[:], hb_ref[:], hnb_ref[:][0])
        ok_ref[:] = ok.astype(jnp.int32)[None, :]
        pt_ref[:] = jnp.concatenate([p.x, p.y, p.z, p.t], axis=0)


def ed_points(pk, s, hblocks, hnblocks):
    """pk, s: [32, B]; hblocks [NB, 128, B]; hnblocks [1, B] ->
    (ok [1, B] int32, point [80, B] int32)."""
    nb = hblocks.shape[0]
    b = pk.shape[-1]
    return _call(
        _ed_kernel, b,
        [(32,), (32,), (nb, 128), (1,)],
        [(1,), (80,)],
        (pk, s, hblocks, hnblocks),
        with_base8=True,
    )


def _kes_kernel(depth, base8_ref, vk_ref, per_ref, s_ref,
                leaf_ref, sib_ref, hb_ref, hnb_ref, ok_ref, pt_ref):
    tile = vk_ref.shape[-1]
    with fe.kernel_consts(tile), pc.kernel_base8(base8_ref[:]):
        ok, p = pv.kes_core(
            vk_ref[:], per_ref[:][0], s_ref[:], leaf_ref[:], sib_ref[:],
            hb_ref[:], hnb_ref[:][0], depth,
        )
        ok_ref[:] = ok.astype(jnp.int32)[None, :]
        pt_ref[:] = jnp.concatenate([p.x, p.y, p.z, p.t], axis=0)


def kes_points(vk, period, s, vk_leaf, siblings, hblocks, hnblocks, depth):
    nb = hblocks.shape[0]
    b = vk.shape[-1]
    return _call(
        functools.partial(_kes_kernel, depth), b,
        [(32,), (1,), (32,), (32,), (depth, 32), (nb, 128), (1,)],
        [(1,), (80,)],
        (vk, period, s, vk_leaf, siblings, hblocks, hnblocks),
        with_base8=True,
    )


def _vrf_prep_kernel(pk_ref, g_ref, c_ref, s_ref, al_ref,
                     ok_ref, pts_ref):
    # stage A: decompress + hash-to-curve — field ops only, no base
    # table, roughly half the monolithic vrf module's op count
    tile = pk_ref.shape[-1]
    with fe.kernel_consts(tile):
        ok, h_pt, y_pt, g_pt = pv.vrf_core_prep(
            pk_ref[:], g_ref[:], c_ref[:], s_ref[:], al_ref[:]
        )
        ok_ref[:] = ok.astype(jnp.int32)[None, :]
        pts_ref[:] = jnp.concatenate(
            [jnp.concatenate([p.x, p.y, p.z, p.t], axis=0)
             for p in (h_pt, y_pt, g_pt)],
            axis=0,
        )


def _vrf_ladder_kernel(base8_ref, c_ref, s_ref, prep_ref, pts_ref):
    # stage B: the three ladders over the stage-A points
    tile = c_ref.shape[-1]
    with fe.kernel_consts(tile), pc.kernel_base8(base8_ref[:]):
        flat = prep_ref[:]
        h_pt, y_pt, g_pt = (
            _unstack_point(flat[80 * i: 80 * (i + 1)]) for i in range(3)
        )
        pts = pv.vrf_core_ladders(c_ref[:], s_ref[:], h_pt, y_pt, g_pt)
        pts_ref[:] = jnp.concatenate(
            [jnp.concatenate([p.x, p.y, p.z, p.t], axis=0) for p in pts],
            axis=0,
        )


def vrf_points(pk, gamma, c, s, alpha):
    """Two chained pallas_calls (split compile — module docstring and
    verify.vrf_core_prep rationale); same (ok [1, B], points [400, B])
    contract as the former single kernel."""
    b = pk.shape[-1]
    ok, prep = _call(
        _vrf_prep_kernel, b,
        [(32,), (32,), (16,), (32,), (32,)],
        [(1,), (240,)],
        (pk, gamma, c, s, alpha),
        with_base8=False,
    )
    (pts,) = _call(
        _vrf_ladder_kernel, b,
        [(16,), (32,), (240,)],
        [(400,)],
        (c, s, prep),
        with_base8=True,
    )
    return ok, pts


def _unstack_point(flat):
    return pc.Point(flat[0:20], flat[20:40], flat[40:60], flat[60:80])


def _vrf_bc_prep_kernel(pk_ref, g_ref, u_ref, v_ref, s_ref, al_ref,
                        ok_ref, c_ref, pts_ref):
    # batch-compatible stage A: decompress + hash-to-curve + DERIVED
    # challenge from the announced U, V bytes (verify.vrf_core_bc_prep);
    # one extra inversion (compress H) vs the draft-03 prep, no ladders
    tile = pk_ref.shape[-1]
    with fe.kernel_consts(tile):
        ok, c16, h_pt, y_pt, g_pt = pv.vrf_core_bc_prep(
            pk_ref[:], g_ref[:], u_ref[:], v_ref[:], s_ref[:], al_ref[:]
        )
        ok_ref[:] = ok.astype(jnp.int32)[None, :]
        c_ref[:] = c16
        pts_ref[:] = jnp.concatenate(
            [jnp.concatenate([p.x, p.y, p.z, p.t], axis=0)
             for p in (h_pt, y_pt, g_pt)],
            axis=0,
        )


def vrf_points_bc(pk, gamma, u, v, s, alpha):
    """Batch-compatible vrf stage: prep (derived challenge) chained into
    the UNCHANGED ladder kernel. -> (ok [1, B], c16 [16, B],
    points [400, B]); the derived c16 feeds the unchanged finish stage."""
    b = pk.shape[-1]
    ok, c16, prep = _call(
        _vrf_bc_prep_kernel, b,
        [(32,), (32,), (32,), (32,), (32,), (32,)],
        [(1,), (16,), (240,)],
        (pk, gamma, u, v, s, alpha),
        with_base8=False,
    )
    (pts,) = _call(
        _vrf_ladder_kernel, b,
        [(16,), (32,), (240,)],
        [(400,)],
        (c16, s, prep),
        with_base8=True,
    )
    return ok, c16, pts


def _finish_kernel(edok_ref, edpt_ref, edr_ref, kesok_ref,
                   kespt_ref, kesr_ref, vrfok_ref, vrfpts_ref, c_ref,
                   beta_ref, tlo_ref, thi_ref, out_ref, eta_ref, lv_ref):
    tile = c_ref.shape[-1]
    with fe.kernel_consts(tile):
        vrf_flat = vrfpts_ref[:]
        pts = [_unstack_point(vrf_flat[80 * i : 80 * (i + 1)]) for i in range(5)]
        v = pv.finish_core(
            edok_ref[:][0] != 0, _unstack_point(edpt_ref[:]), edr_ref[:],
            kesok_ref[:][0] != 0, _unstack_point(kespt_ref[:]), kesr_ref[:],
            vrfok_ref[:][0] != 0, pts, c_ref[:],
            beta_ref[:], tlo_ref[:], thi_ref[:],
        )
        out_ref[:] = jnp.stack(
            [
                v.ok_ocert_sig.astype(jnp.int32),
                v.ok_kes_sig.astype(jnp.int32),
                v.ok_vrf.astype(jnp.int32),
                v.ok_leader.astype(jnp.int32),
                v.leader_ambiguous.astype(jnp.int32),
            ],
            axis=0,
        )
        eta_ref[:] = v.eta
        lv_ref[:] = v.leader_value


def finish(ed_ok, ed_pt, ed_r, kes_ok, kes_pt, kes_r, vrf_ok, vrf_pts,
           c, beta_decl, thr_lo, thr_hi):
    b = c.shape[-1]
    return _call(
        _finish_kernel, b,
        [(1,), (80,), (32,), (1,), (80,), (32,), (1,), (400,), (16,),
         (64,), (32,), (32,)],
        [(5,), (32,), (32,)],
        (ed_ok, ed_pt, ed_r, kes_ok, kes_pt, kes_r, vrf_ok, vrf_pts,
         c, beta_decl, thr_lo, thr_hi),
        with_base8=False,
    )


# ---------------------------------------------------------------------------
# Fused driver (one jit = one host dispatch)
# ---------------------------------------------------------------------------


def verify_praos_tiles(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta_decl, thr_lo, thr_hi,
    *, kes_depth: int,
):
    """All inputs limb-first ([*, B], B a multiple of the tile) ->
    (verdicts [5, B] int32, eta [32, B], leader_value [32, B]).

    Verdict rows: ok_ocert_sig, ok_kes_sig, ok_vrf, ok_leader,
    leader_ambiguous — protocol/batch._pk_materialize re-wraps them into
    the Verdicts the sequential epilogue consumes.
    """
    ed_ok, ed_pt = ed_points(ed_pk, ed_s, ed_hblocks, ed_hnblocks)
    kes_ok, kes_pt = kes_points(
        kes_vk, kes_period, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks, kes_depth,
    )
    vrf_ok, vrf_pts = vrf_points(vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha)
    return finish(
        ed_ok, ed_pt, ed_r, kes_ok, kes_pt, kes_r, vrf_ok, vrf_pts,
        vrf_c, beta_decl, thr_lo, thr_hi,
    )


# ---------------------------------------------------------------------------
# Batch-first entry: relayout on DEVICE
# ---------------------------------------------------------------------------


def _bf(a):
    """[B, n] host-staged (any int dtype) -> [n, B] int32, in XLA: the
    transpose+widen costs ~20 us/header on host (pk_arrays) and ~nothing
    fused into the device infeed."""
    return jnp.transpose(jnp.asarray(a).astype(jnp.int32))


def _bf_blocks(w):
    """SHA-512 word blocks [B, NB, 16, 2] uint32 -> [NB, 128, B] int32
    byte blocks (the limb-first hash input layout), in XLA."""
    w = jnp.asarray(w)
    b, nb = w.shape[0], w.shape[1]
    shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
    hi = (w[..., 0:1] >> shifts) & jnp.uint32(0xFF)
    lo = (w[..., 1:2] >> shifts) & jnp.uint32(0xFF)
    by = jnp.concatenate([hi, lo], axis=-1)  # [B, NB, 16, 8]
    return jnp.transpose(
        by.reshape(b, nb, 128), (1, 2, 0)
    ).astype(jnp.int32)


def staged_to_limb_first(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta, thr_lo, thr_hi,
):
    """The in-XLA relayout: host-staged batch-first uint8/uint32 columns
    -> the 21 limb-first int32 arrays verify_praos_tiles consumes."""
    b = beta.shape[0]
    return (
        _bf(ed_pk), _bf(ed_r), _bf(ed_s),
        _bf_blocks(ed_hblocks),
        jnp.asarray(ed_hnblocks).astype(jnp.int32).reshape(1, b),
        _bf(kes_vk),
        jnp.asarray(kes_period).astype(jnp.int32).reshape(1, b),
        _bf(kes_r), _bf(kes_s), _bf(kes_vk_leaf),
        jnp.transpose(
            jnp.asarray(kes_siblings).astype(jnp.int32), (1, 2, 0)
        ),
        _bf_blocks(kes_hblocks),
        jnp.asarray(kes_hnblocks).astype(jnp.int32).reshape(1, b),
        _bf(vrf_pk), _bf(vrf_gamma), _bf(vrf_c), _bf(vrf_s), _bf(vrf_alpha),
        _bf(beta), _bf(thr_lo), _bf(thr_hi),
    )


def staged_to_limb_first_bc(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta, thr_lo, thr_hi,
):
    """Batch-compatible relayout twin: 22 staged columns (u, v announced
    bytes instead of the 16-byte challenge) -> 22 limb-first arrays."""
    b = beta.shape[0]
    return (
        _bf(ed_pk), _bf(ed_r), _bf(ed_s),
        _bf_blocks(ed_hblocks),
        jnp.asarray(ed_hnblocks).astype(jnp.int32).reshape(1, b),
        _bf(kes_vk),
        jnp.asarray(kes_period).astype(jnp.int32).reshape(1, b),
        _bf(kes_r), _bf(kes_s), _bf(kes_vk_leaf),
        jnp.transpose(
            jnp.asarray(kes_siblings).astype(jnp.int32), (1, 2, 0)
        ),
        _bf_blocks(kes_hblocks),
        jnp.asarray(kes_hnblocks).astype(jnp.int32).reshape(1, b),
        _bf(vrf_pk), _bf(vrf_gamma), _bf(vrf_u), _bf(vrf_v), _bf(vrf_s),
        _bf(vrf_alpha),
        _bf(beta), _bf(thr_lo), _bf(thr_hi),
    )


def verify_praos_staged(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta, thr_lo, thr_hi,
    *, kes_depth: int,
):
    """verify_praos_tiles over the HOST-STAGED batch-first layout
    (protocol/batch.stage's uint8/uint32 [B, ...] columns): every
    transpose/widen happens inside the jit so the host dispatch is a
    plain argument pass."""
    args = staged_to_limb_first(
        ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
        kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks,
        vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
        beta, thr_lo, thr_hi,
    )
    return verify_praos_tiles(*args, kes_depth=kes_depth)


# ---------------------------------------------------------------------------
# Split-jit driver: one jit (= one persistent-cache entry = one Mosaic
# compile unit) PER STAGE, chained at the Python level with on-device
# intermediates. Cold-compile hardening (round-3 postmortem): a wedged
# tunnel mid-compile costs ONE stage, the persistent cache accumulates
# per-stage entries across retries, and warm-up can checkpoint between
# stages. Hot-path cost vs the single fused jit: four extra dispatches
# of ~µs each against ~75 ms/stage kernels — noise.
# ---------------------------------------------------------------------------

_SPLIT_JIT: dict = {}
_AOT_WARM: set = set()
# warmup forensics: (stage@bucket) whose first execute is recorded —
# the compile (or persistent-cache load) happens synchronously inside
# that call, so its wall IS the per-stage compile attribution the
# r02-r05 postmortems were missing
_FIRST_EXEC: set = set()


def _note_first_exec(stage: str, wall_s: float, via: str) -> None:
    if stage in _FIRST_EXEC:
        return
    _FIRST_EXEC.add(stage)
    from ...analysis import costmodel
    from ...obs.warmup import WARMUP

    # the costmodel feature hash of the dispatched program (pinned in
    # analysis/costmodel.json — a dict lookup, no tracing) rides the
    # note so fit_costmodel's calibration join is exact
    WARMUP.note_stage(stage, wall_s, via=via,
                      feature_hash=costmodel.stage_feature_hash(stage))


def _begin_first_exec(stage: str) -> None:
    """Breadcrumb BEFORE a stage's first execute: a child killed at the
    wall mid-compile leaves 'X first execute starting' as the LAST note
    in the warmup report — exact attribution of which stage ate it."""
    if stage in _FIRST_EXEC:
        return
    from ...obs.warmup import WARMUP

    WARMUP.note(f"{stage} first execute starting")


def _capture_resources(stage, fn, args, b, kes_depth, via) -> None:
    """Per-stage device resource accounting (obs/resources.py): the AOT
    executable's analyses are free; the jit path pays one re-lower
    (a trace, no XLA compile) — and only while capture is enabled
    (OCT_STAGE_RESOURCES / an installed flight recorder). Callers gate
    this on the stage's FIRST execute and call it AFTER the warmup
    note, so a kill mid-capture can never eat the compile-wall
    forensics (the note is already flushed)."""
    from ...obs import resources as obs_resources

    obs_resources.capture_stage(
        stage, fn, args, lanes=b, depth=kes_depth, via=via
    )


def _jit1(key, fn):
    if key not in _SPLIT_JIT:
        _SPLIT_JIT[key] = jax.jit(fn)
    return _SPLIT_JIT[key]


def _stage_call(name, fn, b, kes_depth, *args):
    """Dispatch one stage: precompiled AOT executable when available
    (OCT_PK_AOT=1 + a matching scripts/aot_cache entry — see ops/pk/aot),
    else the per-stage jit. An AOT call that fails at runtime disables
    that executable and falls back, so AOT can never be worse than the
    round-4 jit path."""
    from ...testing import chaos
    from . import aot

    # chaos seam (device-error@stage:<name> / compile-stall@stage:<name>):
    # a per-stage failure at the exact host point a real per-stage
    # device error surfaces; disarmed it is one module bool test
    chaos.fire("stage-call", stage=name)

    if aot.enabled():
        sig = aot.sig_of(args)
        key = (name, b, kes_depth, TILE, sig)
        ex = aot.load(name, b, kes_depth, TILE, sig)
        if ex is not None:
            try:
                if key not in _AOT_WARM:
                    _begin_first_exec(f"{name}@b{b}")
                t0 = time.monotonic()
                out = ex(*args)
                if key not in _AOT_WARM:
                    # device-side failures surface asynchronously — the
                    # FIRST call per executable blocks so an incompatible
                    # binary falls back here instead of crashing at the
                    # caller's materialization point; subsequent calls
                    # stay async (the dispatch pipeline depends on it)
                    jax.block_until_ready(out)
                    _AOT_WARM.add(key)
                    wall = time.monotonic() - t0
                    first = f"{name}@b{b}" not in _FIRST_EXEC
                    _note_first_exec(f"{name}@b{b}", wall, "aot")
                    if first:
                        _capture_resources(
                            f"{name}@b{b}", ex, args, b, kes_depth, "aot"
                        )
                return out
            except Exception as e:  # noqa: BLE001 — fail-soft by contract
                import sys

                print(f"# pk-aot: run {key} failed, falling back: {e!r}",
                      file=sys.stderr)
                aot.note_failure(e)  # format rejections latch process-wide
                # the executable LOADED but died on device: without this
                # the report shows only "loaded" plus an unexplained jit
                # first-execute — the one aot outcome load() cannot see
                aot._note_aot(name, "run_failed", detail=repr(e))
                aot._LOADED[key] = None
    stage = f"{name}@b{b}"
    first = stage not in _FIRST_EXEC
    _begin_first_exec(stage)
    t0 = time.monotonic()
    ex = None
    if first and aot.writeback_enabled():
        # the write-back path: compile EXPLICITLY (same wall the jit
        # would have paid) so the executable can be re-serialized into
        # the build-pinned store — the next attempt/round on this build
        # loads warm instead of recompiling, which is what heals the
        # store after a format rejection (ops/pk/aot.compile_and_store)
        ex = aot.compile_and_store(name, b, kes_depth, TILE, fn, args)
    out = ex(*args) if ex is not None else fn(*args)
    _note_first_exec(stage, time.monotonic() - t0, "jit")
    if first:
        _capture_resources(stage, ex if ex is not None else fn, args,
                           b, kes_depth, "jit")
        if ex is not None:
            # later dispatches take the (memoized) store branch async
            _AOT_WARM.add((name, b, kes_depth, TILE, aot.sig_of(args)))
    return out


def split_stage_fns(kes_depth: int):
    """The per-stage jitted callables, keyed for cache warm-up:
    [(name, fn), ...] in dependency order. Used by verify_praos_split
    and by the bench/session scripts to warm one stage at a time.
    `relayout_bc`/`vrf_bc` are the batch-compatible-proof twins; ed, kes
    and finish are SHARED between the two formats (same executables)."""
    return [
        ("relayout", _jit1("relayout", staged_to_limb_first)),
        ("relayout_bc", _jit1("relayout_bc", staged_to_limb_first_bc)),
        ("ed", _jit1("ed", ed_points)),
        ("kes", _jit1(("kes", kes_depth),
                      functools.partial(kes_points, depth=kes_depth))),
        ("vrf", _jit1("vrf", vrf_points)),
        ("vrf_bc", _jit1("vrf_bc", vrf_points_bc)),
        ("finish", _jit1("finish", finish)),
    ]


def _mk_packed_unpack(layout):
    """Factory for the packed `unpack` stage: body-sourced packed
    columns -> the SAME 21 limb-first arrays the crypto stages consume
    (protocol/batch.unpack_packed chained into staged_to_limb_first, all
    in one jit) — the 'relayout extended onto the packed wire format'.
    The four crypto stages and their AOT executables are untouched."""

    def unpack_limb(body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
                    thr_idx, thr_tab, nonce):
        from ...protocol import batch as pbatch

        staged = pbatch.unpack_packed(
            layout, body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
            thr_idx, thr_tab, nonce,
        )
        relayout = (
            staged_to_limb_first_bc if len(staged) == 22
            else staged_to_limb_first
        )
        return relayout(*staged)

    return unpack_limb


def _mk_reduce(scan: bool):
    """Factory for the packed `reduce` stage: verdict-bit packing + the
    on-device nonce scan (protocol/batch.verdict_reduce) over the finish
    stage's limb-first outputs."""

    def reduce_fn(flags, eta, within, n_real, ev0, ev0_set, cand0,
                  cand0_set):
        from ...protocol import batch as pbatch

        return pbatch.verdict_reduce(
            flags, jnp.transpose(eta), within, n_real,
            ev0, ev0_set, cand0, cand0_set, scan=scan,
        )

    return reduce_fn


def packed_unpack_name(layout) -> str:
    """AOT stage name for the packed unpack: the layout is BAKED into
    the traced program but invisible to aot.sig_of's shape hash (two
    layouts with equal body length have identical input shapes), so a
    deterministic layout digest goes into the cache-file name."""
    import hashlib

    tag = hashlib.blake2s(repr(tuple(layout)).encode(),
                          digest_size=3).hexdigest()
    return f"unpack_{tag}"


def verify_praos_packed_split(
    layout, body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
    thr_idx, thr_tab, nonce, within, n_real,
    ev0, ev0_set, cand0, cand0_set, *, scan: bool,
):
    """The packed production dispatch: `unpack` (device limb
    decomposition of the packed wire format) -> the UNCHANGED
    ed/kes/vrf/finish stage jits/AOT executables -> `reduce` (verdict
    bitmasks + nonce scan). Returns (reduce outputs, flags, eta,
    leader_value) with the per-lane arrays left on device."""
    kes_depth = layout.kes_depth
    stages = dict(split_stage_fns(kes_depth))
    unpack = _jit1(("unpack", layout), _mk_packed_unpack(layout))
    reduce_ = _jit1(("reduce", scan), _mk_reduce(scan))
    reduce_name = "reduce" if scan else "reduce_noscan"
    b = np.asarray(body).shape[0]
    a = _stage_call(
        packed_unpack_name(layout), unpack, b, kes_depth,
        body, kes_rs, kt_idx, kt_tab, slot, counter, c0,
        thr_idx, thr_tab, nonce,
    )
    if len(a) == 22:  # batch-compatible proof layout (announced U, V)
        (l_ed_pk, l_ed_r, l_ed_s, l_ed_hb, l_ed_hnb,
         l_kes_vk, l_kes_per, l_kes_r, l_kes_s, l_kes_leaf, l_kes_sib,
         l_kes_hb, l_kes_hnb,
         l_vrf_pk, l_vrf_g, l_vrf_u, l_vrf_v, l_vrf_s, l_vrf_al,
         l_beta, l_tlo, l_thi) = a
    else:
        (l_ed_pk, l_ed_r, l_ed_s, l_ed_hb, l_ed_hnb,
         l_kes_vk, l_kes_per, l_kes_r, l_kes_s, l_kes_leaf, l_kes_sib,
         l_kes_hb, l_kes_hnb,
         l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al,
         l_beta, l_tlo, l_thi) = a
    ed_ok, ed_pt = _stage_call(
        "ed", stages["ed"], b, kes_depth, l_ed_pk, l_ed_s, l_ed_hb, l_ed_hnb
    )
    kes_ok, kes_pt = _stage_call(
        "kes", stages["kes"], b, kes_depth,
        l_kes_vk, l_kes_per, l_kes_s, l_kes_leaf, l_kes_sib,
        l_kes_hb, l_kes_hnb,
    )
    if len(a) == 22:
        vrf_ok, l_vrf_c, vrf_pts = _stage_call(
            "vrf_bc", stages["vrf_bc"], b, kes_depth,
            l_vrf_pk, l_vrf_g, l_vrf_u, l_vrf_v, l_vrf_s, l_vrf_al
        )
    else:
        vrf_ok, vrf_pts = _stage_call(
            "vrf", stages["vrf"], b, kes_depth,
            l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al
        )
    flags, eta, lv = _stage_call(
        "finish", stages["finish"], b, kes_depth,
        ed_ok, ed_pt, l_ed_r, kes_ok, kes_pt, l_kes_r, vrf_ok, vrf_pts,
        l_vrf_c, l_beta, l_tlo, l_thi,
    )
    red = _stage_call(
        reduce_name, reduce_, b, kes_depth,
        flags, eta, within, n_real, ev0, ev0_set, cand0, cand0_set,
    )
    return red, flags, eta, lv


def verify_praos_split(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
    beta, thr_lo, thr_hi,
    *, kes_depth: int,
):
    """Same contract as verify_praos_staged, per-stage jits (or AOT
    executables — _stage_call)."""
    stages = dict(split_stage_fns(kes_depth))
    b = np.asarray(beta).shape[0]
    a = _stage_call(
        "relayout", stages["relayout"], b, kes_depth,
        ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
        kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks,
        vrf_pk, vrf_gamma, vrf_c, vrf_s, vrf_alpha,
        beta, thr_lo, thr_hi,
    )
    (l_ed_pk, l_ed_r, l_ed_s, l_ed_hb, l_ed_hnb,
     l_kes_vk, l_kes_per, l_kes_r, l_kes_s, l_kes_leaf, l_kes_sib,
     l_kes_hb, l_kes_hnb,
     l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al,
     l_beta, l_tlo, l_thi) = a
    ed_ok, ed_pt = _stage_call(
        "ed", stages["ed"], b, kes_depth, l_ed_pk, l_ed_s, l_ed_hb, l_ed_hnb
    )
    kes_ok, kes_pt = _stage_call(
        "kes", stages["kes"], b, kes_depth,
        l_kes_vk, l_kes_per, l_kes_s, l_kes_leaf, l_kes_sib,
        l_kes_hb, l_kes_hnb,
    )
    vrf_ok, vrf_pts = _stage_call(
        "vrf", stages["vrf"], b, kes_depth,
        l_vrf_pk, l_vrf_g, l_vrf_c, l_vrf_s, l_vrf_al
    )
    return _stage_call(
        "finish", stages["finish"], b, kes_depth,
        ed_ok, ed_pt, l_ed_r, kes_ok, kes_pt, l_kes_r, vrf_ok, vrf_pts,
        l_vrf_c, l_beta, l_tlo, l_thi,
    )


def verify_praos_split_bc(
    ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
    kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
    kes_hblocks, kes_hnblocks,
    vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
    beta, thr_lo, thr_hi,
    *, kes_depth: int,
):
    """verify_praos_split for BATCH-COMPATIBLE staged columns: the vrf
    stage derives the challenge from the announced U, V; ed/kes/finish
    dispatch the same per-stage jits/AOT executables as draft-03."""
    stages = dict(split_stage_fns(kes_depth))
    b = np.asarray(beta).shape[0]
    a = _stage_call(
        "relayout_bc", stages["relayout_bc"], b, kes_depth,
        ed_pk, ed_r, ed_s, ed_hblocks, ed_hnblocks,
        kes_vk, kes_period, kes_r, kes_s, kes_vk_leaf, kes_siblings,
        kes_hblocks, kes_hnblocks,
        vrf_pk, vrf_gamma, vrf_u, vrf_v, vrf_s, vrf_alpha,
        beta, thr_lo, thr_hi,
    )
    (l_ed_pk, l_ed_r, l_ed_s, l_ed_hb, l_ed_hnb,
     l_kes_vk, l_kes_per, l_kes_r, l_kes_s, l_kes_leaf, l_kes_sib,
     l_kes_hb, l_kes_hnb,
     l_vrf_pk, l_vrf_g, l_vrf_u, l_vrf_v, l_vrf_s, l_vrf_al,
     l_beta, l_tlo, l_thi) = a
    ed_ok, ed_pt = _stage_call(
        "ed", stages["ed"], b, kes_depth, l_ed_pk, l_ed_s, l_ed_hb, l_ed_hnb
    )
    kes_ok, kes_pt = _stage_call(
        "kes", stages["kes"], b, kes_depth,
        l_kes_vk, l_kes_per, l_kes_s, l_kes_leaf, l_kes_sib,
        l_kes_hb, l_kes_hnb,
    )
    vrf_ok, l_vrf_c, vrf_pts = _stage_call(
        "vrf_bc", stages["vrf_bc"], b, kes_depth,
        l_vrf_pk, l_vrf_g, l_vrf_u, l_vrf_v, l_vrf_s, l_vrf_al
    )
    return _stage_call(
        "finish", stages["finish"], b, kes_depth,
        ed_ok, ed_pt, l_ed_r, kes_ok, kes_pt, l_kes_r, vrf_ok, vrf_pts,
        l_vrf_c, l_beta, l_tlo, l_thi,
    )
