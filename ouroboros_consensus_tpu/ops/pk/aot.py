"""Build-pinned AOT artifact store for the stage programs.

Round-10 redesign of the deviceless-AOT cache: artifacts are keyed by
``(build_id, src_digest, stage, tile)`` and live under one directory
PER RUNTIME BUILD (``<aot_dir>/<build-slug>/``) with a provenance
manifest beside them.  The r02–r05 failure family — "cached executable
is axon format vN, this build is v9" costing ~15 s per doomed
deserialize — is structurally impossible against the store: ``load``
consults the manifest's ``build_id`` BEFORE touching the artifact, so a
build change turns every stale entry into a zero-cost ``wrong_build``
skip instead of a rejected deserialize.

Artifacts enter the store two ways:

  * ``scripts/aot_precompile.py`` — the deviceless artifact BUILDER:
    compiles every stage against a TPU ``TopologyDescription`` on the
    build box and saves under the target build id (``OCT_AOT_BUILD_ID``
    — take it from a previous round's banked ``build_id``); its
    ``--check`` flag re-deserializes every manifest entry under the
    current runtime.
  * WRITE-BACK (``OCT_PK_AOT_WRITEBACK=1``, exported by bench.py to its
    device child): when a stage compiles through the jit path, the
    freshly compiled executable is re-serialized into the store for the
    CURRENT build — so after a format rejection the store heals itself
    and the next attempt/round loads warm instead of recompiling.  This
    replaces the old latch-and-skip behavior: a rejection still latches
    the remaining doomed loads of PRE-rejection entries, but the fresh
    re-serializations (saved after the rejection marker) load normally.

The reference ships pre-linked native crypto (libsodium ``.so``s
resolved at node start); the tpu-native analog of "crypto compiled
before the node runs" is PJRT executable serialization.

Everything here is fail-soft: any load/deserialize/run/save error falls
back to the per-stage jit (persistent compilation cache), which is
never worse than round 4's behavior.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time


def _note_aot(stage: str, outcome: str, wall_s: float = 0.0,
              detail: str = "") -> None:
    """Warmup-forensics breadcrumb (obs/warmup.py): every load outcome —
    loaded / missing / wrong_build / failed / rejected / marker_skip /
    run_failed / saved — is attributed per stage, so a bench attempt
    that dies on the wall still shows which cache path ate it.
    Best-effort by contract."""
    try:
        from ...obs.warmup import WARMUP

        WARMUP.note_aot(stage, outcome, wall_s, detail)
    except Exception:
        pass

_DIR_ENV = "OCT_PK_AOT_DIR"
_ENABLE_ENV = "OCT_PK_AOT"  # "0" disables AOT dispatch (default: on —
# a missing/foreign-build store entry is a zero-cost skip, so the
# driver's bench.py run picks the executables up with no env plumbing)
_WRITEBACK_ENV = "OCT_PK_AOT_WRITEBACK"  # "1" = re-serialize freshly
# compiled stage programs into the store for the current build (bench.py
# exports it to the device child; default off so unit tests never write
# executables into the repo)
_BUILD_ENV = "OCT_AOT_BUILD_ID"  # provenance override for the
# deviceless builder: stamp artifacts with the TARGET runtime's
# platform_version (from a previous round's banked build_id) instead of
# the build box's own


def aot_dir() -> str:
    d = os.environ.get(_DIR_ENV, "")
    if d:
        return d
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    return os.path.join(repo, "scripts", "aot_cache")


# Error substrings that mean the RUNTIME rejects an executable format
# wholesale (e.g. "cached executable is axon format vN, this build is
# v9"). With the build-pinned store these should only ever fire on an
# entry whose manifest LIED about its build (platform_version is a
# proxy, not a proof) — one rejection still predicts the same failure
# for every other pre-rejection entry, so it latches the remaining
# loads of those and persists a marker whose mtime separates doomed
# old entries from the write-back re-serializations that heal the store
# (bench.py greps the same patterns in child logs).
INCOMPATIBLE_PATTERNS = (
    "axon format",
    "serialized executable is incompatible",
    "deserialize failed",
)

_RUNTIME_REJECTED = False
_MARKER_CHECKED = False
_MARKER_TIME: float | None = None
_LOAD_LOCK = threading.Lock()
_BUILD_SLUG: str | None = None
_BUILD_ID: str | None = None


def build_id() -> str:
    """The full runtime build string (PJRT platform_version) artifacts
    are pinned to — overridable via $OCT_AOT_BUILD_ID for the
    deviceless builder."""
    global _BUILD_ID
    env = os.environ.get(_BUILD_ENV)
    if env:
        return env
    if _BUILD_ID is None:
        try:
            import jax

            _BUILD_ID = str(jax.devices()[0].client.platform_version)
        except Exception:
            import jax

            _BUILD_ID = f"jax-{jax.__version__}"
    return _BUILD_ID


def _build_slug() -> str:
    """Stable slug of the pinned build id: the store subdirectory name
    (and the keying the bench child uses for its per-build jax cache)."""
    global _BUILD_SLUG
    if os.environ.get(_BUILD_ENV):
        import hashlib

        return hashlib.blake2s(
            build_id().encode(), digest_size=6
        ).hexdigest()
    if _BUILD_SLUG is None:
        import hashlib

        _BUILD_SLUG = hashlib.blake2s(
            build_id().encode(), digest_size=6
        ).hexdigest()
    return _BUILD_SLUG


def store_dir(slug: str | None = None) -> str:
    """The per-build artifact directory."""
    return os.path.join(aot_dir(), slug or _build_slug())


def manifest_path(slug: str | None = None) -> str:
    return os.path.join(store_dir(slug), "MANIFEST.json")


def entry_key(name: str, b: int, kes_depth: int, tile: int,
              sig: str) -> str:
    return f"{name}_b{b}_d{kes_depth}_t{tile}_{sig}"


def read_manifest(slug: str | None = None) -> dict:
    """{entry_key: meta} for one build's store (empty on any problem —
    a corrupt manifest degrades to 'no artifacts', never a crash)."""
    try:
        with open(manifest_path(slug), encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            return {}  # legacy list-format / hand-edited manifest
        entries = doc.get("entries", {})
        return entries if isinstance(entries, dict) else {}
    except (OSError, json.JSONDecodeError, ValueError):
        return {}


_MANIFEST_CACHE: dict[str, dict] = {}


def _cached_manifest(slug: str | None = None) -> dict:
    """Manifest read once per (process, build): load() consults it per
    stage miss, and per-key memoization bounds everything else. Saves
    refresh the cache in place."""
    s = slug or _build_slug()
    if s not in _MANIFEST_CACHE:
        _MANIFEST_CACHE[s] = read_manifest(s)
    return _MANIFEST_CACHE[s]


def _manifest_update(key: str, meta: dict, slug: str | None = None) -> None:
    """Read-modify-write one manifest entry under an exclusive file
    lock + atomic replace: concurrent writers (parallel precompile
    shards, the write-back racing a second replay thread) each land
    their entry without tearing the JSON."""
    import fcntl

    d = store_dir(slug)
    os.makedirs(d, exist_ok=True)
    lock_path = os.path.join(d, "MANIFEST.lock")
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            entries = read_manifest(slug)
            entries[key] = meta
            payload = {
                "comment": "build-pinned AOT artifact store "
                           "(ops/pk/aot.py); entries keyed "
                           "name_b{lanes}_d{depth}_t{tile}_{sig}",
                "entries": entries,
            }
            tmp = manifest_path(slug) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
            os.replace(tmp, manifest_path(slug))
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)
    _MANIFEST_CACHE.setdefault(slug or _build_slug(), {})[key] = meta


def _reject_marker() -> str:
    return os.path.join(aot_dir(), f"REJECTED.{_build_slug()}")


def _check_marker() -> None:
    """Pick up a rejection persisted by an earlier PROCESS on the same
    build. Unlike the pre-round-10 latch this does NOT disable the load
    path outright: entries saved AFTER the marker (the write-back
    re-serializations that heal the store) still load; only entries the
    rejection already condemned are skipped."""
    global _RUNTIME_REJECTED, _MARKER_CHECKED, _MARKER_TIME
    if _MARKER_CHECKED:
        return
    _MARKER_CHECKED = True
    try:
        _MARKER_TIME = os.path.getmtime(_reject_marker())
    except OSError:
        _MARKER_TIME = None


def clear_rejection() -> None:
    """Drop the persisted per-build rejection (a FULL fresh store was
    written for this build — scripts/aot_precompile after an all-fresh
    run)."""
    global _RUNTIME_REJECTED, _MARKER_CHECKED, _MARKER_TIME
    try:
        os.remove(_reject_marker())
    except OSError:
        pass
    _RUNTIME_REJECTED = False
    _MARKER_CHECKED = True
    _MARKER_TIME = None


def note_failure(exc: BaseException) -> bool:
    """Record an AOT load/run failure; latches the in-process skip of
    PRE-rejection entries when the error says the runtime rejects the
    executable FORMAT, and persists a per-build marker whose mtime
    separates condemned entries from later write-back re-serializations
    (which load normally — the store heals instead of staying dark).
    Returns the latch state."""
    global _RUNTIME_REJECTED, _MARKER_TIME
    msg = str(exc).lower()
    if not _RUNTIME_REJECTED and any(p in msg for p in INCOMPATIBLE_PATTERNS):
        import sys

        print(
            "# pk-aot: runtime rejects this executable format — skipping "
            "the remaining pre-rejection store entries (write-back will "
            "re-serialize fresh ones for this build)",
            file=sys.stderr,
        )
        _RUNTIME_REJECTED = True
        try:
            os.makedirs(aot_dir(), exist_ok=True)
            # tmp -> fsync -> rename: the marker's mtime is load-bearing
            # (it separates condemned entries from post-rejection
            # write-backs), so a torn half-written marker after a crash
            # must be impossible
            tmp = _reject_marker() + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(exc)[:500])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, _reject_marker())
            _MARKER_TIME = os.path.getmtime(_reject_marker())
        except Exception:
            _MARKER_TIME = time.time()  # in-process latch still holds
    return _RUNTIME_REJECTED


def enabled() -> bool:
    """The AOT LOAD path lever (env only — a format rejection no longer
    disables the whole path, it only condemns pre-rejection entries;
    see note_failure)."""
    return os.environ.get(_ENABLE_ENV, "1") != "0"


def writeback_enabled() -> bool:
    """Re-serialize freshly compiled stage programs into the store for
    the current build (bench.py exports OCT_PK_AOT_WRITEBACK=1 to its
    device child; default off so unit runs never write executables)."""
    return enabled() and os.environ.get(_WRITEBACK_ENV, "0") == "1"


_SRC_DIGEST: str | None = None


def _src_digest() -> str:
    """Digest of the kernel source modules. Executables are compiled
    CODE: a cache entry keyed on shapes alone would silently run stale
    kernels after an ops/pk change (the persistent jit cache keys on
    the HLO hash and does not have this hazard)."""
    global _SRC_DIGEST
    if _SRC_DIGEST is None:
        import hashlib

        here = os.path.dirname(os.path.abspath(__file__))
        ops = os.path.dirname(here)
        h = hashlib.blake2s(digest_size=4)
        for mod in ("limbs.py", "hashes.py", "curve.py", "verify.py",
                    "kernels.py"):
            with open(os.path.join(here, mod), "rb") as f:
                h.update(f.read())
        # the pk modules build on these: a hash-core or limb-constant
        # edit there with unchanged shapes must also invalidate the
        # serialized executables
        for mod in ("field.py", "curve.py", "sha512.py", "blake2b.py",
                    "u64.py", os.path.join("host", "ed25519.py")):
            with open(os.path.join(ops, mod), "rb") as f:
                h.update(f.read())
        _SRC_DIGEST = h.hexdigest()
    return _SRC_DIGEST


def sig_of(args) -> str:
    """8-hex-char signature of the argument shapes+dtypes plus the
    kernel source digest. Executables are shape-exact, and the KES
    hash-block count varies per batch (it tracks the longest signed
    header bytes in the batch), so the signature — not just
    (batch, depth, tile) — keys the store entry."""
    import hashlib

    parts = [f"{tuple(a.shape)}:{a.dtype}" for a in args]
    parts.append(_src_digest())
    return hashlib.blake2s(
        "|".join(parts).encode(), digest_size=4
    ).hexdigest()


def stage_path(name: str, b: int, kes_depth: int, tile: int, sig: str,
               slug: str | None = None) -> str:
    return os.path.join(
        store_dir(slug), f"{entry_key(name, b, kes_depth, tile, sig)}.jaxexec"
    )


def save(name: str, b: int, kes_depth: int, tile: int, sig: str, compiled,
         meta: dict) -> str:
    """Serialize a jax.stages.Compiled into the store for the pinned
    build (atomic artifact write + locked manifest update). The
    manifest row carries the provenance every later `load` checks
    BEFORE deserializing: build_id, src_digest, saved_at."""
    from ...testing import chaos
    from jax.experimental import serialize_executable as se

    # chaos seam (aot-reject@stage:<name> against the STORE side): the
    # write-back caller's fail-soft contract absorbs it — a failed save
    # costs the artifact, never the replay
    chaos.fire("aot", stage=name)

    ser, in_tree, out_tree = se.serialize(compiled)
    path = stage_path(name, b, kes_depth, tile, sig)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    blob = pickle.dumps(
        {"ser": ser, "in_tree": in_tree, "out_tree": out_tree, "meta": meta}
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    row = dict(meta)
    row.update({
        "stage": name, "b": b, "kes_depth": kes_depth, "tile": tile,
        "sig": sig, "build_id": build_id(), "src_digest": _src_digest(),
        "saved_at": time.time(), "bytes": len(blob),
    })
    _manifest_update(entry_key(name, b, kes_depth, tile, sig), row)
    return path


# negative results included; writes hold _LOAD_LOCK (the bare `key in
# _LOADED` fast-path read is GIL-atomic on a monotonic memo)
_LOADED: dict = {}  # guarded-by: _LOAD_LOCK


def load(name: str, b: int, kes_depth: int, tile: int, sig: str):
    """Deserialize-and-load a store entry onto the live backend.

    Returns a callable with the stage fn's signature, or None. The
    manifest gates every deserialize: no entry -> `missing`; an entry
    pinned to a DIFFERENT build -> `wrong_build` (zero-cost — this is
    what replaces the ~15 s doomed deserializes of r02-r05); an entry
    condemned by an earlier format rejection (saved before the
    REJECTED marker) -> `marker_skip`. Memoized — including negative
    results, so a failing stage is probed once. Deserializes run
    one-at-a-time under a lock with the latch re-checked inside it:
    concurrent callers (the main dispatch thread and the materialize
    worker's aggregate re-dispatch) can never stack a second doomed
    deserialize behind the first one's rejection."""
    key = (name, b, kes_depth, tile, sig)
    # lock-free memo probe BY DESIGN: a hit is immutable once written,
    # the read is GIL-atomic, and taking _LOAD_LOCK here would park a
    # warm caller behind a concurrent multi-second deserialize; misses
    # re-check under the lock below.
    if key in _LOADED:  # octsync: disable=SYNC203
        return _LOADED[key]  # octsync: disable=SYNC203
    if not enabled():
        return None
    from ...testing import chaos

    if chaos.armed():
        try:
            chaos.fire("aot", stage=name)
        except chaos.AotRejectChaos as e:
            # the injected message matches INCOMPATIBLE_PATTERNS, so
            # this is the r04 failure shape end to end — but the
            # process-wide latch/marker stay untouched: chaos faults
            # are transient by contract, a persisted marker would
            # outlive the injection and condemn real entries
            _note_aot(name, "rejected", detail=repr(e))
            with _LOAD_LOCK:
                _LOADED.setdefault(key, None)
            return None
    meta = _cached_manifest().get(entry_key(name, b, kes_depth, tile, sig))
    if meta is None:
        _note_aot(name, "missing")
        with _LOAD_LOCK:
            _LOADED.setdefault(key, None)
        return None
    if meta.get("build_id") != build_id():
        _note_aot(name, "wrong_build",
                  detail=f"artifact build {meta.get('build_id')!r}")
        with _LOAD_LOCK:
            _LOADED.setdefault(key, None)
        return None

    def _condemned() -> bool:
        _check_marker()
        if not (_RUNTIME_REJECTED or _MARKER_TIME is not None):
            return False
        saved_at = float(meta.get("saved_at") or 0.0)
        marker = _MARKER_TIME if _MARKER_TIME is not None else time.time()
        return saved_at <= marker

    if _condemned():
        _note_aot(name, "marker_skip", detail=_reject_marker())
        with _LOAD_LOCK:
            _LOADED.setdefault(key, None)
        return None
    result = None
    path = stage_path(name, b, kes_depth, tile, sig)
    with _LOAD_LOCK:
        if key in _LOADED:
            return _LOADED[key]
        if _condemned():  # a racing load latched while we waited
            _note_aot(name, "marker_skip", detail=_reject_marker())
            _LOADED[key] = None
            return None
        t0 = time.monotonic()
        try:
            from jax.experimental import serialize_executable as se

            with open(path, "rb") as f:
                blob = pickle.load(f)
            result = se.deserialize_and_load(
                blob["ser"], blob["in_tree"], blob["out_tree"]
            )
            _note_aot(name, "loaded", time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001 — fail-soft by contract
            import sys

            print(f"# pk-aot: load {key} failed: {e!r}", file=sys.stderr)
            rejected = note_failure(e)
            _note_aot(
                name, "rejected" if rejected else "failed",
                time.monotonic() - t0, repr(e),
            )
            result = None
        # memoize INSIDE the lock: a racing caller must see the
        # entry the moment the lock frees, not re-deserialize
        _LOADED[key] = result
    return result


def compile_and_store(name: str, b: int, kes_depth: int, tile: int,
                      jitted_fn, args, via: str = "writeback"):
    """The write-back path: explicitly lower+compile a cold stage jit,
    re-serialize the executable into the store for the CURRENT build,
    and memoize it so later dispatches (and, through the store, later
    PROCESSES on this build) go straight to the warm executable. This
    is how an axon-format rejection heals: the fallback compile that
    was always going to happen anyway now leaves a loadable artifact
    behind instead of only a process-local jit cache entry.

    Fail-soft: any trace/lower/compile/serialize problem returns None
    and the caller dispatches the plain jit exactly as before."""
    sig = sig_of(args)
    key = (name, b, kes_depth, tile, sig)
    try:
        if not hasattr(jitted_fn, "trace"):
            import jax

            jitted_fn = jax.jit(jitted_fn)
        compiled = jitted_fn.trace(*args).lower().compile()
    except Exception as e:  # noqa: BLE001 — never worse than the jit path
        import sys

        print(f"# pk-aot: write-back compile for {key} failed, "
              f"using the jit path: {e!r}", file=sys.stderr)
        return None
    t0 = time.monotonic()
    try:
        path = save(name, b, kes_depth, tile, sig, compiled, {"via": via})
        _note_aot(name, "saved", time.monotonic() - t0, path)
    except Exception as e:  # noqa: BLE001 — the compile still serves
        import sys

        print(f"# pk-aot: write-back save for {key} failed: {e!r}",
              file=sys.stderr)
    with _LOAD_LOCK:
        _LOADED[key] = compiled
    return compiled


def store_status() -> dict:
    """One store query replacing the bench child's old BUILD_ID-marker
    heuristics: how many artifacts exist, and how many are loadable by
    THIS runtime (manifest build_id + src_digest both current)."""
    total = matching = stale_src = 0
    try:
        slugs = [e for e in os.listdir(aot_dir())
                 if os.path.isdir(os.path.join(aot_dir(), e))]
    except OSError:
        slugs = []
    for slug in slugs:
        for meta in read_manifest(slug).values():
            total += 1
            if meta.get("build_id") == build_id():
                if meta.get("src_digest") == _src_digest():
                    matching += 1
                else:
                    stale_src += 1
    return {
        "build_id": build_id(), "slug": _build_slug(),
        "entries": total, "matching": matching, "stale_src": stale_src,
    }


def check_store(slug: str | None = None) -> tuple[int, list[str]]:
    """`aot_precompile.py --check`: verify every manifest entry of one
    build's store deserializes under the CURRENT build id. Returns
    (ok_count, problems) — problems name the entry and why (missing
    artifact, build mismatch, failed deserialize)."""
    problems: list[str] = []
    ok = 0
    entries = read_manifest(slug)
    if not entries:
        return 0, [f"no manifest entries under {store_dir(slug)}"]
    for key, meta in sorted(entries.items()):
        path = os.path.join(store_dir(slug), f"{key}.jaxexec")
        if not os.path.exists(path):
            problems.append(f"{key}: manifest entry with no artifact file")
            continue
        if meta.get("build_id") != build_id():
            problems.append(
                f"{key}: pinned to build {meta.get('build_id')!r}, "
                f"runtime is {build_id()!r}"
            )
            continue
        try:
            from jax.experimental import serialize_executable as se

            with open(path, "rb") as f:
                blob = pickle.load(f)
            se.deserialize_and_load(
                blob["ser"], blob["in_tree"], blob["out_tree"]
            )
            ok += 1
        except Exception as e:  # noqa: BLE001 — report, don't crash
            problems.append(f"{key}: deserialize failed: {e!r}")
    return ok, problems
