"""Deviceless AOT executables for the pk stage programs.

`scripts/aot_precompile.py` compiles each per-stage jit (kernels.
split_stage_fns) against a v5e `TopologyDescription` with NO device
attached — libtpu's compile-only client runs on the build box — and
serializes the PJRT executables here.  A live TPU session
(scripts/tpu_session.sh -> bench.py) then deserializes and RUNS instead
of compiling, so a flaky-tunnel window goes straight to measurement
instead of spending its first ~5 minutes in Mosaic.

The reference ships pre-linked native crypto (libsodium `.so`s resolved
at node start, ouroboros-consensus-cardano/../Praos.hs links against
cardano-crypto-praos); the tpu-native analog of "crypto compiled before
the node runs" is PJRT executable serialization
(jax.experimental.serialize_executable).

Everything here is fail-soft: any load/deserialize/run error disables
the AOT path for that stage and the caller falls back to the normal
per-stage jit (persistent compilation cache), which is never worse than
round 4's behavior.
"""

from __future__ import annotations

import os
import pickle
import threading
import time


def _note_aot(stage: str, outcome: str, wall_s: float = 0.0,
              detail: str = "") -> None:
    """Warmup-forensics breadcrumb (obs/warmup.py): every load outcome —
    loaded / missing / failed / rejected / marker_skip — is attributed
    per stage, so a bench attempt that dies on the wall still shows
    which cache path ate it. Best-effort by contract."""
    try:
        from ...obs.warmup import WARMUP

        WARMUP.note_aot(stage, outcome, wall_s, detail)
    except Exception:
        pass

_DIR_ENV = "OCT_PK_AOT_DIR"
_ENABLE_ENV = "OCT_PK_AOT"  # "0" disables AOT dispatch (default: on —
# a missing/incompatible cache entry falls back to the jit path, so the
# driver's bench.py run picks the executables up with no env plumbing)


def aot_dir() -> str:
    d = os.environ.get(_DIR_ENV, "")
    if d:
        return d
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    return os.path.join(repo, "scripts", "aot_cache")


# Error substrings that mean the RUNTIME rejects this cache's executable
# format wholesale (e.g. "cached executable is axon format vN, this build
# is v9"). One such rejection predicts the same ~15 s failure for every
# other entry in the run, so the first one latches a process-wide skip of
# the AOT load path instead of paying six failed deserializes per bucket
# (BENCH_r05.json tail; bench.py greps the same patterns in child logs).
#
# Round-8 postmortem of why the r05 tail STILL showed six doomed loads in
# one attempt despite the latch: (1) `load()` itself never consulted the
# latch and ran concurrently from two threads — the main dispatch thread
# and the materialize worker that re-dispatches per-lane stages for dirty
# aggregate windows — so deserializes already past the caller's
# `enabled()` check burned their ~15 s anyway; (2) the latch was
# per-PROCESS, so bench attempt 2 (a fresh child) re-paid the whole
# cascade. Now: `load()` checks the latch at entry AND under the
# deserialize lock (no two doomed loads can overlap), and a format
# rejection writes a per-build REJECTED marker next to the executables so
# every later process on the same build skips the load path outright
# (scripts/aot_precompile clears the marker when it writes fresh
# executables via `save`).
INCOMPATIBLE_PATTERNS = (
    "axon format",
    "serialized executable is incompatible",
    "deserialize failed",
)

_RUNTIME_REJECTED = False
_MARKER_CHECKED = False
_LOAD_LOCK = threading.Lock()
_BUILD_SLUG: str | None = None


def _build_slug() -> str:
    """Stable slug of the runtime build (PJRT platform_version): the
    same keying the bench child uses for its per-build jax cache."""
    global _BUILD_SLUG
    if _BUILD_SLUG is None:
        import hashlib

        try:
            import jax

            bid = jax.devices()[0].client.platform_version
        except Exception:
            import jax

            bid = f"jax-{jax.__version__}"
        _BUILD_SLUG = hashlib.blake2s(
            str(bid).encode(), digest_size=6
        ).hexdigest()
    return _BUILD_SLUG


def _reject_marker() -> str:
    return os.path.join(aot_dir(), f"REJECTED.{_build_slug()}")


def _check_marker() -> None:
    """Pick up a rejection persisted by an earlier PROCESS on the same
    build (bench attempt 1 -> attempt 2; one driver round -> the next)."""
    global _RUNTIME_REJECTED, _MARKER_CHECKED
    if _MARKER_CHECKED:
        return
    _MARKER_CHECKED = True
    try:
        if os.path.exists(_reject_marker()):
            import sys

            print(
                "# pk-aot: executables previously rejected by this build "
                f"({_reject_marker()}) — skipping the AOT load path",
                file=sys.stderr,
            )
            _RUNTIME_REJECTED = True
            _note_aot("*", "marker_skip", detail=_reject_marker())
    except Exception:
        pass


def clear_rejection() -> None:
    """Drop the persisted per-build rejection (fresh executables were
    written for this build — scripts/aot_precompile via `save`)."""
    global _RUNTIME_REJECTED, _MARKER_CHECKED
    try:
        os.remove(_reject_marker())
    except OSError:
        pass
    _RUNTIME_REJECTED = False
    _MARKER_CHECKED = True


def note_failure(exc: BaseException) -> bool:
    """Record an AOT load/run failure; latches the process-wide disable
    when the error says the runtime rejects the executable FORMAT (a
    per-build property, not a per-entry one) and persists a per-build
    marker so LATER processes skip the doomed loads too. Returns the
    latch state."""
    global _RUNTIME_REJECTED
    msg = str(exc).lower()
    if not _RUNTIME_REJECTED and any(p in msg for p in INCOMPATIBLE_PATTERNS):
        import sys

        print(
            "# pk-aot: runtime rejects this executable format — skipping "
            "all remaining AOT load attempts this run",
            file=sys.stderr,
        )
        _RUNTIME_REJECTED = True
        try:
            os.makedirs(aot_dir(), exist_ok=True)
            with open(_reject_marker(), "w") as f:
                f.write(str(exc)[:500])
        except Exception:
            pass  # persistence is best-effort; the in-process latch holds
    return _RUNTIME_REJECTED


def enabled() -> bool:
    if os.environ.get(_ENABLE_ENV, "1") == "0":
        return False
    if not _RUNTIME_REJECTED:
        _check_marker()
    return not _RUNTIME_REJECTED


_SRC_DIGEST: str | None = None


def _src_digest() -> str:
    """Digest of the kernel source modules. Executables are compiled
    CODE: a cache entry keyed on shapes alone would silently run stale
    kernels after an ops/pk change (the persistent jit cache keys on
    the HLO hash and does not have this hazard)."""
    global _SRC_DIGEST
    if _SRC_DIGEST is None:
        import hashlib

        here = os.path.dirname(os.path.abspath(__file__))
        ops = os.path.dirname(here)
        h = hashlib.blake2s(digest_size=4)
        for mod in ("limbs.py", "hashes.py", "curve.py", "verify.py",
                    "kernels.py"):
            with open(os.path.join(here, mod), "rb") as f:
                h.update(f.read())
        # the pk modules build on these: a hash-core or limb-constant
        # edit there with unchanged shapes must also invalidate the
        # serialized executables
        for mod in ("field.py", "curve.py", "sha512.py", "blake2b.py",
                    "u64.py", os.path.join("host", "ed25519.py")):
            with open(os.path.join(ops, mod), "rb") as f:
                h.update(f.read())
        _SRC_DIGEST = h.hexdigest()
    return _SRC_DIGEST


def sig_of(args) -> str:
    """8-hex-char signature of the argument shapes+dtypes plus the
    kernel source digest. Executables are shape-exact, and the KES
    hash-block count varies per batch (it tracks the longest signed
    header bytes in the batch), so the signature — not just
    (batch, depth, tile) — keys the cache file."""
    import hashlib

    parts = [f"{tuple(a.shape)}:{a.dtype}" for a in args]
    parts.append(_src_digest())
    return hashlib.blake2s(
        "|".join(parts).encode(), digest_size=4
    ).hexdigest()


def stage_path(name: str, b: int, kes_depth: int, tile: int,
               sig: str) -> str:
    return os.path.join(
        aot_dir(), f"{name}_b{b}_d{kes_depth}_t{tile}_{sig}.jaxexec"
    )


def save(name: str, b: int, kes_depth: int, tile: int, sig: str, compiled,
         meta: dict) -> str:
    """Serialize a jax.stages.Compiled to the AOT cache (atomic)."""
    from jax.experimental import serialize_executable as se

    ser, in_tree, out_tree = se.serialize(compiled)
    path = stage_path(name, b, kes_depth, tile, sig)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # NOTE: the persisted REJECTED marker is NOT cleared here — a
    # partially-regenerated cache (crash mid-precompile, subset of
    # stages) would reopen the doomed-load window for the stale files
    # still on disk. scripts/aot_precompile calls clear_rejection()
    # once, AFTER every stage of a run has been written.
    blob = pickle.dumps(
        {"ser": ser, "in_tree": in_tree, "out_tree": out_tree, "meta": meta}
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


_LOADED: dict = {}


def load(name: str, b: int, kes_depth: int, tile: int, sig: str):
    """Deserialize-and-load a stage executable onto the live backend.

    Returns a callable with the stage fn's signature, or None (missing
    file, deserialization failure, incompatible runtime, latched
    rejection). Memoized — including negative results, so a failing
    stage is probed once. Deserializes run one-at-a-time under a lock
    with the latch re-checked inside it: concurrent callers (the main
    dispatch thread and the materialize worker's aggregate re-dispatch)
    can never stack a second ~15 s doomed deserialize behind the first
    one's rejection."""
    key = (name, b, kes_depth, tile, sig)
    if key in _LOADED:
        return _LOADED[key]
    if not enabled():
        return None
    result = None
    path = stage_path(name, b, kes_depth, tile, sig)
    if os.path.exists(path):
        with _LOAD_LOCK:
            if key in _LOADED:
                return _LOADED[key]
            if not enabled():
                return None
            t0 = time.monotonic()
            try:
                from jax.experimental import serialize_executable as se

                with open(path, "rb") as f:
                    blob = pickle.load(f)
                result = se.deserialize_and_load(
                    blob["ser"], blob["in_tree"], blob["out_tree"]
                )
                _note_aot(name, "loaded", time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001 — fail-soft by contract
                import sys

                print(f"# pk-aot: load {key} failed: {e!r}", file=sys.stderr)
                rejected = note_failure(e)
                _note_aot(
                    name, "rejected" if rejected else "failed",
                    time.monotonic() - t0, repr(e),
                )
                result = None
            # memoize INSIDE the lock: a racing caller must see the
            # entry the moment the lock frees, not re-deserialize
            _LOADED[key] = result
        return result
    _note_aot(name, "missing")
    _LOADED[key] = result
    return result
