"""Limb-first GF(2^255-19) field + mod-L scalar arithmetic ([20, T] int32).

The transposed twin of ops/field.py and ops/bigint.py / ops/scalar.py:
identical representation invariants (13-bit limbs in int32, nearly
normalized bound B_MAX), identical reduction identities (2^260 == 608
mod p), but with the limb axis FIRST so that inside Pallas kernels the
limbs occupy sublanes and the batch tile occupies lanes.

The multiply uses the pad-accumulate formulation (measured fastest of
the candidates in scripts/exp_layout3.py): 20 shifted [41, T] terms from
2D broadcasts, no roll, no scatter — both Mosaic and XLA vectorize it
fully.

Reference equivalent: libsodium fe25519 / sc25519 (see ops/field.py,
ops/scalar.py docstrings for the reference call sites).

Bound certification (octrange, analysis/absint.py): the carry headroom
claims in the docstrings below are machine-checked per ROW of the limb
axis — inputs seeded at the B_MAX = 9500 nearly-normalized bound (or
8191 for normalized scalars), every int32 intermediate proven inside
2^31 at the production lane counts (`python -m
ouroboros_consensus_tpu.analysis range`), pinned in
analysis/certified.json. Per-row tracking is what makes `mul` provable
at all: rows 39-40 of the accumulator hold only carry residues, so the
FOLD^2 fold on row 40 is bounded by ~21·FOLD^2, far under the
whole-tensor worst case 9500·FOLD^2 > 2^31. `sum_mod_l`'s per-term
normalization is proven at the 3×87381 = 262,143-lane-term boundary
(just under the 2^31/8191 = 262,177 threshold an un-normalized
accumulator trips) and regression-flagged when reverted
(tests/test_absint.py).
"""

from __future__ import annotations

import numpy as np
from jax import lax
from jax import numpy as jnp

from .. import field as _f

BITS = _f.BITS  # 13
NLIMBS = _f.NLIMBS  # 20
MASK = _f.MASK
FOLD = _f.FOLD  # 19 * 2^5
P_INT = _f.P_INT
D_INT = _f.D_INT
SQRT_M1_INT = _f.SQRT_M1_INT

_SUBC_COL = _f.SUBC.reshape(NLIMBS, 1)  # [20, 1] broadcasts over lanes
_P_COL = _f.P_LIMBS.reshape(NLIMBS, 1)


# ---------------------------------------------------------------------------
# Constants inside kernels
#
# Pallas kernels may not close over array constants (jax requires them
# as inputs), and this Mosaic version cannot even broadcast [n, 1]
# columns over lanes. But every constant here is a compile-time Python
# int vector — so inside a kernel each one is materialized as a stack
# of scalar-immediate fills ([n, T], memoized per trace), which lowers
# to native scalar->vector broadcasts. Outside kernels the accessors
# return plain [n, 1] jnp constants and XLA broadcasting applies.
# ---------------------------------------------------------------------------

# The context dict is read at TRACE time only and every per-trace entry
# is rebuilt on __enter__, so the jit capture octlint flags cannot
# desync; the whole module is the reviewed exception.
# octlint: disable-file=OCT103
_KCTX: dict = {"t": None, "cache": None}


def kernel_consts(t: int):
    """Enter kernel-constants mode for a trace over tile width t."""

    class _Ctx:
        def __enter__(self):
            _KCTX["t"] = int(t)
            _KCTX["cache"] = {}

        def __exit__(self, *exc):
            _KCTX["t"] = None
            _KCTX["cache"] = None

    return _Ctx()


def _named_consts():
    from ..host import ed25519 as _he

    return {
        "subc": _f.SUBC,
        "p": _f.P_LIMBS,
        "one": _f.ONE,
        "d": _f.int_to_limbs_np(D_INT),
        "sqrt_m1": _f.int_to_limbs_np(SQRT_M1_INT),
        "mont_a": _f.int_to_limbs_np(_he.MONT_A % P_INT),
        "sqrt_m486664": _f.int_to_limbs_np(_he.SQRT_M486664 % P_INT),
    }


def _fill_rows(ints, t):
    return jnp.stack(
        [jnp.full((t,), int(v), jnp.int32) for v in ints], axis=0
    )


def _kc(name):
    arr = _NP_CONSTS[name]
    if _KCTX["t"] is None:
        return jnp.asarray(np.asarray(arr, np.int32).reshape(-1, 1))
    cache = _KCTX["cache"]
    if name not in cache:
        cache[name] = _fill_rows(np.asarray(arr).reshape(-1), _KCTX["t"])
    return cache[name]


def constant(x: int):
    """Field constant: [20, 1] outside kernels (XLA broadcasts), full
    [20, T] scalar-immediate fills inside kernels."""
    x = x % P_INT
    if _KCTX["t"] is None:
        return jnp.asarray(_f.int_to_limbs_np(x).reshape(NLIMBS, 1))
    cache = _KCTX["cache"]
    key = ("int", x)
    if key not in cache:
        cache[key] = _fill_rows(_f.int_to_limbs_np(x), _KCTX["t"])
    return cache[key]


def zeros(t: int):
    return jnp.zeros((NLIMBS, t), jnp.int32)


def ones(t: int):
    if _KCTX["t"] is None:
        return jnp.broadcast_to(_kc("one"), (NLIMBS, t))
    return _kc("one")


# ---------------------------------------------------------------------------
# Carries and ring ops
# ---------------------------------------------------------------------------


def _carry_pass(z):
    c = z >> BITS
    wrapped = jnp.concatenate([c[-1:] * FOLD, c[:-1]], axis=0)
    return (z & MASK) + wrapped


def weak_reduce(z, passes: int = 2):
    for _ in range(passes):
        z = _carry_pass(z)
    return z


def add(a, b):
    return _carry_pass(a + b)


def sub(a, b):
    return _carry_pass(a - b + _kc("subc"))


def neg(a):
    return sub(jnp.zeros_like(a), a)


def mul_small(a, k: int):
    return weak_reduce(a * k, passes=3)


def mul(a, b):
    """Field multiplication, [20, T] x [20, T] -> [20, T].

    Same bound analysis as ops/field.mul: coefficients < 20 * B_MAX^2 <
    2^31; carries can reach limb 40, so the accumulator is 41 rows and
    row 40 folds with weight FOLD^2 (= 2^520 mod p).
    """
    t = max(a.shape[-1], b.shape[-1])  # constants may be [20, 1]
    ztail = jnp.zeros((21, t), jnp.int32)
    first = jnp.broadcast_to(a * b[0:1], (NLIMBS, t))
    acc = jnp.concatenate([first, ztail], axis=0)  # [41, T]
    for i in range(1, NLIMBS):
        term = a * b[i : i + 1]
        shifted = jnp.concatenate(
            [jnp.zeros((i, t), jnp.int32), term, ztail[: 21 - i]], axis=0
        )
        acc = acc + shifted
    # two carry passes over 41 rows (carry cannot leave row 40)
    for _ in range(2):
        c = acc >> BITS
        acc = (acc & MASK) + jnp.concatenate(
            [jnp.zeros((1, t), jnp.int32), c[:-1]], axis=0
        )
    lo, hi, top = acc[:NLIMBS], acc[NLIMBS : 2 * NLIMBS], acc[2 * NLIMBS :]
    lo = lo + hi * FOLD
    row0 = lo[:1] + top * (FOLD * FOLD)
    lo = jnp.concatenate([row0, lo[1:]], axis=0)
    return weak_reduce(lo, passes=2)


def sqr(a):
    return mul(a, a)


def pow2k(a, k: int):
    """a^(2^k), k static. Small k unrolls; large k loops in-kernel."""
    if k <= 4:
        for _ in range(k):
            a = sqr(a)
        return a
    return lax.fori_loop(0, k, lambda _, v: sqr(v), a)


def _chain_2_250m1(x):
    t0 = sqr(x)
    t1 = mul(x, pow2k(t0, 2))  # x^9
    x11 = mul(t0, t1)
    t31 = mul(t1, sqr(x11))
    a = mul(pow2k(t31, 5), t31)
    b = mul(pow2k(a, 10), a)
    c = mul(pow2k(b, 20), b)
    d = mul(pow2k(c, 10), a)
    e = mul(pow2k(d, 50), d)
    f = mul(pow2k(e, 100), e)
    g = mul(pow2k(f, 50), d)
    return g, x11


def inv(x):
    g, x11 = _chain_2_250m1(x)
    return mul(pow2k(g, 5), x11)


def pow22523(x):
    g, _ = _chain_2_250m1(x)
    return mul(pow2k(g, 2), x)


def legendre(x):
    g, _ = _chain_2_250m1(x)
    x4 = pow2k(x, 2)
    x6 = mul(x4, sqr(x))
    return mul(pow2k(g, 4), x6)


# ---------------------------------------------------------------------------
# Canonicalization, comparison, selection
# ---------------------------------------------------------------------------


def canonical(x):
    """Unique representative in [0, p): sequential carries + cond-subs,
    exactly mirroring ops/field.canonical."""
    for _ in range(2):
        c = jnp.zeros_like(x[0])
        out = []
        for i in range(NLIMBS):
            v = x[i] + c
            out.append(v & MASK)
            c = v >> BITS
        hi = out[-1] >> 8
        out[-1] = out[-1] & 0xFF
        out[0] = out[0] + c * FOLD + hi * 19
        x = jnp.stack(out, axis=0)
    p = _kc("p")
    for _ in range(2):
        borrow = jnp.zeros_like(x[0])
        diff = []
        for i in range(NLIMBS):
            v = x[i] - p[i] - borrow
            diff.append(v & MASK)
            borrow = jnp.where(v < 0, 1, 0)
        d = jnp.stack(diff, axis=0)
        x = jnp.where((borrow == 0)[None, :], d, x)
    return x


def eq(a, b):
    """Field equality -> bool[T]."""
    return jnp.all(canonical(a) == canonical(b), axis=0)


def is_zero(a):
    return jnp.all(canonical(a) == 0, axis=0)


def select(cond, a, b):
    """cond ? a : b with cond shaped [T]."""
    return jnp.where(cond[None, :], a, b)


def parity(x):
    return canonical(x)[0] & 1


# ---------------------------------------------------------------------------
# Bytes <-> limbs (little-endian 32-byte strings, [32, T] int32)
# ---------------------------------------------------------------------------


def bytes_to_limbs(b, n: int):
    """[nbytes, T] LE bytes -> [n, T] normalized 13-bit limbs."""
    nbytes = b.shape[0]
    b = b.astype(jnp.int32)
    rows = []
    for i in range(n):
        lo_bit = i * BITS
        acc = None
        for byte in range(lo_bit // 8, min((lo_bit + BITS + 7) // 8, nbytes)):
            sh = byte * 8 - lo_bit
            v = b[byte]
            contrib = (v << sh) if sh >= 0 else (v >> (-sh))
            acc = contrib if acc is None else acc + contrib
        if acc is None:
            acc = jnp.zeros_like(b[0])
        rows.append(acc & MASK)
    return jnp.stack(rows, axis=0)


def from_bytes32(b):
    """[32, T] bytes -> nearly-normalized [20, T] limbs (no mod-p check)."""
    return bytes_to_limbs(b, NLIMBS)


def to_bytes(x):
    """Canonical field element -> [32, T] int32 bytes (values 0..255)."""
    x = canonical(x)
    rows = []
    for byte in range(32):
        lo_bit = byte * 8
        limb = lo_bit // BITS
        off = lo_bit - limb * BITS
        acc = x[limb] >> off
        if limb + 1 < NLIMBS and off + 8 > BITS:
            acc = acc | (x[limb + 1] << (BITS - off))
        rows.append(acc & 0xFF)
    return jnp.stack(rows, axis=0)


def geq_limbs(a, b):
    """a >= b for normalized equal-length limb arrays [n, T] -> bool[T]."""
    borrow = jnp.zeros_like(a[0])
    for i in range(a.shape[0]):
        v = a[i] - b[i] - borrow
        borrow = jnp.where(v < 0, 1, 0)
    return borrow == 0


# ---------------------------------------------------------------------------
# Square roots
# ---------------------------------------------------------------------------


def sqrt_ratio_ext(n, d):
    """The Shanks candidate for sqrt(n/d) and its full classification:
    (rho, good, good_alt, is_pi) where d·rho² equals +n (good), -n
    (good_alt: the root is i·rho), +i·n (is_pi) or -i·n. n/d is a QR
    iff good|good_alt; the ±i·n cases identify which non-residue class
    n/d fell in — the single-exponentiation Elligator2 (pk/verify)
    derives its branch-2 root from them. One ~254-squaring chain total."""
    d2 = sqr(d)
    d3 = mul(d, d2)
    d7 = mul(d3, sqr(d2))
    rho = mul(mul(n, d3), pow22523(mul(n, d7)))
    check = mul(d, sqr(rho))
    good = eq(check, n)
    good_alt = eq(check, neg(n))
    is_pi = eq(check, mul(constant(SQRT_M1_INT), n))
    return rho, good, good_alt, is_pi


def sqrt_ratio(n, d):
    """(ok[T], r) with r = sqrt(n/d), even-parity root (ops/field twin)."""
    rho, good, good_alt, _ = sqrt_ratio_ext(n, d)
    r = select(good, rho, mul(rho, constant(SQRT_M1_INT)))
    ok = good | good_alt
    r = select(parity(r) == 1, neg(r), r)
    return ok, r


def sqrt(x):
    return sqrt_ratio(x, ones(x.shape[-1]))


# ---------------------------------------------------------------------------
# Scalar arithmetic mod L (Barrett, limb-first twin of ops/scalar.py)
# ---------------------------------------------------------------------------

L_INT = 2**252 + 27742317777372353535851937790883648493

from .. import bigint as _bi  # noqa: E402  (host-side limb constants)

L20 = _bi.int_to_limbs_np(L_INT, 20).reshape(20, 1)
L21 = _bi.int_to_limbs_np(L_INT, 21).reshape(21, 1)
_A_LIMBS = 19
_B_LIMBS = 21
MU21 = _bi.int_to_limbs_np(
    (1 << (BITS * (_A_LIMBS + _B_LIMBS))) // L_INT, 21
).reshape(21, 1)


def _seq_carry(z):
    """Full sequential carry over rows -> (normalized, carry_out[T])."""
    c = jnp.zeros_like(z[0])
    out = []
    for i in range(z.shape[0]):
        v = z[i] + c
        out.append(v & MASK)
        c = v >> BITS
    return jnp.stack(out, axis=0), c


def _mul_limbs(a, b):
    """[n, T] x [m, T] -> [n+m, T] nearly normalized (min(n,m) <= 32)."""
    n, m = a.shape[0], b.shape[0]
    t = a.shape[-1]
    out_rows = n + m
    acc = jnp.zeros((out_rows, t), jnp.int32)
    for i in range(m):
        term = a * b[i : i + 1]
        # Mosaic rejects zero-size concat operands: only emit non-empty pads
        parts = []
        if i:
            parts.append(jnp.zeros((i, t), jnp.int32))
        parts.append(term)
        if out_rows - n - i:
            parts.append(jnp.zeros((out_rows - n - i, t), jnp.int32))
        shifted = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
        acc = acc + shifted
    for _ in range(2):
        c = acc >> BITS
        acc = (acc & MASK) + jnp.concatenate(
            [jnp.zeros((1, t), jnp.int32), c[:-1]], axis=0
        )
    return acc


def _sub_mod_2k(a, b, n: int):
    borrow = jnp.zeros_like(a[0])
    out = []
    for i in range(n):
        av = a[i] if i < a.shape[0] else jnp.zeros_like(a[0])
        bv = b[i] if i < b.shape[0] else jnp.zeros_like(b[0])
        v = av - bv - borrow
        out.append(v & MASK)
        borrow = jnp.where(v < 0, 1, 0)
    return jnp.stack(out, axis=0)


def _cond_sub(a, bcol):
    n = a.shape[0]
    b = jnp.broadcast_to(jnp.asarray(bcol), a.shape)
    d = _sub_mod_2k(a, b, n)
    return jnp.where(geq_limbs(a, b)[None, :], d, a)


def barrett_reduce40(v):
    """[40, T] normalized limbs (< 2^512) -> [20, T] limbs < L."""
    t = v.shape[-1]
    v1 = v[_A_LIMBS:]  # [21, T]
    mu = jnp.broadcast_to(_kc("mu21"), (21, t))
    prod = _mul_limbs(v1, mu)
    q = prod[_B_LIMBS:][:21]  # [21, T]
    lc = jnp.broadcast_to(_kc("l21"), (21, t))
    ql = _mul_limbs(q, lc)
    ql, _ = _seq_carry(ql)
    r = _sub_mod_2k(v, ql, 21)
    for _ in range(3):
        r = _cond_sub(r, _kc("l21"))
    return r[:20]


def reduce512(digest_bytes):
    """[64, T] LE bytes (SHA-512 output) -> [20, T] limbs < L."""
    return barrett_reduce40(bytes_to_limbs(digest_bytes, 40))


def mul_mod_l(a, b):
    """[20, T] x [20, T] normalized limb scalars (< 2^253) ->
    [20, T] limbs of a·b mod L (the per-lane coefficient products of the
    aggregated verifier, ops/pk/aggregate.py)."""
    prod = _mul_limbs(a, b)  # [40, T] nearly normalized; a·b < 2^506
    prod, _ = _seq_carry(prod)  # carry cannot leave row 39 (< 2^520)
    return barrett_reduce40(prod)


def reduce_raw_sums(v):
    """[20, T] UN-normalized limb rows (each < 2^30, e.g. the raw int32
    scatter-sums of the aggregate verifier's repeated-key coefficient
    tables: ≤ 2^17 lanes x 13-bit rows < 2^30) -> [20, T] limbs < L.
    One carry pass restores 13-bit rows (value < 2^278 fits 22 rows of
    the zero-padded 40), then the shared Barrett step reduces mod L."""
    t = v.shape[-1]
    wide = jnp.concatenate([v, jnp.zeros((40 - NLIMBS, t), jnp.int32)],
                           axis=0)
    wide, _ = _seq_carry(wide)
    return barrett_reduce40(wide)


def sum_mod_l(terms):
    """Sum a list of [20, T] limb scalars (< L each) over BOTH the list
    and the lane axis -> [20, 1] limbs < L. Each term's lane sum stays
    under int32 on its own (13-bit limbs x T ≤ 2^17 lanes < 2^30,
    asserted), but an UN-normalized cross-term accumulator does not
    (3 terms x 87k lanes overflows 2^31) — so every term is
    carry-normalized back to 13-bit rows before the cross-term add,
    bounding accumulator rows by 2^13·len(terms)."""
    acc = None
    for t in terms:
        assert t.shape[-1] <= 1 << 17, "limb-wise lane sum would overflow int32"
        s = jnp.sum(t, axis=-1, keepdims=True)
        wide = jnp.concatenate(
            [s, jnp.zeros((40 - NLIMBS, 1), jnp.int32)], axis=0
        )
        wide, _ = _seq_carry(wide)  # rows < 2^13; total < 2^260 so no
        acc = wide if acc is None else acc + wide  # carry leaves row 39
    acc, _ = _seq_carry(acc)
    return barrett_reduce40(acc)


def is_canonical_scalar(s_bytes):
    """s < L for [32, T] LE byte scalars -> bool[T]."""
    s = bytes_to_limbs(s_bytes, 20)
    lim = jnp.broadcast_to(_kc("l20"), s.shape)
    return ~geq_limbs(s, lim)


# ---------------------------------------------------------------------------
# Digit windows
# ---------------------------------------------------------------------------


def bits_from_bytes(b, nbits: int):
    """[n, T] LE bytes -> [nbits, T] bits."""
    rows = [(b[i // 8] >> (i % 8)) & 1 for i in range(nbits)]
    return jnp.stack(rows, axis=0)


def windows4_from_bytes(b, nbits: int, msb_first: bool = False):
    """[n, T] LE bytes -> [ceil(nbits/4), T] base-16 digits. msb_first
    reverses the window order at build time (Mosaic has no rev/flip)."""
    assert nbits % 4 == 0
    rows = []
    for w in range(nbits // 4):
        lo_bit = 4 * w
        byte = lo_bit // 8
        off = lo_bit % 8
        rows.append((b[byte] >> off) & 0xF)  # off is 0 or 4: no spill
    if msb_first:
        rows.reverse()
    return jnp.stack(rows, axis=0)


def windows8_from_bytes(b, nbits: int):
    """[n, T] LE bytes -> [nbits/8, T] base-256 digits."""
    assert nbits % 8 == 0
    return b[: nbits // 8].astype(jnp.int32)


def windows4_from_limbs(x, nbits: int = 256, msb_first: bool = False):
    """[20, T] normalized limbs -> [nbits/4, T] base-16 digits."""
    assert nbits % 4 == 0
    rows = []
    for w in range(nbits // 4):
        lo_bit = 4 * w
        limb = lo_bit // BITS
        off = lo_bit - limb * BITS
        acc = x[limb] >> off
        if limb + 1 < x.shape[0] and off + 4 > BITS:
            acc = acc | (x[limb + 1] << (BITS - off))
        rows.append(acc & 0xF)
    if msb_first:
        rows.reverse()
    return jnp.stack(rows, axis=0)


def windows8_from_limbs(x, nbits: int = 256):
    """[20, T] normalized limbs -> [nbits/8, T] base-256 digits."""
    assert nbits % 8 == 0
    rows = []
    for w in range(nbits // 8):
        lo_bit = 8 * w
        limb = lo_bit // BITS
        off = lo_bit - limb * BITS
        acc = x[limb] >> off
        if limb + 1 < x.shape[0] and off + 8 > BITS:
            acc = acc | (x[limb + 1] << (BITS - off))
        rows.append(acc & 0xFF)
    return jnp.stack(rows, axis=0)


# ---------------------------------------------------------------------------
# Named-constants table (after all constants above exist)
# ---------------------------------------------------------------------------

_NP_CONSTS = _named_consts()
_NP_CONSTS["l20"] = _bi.int_to_limbs_np(L_INT, 20)
_NP_CONSTS["l21"] = _bi.int_to_limbs_np(L_INT, 21)
_NP_CONSTS["mu21"] = MU21.reshape(-1)


def p_col():
    """The prime p as a per-limb column/tile array (context-aware)."""
    return _kc("p")
