"""Batched CompactSum KES verification on device.

Per lane: one Ed25519 leaf verification (the KES-signed message) plus
`depth` Blake2b-256 Merkle-node recomputations walking bottom-up; at level
i the period's bit i selects H(vk ‖ sib) vs H(sib ‖ vk) — realized as a
masked select, batch-uniform. The reconstructed root must equal the
declared KES verification key.

Reference equivalent: `cardano-crypto-class` `Cardano.Crypto.KES.CompactSum`
verifySignedKES, the header-signature check in the Praos hot path
(ouroboros-consensus-protocol/.../Protocol/Praos.hs:582) and the storage
integrity check (ouroboros-consensus-cardano shelley Ledger/Integrity.hs:14).
Differentially tested against ops/host/kes.py.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np
from jax import numpy as jnp

from . import blake2b, curve, scalar, sha512
from .host import kes as hk


class KesBatch(NamedTuple):
    vk: np.ndarray  # [B, 32] uint8 — declared root vk
    period: np.ndarray  # [B] int32
    r: np.ndarray  # [B, 32] uint8 — leaf Ed25519 sig R
    s: np.ndarray  # [B, 32] uint8 — leaf Ed25519 sig s
    vk_leaf: np.ndarray  # [B, 32] uint8
    siblings: np.ndarray  # [B, depth, 32] uint8, bottom-up
    hblocks: np.ndarray  # [B, NB, 16, 2] — padded SHA-512(R ‖ vk_leaf ‖ msg)
    hnblocks: np.ndarray  # [B] int32


def stage_np(
    vks: Sequence[bytes],
    periods: Sequence[int],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    depth: int = hk.DEFAULT_DEPTH,
    nb: int | None = None,
) -> KesBatch:
    b = len(vks)
    assert len(periods) == len(msgs) == len(sigs) == b
    sig_len = hk.sig_bytes(depth)
    assert all(len(v) == 32 for v in vks)
    assert all(len(sig) == sig_len for sig in sigs)
    # CompactSum signature layout is fixed-width: slice the whole batch
    # column-wise out of ONE buffer (sig = ed_sig(64) ‖ leaf(32) ‖
    # siblings(depth*32) — hk.decompose_sig per lane, vectorized)
    vk = np.frombuffer(b"".join(vks), np.uint8).reshape(b, 32).copy()
    period = np.asarray(periods, np.int32)
    sg = np.frombuffer(b"".join(sigs), np.uint8).reshape(b, sig_len)
    r = np.ascontiguousarray(sg[:, :32])
    s = np.ascontiguousarray(sg[:, 32:64])
    vk_leaf = np.ascontiguousarray(sg[:, 64:96])
    siblings = np.ascontiguousarray(sg[:, 96:].reshape(b, depth, 32))
    hmsgs = [
        sig[:32] + sig[64:96] + m for sig, m in zip(sigs, msgs)
    ]
    hblocks, hnblocks = sha512.pad_messages_np(hmsgs, nb)
    return KesBatch(vk, period, r, s, vk_leaf, siblings, hblocks, hnblocks)


def build_hblocks(r, vk_leaf, body):
    """Device staging of the KES leaf-signature hash input
    R ‖ vk_leaf ‖ body for a batch of FIXED-length bodies — the packed
    H2D contract: the host ships the raw signed header-body column once
    (no padded block columns, no duplicated R ‖ leaf prefix) and the SHA
    padding runs inside the jit. Byte-identical to `stage_np`'s blocks
    on uniform-length bodies."""
    data = jnp.concatenate(
        [r.astype(jnp.uint8), vk_leaf.astype(jnp.uint8),
         body.astype(jnp.uint8)],
        axis=-1,
    )
    return sha512.pad_blocks_fixed(data, 64 + body.shape[-1])


def verify(vk, period, r, s, vk_leaf, siblings, hblocks, hnblocks, *, depth: int | None = None):
    """Device kernel -> ok bool[B]. depth defaults to siblings.shape[-2]."""
    ok_pre, p = verify_point(vk, period, s, vk_leaf, siblings, hblocks, hnblocks, depth=depth)
    enc = curve.compress(p)
    return ok_pre & jnp.all(enc == jnp.asarray(r).astype(jnp.int32), axis=-1)


def verify_point(vk, period, s, vk_leaf, siblings, hblocks, hnblocks, *, depth: int | None = None):
    """(ok_pre bool[B], P Point): Merkle-root + period checks folded into
    ok_pre; P = s·B − h·A of the leaf signature must equal the R bytes
    (compression deferred so the fused kernel shares one inversion)."""
    from . import ed25519_batch

    vk = jnp.asarray(vk).astype(jnp.int32)
    period = jnp.asarray(period)
    vk_leaf = jnp.asarray(vk_leaf).astype(jnp.int32)
    siblings = jnp.asarray(siblings).astype(jnp.int32)
    if depth is None:
        depth = siblings.shape[-2]

    ok_ed, p = ed25519_batch.verify_point(vk_leaf, s, hblocks, hnblocks)
    root_ok = merkle_root_ok(vk, period, vk_leaf, siblings, depth)
    period_ok = (period >= 0) & (period < (1 << depth))
    return ok_ed & root_ok & period_ok, p


def merkle_root_ok(vk, period, vk_leaf, siblings, depth: int):
    """Reconstruct the CompactSum root bottom-up; bit i of the period
    selects H(vk ‖ sib) vs H(sib ‖ vk) — masked select, batch-uniform."""
    cur = vk_leaf
    for i in range(depth):
        sib = siblings[..., i, :]
        bit = (period >> i) & 1
        left = jnp.concatenate([cur, sib], axis=-1)
        right = jnp.concatenate([sib, cur], axis=-1)
        data = jnp.where((bit == 1)[..., None], right, left)
        cur = blake2b.blake2b_fixed(data, 64, 32)
    return jnp.all(cur == vk, axis=-1)


_JIT: dict = {}


def verify_batch(vks, periods, msgs, sigs, depth: int = hk.DEFAULT_DEPTH) -> np.ndarray:
    global _JIT
    if depth not in _JIT:
        import jax

        _JIT[depth] = jax.jit(verify)
    batch = stage_np(vks, periods, msgs, sigs, depth)
    return np.asarray(_JIT[depth](*(jnp.asarray(x) for x in batch)))
