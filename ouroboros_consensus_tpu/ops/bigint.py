"""Generic multi-precision helpers on 13-bit int32 limbs (batched, jnp).

Unlike ops/field.py (which is specialized to GF(2^255-19) with wrap-around
reduction), these helpers operate on plain non-negative integers spread over
an arbitrary number of 13-bit limbs. Used by the mod-L scalar reduction
(ops/scalar.py) and anywhere byte strings become integers on device.
"""

from __future__ import annotations

import numpy as np
from jax import numpy as jnp

BITS = 13
MASK = (1 << BITS) - 1


def nlimbs_for_bits(bits: int) -> int:
    return -(-bits // BITS)


def int_to_limbs_np(x: int, n: int) -> np.ndarray:
    assert x >= 0 and x < 1 << (BITS * n)
    return np.array([(x >> (BITS * i)) & MASK for i in range(n)], dtype=np.int32)


def limbs_to_int_np(limbs) -> int:
    return sum(int(v) << (BITS * i) for i, v in enumerate(np.asarray(limbs)))


def be8_rows(x):
    """[...] int32 non-negative scalars (< 2^31) -> [..., 8] int32 bytes,
    the big-endian 8-byte encoding `int.to_bytes(8, "big")` produces.
    The packed staging contract relies on this matching the host
    encoders byte-for-byte (OCert signable counters/periods, the VRF
    alpha slot prefix)."""
    shifts = jnp.asarray([24, 16, 8, 0], jnp.int32)
    lo = (x[..., None] >> shifts) & 0xFF
    return jnp.concatenate(
        [jnp.zeros((*x.shape, 4), jnp.int32), lo], axis=-1
    )


def bytes_to_limbs(b, n: int):
    """[..., nbytes] little-endian bytes -> [..., n] normalized limbs."""
    b = b.astype(jnp.int32)
    nbytes = b.shape[-1]
    bits = (b[..., :, None] >> jnp.arange(8, dtype=jnp.int32)) & 1
    bits = bits.reshape(*b.shape[:-1], nbytes * 8)
    want = n * BITS
    if want > nbytes * 8:
        pad = jnp.zeros((*b.shape[:-1], want - nbytes * 8), jnp.int32)
        bits = jnp.concatenate([bits, pad], axis=-1)
    else:
        bits = bits[..., :want]
    groups = bits.reshape(*b.shape[:-1], n, BITS)
    return jnp.sum(groups * (1 << jnp.arange(BITS, dtype=jnp.int32)), axis=-1)


def limbs_to_bits(x, nbits: int):
    """[..., n] normalized limbs -> [..., nbits] bits (little-endian)."""
    bits = (x[..., :, None] >> jnp.arange(BITS, dtype=jnp.int32)) & 1
    bits = bits.reshape(*x.shape[:-1], x.shape[-1] * BITS)
    return bits[..., :nbits]


def carry(z, passes: int = 2, keep: int | None = None):
    """Vectorized carry passes; pads one limb to catch the top carry.
    `keep` truncates/zero-pads the result to a fixed limb count."""
    z = jnp.concatenate([z, jnp.zeros_like(z[..., :1])], axis=-1)
    for _ in range(passes):
        c = z >> BITS
        z = (z & MASK) + jnp.concatenate(
            [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
        )
    if keep is not None:
        cur = z.shape[-1]
        if cur > keep:
            z = z[..., :keep]
        elif cur < keep:
            z = jnp.concatenate(
                [z, jnp.zeros((*z.shape[:-1], keep - cur), jnp.int32)], axis=-1
            )
    return z


def seq_carry(z):
    """Full sequential carry; returns (normalized limbs, final carry-out)."""
    c = jnp.zeros_like(z[..., 0])
    out = []
    for i in range(z.shape[-1]):
        v = z[..., i] + c
        out.append(v & MASK)
        c = v >> BITS
    return jnp.stack(out, axis=-1), c


def mul(a, b):
    """Product of normalized limb vectors: [..., n] x [..., m] -> [..., n+m].

    Accumulation bound: min(n, m) * 2^26 must stay below 2^31, i.e.
    min(n, m) <= 32 limbs (416 bits) — ample for scalar reduction.
    """
    n, m = a.shape[-1], b.shape[-1]
    assert min(n, m) <= 32
    ap = jnp.concatenate(
        [a, jnp.zeros((*a.shape[:-1], m), jnp.int32)], axis=-1
    )  # [..., n+m]
    z = jnp.zeros_like(ap)
    for i in range(m):
        z = z + b[..., i : i + 1] * jnp.roll(ap, i, axis=-1)
    return carry(z, passes=2, keep=n + m)


def mul_const_np(a, k_limbs: np.ndarray):
    """Multiply by a host constant (numpy limb vector)."""
    return mul(a, jnp.broadcast_to(jnp.asarray(k_limbs), (*a.shape[:-1], len(k_limbs))))


def shift_right_limbs(a, k: int):
    return a[..., k:]


def sub_mod_2k(a, b, n: int):
    """(a - b) mod 2^(13n), exact when the true difference is in [0, 2^(13n)).
    Sequential borrow over n limbs. Both inputs must be NORMALIZED
    (limbs <= MASK): the borrow logic only covers borrow in {0, 1}.
    Note bi.mul output is only nearly normalized — seq_carry it first."""
    borrow = jnp.zeros_like(a[..., 0])
    out = []
    for i in range(n):
        av = a[..., i] if i < a.shape[-1] else jnp.zeros_like(a[..., 0])
        bv = b[..., i] if i < b.shape[-1] else jnp.zeros_like(b[..., 0])
        v = av - bv - borrow
        out.append(v & MASK)
        borrow = jnp.where(v < 0, 1, 0)
    return jnp.stack(out, axis=-1)


def geq(a, b):
    """a >= b for normalized limb vectors of equal length -> bool[...]."""
    assert a.shape[-1] == b.shape[-1]
    borrow = jnp.zeros_like(a[..., 0])
    for i in range(a.shape[-1]):
        v = a[..., i] - b[..., i] - borrow
        borrow = jnp.where(v < 0, 1, 0)
    return borrow == 0


def cond_sub(a, b):
    """a - b when a >= b else a (same length, normalized)."""
    n = a.shape[-1]
    d = sub_mod_2k(a, b, n)
    return jnp.where(geq(a, b)[..., None], d, a)
