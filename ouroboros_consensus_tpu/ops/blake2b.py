"""Batched Blake2b device kernel (RFC 7693; unkeyed; digest size 1..64).

Host staging pads messages into zero-filled 128-byte blocks
(`pad_messages_np`); the device kernel runs each lane through the batch-max
block count with masked updates, threading the byte counter and final-block
flag per lane.

Reference equivalents: `cardano-crypto-class` Blake2b_256/Blake2b_224 hash
classes (C libsodium), used for KES Merkle nodes (CompactSum), header
hashes (Praos/Header.hs:158), the VRF input `Blake2b-256(slot ‖ nonce)`
(Praos/VRF.hs:47), leader/nonce range extension (VRF.hs:103,116), and pool
key hashes.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from jax import lax
from jax import numpy as jnp

from . import u64
from .sha512 import _H0_INTS  # Blake2b IV == SHA-512 IV

BLOCK = 128

IV = u64.split_np(_H0_INTS)  # [8, 2]

_SIGMA = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
]


def nblocks_for_len(n: int) -> int:
    return max(1, (n + BLOCK - 1) // BLOCK)


def pad_messages_np(msgs: Sequence[bytes], nb: int | None = None):
    """Messages -> (blocks [B, NB, 16, 2] uint32 LE words, nblocks [B],
    total_len [B]). Zero-padding only (Blake2b has no padding bits)."""
    need = max((nblocks_for_len(len(m)) for m in msgs), default=1)
    if nb is None:
        nb = need
    assert nb >= need
    buf = np.zeros((len(msgs), nb * BLOCK), dtype=np.uint8)
    nblocks = np.zeros((len(msgs),), dtype=np.int32)
    total = np.zeros((len(msgs),), dtype=np.int32)
    for i, m in enumerate(msgs):
        buf[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        nblocks[i] = nblocks_for_len(len(m))
        total[i] = len(m)
    return (
        bytes_to_blocks_np(buf.reshape(len(msgs), nb, BLOCK)),
        nblocks,
        total,
    )


def bytes_to_blocks_np(b: np.ndarray) -> np.ndarray:
    """[..., 128] uint8 -> [..., 16, 2] uint32 little-endian words."""
    w = b.reshape(*b.shape[:-1], 16, 8).astype(np.uint32)
    shifts = np.array([0, 8, 16, 24], dtype=np.uint32)
    lo = (w[..., :4] << shifts).sum(axis=-1, dtype=np.uint32)
    hi = (w[..., 4:] << shifts).sum(axis=-1, dtype=np.uint32)
    return np.stack([hi, lo], axis=-1)


def bytes_to_blocks(b):
    """Device variant: [..., 128] int32 bytes -> [..., 16, 2] uint32 LE words."""
    w = b.astype(jnp.uint32).reshape(*b.shape[:-1], 16, 8)
    shifts = jnp.asarray([0, 8, 16, 24], jnp.uint32)
    lo = (w[..., :4] << shifts).sum(axis=-1).astype(jnp.uint32)
    hi = (w[..., 4:] << shifts).sum(axis=-1).astype(jnp.uint32)
    return jnp.stack([hi, lo], axis=-1)


def _g(v, a, b, c, d, x, y):
    v[a] = u64.add_many(v[a], v[b], x)
    v[d] = u64.rotr(u64.xor(v[d], v[a]), 32)
    v[c] = u64.add(v[c], v[d])
    v[b] = u64.rotr(u64.xor(v[b], v[c]), 24)
    v[a] = u64.add_many(v[a], v[b], y)
    v[d] = u64.rotr(u64.xor(v[d], v[a]), 16)
    v[c] = u64.add(v[c], v[d])
    v[b] = u64.rotr(u64.xor(v[b], v[c]), 63)


def compress(state, block, t_bytes, is_final):
    """One Blake2b compression.

    state [..., 8, 2]; block [..., 16, 2] LE words; t_bytes [...] int32
    (bytes hashed including this block, < 2^31); is_final [...] bool.

    The 12 rounds run as a `lax.fori_loop` whose body gathers the
    round's SIGMA message permutation from a table — same rationale as
    sha512.compress: the Python-unrolled form (~1.5k HLO ops) drives
    XLA:CPU into multi-minute LLVM optimization; the rolled body
    compiles in seconds with identical runtime (rounds are sequential).
    """
    iv = jnp.asarray(IV)
    sig = jnp.asarray(np.array(_SIGMA, dtype=np.int32))  # [10, 16]
    mh, ml = block[..., 0], block[..., 1]  # [..., 16]
    batch = state.shape[:-2]
    vh0 = jnp.concatenate(
        [state[..., 0], jnp.broadcast_to(iv[:, 0], (*batch, 8))], axis=-1
    )
    vl0 = jnp.concatenate(
        [state[..., 1], jnp.broadcast_to(iv[:, 1], (*batch, 8))], axis=-1
    )
    # v12 ^= t (counter fits 31 bits: t_hi = 0); v14 inverted on final block
    vl0 = vl0.at[..., 12].set(vl0[..., 12] ^ t_bytes.astype(jnp.uint32))
    fmask = jnp.where(is_final, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    vh0 = vh0.at[..., 14].set(vh0[..., 14] ^ fmask)
    vl0 = vl0.at[..., 14].set(vl0[..., 14] ^ fmask)

    def body(r, carry):
        vh, vl = carry
        s = sig[r % 10]
        smh = jnp.take(mh, s, axis=-1)
        sml = jnp.take(ml, s, axis=-1)
        v = [(vh[..., i], vl[..., i]) for i in range(16)]

        def g(a, b, c, d, i):
            x = (smh[..., 2 * i], sml[..., 2 * i])
            y = (smh[..., 2 * i + 1], sml[..., 2 * i + 1])
            _g(v, a, b, c, d, x, y)

        g(0, 4, 8, 12, 0)
        g(1, 5, 9, 13, 1)
        g(2, 6, 10, 14, 2)
        g(3, 7, 11, 15, 3)
        g(0, 5, 10, 15, 4)
        g(1, 6, 11, 12, 5)
        g(2, 7, 8, 13, 6)
        g(3, 4, 9, 14, 7)
        vh2 = jnp.stack([v[i][0] for i in range(16)], axis=-1)
        vl2 = jnp.stack([v[i][1] for i in range(16)], axis=-1)
        return vh2, vl2

    vh, vl = lax.fori_loop(0, 12, body, (vh0, vl0))
    oh = state[..., 0] ^ vh[..., :8] ^ vh[..., 8:]
    ol = state[..., 1] ^ vl[..., :8] ^ vl[..., 8:]
    return jnp.stack([oh, ol], axis=-1)


def init_state(batch_shape, digest_size: int):
    h = np.array(IV, dtype=np.uint32).copy()
    h[0, 1] ^= np.uint32(0x01010000 ^ digest_size)
    return jnp.broadcast_to(jnp.asarray(h), (*batch_shape, 8, 2))


def blake2b_blocks(blocks, nblocks, total_len, digest_size: int = 32):
    """Batched Blake2b over zero-padded blocks -> [..., digest_size] bytes.

    blocks [..., NB, 16, 2]; nblocks, total_len [...] int32.
    """
    nb = blocks.shape[-3]
    batch = blocks.shape[:-3]
    nblocks = jnp.asarray(nblocks)
    total_len = jnp.asarray(total_len)
    state = init_state(batch, digest_size)

    def step(st, i, blk):
        is_final = i == nblocks - 1
        t = jnp.where(is_final, total_len, (i + 1) * BLOCK)
        nxt = compress(st, blk, t, is_final)
        return jnp.where((i < nblocks)[..., None, None], nxt, st)

    if nb == 1:
        state = step(state, jnp.int32(0), blocks[..., 0, :, :])
    else:
        def body(i, st):
            blk = lax.dynamic_index_in_dim(blocks, i, axis=len(batch), keepdims=False)
            return step(st, i, blk)

        state = lax.fori_loop(0, nb, body, state)
    nwords = (digest_size + 7) // 8
    outs = [u64.to_bytes_le((state[..., i, 0], state[..., i, 1])) for i in range(nwords)]
    return jnp.concatenate(outs, axis=-1)[..., :digest_size]


_ENV_DEVICE_HASH = "OCT_SIDECAR_DEVICE_HASH"
_hash_spans_jit = None


def _device_hash_enabled() -> bool:
    """``OCT_SIDECAR_DEVICE_HASH`` (default 0): route the sidecar hot
    path's body-hash batch through the device Blake2b kernel instead
    of hashlib. Off by default — the host loop is exact and the device
    batch only pays off once the span batch is large and a device is
    attached; read per call so tests A/B both paths."""
    import os

    return os.environ.get(_ENV_DEVICE_HASH, "0") == "1"


def hash_spans(data, starts, ends, digest_size: int = 32) -> np.ndarray:
    """Blake2b over ``data[starts[i]:ends[i])`` for every i →
    [n, digest_size] uint8 digests — the columnar-sidecar hot path's
    per-header body-hash compare (storage/sidecar.integrity_batch_hook)
    with ZERO header parsing: the spans come straight from the
    sidecar's ``header_end`` column and the index entries. One native
    batch call when the host-crypto library is available (the hot
    path), hashlib loop otherwise; `_device_hash_enabled` routes the
    whole batch through `blake2b_blocks` with bucket-padded shapes."""
    import hashlib

    n = len(starts)
    out = np.empty((n, digest_size), np.uint8)
    if n == 0:
        return out
    mv = memoryview(data)
    if _device_hash_enabled():
        msgs = [bytes(mv[int(s):int(e)]) for s, e in zip(starts, ends)]
        return _hash_spans_device(msgs, digest_size)
    from .. import native_loader

    native = native_loader.native_blake2b_spans(data, starts, ends, digest_size)
    if native is not None:
        return native
    for i in range(n):
        out[i] = np.frombuffer(
            hashlib.blake2b(
                mv[int(starts[i]):int(ends[i])], digest_size=digest_size
            ).digest(),
            np.uint8,
        )
    return out


def _hash_spans_device(msgs, digest_size: int) -> np.ndarray:
    """Bucket-padded device batch: nblocks rounds up to a power of two
    and the batch to a multiple of 256 (zero-length pad lanes, outputs
    dropped), so repeated chunks reuse ONE compiled executable per
    bucket instead of re-tracing per chunk shape."""
    global _hash_spans_jit
    import jax

    if _hash_spans_jit is None:
        _hash_spans_jit = jax.jit(
            blake2b_blocks, static_argnames=("digest_size",)
        )
    need = max(nblocks_for_len(len(m)) for m in msgs)
    nb = 1 << max(0, need - 1).bit_length()
    blocks, nblocks, total = pad_messages_np(msgs, nb=nb)
    n = len(msgs)
    b = max(256, ((n + 255) // 256) * 256)
    if b != n:
        pad = b - n
        blocks = np.concatenate(
            [blocks, np.zeros((pad, *blocks.shape[1:]), blocks.dtype)]
        )
        nblocks = np.concatenate([nblocks, np.ones(pad, np.int32)])
        total = np.concatenate([total, np.zeros(pad, np.int32)])
    dig = np.asarray(
        _hash_spans_jit(blocks, nblocks, total, digest_size=digest_size)
    )
    return dig[:n].astype(np.uint8)


def nonce_fold_scan(etas, within, is_real, ev0, ev0_set, cand0, cand0_set):
    """Device-side Praos nonce fold: `jax.lax.scan` of the evolving /
    candidate nonce bookkeeping over a window's per-lane eta values,
    mirroring protocol/nonces.combine + protocol/praos.reupdate exactly.

    The combine is a NON-associative hash fold (eta' = Blake2b-256(eta ‖
    v), neutral = identity), so the scan is inherently sequential — but
    running it on device means `materialize_verdicts` transfers ONE
    32-byte nonce pair per window instead of the full [B, 32] eta column
    (protocol/batch.py D2H contract; the host epilogue keeps the exact
    per-lane fold as the slow path).

      etas     [B, 32] int32 bytes — vrfNonceValue per lane
      within   [B] bool — slot within the stability window (candidate
               freezing, Praos.hs:497)
      is_real  [B] bool — lane < the window's true size (bucket-pad
               lanes must not fold)
      ev0, cand0 [32] int32; ev0_set, cand0_set [] bool — the carry-in
               (set=False encodes the neutral nonce)

    Returns the carry-out (ev, ev_set, cand, cand_set) after folding
    every real lane in order.
    """

    def step(carry, x):
        ev, evs, cand, cands = carry
        eta_i, w_i, r_i = x
        h = blake2b_fixed(jnp.concatenate([ev, eta_i], axis=-1), 64, 32)
        new_ev = jnp.where(evs, h, eta_i)  # combine(neutral, v) = v
        ev2 = jnp.where(r_i, new_ev, ev)
        evs2 = evs | r_i
        upd = r_i & w_i
        cand2 = jnp.where(upd, ev2, cand)
        cands2 = cands | upd
        return (ev2, evs2, cand2, cands2), ()

    carry, _ = lax.scan(
        step, (ev0, ev0_set, cand0, cand0_set), (etas, within, is_real)
    )
    return carry


def blake2b_fixed(data_bytes, data_len: int, digest_size: int = 32):
    """Single-block fast path: [..., n] int32 bytes with a STATIC common
    length data_len <= 128 (the KES Merkle-node / nonce-evolution shape).
    """
    assert 0 < data_len <= BLOCK
    batch = data_bytes.shape[:-1]
    pad = BLOCK - data_bytes.shape[-1]
    if pad:
        data_bytes = jnp.concatenate(
            [data_bytes, jnp.zeros((*batch, pad), jnp.int32)], axis=-1
        )
    blk = bytes_to_blocks(data_bytes)
    state = init_state(batch, digest_size)
    t = jnp.broadcast_to(jnp.int32(data_len), batch)
    fin = jnp.broadcast_to(jnp.bool_(True), batch)
    state = compress(state, blk, t, fin)
    nwords = (digest_size + 7) // 8
    outs = [u64.to_bytes_le((state[..., i, 0], state[..., i, 1])) for i in range(nwords)]
    return jnp.concatenate(outs, axis=-1)[..., :digest_size]
