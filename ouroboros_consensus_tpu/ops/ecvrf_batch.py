"""Batched ECVRF-ED25519-SHA512-Elligator2 (draft-03) verification on device.

Per lane: decode pk (Y) and proof (Gamma, c, s); Elligator2 hash-to-curve
of (pk, alpha) entirely on device (SHA-512 + field ops); compute
U = s·B − c·Y and V = s·H − c·Γ; recompute the 16-byte challenge from the
compressed (H, Γ, U, V) — a single shared inversion chain via Montgomery's
trick — and compare with c. Also emits beta = SHA-512(suite ‖ 0x03 ‖
encode(8·Γ)), the VRF output the Praos leader check consumes.

alpha is fixed-width (32 bytes): Praos always evaluates the VRF on
InputVRF = Blake2b-256(slot ‖ epoch-nonce) (reference: Praos/VRF.hs:47).

Reference equivalent: the vendored libsodium `ietfdraft03` ECVRF verifier
in `cardano-crypto-praos`, called from
ouroboros-consensus-protocol/.../Protocol/Praos.hs:543 (verifyCertified).
Differentially tested against ops/host/ecvrf.py.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np
from jax import numpy as jnp

from . import curve, field as fe, scalar, sha512
from .host import ed25519 as he

SUITE = 0x04


class EcvrfBatch(NamedTuple):
    pk: np.ndarray  # [B, 32] uint8
    gamma: np.ndarray  # [B, 32] uint8
    c: np.ndarray  # [B, 16] uint8
    s: np.ndarray  # [B, 32] uint8
    alpha: np.ndarray  # [B, 32] uint8


class EcvrfBcBatch(NamedTuple):
    """Batch-compatible (128-byte) proof staging: the proof announces
    U, V instead of the challenge; c is derived ON DEVICE from the
    announced bytes (derive_c_bc)."""

    pk: np.ndarray  # [B, 32] uint8
    gamma: np.ndarray  # [B, 32] uint8
    u: np.ndarray  # [B, 32] uint8 — announced U = k·B
    v: np.ndarray  # [B, 32] uint8 — announced V = k·H
    s: np.ndarray  # [B, 32] uint8
    alpha: np.ndarray  # [B, 32] uint8


def stage_np(
    pks: Sequence[bytes], proofs: Sequence[bytes], alphas: Sequence[bytes]
) -> EcvrfBatch | EcvrfBcBatch:
    """Stage a proof column; the format (80 = draft-03 -> EcvrfBatch,
    128 = batch-compatible -> EcvrfBcBatch) is read off the proof length
    and must be uniform across the batch."""
    b = len(pks)
    assert len(proofs) == b and len(alphas) == b
    assert all(len(p) == 32 for p in pks)
    assert all(len(al) == 32 for al in alphas)
    plen = len(proofs[0]) if proofs else 80
    assert plen in (80, 128)
    assert all(len(pi) == plen for pi in proofs)
    pk = np.frombuffer(b"".join(pks), np.uint8).reshape(b, 32).copy()
    pr = np.frombuffer(b"".join(proofs), np.uint8).reshape(b, plen)
    alpha = np.frombuffer(b"".join(alphas), np.uint8).reshape(b, 32).copy()
    gamma = np.ascontiguousarray(pr[:, :32])
    if plen == 128:
        return EcvrfBcBatch(
            pk, gamma,
            np.ascontiguousarray(pr[:, 32:64]),
            np.ascontiguousarray(pr[:, 64:96]),
            np.ascontiguousarray(pr[:, 96:128]),
            alpha,
        )
    c = np.ascontiguousarray(pr[:, 32:48])
    s = np.ascontiguousarray(pr[:, 48:80])
    return EcvrfBatch(pk, gamma, c, s, alpha)


def alpha_from_slots(slot, epoch_nonce):
    """Device mkInputVRF (Praos/VRF.hs:55-69): Blake2b-256(slot_be8 ‖
    nonce-bytes), the neutral nonce contributing NO bytes.

    slot: [B] int32 (values < 2^31 — the packed staging gates this);
    epoch_nonce: [32] byte array, or None for the neutral nonce.
    Byte-identical to protocol/nonces.mk_input_vrf, so the packed path
    stages 4 bytes of slot instead of the 32-byte alpha column (and
    skips one host Blake2b per header)."""
    from . import bigint as bi
    from . import blake2b

    b = slot.shape[0]
    slot_be8 = bi.be8_rows(slot)  # slot < 2^31
    if epoch_nonce is None:
        data, n = slot_be8, 8
    else:
        nonce_rows = jnp.broadcast_to(
            jnp.asarray(epoch_nonce).astype(jnp.int32), (b, 32)
        )
        data, n = jnp.concatenate([slot_be8, nonce_rows], axis=-1), 40
    return blake2b.blake2b_fixed(data, n, 32)


def elligator2(r):
    """Field element [..., 20] -> Edwards Point. Deterministic map matching
    ops/host/ecvrf.elligator2 exactly (even-x sign convention)."""
    one = fe.ones(r.shape[:-1])
    mont_a = fe.constant(he.MONT_A)
    denom = fe.add(fe.mul_small(fe.sqr(r), 2), one)
    denom = fe.select(fe.is_zero(denom), one, denom)
    u1 = fe.mul(fe.neg(mont_a), fe.inv(denom))  # -A / (1 + 2r^2)
    w1 = fe.mul(u1, fe.add(fe.mul(fe.add(u1, mont_a), u1), one))  # u(u^2+Au+1)
    # legendre in {0, 1, p-1}; square (or zero) keeps u1
    is_sq = fe.eq(fe.legendre(w1), one) | fe.is_zero(w1)
    u2 = fe.sub(fe.neg(u1), mont_a)
    u = fe.select(is_sq, u1, u2)
    w = fe.mul(u, fe.add(fe.mul(fe.add(u, mont_a), u), one))
    _, v = fe.sqrt(w)  # even root; w is square by construction
    # x = sqrt(-486664) * u / v  (x = 0 when v = 0: fe.inv(0) = 0)
    x = fe.mul(fe.mul(fe.constant(he.SQRT_M486664), u), fe.inv(v))
    # y = (u-1)/(u+1)  (y = 0 when u = -1)
    y = fe.mul(fe.sub(u, one), fe.inv(fe.add(u, one)))
    x = fe.select(fe.parity(x) == 1, fe.neg(x), x)
    return curve.Point(x, y, one, fe.mul(x, y))


def hash_to_curve(pk_bytes, alpha_bytes):
    """H = 8 * Elligator2(SHA512(suite ‖ 0x01 ‖ pk ‖ alpha) mod 2^255 mod p)."""
    batch = pk_bytes.shape[:-1]
    prefix = jnp.broadcast_to(jnp.asarray([SUITE, 0x01], jnp.int32), (*batch, 2))
    data = jnp.concatenate([prefix, pk_bytes, alpha_bytes], axis=-1)  # 66 bytes
    digest = sha512.sha512_fixed(data)
    r32 = digest[..., :32].at[..., 31].set(digest[..., 31] & 0x7F)
    r = fe.canonical(fe.from_bytes(r32))
    return curve.mul_cofactor(elligator2(r))


def verify_points(pk, gamma, c, s, alpha):
    """(ok_pre bool[B], points) with points = (H, Γ, U, V, 8Γ) left
    uncompressed: U = s·B − c·Y (wide fixed-base table + 128-bit c
    ladder), V = s·H − c·Γ via ONE shared-doubling Strauss ladder
    (curve.double_scalar_mul_w4). The challenge/beta hashes over the
    compressed encodings are completed by `finish`, so the fused Praos
    kernel can share a single Montgomery inversion chain across every
    point it compresses per lane."""
    pk = jnp.asarray(pk).astype(jnp.int32)
    gamma = jnp.asarray(gamma).astype(jnp.int32)
    c = jnp.asarray(c).astype(jnp.int32)
    s = jnp.asarray(s).astype(jnp.int32)
    alpha = jnp.asarray(alpha).astype(jnp.int32)

    ok_y, y_pt = curve.decompress(pk)
    ok_g, g_pt = curve.decompress(gamma)
    s_ok = scalar.is_canonical32(s)

    h_pt = hash_to_curve(pk, alpha)

    s_digits = scalar.windows4_from_bits(scalar.bits_from_bytes(s, 256))
    c_digits = scalar.windows4_from_bits(scalar.bits_from_bytes(c, 128))

    sb = curve.base_mul_w8(
        scalar.windows8_from_bits(scalar.bits_from_bytes(s, 256))
    )
    u_pt = curve.add(sb, curve.scalar_mul_w4(c_digits, curve.neg(y_pt)))
    v_pt = curve.double_scalar_mul_w4(
        s_digits, h_pt, c_digits, curve.neg(g_pt)
    )
    g8 = curve.mul_cofactor(g_pt)
    return ok_y & ok_g & s_ok, (h_pt, g_pt, u_pt, v_pt, g8)


def finish(ok_pre, c, encs):
    """Complete verification from the 5 compressed encodings (H, Γ, U,
    V, 8Γ) -> (ok, beta)."""
    c = jnp.asarray(c).astype(jnp.int32)
    h_enc, gamma_enc, u_enc, v_enc, g8_enc = encs
    batch = c.shape[:-1]
    p2 = jnp.broadcast_to(jnp.asarray([SUITE, 0x02], jnp.int32), (*batch, 2))
    cdata = jnp.concatenate([p2, h_enc, gamma_enc, u_enc, v_enc], axis=-1)  # 130 B
    c_prime = sha512.sha512_fixed(cdata)[..., :16]

    p3 = jnp.broadcast_to(jnp.asarray([SUITE, 0x03], jnp.int32), (*batch, 2))
    beta = sha512.sha512_fixed(jnp.concatenate([p3, g8_enc], axis=-1))

    ok = ok_pre & jnp.all(c_prime == c, axis=-1)
    return ok, beta


def verify(pk, gamma, c, s, alpha):
    """Device kernel -> (ok bool[B], beta [B, 64] int32 bytes)."""
    ok_pre, points = verify_points(pk, gamma, c, s, alpha)
    encs = curve.compress_many(list(points))
    return finish(ok_pre, c, encs)


# ---------------------------------------------------------------------------
# Batch-compatible (128-byte) proofs: announced U, V; challenge derived
# ---------------------------------------------------------------------------


def derive_c_bc(pk, gamma, u, v, s, alpha):
    """Stage A of the batch-compatible check: decode/validate + hash-to-
    curve + the challenge c = SHA-512(suite ‖ 2 ‖ enc(H) ‖ Γ ‖ U ‖ V)[:16]
    over the ANNOUNCED proof bytes. Returns (ok_pre, c16 int32, H, Y, Γ).

    The announced U, V enter per-lane verification only as bytes: the
    ladders recompute U' = s·B − c·Y and V' = s·H − c·Γ and the finish
    compares H(... enc(U') enc(V')) against this c — equal iff the
    canonical encodings match the announced bytes, so a non-canonical or
    off-curve U/V can never verify (same compare-on-bytes argument as
    ed25519_batch.verify_point)."""
    pk = jnp.asarray(pk).astype(jnp.int32)
    gamma = jnp.asarray(gamma).astype(jnp.int32)
    u = jnp.asarray(u).astype(jnp.int32)
    v = jnp.asarray(v).astype(jnp.int32)
    s = jnp.asarray(s).astype(jnp.int32)
    alpha = jnp.asarray(alpha).astype(jnp.int32)

    ok_y, y_pt = curve.decompress(pk)
    ok_g, g_pt = curve.decompress(gamma)
    s_ok = scalar.is_canonical32(s)
    h_pt = hash_to_curve(pk, alpha)
    h_enc = curve.compress(h_pt)
    batch = pk.shape[:-1]
    p2 = jnp.broadcast_to(jnp.asarray([SUITE, 0x02], jnp.int32), (*batch, 2))
    cdata = jnp.concatenate([p2, h_enc, gamma, u, v], axis=-1)  # 130 B
    c16 = sha512.sha512_fixed(cdata)[..., :16]
    return ok_y & ok_g & s_ok, c16, h_pt, y_pt, g_pt


def verify_points_bc(pk, gamma, u, v, s, alpha):
    """(ok_pre, c16, points) with points = (H, Γ, U', V', 8Γ): the same
    ladder shapes as `verify_points`, driven by the DERIVED challenge."""
    ok_pre, c16, h_pt, y_pt, g_pt = derive_c_bc(pk, gamma, u, v, s, alpha)
    s = jnp.asarray(s).astype(jnp.int32)
    s_digits = scalar.windows4_from_bits(scalar.bits_from_bytes(s, 256))
    c_digits = scalar.windows4_from_bits(scalar.bits_from_bytes(c16, 128))
    sb = curve.base_mul_w8(
        scalar.windows8_from_bits(scalar.bits_from_bytes(s, 256))
    )
    u_pt = curve.add(sb, curve.scalar_mul_w4(c_digits, curve.neg(y_pt)))
    v_pt = curve.double_scalar_mul_w4(
        s_digits, h_pt, c_digits, curve.neg(g_pt)
    )
    g8 = curve.mul_cofactor(g_pt)
    return ok_pre, c16, (h_pt, g_pt, u_pt, v_pt, g8)


def verify_bc(pk, gamma, u, v, s, alpha):
    """Device kernel -> (ok bool[B], beta): per-lane batch-compatible
    verify (the aggregate path's fallback semantics, ops/pk/aggregate)."""
    ok_pre, c16, points = verify_points_bc(pk, gamma, u, v, s, alpha)
    encs = curve.compress_many(list(points))
    return finish(ok_pre, c16, encs)


# ---------------------------------------------------------------------------
# Prove side (forging: checkIsLeader VRF evaluation, Praos.hs:375-397)
# ---------------------------------------------------------------------------


def prove(x, prefix, pk, alpha):
    """Device kernel -> (gamma_enc, c16, u_enc, v_enc, s32, beta) int32
    byte arrays — BOTH serializations of the transcript, so one program
    serves draft-03 (gamma ‖ c ‖ s) and batch-compatible
    (gamma ‖ u ‖ v ‖ s) staging.

    H = h2c(pk, alpha), Γ = x·H, k = SHA512(prefix ‖ H) mod L,
    c = hash_points(H, Γ, k·B, k·H), s = k + c·x mod L;
    beta = SHA512(suite ‖ 3 ‖ 8Γ) emitted for the leader check.
    Mirrors ops/host/ecvrf._prove_parts."""
    from . import bigint as bi

    x = jnp.asarray(x).astype(jnp.int32)
    prefix = jnp.asarray(prefix).astype(jnp.int32)
    pk = jnp.asarray(pk).astype(jnp.int32)
    alpha = jnp.asarray(alpha).astype(jnp.int32)

    h_pt = hash_to_curve(pk, alpha)
    h_enc = curve.compress(h_pt)

    x_limbs = bi.bytes_to_limbs(x, 20)
    x_digits = scalar.windows4_from_bits(scalar.bits_from_bytes(x, 256))
    gamma = curve.scalar_mul_w4(x_digits, h_pt)

    k = scalar.reduce512(
        sha512.sha512_fixed(jnp.concatenate([prefix, h_enc], axis=-1))
    )
    kb = curve.base_mul_w8(
        scalar.windows8_from_bits(scalar.bits_from_limbs(k, 256))
    )
    k_digits = scalar.windows4_from_bits(scalar.bits_from_limbs(k, 256))
    kh = curve.scalar_mul_w4(k_digits, h_pt)

    g8 = curve.mul_cofactor(gamma)
    gamma_enc, u_enc, v_enc, g8_enc = curve.compress_many([gamma, kb, kh, g8])

    batch = pk.shape[:-1]
    p2 = jnp.broadcast_to(jnp.asarray([SUITE, 0x02], jnp.int32), (*batch, 2))
    cdata = jnp.concatenate([p2, h_enc, gamma_enc, u_enc, v_enc], axis=-1)
    c16 = sha512.sha512_fixed(cdata)[..., :16]

    c_limbs = bi.bytes_to_limbs(c16, 20)
    s = scalar.add_mod_l(k, scalar.mul_mod_l(c_limbs, x_limbs))

    p3 = jnp.broadcast_to(jnp.asarray([SUITE, 0x03], jnp.int32), (*batch, 2))
    beta = sha512.sha512_fixed(jnp.concatenate([p3, g8_enc], axis=-1))
    return gamma_enc, c16, u_enc, v_enc, scalar.to_bytes32(s), beta


_PROVE_JIT = None


def stage_prove_np(seeds):
    """Host staging for the prove side: expand each 32-byte VRF seed to
    its (x, prefix, pk) columns — [B, 32] uint8 each — ready for
    `prove` / the forge leader sweep. Factored out of prove_batch so
    protocol/forge.py can stage once per pool and tile across a whole
    slot window."""
    from .host import ed25519 as he

    b = len(seeds)
    x = np.zeros((b, 32), np.uint8)
    prefix = np.zeros((b, 32), np.uint8)
    pk = np.zeros((b, 32), np.uint8)
    for i, seed in enumerate(seeds):
        x_bytes, pref, pk_bytes = he.expand_for_staging(seed)
        x[i] = np.frombuffer(x_bytes, np.uint8)
        prefix[i] = np.frombuffer(pref, np.uint8)
        pk[i] = np.frombuffer(pk_bytes, np.uint8)
    return x, prefix, pk


def encode_proofs_np(g_enc, c16, u_enc, v_enc, s32, batch_compat):
    """Splice prove() output columns into wire proofs: [B, 128] uint8
    (batch-compatible, gamma ‖ u ‖ v ‖ s) or [B, 80] (draft-03,
    gamma ‖ c ‖ s)."""
    if batch_compat:
        cols = [g_enc, u_enc, v_enc, s32]
    else:
        cols = [g_enc, c16, s32]
    return np.concatenate(
        [np.asarray(col) for col in cols], axis=-1
    ).astype(np.uint8)


def prove_batch(seeds, alphas, batch_compat: bool | None = None):
    """Host convenience: -> ([B, 80|128] uint8 proofs, [B, 64] betas).
    batch_compat=None follows the process default (host.fast
    vrf_batch_compat / OCT_VRF_BATCH)."""
    import jax

    from .host import fast

    if batch_compat is None:
        batch_compat = fast.vrf_batch_compat()
    global _PROVE_JIT
    if _PROVE_JIT is None:
        _PROVE_JIT = jax.jit(prove)
    x, prefix, pk = stage_prove_np(seeds)
    alpha = np.stack([np.frombuffer(a, np.uint8) for a in alphas])
    g_enc, c16, u_enc, v_enc, s32, beta = _PROVE_JIT(x, prefix, pk, alpha)
    proofs = encode_proofs_np(g_enc, c16, u_enc, v_enc, s32, batch_compat)
    return proofs, np.asarray(beta).astype(np.uint8)


_JIT: dict = {}


def verify_batch(pks, proofs, alphas):
    """Host convenience: -> (ok [B] bool, beta [B, 64] uint8). Dispatches
    the per-lane kernel matching the staged proof format."""
    batch = stage_np(pks, proofs, alphas)
    key = type(batch).__name__
    if key not in _JIT:
        import jax

        _JIT[key] = jax.jit(
            verify_bc if isinstance(batch, EcvrfBcBatch) else verify
        )
    ok, beta = _JIT[key](*(jnp.asarray(x) for x in batch))
    return np.asarray(ok), np.asarray(beta).astype(np.uint8)
