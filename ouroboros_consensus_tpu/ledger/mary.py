"""Mary-class era: the Allegra rules extended with MULTI-ASSET values
and MINTING — a post-Shelley era whose LEDGER genuinely differs (new tx
wire format, new rules, new state value type), not just different
protocol parameters.

Reference: the ShelleyMA eras (`Shelley/Eras.hs:82-97` StandardAllegra /
StandardMary) and their `CanHardFork` translations
(`Cardano/CanHardFork.hs:273`+ — Shelley→Allegra→Mary carry state while
the value type widens Coin → MaryValue); rule deltas re-derived from
cardano-ledger's ShelleyMA UTXO rule (validity interval replaces TTL,
`consumed + mint == produced` per asset, minting policy witnesses).
Timelock scripts, key witnesses and validity intervals are INHERITED
from the Allegra ledger (ledger/allegra.py).

Wire format (era-tagged; decode_tx of shelley.py CANNOT parse it):
  tx       = [inputs, outputs, fee, [start|null, end|null],
              certs, withdrawals, mint]                     -- classic, or
             [..., mint, scripts, keywits]                  -- witnessed
  output   = [addr, coin]                     -- ada-only, or
             [addr, [coin, assets]]           -- multi-asset
  assets   = [[policy_id/28, [[name, qty]...]]...]
  mint     = [[policy_vk/32, sig/64, [[name, qty]...]]...]
             -- policy id = blake2b-224(policy_vk); sig over the
                witness-free body hash (mint_sig_data); qty may be
                negative (burn)
           | [[script_bytes, null, [[name, qty]...]]...]
             -- TIMELOCK policy: policy id = blake2b-224(script);
                evalTimelock over the tx interval + signatory set
  scripts / keywits exactly as Allegra (allegra.py docstring); the 7-
  field classic form (golden-pinned in round 4) decodes unchanged with
  empty witness sets.
  certs / withdrawals / addr exactly as Shelley (shelley.py docstring)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Mapping

from ..ops.host import ed25519 as host_ed25519
from ..ops.host.hashes import blake2b_224, blake2b_256
from ..utils import cbor
from .allegra import (
    AllegraLedger,
    OutsideValidityInterval,  # noqa: F401 — era re-export (round-4 API)
    ScriptError,  # noqa: F401 — era re-export
    body_hash_of,
    decode_script,
    eval_timelock,
    make_key_witness,
    script_hash,
)
from .shelley import (
    BadInputs,
    ExpiredTx,
    FeeTooSmall,
    MaxTxSizeExceeded,
    ShelleyState,
    ShelleyTxError,
    TxView,
    ValueNotConserved,
    tx_id,
)


class MintError(ShelleyTxError):
    pass


class MaryValue(int):
    """ADA coin (the int value) + native assets. Subclassing int keeps
    every Shelley accounting path (stake sums, pot conservation) correct
    on the ADA component with no changes; the Mary rules alone read
    `.assets` (canonical sorted tuple of ((policy_id, name), qty))."""

    def __new__(cls, coin: int, assets=()) -> "MaryValue":
        self = super().__new__(cls, coin)
        object.__setattr__(
            self, "assets",
            tuple(sorted((k, int(q)) for k, q in dict(assets).items() if q)),
        )
        return self

    def __setattr__(self, k, v):  # immutable after construction
        raise AttributeError("MaryValue is immutable")

    def asset_map(self) -> dict:
        return dict(self.assets)

    def to_triples(self) -> list:
        """Canonical flat wire form [[policy, name, qty]...] — THE one
        asset codec (snapshot and transport codecs both consume it)."""
        return [[pid, name, q] for (pid, name), q in self.assets]

    @classmethod
    def from_triples(cls, coin: int, triples) -> "MaryValue":
        return cls(
            int(coin),
            {(bytes(p), bytes(n)): int(q) for p, n, q in triples},
        )

    def __repr__(self):
        return f"MaryValue({int(self)}, {dict(self.assets)})"


def _decode_value(wire) -> MaryValue:
    if isinstance(wire, int):
        return MaryValue(wire)
    coin, assets = wire
    amap: dict[tuple[bytes, bytes], int] = {}
    for pid, pairs in assets:
        for name, qty in pairs:
            if int(qty) < 0:
                raise ShelleyTxError("negative asset quantity in output")
            amap[(bytes(pid), bytes(name))] = (
                amap.get((bytes(pid), bytes(name)), 0) + int(qty)
            )
    return MaryValue(int(coin), amap)


def _encode_value(v) -> object:
    if not isinstance(v, MaryValue) or not v.assets:
        return int(v)
    by_pid: dict[bytes, list] = {}
    for (pid, name), qty in v.assets:
        by_pid.setdefault(pid, []).append([name, qty])
    return [int(v), [[pid, pairs] for pid, pairs in sorted(by_pid.items())]]


def encode_tx(ins, outs, fee=0, validity=(None, None), certs=(),
              withdrawals=(), mint=(), scripts=(), signers=()) -> bytes:
    """outs: [(payment, stake|None, value)] where value is an int or a
    MaryValue; mint: [(policy_vk, sig, {name: qty})] or
    [(script_bytes, None, {name: qty})] for timelock policies. Without
    scripts/signers the classic 7-field (round-4 golden-pinned) form is
    emitted byte-for-byte."""
    fields = [
        [list(i) for i in ins],
        [[[p, s], _encode_value(v)] for p, s, v in outs],
        fee,
        [validity[0], validity[1]],
        [list(c) for c in certs],
        [list(w) for w in withdrawals],
        [[vk, sg, [[n, q] for n, q in sorted(dict(am).items())]]
         for vk, sg, am in mint],
    ]
    if not scripts and not signers:
        return cbor.encode(fields)
    bh = body_hash_of(fields)
    wits = [list(make_key_witness(seed, bh)) for seed in signers]
    return cbor.encode(fields + [[s for s in scripts], wits])


def mint_sig_data(ins, outs_wire, fee, validity) -> bytes:
    """What a minting policy key signs: the hash of the value-moving
    body (inputs, outputs, fee, validity) — binding the mint to THIS tx."""
    return blake2b_256(cbor.encode([
        [list(i) for i in ins], outs_wire, fee,
        [validity[0], validity[1]],
    ]))


def make_mint_witness(policy_seed: bytes, ins, outs, fee, validity,
                      assets: Mapping[bytes, int]):
    """Sign-side helper: (policy_vk, sig, {name: qty}) for encode_tx's
    mint argument; outs as encode_tx takes them."""
    outs_wire = [[[p, s], _encode_value(v)] for p, s, v in outs]
    sd = mint_sig_data(ins, outs_wire, fee, validity)
    vk = host_ed25519.secret_to_public(policy_seed)
    return (vk, host_ed25519.sign(policy_seed, sd), dict(assets))


def policy_id(policy_vk: bytes) -> bytes:
    return blake2b_224(policy_vk)


@dataclass(frozen=True)
class MaryTx:
    ins: tuple[tuple[bytes, int], ...]
    outs: tuple[tuple[tuple[bytes, bytes | None], MaryValue], ...]
    fee: int
    start: int | None
    end: int | None
    certs: tuple[tuple, ...]
    withdrawals: tuple[tuple[bytes, int], ...]
    mint: tuple[tuple[bytes, bytes | None, tuple], ...]
    # (vk, sig, ((name, qty)..)) or (script_bytes, None, ((name, qty)..))
    outs_wire: tuple  # as decoded, for mint_sig_data recomputation
    size: int
    scripts: tuple[bytes, ...] = ()
    keywits: tuple[tuple[bytes, bytes], ...] = ()
    body_hash: bytes = b""


def decode_tx(tx_bytes: bytes) -> MaryTx:
    try:
        decoded = cbor.decode(tx_bytes)
        if len(decoded) == 7:
            (ins, outs, fee, validity, certs, wdrls, mint) = decoded
            scripts, wits = [], []
        else:
            (ins, outs, fee, validity, certs, wdrls, mint,
             scripts, wits) = decoded
        start, end = validity
        # the body hash only feeds key-witness verification — skip the
        # re-encode+hash for the witness-free classic form (the entire
        # round-4 replay hot path)
        bh = body_hash_of(list(decoded[:7])) if wits else b""
        return MaryTx(
            ins=tuple((bytes(i[0]), int(i[1])) for i in ins),
            outs=tuple(
                ((bytes(a[0]), None if a[1] is None else bytes(a[1])),
                 _decode_value(v))
                for a, v in outs
            ),
            fee=int(fee),
            start=None if start is None else int(start),
            end=None if end is None else int(end),
            certs=tuple(tuple(c) for c in certs),
            withdrawals=tuple((bytes(w[0]), int(w[1])) for w in wdrls),
            mint=tuple(
                (bytes(vk), None if sg is None else bytes(sg),
                 tuple((bytes(n), int(q)) for n, q in pairs))
                for vk, sg, pairs in mint
            ),
            outs_wire=outs,
            size=len(tx_bytes),
            scripts=tuple(bytes(s) for s in scripts),
            keywits=tuple((bytes(w[0]), bytes(w[1])) for w in wits),
            body_hash=bh,
        )
    except ShelleyTxError:
        raise
    except Exception as e:
        raise ShelleyTxError(f"malformed mary tx: {e!r}") from e


def translate_tx_from_shelley(tx_bytes: bytes) -> bytes:
    """InjectTxs translation Shelley→Mary (Cardano/CanHardFork.hs tx
    injection): ttl becomes [null, ttl], mint is empty; certs and
    withdrawals carry verbatim."""
    ins, outs, fee, ttl, certs, wdrls = cbor.decode(tx_bytes)
    return cbor.encode([ins, outs, fee, [None, ttl], certs, wdrls, []])


def translate_tx_from_allegra(tx_bytes: bytes) -> bytes:
    """InjectTxs Allegra→Mary. Witnessed txs cannot cross: key
    witnesses sign the era's body shape, and Mary's body includes the
    mint field — the reference's InjectTxs is partial the same way."""
    (ins, outs, fee, validity, certs, wdrls, scripts, wits) = cbor.decode(
        tx_bytes
    )
    if scripts or wits:
        raise ShelleyTxError(
            "witnessed allegra tx cannot cross the era boundary"
        )
    return cbor.encode([ins, outs, fee, validity, certs, wdrls, []])


class MaryLedger(AllegraLedger):
    """AllegraLedger with the Mary rule deltas (multi-asset + FORGE).
    Timelock scripts, key witnesses and validity intervals come from
    Allegra; certificates, epoch boundaries, snapshots, rewards, pool
    reap and PPUP adoption from Shelley — the Mary era changes the
    value/tx layer only, like the reference's ShelleyMA rule family."""

    # the inherited REAPPLY path must parse the Mary wire format
    _decode_tx = staticmethod(decode_tx)

    # -- era translation INTO Mary ----------------------------------------

    def translate_from_shelley(self, prev: ShelleyState) -> ShelleyState:
        """Shelley→Mary state translation (also Allegra→Mary — the state
        shapes are identical): every UTxO value widens Coin → MaryValue
        (ada-only). Snapshots/pots carry verbatim (CanHardFork.hs:273
        Shelley-family steps)."""
        return replace(
            prev,
            utxo={
                k: (addr, MaryValue(int(c)))
                for k, (addr, c) in prev.utxo.items()
            },
        )

    translate_from_allegra = translate_from_shelley

    # -- the Mary UTXOW/UTXO rules ----------------------------------------

    def apply_tx(self, view: TxView, tx_bytes: bytes) -> TxView:
        tx = decode_tx(tx_bytes)
        pp = view.pparams
        if not tx.ins:
            raise ShelleyTxError("empty input set")
        if len(set(tx.ins)) != len(tx.ins):
            raise BadInputs(tx.ins[0])
        # Allegra validity interval (replaces Shelley's TTL): the slot
        # must lie in [start, end]
        self.check_validity_interval(view, tx.start, tx.end)
        if tx.size > pp.max_tx_size:
            raise MaxTxSizeExceeded(tx.size, pp.max_tx_size)
        min_fee = pp.min_fee_a * tx.size + pp.min_fee_b
        if tx.fee < min_fee:
            raise FeeTooSmall(tx.fee, min_fee)
        if any(int(v) < 0 for _a, v in tx.outs):
            raise ShelleyTxError("negative output")

        consumed = 0
        consumed_assets: dict[tuple[bytes, bytes], int] = {}
        for txin in tx.ins:
            if txin not in view.utxo:
                raise BadInputs(txin)
            val = view.utxo[txin][1]
            consumed += int(val)
            if isinstance(val, MaryValue):
                for k, q in val.assets:
                    consumed_assets[k] = consumed_assets.get(k, 0) + q

        # Allegra witness layer: verified key witnesses feed
        # RequireSignature; script-locked inputs need their timelock
        signatories = self.collect_signatories(tx.keywits, tx.body_hash)
        self.check_script_inputs(
            view, tx.ins, self.script_map(tx.scripts), signatories,
            tx.start, tx.end,
        )

        # FORGE (mint) rule: every group witnessed by its policy — a
        # signing key (sig over mint_sig_data) or a timelock script
        # (policy id = script hash, evalTimelock in the tx context)
        minted: dict[tuple[bytes, bytes], int] = {}
        if tx.mint:
            sd = mint_sig_data(
                [list(i) for i in tx.ins], tx.outs_wire, tx.fee,
                (tx.start, tx.end),
            )
            for vk, sig, pairs in tx.mint:
                if sig is None:
                    # timelock policy: vk position carries script bytes
                    pid = script_hash(vk)
                    if not eval_timelock(
                        decode_script(vk), signatories, tx.start, tx.end
                    ):
                        raise MintError(
                            f"timelock policy failed for {pid.hex()[:8]}"
                        )
                else:
                    if not host_ed25519.verify(vk, sd, sig):
                        raise MintError(
                            f"bad minting-policy signature for "
                            f"{policy_id(vk).hex()[:8]}"
                        )
                    pid = policy_id(vk)
                for name, qty in pairs:
                    if qty == 0:
                        continue
                    minted[(pid, name)] = minted.get((pid, name), 0) + qty

        # scratch for certs/withdrawals — Shelley's machinery verbatim
        scratch = self._scratch_of(view)
        withdrawn = 0
        seen = set()
        for cred, amt in tx.withdrawals:
            if cred in seen:
                raise ShelleyTxError("duplicate withdrawal")
            seen.add(cred)
            if cred not in scratch.rewards:
                raise ShelleyTxError(f"unregistered: {cred.hex()[:8]}")
            if scratch.rewards[cred] != amt:
                raise ShelleyTxError(
                    f"must withdraw full balance {scratch.rewards[cred]}"
                )
            scratch.rewards[cred] = 0
            withdrawn += amt
        deposits_taken = refunds = 0
        for cert in tx.certs:
            try:
                dep, ref = self._apply_cert(scratch, cert)
            except ShelleyTxError:
                raise
            except Exception as e:
                raise ShelleyTxError(f"malformed certificate: {e!r}") from e
            deposits_taken += dep
            refunds += ref

        # ADA conservation (the Shelley equation, mint moves no ada)
        produced_out = sum(int(v) for _a, v in tx.outs)
        if (consumed + withdrawn + refunds
                != produced_out + tx.fee + deposits_taken):
            raise ValueNotConserved(
                consumed + withdrawn + refunds,
                produced_out + tx.fee + deposits_taken,
            )
        # per-asset conservation: consumed + minted == produced
        produced_assets: dict[tuple[bytes, bytes], int] = {}
        for _a, v in tx.outs:
            if isinstance(v, MaryValue):
                for k, q in v.assets:
                    produced_assets[k] = produced_assets.get(k, 0) + q
        lhs: dict[tuple[bytes, bytes], int] = dict(consumed_assets)
        for k, q in minted.items():
            lhs[k] = lhs.get(k, 0) + q
        lhs = {k: q for k, q in lhs.items() if q}
        if lhs != produced_assets:
            raise ValueNotConserved(
                sum(consumed_assets.values()) + sum(minted.values()),
                sum(produced_assets.values()),
            )

        # commit
        tid = tx_id(tx_bytes)
        for txin in tx.ins:
            del view.utxo[txin]
        for ix, (addr, val) in enumerate(tx.outs):
            view.utxo[(tid, ix)] = (addr, val)
        self._commit_scratch(view, scratch, deposits_taken, refunds, tx.fee)
        return view
