"""Conway-class era: the Babbage rules with ON-CHAIN GOVERNANCE in
place of the genesis-delegate machinery — DRep registration and vote
delegation, deposit-backed governance actions, stake-weighted DRep
voting, and epoch-boundary ratification/enactment. PPUP proposals and
MIR certificates are REMOVED (a genuine rule *removal*, like the
reference's Conway dropping the genesis-delegate update system).

Reference: StandardConway (`Shelley/Eras.hs:85-97`) and the
Babbage→Conway `CanHardFork` step (`Cardano/CanHardFork.hs:273`);
the governance shapes re-derived from cardano-ledger's Conway GOV/
RATIFY/ENACT rules, deliberately scoped to two action kinds (parameter
change, treasury withdrawal) voted by DReps.

New certificates (extending the Shelley tags; tags 5 PPUP and 6 MIR are
REJECTED in this era):
  [7, drep_cred]            -- DRep registration (takes drep_deposit)
  [8, drep_cred]            -- DRep deregistration (refunds)
  [9, stake_cred, drep_cred]-- vote delegation (stake cred must be
                               registered; drep must be registered)

Tx wire (babbage fields + two governance fields):
  tx = [...babbage 17 fields..., proposals, votes]
  proposal = [return_cred, action]; the proposer pays
             pparams.gov_action_deposit (into the deposits pot,
             refunded to return_cred's reward account on enact/expiry)
  action   = [0, {pparam: value}]          -- parameter change
           | [1, [[cred, amount]...]]      -- treasury withdrawal
  vote     = [drep_cred, txid/32, ix, yes]  -- one DRep's vote on an
             open action (id = (txid, ix) of the proposing tx)

Ratification (at every epoch boundary, NEWEPOCH order — after rewards,
before pool reap): an action passes when the yes-stake of voting DReps
exceeds pparams.drep_threshold of ALL drep-delegated stake; actions
older than pparams.gov_action_lifetime epochs expire. Either way the
deposit returns to the return credential (treasury if unregistered).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from fractions import Fraction
from typing import Mapping

from ..utils import cbor
from .alonzo import AlonzoPParams
from .babbage import BabbageLedger, BabbageTx
from .babbage import decode_tx as babbage_decode_fields
from .shelley import (
    DelegError,
    ShelleyState,
    ShelleyTxError,
    TxView,
    tx_id,
)


class GovError(ShelleyTxError):
    pass


@dataclass(frozen=True)
class ConwayPParams(AlonzoPParams):
    """AlonzoPParams + the Conway governance parameters."""

    drep_deposit: int = 500
    gov_action_deposit: int = 1000
    gov_action_lifetime: int = 2  # epochs an action stays open
    drep_threshold: Fraction = Fraction(1, 2)

    UPDATABLE = AlonzoPParams.UPDATABLE + (
        "drep_deposit", "gov_action_deposit", "gov_action_lifetime",
        "drep_threshold",
    )

    @classmethod
    def from_alonzo(cls, pp, **overrides) -> "ConwayPParams":
        base = {
            f: getattr(pp, f, None)
            for f in AlonzoPParams.__dataclass_fields__
        }
        base = {k: v for k, v in base.items() if v is not None}
        base.update(overrides)
        return cls(**base)


@dataclass(frozen=True)
class GovAction:
    kind: int  # 0 = pparam change, 1 = treasury withdrawal
    payload: tuple  # sorted pparam items / ((cred, amount)...)
    return_cred: bytes
    deposit: int
    proposed_epoch: int


@dataclass(frozen=True)
class ConwayState(ShelleyState):
    """ShelleyState + the governance sub-state. dataclasses.replace in
    the inherited rules preserves this class, so every Shelley-family
    boundary step flows through unchanged."""

    dreps: Mapping[bytes, int] = field(default_factory=dict)
    drep_delegations: Mapping[bytes, bytes] = field(default_factory=dict)
    gov_actions: Mapping[tuple, GovAction] = field(default_factory=dict)
    gov_votes: Mapping[tuple, bool] = field(default_factory=dict)


@dataclass(frozen=True)
class ConwayTx(BabbageTx):
    proposals: tuple = ()  # ((return_cred, kind, payload)...)
    votes: tuple = ()  # ((drep_cred, txid, ix, yes)...)


def encode_tx(*args, proposals=(), votes=(), **kw) -> bytes:
    """babbage.encode_tx + [proposals, votes]. proposals:
    [(return_cred, action)] with action = [0, {param: val}] or
    [1, [[cred, amt]...]]; votes: [(drep_cred, txid, ix, yes)]."""
    from . import babbage as bb

    inner = bb.encode_tx(*args, **kw)
    fields = cbor.decode(inner)
    return cbor.encode(fields + [
        [[rc, act] for rc, act in proposals],
        [[d, t, int(ix), bool(y)] for d, t, ix, y in votes],
    ])


def decode_tx(tx_bytes: bytes) -> ConwayTx:
    try:
        decoded = cbor.decode(tx_bytes)
        if len(decoded) != 19:
            raise ShelleyTxError(
                f"conway tx must have 19 fields, got {len(decoded)}"
            )
        props, votes = decoded[17], decoded[18]
        inner = babbage_decode_fields(cbor.encode(list(decoded[:17])))
        fields = {
            f: getattr(inner, f) for f in type(inner).__dataclass_fields__
        }
        # the size the fee/max_tx_size rules read must cover the WHOLE
        # tx — including the governance fields stripped for the inner
        # decode
        fields["size"] = len(tx_bytes)
        return ConwayTx(
            **fields,
            proposals=tuple(
                (bytes(rc), (int(act[0]), act[1])) for rc, act in props
            ),
            votes=tuple(
                (bytes(d), bytes(t), int(ix), bool(y))
                for d, t, ix, y in votes
            ),
        )
    except ShelleyTxError:
        raise
    except Exception as e:
        raise ShelleyTxError(f"malformed conway tx: {e!r}") from e


def translate_tx_from_babbage(tx_bytes: bytes) -> bytes:
    """InjectTxs Babbage→Conway: no proposals, no votes."""
    fields = cbor.decode(tx_bytes)
    return cbor.encode(list(fields) + [[], []])


class ConwayLedger(BabbageLedger):
    """BabbageLedger + governance; PPUP/MIR certificates rejected."""

    _decode_tx = staticmethod(decode_tx)

    # -- era translation INTO Conway ---------------------------------------

    def translate_from_babbage(self, prev: ShelleyState) -> ConwayState:
        """Babbage→Conway: pparams widen with governance params; any
        open PPUP proposals are DROPPED (the update system they belong
        to no longer exists — the reference's Conway translation does
        exactly this to the shelley gov state)."""
        pp = prev.pparams
        if not isinstance(pp, ConwayPParams):
            pp = ConwayPParams.from_alonzo(pp)
        base = {
            f: getattr(prev, f) for f in ShelleyState.__dataclass_fields__
        }
        base.update(pparams=pp, proposals={}, pending_mir={})
        return ConwayState(**base)

    # -- certificates ------------------------------------------------------

    def _apply_cert(self, v: TxView, cert: tuple) -> tuple[int, int]:
        tag = cert[0]
        if tag == 5:
            raise GovError(
                "PPUP proposals were removed in Conway; use a "
                "parameter-change governance action"
            )
        if tag == 6:
            raise GovError("MIR certificates were removed in Conway")
        if tag == 7:  # DRep registration
            cred = bytes(cert[1])
            if cred in v.dreps:
                raise GovError(f"drep already registered: {cred.hex()[:8]}")
            dep = v.pparams.drep_deposit
            v.dreps[cred] = dep
            return dep, 0
        if tag == 8:  # DRep deregistration
            cred = bytes(cert[1])
            if cred not in v.dreps:
                raise GovError(f"drep not registered: {cred.hex()[:8]}")
            refund = v.dreps.pop(cred)
            v.drep_delegations = {
                c: d for c, d in v.drep_delegations.items() if d != cred
            }
            return 0, refund
        if tag == 9:  # vote delegation
            cred, drep = bytes(cert[1]), bytes(cert[2])
            if cred not in v.stake_creds:
                raise DelegError(
                    f"delegator not registered: {cred.hex()[:8]}"
                )
            if drep not in v.dreps:
                raise GovError(f"unknown drep: {drep.hex()[:8]}")
            v.drep_delegations[cred] = drep
            return 0, 0
        return super()._apply_cert(v, cert)

    # -- GOV rule (proposals + votes inside apply) -------------------------

    def _apply_gov(self, scratch: TxView, tx: ConwayTx,
                   tid: bytes, check: bool = True) -> int:
        """Validate + record this tx's proposals and votes; returns the
        governance deposits taken. `check=False` is the reapply mode:
        record the same state mutations with NO validation (reapply
        skips all checks, Extended.hs:159) — in particular a vote must
        be recorded even if its DRep deregistered in a LATER tx of the
        same block, which the post-block view can no longer certify."""
        deposits = 0
        for ix, (return_cred, (kind, payload)) in enumerate(tx.proposals):
            if kind == 0:
                if check:
                    scratch.pparams.with_updates(payload)  # validates
                norm = tuple(sorted(
                    (k.decode() if isinstance(k, bytes) else k,
                     tuple(v) if isinstance(v, (list, tuple)) else v)
                    for k, v in payload.items()
                ))
            elif kind == 1:
                norm = tuple(
                    (bytes(c), int(a)) for c, a in payload
                )
                if check and any(a <= 0 for _c, a in norm):
                    raise GovError("non-positive treasury withdrawal")
            else:
                raise GovError(f"unknown governance action kind {kind}")
            dep = scratch.pparams.gov_action_deposit
            scratch.gov_actions[(tid, ix)] = GovAction(
                kind=kind, payload=norm, return_cred=return_cred,
                deposit=dep, proposed_epoch=scratch.epoch,
            )
            deposits += dep
        for drep, txid, ix, yes in tx.votes:
            if check:
                if drep not in scratch.dreps:
                    raise GovError(
                        f"vote from unknown drep {drep.hex()[:8]}"
                    )
                if (txid, ix) not in scratch.gov_actions:
                    raise GovError(
                        f"vote on unknown action {txid.hex()[:8]}#{ix}"
                    )
            scratch.gov_votes[((txid, ix), drep)] = yes
        return deposits

    # apply_tx: inherited from Babbage — its ref-ins rule decodes via
    # self._decode_tx, so it already reads ConwayTx here

    def _apply_era_extras(self, scratch: TxView, tx, tx_bytes: bytes) -> int:
        """Governance rides the certificate scratch/commit window and
        the same conservation equation (deposits_taken) — alonzo's
        _apply_decoded hook."""
        if not isinstance(tx, ConwayTx):
            return 0
        return self._apply_gov(scratch, tx, tx_id(tx_bytes))

    # -- state plumbing ----------------------------------------------------

    def mempool_view(self, state: ConwayState, slot: int) -> TxView:
        view = super().mempool_view(state, slot)
        view.dreps = dict(state.dreps)
        view.drep_delegations = dict(state.drep_delegations)
        view.gov_actions = dict(state.gov_actions)
        view.gov_votes = dict(state.gov_votes)
        return view

    def _commit_block_view(self, st: ConwayState, view: TxView,
                           slot: int) -> ConwayState:
        st = super()._commit_block_view(st, view, slot)
        return replace(
            st,
            dreps=view.dreps,
            drep_delegations=view.drep_delegations,
            gov_actions=view.gov_actions,
            gov_votes=view.gov_votes,
        )

    # reapply: the inherited cert loop already replays DRep certs
    # (tags 7-9 dispatch through Conway's _apply_cert, and the commit
    # seam carries the gov fields); only proposals/votes live outside
    # the cert loop and need replaying here
    def reapply_block(self, ticked, block):
        st = super().reapply_block(ticked, block)
        gov_txs = [
            (tx, tx_id(tx_bytes))
            for tx_bytes in block.txs
            for tx in (self._decode_tx(tx_bytes),)
            if tx.is_valid and (tx.proposals or tx.votes)
        ]
        if not gov_txs:
            return st
        view = self.mempool_view(st, ticked.slot)
        dep = 0
        for tx, tid in gov_txs:
            dep += self._apply_gov(view, tx, tid, check=False)
        return replace(
            st,
            gov_actions=view.gov_actions,
            gov_votes=view.gov_votes,
            deposits=st.deposits + dep,
        )

    # -- RATIFY / ENACT at the epoch boundary ------------------------------

    def _drep_stake(self, st: ConwayState) -> dict[bytes, int]:
        """Per-DRep voting stake: utxo value + rewards of every stake
        credential delegated to it (current state, like the reference's
        DRep distr computed at the boundary)."""
        per: dict[bytes, int] = {}
        stake: dict[bytes, int] = {}
        for (addr, coin) in st.utxo.values():
            cred = addr[1] if len(addr) > 1 else None
            if cred is not None and cred in st.drep_delegations:
                stake[cred] = stake.get(cred, 0) + int(coin)
        for cred, amt in st.rewards.items():
            if amt and cred in st.drep_delegations:
                stake[cred] = stake.get(cred, 0) + amt
        for cred, amt in stake.items():
            drep = st.drep_delegations[cred]
            if drep in st.dreps:
                per[drep] = per.get(drep, 0) + amt
        return per

    def _refund_gov_deposit(self, st_fields: dict, action: GovAction):
        if action.return_cred in st_fields["rewards"]:
            st_fields["rewards"][action.return_cred] = (
                st_fields["rewards"].get(action.return_cred, 0)
                + action.deposit
            )
        else:
            st_fields["treasury"] += action.deposit
        st_fields["deposits"] -= action.deposit

    def _adopt_pparams(self, st: ConwayState) -> ConwayState:
        """Replaces the Shelley PPUP adoption step at the boundary with
        Conway RATIFY/ENACT: stake-weighted DRep voting, expiry after
        gov_action_lifetime epochs."""
        if not st.gov_actions:
            return st
        drep_stake = self._drep_stake(st)
        total_stake = sum(drep_stake.values())
        threshold = st.pparams.drep_threshold
        fields = dict(
            rewards=dict(st.rewards), treasury=st.treasury,
            deposits=st.deposits, reserves=st.reserves,
        )
        pparams = st.pparams
        actions = dict(st.gov_actions)
        votes = dict(st.gov_votes)
        for aid in sorted(actions):
            action = actions[aid]
            yes = sum(
                drep_stake.get(drep, 0)
                for (vid, drep), y in votes.items()
                if vid == aid and y
            )
            ratified = (
                total_stake > 0 and Fraction(yes, total_stake) > threshold
            )
            expired = (
                st.epoch - action.proposed_epoch
                > pparams.gov_action_lifetime
            )
            if not ratified and not expired:
                continue
            if ratified:
                if action.kind == 0:
                    pparams = pparams.with_updates(dict(action.payload))
                else:  # treasury withdrawal
                    for cred, amt in action.payload:
                        if (amt <= fields["treasury"]
                                and cred in st.stake_creds):
                            fields["treasury"] -= amt
                            fields["rewards"][cred] = (
                                fields["rewards"].get(cred, 0) + amt
                            )
            self._refund_gov_deposit(fields, action)
            del actions[aid]
            votes = {k: v for k, v in votes.items() if k[0] != aid}
        return replace(
            st,
            pparams=pparams,
            gov_actions=actions,
            gov_votes=votes,
            rewards=fields["rewards"],
            treasury=fields["treasury"],
            deposits=fields["deposits"],
            reserves=fields["reserves"],
            proposals={},
        )
