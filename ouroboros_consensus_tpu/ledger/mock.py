"""Mock UTxO ledger — the test/benchmark ledger of the framework.

Reference: the `mock-block` library's `SimpleBlock` ledger
(ouroboros-consensus/src/mock-block/.../Mock/Ledger/*): a minimal UTxO
ledger sufficient to drive ThreadNet tests, the mempool, and the
db-synthesizer/db-analyser benchmark pipeline, while keeping tx-level
Shelley fidelity out of the hot path (SURVEY.md §7.2 step 11).

Tx wire format (deterministic CBOR):
    [[ [txid, ix], ... ],  [ [addr, amount], ... ]]
txid = Blake2b-256 of the tx bytes. Genesis UTxO enters as outputs of the
zero txid. The pool stake distribution is static per-epoch configuration
(the Praos LedgerView), as the reference's mock ledger fixes its stake
distribution at genesis (Mock/Ledger/Stake.hs).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Mapping

from ..ops.host.hashes import blake2b_256
from ..protocol.views import LedgerView
from ..utils import cbor
from .abstract import Forecast, LedgerError


class InvalidTx(LedgerError):
    pass


@dataclass
class MissingInput(InvalidTx):
    txin: tuple[bytes, int]


@dataclass
class ValueNotConserved(InvalidTx):
    consumed: int
    produced: int


def tx_id(tx_bytes: bytes) -> bytes:
    return blake2b_256(tx_bytes)


def decode_tx(tx_bytes: bytes):
    ins, outs = cbor.decode(tx_bytes)
    return (
        [(bytes(i[0]), i[1]) for i in ins],
        [(bytes(o[0]), o[1]) for o in outs],
    )


def encode_tx(ins, outs) -> bytes:
    return cbor.encode([[list(i) for i in ins], [list(o) for o in outs]])


@dataclass(frozen=True)
class MockConfig:
    ledger_view: LedgerView  # static pool distribution (mock stake)
    stability_window: int  # forecast horizon (3k/f for Praos)
    check_value_conservation: bool = True


@dataclass(frozen=True)
class MockState:
    """UTxO map + tip slot. Immutable; apply returns a new state."""

    utxo: Mapping[tuple[bytes, int], tuple[bytes, int]]
    tip_slot_: int | None = None


@dataclass(frozen=True)
class TickedMockState:
    state: MockState
    slot: int


class MockLedger:
    """Ledger instance (ledger/abstract.py) for the mock UTxO rules."""

    def __init__(self, config: MockConfig):
        self.config = config

    def genesis_state(self, initial_outputs) -> MockState:
        """initial_outputs: list of (addr, amount) spendable as
        (zero-txid, index)."""
        utxo = {
            (bytes(32), ix): (addr, amt)
            for ix, (addr, amt) in enumerate(initial_outputs)
        }
        return MockState(utxo)

    def tick(self, state: MockState, slot: int) -> TickedMockState:
        return TickedMockState(state, slot)

    def apply_tx(self, utxo: dict, tx_bytes: bytes) -> dict:
        """Validates FULLY before mutating: on failure `utxo` is
        untouched (atomic-on-failure — the Mempool's fast path applies
        into its cached view without a defensive copy)."""
        try:
            ins, outs = decode_tx(tx_bytes)
            # shape checks inside the guard: structurally-decodable
            # garbage (unhashable inputs, non-int amounts) must also be
            # an INVALID TX, not a crash — peers gossip arbitrary bytes
            if len(set(ins)) != len(ins):
                raise MissingInput(ins[0])  # duplicate input spends
            consumed = 0
            for txin in ins:
                if txin not in utxo:
                    raise MissingInput(txin)
                consumed += utxo[txin][1]
            produced = sum(a for _, a in outs)
            if not isinstance(produced, int) or not isinstance(consumed, int):
                raise InvalidTx("non-integer value")
        except InvalidTx:
            raise
        except Exception as e:
            raise InvalidTx(f"malformed tx: {e!r}") from e
        if self.config.check_value_conservation and consumed != produced:
            raise ValueNotConserved(consumed, produced)
        tid = tx_id(tx_bytes)
        for txin in ins:
            del utxo[txin]
        for ix, (addr, amt) in enumerate(outs):
            utxo[(tid, ix)] = (addr, amt)
        return utxo

    def apply_block(self, ticked: TickedMockState, block) -> MockState:
        utxo = dict(ticked.state.utxo)
        for tx in block.txs:
            utxo = self.apply_tx(utxo, tx)
        return MockState(utxo, ticked.slot)

    def reapply_block(self, ticked: TickedMockState, block) -> MockState:
        """Previously validated: inputs are known-present; skip checks."""
        utxo = dict(ticked.state.utxo)
        for tx in block.txs:
            ins, outs = decode_tx(tx)
            tid = tx_id(tx)
            for txin in ins:
                utxo.pop(txin, None)
            for ix, (addr, amt) in enumerate(outs):
                utxo[(tid, ix)] = (addr, amt)
        return MockState(utxo, ticked.slot)

    def tip_slot(self, state: MockState) -> int | None:
        return state.tip_slot_

    def protocol_ledger_view(self, ticked: TickedMockState) -> LedgerView:
        return self.config.ledger_view

    def ledger_view_forecast_at(self, state: MockState) -> Forecast:
        at = -1 if state.tip_slot_ is None else state.tip_slot_
        return Forecast(
            at=at,
            max_for=at + 1 + self.config.stability_window,
            view_fn=lambda s: self.config.ledger_view,
        )

    def tick_then_apply(self, state, block):
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        return self.reapply_block(self.tick(state, block.slot), block)
