"""Mock UTxO ledger — the test/benchmark ledger of the framework.

Reference: the `mock-block` library's `SimpleBlock` ledger
(ouroboros-consensus/src/mock-block/.../Mock/Ledger/*): a minimal UTxO
ledger sufficient to drive ThreadNet tests, the mempool, and the
db-synthesizer/db-analyser benchmark pipeline, while keeping tx-level
Shelley fidelity out of the hot path (SURVEY.md §7.2 step 11).

Tx wire format (deterministic CBOR):
    [[ [txid, ix], ... ],  [ [addr, amount], ... ]]
txid = Blake2b-256 of the tx bytes. Genesis UTxO enters as outputs of the
zero txid. The pool stake distribution is either static configuration
(the Praos LedgerView, like the reference's mock ledger fixing stake at
genesis — Mock/Ledger/Stake.hs) or DERIVED from the UTxO with
epoch-boundary snapshots (StakeConfig: the mark/set/go-shaped rule real
eras use; Ledger/SupportsProtocol.hs ledgerViewForecastAt).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Mapping

from ..ops.host.hashes import blake2b_256
from ..protocol.views import LedgerView
from ..utils import cbor
from .abstract import Forecast, LedgerError


class InvalidTx(LedgerError):
    pass


@dataclass
class MissingInput(InvalidTx):
    txin: tuple[bytes, int]


@dataclass
class ValueNotConserved(InvalidTx):
    consumed: int
    produced: int


def tx_id(tx_bytes: bytes) -> bytes:
    return blake2b_256(tx_bytes)


def decode_tx(tx_bytes: bytes):
    ins, outs = cbor.decode(tx_bytes)
    return (
        [(bytes(i[0]), i[1]) for i in ins],
        [(bytes(o[0]), o[1]) for o in outs],
    )


def encode_tx(ins, outs) -> bytes:
    return cbor.encode([[list(i) for i in ins], [list(o) for o in outs]])


@dataclass(frozen=True)
class StakeConfig:
    """Epoch-varying stake derivation (Ledger/SupportsProtocol.hs
    ledgerViewForecastAt; stake snapshots via the rules reached from
    shelley/.../Shelley/Ledger/Ledger.hs:584):

    pool stake is DERIVED from the UTxO — each address delegates to a
    pool (`delegations`), a pool's stake is the delegated value share —
    and the distribution used for epoch E's leader election is the
    SNAPSHOT taken at the end of epoch E-2 (the "set" snapshot of
    Cardano's mark/set/go rotation: stake decided two boundaries back,
    so forgers and validators agree before the epoch starts)."""

    delegations: Mapping[bytes, bytes]  # addr -> pool_id
    pool_vrf_hashes: Mapping[bytes, bytes]  # pool_id -> Blake2b-256(vrf vk)
    epoch_length: int


@dataclass(frozen=True)
class MockConfig:
    ledger_view: LedgerView  # static pool distribution (mock stake)
    stability_window: int  # forecast horizon (3k/f for Praos)
    check_value_conservation: bool = True
    # None = static stake (ledger_view used for every epoch)
    stake: StakeConfig | None = None


@dataclass(frozen=True)
class MockState:
    """UTxO map + tip slot. Immutable; apply returns a new state.

    `snapshots` (stake config only): most recent end-of-epoch stake
    distributions, newest last, each (lo_label, hi_label, ((pool_id,
    num, den), ...)) — the entry covers every sealed epoch label in
    [lo, hi] (a RANGE because several block-free boundaries can be
    crossed at once, all sharing the tip's distribution); genesis seeds
    (-2, -1, genesis_distr), covering epochs 0 and 1."""

    utxo: Mapping[tuple[bytes, int], tuple[bytes, int]]
    tip_slot_: int | None = None
    snapshots: tuple = ()


@dataclass(frozen=True)
class TickedMockState:
    state: MockState
    slot: int


class MockLedger:
    """Ledger instance (ledger/abstract.py) for the mock UTxO rules."""

    def __init__(self, config: MockConfig):
        self.config = config

    def genesis_state(self, initial_outputs) -> MockState:
        """initial_outputs: list of (addr, amount) spendable as
        (zero-txid, index)."""
        utxo = {
            (bytes(32), ix): (addr, amt)
            for ix, (addr, amt) in enumerate(initial_outputs)
        }
        snaps = ()
        if self.config.stake is not None:
            # labels -2..-1: the genesis distribution is the sealed
            # snapshot for BOTH epoch 0 (wants label -2) and epoch 1
            # (wants -1)
            snaps = ((-2, -1, self._stake_distr(utxo)),)
        return MockState(utxo, snapshots=snaps)

    # -- epoch-varying stake (StakeConfig) --------------------------------

    def _stake_distr(self, utxo) -> tuple:
        """Delegated value share per pool, as ((pool_id, num, den), ...)."""
        cfg = self.config.stake
        per: dict[bytes, int] = {}
        total = 0
        for addr, amt in utxo.values():
            pid = cfg.delegations.get(addr)
            if pid is not None:
                per[pid] = per.get(pid, 0) + amt
                total += amt
        if total == 0:
            return ()
        return tuple(
            (pid, amt, total) for pid, amt in sorted(per.items())
        )

    def _advance_snapshots(self, state: MockState, slot: int) -> MockState:
        """Seal end-of-epoch snapshots for every boundary crossed between
        the state's tip and `slot`. No blocks ran in between, so every
        newly sealed label shares the tip's distribution — recorded as
        ONE range entry [last_sealed+1, e_now-1] (collapsing to a single
        newest label would make a later epoch's lookup skip past the
        range and fall back to a stale older snapshot)."""
        cfg = self.config.stake
        e_now = slot // cfg.epoch_length
        last_sealed = state.snapshots[-1][1] if state.snapshots else -1
        newest_sealed = e_now - 1
        if newest_sealed <= last_sealed:
            return state
        snaps = state.snapshots + (
            (last_sealed + 1, newest_sealed, self._stake_distr(state.utxo)),
        )
        return replace(state, snapshots=snaps[-3:])

    def view_for_epoch(self, state: MockState, epoch: int) -> LedgerView:
        """The LedgerView for `epoch`'s leader election: the snapshot
        range containing label epoch-2 (exact — see _advance_snapshots)."""
        from fractions import Fraction

        from ..protocol.views import IndividualPoolStake

        cfg = self.config.stake
        if cfg is None:
            return self.config.ledger_view
        want = epoch - 2
        chosen = None
        for lo, hi, distr in state.snapshots:
            if lo <= want <= hi:
                chosen = distr
                break
        if chosen is None:
            raise ValueError(
                f"no stake snapshot for epoch {epoch} "
                f"(ranges {[(lo, hi) for lo, hi, _ in state.snapshots]})"
            )
        return LedgerView(
            pool_distr={
                pid: IndividualPoolStake(
                    Fraction(num, den), cfg.pool_vrf_hashes[pid]
                )
                for pid, num, den in chosen
            }
        )

    def tick(self, state: MockState, slot: int) -> TickedMockState:
        if self.config.stake is not None:
            state = self._advance_snapshots(state, slot)
        return TickedMockState(state, slot)

    def apply_tx(self, utxo: dict, tx_bytes: bytes) -> dict:
        """Validates FULLY before mutating: on failure `utxo` is
        untouched (atomic-on-failure — the Mempool's fast path applies
        into its cached view without a defensive copy)."""
        try:
            ins, outs = decode_tx(tx_bytes)
            # shape checks inside the guard: structurally-decodable
            # garbage (unhashable inputs, non-int amounts) must also be
            # an INVALID TX, not a crash — peers gossip arbitrary bytes
            if len(set(ins)) != len(ins):
                raise MissingInput(ins[0])  # duplicate input spends
            if not all(isinstance(ix, int) for _t, ix in ins):
                # a float index like 0.0 would FIND the int-keyed
                # outpoint (0.0 == 0 under dict lookup) — reject the
                # malformed encoding instead
                raise InvalidTx("non-integer input index")
            consumed = 0
            for txin in ins:
                if txin not in utxo:
                    raise MissingInput(txin)
                consumed += utxo[txin][1]
            produced = sum(a for _, a in outs)
            if not isinstance(produced, int) or not isinstance(consumed, int):
                raise InvalidTx("non-integer value")
        except InvalidTx:
            raise
        except Exception as e:
            raise InvalidTx(f"malformed tx: {e!r}") from e
        if self.config.check_value_conservation and consumed != produced:
            raise ValueNotConserved(consumed, produced)
        tid = tx_id(tx_bytes)
        for txin in ins:
            del utxo[txin]
        for ix, (addr, amt) in enumerate(outs):
            utxo[(tid, ix)] = (addr, amt)
        return utxo

    def apply_block(self, ticked: TickedMockState, block) -> MockState:
        utxo = dict(ticked.state.utxo)
        for tx in block.txs:
            utxo = self.apply_tx(utxo, tx)
        return MockState(utxo, ticked.slot, ticked.state.snapshots)

    def reapply_block(self, ticked: TickedMockState, block) -> MockState:
        """Previously validated: inputs are known-present; skip checks."""
        utxo = dict(ticked.state.utxo)
        for tx in block.txs:
            ins, outs = decode_tx(tx)
            tid = tx_id(tx)
            for txin in ins:
                utxo.pop(txin, None)
            for ix, (addr, amt) in enumerate(outs):
                utxo[(tid, ix)] = (addr, amt)
        return MockState(utxo, ticked.slot, ticked.state.snapshots)

    def tip_slot(self, state: MockState) -> int | None:
        return state.tip_slot_

    def protocol_ledger_view(self, ticked: TickedMockState) -> LedgerView:
        if self.config.stake is not None:
            epoch = ticked.slot // self.config.stake.epoch_length
            return self.view_for_epoch(ticked.state, epoch)
        return self.config.ledger_view

    def ledger_view_forecast_at(self, state: MockState) -> Forecast:
        at = -1 if state.tip_slot_ is None else state.tip_slot_
        if self.config.stake is not None:
            cfg = self.config.stake

            def view_fn(s):
                # the snapshot for slot s's epoch is already sealed (it
                # was taken >= 1 full epoch before s, and the forecast
                # horizon is the stability window < epoch length)
                st = self._advance_snapshots(state, s)
                return self.view_for_epoch(st, s // cfg.epoch_length)

            return Forecast(
                at=at,
                max_for=at + 1 + self.config.stability_window,
                view_fn=view_fn,
            )
        return Forecast(
            at=at,
            max_for=at + 1 + self.config.stability_window,
            view_fn=lambda s: self.config.ledger_view,
        )

    def tick_then_apply(self, state, block):
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        return self.reapply_block(self.tick(state, block.slot), block)
