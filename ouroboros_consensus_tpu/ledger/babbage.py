"""Babbage-class era: the Alonzo rules extended with REFERENCE INPUTS,
INLINE DATUMS, REFERENCE SCRIPTS and COLLATERAL RETURN — the era that
lets scripts and datums live on chain instead of in every witness set.

Reference: StandardBabbage (`Shelley/Eras.hs:85-97`) and the
Alonzo→Babbage `CanHardFork` step (`Cardano/CanHardFork.hs:273`); rule
deltas re-derived from cardano-ledger's Babbage UTXO/UTXOW rules
(reference inputs are read-only, inline datums satisfy the datum
witness, the collateral return output takes index |outs|).

Tx wire (era-tagged; alonzo.decode_tx CANNOT parse it):
  tx  = [ins, ref_ins, outs, fee, [start|null, end|null], certs,
         withdrawals, mint, collateral, coll_return|null,
         total_collateral, scripts, keywits, datums, redeemers,
         budget, is_valid]
  out = [addr, value]
      | [addr, value, datum_field]
      | [addr, value, datum_field|null, ref_script]
  datum_field = [0, hash/32]       -- datum by hash (Alonzo-style)
              | [1, datum_bytes]   -- INLINE datum
  coll_return = out (ada-only; receives collateral change on phase-2
                failure; the on-chain output id is (txid, |outs|))
  total_collateral = the ada amount burned on phase-2 failure
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..ops.host.hashes import blake2b_256
from ..utils import cbor
from .allegra import MissingWitness, is_script_addr
from .alonzo import (
    AlonzoLedger,
    AlonzoPParams,
    AlonzoTx,
    CollateralError,
    datum_hash,
    is_plutus,
)
from .mary import MaryValue, _decode_value, _encode_value
from .shelley import (
    BadInputs,
    ShelleyState,
    ShelleyTxError,
    TxView,
)

# utxo address-tuple datum slot: either a 32-byte hash (Alonzo form) or
# ("inline", datum_bytes)


def _encode_datum_field(d):
    if d is None:
        return None
    if isinstance(d, bytes) and len(d) == 32:
        return [0, d]
    if isinstance(d, tuple) and d[0] == "inline":
        return [1, d[1]]
    raise ShelleyTxError(f"bad datum field {d!r}")


def _decode_datum_field(w):
    if w is None:
        return None
    tag = int(w[0])
    if tag == 0:
        return bytes(w[1])
    if tag == 1:
        return ("inline", bytes(w[1]))
    raise ShelleyTxError(f"bad datum field tag {tag}")


def _encode_out(o):
    p, s, v = o[0], o[1], o[2]
    d = _encode_datum_field(o[3]) if len(o) > 3 else None
    r = o[4] if len(o) > 4 else None
    base = [[p, s], _encode_value(v)]
    if d is None and r is None:
        return base
    if r is None:
        return base + [d]
    return base + [d, r]


def _decode_out(o):
    addr, v = o[0], o[1]
    payment = bytes(addr[0])
    stake = None if addr[1] is None else bytes(addr[1])
    d = _decode_datum_field(o[2]) if len(o) > 2 else None
    r = bytes(o[3]) if len(o) > 3 and o[3] is not None else None
    parts = [payment, stake]
    if d is not None or r is not None:
        parts.append(d)
    if r is not None:
        parts.append(r)
    return (tuple(parts), _decode_value(v))


def encode_tx(ins, outs, fee=0, validity=(None, None), certs=(),
              withdrawals=(), mint=(), ref_ins=(), collateral=(),
              coll_return=None, total_collateral=0, scripts=(),
              signers=(), datums=(), redeemers=(), budget=0,
              is_valid=True) -> bytes:
    """outs: [(payment, stake|None, value[, datum[, ref_script]])] where
    datum is a 32-byte hash or ("inline", bytes)."""
    outs_wire = [_encode_out(o) for o in outs]
    cr_wire = None if coll_return is None else _encode_out(coll_return)
    fields = [
        [list(i) for i in ins],
        [list(i) for i in ref_ins],
        outs_wire,
        fee,
        [validity[0], validity[1]],
        [list(c) for c in certs],
        [list(w) for w in withdrawals],
        [[vk, sg, [[n, q] for n, q in sorted(dict(am).items())]]
         for vk, sg, am in mint],
        [list(i) for i in collateral],
        cr_wire,
        int(total_collateral),
        [s for s in scripts],
    ]
    from .allegra import body_hash_of, make_key_witness

    bh = body_hash_of(fields)
    wits = [list(make_key_witness(seed, bh)) for seed in signers]
    return cbor.encode(fields + [
        wits,
        [d for d in datums],
        [[int(p), int(ix), t] for p, ix, t in redeemers],
        int(budget),
        bool(is_valid),
    ])


@dataclass(frozen=True)
class BabbageTx(AlonzoTx):
    ref_ins: tuple[tuple[bytes, int], ...] = ()
    coll_return: tuple | None = None  # decoded out or None
    total_collateral: int = 0


def decode_tx(tx_bytes: bytes) -> BabbageTx:
    try:
        (ins, ref_ins, outs, fee, validity, certs, wdrls, mint, coll,
         cr, total_coll, scripts, wits, datums, redeemers, budget,
         is_valid) = cbor.decode(tx_bytes)
        start, end = validity
        from .allegra import body_hash_of

        # needed by key-witness checks AND as the collateral-return
        # output id (_consume_collateral) — skip only when neither can
        # ever read it
        if wits or cr is not None:
            bh = body_hash_of(
                [ins, ref_ins, outs, fee, validity, certs, wdrls, mint,
                 coll, cr, total_coll, scripts]
            )
        else:
            bh = b""
        return BabbageTx(
            ins=tuple((bytes(i[0]), int(i[1])) for i in ins),
            outs=tuple(_decode_out(o) for o in outs),
            fee=int(fee),
            start=None if start is None else int(start),
            end=None if end is None else int(end),
            certs=tuple(tuple(c) for c in certs),
            withdrawals=tuple((bytes(w[0]), int(w[1])) for w in wdrls),
            mint=tuple(
                (bytes(vk), None if sg is None else bytes(sg),
                 tuple((bytes(n), int(q)) for n, q in pairs))
                for vk, sg, pairs in mint
            ),
            collateral=tuple((bytes(i[0]), int(i[1])) for i in coll),
            scripts=tuple(bytes(s) for s in scripts),
            keywits=tuple((bytes(w[0]), bytes(w[1])) for w in wits),
            datums=tuple(bytes(d) for d in datums),
            redeemers=tuple(
                (int(r[0]), int(r[1]), r[2]) for r in redeemers
            ),
            budget=int(budget),
            is_valid=bool(is_valid),
            outs_wire=outs,
            body_hash=bh,
            size=len(tx_bytes),
            ref_ins=tuple((bytes(i[0]), int(i[1])) for i in ref_ins),
            coll_return=None if cr is None else _decode_out(cr),
            total_collateral=int(total_coll),
        )
    except ShelleyTxError:
        raise
    except Exception as e:
        raise ShelleyTxError(f"malformed babbage tx: {e!r}") from e


def translate_tx_from_alonzo(tx_bytes: bytes) -> bytes:
    """InjectTxs Alonzo→Babbage: no reference inputs, no collateral
    return. Witnessed/script-carrying txs cannot cross (witnesses sign
    the era's body shape — the reference's InjectTxs is partial the
    same way)."""
    (ins, outs, fee, validity, certs, wdrls, mint, coll, scripts,
     wits, datums, redeemers, budget, is_valid) = cbor.decode(tx_bytes)
    if scripts or wits or datums or redeemers:
        raise ShelleyTxError(
            "witnessed alonzo tx cannot cross the era boundary"
        )
    return cbor.encode([
        ins, [], outs, fee, validity, certs, wdrls, mint, coll, None, 0,
        [], [], [], [], budget, is_valid,
    ])


class BabbageLedger(AlonzoLedger):
    """AlonzoLedger + the Babbage deltas. The witness-resolution layer
    (scripts, datums) now ALSO reads reference inputs; phase-2 failure
    burns exactly total_collateral and pays the change to the collateral
    return output."""

    _decode_tx = staticmethod(decode_tx)

    # -- era translation INTO Babbage --------------------------------------

    def translate_from_alonzo(self, prev: ShelleyState) -> ShelleyState:
        pp = prev.pparams
        if not isinstance(pp, AlonzoPParams):
            pp = AlonzoPParams.from_shelley(pp)
        return replace(prev, pparams=pp)

    # -- witness resolution with reference inputs --------------------------

    def _resolve_witnesses(self, view: TxView, tx: BabbageTx):
        """Witness-set scripts/datums plus everything the reference
        inputs carry (Babbage UTXOW: refScripts/refDatums satisfy
        witnessing)."""
        from .allegra import script_hash

        scripts_by_hash, datums_by_hash = super()._resolve_witnesses(
            view, tx
        )
        for txin in tx.ref_ins:
            if txin not in view.utxo:
                raise BadInputs(txin)
            addr = view.utxo[txin][0]
            if len(addr) > 3 and addr[3] is not None:
                scripts_by_hash.setdefault(script_hash(addr[3]), addr[3])
            if len(addr) > 2 and isinstance(addr[2], tuple):
                d = addr[2][1]
                datums_by_hash.setdefault(datum_hash(d), d)
        return scripts_by_hash, datums_by_hash

    def _datum_for(self, addr, datums_by_hash):
        """The datum term for a script-locked utxo entry: inline datum
        directly, else by hash from the resolved datum set."""
        d = addr[2] if len(addr) > 2 else None
        if isinstance(d, tuple):  # ("inline", bytes)
            try:
                return cbor.decode(d[1])
            except Exception as e:
                raise ShelleyTxError(f"undecodable inline datum: {e!r}") from e
        return super()._datum_for(addr, datums_by_hash)

    def _check_collateral(self, view: TxView, tx: BabbageTx,
                          need_phase2: bool) -> int:
        total = super()._check_collateral(view, tx, need_phase2)
        if need_phase2 and tx.coll_return is not None:
            ret_val = int(tx.coll_return[1])
            if isinstance(tx.coll_return[1], MaryValue) and \
                    tx.coll_return[1].assets:
                raise CollateralError("collateral return must be ada-only")
            if ret_val < 0 or ret_val > total or tx.total_collateral < 0:
                raise CollateralError(
                    f"collateral return {ret_val} out of range of "
                    f"collateral {total}"
                )
            if tx.total_collateral != total - ret_val:
                raise CollateralError(
                    f"total_collateral {tx.total_collateral} != "
                    f"collateral {total} - return {ret_val}"
                )
        return total

    def _consume_collateral(self, view: TxView, tx: BabbageTx) -> None:
        """Phase-2 failure: burn total_collateral into fees; the change
        goes to the collateral return output at index |outs|."""
        burned = 0
        for txin in tx.collateral:
            burned += int(view.utxo.pop(txin)[1])
        if tx.coll_return is not None:
            from .shelley import tx_id as _tx_id

            # the decode path kept outs_wire; recompute the txid from
            # the raw bytes the caller handed us is not available here,
            # so the return output id uses the body hash — stable and
            # collision-free within this ledger
            addr, val = tx.coll_return
            view.utxo[(tx.body_hash, len(tx.outs))] = (addr, val)
            burned -= int(val)
        view.fee_delta += burned

    # the Alonzo _apply_decoded works verbatim on BabbageTx — the deltas
    # ride the overridden seams (_resolve_witnesses, _datum_for,
    # _check_collateral, _consume_collateral); only the reference-input
    # precondition is new
    def apply_tx(self, view: TxView, tx_bytes: bytes) -> TxView:
        # self._decode_tx so Conway (and any later era) inherits the
        # rule against its own tx format without re-stating it
        tx = self._decode_tx(tx_bytes)
        # reference inputs must exist and are read-only
        for txin in tx.ref_ins:
            if txin not in view.utxo:
                raise BadInputs(txin)
            if txin in tx.ins:
                raise ShelleyTxError("input is both spent and referenced")
        return self._apply_decoded(view, tx, tx_bytes)
