"""ByronSpec: an independently written executable specification of the
Byron-class rules, run in lock-step with the implementation ledger.

Reference: `src/byronspec/` (wraps `byron-spec-ledger`) + `Ledger/Dual.hs`
— the real Byron impl and the executable spec applied to the same
blocks, any disagreement surfaced immediately (DualByron ThreadNet test,
`byron-test/Test/ThreadNet/DualByron.hs`).

Independence contract (same as ledger/dual.py's mock pairing): the spec
decodes wire bytes itself, computes tx ids itself (hashlib directly),
and owns its abstract state; it shares only the FOUNDATION libraries
with the impl — generic CBOR and the Ed25519 primitive — exactly as
byron-spec-ledger shares cardano-binary/cardano-crypto with the real
implementation. No impl code is consulted while the spec folds.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Mapping

from ..ops.host import ed25519 as _ed
from ..utils import cbor
from . import byron as impl_byron
from .byron import ByronGenesis, ByronTxError


class DualByronMismatch(AssertionError):
    """Impl and spec disagree — a conformance bug, never a valid-chain
    outcome."""


class SpecRejected(Exception):
    """The spec's own invalid verdict (never escapes the pairing)."""


@dataclass(frozen=True)
class ByronSpecState:
    """Abstract state: utxo (outpoint -> (owner, value)) + the
    delegation relation, nothing else."""

    utxo: Mapping[tuple[bytes, int], tuple[bytes, int]]
    delegation: Mapping[bytes, bytes]
    fees: int = 0

    @property
    def balances(self) -> dict[bytes, int]:
        out: dict[bytes, int] = {}
        for addr, amt in self.utxo.values():
            out[addr] = out.get(addr, 0) + amt
        return out


class ByronSpecLedger:
    """The executable spec, written from the wire format down."""

    def __init__(self, genesis_keys, pparams, epoch_length: int):
        self.genesis_keys = set(genesis_keys)
        self.fee_a = pparams.min_fee_a
        self.fee_b = pparams.min_fee_b
        self.max_size = pparams.max_tx_size
        self.epoch_length = epoch_length

    @staticmethod
    def _hash(data: bytes, n: int) -> bytes:
        return hashlib.blake2b(data, digest_size=n).digest()

    def genesis_state(self, initial_outputs) -> ByronSpecState:
        return ByronSpecState(
            utxo={(bytes(32), ix): (bytes(a), int(c))
                  for ix, (a, c) in enumerate(initial_outputs)},
            delegation={vk: vk for vk in self.genesis_keys},
        )

    def apply_payload(self, st: ByronSpecState, raw: bytes,
                      slot: int) -> ByronSpecState:
        try:
            tag, body = cbor.decode(raw)
        except Exception as e:
            raise SpecRejected(f"undecodable: {e!r}") from e
        if tag == 0:
            return self._apply_tx(st, body, raw)
        if tag == 1:
            return self._apply_dcert(st, body, slot)
        raise SpecRejected(f"unknown tag {tag!r}")

    def _apply_tx(self, st: ByronSpecState, body, raw: bytes) -> ByronSpecState:
        try:
            ins_o, outs_o, wits_o = body
            ins = [(bytes(i[0]), i[1]) for i in ins_o]
            outs = [(bytes(a), c) for a, c in outs_o]
            wits = [(bytes(vk), bytes(sg)) for vk, sg in wits_o]
            if not all(isinstance(ix, int) for _t, ix in ins):
                raise SpecRejected("non-integer index")
            if not all(isinstance(c, int) for _a, c in outs):
                raise SpecRejected("non-integer amount")
        except SpecRejected:
            raise
        except Exception as e:
            raise SpecRejected(f"malformed tx: {e!r}") from e
        if len(raw) > self.max_size:
            raise SpecRejected("oversize")
        if not ins or len(set(ins)) != len(ins):
            raise SpecRejected("empty or duplicate inputs")
        if any(c <= 0 for _a, c in outs):
            raise SpecRejected("non-positive output")
        # the spec's own signing-data derivation
        sig_data = self._hash(cbor.encode([
            [[t, ix] for t, ix in ins], [[a, c] for a, c in outs],
        ]), 32)
        wit_by_addr = {self._hash(vk, 28): (vk, sg) for vk, sg in wits}
        utxo = dict(st.utxo)
        consumed = 0
        for txin in ins:
            if txin not in utxo:
                raise SpecRejected(f"missing input {txin!r}")
            addr, amt = utxo.pop(txin)
            w = wit_by_addr.get(addr)
            if w is None:
                raise SpecRejected("unwitnessed input")
            consumed += amt
        for vk, sg in wits:
            if not _ed.verify(vk, sig_data, sg):
                raise SpecRejected("bad witness signature")
        produced = sum(c for _a, c in outs)
        if consumed < produced:
            raise SpecRejected("value not conserved")
        fee = consumed - produced
        if fee < self.fee_a + self.fee_b * len(raw):
            raise SpecRejected("fee too small")
        tid = sig_data  # tx id = hash of the witness-free body
        for ix, (addr, amt) in enumerate(outs):
            utxo[(tid, ix)] = (addr, amt)
        return ByronSpecState(utxo, st.delegation, st.fees + fee)

    def _apply_dcert(self, st: ByronSpecState, body, slot: int) -> ByronSpecState:
        try:
            gvk, dvk, epoch, sig = body
            gvk, dvk, sig = bytes(gvk), bytes(dvk), bytes(sig)
            epoch = int(epoch)
        except Exception as e:
            raise SpecRejected(f"malformed dcert: {e!r}") from e
        if gvk not in self.genesis_keys:
            raise SpecRejected("not a genesis key")
        if epoch != slot // self.epoch_length:
            raise SpecRejected("wrong epoch")
        if not _ed.verify(gvk, cbor.encode([dvk, epoch]), sig):
            raise SpecRejected("bad cert signature")
        dlg = dict(st.delegation)
        for gk, cur in dlg.items():
            if cur == dvk and gk != gvk:
                raise SpecRejected("delegate already in use")
        dlg[gvk] = dvk
        return ByronSpecState(st.utxo, dlg, st.fees)


# ---------------------------------------------------------------------------
# The pairing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DualByronState:
    impl: impl_byron.ByronState
    spec: ByronSpecState

    @property
    def utxo(self):
        return self.impl.utxo

    @property
    def delegation(self):
        return self.impl.delegation

    @property
    def tip_slot_(self):
        return self.impl.tip_slot_


@dataclass(frozen=True)
class TickedDualByronState:
    state: DualByronState
    slot: int


class DualByronLedger:
    """Ledger interface over the (ByronLedger, ByronSpecLedger) pair —
    the DualByron conformance harness as a drop-in ledger."""

    def __init__(self, genesis: ByronGenesis):
        self.genesis = genesis
        self.impl = impl_byron.ByronLedger(genesis)
        self.spec = ByronSpecLedger(
            genesis.genesis_keys, genesis.pparams, genesis.epoch_length
        )

    def _check_agreement(self, st: DualByronState, where: str) -> None:
        impl_bal: dict[bytes, int] = {}
        for addr, amt in st.impl.utxo.values():
            impl_bal[addr] = impl_bal.get(addr, 0) + amt
        if impl_bal != dict(st.spec.balances):
            raise DualByronMismatch(
                f"{where}: impl balances {impl_bal} != spec "
                f"{dict(st.spec.balances)}"
            )
        if dict(st.impl.delegation) != dict(st.spec.delegation):
            raise DualByronMismatch(
                f"{where}: delegation maps disagree: "
                f"{st.impl.delegation} != {st.spec.delegation}"
            )
        if st.impl.fees != st.spec.fees:
            raise DualByronMismatch(
                f"{where}: fee pots disagree: {st.impl.fees} != "
                f"{st.spec.fees}"
            )

    def genesis_state(self, initial_outputs) -> DualByronState:
        st = DualByronState(
            self.impl.genesis_state(initial_outputs),
            self.spec.genesis_state(initial_outputs),
        )
        self._check_agreement(st, "genesis")
        return st

    def tick(self, state: DualByronState, slot: int) -> TickedDualByronState:
        return TickedDualByronState(state, slot)

    def _apply(self, ticked: TickedDualByronState, block,
               check: bool) -> DualByronState:
        hdr = getattr(block, "header", None)
        impl_ticked = self.impl.tick(ticked.state.impl, ticked.slot)
        if hdr is not None and getattr(hdr, "is_ebb", False):
            return DualByronState(
                self.impl.apply_block(impl_ticked, block), ticked.state.spec
            )
        # fold BOTH ledgers per payload, demanding validity agreement
        # (the reference applyHelper pairing)
        impl_view = self.impl.mempool_view(ticked.state.impl, ticked.slot)
        spec = ticked.state.spec
        for raw in block.txs:
            impl_err = spec_err = None
            try:
                impl_view = self.impl.apply_tx(impl_view, raw)
            except ByronTxError as e:
                impl_err = e
            try:
                spec = self.spec.apply_payload(spec, raw, ticked.slot)
            except SpecRejected as e:
                spec_err = e
            if (impl_err is None) != (spec_err is None):
                raise DualByronMismatch(
                    f"block @{block.slot}: validity disagreement — "
                    f"impl: {impl_err!r}, spec: {spec_err!r}"
                )
            if impl_err is not None:
                raise impl_err
        out = DualByronState(
            impl_byron.ByronState(
                utxo=impl_view.utxo, delegation=impl_view.delegation,
                fees=ticked.state.impl.fees + impl_view.fee_delta,
                tip_slot_=ticked.slot,
            ),
            spec,
        )
        if check:
            self._check_agreement(out, f"block @{block.slot}")
        return out

    def apply_block(self, ticked, block) -> DualByronState:
        return self._apply(ticked, block, check=True)

    def reapply_block(self, ticked, block) -> DualByronState:
        return self._apply(ticked, block, check=False)

    def tip_slot(self, state: DualByronState):
        return state.impl.tip_slot_

    def mempool_view(self, state: DualByronState, slot: int):
        return self.impl.mempool_view(state.impl, slot)

    def apply_tx(self, view, tx_bytes: bytes):
        return self.impl.apply_tx(view, tx_bytes)

    def protocol_ledger_view(self, ticked: TickedDualByronState):
        return self.impl.protocol_ledger_view(
            self.impl.tick(ticked.state.impl, ticked.slot)
        )

    def ledger_view_forecast_at(self, state: DualByronState):
        return self.impl.ledger_view_forecast_at(state.impl)

    def tick_then_apply(self, state, block):
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        return self.reapply_block(self.tick(state, block.slot), block)

    def inspect(self, old, new) -> list:
        """Delegate to the impl side (dualLedgerStateMain projection) so
        ByronDelegationChanged surfaces on DualByron nodes too."""
        return self.impl.inspect(old.impl, new.impl)
