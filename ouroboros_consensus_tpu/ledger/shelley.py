"""Shelley-class ledger: real tx-level STS rules, certificates, deposits,
mark/set/go stake snapshots, reward calculation, and protocol-parameter
updates — the depth the mock ledger deliberately omits.

Reference (behavioral parity, re-designed):
  - `ouroboros-consensus-cardano/src/shelley/.../Shelley/Ledger/Ledger.hs`
    (applyBlockLedgerResult / ledgerViewForecastAt around :584)
  - the Shelley ledger STS rule family it delegates to (cardano-ledger):
    LEDGER = UTXOW -> UTXO -> DELEGS -> POOL; TICK -> NEWEPOCH ->
    (RUPD rewards, SNAP snapshot rotation, POOLREAP retirements, and
    PPUP protocol-parameter adoption)
  - `Ledger/SupportsProtocol.hs` ledgerViewForecastAt: the LedgerView
    served for an epoch is the sealed "set" snapshot (mark/set/go
    rotation: stake decided two boundaries back).

Everything is value-semantics: `apply` returns new frozen states; the
per-tx fast path used by the Mempool mutates ONLY a `TxView` scratch
object obtained from `mempool_view` (atomic-on-failure, like the mock
ledger's apply_tx).

Wire format (deterministic CBOR, ../utils/cbor.py):
  tx      = [inputs, outputs, fee, ttl, certs, withdrawals]
  input   = [txid/32, ix]
  output  = [addr, coin];  addr = [payment/28, stake/28|null]
  cert    = [0, cred]                     -- stake key registration
          | [1, cred]                     -- stake key deregistration
          | [2, cred, pool_id]            -- delegation
          | [3, pool_id, vrf_hash, pledge, cost, margin_num, margin_den,
               reward_cred, [owner_cred...]]  -- pool registration/update
          | [4, pool_id, epoch]           -- pool retirement
          | [5, proposer_id, {pparam: value}] -- pparam update proposal
          | [6, pot, proposer_id, [[cred, amount]...]]
               -- MIR (move instantaneous rewards): pot 0 = reserves,
                  1 = treasury; genesis-delegate-proposed; applied at
                  the NEXT epoch boundary (later certs override earlier
                  same-(pot, cred) allocations, the reference's MIR
                  combining rule)
  withdrawal = [cred, coin]   (must withdraw the FULL reward balance)
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from fractions import Fraction
from typing import Mapping

from ..ops.host.hashes import blake2b_256
from ..protocol.views import IndividualPoolStake, LedgerView
from ..utils import cbor
from .abstract import Forecast, LedgerError


class ShelleyTxError(LedgerError):
    pass


@dataclass
class BadInputs(ShelleyTxError):
    txin: tuple[bytes, int]


@dataclass
class ExpiredTx(ShelleyTxError):
    ttl: int
    slot: int


@dataclass
class FeeTooSmall(ShelleyTxError):
    supplied: int
    required: int


@dataclass
class ValueNotConserved(ShelleyTxError):
    consumed: int
    produced: int


@dataclass
class MaxTxSizeExceeded(ShelleyTxError):
    size: int
    limit: int


@dataclass
class DelegError(ShelleyTxError):
    why: str


@dataclass
class PoolError(ShelleyTxError):
    why: str


@dataclass
class WithdrawalError(ShelleyTxError):
    why: str


def tx_id(tx_bytes: bytes) -> bytes:
    return blake2b_256(tx_bytes)


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


def encode_addr(payment: bytes, stake: bytes | None) -> list:
    return [payment, stake]


def encode_tx(ins, outs, fee=0, ttl=2**62, certs=(), withdrawals=()) -> bytes:
    """outs: [(payment, stake|None, coin)]."""
    return cbor.encode([
        [list(i) for i in ins],
        [[encode_addr(p, s), c] for p, s, c in outs],
        fee, ttl,
        [list(c) for c in certs],
        [list(w) for w in withdrawals],
    ])


@dataclass(frozen=True)
class Tx:
    ins: tuple[tuple[bytes, int], ...]
    outs: tuple[tuple[tuple[bytes, bytes | None], int], ...]
    fee: int
    ttl: int
    certs: tuple[tuple, ...]
    withdrawals: tuple[tuple[bytes, int], ...]
    size: int


def decode_tx(tx_bytes: bytes) -> Tx:
    try:
        ins, outs, fee, ttl, certs, wdrls = cbor.decode(tx_bytes)
        return Tx(
            ins=tuple((bytes(i[0]), int(i[1])) for i in ins),
            outs=tuple(
                ((bytes(a[0]), None if a[1] is None else bytes(a[1])), int(c))
                for a, c in outs
            ),
            fee=int(fee),
            ttl=int(ttl),
            certs=tuple(tuple(c) for c in certs),
            withdrawals=tuple((bytes(w[0]), int(w[1])) for w in wdrls),
            size=len(tx_bytes),
        )
    except ShelleyTxError:
        raise
    except Exception as e:  # malformed gossip is an invalid tx, not a crash
        raise ShelleyTxError(f"malformed tx: {e!r}") from e


# ---------------------------------------------------------------------------
# Parameters / state
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PParams:
    """The protocol parameters the rules consume (a real subset of
    Shelley's PParams; updatable via [5, ...] proposals)."""

    min_fee_a: int = 44
    min_fee_b: int = 155381
    max_tx_size: int = 16384
    key_deposit: int = 2_000_000
    pool_deposit: int = 500_000_000
    e_max: int = 18  # max retirement horizon in epochs
    n_opt: int = 3  # k: target pool count (saturation z0 = 1/n_opt)
    a0: Fraction = Fraction(3, 10)  # pledge influence
    rho: Fraction = Fraction(3, 1000)  # monetary expansion per epoch
    tau: Fraction = Fraction(1, 5)  # treasury cut
    min_pool_cost: int = 0

    UPDATABLE = (
        "min_fee_a", "min_fee_b", "max_tx_size", "key_deposit",
        "pool_deposit", "e_max", "n_opt", "a0", "rho", "tau",
        "min_pool_cost",
    )

    def with_updates(self, upd: Mapping[str, object]) -> "PParams":
        clean = {}
        for k, v in upd.items():
            k = k.decode() if isinstance(k, bytes) else k
            if k not in self.UPDATABLE:
                raise ShelleyTxError(f"not an updatable pparam: {k}")
            cur = getattr(self, k)
            if isinstance(cur, Fraction):
                # fractions travel on the wire as [num, den]
                clean[k] = (
                    Fraction(int(v[0]), int(v[1]))
                    if isinstance(v, (list, tuple)) else Fraction(v)
                )
            else:
                clean[k] = int(v)
        return replace(self, **clean)


@dataclass(frozen=True)
class PoolParams:
    pool_id: bytes  # operator key hash (28)
    vrf_hash: bytes  # Blake2b-256 of the pool's VRF vk
    pledge: int
    cost: int
    margin: Fraction
    reward_cred: bytes
    owners: tuple[bytes, ...]


@dataclass(frozen=True)
class Snapshot:
    """A sealed stake distribution: per-credential stake plus the
    delegation map and pool params AS OF the capture boundary."""

    stake: Mapping[bytes, int]
    delegations: Mapping[bytes, bytes]
    pools: Mapping[bytes, PoolParams]

    def pool_stake(self) -> dict[bytes, int]:
        per: dict[bytes, int] = {}
        for cred, amt in self.stake.items():
            pid = self.delegations.get(cred)
            if pid is not None and pid in self.pools:
                per[pid] = per.get(pid, 0) + amt
        return per


EMPTY_SNAPSHOT = Snapshot({}, {}, {})


@dataclass(frozen=True)
class ShelleyGenesis:
    pparams: PParams
    epoch_length: int
    stability_window: int  # forecast horizon (3k/f for Praos)
    genesis_delegates: tuple[bytes, ...] = ()  # pparam-update proposers
    update_quorum: int = 1
    # total supply is conserved: utxo + pots (fees/deposits/treasury/
    # reserves/rewards); anything not in the genesis utxo starts in
    # reserves, funding monetary expansion
    max_supply: int = 45_000_000_000_000_000
    # ERA-RELATIVE epoch arithmetic (the reference ledger receives
    # EpochInfo from the HFC summary, never computes slot//length
    # globally): this era starts at `era_start_slot`, which is epoch
    # number `era_start_epoch` of the chain — a mid-chain era whose
    # epoch length differs from its predecessors sets both from the
    # HFC Summary bound. Defaults preserve the standalone (slot 0,
    # epoch 0) behavior.
    era_start_slot: int = 0
    era_start_epoch: int = 0

    def epoch_of_slot(self, slot: int) -> int:
        return (
            self.era_start_epoch
            + (slot - self.era_start_slot) // self.epoch_length
        )

    def is_epoch_boundary(self, slot: int) -> bool:
        return (slot - self.era_start_slot) % self.epoch_length == 0


@dataclass(frozen=True)
class ShelleyState:
    utxo: Mapping[tuple[bytes, int], tuple[tuple[bytes, bytes | None], int]]
    fees: int  # fee pot of the CURRENT epoch
    deposits: int
    treasury: int
    reserves: int
    stake_creds: Mapping[bytes, int]  # cred -> held deposit
    rewards: Mapping[bytes, int]  # reward accounts of registered creds
    delegations: Mapping[bytes, bytes]
    pools: Mapping[bytes, PoolParams]
    pool_deposits: Mapping[bytes, int]  # pool_id -> deposit actually taken
    retiring: Mapping[bytes, int]  # pool_id -> retirement epoch
    mark: Snapshot
    set_: Snapshot
    go: Snapshot
    blocks_current: Mapping[bytes, int]  # pool -> blocks this epoch
    blocks_prev: Mapping[bytes, int]  # pool -> blocks previous epoch
    prev_fees: int  # previous epoch's fee pot (feeds its reward pot)
    pparams: PParams
    proposals: Mapping[bytes, tuple]  # proposer -> sorted pparam updates
    epoch: int
    tip_slot_: int | None = None
    # MIR allocations awaiting the boundary: (pot, cred) -> amount
    # (pot 0 = reserves, 1 = treasury)
    pending_mir: Mapping[tuple[int, bytes], int] = field(
        default_factory=dict
    )


@dataclass(frozen=True)
class TickedShelleyState:
    state: ShelleyState
    slot: int


@dataclass
class TxView:
    """Mutable scratch for per-tx validation (the Mempool's cached view).
    Carries exactly the sub-state the LEDGER rules read/write."""

    utxo: dict
    stake_creds: dict
    rewards: dict
    delegations: dict
    pools: dict
    pool_deposits: dict
    retiring: dict
    proposals: dict
    pparams: PParams
    epoch: int
    slot: int
    deposit_delta: int = 0
    fee_delta: int = 0
    pending_mir: dict = field(default_factory=dict)
    # pot balances the MIR rule guards against (read-only in the rules)
    reserves: int = 0
    treasury: int = 0
    # Conway governance scratch (ledger/conway.py; empty in prior eras —
    # living on the shared TxView keeps _scratch_of/_commit_scratch the
    # one copy/commit point for every era)
    dreps: dict = field(default_factory=dict)  # drep cred -> deposit
    drep_delegations: dict = field(default_factory=dict)
    gov_actions: dict = field(default_factory=dict)  # (txid, ix) -> action
    gov_votes: dict = field(default_factory=dict)  # (action_id, drep) -> bool


def total_ada(gen: ShelleyGenesis, st: ShelleyState) -> int:
    """Conservation invariant: every lovelace is in exactly one pot."""
    return (
        sum(c for _a, c in st.utxo.values())
        + st.fees + st.prev_fees + st.deposits + st.treasury + st.reserves
        + sum(st.rewards.values())
    )


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class ShelleyLedger:
    """Ledger instance (ledger/abstract.py) for the Shelley-class rules."""

    def __init__(self, genesis: ShelleyGenesis):
        self.genesis = genesis

    # -- construction ------------------------------------------------------

    def genesis_state(
        self,
        initial_outputs,
        initial_pools: tuple[PoolParams, ...] = (),
        initial_delegations: tuple[tuple[bytes, bytes], ...] = (),
    ) -> ShelleyState:
        """initial_outputs: [(payment, stake|None, coin)] spendable as
        (zero-txid, ix); the rest of max_supply starts in reserves.

        `initial_pools` / `initial_delegations` are GENESIS STAKING (the
        reference shelley-genesis `sgStaking` field): pools and stake
        credentials pre-registered with no deposits taken, and all three
        stake snapshots sealed from the genesis distribution — so
        epoch-0/1 elections have stake before any on-chain registration
        could possibly rotate into the SET snapshot."""
        utxo = {
            (bytes(32), ix): ((p, s), c)
            for ix, (p, s, c) in enumerate(initial_outputs)
        }
        circulating = sum(c for _p, _s, c in initial_outputs)
        if circulating > self.genesis.max_supply:
            raise ValueError("genesis outputs exceed max_supply")
        pools: dict[bytes, PoolParams] = {}
        for p in initial_pools:
            # same POOL-rule checks certificate registration enforces —
            # an invalid genesis pool must not corrupt the reward math
            if not (0 <= p.margin <= 1):
                raise ValueError(f"genesis pool margin out of range: {p.margin}")
            if p.cost < self.genesis.pparams.min_pool_cost:
                raise ValueError(f"genesis pool cost below minPoolCost: {p.cost}")
            if p.pool_id in pools:
                raise ValueError(f"duplicate genesis pool {p.pool_id.hex()[:8]}")
            pools[p.pool_id] = p
        seen_creds = set()
        for cred, pid in initial_delegations:
            if pid not in pools:
                raise ValueError(f"delegation to unknown pool {pid.hex()[:8]}")
            if cred in seen_creds:
                raise ValueError(f"duplicate genesis delegation {cred.hex()[:8]}")
            seen_creds.add(cred)
        st = ShelleyState(
            utxo=utxo, fees=0, deposits=0, treasury=0,
            reserves=self.genesis.max_supply - circulating,
            stake_creds={cred: 0 for cred, _ in initial_delegations},
            rewards={cred: 0 for cred, _ in initial_delegations},
            delegations=dict(initial_delegations),
            pools=pools,
            pool_deposits={pid: 0 for pid in pools},
            retiring={}, mark=EMPTY_SNAPSHOT, set_=EMPTY_SNAPSHOT,
            go=EMPTY_SNAPSHOT, blocks_current={}, blocks_prev={},
            prev_fees=0, pparams=self.genesis.pparams, proposals={},
            epoch=0,
        )
        if pools or initial_delegations:
            snap = self._stake_distr(st)
            st = replace(st, mark=snap, set_=snap, go=snap)
        return st

    def translate_from_utxo_ledger(
        self,
        prev_state,
        at_slot: int,
        stake_of=None,  # payment addr -> stake cred | None
        initial_pools: tuple[PoolParams, ...] = (),
        initial_delegations: tuple[tuple[bytes, bytes], ...] = (),
    ) -> ShelleyState:
        """Era translation INTO Shelley (the Byron->Shelley shape,
        Cardano/CanHardFork.hs translateLedgerStateByronToShelleyWrapper):
        the previous era's UTxO (outpoint -> (addr, coin)) is carried
        over verbatim, re-addressed with the configured stake credential
        per payment address, the Shelley genesis staking registers pools
        and delegations exactly as `genesis_state` does, and all three
        snapshots seal the carried-over distribution — elections in the
        first Shelley epochs run on it, just as the reference bootstraps
        from sgStaking across the Byron boundary."""
        if not self.genesis.is_epoch_boundary(at_slot):
            raise ValueError(
                f"era boundary slot {at_slot} must start a Shelley epoch "
                f"(epoch_length={self.genesis.epoch_length}, era start "
                f"{self.genesis.era_start_slot})"
            )
        stake_fn = stake_of if stake_of is not None else (lambda _a: None)
        st = self.genesis_state(
            [], initial_pools=initial_pools,
            initial_delegations=initial_delegations,
        )
        utxo = {
            k: ((addr, stake_fn(addr)), int(amt))
            for k, (addr, amt) in prev_state.utxo.items()
        }
        circulating = sum(c for _a, c in utxo.values())
        if circulating > self.genesis.max_supply:
            raise ValueError("carried-over UTxO exceeds max_supply")
        st = replace(
            st, utxo=utxo,
            reserves=self.genesis.max_supply - circulating,
            epoch=self.genesis.epoch_of_slot(at_slot),
            tip_slot_=getattr(prev_state, "tip_slot_", None),
        )
        snap = self._stake_distr(st)
        return replace(st, mark=snap, set_=snap, go=snap)

    # -- LEDGER rules (per tx) ---------------------------------------------

    def _apply_cert(self, v: TxView, cert: tuple) -> tuple[int, int]:
        """DELEGS/POOL/PPUP rules; returns (deposit_taken, refund_given)."""
        tag = cert[0]
        if tag == 0:  # stake key registration
            cred = bytes(cert[1])
            if cred in v.stake_creds:
                raise DelegError(f"already registered: {cred.hex()[:8]}")
            dep = v.pparams.key_deposit
            v.stake_creds[cred] = dep
            v.rewards[cred] = 0
            return dep, 0
        if tag == 1:  # deregistration
            cred = bytes(cert[1])
            if cred not in v.stake_creds:
                raise DelegError(f"not registered: {cred.hex()[:8]}")
            if v.rewards.get(cred, 0) != 0:
                raise DelegError("non-zero rewards; withdraw first")
            refund = v.stake_creds.pop(cred)
            v.rewards.pop(cred, None)
            v.delegations.pop(cred, None)
            return 0, refund
        if tag == 2:  # delegation
            cred, pid = bytes(cert[1]), bytes(cert[2])
            if cred not in v.stake_creds:
                raise DelegError(f"delegator not registered: {cred.hex()[:8]}")
            if pid not in v.pools:
                raise DelegError(f"unknown pool: {pid.hex()[:8]}")
            v.delegations[cred] = pid
            return 0, 0
        if tag == 3:  # pool registration / re-registration (update)
            (_t, pid, vrf_hash, pledge, cost, m_num, m_den,
             reward_cred, owners) = cert
            margin = Fraction(int(m_num), int(m_den))
            if not (0 <= margin <= 1):
                raise PoolError(f"margin out of range: {margin}")
            if int(cost) < v.pparams.min_pool_cost:
                raise PoolError(f"cost below minPoolCost: {cost}")
            pp = PoolParams(
                pool_id=bytes(pid), vrf_hash=bytes(vrf_hash),
                pledge=int(pledge), cost=int(cost), margin=margin,
                reward_cred=bytes(reward_cred),
                owners=tuple(bytes(o) for o in owners),
            )
            fresh = pp.pool_id not in v.pools
            v.pools[pp.pool_id] = pp
            # re-registration also cancels a pending retirement
            v.retiring.pop(pp.pool_id, None)
            if fresh:
                # record the deposit ACTUALLY taken so POOLREAP refunds
                # exactly it even if pparams.pool_deposit changes later
                v.pool_deposits[pp.pool_id] = v.pparams.pool_deposit
                return v.pparams.pool_deposit, 0
            return 0, 0
        if tag == 4:  # retirement
            pid, epoch = bytes(cert[1]), int(cert[2])
            if pid not in v.pools:
                raise PoolError(f"unknown pool: {pid.hex()[:8]}")
            if not (v.epoch < epoch <= v.epoch + v.pparams.e_max):
                raise PoolError(
                    f"retirement epoch {epoch} outside "
                    f"({v.epoch}, {v.epoch + v.pparams.e_max}]"
                )
            v.retiring[pid] = epoch
            return 0, 0
        if tag == 6:  # MIR — move instantaneous rewards
            pot, proposer = int(cert[1]), bytes(cert[2])
            if pot not in (0, 1):
                raise ShelleyTxError(f"MIR pot must be 0 or 1: {pot}")
            if proposer not in self.genesis.genesis_delegates:
                raise ShelleyTxError(
                    f"MIR proposer is not a genesis delegate: "
                    f"{proposer.hex()[:8]}"
                )
            allocs: dict[bytes, int] = {}
            for cred, amt in cert[3]:
                if int(amt) <= 0:
                    raise ShelleyTxError("non-positive MIR amount")
                allocs[bytes(cred)] = int(amt)
            # guard the pot: all pending allocations to this pot (with
            # this cert's overrides applied) must fit its balance
            merged = {
                c: a for (p, c), a in v.pending_mir.items() if p == pot
            }
            merged.update(allocs)
            balance = v.reserves if pot == 0 else v.treasury
            if sum(merged.values()) > balance:
                raise ShelleyTxError(
                    f"MIR over-allocates pot {pot}: "
                    f"{sum(merged.values())} > {balance}"
                )
            for cred, amt in allocs.items():
                v.pending_mir[(pot, cred)] = amt
            return 0, 0
        if tag == 5:  # pparam update proposal (PPUP)
            proposer, upd = bytes(cert[1]), cert[2]
            if proposer not in self.genesis.genesis_delegates:
                raise ShelleyTxError(
                    f"pparam proposer is not a genesis delegate: "
                    f"{proposer.hex()[:8]}"
                )
            v.pparams.with_updates(upd)  # validates keys/values
            v.proposals[proposer] = tuple(sorted(
                (k.decode() if isinstance(k, bytes) else k,
                 tuple(v2) if isinstance(v2, (list, tuple)) else v2)
                for k, v2 in upd.items()
            ))
            return 0, 0
        raise ShelleyTxError(f"unknown certificate tag: {tag!r}")

    @staticmethod
    def _scratch_of(view: TxView) -> TxView:
        """The certs/withdrawals scratch copy (shared with the Mary
        subclass so a new TxView field can never diverge between eras)."""
        return TxView(
            utxo=view.utxo,  # utxo itself is only read until commit
            stake_creds=dict(view.stake_creds),
            rewards=dict(view.rewards),
            delegations=dict(view.delegations),
            pools=dict(view.pools),
            pool_deposits=dict(view.pool_deposits),
            retiring=dict(view.retiring),
            proposals=dict(view.proposals),
            pparams=view.pparams, epoch=view.epoch, slot=view.slot,
            pending_mir=dict(view.pending_mir),
            reserves=view.reserves, treasury=view.treasury,
            dreps=dict(view.dreps),
            drep_delegations=dict(view.drep_delegations),
            gov_actions=dict(view.gov_actions),
            gov_votes=dict(view.gov_votes),
        )

    @staticmethod
    def _commit_scratch(view: TxView, scratch: TxView,
                        deposits_taken: int, refunds: int, fee: int) -> None:
        view.stake_creds = scratch.stake_creds
        view.rewards = scratch.rewards
        view.delegations = scratch.delegations
        view.pools = scratch.pools
        view.pool_deposits = scratch.pool_deposits
        view.retiring = scratch.retiring
        view.proposals = scratch.proposals
        view.pending_mir = scratch.pending_mir
        view.dreps = scratch.dreps
        view.drep_delegations = scratch.drep_delegations
        view.gov_actions = scratch.gov_actions
        view.gov_votes = scratch.gov_votes
        view.deposit_delta += deposits_taken - refunds
        view.fee_delta += fee

    def apply_tx(self, view: TxView, tx_bytes: bytes) -> TxView:
        """Full UTXOW/UTXO/DELEGS/POOL validation; mutates `view` only
        on success (atomic-on-failure for the Mempool fast path)."""
        tx = decode_tx(tx_bytes)
        pp = view.pparams
        if not tx.ins:
            raise ShelleyTxError("empty input set")
        if len(set(tx.ins)) != len(tx.ins):
            raise BadInputs(tx.ins[0])
        if tx.ttl < view.slot:
            raise ExpiredTx(tx.ttl, view.slot)
        if tx.size > pp.max_tx_size:
            raise MaxTxSizeExceeded(tx.size, pp.max_tx_size)
        min_fee = pp.min_fee_a * tx.size + pp.min_fee_b
        if tx.fee < min_fee:
            raise FeeTooSmall(tx.fee, min_fee)
        if any(c < 0 for _a, c in tx.outs):
            raise ShelleyTxError("negative output")

        consumed = 0
        for txin in tx.ins:
            if txin not in view.utxo:
                raise BadInputs(txin)
            consumed += view.utxo[txin][1]

        # run certs/withdrawals against a scratch copy so a late rule
        # failure can't leave the view half-mutated
        scratch = self._scratch_of(view)
        # withdrawals BEFORE certificates (the DELEGS rule applies the
        # wdrls in its base case, so withdraw-and-deregister in one tx is
        # valid — the cert's zero-rewards check sees the drained account)
        withdrawn = 0
        seen = set()
        for cred, amt in tx.withdrawals:
            if cred in seen:
                raise WithdrawalError("duplicate withdrawal")
            seen.add(cred)
            if cred not in scratch.rewards:
                raise WithdrawalError(f"unregistered: {cred.hex()[:8]}")
            if scratch.rewards[cred] != amt:
                raise WithdrawalError(
                    f"must withdraw full balance "
                    f"{scratch.rewards[cred]}, got {amt}"
                )
            scratch.rewards[cred] = 0
            withdrawn += amt
        deposits_taken = refunds = 0
        for cert in tx.certs:
            try:
                dep, ref = self._apply_cert(scratch, cert)
            except ShelleyTxError:
                raise
            except Exception as e:
                # wrong arity, zero-denominator margins, non-int fields:
                # malformed gossip is an INVALID TX, not a crash
                raise ShelleyTxError(f"malformed certificate: {e!r}") from e
            deposits_taken += dep
            refunds += ref

        produced_out = sum(c for _a, c in tx.outs)
        if (consumed + withdrawn + refunds
                != produced_out + tx.fee + deposits_taken):
            raise ValueNotConserved(
                consumed + withdrawn + refunds,
                produced_out + tx.fee + deposits_taken,
            )

        # commit
        tid = tx_id(tx_bytes)
        for txin in tx.ins:
            del view.utxo[txin]
        for ix, (addr, coin) in enumerate(tx.outs):
            view.utxo[(tid, ix)] = (addr, coin)
        self._commit_scratch(view, scratch, deposits_taken, refunds, tx.fee)
        return view

    # -- Mempool seam ------------------------------------------------------

    def mempool_view(self, state: ShelleyState, slot: int) -> TxView:
        return TxView(
            utxo=dict(state.utxo),
            stake_creds=dict(state.stake_creds),
            rewards=dict(state.rewards),
            delegations=dict(state.delegations),
            pools=dict(state.pools),
            pool_deposits=dict(state.pool_deposits),
            retiring=dict(state.retiring),
            proposals=dict(state.proposals),
            pparams=state.pparams,
            epoch=state.epoch,
            slot=slot,
            pending_mir=dict(state.pending_mir),
            reserves=state.reserves,
            treasury=state.treasury,
        )

    # -- epoch boundary (TICK -> NEWEPOCH) ---------------------------------

    def _stake_distr(self, st: ShelleyState) -> Snapshot:
        """SNAP: per-credential stake = held utxo value (outputs whose
        address names the credential) + reward balance."""
        stake: dict[bytes, int] = {}
        for (addr, coin) in st.utxo.values():
            cred = addr[1]
            if cred is not None and cred in st.stake_creds:
                stake[cred] = stake.get(cred, 0) + coin
        for cred, amt in st.rewards.items():
            if amt:
                stake[cred] = stake.get(cred, 0) + amt
        return Snapshot(stake, dict(st.delegations), dict(st.pools))

    def _reward_update(self, st: ShelleyState) -> ShelleyState:
        """RUPD/MIR: distribute the previous epoch's reward pot using the
        GO snapshot and that epoch's per-pool block counts.

        pot = rho * reserves + prev_fees;  treasury takes tau * pot; the
        member/operator split uses the maxPool formula
        (cardano-ledger Shelley spec §11.8, re-derived):
          z0 = 1/n_opt, sigma' = min(sigma, z0), p' = min(pledge/T, z0)
          maxP = R/(1+a0) * (sigma' + p'*a0*(sigma' - p'*(z0-sigma')/z0)/z0)
        scaled by apparent performance beta = blocks/expected. Unclaimed
        rewards (unregistered accounts) return to reserves."""
        go = st.go
        pool_stake = go.pool_stake()
        total_stake = sum(go.stake.values())
        total_blocks = sum(st.blocks_prev.values())
        pp = st.pparams
        pot = int(pp.rho * st.reserves) + st.prev_fees
        treasury_cut = int(pp.tau * pot)
        big_r = pot - treasury_cut
        rewards = dict(st.rewards)
        paid = 0
        if total_blocks and total_stake and big_r > 0:
            z0 = Fraction(1, pp.n_opt)
            for pid, n_blocks in sorted(st.blocks_prev.items()):
                pparams_pool = go.pools.get(pid)
                if pparams_pool is None or n_blocks == 0:
                    continue
                pstake = pool_stake.get(pid, 0)
                sigma = Fraction(pstake, total_stake)
                p = min(Fraction(pparams_pool.pledge, total_stake), z0)
                s_c = min(sigma, z0)
                max_p = int(
                    Fraction(big_r, 1) / (1 + pp.a0)
                    * (s_c + p * pp.a0 * (s_c - p * (z0 - s_c) / z0) / z0)
                )
                beta = Fraction(n_blocks, total_blocks)
                expected = sigma if sigma > 0 else Fraction(1)
                perf = min(Fraction(1), beta / expected)
                pool_r = int(max_p * perf)
                if pool_r <= 0:
                    continue
                # operator: cost + margin of the rest (+ member share of
                # owner stake); members: stake-proportional remainder
                cost = min(pparams_pool.cost, pool_r)
                rest = pool_r - cost
                op_take = cost + int(pparams_pool.margin * rest)
                member_pot = pool_r - op_take
                owner_creds = set(pparams_pool.owners)
                member_stake = sum(
                    amt for cred, amt in go.stake.items()
                    if go.delegations.get(cred) == pid
                    and cred not in owner_creds
                )
                distributed = 0
                if member_stake > 0 and member_pot > 0:
                    for cred, amt in sorted(go.stake.items()):
                        if (go.delegations.get(cred) != pid
                                or cred in owner_creds):
                            continue
                        share = member_pot * amt // member_stake
                        if share and cred in st.stake_creds:
                            rewards[cred] = rewards.get(cred, 0) + share
                            distributed += share
                op_total = op_take + (member_pot - distributed
                                      if member_stake == 0 else 0)
                if pparams_pool.reward_cred in st.stake_creds:
                    rewards[pparams_pool.reward_cred] = (
                        rewards.get(pparams_pool.reward_cred, 0) + op_total
                    )
                    distributed += op_total
                paid += distributed
        # conservation: prev_fees is consumed; rho*reserves funds the
        # rest of the pot; unclaimed big_r returns to reserves implicitly
        return replace(
            st,
            treasury=st.treasury + treasury_cut,
            reserves=st.reserves + st.prev_fees - treasury_cut - paid,
            rewards=rewards,
            prev_fees=0,
        )

    def _pool_reap(self, st: ShelleyState, epoch: int) -> ShelleyState:
        """POOLREAP: delete pools whose retirement epoch arrived; refund
        the pool deposit to the operator's reward account (treasury if
        the account is gone); drop delegations to dead pools."""
        dead = {pid for pid, e in st.retiring.items() if e <= epoch}
        if not dead:
            return st
        pools = {p: pp for p, pp in st.pools.items() if p not in dead}
        pool_deposits = {
            p: d for p, d in st.pool_deposits.items() if p not in dead
        }
        retiring = {p: e for p, e in st.retiring.items() if p not in dead}
        rewards = dict(st.rewards)
        deposits = st.deposits
        treasury = st.treasury
        for pid in sorted(dead):
            pp = st.pools[pid]
            # refund the deposit RECORDED at registration, not the current
            # pparam (which a PPUP update may have changed since); every
            # registered pool has an entry — a KeyError here means a
            # desynced registration path, which must fail loudly
            dep = st.pool_deposits[pid]
            deposits -= dep
            if pp.reward_cred in st.stake_creds:
                rewards[pp.reward_cred] = rewards.get(pp.reward_cred, 0) + dep
            else:
                treasury += dep
        delegations = {
            c: p for c, p in st.delegations.items() if p not in dead
        }
        return replace(
            st, pools=pools, pool_deposits=pool_deposits, retiring=retiring,
            rewards=rewards, deposits=deposits, treasury=treasury,
            delegations=delegations,
        )

    def _adopt_pparams(self, st: ShelleyState) -> ShelleyState:
        """PPUP adoption: an update carried by >= update_quorum genesis
        delegates with IDENTICAL content is adopted at the boundary."""
        if not st.proposals:
            return st
        votes: dict[tuple, int] = {}
        for upd in st.proposals.values():
            votes[upd] = votes.get(upd, 0) + 1
        winner = None
        for upd, n in sorted(votes.items(), key=lambda kv: (kv[1], repr(kv[0]))):
            if n >= self.genesis.update_quorum:
                winner = upd
        pparams = st.pparams
        if winner is not None:
            pparams = pparams.with_updates(dict(winner))
        return replace(st, pparams=pparams, proposals={})

    def _apply_mir(self, st: ShelleyState) -> ShelleyState:
        """Apply pending MIR allocations (the reference's MIR rule at
        the boundary tick): funds move pot -> registered reward
        accounts; allocations to unregistered credentials (or exceeding
        the pot, possible if the pot shrank since the cert) stay put."""
        if not st.pending_mir:
            return st
        rewards = dict(st.rewards)
        reserves, treasury = st.reserves, st.treasury
        for (pot, cred), amt in sorted(st.pending_mir.items()):
            if cred not in st.stake_creds:
                continue
            if pot == 0:
                if amt > reserves:
                    continue
                reserves -= amt
            else:
                if amt > treasury:
                    continue
                treasury -= amt
            rewards[cred] = rewards.get(cred, 0) + amt
        return replace(
            st, rewards=rewards, reserves=reserves, treasury=treasury,
            pending_mir={},
        )

    def _new_epoch(self, st: ShelleyState, epoch: int) -> ShelleyState:
        """One boundary crossing, in the reference's NEWEPOCH order:
        MIR application, rewards (from GO + prev blocks), snapshot
        rotation, pool reap, pparam adoption."""
        st = self._apply_mir(st)
        st = self._reward_update(st)
        st = replace(
            st,
            mark=self._stake_distr(st),
            set_=st.mark,
            go=st.set_,
            blocks_prev=st.blocks_current,
            blocks_current={},
            prev_fees=st.fees,
            fees=0,
            epoch=epoch,
        )
        st = self._pool_reap(st, epoch)
        return self._adopt_pparams(st)

    def tick(self, state: ShelleyState, slot: int) -> TickedShelleyState:
        e_now = self.genesis.epoch_of_slot(slot)
        st = state
        while st.epoch < e_now:
            st = self._new_epoch(st, st.epoch + 1)
        return TickedShelleyState(st, slot)

    # -- block application -------------------------------------------------

    def _issuer_pool(self, block) -> bytes | None:
        from ..block.abstract import issuer_vk_of

        header = getattr(block, "header", None)
        vk = issuer_vk_of(header) if header is not None else None
        if vk is None:
            return None
        from ..protocol.views import hash_key

        return hash_key(vk)

    def _count_block(self, st: ShelleyState, block) -> ShelleyState:
        pid = self._issuer_pool(block)
        if pid is None:
            return st
        blocks = dict(st.blocks_current)
        blocks[pid] = blocks.get(pid, 0) + 1
        return replace(st, blocks_current=blocks)

    def _commit_block_view(self, st: ShelleyState, view: TxView,
                           slot: int) -> ShelleyState:
        """Fold a fully-applied block view back into the state — the one
        commit point shared by apply_block and reapply_block across all
        eras (Conway extends it with the governance sub-state)."""
        return replace(
            st,
            utxo=view.utxo,
            stake_creds=view.stake_creds,
            rewards=view.rewards,
            delegations=view.delegations,
            pools=view.pools,
            pool_deposits=view.pool_deposits,
            retiring=view.retiring,
            proposals=view.proposals,
            pending_mir=view.pending_mir,
            fees=st.fees + view.fee_delta,
            deposits=st.deposits + view.deposit_delta,
            tip_slot_=slot,
        )

    def apply_block(self, ticked: TickedShelleyState, block) -> ShelleyState:
        st = ticked.state
        view = self.mempool_view(st, ticked.slot)
        for tx in block.txs:
            view = self.apply_tx(view, tx)
        st = self._commit_block_view(st, view, ticked.slot)
        return self._count_block(st, block)

    # tx-layer decode seam: era subclasses (Mary) override so the
    # REAPPLY path parses their wire format too
    _decode_tx = staticmethod(decode_tx)

    def reapply_block(self, ticked: TickedShelleyState, block) -> ShelleyState:
        """Previously validated: replay the value movements without the
        rule checks (mirrors the mock ledger's reapply shape)."""
        st = ticked.state
        view = self.mempool_view(st, ticked.slot)
        for tx_bytes in block.txs:
            tx = self._decode_tx(tx_bytes)
            tid = tx_id(tx_bytes)
            for txin in tx.ins:
                view.utxo.pop(txin, None)
            for ix, (addr, coin) in enumerate(tx.outs):
                view.utxo[(tid, ix)] = (addr, coin)
            # same order as apply_tx: withdrawals drain the account before
            # any deregistration cert re-checks it
            for cred, amt in tx.withdrawals:
                view.rewards[cred] = 0
            dep = ref = 0
            for cert in tx.certs:
                d, r = self._apply_cert(view, cert)
                dep += d
                ref += r
            view.deposit_delta += dep - ref
            view.fee_delta += tx.fee
        st = self._commit_block_view(st, view, ticked.slot)
        return self._count_block(st, block)

    # -- protocol interface ------------------------------------------------

    def tip_slot(self, state: ShelleyState) -> int | None:
        return state.tip_slot_

    def _view_from_snapshot(self, snap: Snapshot) -> LedgerView:
        per = snap.pool_stake()
        total = sum(per.values())
        if total == 0:
            return LedgerView(pool_distr={})
        return LedgerView(pool_distr={
            pid: IndividualPoolStake(
                Fraction(amt, total), snap.pools[pid].vrf_hash
            )
            for pid, amt in sorted(per.items())
        })

    def protocol_ledger_view(self, ticked: TickedShelleyState) -> LedgerView:
        """Election view for the ticked slot's epoch: the SET snapshot
        (sealed two boundaries back — forgers and validators agree on it
        before the epoch starts)."""
        return self._view_from_snapshot(ticked.state.set_)

    def view_for_epoch(self, state: ShelleyState, epoch: int) -> LedgerView:
        """db-analyser seam (same contract as MockLedger.view_for_epoch):
        the view epoch E elects with, given a state already in E."""
        if epoch < state.epoch:
            raise ValueError(f"state is past epoch {epoch}")
        st = state
        while st.epoch < epoch:
            st = self._new_epoch(st, st.epoch + 1)
        return self._view_from_snapshot(st.set_)

    def ledger_view_forecast_at(self, state: ShelleyState) -> Forecast:
        at = -1 if state.tip_slot_ is None else state.tip_slot_

        def view_fn(s):
            return self.protocol_ledger_view(self.tick(state, s))

        return Forecast(
            at=at,
            max_for=at + 1 + self.genesis.stability_window,
            view_fn=view_fn,
        )


    def inspect(self, old: ShelleyState, new: ShelleyState) -> list:
        """InspectLedger instance (reference shelley Ledger/Inspect.hs
        ShelleyLedgerUpdate): report proposal-set changes and boundary
        pparam adoptions — the events cardano-node logs for operators."""
        from .inspect import ShelleyPParamsAdopted, ShelleyUpdatedProposals

        events: list = []
        if new.proposals != old.proposals:
            props = tuple(sorted(
                (p.hex(), upd) for p, upd in new.proposals.items()
            ))
            events.append(ShelleyUpdatedProposals(
                message=(
                    f"protocol update proposals: {len(new.proposals)} open"
                ),
                proposals=props,
            ))
        if new.pparams != old.pparams:
            changed = tuple(
                (f, getattr(old.pparams, f), getattr(new.pparams, f))
                for f in PParams.UPDATABLE
                if getattr(old.pparams, f) != getattr(new.pparams, f)
            )
            events.append(ShelleyPParamsAdopted(
                message=f"adopted pparam update: {[c[0] for c in changed]}",
                changed=changed,
            ))
        return events

    def tick_then_apply(self, state, block):
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        return self.reapply_block(self.tick(state, block.slot), block)
