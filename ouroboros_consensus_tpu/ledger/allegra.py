"""Allegra-class era: the Shelley rules extended with TIMELOCK SCRIPTS,
VALIDITY INTERVALS and explicit KEY WITNESSES — the first era whose
outputs can be locked by a *script* rather than a key.

Reference: StandardAllegra (`Shelley/Eras.hs:85-97`) and the
Shelley→Allegra `CanHardFork` step (`Cardano/CanHardFork.hs:273`); the
timelock language and its evaluation semantics are re-derived from
cardano-ledger's Allegra `Timelock` (evalTimelock over the tx validity
interval + the witnessing key-hash set).

Wire format (era-tagged; shelley.decode_tx CANNOT parse it):
  tx       = [inputs, outputs, fee, [start|null, end|null],
              certs, withdrawals, scripts, keywits]
  output   = [addr, coin]            -- addr as Shelley
  scripts  = [script_bytes...]       -- witness set: the attached scripts
  keywit   = [vk/32, sig/64]         -- sig over blake2b_256(body) where
                                        body = tx with scripts/keywits
                                        stripped (witness-free prefix)
  certs / withdrawals exactly as Shelley

Timelock script language (CBOR):
  [0, keyhash/28]        -- RequireSignature: keyhash must be among the
                            tx's witnessing key hashes
  [1, [script...]]       -- RequireAllOf
  [2, [script...]]       -- RequireAnyOf
  [3, m, [script...]]    -- RequireMOf
  [4, slot]              -- RequireTimeStart: the validity interval's
                            lower bound exists and >= slot
  [5, slot]              -- RequireTimeExpire: the interval's upper
                            bound exists and <= slot
Evaluation reads ONLY the interval and the signatory set (deterministic
phase-1, like the reference: the current slot never enters script
evaluation — interval membership is the UTXO rule's job).

A script-locked output's payment credential is
`SCRIPT_ADDR_PREFIX + blake2b_224(script_bytes)` (29 bytes — key
credentials here are 28-byte hashes or 32-byte vks, so the tagged form
cannot collide with either).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..ops.host import ed25519 as host_ed25519
from ..ops.host.hashes import blake2b_224, blake2b_256
from ..utils import cbor
from .shelley import (
    BadInputs,
    ExpiredTx,
    FeeTooSmall,
    MaxTxSizeExceeded,
    ShelleyLedger,
    ShelleyState,
    ShelleyTxError,
    TxView,
    ValueNotConserved,
    tx_id,
)

SCRIPT_ADDR_PREFIX = b"\xf1"


class ScriptError(ShelleyTxError):
    pass


class OutsideValidityInterval(ShelleyTxError):
    def __init__(self, start, end, slot):
        super().__init__(f"slot {slot} outside validity [{start}, {end}]")
        self.start, self.end, self.slot = start, end, slot


class MissingWitness(ShelleyTxError):
    pass


# ---------------------------------------------------------------------------
# Timelock scripts
# ---------------------------------------------------------------------------


def script_hash(script_bytes: bytes) -> bytes:
    return blake2b_224(script_bytes)


def script_addr(script_bytes: bytes) -> bytes:
    """Payment credential locking an output with this script."""
    return SCRIPT_ADDR_PREFIX + script_hash(script_bytes)


def is_script_addr(payment: bytes) -> bool:
    return len(payment) == 29 and payment[:1] == SCRIPT_ADDR_PREFIX


def key_hash(vk: bytes) -> bytes:
    """Witness key hash (the RequireSignature credential)."""
    return blake2b_224(vk)


# sign-side script constructors (what a wallet/test builds)
def require_signature(vk_or_hash: bytes) -> bytes:
    kh = vk_or_hash if len(vk_or_hash) == 28 else key_hash(vk_or_hash)
    return cbor.encode([0, kh])


def require_all_of(scripts) -> bytes:
    return cbor.encode([1, [cbor.decode(s) for s in scripts]])


def require_any_of(scripts) -> bytes:
    return cbor.encode([2, [cbor.decode(s) for s in scripts]])


def require_m_of(m: int, scripts) -> bytes:
    return cbor.encode([3, m, [cbor.decode(s) for s in scripts]])


def require_time_start(slot: int) -> bytes:
    return cbor.encode([4, slot])


def require_time_expire(slot: int) -> bytes:
    return cbor.encode([5, slot])


_MAX_SCRIPT_DEPTH = 32


def decode_script(script_bytes: bytes):
    """Decode attacker-supplied script bytes; malformed CBOR is an
    INVALID TX (ScriptError), never a crash (shelley.py:153 rule)."""
    try:
        return cbor.decode(script_bytes)
    except Exception as e:
        raise ScriptError(f"undecodable script: {e!r}") from e


def eval_timelock(node, signatories: frozenset, start, end,
                  _depth: int = 0) -> bool:
    """evalTimelock: node is the DECODED script term."""
    if _depth > _MAX_SCRIPT_DEPTH:
        raise ScriptError("timelock nesting too deep")
    try:
        tag = int(node[0])
        if tag == 0:
            return bytes(node[1]) in signatories
        if tag == 1:
            return all(
                eval_timelock(s, signatories, start, end, _depth + 1)
                for s in node[1]
            )
        if tag == 2:
            return any(
                eval_timelock(s, signatories, start, end, _depth + 1)
                for s in node[1]
            )
        if tag == 3:
            m = int(node[1])
            return sum(
                1 for s in node[2]
                if eval_timelock(s, signatories, start, end, _depth + 1)
            ) >= m
        if tag == 4:
            return start is not None and start >= int(node[1])
        if tag == 5:
            return end is not None and end <= int(node[1])
    except ScriptError:
        raise
    except Exception as e:
        raise ScriptError(f"malformed timelock: {e!r}") from e
    raise ScriptError(f"unknown timelock tag: {node[0]!r}")


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


def body_fields(ins, outs, fee, validity, certs, withdrawals) -> list:
    return [
        [list(i) for i in ins],
        outs,
        fee,
        [validity[0], validity[1]],
        [list(c) for c in certs],
        [list(w) for w in withdrawals],
    ]


def body_hash_of(fields: list) -> bytes:
    """What key witnesses sign: the hash of the witness-free prefix."""
    return blake2b_256(cbor.encode(fields))


def make_key_witness(seed: bytes, body_hash: bytes) -> tuple[bytes, bytes]:
    vk = host_ed25519.secret_to_public(seed)
    return (vk, host_ed25519.sign(seed, body_hash))


def encode_tx(ins, outs, fee=0, validity=(None, None), certs=(),
              withdrawals=(), scripts=(), signers=()) -> bytes:
    """outs: [(payment, stake|None, coin)]; signers: seeds whose key
    witnesses to attach (the sign-side convenience)."""
    fields = body_fields(
        ins, [[[p, s], int(c)] for p, s, c in outs], fee, validity,
        certs, withdrawals,
    )
    bh = body_hash_of(fields)
    wits = [list(make_key_witness(seed, bh)) for seed in signers]
    return cbor.encode(fields + [[s for s in scripts], wits])


@dataclass(frozen=True)
class AllegraTx:
    ins: tuple[tuple[bytes, int], ...]
    outs: tuple[tuple[tuple[bytes, bytes | None], int], ...]
    fee: int
    start: int | None
    end: int | None
    certs: tuple[tuple, ...]
    withdrawals: tuple[tuple[bytes, int], ...]
    scripts: tuple[bytes, ...]
    keywits: tuple[tuple[bytes, bytes], ...]
    body_hash: bytes
    size: int


def decode_tx(tx_bytes: bytes) -> AllegraTx:
    try:
        ins, outs, fee, validity, certs, wdrls, scripts, wits = cbor.decode(
            tx_bytes
        )
        start, end = validity
        bh = body_hash_of(
            body_fields(ins, outs, fee, (start, end), certs, wdrls)
        )
        return AllegraTx(
            ins=tuple((bytes(i[0]), int(i[1])) for i in ins),
            outs=tuple(
                ((bytes(a[0]), None if a[1] is None else bytes(a[1])), int(c))
                for a, c in outs
            ),
            fee=int(fee),
            start=None if start is None else int(start),
            end=None if end is None else int(end),
            certs=tuple(tuple(c) for c in certs),
            withdrawals=tuple((bytes(w[0]), int(w[1])) for w in wdrls),
            scripts=tuple(bytes(s) for s in scripts),
            keywits=tuple((bytes(w[0]), bytes(w[1])) for w in wits),
            body_hash=bh,
            size=len(tx_bytes),
        )
    except ShelleyTxError:
        raise
    except Exception as e:
        raise ShelleyTxError(f"malformed allegra tx: {e!r}") from e


def translate_tx_from_shelley(tx_bytes: bytes) -> bytes:
    """InjectTxs Shelley→Allegra: ttl becomes [null, ttl]; no scripts,
    no key witnesses (Shelley-format txs carry none)."""
    ins, outs, fee, ttl, certs, wdrls = cbor.decode(tx_bytes)
    return cbor.encode([ins, outs, fee, [None, ttl], certs, wdrls, [], []])


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class AllegraLedger(ShelleyLedger):
    """ShelleyLedger + the Allegra deltas: validity interval replaces
    TTL; script-locked outputs spendable by attached timelock scripts;
    key witnesses feed RequireSignature. Certificates, snapshots,
    rewards, POOLREAP and PPUP are INHERITED — like the reference's
    ShelleyMA eras sharing the Shelley rule family."""

    _decode_tx = staticmethod(decode_tx)

    # -- era translation INTO Allegra --------------------------------------

    def translate_from_shelley(self, prev: ShelleyState) -> ShelleyState:
        """Shelley→Allegra: state fields are identical (Coin stays Coin;
        the value type widens only at the Mary step)."""
        return prev

    # -- shared witness machinery (Mary/Alonzo subclasses reuse) -----------

    @staticmethod
    def collect_signatories(keywits, body_hash: bytes) -> frozenset:
        """Verify every key witness; the resulting key-hash set is the
        RequireSignature context. A bad signature is an invalid tx (the
        UTXOW rule), not an ignored witness."""
        sigs = set()
        for vk, sig in keywits:
            if not host_ed25519.verify(vk, body_hash, sig):
                raise MissingWitness(
                    f"invalid key witness for {key_hash(vk).hex()[:8]}"
                )
            sigs.add(key_hash(vk))
        return frozenset(sigs)

    @staticmethod
    def script_map(scripts) -> dict[bytes, bytes]:
        return {script_hash(s): s for s in scripts}

    def check_script_inputs(self, view: TxView, ins, scripts_by_hash,
                            signatories, start, end) -> None:
        """For every input locked by a script credential: the script must
        be attached and must evaluate (UTXOW missing-script +
        evalTimelock)."""
        for txin in ins:
            payment = view.utxo[txin][0][0]
            if not is_script_addr(payment):
                continue
            h = payment[1:]
            script = scripts_by_hash.get(h)
            if script is None:
                raise MissingWitness(
                    f"missing script witness for {h.hex()[:8]}"
                )
            if not eval_timelock(
                decode_script(script), signatories, start, end
            ):
                raise ScriptError(
                    f"timelock evaluation failed for {h.hex()[:8]}"
                )

    def check_validity_interval(self, view: TxView, start, end) -> None:
        if start is not None and view.slot < start:
            raise OutsideValidityInterval(start, end, view.slot)
        if end is not None and view.slot > end:
            raise ExpiredTx(end, view.slot)

    # -- the Allegra UTXOW/UTXO rules --------------------------------------

    def apply_tx(self, view: TxView, tx_bytes: bytes) -> TxView:
        tx = decode_tx(tx_bytes)
        pp = view.pparams
        if not tx.ins:
            raise ShelleyTxError("empty input set")
        if len(set(tx.ins)) != len(tx.ins):
            raise BadInputs(tx.ins[0])
        self.check_validity_interval(view, tx.start, tx.end)
        if tx.size > pp.max_tx_size:
            raise MaxTxSizeExceeded(tx.size, pp.max_tx_size)
        min_fee = pp.min_fee_a * tx.size + pp.min_fee_b
        if tx.fee < min_fee:
            raise FeeTooSmall(tx.fee, min_fee)
        if any(c < 0 for _a, c in tx.outs):
            raise ShelleyTxError("negative output")

        consumed = 0
        for txin in tx.ins:
            if txin not in view.utxo:
                raise BadInputs(txin)
            consumed += int(view.utxo[txin][1])

        signatories = self.collect_signatories(tx.keywits, tx.body_hash)
        self.check_script_inputs(
            view, tx.ins, self.script_map(tx.scripts), signatories,
            tx.start, tx.end,
        )

        scratch = self._scratch_of(view)
        withdrawn = 0
        seen = set()
        for cred, amt in tx.withdrawals:
            if cred in seen:
                raise ShelleyTxError("duplicate withdrawal")
            seen.add(cred)
            if cred not in scratch.rewards:
                raise ShelleyTxError(f"unregistered: {cred.hex()[:8]}")
            if scratch.rewards[cred] != amt:
                raise ShelleyTxError(
                    f"must withdraw full balance {scratch.rewards[cred]}"
                )
            scratch.rewards[cred] = 0
            withdrawn += amt
        deposits_taken = refunds = 0
        for cert in tx.certs:
            try:
                dep, ref = self._apply_cert(scratch, cert)
            except ShelleyTxError:
                raise
            except Exception as e:
                raise ShelleyTxError(f"malformed certificate: {e!r}") from e
            deposits_taken += dep
            refunds += ref

        produced_out = sum(int(c) for _a, c in tx.outs)
        if (consumed + withdrawn + refunds
                != produced_out + tx.fee + deposits_taken):
            raise ValueNotConserved(
                consumed + withdrawn + refunds,
                produced_out + tx.fee + deposits_taken,
            )

        tid = tx_id(tx_bytes)
        for txin in tx.ins:
            del view.utxo[txin]
        for ix, (addr, coin) in enumerate(tx.outs):
            view.utxo[(tid, ix)] = (addr, coin)
        self._commit_scratch(view, scratch, deposits_taken, refunds, tx.fee)
        return view
