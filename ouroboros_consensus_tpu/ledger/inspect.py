"""InspectLedger: surface ledger-internal events to the node.

Reference: `Ouroboros.Consensus.Ledger.Inspect` — `inspectLedger cfg old
new :: [LedgerEvent]`, called after every ledger transition; events are
warnings (unexpected protocol-version signals) or updates (upcoming
changes). The flagship instance is the HFC's
(`HardFork/Combinator/Ledger.hs` inspectHardForkLedger): it reports when
the next era's transition becomes known and when an era boundary is
crossed — cardano-node renders these as the famous "entering era" logs.

Ledgers opt in by defining `inspect(old_state, new_state) -> [event]`;
`inspect_ledger` is the total wrapper. ChainDB traces the events on
every adoption (ChainSel's ledger trace).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class LedgerEvent:
    pass


@dataclass(frozen=True)
class LedgerWarning(LedgerEvent):
    message: str


@dataclass(frozen=True)
class LedgerUpdate(LedgerEvent):
    message: str


@dataclass(frozen=True)
class HardForkEraTransition(LedgerUpdate):
    """Crossed an era boundary (inspectHardForkLedger's TransitionKnown
    → era-crossing report)."""

    from_era: str = ""
    to_era: str = ""


def inspect_ledger(ledger, old_state, new_state) -> list[LedgerEvent]:
    """Total wrapper: ledgers without an `inspect` method emit nothing
    (the default InspectLedger instance)."""
    fn = getattr(ledger, "inspect", None)
    if fn is None:
        return []
    return fn(old_state, new_state)


@dataclass(frozen=True)
class ShelleyUpdatedProposals(LedgerUpdate):
    """Protocol-parameter update proposals changed (the Shelley
    InspectLedger instance's ShelleyUpdatedProtocolUpdates)."""

    proposals: tuple = ()


@dataclass(frozen=True)
class ShelleyPParamsAdopted(LedgerUpdate):
    """An epoch boundary adopted new protocol parameters (PPUP NEWPP)."""

    changed: tuple = ()  # (field, old, new) triples


@dataclass(frozen=True)
class ByronDelegationChanged(LedgerUpdate):
    """A Byron delegation certificate moved signing rights (the PBFT
    ledger view changed) — operators watch this: the wrong forging key
    after a re-delegation produces only rejected blocks."""

    changes: tuple = ()  # (genesis_key, old_delegate, new_delegate) hex
