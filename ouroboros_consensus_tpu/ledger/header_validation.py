"""Header validation: envelope checks + protocol state update.

Reference: `Ouroboros.Consensus.HeaderValidation` — `HeaderState`
(HeaderValidation.hs:151) pairs the protocol ChainDepState with the tip
(`AnnTip`); `tickHeaderState` (:186); `validateHeader` (:413-432) runs the
protocol-independent envelope checks (`BasicEnvelopeValidation` :251 —
block number and slot monotonic, prev-hash matches) and then the
protocol's `update`; `revalidateHeader` (:441) is the assert-only +
`reupdate` fast path for previously-validated headers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, TypeVar

from ..block.abstract import Point

S = TypeVar("S")


class HeaderEnvelopeError(Exception):
    pass


@dataclass
class UnexpectedBlockNo(HeaderEnvelopeError):
    expected: int
    actual: int


@dataclass
class UnexpectedSlotNo(HeaderEnvelopeError):
    expected_at_least: int
    actual: int


@dataclass
class UnexpectedPrevHash(HeaderEnvelopeError):
    expected: bytes | None
    actual: bytes | None


@dataclass(frozen=True)
class AnnTip:
    """Annotated tip (HeaderValidation.hs:96): slot, block no, hash."""

    slot: int
    block_no: int
    hash_: bytes

    @property
    def point(self) -> Point:
        return Point(self.slot, self.hash_)


@dataclass(frozen=True)
class HeaderState:
    """HeaderValidation.hs:151 — tip + protocol chain-dep state."""

    tip: AnnTip | None  # None = genesis
    chain_dep_state: Any


@dataclass(frozen=True)
class TickedHeaderState:
    tip: AnnTip | None
    ticked_chain_dep_state: Any


def tick_header_state(protocol, ledger_view, slot: int, hs: HeaderState) -> TickedHeaderState:
    """tickHeaderState (HeaderValidation.hs:186)."""
    return TickedHeaderState(hs.tip, protocol.tick(ledger_view, slot, hs.chain_dep_state))


def validate_envelope(tip: AnnTip | None, header) -> None:
    """BasicEnvelopeValidation (HeaderValidation.hs:251): first block no /
    slot are minimal, successors increment block no, advance the slot, and
    link prev-hash to the tip hash."""
    if tip is None:
        expected_bno = 0
        min_slot = 0
        expected_prev = None
    else:
        expected_bno = tip.block_no + 1
        min_slot = tip.slot + 1
        expected_prev = tip.hash_
    if header.block_no != expected_bno:
        raise UnexpectedBlockNo(expected_bno, header.block_no)
    if header.slot < min_slot:
        raise UnexpectedSlotNo(min_slot, header.slot)
    if header.prev_hash != expected_prev:
        raise UnexpectedPrevHash(expected_prev, header.prev_hash)


def validate_header(protocol, ticked: TickedHeaderState, header) -> HeaderState:
    """validateHeader (HeaderValidation.hs:413-432): envelope then
    protocol `update` (the crypto); returns the new HeaderState."""
    validate_envelope(ticked.tip, header)
    st = protocol.update(header.to_view(), header.slot, ticked.ticked_chain_dep_state)
    return HeaderState(AnnTip(header.slot, header.block_no, header.hash_), st)


def revalidate_header(protocol, ticked: TickedHeaderState, header) -> HeaderState:
    """revalidateHeader (HeaderValidation.hs:441): envelope as assertion,
    `reupdate` (no crypto) — the replay/reapply fast path."""
    validate_envelope(ticked.tip, header)
    st = protocol.reupdate(header.to_view(), header.slot, ticked.ticked_chain_dep_state)
    return HeaderState(AnnTip(header.slot, header.block_no, header.hash_), st)
