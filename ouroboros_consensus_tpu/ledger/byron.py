"""Byron-class ledger: real UTxO + heavyweight-delegation rules behind
PBFT — the first era of the Cardano composite, with actual tx-level
state (not the signature-only mock it replaces).

Reference (behavioral parity, re-designed):
  - `ouroboros-consensus-cardano/src/byron/.../Byron/Ledger/Ledger.hs:501`
    area (applyBlockLedgerResult delegating to cardano-ledger-byron's
    CHAIN rule: UTXOW witnesses -> UTXO accounting -> DELEG certs)
  - `Byron/Ledger/Mempool.hs` (per-payload mempool application)
  - `Byron/EBBs.hs` (epoch boundary blocks: no ledger effect)
  - PBFT's ledger view is Byron's DELEGATION MAP (Protocol/PBFT.hs:190
    PBftLedgerView) — this module produces it, closing the loop the
    mock era left open (static delegate list).

Scope cuts vs cardano-ledger-byron, documented not silent:
  * addresses are blake2b-224(spending vk) — no attributes/derivation
    paths; deliberately the SAME 28-byte shape as a Shelley payment
    credential so the Byron->Shelley translation carries addressing
    verbatim (CanHardFork.hs translateLedgerStateByronToShelleyWrapper).
  * delegation certificates activate at the NEXT slot, not after the
    reference's scheduling delay window (Byron Delegation.Scheduling).
  * no Byron software-update proposals/votes (the reference's Update
    payload) — the HFC era transition is config-driven here.
  * fees accumulate in a pot (value conservation stays checkable); the
    pot folds into Shelley reserves at the era boundary, like the
    reference's utxo-only translation.

Wire format (deterministic CBOR, ../utils/cbor.py). A block-body item
("payload") is a tagged union — Byron blocks carry tx AND delegation
payloads (Byron/Ledger/Block.hs body = txs + dlg + update):

  payload = [0, tx]     | [1, dcert]
  tx      = [ins, outs, witnesses]
  in      = [txid/32, ix]
  out     = [addr/28, coin]
  witness = [vk/32, sig/64]        -- sig over blake2b_256(cbor([ins,outs]))
  dcert   = [genesis_vk/32, delegate_vk/32, epoch, sig/64]
                                   -- sig by the GENESIS key over
                                      cbor([delegate_vk, epoch])
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Mapping

from ..ops.host import ed25519 as host_ed25519
from ..ops.host.hashes import blake2b_224, blake2b_256
from ..protocol.instances import PBftLedgerView
from ..utils import cbor
from .abstract import Forecast, LedgerError


class ByronTxError(LedgerError):
    pass


@dataclass
class ByronBadInputs(ByronTxError):
    txin: tuple[bytes, int]


@dataclass
class ByronValueNotConserved(ByronTxError):
    consumed: int
    produced: int


@dataclass
class ByronFeeTooSmall(ByronTxError):
    supplied: int
    required: int


@dataclass
class ByronMissingWitness(ByronTxError):
    addr: bytes


@dataclass
class ByronInvalidWitness(ByronTxError):
    why: str


@dataclass
class ByronDelegError(ByronTxError):
    why: str


@dataclass
class ByronTxSizeExceeded(ByronTxError):
    size: int
    limit: int


def addr_of(vk: bytes) -> bytes:
    """Address = blake2b-224 of the spending key (Shelley payment-cred
    compatible; see module scope notes)."""
    return blake2b_224(vk)


def tx_sig_data(ins, outs) -> bytes:
    """What witnesses sign: the hash of the witness-free body (Byron's
    TxSigData = hash of the Tx proper)."""
    return blake2b_256(cbor.encode([
        [[i[0], i[1]] for i in ins],
        [[a, c] for a, c in outs],
    ]))


def tx_id_of(ins, outs) -> bytes:
    """Outputs are created under the id of the witness-free tx body
    (Byron hashes Tx, not ATxAux — witnesses don't malleate the id)."""
    return tx_sig_data(ins, outs)


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


def encode_tx(ins, outs, witnesses) -> bytes:
    """payload bytes for a tx: ins [(txid, ix)], outs [(addr, coin)],
    witnesses [(vk, sig)]."""
    return cbor.encode([0, [
        [[t, ix] for t, ix in ins],
        [[a, c] for a, c in outs],
        [[vk, sg] for vk, sg in witnesses],
    ]])


def encode_dcert(genesis_vk: bytes, delegate_vk: bytes, epoch: int,
                 sig: bytes) -> bytes:
    return cbor.encode([1, [genesis_vk, delegate_vk, epoch, sig]])


def make_tx(ins, outs, seeds) -> bytes:
    """Sign-side helper: build a witnessed tx, one witness per seed (in
    input order — each input's address must be addr_of(its vk))."""
    sd = tx_sig_data(ins, outs)
    wits = [(host_ed25519.secret_to_public(s), host_ed25519.sign(s, sd))
            for s in seeds]
    return encode_tx(ins, outs, wits)


def make_dcert(genesis_seed: bytes, delegate_vk: bytes, epoch: int) -> bytes:
    gvk = host_ed25519.secret_to_public(genesis_seed)
    sig = host_ed25519.sign(genesis_seed, cbor.encode([delegate_vk, epoch]))
    return encode_dcert(gvk, delegate_vk, epoch, sig)


@dataclass(frozen=True)
class ByronTx:
    ins: tuple[tuple[bytes, int], ...]
    outs: tuple[tuple[bytes, int], ...]
    witnesses: tuple[tuple[bytes, bytes], ...]
    size: int


@dataclass(frozen=True)
class ByronDCert:
    genesis_vk: bytes
    delegate_vk: bytes
    epoch: int
    sig: bytes


def decode_payload(raw: bytes) -> ByronTx | ByronDCert:
    try:
        tag, body = cbor.decode(raw)
        if tag == 0:
            ins, outs, wits = body
            return ByronTx(
                ins=tuple((bytes(i[0]), int(i[1])) for i in ins),
                outs=tuple((bytes(a), int(c)) for a, c in outs),
                witnesses=tuple((bytes(vk), bytes(sg)) for vk, sg in wits),
                size=len(raw),
            )
        if tag == 1:
            gvk, dvk, epoch, sig = body
            return ByronDCert(bytes(gvk), bytes(dvk), int(epoch), bytes(sig))
        raise ByronTxError(f"unknown payload tag {tag!r}")
    except ByronTxError:
        raise
    except Exception as e:  # malformed gossip = invalid payload, not a crash
        raise ByronTxError(f"malformed payload: {e!r}") from e


# ---------------------------------------------------------------------------
# Parameters / state
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ByronPParams:
    """The Byron protocol parameters the rules consume (TxFeePolicy's
    linear a + b*size and the size limit)."""

    min_fee_a: int = 155381  # lovelace (Byron's summand)
    min_fee_b: int = 44  # lovelace/byte (Byron's multiplier, rounded)
    max_tx_size: int = 4096


@dataclass(frozen=True)
class ByronGenesis:
    pparams: ByronPParams
    genesis_keys: tuple[bytes, ...]  # cold vks, index order = PBFT's
    epoch_length: int = 40
    security_param: int = 5
    # forecast horizon in slots; None = Byron's 2k (kSlotSecurityParam).
    # Tests with tiny k widen it explicitly rather than distorting k.
    stability_window: int | None = None


@dataclass(frozen=True)
class ByronState:
    """utxo: outpoint -> (addr, coin) — the exact shape
    ShelleyLedger.translate_from_utxo_ledger consumes."""

    utxo: Mapping[tuple[bytes, int], tuple[bytes, int]]
    delegation: Mapping[bytes, bytes]  # genesis vk -> delegate vk
    fees: int
    tip_slot_: int | None = None


@dataclass(frozen=True)
class TickedByronState:
    state: ByronState
    slot: int


@dataclass
class ByronTxView:
    """Mutable mempool scratch (the Shelley TxView shape): exactly the
    sub-state the Byron rules read/write, atomic-on-failure."""

    utxo: dict
    delegation: dict
    pparams: ByronPParams
    epoch: int
    fee_delta: int = 0


class ByronLedger:
    """Ledger instance (ledger/abstract.py) for the Byron-class rules."""

    def __init__(self, genesis: ByronGenesis):
        self.genesis = genesis
        self._gk_index = {vk: i for i, vk in enumerate(genesis.genesis_keys)}

    # -- construction ------------------------------------------------------

    def genesis_state(self, initial_outputs) -> ByronState:
        """initial_outputs: [(addr, coin)] spendable as (zero-txid, ix).
        Delegation starts as the identity map (each genesis key is its
        own delegate), like the reference's genesis delegation."""
        return ByronState(
            utxo={(bytes(32), ix): (bytes(a), int(c))
                  for ix, (a, c) in enumerate(initial_outputs)},
            delegation={vk: vk for vk in self.genesis.genesis_keys},
            fees=0,
        )

    # -- rules (per payload) ----------------------------------------------

    def _apply_tx_rules(self, v: ByronTxView, tx: ByronTx,
                        check_witnesses: bool) -> None:
        """UTXOW -> UTXO (Byron's utxow/utxo STS rules): witnesses first,
        then accounting; mutates `v` only on success path order (callers
        pass a scratch they discard on exception)."""
        if tx.size > v.pparams.max_tx_size:
            raise ByronTxSizeExceeded(tx.size, v.pparams.max_tx_size)
        if not tx.ins:
            raise ByronTxError("empty input list")
        if len(set(tx.ins)) != len(tx.ins):
            raise ByronTxError("duplicate input")
        for _a, c in tx.outs:
            if c <= 0:
                raise ByronTxError("non-positive output")
        # UTXOW: every input's address must be witnessed by the matching
        # key, every witness must verify over the tx sig data
        wit_addrs = {addr_of(vk) for vk, _s in tx.witnesses}
        consumed = 0
        for txin in tx.ins:
            if txin not in v.utxo:
                raise ByronBadInputs(txin)
            addr, coin = v.utxo[txin]
            if addr not in wit_addrs:
                raise ByronMissingWitness(addr)
            consumed += coin
        if check_witnesses:
            sd = tx_sig_data(tx.ins, tx.outs)
            for vk, sig in tx.witnesses:
                if not host_ed25519.verify(vk, sd, sig):
                    raise ByronInvalidWitness(
                        f"bad witness by {vk.hex()[:8]}"
                    )
        # UTXO: linear fee policy, value conservation (fee is implicit)
        produced = sum(c for _a, c in tx.outs)
        if consumed < produced:
            raise ByronValueNotConserved(consumed, produced)
        fee = consumed - produced
        required = v.pparams.min_fee_a + v.pparams.min_fee_b * tx.size
        if fee < required:
            raise ByronFeeTooSmall(fee, required)
        for txin in tx.ins:
            del v.utxo[txin]
        tid = tx_id_of(tx.ins, tx.outs)
        for ix, (addr, coin) in enumerate(tx.outs):
            v.utxo[(tid, ix)] = (addr, coin)
        v.fee_delta += fee

    def _apply_dcert_rules(self, v: ByronTxView, c: ByronDCert,
                           check_witnesses: bool) -> None:
        """DELEG (Byron's delegation STS): only a genesis key can
        delegate; the cert is signed by it; activation is immediate
        (scope cut, module docstring)."""
        if c.genesis_vk not in self._gk_index:
            raise ByronDelegError(
                f"not a genesis key: {c.genesis_vk.hex()[:8]}"
            )
        if c.epoch != v.epoch:
            raise ByronDelegError(
                f"cert epoch {c.epoch} != current epoch {v.epoch}"
            )
        if check_witnesses:
            body = cbor.encode([c.delegate_vk, c.epoch])
            if not host_ed25519.verify(c.genesis_vk, body, c.sig):
                raise ByronDelegError("bad delegation signature")
        # one delegate must not serve two genesis keys (the reference's
        # Bimap injectivity)
        for gk, dvk in v.delegation.items():
            if dvk == c.delegate_vk and gk != c.genesis_vk:
                raise ByronDelegError(
                    f"delegate {c.delegate_vk.hex()[:8]} already serves "
                    f"another genesis key"
                )
        v.delegation[c.genesis_vk] = c.delegate_vk

    def _apply_payload(self, v: ByronTxView, raw: bytes,
                       check_witnesses: bool) -> None:
        p = decode_payload(raw)
        if isinstance(p, ByronTx):
            self._apply_tx_rules(v, p, check_witnesses)
        else:
            self._apply_dcert_rules(v, p, check_witnesses)

    # -- ledger interface --------------------------------------------------

    def tick(self, state: ByronState, slot: int) -> TickedByronState:
        return TickedByronState(state, slot)

    def _scratch(self, st: ByronState, slot: int) -> ByronTxView:
        return ByronTxView(
            utxo=dict(st.utxo),
            delegation=dict(st.delegation),
            pparams=self.genesis.pparams,
            epoch=slot // self.genesis.epoch_length,
        )

    def _apply(self, ticked: TickedByronState, block,
               check_witnesses: bool) -> ByronState:
        hdr = getattr(block, "header", None)
        if hdr is not None and getattr(hdr, "is_ebb", False):
            # EBB: no ledger effect (Byron/EBBs.hs)
            return replace(ticked.state, tip_slot_=ticked.slot)
        v = self._scratch(ticked.state, ticked.slot)
        for raw in block.txs:
            self._apply_payload(v, raw, check_witnesses)
        return ByronState(
            utxo=v.utxo,
            delegation=v.delegation,
            fees=ticked.state.fees + v.fee_delta,
            tip_slot_=ticked.slot,
        )

    def apply_block(self, ticked: TickedByronState, block) -> ByronState:
        return self._apply(ticked, block, check_witnesses=True)

    def reapply_block(self, ticked: TickedByronState, block) -> ByronState:
        """Previously validated: skip witness crypto, still fold state
        (reapplyBlockLedgerResult)."""
        return self._apply(ticked, block, check_witnesses=False)

    def tip_slot(self, state: ByronState) -> int | None:
        return state.tip_slot_

    # -- mempool seam (HardForkLedger.mempool_view / apply_tx) -------------

    def mempool_view(self, state: ByronState, slot: int) -> ByronTxView:
        return self._scratch(state, slot)

    def apply_tx(self, view, tx_bytes: bytes):
        """Atomic-on-failure per-payload application. Accepts either a
        ByronTxView (node mempool path) or a bare utxo dict (legacy
        callers): the dict path gets a throwaway delegation scratch."""
        if isinstance(view, ByronTxView):
            scratch = ByronTxView(
                utxo=dict(view.utxo), delegation=dict(view.delegation),
                pparams=view.pparams, epoch=view.epoch,
                fee_delta=view.fee_delta,
            )
            self._apply_payload(scratch, tx_bytes, check_witnesses=True)
            view.utxo = scratch.utxo
            view.delegation = scratch.delegation
            view.fee_delta = scratch.fee_delta
            return view
        scratch = ByronTxView(
            utxo=dict(view), delegation={}, pparams=self.genesis.pparams,
            epoch=0,
        )
        p = decode_payload(tx_bytes)
        if not isinstance(p, ByronTx):
            raise ByronTxError("delegation cert outside a block body")
        self._apply_tx_rules(scratch, p, check_witnesses=True)
        return scratch.utxo

    # -- protocol view (PBFT's delegation map) -----------------------------

    def _pbft_view(self, st: ByronState) -> PBftLedgerView:
        """delegate vk -> genesis key INDEX (what PBftProtocol consumes);
        derived from the ledger's genesis->delegate map."""
        return PBftLedgerView({
            dvk: self._gk_index[gvk] for gvk, dvk in st.delegation.items()
        })

    def protocol_ledger_view(self, ticked: TickedByronState) -> PBftLedgerView:
        return self._pbft_view(ticked.state)

    def ledger_view_forecast_at(self, state: ByronState) -> Forecast:
        """PBFT delegation forecast: Byron's stability window is 2k
        slots (cardano-ledger-byron's kSlotSecurityParam); within it the
        delegation map in force is the tip's (immediate activation —
        module scope notes)."""
        at = state.tip_slot_ if state.tip_slot_ is not None else 0
        window = (
            self.genesis.stability_window
            if self.genesis.stability_window is not None
            else 2 * self.genesis.security_param
        )
        view = self._pbft_view(state)
        return Forecast(at=at, max_for=at + window, view_fn=lambda _s: view)

    def inspect(self, old: ByronState, new: ByronState) -> list:
        """InspectLedger: report delegation-map changes (the operator
        signal Byron's delegation payloads produce — byron
        Ledger/Inspect-analog; the reference logs proposal/update
        events, our Byron scope carries dcerts)."""
        from .inspect import ByronDelegationChanged

        changed = tuple(sorted(
            (gk.hex()[:16], old.delegation.get(gk, b"").hex()[:16],
             dvk.hex()[:16])
            for gk, dvk in new.delegation.items()
            if old.delegation.get(gk) != dvk
        ))
        if not changed:
            return []
        return [ByronDelegationChanged(
            message=f"delegation map changed for {len(changed)} genesis key(s)",
            changes=changed,
        )]
