"""Ledger abstraction: the tick/apply state machine over ledger states.

Reference: `Ouroboros.Consensus.Ledger.Abstract` (Ledger/Abstract.hs:74,
108) — `ApplyBlock`/`UpdateLedger` with `applyBlockLedgerResult` (full
checks), `reapplyBlockLedgerResult` (previously-validated fast path), and
the composites `tickThenApply` / `tickThenReapply` (:132,168); plus
`LedgerSupportsProtocol` (Ledger/SupportsProtocol.hs): `protocol_ledger_view`
and a bounded-horizon forecast of future ledger views (Forecast.hs).

A Ledger instance is an object describing ONE block type's ledger rules;
ledger STATES are immutable values it produces. Queries (Ledger/Query.hs)
are plain methods on the state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, Protocol as TyProtocol, TypeVar

St = TypeVar("St")


class LedgerError(Exception):
    """Block application failure (the ledger's STS rule violations)."""


@dataclass(frozen=True)
class OutsideForecastRange(Exception):
    at: int  # anchor slot of the forecast
    max_for: int  # first slot beyond the horizon
    for_slot: int  # requested slot


@dataclass(frozen=True)
class Forecast:
    """Bounded-horizon projection of ledger views (Forecast.hs:20-40)."""

    at: int  # anchor slot
    max_for: int  # exclusive horizon: views available for slots < max_for
    view_fn: Any  # slot -> LedgerView

    def forecast_for(self, slot: int):
        if slot >= self.max_for:
            raise OutsideForecastRange(self.at, self.max_for, slot)
        return self.view_fn(slot)


class Ledger(TyProtocol):
    """ApplyBlock + LedgerSupportsProtocol, instance-as-object."""

    def tick(self, state, slot: int):
        """applyChainTickLedgerResult: advance time, no block."""
        ...

    def apply_block(self, ticked_state, block):
        """applyBlockLedgerResult: full validation; raises LedgerError."""
        ...

    def reapply_block(self, ticked_state, block):
        """reapplyBlockLedgerResult: previously-validated, no checks."""
        ...

    def tip_slot(self, state) -> int | None:
        """GetTip: slot of the most recently applied block (None=genesis)."""
        ...

    def protocol_ledger_view(self, ticked_state):
        """LedgerView at the ticked state's slot."""
        ...

    def ledger_view_forecast_at(self, state) -> Forecast:
        """Forecast of ledger views anchored at the state's tip."""
        ...

    def tick_then_apply(self, state, block):
        """tickThenApply (Ledger/Abstract.hs:132)."""
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        """tickThenReapply (Ledger/Abstract.hs:168)."""
        return self.reapply_block(self.tick(state, block.slot), block)
