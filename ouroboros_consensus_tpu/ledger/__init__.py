"""Ledger layer: abstract interface, header validation, extended state, mock."""

from .abstract import Forecast, Ledger, LedgerError, OutsideForecastRange
from .extended import ExtLedger, ExtLedgerState, TickedExtLedgerState
from .header_validation import (
    AnnTip,
    HeaderEnvelopeError,
    HeaderState,
    TickedHeaderState,
    revalidate_header,
    tick_header_state,
    validate_envelope,
    validate_header,
)
