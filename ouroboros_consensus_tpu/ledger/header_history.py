"""HeaderStateHistory: k-deep anchored history of header states.

Reference: `Ouroboros.Consensus.HeaderStateHistory` — an AnchoredSeq of
header states over the recent chain, with `current`, `append`, `rewind`,
`trim` and `fromChain` (HeaderStateHistory.hs:62-146). The reference uses
it in two places this module serves too:

* the ChainSync client's `theirHeaderStateHistory` (Client.hs:291): the
  per-peer candidate keeps the state after every header so a
  roll_backward is an O(1) truncation (`miniprotocol/chainsync.py`'s
  Candidate subclasses this);
* header-state-at-a-recent-point queries on OUR chain (seeding a peer
  candidate at the intersection) without touching the LedgerDB's full
  ExtLedgerStates (`storage/chaindb.py` maintains one per ChainDB and
  answers `header_state_at` from it).

The structure is two parallel lists with the invariant
``len(states) == len(headers) + 1``: ``states[0]`` is the state at the
anchor (the intersection / the immutable tip), ``states[i+1]`` the state
after validating ``headers[i]``. Entries only need a ``.point``
attribute — block Headers and AnnTips both qualify. States are opaque:
the ChainSync client stores raw protocol chain-dep states, the ChainDB
stores full HeaderStates (tip + chain-dep state).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ..block.abstract import Point


@dataclass
class HeaderStateHistory:
    """Anchored header-state sequence with O(1) rollback and k-trimming.

    Invariant: len(states) == len(headers) + 1 — states[0] is the state
    at the anchor, states[i+1] the state after headers[i].
    """

    headers: list = field(default_factory=list)
    states: list = field(default_factory=list)
    # trim bound (HeaderStateHistory.hs `trim` trims to the security
    # parameter k): a long history holds O(k) state; rolling back deeper
    # than k fails. None = unbounded (test-only).
    k: int | None = None
    trimmed: bool = False  # anchor advanced past the original base
    # optional `settled(point) -> bool` gate: only entries the callback
    # approves may be trimmed (the ChainSync client sets this to "is the
    # block already adopted on OUR chain" — dropping a not-yet-fetched
    # header would orphan BlockFetch's anchor). None = always trimmable.
    settled: Any = None

    def __len__(self) -> int:
        return len(self.headers)

    def current(self):
        """Newest state (HeaderStateHistory.hs `current`)."""
        return self.states[-1]

    def tip_point(self) -> Point | None:
        return self.headers[-1].point if self.headers else None

    def reset(self, base_state) -> None:
        """Re-anchor at `base_state` with an empty suffix."""
        self.headers = []
        self.states = [base_state]
        self.trimmed = False

    def extend(self, entry, state) -> None:
        """`append` + trim-to-k (HeaderStateHistory.hs:99)."""
        self.headers.append(entry)
        self.states.append(state)
        self.trim()

    def trim(self) -> None:
        """Advance the anchor while the history exceeds k and its oldest
        entry is settled (HeaderStateHistory.hs `trim`). Called on
        extension AND by owners whose settling is asynchronous (the
        ChainSync client re-trims after BlockFetch adopts blocks)."""
        while self.k is not None and len(self.headers) > self.k:
            if self.settled is not None and not self.settled(
                self.headers[0].point
            ):
                break
            del self.headers[0]
            del self.states[0]
            self.trimmed = True

    def truncate_to(self, point: Point | None) -> bool:
        """`rewind` (HeaderStateHistory.hs:117): roll the suffix back to
        `point` (None = back to the anchor). False if the point is no
        longer in the history — including an anchor rollback after
        trimming (deeper than k)."""
        if point is None:
            if self.trimmed:
                return False
            del self.headers[:]
            del self.states[1:]
            return True
        for i in range(len(self.headers) - 1, -1, -1):
            if self.headers[i].point == point:
                del self.headers[i + 1 :]
                del self.states[i + 2 :]
                return True
        return False

    def rollback_n(self, n: int) -> bool:
        """Drop the newest n entries; False if n exceeds the history."""
        if n > len(self.headers):
            return False
        if n:
            del self.headers[-n:]
            del self.states[-n:]
        return True

    def state_at(self, point: Point):
        """Non-destructive lookup: the state AFTER the entry at `point`
        (newest-first scan — intersections cluster near the tip), or
        None if the point is not in the history."""
        for i in range(len(self.headers) - 1, -1, -1):
            if self.headers[i].point == point:
                return self.states[i + 1]
        return None

    @classmethod
    def from_chain(
        cls, protocol, view_for_slot, base_state, headers, k: int | None = None
    ) -> "HeaderStateHistory":
        """Recompute a history by folding `headers` from `base_state`
        (HeaderStateHistory.hs `fromChain` — used by tests and by
        clients re-seeding after a deep intersection change).
        `view_for_slot(slot)` supplies the ledger view forecast."""
        hh = cls(k=k)
        hh.reset(base_state)
        for h in headers:
            ticked = protocol.tick(view_for_slot(h.slot), h.slot, hh.current())
            hh.extend(h, protocol.update(h.to_view(), h.slot, ticked))
        return hh
