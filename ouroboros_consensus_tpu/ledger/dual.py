"""DualLedger: run an implementation ledger and a SPEC ledger in
lock-step, failing loudly on any disagreement.

Reference: `Ouroboros.Consensus.Ledger.Dual` — `DualBlock m a` pairs the
real Byron implementation with the executable `byron-spec-ledger`
specification (`src/byronspec/`), applied to the same blocks; divergence
is a conformance bug, surfaced immediately rather than as a consensus
split months later (driven by `byron-test/Test/ThreadNet/DualByron.hs`).

Here the pair is (MockLedger, SpecLedger). The spec is INDEPENDENTLY
WRITTEN small-step semantics with its own abstract state and its own
rule code: it decodes the wire bytes itself, computes tx ids itself
(hashlib, not the impl's hash helpers), and owns an abstract UTxO — no
impl state is consulted while it folds. Conformance is checked two ways
per tx, exactly the reference's applyHelper pairing:

  * VALIDITY agreement — impl and spec must accept/reject the same txs
    (one accepting while the other rejects is a DualLedgerMismatch);
  * STATE agreement — after each block the impl's UTxO and the spec's,
    projected to per-address balances (`agreeOnUTxO`-style), must match.

The DualLedger satisfies the same duck-typed ledger interface the
storage layer consumes (ledger/abstract.py shapes), so a ChainDB can run
entirely on the paired state — which is exactly what the DualByron
ThreadNet test does.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Mapping

from ..utils import cbor
from . import mock as mock_ledger
from .mock import LedgerError


class DualLedgerMismatch(AssertionError):
    """Impl and spec disagree — a conformance bug, never a valid chain
    outcome (the reference calls this a 'dual ledger assertion')."""


# ---------------------------------------------------------------------------
# The spec side: an independently written executable UTxO semantics
# ---------------------------------------------------------------------------


class SpecRejected(Exception):
    """The spec's own invalid-tx verdict (never escapes the pairing)."""


@dataclass(frozen=True)
class SpecState:
    """The spec's own abstract state: outpoint -> (owner, value)."""

    utxo: Mapping[tuple[bytes, int], tuple[bytes, int]]
    tip_slot_: int | None = None

    @property
    def balances(self) -> dict[bytes, int]:
        """Per-address totals — the agreement projection."""
        out: dict[bytes, int] = {}
        for addr, amt in self.utxo.values():
            out[addr] = out.get(addr, 0) + amt
        return out


class SpecLedger:
    """The executable specification, written from the wire format down:
    its own decoder, its own tx-id computation, its own rules. It shares
    nothing with MockLedger but the generic CBOR library (as byron-spec
    shares cardano-binary)."""

    def __init__(self, check_value_conservation: bool = True):
        self.check_value_conservation = check_value_conservation

    @staticmethod
    def _tx_id(tx_bytes: bytes) -> bytes:
        return hashlib.blake2b(tx_bytes, digest_size=32).digest()

    def genesis_state(self, initial_outputs) -> SpecState:
        return SpecState({
            (bytes(32), ix): (addr, amt)
            for ix, (addr, amt) in enumerate(initial_outputs)
        })

    def apply_tx(self, state: SpecState, tx_bytes: bytes) -> SpecState:
        try:
            # exactly-two unpack: extra trailing elements must be an
            # agreed rejection (the impl's decode_tx unpacks the same way)
            ins_o, outs_o = cbor.decode(tx_bytes)
            ins = [(bytes(i[0]), i[1]) for i in ins_o]
            outs = [(bytes(o[0]), o[1]) for o in outs_o]
            # int() coercion would ACCEPT whole floats the impl rejects,
            # turning an agreed rejection into a false mismatch
            if not all(isinstance(ix, int) for _t, ix in ins):
                raise SpecRejected("non-integer input index")
            if not all(isinstance(amt, int) for _a, amt in outs):
                raise SpecRejected("non-integer amount")
        except SpecRejected:
            raise
        except Exception as e:
            raise SpecRejected(f"undecodable: {e!r}") from e
        if len(set(ins)) != len(ins):
            raise SpecRejected("duplicate input")
        utxo = dict(state.utxo)
        consumed = 0
        for txin in ins:
            if txin not in utxo:
                raise SpecRejected(f"missing input {txin!r}")
            consumed += utxo.pop(txin)[1]
        produced = sum(amt for _a, amt in outs)
        if self.check_value_conservation and consumed != produced:
            raise SpecRejected(f"not conserved: {consumed} != {produced}")
        tid = self._tx_id(tx_bytes)
        for ix, (addr, amt) in enumerate(outs):
            utxo[(tid, ix)] = (addr, amt)
        return SpecState(utxo, state.tip_slot_)


# ---------------------------------------------------------------------------
# The pairing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DualState:
    impl: mock_ledger.MockState
    spec: SpecState

    # the storage layer reads .utxo for mempool anchoring: expose the
    # impl side (the reference's dualLedgerStateMain projection)
    @property
    def utxo(self):
        return self.impl.utxo


@dataclass(frozen=True)
class TickedDualState:
    state: DualState
    slot: int


def _project(utxo) -> dict[bytes, int]:
    """Impl state -> spec abstraction (per-address totals)."""
    out: dict[bytes, int] = {}
    for (addr, amt) in utxo.values():
        out[addr] = out.get(addr, 0) + amt
    return out


class DualLedger:
    """Ledger interface over the (impl, spec) pair."""

    def __init__(self, config: mock_ledger.MockConfig):
        self.config = config
        self.impl = mock_ledger.MockLedger(config)
        self.spec = SpecLedger(config.check_value_conservation)

    def _check_agreement(self, st: DualState, where: str) -> None:
        projected = _project(st.impl.utxo)
        if projected != dict(st.spec.balances):
            raise DualLedgerMismatch(
                f"{where}: impl projects {projected}, spec has "
                f"{dict(st.spec.balances)}"
            )

    # -- ledger interface ----------------------------------------------------

    def genesis_state(self, initial_outputs) -> DualState:
        st = DualState(
            self.impl.genesis_state(initial_outputs),
            self.spec.genesis_state(initial_outputs),
        )
        self._check_agreement(st, "genesis")
        return st

    def tick(self, state: DualState, slot: int) -> TickedDualState:
        return TickedDualState(state, slot)

    def apply_tx(self, utxo: dict, tx_bytes: bytes) -> dict:
        """Mempool path: impl-only (the spec pairs at BLOCK granularity,
        like the reference — DualBlock has no dual mempool)."""
        return self.impl.apply_tx(utxo, tx_bytes)

    def _apply(self, ticked: TickedDualState, block, check: bool) -> DualState:
        """Fold BOTH ledgers independently over the same txs, requiring
        validity agreement per tx (the reference applyHelper pairs the
        two outcomes) and state agreement per block."""
        utxo = dict(ticked.state.impl.utxo)
        spec = ticked.state.spec
        for tx in block.txs:
            impl_err = spec_err = None
            try:
                utxo = self.impl.apply_tx(utxo, tx)
            except LedgerError as e:
                impl_err = e
            try:
                spec = self.spec.apply_tx(spec, tx)
            except SpecRejected as e:
                spec_err = e
            if (impl_err is None) != (spec_err is None):
                raise DualLedgerMismatch(
                    f"block @{block.slot}: validity disagreement — "
                    f"impl: {impl_err!r}, spec: {spec_err!r}"
                )
            if impl_err is not None:
                raise impl_err  # both agree the tx is invalid
        out = DualState(
            mock_ledger.MockState(utxo, ticked.slot),
            SpecState(spec.utxo, block.slot),
        )
        if check:
            self._check_agreement(out, f"block @{block.slot}")
        return out

    def apply_block(self, ticked: TickedDualState, block) -> DualState:
        return self._apply(ticked, block, check=True)

    def reapply_block(self, ticked: TickedDualState, block) -> DualState:
        """Previously validated (LedgerDB replay): both sides still fold
        — their states must stay paired — but the agreement assertion
        is skipped, mirroring the reference's reapply (no checks)."""
        return self._apply(ticked, block, check=False)

    def tip_slot(self, state: DualState):
        return state.impl.tip_slot_

    def protocol_ledger_view(self, ticked: TickedDualState):
        return self.config.ledger_view

    def ledger_view_forecast_at(self, state: DualState):
        return self.impl.ledger_view_forecast_at(state.impl)

    def tick_then_apply(self, state: DualState, block) -> DualState:
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state: DualState, block) -> DualState:
        return self.reapply_block(self.tick(state, block.slot), block)

    def inspect(self, old: DualState, new: DualState) -> list:
        return []
