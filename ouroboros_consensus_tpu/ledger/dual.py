"""DualLedger: run an implementation ledger and a SPEC ledger in
lock-step, failing loudly on any disagreement.

Reference: `Ouroboros.Consensus.Ledger.Dual` — `DualBlock m a` pairs the
real Byron implementation with the executable `byron-spec-ledger`
specification (`src/byronspec/`), applied to the same blocks; divergence
is a conformance bug, surfaced immediately rather than as a consensus
split months later (driven by `byron-test/Test/ThreadNet/DualByron.hs`).

Here the pair is (MockLedger, SpecLedger): the impl tracks a full UTxO
map keyed by outpoint; the spec tracks only per-address balances — a
coarser, independently-written semantics. The agreement relation (the
reference's `agreeOnUTxO`-style projection) is "the impl's UTxO, summed
per address, equals the spec's balance table".

The DualLedger satisfies the same duck-typed ledger interface the
storage layer consumes (ledger/abstract.py shapes), so a ChainDB can run
entirely on the paired state — which is exactly what the DualByron
ThreadNet test does.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

from . import mock as mock_ledger
from .mock import LedgerError, decode_tx


class DualLedgerMismatch(AssertionError):
    """Impl and spec disagree — a conformance bug, never a valid chain
    outcome (the reference calls this a 'dual ledger assertion')."""


# ---------------------------------------------------------------------------
# The spec side: per-address balance accounting (independent semantics)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SpecState:
    balances: Mapping[bytes, int]  # addr -> total unspent value
    tip_slot_: int | None = None


class SpecLedger:
    """The executable specification: value moves between addresses;
    inputs are resolved through the IMPL's view of what they are worth
    (the spec abstracts outpoints away entirely)."""

    def genesis_state(self, initial_outputs) -> SpecState:
        bal: dict[bytes, int] = {}
        for addr, amt in initial_outputs:
            bal[addr] = bal.get(addr, 0) + amt
        return SpecState(bal)

    def apply_tx(self, state: SpecState, tx_bytes: bytes, resolve) -> SpecState:
        """`resolve(txin) -> (addr, amount)` supplies the input values
        (the spec's environment; byron-spec gets them from its own
        abstract UTxO — here the impl state is the oracle, which is fine
        because the CONSERVATION and balance bookkeeping are still
        checked independently)."""
        ins, outs = decode_tx(tx_bytes)
        bal = dict(state.balances)
        for txin in ins:
            addr, amt = resolve(txin)
            if bal.get(addr, 0) < amt:
                raise LedgerError(f"spec: {addr!r} underfunded")
            bal[addr] -= amt
            if not bal[addr]:
                del bal[addr]
        for addr, amt in outs:
            bal[addr] = bal.get(addr, 0) + amt
        return SpecState(bal, state.tip_slot_)


# ---------------------------------------------------------------------------
# The pairing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DualState:
    impl: mock_ledger.MockState
    spec: SpecState

    # the storage layer reads .utxo for mempool anchoring: expose the
    # impl side (the reference's dualLedgerStateMain projection)
    @property
    def utxo(self):
        return self.impl.utxo


@dataclass(frozen=True)
class TickedDualState:
    state: DualState
    slot: int


def _project(utxo) -> dict[bytes, int]:
    """Impl state -> spec abstraction (per-address totals)."""
    out: dict[bytes, int] = {}
    for (addr, amt) in utxo.values():
        out[addr] = out.get(addr, 0) + amt
    return out


class DualLedger:
    """Ledger interface over the (impl, spec) pair."""

    def __init__(self, config: mock_ledger.MockConfig):
        self.config = config
        self.impl = mock_ledger.MockLedger(config)
        self.spec = SpecLedger()

    def _check_agreement(self, st: DualState, where: str) -> None:
        projected = _project(st.impl.utxo)
        if projected != dict(st.spec.balances):
            raise DualLedgerMismatch(
                f"{where}: impl projects {projected}, spec has "
                f"{dict(st.spec.balances)}"
            )

    # -- ledger interface ----------------------------------------------------

    def genesis_state(self, initial_outputs) -> DualState:
        st = DualState(
            self.impl.genesis_state(initial_outputs),
            self.spec.genesis_state(initial_outputs),
        )
        self._check_agreement(st, "genesis")
        return st

    def tick(self, state: DualState, slot: int) -> TickedDualState:
        return TickedDualState(state, slot)

    def apply_tx(self, utxo: dict, tx_bytes: bytes) -> dict:
        """Mempool path: impl-only (the spec pairs at BLOCK granularity,
        like the reference — DualBlock has no dual mempool)."""
        return self.impl.apply_tx(utxo, tx_bytes)

    def _apply(self, ticked: TickedDualState, block, check: bool) -> DualState:
        """One incremental pass: the impl's UTxO fold IS the spec's
        input-resolution oracle (values read before each tx mutates)."""
        utxo = dict(ticked.state.impl.utxo)
        spec = ticked.state.spec
        for tx in block.txs:
            ins, _outs = decode_tx(tx)
            resolved = {i: utxo[i] for i in ins if i in utxo}
            utxo = self.impl.apply_tx(utxo, tx)
            spec = self.spec.apply_tx(spec, tx, resolved.__getitem__)
        out = DualState(
            mock_ledger.MockState(utxo, ticked.slot),
            SpecState(spec.balances, block.slot),
        )
        if check:
            self._check_agreement(out, f"block @{block.slot}")
        return out

    def apply_block(self, ticked: TickedDualState, block) -> DualState:
        return self._apply(ticked, block, check=True)

    def reapply_block(self, ticked: TickedDualState, block) -> DualState:
        """Previously validated (LedgerDB replay): both sides still fold
        — their states must stay paired — but the agreement assertion
        is skipped, mirroring the reference's reapply (no checks)."""
        return self._apply(ticked, block, check=False)

    def tip_slot(self, state: DualState):
        return state.impl.tip_slot_

    def protocol_ledger_view(self, ticked: TickedDualState):
        return self.config.ledger_view

    def ledger_view_forecast_at(self, state: DualState):
        return self.impl.ledger_view_forecast_at(state.impl)

    def tick_then_apply(self, state: DualState, block) -> DualState:
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state: DualState, block) -> DualState:
        return self.reapply_block(self.tick(state, block.slot), block)

    def inspect(self, old: DualState, new: DualState) -> list:
        return []
