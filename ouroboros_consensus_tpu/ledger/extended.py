"""Extended ledger state: ledger state ⊗ header state — the unit of
validation, the LedgerDB checkpoint, and the snapshot payload.

Reference: `Ouroboros.Consensus.Ledger.Extended` (Ledger/Extended.hs:53)
`ExtLedgerState {ledgerState, headerState}`; its ApplyBlock instance
(:123-159): tick = ledger tick + protocolLedgerView + tickHeaderState;
apply = ledger apply THEN validateHeader; reapply skips all checks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from . import header_validation as hv
from .abstract import Forecast, Ledger


@dataclass(frozen=True)
class ExtLedgerState:
    ledger_state: Any
    header_state: hv.HeaderState


@dataclass(frozen=True)
class TickedExtLedgerState:
    ticked_ledger_state: Any
    ledger_view: Any
    ticked_header_state: hv.TickedHeaderState


class ExtLedger:
    """ApplyBlock (ExtLedgerState blk) — pairs a Ledger with a protocol.

    Implements the same Ledger interface (ledger/abstract.py) so LedgerDB
    and ChainSel work uniformly over extended states.
    """

    def __init__(self, ledger: Ledger, protocol):
        self.ledger = ledger
        self.protocol = protocol

    def genesis(self, genesis_ledger_state) -> ExtLedgerState:
        return ExtLedgerState(
            genesis_ledger_state,
            hv.HeaderState(None, self.protocol.initial_state()),
        )

    def tick(self, state: ExtLedgerState, slot: int) -> TickedExtLedgerState:
        """Extended.hs:123-140: ledger tick, ledger view, header tick."""
        lt = self.ledger.tick(state.ledger_state, slot)
        view = self.ledger.protocol_ledger_view(lt)
        ht = hv.tick_header_state(self.protocol, view, slot, state.header_state)
        return TickedExtLedgerState(lt, view, ht)

    def apply_block(self, ticked: TickedExtLedgerState, block) -> ExtLedgerState:
        """Extended.hs:142-156: ledger apply then validateHeader."""
        ls = self.ledger.apply_block(ticked.ticked_ledger_state, block)
        hs = hv.validate_header(self.protocol, ticked.ticked_header_state, block.header)
        return ExtLedgerState(ls, hs)

    def reapply_block(self, ticked: TickedExtLedgerState, block) -> ExtLedgerState:
        """Extended.hs:159: no checks anywhere."""
        ls = self.ledger.reapply_block(ticked.ticked_ledger_state, block)
        hs = hv.revalidate_header(self.protocol, ticked.ticked_header_state, block.header)
        return ExtLedgerState(ls, hs)

    def tip_slot(self, state: ExtLedgerState) -> int | None:
        return self.ledger.tip_slot(state.ledger_state)

    def tip_point(self, state: ExtLedgerState):
        t = state.header_state.tip
        return None if t is None else t.point

    def ledger_view_forecast_at(self, state: ExtLedgerState) -> Forecast:
        return self.ledger.ledger_view_forecast_at(state.ledger_state)

    def tick_then_apply(self, state, block):
        return self.apply_block(self.tick(state, block.slot), block)

    def tick_then_reapply(self, state, block):
        return self.reapply_block(self.tick(state, block.slot), block)
