"""Alonzo-class era: the Mary rules extended with PHASE-2 SCRIPT
WITNESSES — executable spending/minting scripts with datums, redeemers,
execution-unit budgets, collateral, and the two-phase IsValid
validation that makes script failure consume collateral instead of
invalidating the block.

Reference: StandardAlonzo (`Shelley/Eras.hs:85-97`) and the
Mary→Alonzo `CanHardFork` step (`Cardano/CanHardFork.hs:273`); the
two-phase semantics (IsValid flag recomputed by validators, collateral
consumed on phase-2 failure) re-derived from cardano-ledger's Alonzo
UTXOS rule. The script language is deliberately simple (the task is
the *witnessing machinery*, not Plutus): a deterministic, metered
expression interpreter — see `eval_script`.

Script wire (extends the Allegra timelock tags 0-5):
  [6, expr]  -- phase-2 script; `expr` is an ouroscript term:
    [0, const]     literal int/bytes
    [1]            datum          [2]            redeemer
    [3, f]         context: f=0 interval start (-1 none), f=1 end,
                   f=2 signatory count, f=3 current ada fee
    [4, a, b] eq   [5, a, b] lt   [6, a, b] add  [7, a, b] and
    [8, a, b] or   [9, a] not     [10, a] blake2b_256
    [11, a] len    [12, keyhash]  signed-by
  A script PASSES iff it evaluates to a truthy int without exceeding
  the step budget. Every node costs 1 step; hashing costs 16.

Tx wire (era-tagged; mary.decode_tx CANNOT parse it):
  tx  = [ins, outs, fee, [start|null, end|null], certs, withdrawals,
         mint, collateral, scripts, keywits, datums, redeemers,
         budget, is_valid]
  out = [addr, value] | [addr, value, datum_hash/32]
  collateral = [input...]      -- key-locked, ada-only
  datums     = [datum_bytes...]
  redeemers  = [[purpose, index, term]...]  -- purpose 0 = spend (index
               into the tx's input list), 1 = mint (index into mint)
  budget     = declared execution units (steps)
  is_valid   = bool — the forger's phase-2 claim; every validator
               recomputes it and REJECTS the block on mismatch
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from fractions import Fraction
from typing import Mapping

from ..ops.host import ed25519 as host_ed25519
from ..ops.host.hashes import blake2b_256
from ..utils import cbor
from .allegra import (
    MissingWitness,
    ScriptError,
    decode_script,
    eval_timelock,
    is_script_addr,
    script_hash,
)
from .mary import (
    MaryLedger,
    MaryValue,
    MintError,
    _decode_value,
    _encode_value,
    mint_sig_data,
    policy_id,
)
from .shelley import (
    BadInputs,
    FeeTooSmall,
    MaxTxSizeExceeded,
    PParams,
    ShelleyState,
    ShelleyTxError,
    TxView,
    ValueNotConserved,
    tx_id,
)

PLUTUS_TAG = 6


class Phase2Error(ShelleyTxError):
    """Raised internally when a phase-2 script fails — callers convert
    it into the collateral-consuming path, never into block rejection."""


class IsValidMismatch(ShelleyTxError):
    """The forger's IsValid claim disagrees with recomputation — this
    DOES invalidate the block (Alonzo UTXOS rule)."""


class CollateralError(ShelleyTxError):
    pass


@dataclass(frozen=True)
class AlonzoPParams(PParams):
    """PParams + the Alonzo script-economics parameters."""

    price_exunit: Fraction = Fraction(1, 100)  # lovelace per step
    max_tx_exunits: int = 1_000_000
    collateral_percent: int = 150
    max_collateral_inputs: int = 3

    UPDATABLE = PParams.UPDATABLE + (
        "price_exunit", "max_tx_exunits", "collateral_percent",
        "max_collateral_inputs",
    )

    @classmethod
    def from_shelley(cls, pp: PParams, **overrides) -> "AlonzoPParams":
        base = {
            f: getattr(pp, f)
            for f in PParams.__dataclass_fields__  # noqa: SLF001
        }
        base.update(overrides)
        return cls(**base)


# ---------------------------------------------------------------------------
# The ouroscript interpreter (deterministic, metered)
# ---------------------------------------------------------------------------


@dataclass
class ScriptContext:
    datum: object  # decoded CBOR term or None
    redeemer: object
    start: int | None
    end: int | None
    signatories: frozenset
    fee: int


class _Budget:
    __slots__ = ("left",)

    def __init__(self, steps: int):
        self.left = steps

    def spend(self, n: int):
        self.left -= n
        if self.left < 0:
            raise Phase2Error("execution budget exceeded")


def eval_script(expr, ctx: ScriptContext, budget: _Budget):
    budget.spend(1)
    try:
        tag = int(expr[0])
    except Exception as e:
        raise Phase2Error(f"malformed script term: {e!r}") from e
    if tag == 0:
        return expr[1]
    if tag == 1:
        return ctx.datum
    if tag == 2:
        return ctx.redeemer
    if tag == 3:
        f = int(expr[1])
        if f == 0:
            return -1 if ctx.start is None else ctx.start
        if f == 1:
            return -1 if ctx.end is None else ctx.end
        if f == 2:
            return len(ctx.signatories)
        if f == 3:
            return ctx.fee
        raise Phase2Error(f"unknown context field {f}")
    if tag == 4:
        return int(
            eval_script(expr[1], ctx, budget)
            == eval_script(expr[2], ctx, budget)
        )
    if tag == 5:
        a = eval_script(expr[1], ctx, budget)
        b = eval_script(expr[2], ctx, budget)
        if not isinstance(a, int) or not isinstance(b, int):
            raise Phase2Error("lt on non-ints")
        return int(a < b)
    if tag == 6:
        a = eval_script(expr[1], ctx, budget)
        b = eval_script(expr[2], ctx, budget)
        if not isinstance(a, int) or not isinstance(b, int):
            raise Phase2Error("add on non-ints")
        return a + b
    if tag == 7:
        return int(
            bool(eval_script(expr[1], ctx, budget))
            and bool(eval_script(expr[2], ctx, budget))
        )
    if tag == 8:
        return int(
            bool(eval_script(expr[1], ctx, budget))
            or bool(eval_script(expr[2], ctx, budget))
        )
    if tag == 9:
        return int(not bool(eval_script(expr[1], ctx, budget)))
    if tag == 10:
        budget.spend(16)
        v = eval_script(expr[1], ctx, budget)
        if not isinstance(v, bytes):
            raise Phase2Error("hash on non-bytes")
        return blake2b_256(v)
    if tag == 11:
        v = eval_script(expr[1], ctx, budget)
        if not isinstance(v, bytes):
            raise Phase2Error("len on non-bytes")
        return len(v)
    if tag == 12:
        return int(bytes(expr[1]) in ctx.signatories)
    raise Phase2Error(f"unknown script op {tag}")


def run_script(script_bytes: bytes, ctx: ScriptContext,
               budget: _Budget) -> None:
    """Raise Phase2Error unless the script passes."""
    try:
        term = cbor.decode(script_bytes)
    except Exception as e:
        raise Phase2Error(f"undecodable script: {e!r}") from e
    if int(term[0]) != PLUTUS_TAG:
        raise Phase2Error("not a phase-2 script")
    result = eval_script(term[1], ctx, budget)
    if not (isinstance(result, int) and result):
        raise Phase2Error(f"script evaluated to {result!r}")


def plutus_script(expr) -> bytes:
    """Sign-side constructor: wrap an ouroscript term."""
    return cbor.encode([PLUTUS_TAG, expr])


def is_plutus(script_bytes: bytes) -> bool:
    try:
        return int(cbor.decode(script_bytes)[0]) == PLUTUS_TAG
    except Exception:
        return False


def datum_hash(datum_bytes: bytes) -> bytes:
    return blake2b_256(datum_bytes)


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


def _encode_out(p, s, v, dh=None):
    return [[p, s], _encode_value(v)] if dh is None else (
        [[p, s], _encode_value(v), dh]
    )


def encode_tx(ins, outs, fee=0, validity=(None, None), certs=(),
              withdrawals=(), mint=(), collateral=(), scripts=(),
              signers=(), datums=(), redeemers=(), budget=0,
              is_valid=True) -> bytes:
    """outs: [(payment, stake|None, value)] or
    [(payment, stake|None, value, datum_hash)]; redeemers:
    [(purpose, index, term)]."""
    outs_wire = [
        _encode_out(*o) if len(o) == 4 else _encode_out(o[0], o[1], o[2])
        for o in outs
    ]
    fields = [
        [list(i) for i in ins],
        outs_wire,
        fee,
        [validity[0], validity[1]],
        [list(c) for c in certs],
        [list(w) for w in withdrawals],
        [[vk, sg, [[n, q] for n, q in sorted(dict(am).items())]]
         for vk, sg, am in mint],
        [list(i) for i in collateral],
        [s for s in scripts],
    ]
    from .allegra import body_hash_of, make_key_witness

    bh = body_hash_of(fields)
    wits = [list(make_key_witness(seed, bh)) for seed in signers]
    return cbor.encode(fields + [
        wits,
        [d for d in datums],
        [[int(p), int(ix), t] for p, ix, t in redeemers],
        int(budget),
        bool(is_valid),
    ])


@dataclass(frozen=True)
class AlonzoTx:
    ins: tuple[tuple[bytes, int], ...]
    outs: tuple  # ((payment, stake|None[, datum_hash]), MaryValue)
    fee: int
    start: int | None
    end: int | None
    certs: tuple[tuple, ...]
    withdrawals: tuple[tuple[bytes, int], ...]
    mint: tuple
    collateral: tuple[tuple[bytes, int], ...]
    scripts: tuple[bytes, ...]
    keywits: tuple[tuple[bytes, bytes], ...]
    datums: tuple[bytes, ...]
    redeemers: tuple  # ((purpose, index, term)...)
    budget: int
    is_valid: bool
    outs_wire: tuple
    body_hash: bytes
    size: int


def _decode_out(o):
    addr, v = o[0], o[1]
    payment = bytes(addr[0])
    stake = None if addr[1] is None else bytes(addr[1])
    if len(o) >= 3 and o[2] is not None:
        return ((payment, stake, bytes(o[2])), _decode_value(v))
    return ((payment, stake), _decode_value(v))


def decode_tx(tx_bytes: bytes) -> AlonzoTx:
    try:
        (ins, outs, fee, validity, certs, wdrls, mint, coll, scripts,
         wits, datums, redeemers, budget, is_valid) = cbor.decode(tx_bytes)
        start, end = validity
        from .allegra import body_hash_of

        if wits:
            bh = body_hash_of(
                [ins, outs, fee, validity, certs, wdrls, mint, coll,
                 scripts]
            )
        else:
            bh = b""
        return AlonzoTx(
            ins=tuple((bytes(i[0]), int(i[1])) for i in ins),
            outs=tuple(_decode_out(o) for o in outs),
            fee=int(fee),
            start=None if start is None else int(start),
            end=None if end is None else int(end),
            certs=tuple(tuple(c) for c in certs),
            withdrawals=tuple((bytes(w[0]), int(w[1])) for w in wdrls),
            mint=tuple(
                (bytes(vk), None if sg is None else bytes(sg),
                 tuple((bytes(n), int(q)) for n, q in pairs))
                for vk, sg, pairs in mint
            ),
            collateral=tuple((bytes(i[0]), int(i[1])) for i in coll),
            scripts=tuple(bytes(s) for s in scripts),
            keywits=tuple((bytes(w[0]), bytes(w[1])) for w in wits),
            datums=tuple(bytes(d) for d in datums),
            redeemers=tuple(
                (int(r[0]), int(r[1]), r[2]) for r in redeemers
            ),
            budget=int(budget),
            is_valid=bool(is_valid),
            outs_wire=outs,
            body_hash=bh,
            size=len(tx_bytes),
        )
    except ShelleyTxError:
        raise
    except Exception as e:
        raise ShelleyTxError(f"malformed alonzo tx: {e!r}") from e


def translate_tx_from_mary(tx_bytes: bytes) -> bytes:
    """InjectTxs Mary→Alonzo: no collateral/datums/redeemers; classic
    mint groups carry verbatim; IsValid is trivially true. Witnessed
    txs cannot cross (key witnesses sign the era's body shape — the
    reference's InjectTxs is partial the same way)."""
    decoded = cbor.decode(tx_bytes)
    if len(decoded) == 7:
        ins, outs, fee, validity, certs, wdrls, mint = decoded
    else:
        ins, outs, fee, validity, certs, wdrls, mint, scripts, wits = decoded
        if scripts or wits:
            raise ShelleyTxError(
                "witnessed mary tx cannot cross the era boundary"
            )
    return cbor.encode([
        ins, outs, fee, validity, certs, wdrls, mint, [], [],
        [], [], [], 0, True,
    ])


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class AlonzoLedger(MaryLedger):
    """MaryLedger + the Alonzo deltas: phase-2 scripts (datums,
    redeemers, ExUnits, collateral) under two-phase IsValid validation.
    Everything below the tx layer is inherited."""

    _decode_tx = staticmethod(decode_tx)

    # -- era translation INTO Alonzo ---------------------------------------

    def translate_from_mary(self, prev: ShelleyState) -> ShelleyState:
        """Mary→Alonzo: values/snapshots/pots carry verbatim; the
        pparams widen with the script-economics fields
        (CanHardFork.hs:273 translateLedgerState MaryToAlonzo)."""
        pp = prev.pparams
        if not isinstance(pp, AlonzoPParams):
            pp = AlonzoPParams.from_shelley(pp)
        return replace(prev, pparams=pp)

    # -- phase-2 machinery (Babbage overrides the resolution seams) --------

    def _resolve_witnesses(self, view: TxView, tx: AlonzoTx):
        """(scripts_by_hash, datums_by_hash) from the witness set alone
        — Babbage widens this with reference inputs."""
        return (
            self.script_map(tx.scripts),
            {datum_hash(d): d for d in tx.datums},
        )

    def _datum_for(self, addr, datums_by_hash):
        """Datum term for a script-locked utxo entry (by hash only here;
        Babbage adds inline datums)."""
        dh = addr[2] if len(addr) > 2 else None
        if dh is None:
            raise ShelleyTxError(
                "phase-2 script output carries no datum hash"
            )
        datum = datums_by_hash.get(dh)
        if datum is None:
            raise MissingWitness(f"missing datum witness {dh.hex()[:8]}")
        try:
            return cbor.decode(datum)
        except Exception as e:
            raise ShelleyTxError(f"undecodable datum: {e!r}") from e

    def _phase2_jobs(self, view: TxView, tx: AlonzoTx, scripts_by_hash,
                     datums_by_hash):
        """Collect (script, datum_term, redeemer_term) for every phase-2
        witness the tx needs. Structural problems (missing script/datum/
        redeemer, non-script datum outputs) are PHASE-1 errors."""
        redeemer_of = {(p, ix): term for p, ix, term in tx.redeemers}
        jobs = []
        for ix, txin in enumerate(tx.ins):
            entry = view.utxo[txin]
            addr = entry[0]
            payment = addr[0]
            if not is_script_addr(payment):
                continue
            h = payment[1:]
            script = scripts_by_hash.get(h)
            if script is None:
                raise MissingWitness(
                    f"missing script witness for {h.hex()[:8]}"
                )
            if not is_plutus(script):
                continue  # timelock — phase-1, handled by Allegra check
            datum = self._datum_for(addr, datums_by_hash)
            if (0, ix) not in redeemer_of:
                raise MissingWitness(f"missing redeemer for input {ix}")
            jobs.append((script, datum, redeemer_of[(0, ix)]))
        for mx, (vk, sig, _pairs) in enumerate(tx.mint):
            if sig is None and is_plutus(vk):
                if (1, mx) not in redeemer_of:
                    raise MissingWitness(
                        f"missing redeemer for mint group {mx}"
                    )
                jobs.append((vk, None, redeemer_of[(1, mx)]))
        return jobs

    def _check_collateral(self, view: TxView, tx: AlonzoTx,
                          need_phase2: bool) -> int:
        pp = view.pparams
        if not need_phase2:
            return 0
        if not tx.collateral:
            raise CollateralError("phase-2 scripts but no collateral")
        if len(set(tx.collateral)) != len(tx.collateral):
            raise CollateralError("duplicate collateral input")
        if len(tx.collateral) > pp.max_collateral_inputs:
            raise CollateralError("too many collateral inputs")
        total = 0
        for txin in tx.collateral:
            if txin not in view.utxo:
                raise BadInputs(txin)
            addr, val = view.utxo[txin][0], view.utxo[txin][1]
            if is_script_addr(addr[0]):
                raise CollateralError("collateral must be key-locked")
            if isinstance(val, MaryValue) and val.assets:
                raise CollateralError("collateral must be ada-only")
            total += int(val)
        if total * 100 < tx.fee * pp.collateral_percent:
            raise CollateralError(
                f"collateral {total} below "
                f"{pp.collateral_percent}% of fee {tx.fee}"
            )
        return total

    def _consume_collateral(self, view: TxView, tx: AlonzoTx) -> None:
        """Phase-2 failure: ONLY the collateral moves (to the fee pot);
        the rest of the tx leaves no trace (Alonzo UTXOS scriptsInvalid)."""
        burned = 0
        for txin in tx.collateral:
            burned += int(view.utxo.pop(txin)[1])
        view.fee_delta += burned

    # -- the Alonzo UTXOW/UTXOS rules --------------------------------------

    def apply_tx(self, view: TxView, tx_bytes: bytes) -> TxView:
        return self._apply_decoded(view, decode_tx(tx_bytes), tx_bytes)

    def _apply_era_extras(self, scratch: TxView, tx, tx_bytes: bytes) -> int:
        """Deposit-taking rule families beyond certificates (none before
        Conway); returns the deposits taken."""
        return 0

    def _apply_decoded(self, view: TxView, tx, tx_bytes: bytes) -> TxView:
        pp = view.pparams
        if not tx.ins:
            raise ShelleyTxError("empty input set")
        if len(set(tx.ins)) != len(tx.ins):
            raise BadInputs(tx.ins[0])
        self.check_validity_interval(view, tx.start, tx.end)
        if tx.size > pp.max_tx_size:
            raise MaxTxSizeExceeded(tx.size, pp.max_tx_size)
        if tx.budget > pp.max_tx_exunits:
            raise ShelleyTxError(
                f"budget {tx.budget} exceeds era max {pp.max_tx_exunits}"
            )
        # fee covers the declared budget at the era's ExUnits price
        min_fee = (pp.min_fee_a * tx.size + pp.min_fee_b
                   + int(pp.price_exunit * tx.budget))
        if tx.fee < min_fee:
            raise FeeTooSmall(tx.fee, min_fee)
        if any(int(v) < 0 for _a, v in tx.outs):
            raise ShelleyTxError("negative output")

        consumed = 0
        consumed_assets: dict[tuple[bytes, bytes], int] = {}
        for txin in tx.ins:
            if txin not in view.utxo:
                raise BadInputs(txin)
            val = view.utxo[txin][1]
            consumed += int(val)
            if isinstance(val, MaryValue):
                for k, q in val.assets:
                    consumed_assets[k] = consumed_assets.get(k, 0) + q

        signatories = self.collect_signatories(tx.keywits, tx.body_hash)
        scripts_by_hash, datums_by_hash = self._resolve_witnesses(view, tx)
        # phase-1 script checks: timelocks on inputs (plutus inputs are
        # checked structurally here, executed in phase 2)
        for txin in tx.ins:
            payment = view.utxo[txin][0][0]
            if not is_script_addr(payment):
                continue
            h = payment[1:]
            script = scripts_by_hash.get(h)
            if script is None:
                raise MissingWitness(
                    f"missing script witness for {h.hex()[:8]}"
                )
            if not is_plutus(script):
                if not eval_timelock(
                    decode_script(script), signatories, tx.start, tx.end
                ):
                    raise ScriptError(
                        f"timelock evaluation failed for {h.hex()[:8]}"
                    )
        jobs = self._phase2_jobs(view, tx, scripts_by_hash, datums_by_hash)
        self._check_collateral(view, tx, bool(jobs))

        # phase 2: run the scripts; recompute IsValid and demand the
        # forger agreed (mismatch invalidates the BLOCK)
        phase2_ok = True
        budget = _Budget(tx.budget)
        ctx_base = dict(
            start=tx.start, end=tx.end, signatories=signatories, fee=tx.fee,
        )
        try:
            for script, datum, redeemer in jobs:
                run_script(
                    script,
                    ScriptContext(datum=datum, redeemer=redeemer, **ctx_base),
                    budget,
                )
        except Phase2Error:
            phase2_ok = False
        if phase2_ok != tx.is_valid:
            raise IsValidMismatch(
                f"forger claimed IsValid={tx.is_valid}, "
                f"recomputed {phase2_ok}"
            )
        if not phase2_ok:
            self._consume_collateral(view, tx)
            return view

        # FORGE: key policies as Mary; plutus policies already ran above;
        # timelock policies evaluate here
        minted: dict[tuple[bytes, bytes], int] = {}
        if tx.mint:
            sd = mint_sig_data(
                [list(i) for i in tx.ins], tx.outs_wire, tx.fee,
                (tx.start, tx.end),
            )
            for vk, sig, pairs in tx.mint:
                if sig is None:
                    pid = script_hash(vk)
                    if not is_plutus(vk) and not eval_timelock(
                        decode_script(vk), signatories, tx.start, tx.end
                    ):
                        raise MintError(
                            f"timelock policy failed for {pid.hex()[:8]}"
                        )
                else:
                    if not host_ed25519.verify(vk, sd, sig):
                        raise MintError(
                            f"bad minting-policy signature for "
                            f"{policy_id(vk).hex()[:8]}"
                        )
                    pid = policy_id(vk)
                for name, qty in pairs:
                    if qty == 0:
                        continue
                    minted[(pid, name)] = minted.get((pid, name), 0) + qty

        scratch = self._scratch_of(view)
        withdrawn = 0
        seen = set()
        for cred, amt in tx.withdrawals:
            if cred in seen:
                raise ShelleyTxError("duplicate withdrawal")
            seen.add(cred)
            if cred not in scratch.rewards:
                raise ShelleyTxError(f"unregistered: {cred.hex()[:8]}")
            if scratch.rewards[cred] != amt:
                raise ShelleyTxError(
                    f"must withdraw full balance {scratch.rewards[cred]}"
                )
            scratch.rewards[cred] = 0
            withdrawn += amt
        deposits_taken = refunds = 0
        for cert in tx.certs:
            try:
                dep, ref = self._apply_cert(scratch, cert)
            except ShelleyTxError:
                raise
            except Exception as e:
                raise ShelleyTxError(f"malformed certificate: {e!r}") from e
            deposits_taken += dep
            refunds += ref
        # era-extension hook (Conway governance): extra rule families
        # that take deposits ride the same conservation equation and
        # scratch/commit window as certificates
        deposits_taken += self._apply_era_extras(scratch, tx, tx_bytes)

        produced_out = sum(int(v) for _a, v in tx.outs)
        if (consumed + withdrawn + refunds
                != produced_out + tx.fee + deposits_taken):
            raise ValueNotConserved(
                consumed + withdrawn + refunds,
                produced_out + tx.fee + deposits_taken,
            )
        produced_assets: dict[tuple[bytes, bytes], int] = {}
        for _a, v in tx.outs:
            if isinstance(v, MaryValue):
                for k, q in v.assets:
                    produced_assets[k] = produced_assets.get(k, 0) + q
        lhs: dict[tuple[bytes, bytes], int] = dict(consumed_assets)
        for k, q in minted.items():
            lhs[k] = lhs.get(k, 0) + q
        lhs = {k: q for k, q in lhs.items() if q}
        if lhs != produced_assets:
            raise ValueNotConserved(
                sum(consumed_assets.values()) + sum(minted.values()),
                sum(produced_assets.values()),
            )

        tid = tx_id(tx_bytes)
        for txin in tx.ins:
            del view.utxo[txin]
        for ix, (addr, val) in enumerate(tx.outs):
            view.utxo[(tid, ix)] = (addr, val)
        self._commit_scratch(view, scratch, deposits_taken, refunds, tx.fee)
        return view

    # -- reapply (trusts the recorded IsValid flag) ------------------------

    def reapply_block(self, ticked, block):
        st = ticked.state
        view = self.mempool_view(st, ticked.slot)
        for tx_bytes in block.txs:
            tx = self._decode_tx(tx_bytes)
            if not tx.is_valid:
                self._consume_collateral(view, tx)
                continue
            tid = tx_id(tx_bytes)
            for txin in tx.ins:
                view.utxo.pop(txin, None)
            for ix, (addr, val) in enumerate(tx.outs):
                view.utxo[(tid, ix)] = (addr, val)
            for cred, _amt in tx.withdrawals:
                view.rewards[cred] = 0
            dep = ref = 0
            for cert in tx.certs:
                d, r = self._apply_cert(view, cert)
                dep += d
                ref += r
            view.deposit_delta += dep - ref
            view.fee_delta += tx.fee
        st = self._commit_block_view(st, view, ticked.slot)
        return self._count_block(st, block)
