"""JSON node config + genesis loading for the CLI tools.

Reference: `ouroboros-consensus-cardano/src/tools/Cardano/Node/`
(Types.hs + Protocol/{Byron,Shelley,Alonzo,Conway}.hs) — db-analyser and
db-synthesizer read a `config.json` pointing at per-era genesis files and
credential files (fixture: `test/tools-test/disk/config/config.json`),
from which `mkProtocolInfo` assembles the protocol configuration.

This framework's single-protocol analog:

  config.json            {"Protocol": "Praos",
                          "GenesisFile": "genesis.json",
                          "CredentialsFile": "credentials.json"?}
  genesis.json           protocol parameters + pool distribution
                         (verification side: what validation needs)
  credentials.json       signing seeds per pool (synthesizer side only,
                         the analog of the bulk credentials file
                         DBSynthesizer/Run.hs loads)

`write_genesis_files` is the inverse, emitted by db_synthesizer so a
synthesized chain carries its own config — the tools-test pipeline shape
(synthesize with config → analyse with the same config).
"""

from __future__ import annotations

import json
import os
from fractions import Fraction

from ..protocol.praos import PraosParams
from ..protocol.views import IndividualPoolStake, LedgerView
from ..testing.fixtures import PoolCredentials


def _params_to_json(p: PraosParams) -> dict:
    return {
        "slotsPerKESPeriod": p.slots_per_kes_period,
        "maxKESEvolutions": p.max_kes_evolutions,
        "securityParam": p.security_param,
        "activeSlotsCoeff": [
            p.active_slot_coeff.numerator, p.active_slot_coeff.denominator
        ],
        "epochLength": p.epoch_length,
        "kesDepth": p.kes_depth,
    }


def _params_from_json(o: dict) -> PraosParams:
    num, den = o["activeSlotsCoeff"]
    return PraosParams(
        slots_per_kes_period=o["slotsPerKESPeriod"],
        max_kes_evolutions=o["maxKESEvolutions"],
        security_param=o["securityParam"],
        active_slot_coeff=Fraction(num, den),
        epoch_length=o["epochLength"],
        kes_depth=o["kesDepth"],
    )


def write_genesis_files(
    dir_path: str,
    params: PraosParams,
    lview: LedgerView,
    pools: list[PoolCredentials] | None = None,
) -> str:
    """Write config.json + genesis.json (+ credentials.json when signing
    material is provided). Returns the config.json path."""
    os.makedirs(dir_path, exist_ok=True)
    genesis = {
        "params": _params_to_json(params),
        "poolDistr": [
            {
                "poolId": pid.hex(),
                "stake": [ips.stake.numerator, ips.stake.denominator],
                "vrfKeyHash": ips.vrf_key_hash.hex(),
            }
            for pid, ips in sorted(lview.pool_distr.items())
        ],
    }
    with open(os.path.join(dir_path, "genesis.json"), "w") as f:
        json.dump(genesis, f, indent=1, sort_keys=True)
    config = {"Protocol": "Praos", "GenesisFile": "genesis.json"}
    if pools is not None:
        creds = [
            {
                "coldSeed": p.cold_seed.hex(),
                "vrfSeed": p.vrf_seed.hex(),
                "kesSeed": p.kes_seed.hex(),
                "kesDepth": p.kes_depth,
            }
            for p in pools
        ]
        with open(os.path.join(dir_path, "credentials.json"), "w") as f:
            json.dump(creds, f, indent=1)
        config["CredentialsFile"] = "credentials.json"
    cpath = os.path.join(dir_path, "config.json")
    with open(cpath, "w") as f:
        json.dump(config, f, indent=1, sort_keys=True)
    return cpath


def load_config(config_path: str):
    """mkProtocolInfo analog: (params, ledger_view, pools|None)."""
    base = os.path.dirname(os.path.abspath(config_path))
    with open(config_path) as f:
        config = json.load(f)
    if config.get("Protocol", "Praos") != "Praos":
        raise ValueError(f"unsupported Protocol {config.get('Protocol')!r}")
    with open(os.path.join(base, config["GenesisFile"])) as f:
        genesis = json.load(f)
    params = _params_from_json(genesis["params"])
    lview = LedgerView(
        pool_distr={
            bytes.fromhex(e["poolId"]): IndividualPoolStake(
                Fraction(e["stake"][0], e["stake"][1]),
                bytes.fromhex(e["vrfKeyHash"]),
            )
            for e in genesis["poolDistr"]
        }
    )
    pools = None
    if "CredentialsFile" in config:
        with open(os.path.join(base, config["CredentialsFile"])) as f:
            creds = json.load(f)
        pools = [
            PoolCredentials(
                cold_seed=bytes.fromhex(c["coldSeed"]),
                vrf_seed=bytes.fromhex(c["vrfSeed"]),
                kes_seed=bytes.fromhex(c["kesSeed"]),
                kes_depth=c["kesDepth"],
            )
            for c in creds
        ]
    return params, lview, pools


# ---------------------------------------------------------------------------
# TextEnvelope credential files (Cardano.Api shim)
# ---------------------------------------------------------------------------

# The reference's tools read node credentials from TextEnvelope JSON
# files ({"type", "description", "cborHex"} — src/tools/Cardano/Api/,
# KeysShelley.hs / SerialiseTextEnvelope): one file per key. The same
# format here, with this framework's type strings.

_ENVELOPE_TYPES = {
    "cold": "ColdSigningKey_ed25519",
    "vrf": "VrfSigningKey_ecvrf25519",
    "kes": "KesSigningKey_compactsum",
}


def write_text_envelopes(dir_path: str, pool: PoolCredentials) -> dict:
    """cold.skey / vrf.skey / kes.skey, one TextEnvelope JSON each
    (operational certificates are issued at runtime from these keys —
    protocol/hotkey.issue_ocert). Returns {kind: path}."""
    from ..utils import cbor as _cbor

    os.makedirs(dir_path, exist_ok=True)
    paths = {}
    seeds = {"cold": pool.cold_seed, "vrf": pool.vrf_seed, "kes": pool.kes_seed}
    for kind, seed in seeds.items():
        payload = (
            _cbor.encode([seed, pool.kes_depth]) if kind == "kes"
            else _cbor.encode(seed)
        )
        env = {
            "type": _ENVELOPE_TYPES[kind],
            "description": f"{kind} signing key",
            "cborHex": payload.hex(),
        }
        p = os.path.join(dir_path, f"{kind}.skey")
        with open(p, "w") as f:
            json.dump(env, f, indent=1)
        paths[kind] = p
    return paths


def read_text_envelope(path: str, expected_type: str) -> bytes:
    """One envelope -> raw CBOR payload; type string is CHECKED (the
    reference fails on a type mismatch, SerialiseTextEnvelope)."""
    with open(path) as f:
        env = json.load(f)
    if env.get("type") != expected_type:
        raise ValueError(
            f"{path}: envelope type {env.get('type')!r}, "
            f"expected {expected_type!r}"
        )
    return bytes.fromhex(env["cborHex"])


def load_pool_from_envelopes(dir_path: str) -> PoolCredentials:
    from ..utils import cbor as _cbor

    cold = _cbor.decode(
        read_text_envelope(
            os.path.join(dir_path, "cold.skey"), _ENVELOPE_TYPES["cold"]
        )
    )
    vrf = _cbor.decode(
        read_text_envelope(
            os.path.join(dir_path, "vrf.skey"), _ENVELOPE_TYPES["vrf"]
        )
    )
    kes_seed, kes_depth = _cbor.decode(
        read_text_envelope(
            os.path.join(dir_path, "kes.skey"), _ENVELOPE_TYPES["kes"]
        )
    )
    return PoolCredentials(
        cold_seed=bytes(cold), vrf_seed=bytes(vrf),
        kes_seed=bytes(kes_seed), kes_depth=kes_depth,
    )


# ---------------------------------------------------------------------------
# Shelley genesis files (the reference's shelley-genesis.json shape:
# sgProtocolParams / sgInitialFunds / sgStaking — Node config points at
# it per era; cardano-node ShelleyGenesis + protocolInfoShelley)
# ---------------------------------------------------------------------------


def _frac_json(f):
    from fractions import Fraction

    if isinstance(f, Fraction):
        return [f.numerator, f.denominator]
    return f


def write_shelley_genesis(
    dir_path: str,
    genesis,  # ledger.shelley.ShelleyGenesis
    initial_funds,  # [(payment, stake|None, coin)]
    initial_pools=(),  # [shelley.PoolParams]
    initial_delegations=(),  # [(cred, pool_id)]
    filename: str = "shelley-genesis.json",
) -> str:
    """Write a Shelley genesis file (sgInitialFunds + sgStaking)."""
    from ..ledger import shelley as sh

    pp = genesis.pparams
    obj = {
        "protocolParams": {
            f: _frac_json(getattr(pp, f)) for f in sh.PParams.UPDATABLE
        },
        "epochLength": genesis.epoch_length,
        "stabilityWindow": genesis.stability_window,
        "maxSupply": genesis.max_supply,
        "updateQuorum": genesis.update_quorum,
        "genDelegs": [d.hex() for d in genesis.genesis_delegates],
        "initialFunds": [
            [p.hex(), None if s is None else s.hex(), c]
            for p, s, c in initial_funds
        ],
        "staking": {
            "pools": [
                {
                    "poolId": p.pool_id.hex(),
                    "vrfKeyHash": p.vrf_hash.hex(),
                    "pledge": p.pledge,
                    "cost": p.cost,
                    "margin": _frac_json(p.margin),
                    "rewardCred": p.reward_cred.hex(),
                    "owners": [o.hex() for o in p.owners],
                }
                for p in initial_pools
            ],
            "stake": [
                [c.hex(), pid.hex()] for c, pid in initial_delegations
            ],
        },
    }
    path = os.path.join(dir_path, filename)
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
    return path


def load_shelley_genesis(path: str):
    """-> (ShelleyLedger, genesis ShelleyState) — protocolInfoShelley."""
    from fractions import Fraction

    from ..ledger import shelley as sh

    with open(path) as f:
        obj = json.load(f)
    pp_kw = {}
    for k, v in obj["protocolParams"].items():
        pp_kw[k] = Fraction(v[0], v[1]) if isinstance(v, list) else int(v)
    genesis = sh.ShelleyGenesis(
        pparams=sh.PParams(**pp_kw),
        epoch_length=int(obj["epochLength"]),
        stability_window=int(obj["stabilityWindow"]),
        max_supply=int(obj["maxSupply"]),
        genesis_delegates=tuple(
            bytes.fromhex(d) for d in obj.get("genDelegs", [])
        ),
        update_quorum=int(obj.get("updateQuorum", 1)),
    )
    ledger = sh.ShelleyLedger(genesis)
    staking = obj.get("staking", {})
    pools = tuple(
        sh.PoolParams(
            pool_id=bytes.fromhex(p["poolId"]),
            vrf_hash=bytes.fromhex(p["vrfKeyHash"]),
            pledge=int(p["pledge"]),
            cost=int(p["cost"]),
            margin=(
                Fraction(p["margin"][0], p["margin"][1])
                if isinstance(p["margin"], list) else Fraction(p["margin"])
            ),
            reward_cred=bytes.fromhex(p["rewardCred"]),
            owners=tuple(bytes.fromhex(o) for o in p.get("owners", [])),
        )
        for p in staking.get("pools", [])
    )
    delegations = tuple(
        (bytes.fromhex(c), bytes.fromhex(pid))
        for c, pid in staking.get("stake", [])
    )
    state = ledger.genesis_state(
        [
            (bytes.fromhex(p), None if s is None else bytes.fromhex(s), c)
            for p, s, c in obj.get("initialFunds", [])
        ],
        initial_pools=pools,
        initial_delegations=delegations,
    )
    return ledger, state
