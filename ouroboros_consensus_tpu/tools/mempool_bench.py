"""mempool-bench: timed bulk tx additions against a mocked ledger.

Reference: `ouroboros-consensus/bench/mempool-bench/Main.hs:50` — the
"Just adding" benchmark adds batches of txs (CI sizes 10k and 1M) to a
mempool backed by a mocked ledger and reports per-batch wall time as
CSV/JSON for the dashboard (docs/website/docs/benchmarks/index.md).

Usage:  python -m ouroboros_consensus_tpu.tools.mempool_bench \
            [--sizes 10000,1000000] [--csv out.csv]
Prints one JSON line per size: {"n_txs": N, "seconds": s, "txs_per_s": r}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..ledger import mock as mock_ledger
from ..mempool import Mempool


def build_mempool(n_outputs: int) -> Mempool:
    ledger = mock_ledger.MockLedger(mock_ledger.MockConfig(None, 100))
    state = ledger.genesis_state(
        [(b"addr-%d" % i, 1) for i in range(n_outputs)]
    )
    # capacity out of the picture — the ledger fold is what's timed
    return Mempool(ledger, lambda: (state, 0), capacity_bytes=1 << 62)


def gen_txs(n: int) -> list[bytes]:
    """n independent single-input single-output txs (the benchmark's
    simple txs: every one validates against the genesis UTxO)."""
    return [
        mock_ledger.encode_tx([(bytes(32), i)], [(b"out-%d" % i, 1)])
        for i in range(n)
    ]


def bench_add_txs(n: int) -> dict:
    pool = build_mempool(n)
    txs = gen_txs(n)
    t0 = time.monotonic()
    accepted, rejected = pool.try_add_txs(txs)
    dt = time.monotonic() - t0
    assert not rejected, f"{len(rejected)} unexpected rejections"
    assert len(accepted) == n
    return {
        "n_txs": n,
        "seconds": round(dt, 4),
        "txs_per_s": round(n / dt) if dt else None,
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--sizes", default="10000,1000000",
        help="comma-separated batch sizes (reference CI: 10k and 1M)",
    )
    ap.add_argument("--csv", default=None, help="also append CSV rows here")
    args = ap.parse_args(argv)
    rows = []
    for size in (int(s) for s in args.sizes.split(",")):
        r = bench_add_txs(size)
        rows.append(r)
        print(json.dumps(r))
    if args.csv:
        with open(args.csv, "a") as f:
            for r in rows:
                f.write(f"{r['n_txs']},{r['seconds']},{r['txs_per_s']}\n")


if __name__ == "__main__":
    main()
