"""Cardano.Api shim: typed key roles + operational certificates.

Reference: the key/certificate machinery the reference vendors for its
tools — `src/tools/Cardano/Api/KeysShelley.hs` (1,221 LoC of key-role
newtypes: Payment/Stake/StakePool/GenesisDelegate keys, each with
SigningKey/VerificationKey, raw serialization, key hashes and
TextEnvelope types), `.../Cardano/Api/KeysPraos.hs` (VRF + KES roles),
and `.../Cardano/Api/OperationalCertificate.hs` (OperationalCertificate,
the issue counter, `issueOperationalCertificate`).

TPU-first design note: roles are DATA here (one registry row per role:
envelope strings + derivation + hash width), not one newtype pile per
role — the behavior matched is serialization, role type-checking at
load, key hashing, and the OpCert issue/verify cycle.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Callable

from ..ops.host import fast
from ..ops.host import kes as host_kes
from ..ops.host.ed25519 import verify as _ed25519_verify
from ..ops.host.hashes import blake2b_224, blake2b_256
from ..protocol.views import OCert
from ..utils import cbor as _cbor


# ---------------------------------------------------------------------------
# Key roles (KeysShelley.hs newtypes -> a role registry)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KeyRole:
    """One key role: its envelope type strings, how a verification key
    is derived from a signing seed, and how it is hashed.

    KeysShelley.hs gives each role `SigningKey`/`VerificationKey`
    instances plus `verificationKeyHash`; KeysPraos.hs the VRF/KES
    roles. `vk_hash` is Blake2b-224 for operator/address roles (KeyHash)
    and Blake2b-256 for VRF (hashVerKeyVRF).
    """

    name: str
    signing_type: str  # TextEnvelope "type" for the signing key
    verification_type: str  # TextEnvelope "type" for the verification key
    derive_vk: Callable[[bytes], bytes]
    vk_hash: Callable[[bytes], bytes]


def _kes_derive(seed: bytes, depth: int = host_kes.DEFAULT_DEPTH) -> bytes:
    return host_kes.derive_vk(seed, depth)


KEY_ROLES: dict[str, KeyRole] = {
    r.name: r
    for r in [
        # address roles (KeysShelley.hs PaymentKey/StakeKey)
        KeyRole("payment", "PaymentSigningKey_ed25519",
                "PaymentVerificationKey_ed25519",
                fast.ed25519_public, blake2b_224),
        KeyRole("stake", "StakeSigningKey_ed25519",
                "StakeVerificationKey_ed25519",
                fast.ed25519_public, blake2b_224),
        # operator roles (KeysShelley.hs StakePoolKey/GenesisDelegateKey)
        KeyRole("stake_pool", "StakePoolSigningKey_ed25519",
                "StakePoolVerificationKey_ed25519",
                fast.ed25519_public, blake2b_224),
        KeyRole("genesis_delegate", "GenesisDelegateSigningKey_ed25519",
                "GenesisDelegateVerificationKey_ed25519",
                fast.ed25519_public, blake2b_224),
        # forging roles (KeysPraos.hs VrfKey/KesKey)
        KeyRole("vrf", "VrfSigningKey_ecvrf25519",
                "VrfVerificationKey_ecvrf25519",
                fast.ed25519_public, blake2b_256),
        KeyRole("kes", "KesSigningKey_compactsum",
                "KesVerificationKey_compactsum",
                _kes_derive, blake2b_224),
    ]
}


@dataclass(frozen=True)
class SigningKey:
    role: KeyRole
    seed: bytes
    kes_depth: int | None = None  # KES only: the tree depth

    def verification_key(self) -> "VerificationKey":
        if self.role.name == "kes":
            depth = (
                self.kes_depth if self.kes_depth is not None
                else host_kes.DEFAULT_DEPTH
            )
            return VerificationKey(self.role, _kes_derive(self.seed, depth))
        return VerificationKey(self.role, self.role.derive_vk(self.seed))


@dataclass(frozen=True)
class VerificationKey:
    role: KeyRole
    vk: bytes

    def key_hash(self) -> bytes:
        """verificationKeyHash (KeysShelley.hs per-role instances)."""
        return self.role.vk_hash(self.vk)


def generate_signing_key(role_name: str, seed: bytes,
                         kes_depth: int | None = None) -> SigningKey:
    """deterministicSigningKey analog: role + 32-byte seed."""
    if len(seed) != 32:
        raise ValueError(f"signing seed must be 32 bytes, got {len(seed)}")
    return SigningKey(KEY_ROLES[role_name], seed, kes_depth)


# ---------------------------------------------------------------------------
# TextEnvelope serialization (SerialiseTextEnvelope / SerialiseAsCBOR)
# ---------------------------------------------------------------------------


def write_envelope(path: str, type_: str, description: str, payload: bytes) -> str:
    env = {"type": type_, "description": description, "cborHex": payload.hex()}
    with open(path, "w") as f:
        json.dump(env, f, indent=1)
    return path


def read_envelope(path: str, expected_type: str) -> bytes:
    """Type string CHECKED on load — the reference fails a mismatch
    (TextEnvelopeTypeError, SerialiseTextEnvelope)."""
    with open(path) as f:
        env = json.load(f)
    if env.get("type") != expected_type:
        raise ValueError(
            f"{path}: envelope type {env.get('type')!r}, "
            f"expected {expected_type!r}"
        )
    return bytes.fromhex(env["cborHex"])


def write_signing_key(path: str, sk: SigningKey) -> str:
    if sk.role.name == "kes":
        depth = (
            sk.kes_depth if sk.kes_depth is not None
            else host_kes.DEFAULT_DEPTH
        )
        payload = _cbor.encode([sk.seed, depth])
    else:
        payload = _cbor.encode(sk.seed)
    return write_envelope(
        path, sk.role.signing_type, f"{sk.role.name} signing key", payload
    )


def read_signing_key(path: str, role_name: str) -> SigningKey:
    role = KEY_ROLES[role_name]
    payload = _cbor.decode(read_envelope(path, role.signing_type))
    if role.name == "kes":
        seed, depth = payload
        return SigningKey(role, bytes(seed), int(depth))
    return SigningKey(role, bytes(payload))


def write_verification_key(path: str, vkey: VerificationKey) -> str:
    return write_envelope(
        path, vkey.role.verification_type,
        f"{vkey.role.name} verification key", _cbor.encode(vkey.vk),
    )


def read_verification_key(path: str, role_name: str) -> VerificationKey:
    role = KEY_ROLES[role_name]
    return VerificationKey(
        role, bytes(_cbor.decode(read_envelope(path, role.verification_type)))
    )


# ---------------------------------------------------------------------------
# Operational certificates (Cardano/Api/OperationalCertificate.hs)
# ---------------------------------------------------------------------------

OPCERT_TYPE = "NodeOperationalCertificate"
OPCERT_COUNTER_TYPE = "NodeOperationalCertificateIssueCounter"


def encode_ocert(ocert: OCert) -> bytes:
    """CBOR [kes_vk, counter, kes_period, sigma] — the reference's
    OperationalCertificate ToCBOR shape."""
    return _cbor.encode(
        [ocert.vk_hot, ocert.counter, ocert.kes_period, ocert.sigma]
    )


def decode_ocert(data: bytes) -> OCert:
    vk_hot, counter, kes_period, sigma = _cbor.decode(data)
    return OCert(bytes(vk_hot), int(counter), int(kes_period), bytes(sigma))


def write_ocert(path: str, ocert: OCert) -> str:
    return write_envelope(
        path, OPCERT_TYPE, "", encode_ocert(ocert)
    )


def read_ocert(path: str) -> OCert:
    return decode_ocert(read_envelope(path, OPCERT_TYPE))


@dataclass(frozen=True)
class OpCertIssueCounter:
    """The on-disk issue counter (OperationalCertificateIssueCounter):
    next issue number + the cold verification key it belongs to."""

    next_counter: int
    cold_vk: bytes


def write_counter(path: str, counter: OpCertIssueCounter) -> str:
    return write_envelope(
        path, OPCERT_COUNTER_TYPE,
        f"Next certificate issue number: {counter.next_counter}",
        _cbor.encode([counter.next_counter, counter.cold_vk]),
    )


def read_counter(path: str) -> OpCertIssueCounter:
    n, vk = _cbor.decode(read_envelope(path, OPCERT_COUNTER_TYPE))
    return OpCertIssueCounter(int(n), bytes(vk))


class OperationalCertIssueError(Exception):
    """issueOperationalCertificate errors: counter file for a different
    cold key (OperationalCertKeyMismatch)."""


def issue_operational_certificate(
    cold_sk: SigningKey,
    counter: OpCertIssueCounter,
    kes_vk: bytes,
    kes_period: int,
) -> tuple[OCert, OpCertIssueCounter]:
    """issueOperationalCertificate: sign (kes_vk, counter, period) with
    the cold key; the caller persists the bumped counter. Fails if the
    counter file belongs to a different cold key."""
    cold_vk = fast.ed25519_public(cold_sk.seed)
    if counter.cold_vk != cold_vk:
        raise OperationalCertIssueError(
            "issue counter belongs to a different cold key"
        )
    oc = OCert(kes_vk, counter.next_counter, kes_period, b"")
    sigma = fast.ed25519_sign(cold_sk.seed, oc.signable())
    return (
        OCert(kes_vk, counter.next_counter, kes_period, sigma),
        OpCertIssueCounter(counter.next_counter + 1, cold_vk),
    )


def verify_operational_certificate(ocert: OCert, cold_vk: bytes) -> bool:
    """The OCERT check's signature leg (Praos.hs:585-606 host twin):
    does the cold key certify this KES vk/counter/period?"""
    return _ed25519_verify(cold_vk, ocert.signable(), ocert.sigma)


# ---------------------------------------------------------------------------
# Node credential bundles (the gen-node-keys cycle the reference's
# tools-test exercises: cold/vrf/kes keys + opcert + counter on disk)
# ---------------------------------------------------------------------------


def generate_node_keys(
    dir_path: str, seeds: dict[str, bytes], kes_depth: int = host_kes.DEFAULT_DEPTH
) -> dict[str, str]:
    """Write a full node credential set: cold(.skey/.vkey/.counter),
    vrf, kes, and an opcert issued for KES period 0. Returns
    {artifact: path}."""
    os.makedirs(dir_path, exist_ok=True)
    paths = {}
    cold = generate_signing_key("stake_pool", seeds["cold"])
    vrf = generate_signing_key("vrf", seeds["vrf"])
    kes = generate_signing_key("kes", seeds["kes"], kes_depth)
    for name, sk in [("cold", cold), ("vrf", vrf), ("kes", kes)]:
        paths[f"{name}.skey"] = write_signing_key(
            os.path.join(dir_path, f"{name}.skey"), sk
        )
        paths[f"{name}.vkey"] = write_verification_key(
            os.path.join(dir_path, f"{name}.vkey"), sk.verification_key()
        )
    counter = OpCertIssueCounter(0, cold.verification_key().vk)
    ocert, counter = issue_operational_certificate(
        cold, counter, kes.verification_key().vk, kes_period=0
    )
    paths["opcert"] = write_ocert(os.path.join(dir_path, "node.opcert"), ocert)
    paths["counter"] = write_counter(
        os.path.join(dir_path, "cold.counter"), counter
    )
    return paths


def load_node_keys(dir_path: str):
    """-> (cold SigningKey, vrf SigningKey, kes SigningKey, OCert,
    OpCertIssueCounter), verifying the opcert against the cold key."""
    cold = read_signing_key(os.path.join(dir_path, "cold.skey"), "stake_pool")
    vrf = read_signing_key(os.path.join(dir_path, "vrf.skey"), "vrf")
    kes = read_signing_key(os.path.join(dir_path, "kes.skey"), "kes")
    ocert = read_ocert(os.path.join(dir_path, "node.opcert"))
    counter = read_counter(os.path.join(dir_path, "cold.counter"))
    if not verify_operational_certificate(
        ocert, cold.verification_key().vk
    ):
        raise OperationalCertIssueError("opcert signature invalid")
    return cold, vrf, kes, ocert, counter
