"""db-synthesizer: forge a synthetic Praos chain as fast as possible.

Reference: `Cardano.Tools.DBSynthesizer` — the `runForge` loop
(Tools/DBSynthesizer/Forging.hs:54-57 "mirrors the forging loop from
NodeKernel") minus clock and network: per slot, check leadership for every
credential, forge and append the winner's block directly to the
ImmutableDB, threading the protocol state with `reupdate` (the trusted,
crypto-free path — we produced the signatures ourselves).

Limits mirror the reference's `ForgeLimit` (Types.hs): slot count, block
count, or epoch count.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from fractions import Fraction

from ..block.forge import evaluate_vrf, forge_block
from ..protocol import nonces, praos
from ..protocol.leader import check_leader_value
from ..protocol.praos import PraosParams, PraosState
from ..protocol.views import LedgerView
from ..storage.immutable import ImmutableDB
from ..testing import fixtures


@dataclass(frozen=True)
class ForgeLimit:
    """Stop condition (exactly one should be set). Types.hs ForgeLimit."""

    slots: int | None = None
    blocks: int | None = None
    epochs: int | None = None


@dataclass
class ForgeResult:
    """Counters the reference prints at the end of a run."""

    n_slots: int = 0
    n_blocks: int = 0
    wall_s: float = 0.0
    final_state: PraosState | None = None


def default_params(kes_depth: int = 7) -> PraosParams:
    """Benchmark-chain parameters: mainnet-shaped ratios scaled down so
    a synthetic chain crosses epochs (stability windows stay non-trivial)."""
    return PraosParams(
        slots_per_kes_period=3600,
        max_kes_evolutions=62,
        security_param=108,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=4320,
        kes_depth=kes_depth,
    )


def make_credentials(n_pools: int, kes_depth: int = 7):
    pools = [fixtures.make_pool(i, kes_depth=kes_depth) for i in range(n_pools)]
    return pools, fixtures.make_ledger_view(pools)


_VRF_BUCKET = 4096


def _prove_span(pools, slots, eta0):
    """Batched device VRF evaluation for every (slot, pool) pair of a
    span. Returns {(slot, pool_index): PraosIsLeader}. The VRF is the
    only per-header forging cost with no chain dependency (alpha =
    InputVRF(slot, eta0), Praos/VRF.hs:47), so it batches across the
    whole span on device; header assembly + KES signing stay sequential
    because each body embeds the previous header's hash (signature
    included).
    """
    from ..protocol.praos import PraosIsLeader

    from ..ops import ecvrf_batch

    pairs = [(s, i) for s in slots for i in range(len(pools))]
    out = {}
    for lo in range(0, len(pairs), _VRF_BUCKET):
        part = pairs[lo : lo + _VRF_BUCKET]
        seeds = [pools[i].vrf_seed for _s, i in part]
        alphas = [nonces.mk_input_vrf(s, eta0) for s, _i in part]
        # pad to the bucket so the jit caches exactly one shape
        pad = _VRF_BUCKET - len(part)
        if pad:
            seeds.extend([seeds[0]] * pad)
            alphas.extend([alphas[0]] * pad)
        proofs, betas = ecvrf_batch.prove_batch(seeds, alphas)
        for (s, i), proof, beta in zip(part, proofs, betas):
            out[(s, i)] = PraosIsLeader(beta.tobytes(), proof.tobytes())
    return out


def synthesize(
    db_path: str,
    params: PraosParams,
    pools: list[fixtures.PoolCredentials],
    lview: LedgerView,
    limit: ForgeLimit,
    txs_per_block: int = 0,
    chunk_size: int = 21600,
    vrf_backend: str = "auto",
    trace=lambda s: None,
    ledger_view_for_epoch=None,  # epoch -> LedgerView (epoch-varying
    # stake: forge against the distribution validators will derive);
    # None = the constant `lview`
    txs_for_block=None,  # (slot, block_no) -> tuple[bytes, ...]
    ledger=None,  # LEDGER IN THE LOOP: fold this ledger (view_for_epoch
    genesis_state=None,  # + tick_then_apply) over the forged blocks and
    # derive each epoch's election view from ITS stake snapshots — the
    # forging twin of db_analyser's ledger-derived revalidation (so
    # Shelley-backed chains synthesize at tool level)
    resume: bool = False,  # continue forging into a NON-empty DB: the
    # store is reopened dirty-aware (deep revalidation + repair when
    # the last writer crashed), the protocol state rebuilt by
    # replaying the surviving chain with the trusted reupdate path,
    # and forging continues from the tip — forging is deterministic,
    # so a killed-and-resumed synthesis converges on the byte-
    # identical chain an uninterrupted run produces
    network_magic: int | None = None,  # chain magic for the DB marker
) -> ForgeResult:
    """The forging loop (Forging.hs:57): tick → leader check per
    credential → forge → append, until the limit trips.

    The writer speaks the store crash protocol (storage/guard.py): DB
    lock held for the whole forge, chain-magic marker written on
    first open, clean-shutdown marker absent while forging and written
    back after the final flush — a killed synthesis leaves a DIRTY
    store whose next open deep-revalidates and repairs.

    vrf_backend: "device" evaluates VRFs in epoch-span batches on the
    accelerator; "host" per-slot on the CPU; "auto" picks device when
    the run is big enough to amortize the kernel compile."""
    from ..storage import guard as _guard_mod
    from ..storage.open import open_repair_store

    if resume and ledger is not None:
        raise ValueError(
            "resume is not supported in ledger mode (the ledger fold "
            "has its own snapshot/replay machinery)"
        )
    os.makedirs(db_path, exist_ok=True)
    # open as a READER first: the non-empty-DB refusal below must be
    # side-effect-free (an operator mistake may not dirty a healthy
    # store); promote_writer() adopts the writer protocol only once we
    # have committed to mutating
    guard = _guard_mod.StoreGuard(
        db_path, network_magic=network_magic, writer=False
    )
    guard.open()
    try:
        if resume:
            # a resume is committed to writing: adopt the writer
            # protocol up front so any tail repair the open computes
            # happens under the writer guard (never a reader's)
            guard.promote_writer()
            if guard.opened_dirty:
                # the previous writer crashed: reopen with the full
                # ValidateAllChunks + repair scan (torn tails truncated
                # + quarantined, lagging indices rebuilt) before
                # trusting the tip
                imm = open_repair_store(db_path, chunk_size=chunk_size)
            else:
                imm = ImmutableDB(
                    os.path.join(db_path, "immutable"),
                    chunk_size=chunk_size,
                )
        else:
            # repair=False: this probe happens under the READER guard —
            # the non-empty refusal below must be side-effect-free (an
            # operator mistake may not touch somebody else's dirty tail)
            imm = ImmutableDB(
                os.path.join(db_path, "immutable"), chunk_size=chunk_size,
                repair=False,
            )
            if not imm.is_empty:
                raise RuntimeError(
                    f"refusing to forge into non-empty DB at {db_path} "
                    "(pass resume=True to continue a crashed synthesis)"
                )
            if imm.repairs:
                # "empty" came out of a read-only scan that COMPUTED
                # repairs (e.g. a wholly-torn first chunk reparsed to
                # zero entries): forging here would append after
                # un-truncated garbage
                raise RuntimeError(
                    f"refusing to forge into corrupted store at "
                    f"{db_path} (pass resume=True to repair and "
                    "continue, or run db_truncater --to-last-valid)"
                )
            guard.promote_writer()
            imm.prepare_write()  # the probe was read-only by design
        out = _synthesize_locked(
            imm, db_path, params, pools, lview, limit, txs_per_block,
            vrf_backend, trace, ledger_view_for_epoch, txs_for_block,
            ledger, genesis_state,
        )
    except BaseException:
        # a killed/raising forge leaves DIRTY; the pre-writer refusal
        # path releases the lock without having touched any marker
        guard.close(clean=False)
        raise
    guard.close(clean=True)
    return out


# trusted-fold memo: a resume whose deep-open confirms the tip this
# process itself forged skips the whole-chain reupdate replay. Keyed by
# the store path; the (tip slot, tip hash) check makes a stale entry —
# another writer, an external truncation — fall through to the replay.
# The stored tuple is EXACTLY what _replay_forged_state would return.
_REPLAY_MEMO: dict[str, tuple] = {}


def _replay_forged_state(params, lview, imm):
    """Rebuild the forging state from a surviving chain: the trusted
    reupdate fold (we forged these signatures ourselves — exactly the
    reference's crypto-free path; tick/reupdate never read the stake
    distribution, so the constant view serves every epoch). Yields the
    PraosState at the tip plus the per-pool ocert counters, tip hash,
    next block number and next slot — everything the forging loop
    threads."""
    from ..block.praos_block import Block

    st = PraosState()
    prev_hash = None
    block_no = 0
    slot = 0
    for _entry, raw in imm.stream_all():
        b = Block.from_bytes(raw)
        ticked = praos.tick(params, lview, b.slot, st)
        st = praos.reupdate(params, b.header.to_view(), b.slot, ticked)
        prev_hash = b.hash_
        block_no = b.block_no + 1
        slot = b.slot + 1
    # reupdate keyed these by hash_key(vk_cold) == pool.pool_id
    return st, dict(st.ocert_counters), prev_hash, block_no, slot


def _forge_pipeline(
    imm, params, pools, lview, limit, res, st, prev_hash, block_no,
    slot, counters, ledger_view_for_epoch, txs_per_block, txs_for_block,
    engine, trace,
):
    """The batched forging fast path: elect whole slot windows in one
    sweep (device or batched-host, protocol/forge.py), then run the
    sequential assembly tail over just the elected slots. Byte- and
    state-identical to the per-slot loop below for the same inputs
    (tests/test_forge.py holds the equation); returns the threaded
    (st, prev_hash, block_no, slot)."""
    from ..protocol import batch as pbatch
    from ..protocol import forge as forge_mod
    from ..testing import chaos

    asm = forge_mod.BlockAssembler(params, pools)
    stg = forge_mod.stage_pools(pools) if engine == "device" else None
    tracer = pbatch.BATCH_TRACER

    def done() -> bool:
        if limit.slots is not None and slot >= limit.slots:
            return True
        if limit.blocks is not None and block_no >= limit.blocks:
            return True
        if limit.epochs is not None and params.epoch_of(slot) >= limit.epochs:
            return True
        return False

    while not done():
        lv_now = (
            ledger_view_for_epoch(params.epoch_of(slot))
            if ledger_view_for_epoch is not None
            else lview
        )
        # eta0 is epoch-constant: one tick at the window start serves
        # the whole (epoch-clamped) window's elections; the per-block
        # reupdate below re-ticks at each forged slot exactly as the
        # reference loop does
        ticked0 = praos.tick(params, lv_now, slot, st)
        eta0 = ticked0.state.epoch_nonce
        epoch_end = (params.epoch_of(slot) + 1) * params.epoch_length
        wend = min(epoch_end, slot + forge_mod.window_slots(len(pools)))
        if limit.slots is not None:
            wend = min(wend, limit.slots)
        if limit.blocks is not None:
            # don't elect far past where the block limit will trip:
            # ~1/f slots per block, padded 2x + a margin
            need = limit.blocks - block_no
            est = int(2 * need / float(params.active_slot_coeff)) + 64
            wend = min(wend, slot + est)
        wend = max(wend, slot + 1)
        windex = forge_mod.next_window_index()
        thr = forge_mod.pool_thresholds(params, lv_now, pools)
        t_el = time.monotonic()
        elected = forge_mod.elect_window_recovering(
            params, pools, stg, thr, range(slot, wend), eta0, engine,
            lv_now, windex, tracer=tracer,
        )
        elect_s = time.monotonic() - t_el
        if engine == "device" and elected:
            # pre-sign the window's deduped OCert issues through the
            # forge_sign graph (byte-identical to the host signer)
            triples = {
                (el.pool, counters.get(pools[el.pool].pool_id, 0),
                 asm.ocert_window(el.slot))
                for el in elected
            }
            missing = {t for t in triples if t not in asm._ocerts}
            if missing:
                asm.prime_ocerts(
                    forge_mod.sign_ocerts_batch(pools, missing)
                )
        t_asm = time.monotonic()
        signed = 0
        last_forged = slot
        for el in elected:
            if limit.blocks is not None and block_no >= limit.blocks:
                break
            s = el.slot
            ticked = praos.tick(params, lv_now, s, st)
            n = counters.get(pools[el.pool].pool_id, 0)
            if txs_for_block is not None:
                txs = tuple(txs_for_block(s, block_no))
            else:
                txs = tuple(
                    b"tx-%d-%d" % (s, i) for i in range(txs_per_block)
                )
            block = asm.forge(
                el.pool, slot=s, block_no=block_no, prev_hash=prev_hash,
                txs=txs, ocert_counter=n, is_leader=el.is_leader,
            )
            imm.append_block(s, block_no, block.hash_, block.bytes_)
            st = praos.reupdate(params, block.header.to_view(), s, ticked)
            counters[pools[el.pool].pool_id] = n
            prev_hash = block.hash_
            block_no += 1
            last_forged = s
            signed += 1
            res.n_blocks += 1
            chaos.fire("forge")
            if res.n_blocks % 1000 == 0:
                trace(f"forged {res.n_blocks} blocks to slot {s}")
        if limit.blocks is not None and block_no >= limit.blocks:
            # the reference loop stops right after the tripping block's
            # slot — count only the slots up to and including it
            consumed = last_forged + 1 - slot
        else:
            consumed = wend - slot
        slot += consumed
        res.n_slots += consumed
        if tracer is not None:
            from ..utils.trace import ForgeSpan

            tracer(ForgeSpan(
                index=windex, engine=engine, slots=consumed,
                pairs=(wend - (slot - consumed)) * len(pools),
                elected=len(elected), signed=signed, elect_s=elect_s,
                assemble_s=time.monotonic() - t_asm,
            ))
    return st, prev_hash, block_no, slot


def _synthesize_locked(
    imm, db_path, params, pools, lview, limit, txs_per_block,
    vrf_backend, trace, ledger_view_for_epoch, txs_for_block,
    ledger, genesis_state,
) -> ForgeResult:

    from ..protocol import forge as forge_mod

    n_target = limit.slots or limit.blocks or (
        (limit.epochs or 0) * params.epoch_length
    )
    engine = forge_mod.engine_from_env(vrf_backend)
    if ledger is not None:
        # the ledger fold derives each epoch's view from state the loop
        # itself threads — the whole-window election has no view to
        # elect against yet, so ledger mode stays on the per-slot loop
        engine = "loop"
    if vrf_backend == "auto":
        # host signing runs through the native C library (ops/host/fast)
        # at ~0.3 ms/proof — robust on every platform; the device span
        # prover stays opt-in (vrf_backend="device") for chips where the
        # sign-side kernels compile fast
        vrf_backend = "host"

    res = ForgeResult()
    t0 = time.monotonic()
    st = PraosState()
    prev_hash: bytes | None = None
    block_no = 0
    slot = 0
    counters: dict[bytes, int] = {}
    if not imm.is_empty:
        # resume: rebuild the forging state from the surviving (just
        # deep-validated/repaired) chain and continue from the tip —
        # forging is deterministic, so the resumed chain converges on
        # the uninterrupted run's bytes
        tip = imm.tip()
        memo_key = os.path.realpath(db_path)
        memo = _REPLAY_MEMO.get(memo_key)
        if (
            memo is not None
            and memo[0] == tip.slot
            and memo[1] == tip.hash_
        ):
            st, counters, prev_hash, block_no, slot = (
                memo[2], dict(memo[3]), memo[4], memo[5], memo[6],
            )
            trace(f"resuming synthesis at slot {slot} "
                  f"({block_no} blocks survive, memoized fold)")
        else:
            st, counters, prev_hash, block_no, slot = _replay_forged_state(
                params, lview, imm
            )
            trace(f"resuming synthesis at slot {slot} "
                  f"({block_no} blocks survive)")

    if ledger is not None:
        if genesis_state is None:
            raise ValueError("ledger mode needs genesis_state")
        if ledger_view_for_epoch is not None:
            raise ValueError("pass ledger OR ledger_view_for_epoch")
        if txs_per_block and txs_for_block is None:
            raise ValueError(
                "ledger mode folds every tx through the ledger rules: "
                "placeholder txs_per_block txs would not decode — "
                "supply real txs via txs_for_block"
            )
        ledger_epoch_len = getattr(
            getattr(ledger, "genesis", None), "epoch_length", None
        )
        if ledger_epoch_len is not None and ledger_epoch_len != params.epoch_length:
            raise ValueError(
                f"ledger epoch_length {ledger_epoch_len} != protocol "
                f"epoch_length {params.epoch_length}: the two epoch "
                "clocks would silently desync"
            )
        lst = genesis_state
        _view_cache: dict[int, object] = {}

        def ledger_view_for_epoch(epoch):  # noqa: F811 — the seam above
            # epoch-constant: derive once per epoch, not per slot
            if epoch not in _view_cache:
                tls = ledger.tick(lst, max(slot, 1))
                _view_cache[epoch] = ledger.view_for_epoch(tls.state, epoch)
            return _view_cache[epoch]

    def done() -> bool:
        if limit.slots is not None and slot >= limit.slots:
            return True
        if limit.blocks is not None and block_no >= limit.blocks:
            return True
        if limit.epochs is not None and params.epoch_of(slot) >= limit.epochs:
            return True
        return False

    span_proofs: dict = {}
    span_end = 0

    if engine != "loop":
        # the batched pipeline (protocol/forge.py): whole-window
        # elections + amortized assembly. It advances the same state
        # the loop below threads, so after it returns done() is True
        # and the per-slot reference loop is a no-op — except when a
        # recovery ladder exhausted mid-run, which re-enters it as the
        # floor that cannot fail for device reasons.
        st, prev_hash, block_no, slot = _forge_pipeline(
            imm, params, pools, lview, limit, res, st, prev_hash,
            block_no, slot, counters, ledger_view_for_epoch,
            txs_per_block, txs_for_block, engine, trace,
        )
    while not done():
        lv_now = (
            ledger_view_for_epoch(params.epoch_of(slot))
            if ledger_view_for_epoch is not None
            else lview
        )
        ticked = praos.tick(params, lv_now, slot, st)
        eta0 = ticked.state.epoch_nonce
        if vrf_backend == "device" and slot >= span_end:
            # next span: up to the epoch boundary (eta0 is epoch-constant)
            epoch_end = (params.epoch_of(slot) + 1) * params.epoch_length
            span_end = min(epoch_end, slot + 16 * _VRF_BUCKET)
            if limit.slots is not None:
                span_end = min(span_end, limit.slots)
            if limit.blocks is not None:
                # don't prove far past where the block limit will trip:
                # ~1/f slots per block, padded 2x + a margin
                need = limit.blocks - block_no
                est = int(2 * need / float(params.active_slot_coeff)) + 64
                span_end = min(span_end, slot + est)
            span_proofs = _prove_span(pools, range(slot, span_end), eta0)
        for pi, pool in enumerate(pools):
            if vrf_backend == "device":
                is_leader = span_proofs[(slot, pi)]
            else:  # host: lazy per-slot evaluation (small runs)
                is_leader = evaluate_vrf(pool, slot, eta0)
            lv_val = nonces.vrf_leader_value(is_leader.vrf_output)
            entry = lv_now.pool_distr.get(pool.pool_id)
            if entry is None:
                continue  # pool has no stake this epoch
            if not check_leader_value(lv_val, entry.stake, params.active_slot_coeff):
                continue
            n = counters.get(pool.pool_id, 0)
            if txs_for_block is not None:
                txs = tuple(txs_for_block(slot, block_no))
            else:
                txs = tuple(
                    b"tx-%d-%d" % (slot, i) for i in range(txs_per_block)
                )
            block = forge_block(
                params,
                pool,
                slot=slot,
                block_no=block_no,
                prev_hash=prev_hash,
                epoch_nonce=eta0,
                txs=txs,
                ocert_counter=n,
                is_leader=is_leader,
            )
            if ledger is not None:
                # the fold MUST accept what we forged BEFORE the block
                # is persisted — a rejected tx must not leave an
                # invalid block on disk
                lst = ledger.tick_then_apply(lst, block)
            imm.append_block(slot, block_no, block.hash_, block.bytes_)
            st = praos.reupdate(params, block.header.to_view(), slot, ticked)
            counters[pool.pool_id] = n
            prev_hash = block.hash_
            block_no += 1
            res.n_blocks += 1
            if res.n_blocks % 1000 == 0:
                trace(f"forged {res.n_blocks} blocks to slot {slot}")
            break  # first winning credential forges (one block per slot)
        # NB: on a leaderless slot `st` is left un-ticked — tick is a pure
        # function of (state, slot) re-derived at the next forged block;
        # latching `ticked.state` here would rotate the epoch nonce twice
        # (is_new_epoch keys off last_slot, which only blocks advance)
        slot += 1
        res.n_slots += 1

    imm.flush()
    # forge-time sidecars: seal every retired chunk's columnar sidecar
    # NOW so the first replay opens hot (write-once; skips fresh seals;
    # no-op under OCT_SIDECAR=0 or without the native extractor)
    from ..storage import sidecar as sidecar_mod

    # walked=True: the forge wrote these exact bytes this run — the
    # seal covers a chunk whose integrity holds by construction
    sidecar_mod.backfill_store(imm, walked=True)
    res.wall_s = time.monotonic() - t0
    res.final_state = st
    tip = imm.tip()
    if tip is not None:
        # seed the trusted-fold memo: a resume-then-extend onto this
        # exact tip skips the whole-chain reupdate replay
        _REPLAY_MEMO[os.path.realpath(db_path)] = (
            tip.slot, tip.hash_, st, dict(counters), prev_hash,
            block_no, tip.slot + 1,
        )
    return res


def main(argv=None) -> None:
    """CLI (app/db-synthesizer.hs + DBSynthesizer/Parsers.hs analog)."""
    import argparse

    p = argparse.ArgumentParser(prog="db_synthesizer", description=__doc__)
    p.add_argument("--out", required=True, help="chain DB directory to create")
    p.add_argument("--pools", type=int, default=2)
    p.add_argument("--kes-depth", type=int, default=7)
    lim = p.add_mutually_exclusive_group(required=True)
    lim.add_argument("--slots", type=int)
    lim.add_argument("--blocks", type=int)
    lim.add_argument("--epochs", type=int)
    p.add_argument("--txs-per-block", type=int, default=0)
    p.add_argument("--resume", action="store_true",
                   help="continue a crashed synthesis: deep-validate + "
                        "repair the surviving chain, rebuild the "
                        "forging state from it, forge on from the tip")
    p.add_argument("--config", default=None,
                   help="node config.json (with CredentialsFile) instead "
                        "of --pools/--kes-depth generated credentials")
    p.add_argument("--cardano", action="store_true",
                   help="forge the multi-era composite (era-tagged "
                        "blocks crossing the Byron/Shelley/Babbage "
                        "boundaries); pairs with db_analyser --cardano")
    p.add_argument("--with-ledgers", action="store_true",
                   help="with --cardano: real era ledgers in the loop")
    a = p.parse_args(argv)
    if a.with_ledgers and not a.cardano:
        p.error("--with-ledgers requires --cardano")
    if a.cardano:
        from ..hardfork import composite as cardano

        if a.config is not None:
            p.error("--cardano uses the composite's built-in config")
        if not a.slots:
            p.error("--cardano forges by --slots")
        cfg = cardano.CardanoMockConfig(with_ledgers=a.with_ledgers)
        n = cardano.synthesize(a.out, cfg, a.slots)
        print(f"forged {n} blocks over {a.slots} slots at {a.out}")
        return
    if a.config:
        from .config import load_config

        params, lview, pools = load_config(a.config)
        if pools is None:
            p.error("--config needs a CredentialsFile to forge with")
    else:
        params = default_params(kes_depth=a.kes_depth)
        pools, lview = make_credentials(a.pools, kes_depth=a.kes_depth)
    res = synthesize(
        a.out, params, pools, lview,
        ForgeLimit(slots=a.slots, blocks=a.blocks, epochs=a.epochs),
        txs_per_block=a.txs_per_block,
        trace=lambda s: print(s),
        resume=a.resume,
    )
    # the chain carries its own config (tools-test pipeline shape)
    from .config import write_genesis_files

    write_genesis_files(
        os.path.join(a.out, "config"), params, lview, pools
    )
    print(
        f"forged {res.n_blocks} blocks over {res.n_slots} slots "
        f"in {res.wall_s:.1f}s"
    )


if __name__ == "__main__":
    main()
