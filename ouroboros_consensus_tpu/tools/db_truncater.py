"""db-truncater: truncate an ImmutableDB after a given point/slot — and
repair it to its last valid block.

Reference: `Cardano.Tools.DBTruncater` (Tools/DBTruncater/Run.hs
`truncate`): open the ImmutableDB, find the truncation point, drop
everything after it. Used to rewind a chain for reproduction runs.

Beyond the reference's slot-addressed truncation, this CLI fronts the
open-with-repair scan (storage/immutable.py + storage/repair.py):

    --to-last-valid      run the full ValidateAllChunks walk (CRC +
                         body-hash integrity, per-blob order) and
                         truncate the store to its last valid block ON
                         DISK — torn tails cut, lagging/corrupt indices
                         rebuilt, stranded chunks dropped; every
                         snipped byte QUARANTINED, every action a
                         first-class repair row
    --dry-run            the same scan, read-only: report what WOULD
                         be snipped (applied=False rows), disk untouched
    --quarantine-dir D   where snipped bytes go (default
                         <db>/immutable/quarantine)

The repair path speaks the store crash protocol (storage/guard.py):
the DB lock is held for the scan, and a completed repair writes the
clean-shutdown marker back — the repaired store opens clean.
"""

from __future__ import annotations

import os

from ..block.abstract import Point
from ..storage.immutable import ImmutableDB


def _refuse_virgin(db_path: str, fs=None) -> None:
    """A writer-mode open of a path with no store would FABRICATE one
    (lock + default-magic marker + clean marker + empty immutable/) and
    report success — an operator's typo'd --db must refuse loudly
    instead, before any side effect."""
    from ..utils.fs import REAL_FS

    vfs = fs if fs is not None else REAL_FS
    if not vfs.exists(os.path.join(db_path, "immutable")):
        raise FileNotFoundError(
            f"no store at {db_path} (refusing to create one — check --db)"
        )


def truncate(db_path: str, after_slot: int | None) -> int:
    """Truncate the DB at `db_path` to blocks with slot <= after_slot
    (None wipes it). Returns the number of blocks remaining.

    Mutates the store, so it speaks the crash protocol like repair():
    writer lock held for the rewind (a concurrent forge/analysis
    refuses with DbLocked), marker checked, clean-shutdown marker
    rewritten only on an orderly finish. A DIRTY open (missing clean-
    shutdown marker) escalates to the full integrity walk WITH repair
    first — stamping the marker back after a most-recent-chunk open
    would bless rot in older chunks the rewind never looked at."""
    from ..storage import guard as guard_mod
    from ..storage import repair as repair_mod
    from ..storage.open import open_repair_store

    _refuse_virgin(db_path)
    with guard_mod.StoreGuard(db_path, writer=True) as guard:
        if guard.opened_dirty:
            repair_mod.note_repair(
                "dirty-open-escalated",
                detail="no clean-shutdown marker: slot truncate runs "
                       "the full repair walk first",
            )
            imm = open_repair_store(db_path)
        else:
            imm = ImmutableDB(os.path.join(db_path, "immutable"))
        if after_slot is None:
            imm.truncate_after(None)
        else:
            # find the last block at or before the slot
            target = None
            for n in imm._chunks:
                for e in imm._entries[n]:
                    if e.slot <= after_slot:
                        target = Point(e.slot, e.hash_)
            imm.truncate_after(target)
        imm.flush()
        return imm.n_blocks()


def repair(db_path: str, dry_run: bool = False,
           quarantine_dir: str | None = None, fs=None,
           network_magic: int | None = None) -> dict:
    """--to-last-valid: the open-with-repair scan. Opens the store
    under the crash protocol (lock; marker check; writer mode unless
    dry-run) with the full integrity walk and on-disk repair, and
    returns a report:

        {"blocks": <remaining>, "applied": <not dry_run>,
         "opened_dirty": <clean marker was absent>,
         "actions": {action: count}, "repairs": [row, ...]}

    ``dry_run=True`` runs the IDENTICAL scan read-only: the report
    lists every action the repair would take (applied=False), and the
    store — chunks, indices and markers — is byte-untouched (only the
    advisory lock file may be created; flock needs a file to lock)."""
    from ..storage import guard as guard_mod
    from ..storage.open import open_repair_store

    _refuse_virgin(db_path, fs=fs)
    guard = guard_mod.StoreGuard(
        db_path, network_magic=network_magic, fs=fs, writer=not dry_run
    )
    guard.open()
    try:
        imm = open_repair_store(
            db_path, fs=fs, quarantine_dir=quarantine_dir,
            repair=not dry_run,
        )
        if not dry_run:
            imm.flush()
            # regenerate sidecars the repair walk invalidated: any
            # rewritten/truncated chunk had its stale seal quarantined,
            # so re-seal from the now-consistent bytes (write-once —
            # chunks whose seal survived are skipped)
            from ..storage import sidecar as sidecar_mod

            # walked=True: everything that survives --to-last-valid sits
            # at or below the validated truncation point — the repair
            # walk that chose it covered every surviving blob
            sidecar_mod.backfill_store(imm, walked=True)
        from ..storage import repair as repair_mod

        # applied_only=False: a dry-run's report IS its would-repair rows
        actions = repair_mod.count_actions(imm.repairs, applied_only=False)
        report = {
            "blocks": imm.n_blocks(),
            "applied": not dry_run,
            "opened_dirty": guard.opened_dirty,
            "actions": actions,
            "repairs": list(imm.repairs),
        }
    except BaseException:
        guard.close(clean=False)
        raise
    # a completed repair leaves a consistent store: mark it clean (a
    # dry-run was a reader and never touched the markers)
    guard.close(clean=True)
    return report


def main(argv=None) -> None:
    import argparse
    import json

    p = argparse.ArgumentParser(prog="db_truncater", description=__doc__)
    p.add_argument("--db", required=True, help="chain DB directory")
    mode = p.add_mutually_exclusive_group()
    mode.add_argument(
        "--truncate-after-slot", type=int, default=None,
        help="keep blocks with slot <= N (omit with no --to-last-valid "
             "to wipe)",
    )
    mode.add_argument(
        "--to-last-valid", action="store_true",
        help="repair mode: full integrity walk, truncate to the last "
             "valid block on disk (snipped bytes quarantined)",
    )
    p.add_argument("--dry-run", action="store_true",
                   help="with --to-last-valid: report what would be "
                        "snipped; the store is not touched")
    p.add_argument("--quarantine-dir", default=None,
                   help="where snipped bytes go (default "
                        "<db>/immutable/quarantine)")
    a = p.parse_args(argv)
    if a.dry_run and not a.to_last_valid:
        p.error("--dry-run only applies to --to-last-valid")
    if a.quarantine_dir and not a.to_last_valid:
        p.error("--quarantine-dir only applies to --to-last-valid")
    if a.to_last_valid:
        rep = repair(a.db, dry_run=a.dry_run,
                     quarantine_dir=a.quarantine_dir)
        print(json.dumps(rep))
        verb = "would repair" if a.dry_run else "repaired"
        acts = ", ".join(f"{k}={v}"
                         for k, v in sorted(rep["actions"].items()))
        print(f"{verb}: {acts or 'nothing'}; "
              f"{rep['blocks']} valid blocks remain")
        return
    n = truncate(a.db, a.truncate_after_slot)
    print(f"truncated; {n} blocks remain")


if __name__ == "__main__":
    main()
