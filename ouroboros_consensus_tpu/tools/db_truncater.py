"""db-truncater: truncate an ImmutableDB after a given point/slot.

Reference: `Cardano.Tools.DBTruncater` (Tools/DBTruncater/Run.hs
`truncate`): open the ImmutableDB, find the truncation point, drop
everything after it. Used to rewind a chain for reproduction runs.
"""

from __future__ import annotations

import os

from ..block.abstract import Point
from ..storage.immutable import ImmutableDB


def truncate(db_path: str, after_slot: int | None) -> int:
    """Truncate the DB at `db_path` to blocks with slot <= after_slot
    (None wipes it). Returns the number of blocks remaining."""
    imm = ImmutableDB(os.path.join(db_path, "immutable"))
    if after_slot is None:
        imm.truncate_after(None)
    else:
        # find the last block at or before the slot
        target = None
        for n in imm._chunks:
            for e in imm._entries[n]:
                if e.slot <= after_slot:
                    target = Point(e.slot, e.hash_)
        imm.truncate_after(target)
    imm.flush()
    return imm.n_blocks()


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="db_truncater", description=__doc__)
    p.add_argument("--db", required=True, help="chain DB directory")
    p.add_argument(
        "--truncate-after-slot", type=int, default=None,
        help="keep blocks with slot <= N (omit to wipe)",
    )
    a = p.parse_args(argv)
    n = truncate(a.db, a.truncate_after_slot)
    print(f"truncated; {n} blocks remain")


if __name__ == "__main__":
    main()
