"""immdb-server: serve an ImmutableDB over ChainSync + BlockFetch
without a full ChainDB.

Reference: `Cardano.Tools.ImmDBServer` (Tools/ImmDBServer/{Diffusion,
MiniProtocols}.hs) — a stripped node that answers header/block requests
straight from an on-disk ImmutableDB, used to feed syncing test nodes.

Here the server speaks the same tuple wire protocol as
miniprotocol/chainsync+blockfetch, over either sim Channels (tests) or
an asyncio TCP transport (serve_tcp) using length-prefixed CBOR frames —
the host-side "DCN" transport of SURVEY.md §5.8.
"""

from __future__ import annotations

import os

from ..block.abstract import Point
from ..block.praos_block import Block
from ..storage.immutable import ImmutableDB

_NETWORK_MAGIC = 764824073  # mainnet magic: the DbMarker/handshake guard


class ImmutableChainView:
    """Adapts an ImmutableDB to the slice of the ChainDB surface the
    chainsync/blockfetch servers read (static chain: no rollbacks).

    The whole chain is presented as the immutable part (empty volatile
    fragment), so the servers stream straight off disk instead of
    materializing every block up front."""

    def __init__(self, db_path: str):
        self.imm = ImmutableDB(os.path.join(db_path, "immutable"))
        self.immutable = self.imm  # chainsync/blockfetch server surface
        self.current_chain: list = []
        self.runtime = None  # no event runtime: servers poll

    def _anchor_point(self) -> Point | None:
        return self.imm.tip_point()

    def tip_point(self) -> Point | None:
        return self.imm.tip_point()

    def new_follower(self, include_tentative: bool = False):
        class _StaticFollower:
            """The chain never changes: no updates, no tentative state."""

            def take_updates(self):
                return []

            def reset_position(self):
                pass

            def close(self):
                pass

        return _StaticFollower()


def serve_sim(view: ImmutableChainView, cs_rx, cs_tx, bf_rx, bf_tx):
    """Spawn-able pair of server generators over sim channels."""
    from ..miniprotocol import blockfetch, chainsync

    return (
        chainsync.server(view, cs_rx, cs_tx, poll_interval=0.5),
        blockfetch.server(view, bf_rx, bf_tx),
    )


# -- asyncio TCP transport ---------------------------------------------------
# Framing shared with the full node-to-node transport (node/transport.py
# owns it now; this tool predates it and keeps its local aliases).

from ..node.transport import frame as _frame  # noqa: E402
from ..node.transport import read_frame as _read_frame  # noqa: E402


async def serve_metrics(host: str = "127.0.0.1", port: int = 9100,
                        registry=None):
    """Prometheus exposition endpoint beside the block service
    (`--metrics-port`; `port=0` binds ephemeral for tests).

    Rebased onto `obs/server.py` — ONE HTTP implementation for the
    whole repo: /metrics and /metrics.json behave exactly as before
    (scrape/request counters included), and the live-plane routes
    /healthz and /progress come along for free (SURVEY.md layer 4-5:
    the cardano-node EKG/Prometheus bridge analog, now also the serving
    tier's SLO surface)."""
    from ..obs import server as obs_server

    return await obs_server.serve_metrics(host, port, registry=registry)


async def serve_tcp(db_path: str, host: str = "127.0.0.1", port: int = 3001,
                    network_magic: int = _NETWORK_MAGIC):
    """One TCP service multiplexing chainsync-style requests: each frame
    is a request tuple; the reply frame(s) follow. Static chain only."""
    import asyncio

    from ..obs.registry import default_registry

    view = ImmutableChainView(db_path)
    requests = default_registry().counter(
        "oct_immdb_requests_total", "immdb-server request frames", ("kind",)
    )
    # label values come off the WIRE: bucket anything outside the known
    # protocol vocabulary as "other", or a misbehaving peer could grow
    # one counter child per arbitrary kind string (unbounded registry
    # memory + exposition bloat)
    _KNOWN_KINDS = frozenset((
        "propose_versions", "find_intersect", "request_range",
        "headers_from", "done",
    ))

    async def handle(reader, writer):
        handshaken = False
        try:
            while True:
                msg = await _read_frame(reader)
                kind = msg[0]
                requests.labels(
                    kind=kind if kind in _KNOWN_KINDS else "other"
                ).inc()
                if not handshaken and kind != "propose_versions":
                    # the reference handshakes BEFORE serving
                    # (ImmDBServer/Diffusion.hs): an un-negotiated peer
                    # gets nothing — that is the whole cross-net guard
                    writer.write(
                        _frame(("refuse", "handshake required first"))
                    )
                    await writer.drain()
                    break
                if kind == "propose_versions":
                    # NodeToNode handshake (miniprotocol/handshake.py):
                    # the reference immdb-server performs the full wire
                    # handshake before serving (ImmDBServer/Diffusion.hs)
                    from ..miniprotocol import handshake as hs

                    ours = {
                        v: hs.VersionData(network_magic=network_magic)
                        for v in hs.NODE_TO_NODE_VERSIONS
                    }
                    theirs = {
                        int(v): hs.VersionData(network_magic=d)
                        for v, d in msg[1]
                    }
                    try:
                        version, data = hs.negotiate(ours, theirs)
                    except hs.HandshakeRefused as e:
                        writer.write(_frame(("refuse", str(e))))
                        await writer.drain()
                        break
                    writer.write(
                        _frame(("accept_version", version, data.network_magic))
                    )
                    handshaken = True
                elif kind == "find_intersect":
                    # same contract as miniprotocol/chainsync.py server:
                    # None in the offered points = genesis fallback; no
                    # match at all -> intersect_not_found
                    points = msg[1]

                    def _have(p):
                        try:
                            view.imm.get_block_bytes(p)
                            return True
                        except Exception:
                            return False

                    found = next(
                        (p for p in points if p is not None and _have(p)), None
                    )
                    if found is not None or None in points:
                        writer.write(
                            _frame(("intersect_found", found, view.tip_point()))
                        )
                    else:
                        writer.write(_frame(("intersect_not_found", view.tip_point())))
                elif kind == "request_range":
                    # same contract as miniprotocol/blockfetch.py server:
                    # an unsatisfiable range answers no_blocks, never a
                    # partial/overshooting stream
                    from ..miniprotocol.blockfetch import _range_stream

                    stream = _range_stream(view, msg[1], msg[2])
                    first = next(stream, None) if stream is not None else None
                    if first is None:
                        writer.write(_frame(("no_blocks",)))
                    else:
                        writer.write(_frame(("start_batch",)))
                        writer.write(_frame(("block", first.bytes_)))
                        for b in stream:
                            writer.write(_frame(("block", b.bytes_)))
                        writer.write(_frame(("batch_done",)))
                elif kind == "headers_from":
                    # bulk header stream after a point (sync accelerator)
                    start = msg[1]
                    it = (
                        view.imm.stream_all()
                        if start is None
                        else view.imm.stream_from(start.slot)
                    )
                    for _i, (_e, raw) in zip(range(1000), it):
                        hdr = Block.from_bytes(raw).header
                        writer.write(
                            _frame(("roll_forward", hdr.bytes_, view.tip_point()))
                        )
                    writer.write(_frame(("await_reply",)))
                elif kind == "done":
                    break
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(handle, host, port)
    return server


def main(argv=None) -> None:
    import argparse
    import asyncio

    p = argparse.ArgumentParser(prog="immdb_server", description=__doc__)
    p.add_argument("--db", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=3001)
    p.add_argument("--network-magic", type=int, default=_NETWORK_MAGIC,
                   help="handshake guard; clients proposing a different "
                        "magic are refused (default: mainnet)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve Prometheus text exposition (/metrics) and "
                        "the JSON snapshot (/metrics.json) on this port; "
                        "0 = disabled")
    a = p.parse_args(argv)

    async def run():
        server = await serve_tcp(a.db, a.host, a.port, a.network_magic)
        print(f"immdb-server listening on {a.host}:{a.port}")
        if a.metrics_port:
            msrv = await serve_metrics(a.host, a.metrics_port)
            print(f"metrics on http://{a.host}:{a.metrics_port}/metrics")
        async with server:
            await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
