"""CLI tools (reference: ouroboros-consensus-cardano src/tools):
db_synthesizer (chain forging), db_analyser (validation + benchmarks),
db_truncater, immdb_server."""
