"""db-analyser: stream a stored chain and validate / benchmark it.

Reference: `Cardano.Tools.DBAnalyser` (Analysis.hs:75-88, Run.hs:42-151).
Implemented analyses:

  * ``only_validation`` — open the ImmutableDB with full integrity
    checking (ValidateAllChunks analog: reparse + body-hash check per
    block, Run.hs:133-143) and run full header revalidation. With the
    ``device`` backend the Praos crypto executes as epoch-segmented
    fused TPU batches (protocol/batch.py); with the ``host`` backend it
    folds the sequential pure-Python reference path — the same work the
    reference's libsodium-backed fold does.
  * ``benchmark_ledger_ops`` — per-block timing of forecast / header
    tick / header apply / ledger tick / ledger apply, CSV rows matching
    the reference's SlotDataPoint columns (Analysis.hs:526-607). Host
    backend only (per-block timing is meaningless inside a fused batch).
  * ``count_blocks`` — CountBlocks analog.

The device path is the north-star benchmark: headers validated/sec over
a db-synthesizer chain.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..block.praos_block import Block, Header
from ..protocol import batch as pbatch
from ..protocol import praos
from ..protocol.praos import PraosParams, PraosState
from ..protocol.views import LedgerView
from ..storage.immutable import ImmutableDB
from ..storage.open import default_check_integrity


@dataclass
class ValidationResult:
    n_blocks: int = 0
    n_valid: int = 0
    wall_s: float = 0.0
    stage_s: float = 0.0  # host SoA staging time (device backend)
    device_s: float = 0.0  # kernel execution time (device backend)
    error: Exception | None = None
    final_state: PraosState | None = None


@dataclass
class SlotDataPoint:
    """One CSV row of benchmark_ledger_ops (SlotDataPoint.hs)."""

    slot: int
    block_no: int
    block_bytes: int
    mut_forecast_us: float
    mut_header_tick_us: float
    mut_header_apply_us: float
    mut_block_tick_us: float
    mut_block_apply_us: float

    CSV_HEADER = (
        "slot,block_no,block_bytes,mut_forecast,mut_headerTick,"
        "mut_headerApply,mut_blockTick,mut_blockApply"
    )

    def csv(self) -> str:
        return (
            f"{self.slot},{self.block_no},{self.block_bytes},"
            f"{self.mut_forecast_us:.1f},{self.mut_header_tick_us:.1f},"
            f"{self.mut_header_apply_us:.1f},{self.mut_block_tick_us:.1f},"
            f"{self.mut_block_apply_us:.1f}"
        )


def open_immutable(db_path: str, validate_all: bool = False) -> ImmutableDB:
    import os

    return ImmutableDB(
        os.path.join(db_path, "immutable"),
        check_integrity=default_check_integrity if validate_all else None,
        validate_all=validate_all,
    )


def _epoch_segments(params: PraosParams, headers):
    """Cut a header stream at epoch boundaries (SURVEY.md §5.7: nonce and
    pool distribution are epoch-constant, so a batch spans one epoch)."""
    seg: list = []
    epoch = None
    for h in headers:
        e = params.epoch_of(h.slot)
        if epoch is None or e == epoch:
            seg.append(h)
            epoch = e
        else:
            yield seg
            seg = [h]
            epoch = e
    if seg:
        yield seg


def revalidate(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    backend: str = "device",
    validate_all: bool = True,
    max_batch: int = 8192,
    trace=lambda s: None,
) -> ValidationResult:
    """only-validation analysis: full chain revalidation from genesis.

    backend="device": epoch-segmented batches through the fused kernel
    (further split at max_batch to bound device memory; the jit caches
    per padded shape).
    backend="host": the sequential fold (reference semantics, pure host).
    """
    res = ValidationResult()
    t0 = time.monotonic()
    imm = open_immutable(db_path, validate_all=validate_all)

    def headers():
        for entry, raw in imm.stream_all():
            res.n_blocks += 1
            yield Block.from_bytes(raw).header

    st = PraosState()
    if backend == "host":
        try:
            for h in headers():
                hv = h.to_view()
                ticked = praos.tick(params, lview, h.slot, st)
                st = praos.update(params, hv, h.slot, ticked)
                res.n_valid += 1
        except praos.PraosValidationError as e:
            res.error = e
    elif backend == "device":
        done = False
        for seg in _epoch_segments(params, headers()):
            if done:
                break
            for i in range(0, len(seg), max_batch):
                sub = seg[i : i + max_batch]
                hvs = [h.to_view() for h in sub]
                ticked = praos.tick(params, lview, sub[0].slot, st)
                ts = time.monotonic()
                result = pbatch.validate_batch(params, ticked, hvs)
                res.device_s += time.monotonic() - ts
                st = result.state
                res.n_valid += result.n_valid
                if result.error is not None:
                    res.error = result.error
                    done = True
                    break
                trace(f"validated {res.n_valid} headers")
    else:
        raise ValueError(f"unknown backend {backend!r}")

    res.final_state = st
    res.wall_s = time.monotonic() - t0
    return res


def benchmark_ledger_ops(
    db_path: str,
    params: PraosParams,
    lview: LedgerView,
    ledger=None,
    genesis_state=None,
    out_csv=None,
) -> list[SlotDataPoint]:
    """Per-block μs timings of the five ledger ops (Analysis.hs:526-607).

    The ledger tick/apply columns use the mock ledger when one is given
    (matching the reference, where ledger cost dwarfs header cost only
    on real eras); header columns always run the host Praos path.
    """
    imm = open_immutable(db_path, validate_all=False)
    rows: list[SlotDataPoint] = []
    st = PraosState()
    lst = genesis_state
    for entry, raw in imm.stream_all():
        block = Block.from_bytes(raw)
        h = block.header
        hv = h.to_view()

        t = time.monotonic()
        # forecast: ledger view at the header's slot (epoch-constant here)
        _ = lview
        forecast_us = (time.monotonic() - t) * 1e6

        t = time.monotonic()
        ticked = praos.tick(params, lview, h.slot, st)
        header_tick_us = (time.monotonic() - t) * 1e6

        t = time.monotonic()
        st = praos.update(params, hv, h.slot, ticked)
        header_apply_us = (time.monotonic() - t) * 1e6

        block_tick_us = block_apply_us = 0.0
        if ledger is not None and lst is not None:
            t = time.monotonic()
            tls = ledger.tick(lst, h.slot)
            block_tick_us = (time.monotonic() - t) * 1e6
            t = time.monotonic()
            lst = ledger.apply_block(tls, block)
            block_apply_us = (time.monotonic() - t) * 1e6

        rows.append(
            SlotDataPoint(
                slot=h.slot,
                block_no=h.block_no,
                block_bytes=len(raw),
                mut_forecast_us=forecast_us,
                mut_header_tick_us=header_tick_us,
                mut_header_apply_us=header_apply_us,
                mut_block_tick_us=block_tick_us,
                mut_block_apply_us=block_apply_us,
            )
        )
    if out_csv is not None:
        with open(out_csv, "w") as f:
            f.write(SlotDataPoint.CSV_HEADER + "\n")
            for r in rows:
                f.write(r.csv() + "\n")
    return rows


def count_blocks(db_path: str) -> int:
    imm = open_immutable(db_path)
    return imm.n_blocks()
